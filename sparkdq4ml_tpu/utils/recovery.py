"""Failure detection & recovery — the resilient-execution policy engine
(SURVEY.md §5 "Failure detection / elastic recovery").

The reference inherits Spark's recovery model — task retry, lineage
recomputation, checkpoint dirs — but configures none of it (``local[*]``,
no checkpoint dir, `DataQuality4MachineLearningApp.java:38-41`). The
TPU-native equivalents of those primitives:

* **Detection** — :func:`check_finite` inspects a result pytree for
  NaN/Inf (a diverged solver, a flaky interconnect transfer); the global
  NaN traps in ``utils.debug`` localize the producing op when needed.
  Device-side faults (OOM, interconnect resets, preempted tunnels)
  surface as ``XlaRuntimeError`` and are caught by the retry loop.
* **Deterministic re-execution (lineage)** — every fit in this framework
  is a pure function of (frame, params, seed), so a failed task re-runs
  identically; :func:`resilient_call` is the task-retry loop
  (``spark.task.maxFailures`` analogue) with exponential backoff +
  deterministic jitter (:class:`RetryPolicy`), per-attempt deadlines
  (:class:`DeadlineExceeded`), and a :class:`CircuitBreaker` that stops
  hammering a failing device path.
* **Graceful degradation** — :func:`resilient_call` walks a *fallback
  ladder*: when the primary path exhausts its retries (or its breaker is
  open) the next rung runs instead — e.g. sharded Gramian → single-device
  CPU Gramian (``parallel.distributed.compute_gram``), sharded packed fit
  → single-device fit → ``normal`` solver (``models.regression``).
* **Checkpointing** — :func:`fit_or_resume` persists the fitted stage via
  the models/base persistence layer and resumes from the artifact after a
  driver crash/preemption instead of refitting; with ``checkpoint_every``
  it checkpoints *mid-fit* every N solver iterations, so a preemption
  (real, or injected via ``utils.faults``) loses at most one segment.
* **Telemetry** — every retry, backoff, fallback, breaker trip, and
  resume lands in :data:`RECOVERY_LOG` as a structured
  :class:`RecoveryEvent` (mirrored into ``utils.profiling.counters`` and
  the ``sparkdq4ml_tpu.recovery`` logger), so recovery is observable,
  never silent. A clean run records zero events.

Fault injection for all of the above lives in :mod:`~sparkdq4ml_tpu.utils.faults`;
the chaos env vars, policy knobs, and the fallback ladder are documented in
README.md § "Failure model & fault injection".
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Callable, List, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger("sparkdq4ml_tpu.recovery")


class FitFailure(RuntimeError):
    """A computation failed (non-finite result or device error) and did not
    recover within the configured retries/fallbacks."""


class DeadlineExceeded(RuntimeError):
    """An attempt ran past its per-attempt deadline. The in-flight device
    call cannot be cancelled (XLA dispatches are not interruptible); the
    retry loop stops *waiting* on it and moves on."""


class CircuitOpenError(FitFailure):
    """Every rung of the ladder was skipped because its breaker is open —
    nothing even ran. A :class:`FitFailure` subclass so callers guarding
    the generic failure path catch it too."""


# ---------------------------------------------------------------------------
# Telemetry: the structured recovery-event log
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RecoveryEvent:
    """One recovery decision, structured for assertions and dashboards."""

    site: str            # instrumented call site ("gram_sharded", "fit", …)
    action: str          # retry | fallback | recovered | exhausted |
    #                      circuit_open | circuit_skip | deadline |
    #                      preempted | resumed | checkpoint
    # wire sites (net_accept/net_read/net_write in serve/net.py, and the
    # client's net_client) add: conn_reset | partial_write | timeout |
    # hedge — one event per fault the network ladder absorbed
    attempt: int = 0     # 1-based attempt within the current rung
    rung: str = ""       # ladder rung label ("primary", "single_device", …)
    cause: str = ""      # exception repr / "non-finite" / ""
    backoff_s: float = 0.0
    detail: str = ""
    time_s: float = 0.0  # wall-clock timestamp (time.time)
    # Active-span correlation (None when tracing was off): the logfmt span
    # stream and the Chrome/Perfetto trace emit the same ids, so a retry
    # line here pins to the exact span it happened inside.
    trace_id: Optional[int] = None
    span_id: Optional[int] = None

    def as_kv(self) -> str:
        from .logging import format_kv

        return format_kv(
            site=self.site, action=self.action, attempt=self.attempt,
            rung=self.rung, cause=self.cause,
            backoff_s=round(self.backoff_s, 4), detail=self.detail,
            trace_id=self.trace_id, span_id=self.span_id)


class RecoveryLog:
    """Append-only structured event log + counter mirror. Thread-safe;
    bounded (drops oldest beyond ``maxlen``) so a hot retry loop can never
    grow memory without bound."""

    def __init__(self, maxlen: int = 10_000):
        self._events: List[RecoveryEvent] = []
        self._maxlen = maxlen
        self._lock = threading.Lock()

    def record(self, site: str, action: str, **kw) -> RecoveryEvent:
        if "trace_id" not in kw:
            from . import observability as _obs

            kw["trace_id"], kw["span_id"] = _obs.current_ids()
        ev = RecoveryEvent(site=site, action=action, time_s=time.time(), **kw)
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self._maxlen:
                del self._events[: len(self._events) - self._maxlen]
        from . import profiling

        profiling.counters.increment(f"recovery.{action}")
        if site:
            # per-site mirror (recovery.retry.pipeline_flush, …): the
            # Prometheus scrape can attribute recovery activity to the
            # subsystem that absorbed it — cardinality bounded by the
            # FAULT_SITES registry, not by data
            profiling.counters.increment(f"recovery.{action}.{site}")
        level = (logging.INFO if action in ("resumed", "checkpoint",
                                            "recovered")
                 else logging.WARNING)
        logger.log(level, "recovery %s", ev.as_kv())
        return ev

    def events(self, site: Optional[str] = None,
               action: Optional[str] = None) -> List[RecoveryEvent]:
        with self._lock:
            evs = list(self._events)
        if site is not None:
            evs = [e for e in evs if e.site == site]
        if action is not None:
            evs = [e for e in evs if e.action == action]
        return evs

    def count(self, action: Optional[str] = None,
              site: Optional[str] = None) -> int:
        return len(self.events(site=site, action=action))

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


RECOVERY_LOG = RecoveryLog()


def recovery_events(site: Optional[str] = None,
                    action: Optional[str] = None) -> List[RecoveryEvent]:
    """The process-global structured recovery log (see :data:`RECOVERY_LOG`)."""
    return RECOVERY_LOG.events(site=site, action=action)


# ---------------------------------------------------------------------------
# Policy: backoff, deadlines, circuit breaker
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry-loop policy: attempts, exponential backoff with deterministic
    jitter, per-attempt deadline, and a total budget.

    Jitter is a pure function of (seed, site, attempt) — crc32-keyed, not
    ``random`` — so a failing run replays with identical sleeps (the same
    reproducibility rule as the fault schedule in ``utils.faults``).
    """

    max_attempts: int = 3
    backoff_base: float = 0.05     # s before the 2nd attempt
    backoff_factor: float = 2.0
    backoff_max: float = 5.0
    jitter: float = 0.1            # +[0, jitter) fraction of the backoff
    seed: int = 0
    attempt_deadline: Optional[float] = None   # s per attempt (thread-waited)
    total_deadline: Optional[float] = None     # s across all attempts/rungs
    sleep: Callable[[float], None] = time.sleep

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def backoff(self, attempt: int, site: str = "") -> float:
        """Seconds to wait after failed ``attempt`` (1-based)."""
        if attempt >= self.max_attempts:
            return 0.0  # no sleep before a fallback/raise
        base = min(self.backoff_base * self.backoff_factor ** (attempt - 1),
                   self.backoff_max)
        if self.jitter <= 0.0 or base <= 0.0:
            return base
        from .faults import _det_uniform

        return base * (1.0 + self.jitter
                       * _det_uniform(self.seed, site, attempt))

    _CONF_KEYS = {
        "maxAttempts": ("max_attempts", int),
        "backoffBase": ("backoff_base", float),
        "backoffFactor": ("backoff_factor", float),
        "backoffMax": ("backoff_max", float),
        "jitter": ("jitter", float),
        "seed": ("seed", int),
        "attemptDeadline": ("attempt_deadline", float),
        "totalDeadline": ("total_deadline", float),
    }

    @classmethod
    def _conf_kwargs(cls, conf: Mapping, prefix: str) -> dict:
        kw = {}
        for conf_key, (attr, cast) in cls._CONF_KEYS.items():
            v = conf.get(prefix + conf_key)
            if v is not None:
                kw[attr] = cast(v)
        return kw

    @classmethod
    def from_conf(cls, conf: Optional[Mapping] = None,
                  prefix: str = "spark.recovery.", **overrides) -> "RetryPolicy":
        """Build from session conf / env-style string mappings, e.g.
        ``spark.recovery.maxAttempts``, ``.backoffBase``, ``.backoffMax``,
        ``.backoffFactor``, ``.jitter``, ``.seed``, ``.attemptDeadline``,
        ``.totalDeadline``. Unset keys keep the dataclass defaults."""
        kw = cls._conf_kwargs(conf or {}, prefix)
        kw.update(overrides)
        return cls(**kw)


def active_policy(site: str = "", **overrides) -> RetryPolicy:
    """The active session's retry policy: global ``spark.recovery.*``
    conf keys, with per-site ``spark.recovery.<site>.*`` keys layered on
    top (e.g. ``spark.recovery.gram_sharded.maxAttempts`` tunes only the
    sharded-Gramian ladder). Defaults when no session exists; lazy
    session lookup — recovery must stay importable without a session."""
    conf: Mapping = {}
    try:
        from ..session import TpuSession

        active = TpuSession.active()
        conf = active.conf if active is not None else {}
    except Exception:
        conf = {}
    kw = RetryPolicy._conf_kwargs(conf, "spark.recovery.")
    if site:
        kw.update(RetryPolicy._conf_kwargs(
            conf, f"spark.recovery.{site}."))
    kw.update(overrides)
    return RetryPolicy(**kw)


class CircuitBreaker:
    """Per-key consecutive-failure breaker: after ``failure_threshold``
    straight failures the key *opens* and calls are refused (the ladder
    skips straight to the next rung) until ``cooldown`` seconds pass, when
    one half-open trial is allowed; success closes the breaker."""

    def __init__(self, failure_threshold: int = 3, cooldown: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = int(failure_threshold)
        self.cooldown = float(cooldown)
        self._clock = clock
        self._state: dict = {}     # key -> [consecutive_failures, opened_at]
        self._lock = threading.Lock()

    def allow(self, key: str) -> bool:
        with self._lock:
            fails, opened = self._state.get(key, (0, None))
            if opened is None:
                return True
            if self._clock() - opened >= self.cooldown:
                return True    # half-open: one trial
            return False

    def is_open(self, key: str) -> bool:
        return not self.allow(key)

    def record_success(self, key: str) -> None:
        with self._lock:
            self._state.pop(key, None)

    def record_failure(self, key: str) -> bool:
        """Returns True when this failure OPENED the breaker."""
        with self._lock:
            fails, opened = self._state.get(key, (0, None))
            fails += 1
            just_opened = fails >= self.failure_threshold and opened is None
            if fails >= self.failure_threshold:
                opened = self._clock()
            self._state[key] = (fails, opened)
            return just_opened

    def trip(self, key: str) -> None:
        """Force the breaker OPEN for ``key`` now, as if
        ``failure_threshold`` consecutive failures just landed — the
        ``serve_admit:breaker_trip`` chaos hook. Recovery follows the
        normal path: the cooldown admits a half-open trial, and a success
        closes the key (``record_success``)."""
        with self._lock:
            self._state[key] = (self.failure_threshold, self._clock())

    def reset(self, key: Optional[str] = None) -> None:
        with self._lock:
            if key is None:
                self._state.clear()
            else:
                self._state.pop(key, None)

    def snapshot(self) -> dict:
        """Per-key breaker state for observability surfaces (the serving
        layer's ``QueryServer.stats()``): consecutive failure count and
        whether the key is currently refusing calls (``open`` goes False
        again once the cooldown admits a half-open trial)."""
        with self._lock:
            now = self._clock()
            return {
                key: {
                    "consecutive_failures": fails,
                    "open": (opened is not None
                             and now - opened < self.cooldown),
                }
                for key, (fails, opened) in self._state.items()
            }


#: Process-global breaker guarding device execution paths (sharded Gramian,
#: packed fit). Keys are site names; tests reset it via ``reset()``.
DEVICE_BREAKER = CircuitBreaker()


def _run_with_deadline(fn: Callable, seconds: Optional[float]):
    """Run ``fn()`` bounded by ``seconds``: the call runs in a DAEMON
    thread and :class:`DeadlineExceeded` is raised when it overruns. The
    worker cannot be cancelled (document over pretend: the dispatch keeps
    running), but the retry loop regains control — which for a wedged
    device tunnel is the whole battle. Daemon, not a ThreadPoolExecutor:
    concurrent.futures joins its non-daemon workers at interpreter exit,
    so one wedged call would block process shutdown forever — the exact
    hang this deadline exists to escape."""
    if seconds is None:
        return fn()
    box: dict = {}

    def runner():
        try:
            box["value"] = fn()
        except BaseException as e:        # re-raised on the caller thread
            box["error"] = e

    t = threading.Thread(target=runner, daemon=True,
                         name="sparkdq4ml-deadline")
    t.start()
    t.join(seconds)
    if t.is_alive():
        raise DeadlineExceeded(
            f"attempt exceeded its {seconds:.3g} s deadline; the in-flight "
            "call may still be running")
    if "error" in box:
        raise box["error"]
    return box["value"]


# ---------------------------------------------------------------------------
# Detection
# ---------------------------------------------------------------------------

def check_finite(tree, _seen=None) -> bool:
    """True when every inexact array leaf in ``tree`` is fully finite.

    Works on device arrays, numpy arrays, fitted models (via their
    ``_persist_attrs`` when declared, else their instance ``__dict__`` —
    models with custom persistence must not silently pass), and arbitrary
    pytrees; non-numeric leaves pass. Cycles are guarded.
    """
    if _seen is None:
        _seen = set()
    if id(tree) in _seen:
        return True
    _seen.add(id(tree))

    attrs = getattr(tree, "_persist_attrs", None)
    if attrs is not None:
        return all(check_finite(getattr(tree, a, None), _seen)
                   for a in attrs)
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) == 1 and leaves[0] is tree \
            and not isinstance(tree, (jax.Array, np.ndarray, float,
                                      np.floating)) \
            and hasattr(tree, "__dict__"):
        # tree itself is one opaque leaf (a model object): scan its public
        # attributes directly
        return check_finite({k: v for k, v in vars(tree).items()
                             if not k.startswith("_")}, _seen)
    for leaf in leaves:
        if isinstance(leaf, (jax.Array, np.ndarray, float, np.floating)):
            arr = np.asarray(leaf)
            if np.issubdtype(arr.dtype, np.inexact) \
                    and not np.all(np.isfinite(arr)):
                return False
        elif hasattr(leaf, "__dict__") and id(leaf) not in _seen:
            # opaque object leaf (e.g. a model with custom save()): scan
            # its PUBLIC instance attributes instead of passing it blindly.
            # Private attrs are skipped — e.g. a model's _summary_source
            # frame legitimately carries NaN in masked slots.
            _seen.add(id(leaf))
            public = {k: v for k, v in vars(leaf).items()
                      if not k.startswith("_")}
            if not check_finite(public, _seen):
                return False
    return True


def result_validator() -> Optional[Callable]:
    """The NaN/Inf result validator for fit paths — :func:`check_finite`
    when detection is armed, else ``None``.

    Armed when a fault plan is installed (``utils.faults``; chaos tests
    must detect their own injected NaNs) or the active session opts in
    via ``spark.recovery.validate=on``. Off by default: a legitimately
    divergent fit (pathological data, zero valid rows) must keep
    returning its NaNs rather than silently refitting down the fallback
    ladder to *different* coefficients. Device errors always retry
    regardless — they never carry a legitimate result."""
    from . import faults as _faults

    if _faults.active() is not None:
        return check_finite
    try:
        from ..session import TpuSession

        s = TpuSession.active()
        from ..config import CONF_TRUE

        if s is not None and str(
                s.conf.get("spark.recovery.validate", "off")).lower() \
                in CONF_TRUE:
            return check_finite
    except Exception:
        pass
    return None


# ---------------------------------------------------------------------------
# The retry / fallback engine
# ---------------------------------------------------------------------------

def _retryable_errors() -> tuple:
    return (jax.errors.JaxRuntimeError, DeadlineExceeded)


def resilient_call(fn: Callable, *, site: str = "call",
                   policy: Optional[RetryPolicy] = None,
                   validate: Optional[Callable] = None,
                   fallbacks: Sequence[Tuple[str, Callable]] = (),
                   breaker: Optional[CircuitBreaker] = None,
                   on_failure: Optional[Callable] = None,
                   log: RecoveryLog = None):
    """Run ``fn()`` under the full resilience policy.

    The execution plan is a **ladder**: ``[("primary", fn)] + fallbacks``.
    Each rung gets up to ``policy.max_attempts`` attempts with exponential
    backoff + deterministic jitter between them; a rung whose breaker key
    (``site/rung``) is open is skipped outright (one ``circuit_skip``
    event), and when every rung fails the ladder raises
    :class:`FitFailure`. An attempt fails on a device error
    (``XlaRuntimeError``), a :class:`DeadlineExceeded`, or a result
    rejected by ``validate`` — the detection/lineage-replay loop.

    ``on_failure(attempt, error_or_none)`` runs after each failed attempt
    (cache clearing, re-seeding); when it returns a callable, that
    callable REPLACES the current rung's function for the remaining
    attempts — the downgrade hook (e.g. swap an ``owlqn`` solve for
    ``normal``).

    Every decision is recorded in ``log`` (default :data:`RECOVERY_LOG`);
    a clean first-attempt success records nothing.
    """
    from . import faults as _faults

    policy = policy or active_policy(site)
    log = log or RECOVERY_LOG
    started = time.monotonic()
    ladder = [("primary", fn)] + list(fallbacks)
    last_err: Optional[BaseException] = None
    last_cause = ""
    ran_any = False

    for rung_idx, (rung, call) in enumerate(ladder):
        key = f"{site}/{rung}"
        if breaker is not None and not breaker.allow(key):
            log.record(site, "circuit_skip", rung=rung,
                       detail="breaker open; skipping rung")
            continue
        ran_any = True
        if rung_idx > 0:
            log.record(site, "fallback", rung=rung, cause=last_cause,
                       detail=f"degrading to {rung!r}")
        for attempt in range(1, policy.max_attempts + 1):
            if policy.total_deadline is not None and \
                    time.monotonic() - started > policy.total_deadline:
                log.record(site, "deadline", rung=rung, attempt=attempt,
                           detail="total deadline exhausted")
                raise FitFailure(
                    f"{site}: total deadline of {policy.total_deadline:.3g}"
                    f" s exhausted after {attempt - 1} attempt(s) on rung "
                    f"{rung!r}") from last_err
            err: Optional[BaseException] = None
            try:
                # block_until_ready INSIDE the attempt: jax dispatch is
                # async, so a real device fault otherwise surfaces at the
                # caller's first host read — outside this ladder, past
                # the breaker, past every fallback. Syncing here also
                # makes attempt_deadline bound the actual device work,
                # not just the (instant) dispatch. Non-jax results pass
                # through untouched.
                out = _run_with_deadline(
                    lambda: jax.block_until_ready(call()),
                    policy.attempt_deadline)
            except _faults.Preemption:
                raise    # preemption is fit_or_resume's to handle
            except _retryable_errors() as e:
                err = e
            else:
                if validate is None or validate(out):
                    if breaker is not None:
                        breaker.record_success(key)
                    if attempt > 1 or rung_idx > 0:
                        log.record(site, "recovered", rung=rung,
                                   attempt=attempt)
                    return out
            last_err = err
            last_cause = (f"{type(err).__name__}: {err}" if err is not None
                          else "non-finite result")
            if breaker is not None and breaker.record_failure(key):
                log.record(site, "circuit_open", rung=rung, attempt=attempt,
                           cause=last_cause,
                           detail=f"breaker opened for {key!r}")
            wait = policy.backoff(attempt, site)
            log.record(site, "retry" if attempt < policy.max_attempts
                       else "exhausted", rung=rung, attempt=attempt,
                       cause=last_cause, backoff_s=wait)
            if on_failure is not None:
                downgraded = on_failure(attempt, err)
                if callable(downgraded):
                    call = downgraded
            if wait > 0.0:
                policy.sleep(wait)
    if not ran_any:
        raise CircuitOpenError(
            f"{site}: every rung's circuit breaker is open") from last_err
    raise FitFailure(
        f"{site}: failed after {len(ladder)} rung(s) x "
        f"{policy.max_attempts} attempt(s): {last_cause}") from last_err


def retry(fn: Callable, retries: int = 3,
          validate: Callable = check_finite,
          on_failure: Optional[Callable] = None):
    """Back-compat shim over :func:`resilient_call`: ``retries`` attempts,
    no backoff sleeps, no fallback ladder — the original task-retry loop
    (``spark.task.maxFailures`` analogue). ``on_failure(attempt, err)``
    runs between attempts; a callable return value downgrades ``fn``."""
    if retries < 1:
        raise ValueError("retries must be >= 1")
    policy = RetryPolicy(max_attempts=retries, backoff_base=0.0, jitter=0.0)
    try:
        return resilient_call(fn, site="retry", policy=policy,
                              validate=validate, on_failure=on_failure)
    except FitFailure as e:
        # preserve the historical message shape ("failed after N attempts")
        raise FitFailure(
            f"computation failed after {retries} attempts: "
            f"{e.__cause__ if e.__cause__ is not None else 'non-finite'}"
        ) from e.__cause__


# ---------------------------------------------------------------------------
# Checkpoint / resume (+ periodic mid-fit checkpointing)
# ---------------------------------------------------------------------------

def _has_stage(checkpoint_dir: str) -> bool:
    return os.path.exists(os.path.join(checkpoint_dir, "stage.json")) or \
        os.path.exists(os.path.join(checkpoint_dir, "metadata.json"))


def _atomic_save(model, checkpoint_dir: str,
                 progress: Optional[dict] = None) -> None:
    """Write to a sibling tmp dir, then one rename — a crash mid-save (the
    scenario this module exists for) must never leave a half-written dir
    that the resume branch would pick up. ``progress`` (the mid-fit
    checkpoint state) rides inside the same atomic rename."""
    import json
    import shutil

    from ..models.base import save_stage

    tmp = checkpoint_dir.rstrip("/\\") + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    save_stage(model, tmp)
    if progress is not None:
        with open(os.path.join(tmp, "progress.json"), "w") as f:
            json.dump(progress, f)
    shutil.rmtree(checkpoint_dir, ignore_errors=True)
    os.rename(tmp, checkpoint_dir)


def _read_progress(checkpoint_dir: str) -> Optional[dict]:
    import json

    try:
        with open(os.path.join(checkpoint_dir, "progress.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fit_converged(model) -> Optional[bool]:
    """Convergence flag from the model's fit trajectory, when it has one."""
    src = getattr(model, "_summary_source", None)
    if src is None or len(src) < 2 or src[1] is None:
        return None
    converged = getattr(src[1], "converged", None)
    if converged is None:
        return None
    return bool(np.asarray(converged))


def fit_or_resume(estimator, frame, checkpoint_dir: str, mesh=None,
                  retries: int = 1, checkpoint_every: Optional[int] = None,
                  max_preemptions: int = 8):
    """Fit with a persistent checkpoint: if ``checkpoint_dir`` already holds
    a saved, *finished* stage, load and return it WITHOUT refitting
    (crash/preemption resume); otherwise fit (with retry semantics when
    ``retries > 1``), save atomically, and return the model.

    ``checkpoint_every=N`` enables **periodic mid-fit checkpointing** for
    iterative estimators (those with a ``max_iter`` param): the fit runs
    in segments of N iterations, each segment checkpointing its model +
    a ``progress.json`` cursor in one atomic rename. A crash or
    (injected) :class:`~sparkdq4ml_tpu.utils.faults.Preemption` between
    segments resumes from the cursor — at most one segment of work is
    lost. Segments re-run the data pass; for the Gramian-statistics
    solvers that pass is one masked matmul, so the dominant cost
    (tracing + compile) is paid once and cached. A simulated preemption
    is caught here (up to ``max_preemptions`` times), recorded in the
    recovery log, and turned into an immediate resume — the in-process
    equivalent of the restart-after-eviction path.
    """
    import inspect

    from ..models.base import load_stage
    from . import faults as _faults

    iterative = (checkpoint_every is not None
                 and getattr(estimator, "max_iter", None) is not None)
    if _has_stage(checkpoint_dir):
        progress = _read_progress(checkpoint_dir)
        finished = progress is None or progress.get("finished", True)
        if finished:
            logger.info("resuming fitted stage from %s", checkpoint_dir)
            RECOVERY_LOG.record("fit", "resumed",
                                detail=f"loaded stage from {checkpoint_dir}")
            return load_stage(checkpoint_dir)
        # The cursor marks the stage UNFINISHED — never hand it back as
        # the final model, even when this call didn't ask for segmented
        # fitting: continue from the cursor (iterative) or refit in full.
        if iterative:
            logger.info("resuming mid-fit from %s (%s/%s iterations)",
                        checkpoint_dir, progress.get("budget"),
                        progress.get("total"))
            RECOVERY_LOG.record(
                "fit", "resumed", detail=(
                    f"mid-fit cursor at {progress.get('budget')}"
                    f"/{progress.get('total')} iterations"))
        else:
            logger.info("checkpoint %s holds an UNFINISHED mid-fit "
                        "segment; refitting in full", checkpoint_dir)

    takes_mesh = "mesh" in inspect.signature(estimator.fit).parameters

    def do_fit(est):
        _faults.inject("fit")
        if takes_mesh:
            return est.fit(frame, mesh=mesh)
        return est.fit(frame)

    preemptions = 0
    while True:
        try:
            if iterative:
                return _fit_segments(estimator, checkpoint_dir, do_fit,
                                     retries, int(checkpoint_every))
            model = retry(lambda: do_fit(estimator), retries=retries)
            _atomic_save(model, checkpoint_dir)
            return model
        except _faults.Preemption as e:
            preemptions += 1
            RECOVERY_LOG.record("fit", "preempted", attempt=preemptions,
                                cause=str(e))
            if preemptions >= max_preemptions:
                raise FitFailure(
                    f"fit preempted {preemptions} times; giving up") from e
            if _has_stage(checkpoint_dir):
                progress = _read_progress(checkpoint_dir)
                if progress is None or progress.get("finished", True):
                    # a completed stage landed before the preemption —
                    # the restart path would just load it
                    return load_stage(checkpoint_dir)
            # else: loop — re-enter exactly like a restarted process would


def _fit_segments(estimator, checkpoint_dir: str, do_fit, retries: int,
                  every: int):
    """Segmented fit: grow the iteration budget ``every`` at a time,
    checkpointing after each segment. Re-fitting with a larger budget is
    deterministic lineage replay (a fit is a pure function of its
    inputs), so the final model is identical to a single uninterrupted
    fit that converged within the same budget."""
    import copy

    if every < 1:
        raise ValueError("checkpoint_every must be >= 1")
    total = int(estimator.max_iter)
    progress = _read_progress(checkpoint_dir) or {}
    done = int(progress.get("budget", 0)) if not progress.get(
        "finished", False) else 0
    model = None
    while True:
        budget = min(done + every, total)
        est = copy.copy(estimator)
        est.max_iter = budget
        model = retry(lambda: do_fit(est), retries=retries)
        converged = _fit_converged(model)
        finished = bool(converged) or budget >= total
        _atomic_save(model, checkpoint_dir, progress={
            "budget": budget, "total": total, "finished": finished})
        RECOVERY_LOG.record(
            "fit", "checkpoint",
            detail=f"segment at {budget}/{total} iterations"
                   + (" (finished)" if finished else ""))
        if finished:
            return model
        done = budget
