from .logging import configure_logging
from .profiling import PhaseTimer, block_until_ready, timed, trace
from .recovery import FitFailure, check_finite, fit_or_resume, retry
