from .logging import configure_logging
from .profiling import PhaseTimer, block_until_ready, timed, trace
