from . import faults
from .logging import configure_logging, format_kv
from .profiling import PhaseTimer, block_until_ready, counters, timed, trace
from .recovery import (RECOVERY_LOG, CircuitBreaker, CircuitOpenError,
                       DeadlineExceeded, FitFailure, RecoveryEvent,
                       RecoveryLog, RetryPolicy, check_finite, fit_or_resume,
                       recovery_events, resilient_call, retry)
