from . import faults, observability
from .logging import configure_logging, format_kv
from .observability import METRICS, TRACER, metrics_snapshot, prometheus_text
from .profiling import PhaseTimer, block_until_ready, counters, timed, trace
from .recovery import (RECOVERY_LOG, CircuitBreaker, CircuitOpenError,
                       DeadlineExceeded, FitFailure, RecoveryEvent,
                       RecoveryLog, RetryPolicy, check_finite, fit_or_resume,
                       recovery_events, resilient_call, retry)
