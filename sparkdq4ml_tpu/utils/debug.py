"""Numeric-safety debug switches (SURVEY.md §5 "Race detection / sanitizers").

The reference stack has no sanitizers to mirror (no native code, no app-level
threads); the JAX-native equivalent is runtime NaN/Inf detection in compiled
programs — the numerics sanitizer for a pure-SPMD framework. Enable in test
or debugging sessions; it forces a device sync per op, so keep it out of
benchmarks.
"""

from __future__ import annotations

import jax


def enable_nan_checks(enable: bool = True) -> None:
    """Raise on any NaN produced inside jitted code (``jax_debug_nans``)."""
    jax.config.update("jax_debug_nans", enable)


def enable_inf_checks(enable: bool = True) -> None:
    jax.config.update("jax_debug_infs", enable)


class nan_checks:
    """Context manager: ``with nan_checks(): model = lr.fit(df)``."""

    def __init__(self, enable: bool = True):
        self.enable = enable
        self._saved = None

    def __enter__(self):
        self._saved = jax.config.jax_debug_nans
        jax.config.update("jax_debug_nans", self.enable)
        return self

    def __exit__(self, *exc):
        jax.config.update("jax_debug_nans", self._saved)
        return False


def backend_initializes(timeout_s: int = 150) -> bool:
    """True when the default JAX backend comes up in a THROWAWAY process.

    A tunneled-TPU pool can wedge (device claim blocks forever inside PJRT
    init — observed when a prior client dies mid-claim); probing in a
    subprocess lets callers fall back to CPU instead of hanging. Shared by
    ``bench.py`` and ``__graft_entry__.dryrun_multichip``.
    """
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False
