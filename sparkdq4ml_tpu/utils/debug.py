"""Numeric-safety debug switches (SURVEY.md §5 "Race detection / sanitizers").

The reference stack has no sanitizers to mirror (no native code, no app-level
threads); the JAX-native equivalent is runtime NaN/Inf detection in compiled
programs — the numerics sanitizer for a pure-SPMD framework. Enable in test
or debugging sessions; it forces a device sync per op, so keep it out of
benchmarks.
"""

from __future__ import annotations

import jax


def enable_nan_checks(enable: bool = True) -> None:
    """Raise on any NaN produced inside jitted code (``jax_debug_nans``)."""
    jax.config.update("jax_debug_nans", enable)


def enable_inf_checks(enable: bool = True) -> None:
    jax.config.update("jax_debug_infs", enable)


class nan_checks:
    """Context manager: ``with nan_checks(): model = lr.fit(df)``."""

    def __init__(self, enable: bool = True):
        self.enable = enable
        self._saved = None

    def __enter__(self):
        self._saved = jax.config.jax_debug_nans
        jax.config.update("jax_debug_nans", self.enable)
        return self

    def __exit__(self, *exc):
        jax.config.update("jax_debug_nans", self._saved)
        return False


def probe_backend_platform(timeout_s: float = 150):
    """The default backend's platform name, probed in a THROWAWAY process —
    or ``None`` when the backend fails to come up.

    A tunneled-TPU pool can wedge (device claim blocks forever inside PJRT
    init — observed when a prior client dies mid-claim); probing in a
    subprocess lets callers fall back to CPU instead of hanging. Returning
    the platform (not just a bool) lets ``master="tpu[...]"`` distinguish
    "backend wedged" from "machine simply has no TPU".
    """
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, timeout=timeout_s, text=True)
        if proc.returncode != 0:
            return None
        lines = proc.stdout.strip().splitlines()
        plat = lines[-1] if lines else None
        if plat:
            # every fresh success feeds the cross-process cache, so e.g.
            # bench's retry probe spares the TpuSession right after it
            # from paying a duplicate cold-import subprocess
            _store_probe_platform(plat)
        return plat
    except (subprocess.TimeoutExpired, OSError):
        return None


def backend_initializes(timeout_s: float = 150) -> bool:
    """True when the default JAX backend comes up in a THROWAWAY process.
    Shared by ``bench.py``, ``__graft_entry__.dryrun_multichip`` and
    ``TpuSession``; see :func:`probe_backend_platform`."""
    return probe_backend_platform(timeout_s) is not None


def backend_initializes_retry(probe_timeout_s: int = 150,
                              deadline_s: float = 0.0,
                              interval_s: float = 60.0,
                              log=None) -> bool:
    """Bounded-retry probe: keep probing a wedged backend until it comes up
    or ``deadline_s`` of wall-clock elapses.

    A transient tunnel wedge must not cost an entire bench capture (it did
    in round 3 — one failed 150 s probe conceded the whole round to CPU).
    ``deadline_s=0`` degrades to the single probe. Returns as soon as a
    probe succeeds; sleeps ``interval_s`` between failed probes.
    """
    import time

    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        if backend_initializes(probe_timeout_s):
            if log is not None and attempt > 1:
                log("backend came up on probe attempt %d (%.0f s in)"
                    % (attempt, time.monotonic() - start))
            return True
        remaining = deadline_s - (time.monotonic() - start)
        if remaining <= 0:
            return False
        if log is not None:
            log("backend probe %d failed; retrying for another %.0f s"
                % (attempt, remaining))
        time.sleep(min(interval_s, max(remaining, 0.0)))


_ENSURED_PLATFORM: str = ""
_FELL_BACK: bool = False


def fell_back_to_cpu() -> bool:
    """True when :func:`ensure_backend` pinned CPU because the default
    backend was wedged (as opposed to CPU being forced or already live)."""
    return _FELL_BACK


def process_on_cpu() -> bool:
    """True when THIS process is already committed to the CPU backend —
    an earlier wedge fallback pinned it, or a CPU backend initialized
    first. Backends are per-process: once true, no accelerator probe can
    help this process; only a fresh one can claim the device."""
    if _FELL_BACK:
        return True
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends) and jax.default_backend() == "cpu"
    except Exception:
        return False


def ensure_backend(timeout_s: float = 150) -> str:
    """Make THIS process safe to initialize a JAX backend, probing first.

    Entry-point guard (VERDICT r3 item 3): ``jax.devices()`` on a wedged
    tunneled-TPU pool blocks forever inside PJRT init, which made every
    user-facing entry point (``TpuSession``, the examples) hang. This
    probes the default backend in a throwaway subprocess and, when the
    probe fails, pins this process to CPU *before* any backend init —
    the session then comes up degraded instead of never
    (the reference's session init always succeeds,
    ``DataQuality4MachineLearningApp.java:38-41``).

    Returns the platform string this process will use (``"cpu"`` after a
    fallback, ``"default"`` when the stock backend is healthy). No-ops —
    cheaply — when a platform was already forced via ``JAX_PLATFORMS``,
    when a backend is already live in-process, or on a repeat call.
    """
    global _ENSURED_PLATFORM, _FELL_BACK
    import logging
    import os

    if _ENSURED_PLATFORM:
        return _ENSURED_PLATFORM
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:      # backend already up in-process:
            _ENSURED_PLATFORM = jax.default_backend()
            return _ENSURED_PLATFORM  # probing can't help, hanging is past
    except Exception:
        pass
    forced = os.environ.get("JAX_PLATFORMS", "")
    if forced:
        # Make the env choice authoritative IN-PROCESS too: a site hook
        # (sitecustomize force-registering a tunneled backend) can override
        # the env var, in which case trusting it alone would still hang.
        try:
            jax.config.update("jax_platforms", forced)
        except Exception:
            pass
        _ENSURED_PLATFORM = forced
        return forced
    plat = probe_platform_cached(timeout_s)
    if plat is not None:
        _ENSURED_PLATFORM = "default"
        return _ENSURED_PLATFORM
    logging.getLogger(__name__).warning(
        "default JAX backend did not initialize within %.0f s (wedged "
        "device tunnel?); falling back to backend=cpu", timeout_s)
    jax.config.update("jax_platforms", "cpu")
    _ENSURED_PLATFORM = "cpu"
    _FELL_BACK = True
    return _ENSURED_PLATFORM


def probe_platform_cached(timeout_s: float = 150):
    """Cached-or-fresh probe: the default backend's platform, or None.

    Only HEALTHY verdicts are cached (TTL 600 s,
    ``SPARKDQ4ML_PROBE_CACHE_TTL=0`` disables): the probe subprocess pays
    a cold jax import + device claim, which short-lived scripts shouldn't
    each re-pay — but a cached *negative* would amplify one transient
    wedge into a TTL-long silent-CPU outage, so failures always re-probe.
    """
    plat = _cached_probe_platform()
    if plat is None:
        plat = probe_backend_platform(timeout_s)  # stores on success
    return plat


def _probe_cache_path() -> str:
    import os
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else "u"  # windows: no getuid
    return os.path.join(tempfile.gettempdir(),
                        f"sparkdq4ml_probe_{uid}.json")


def _probe_cache_ttl() -> float:
    import os

    try:
        return float(os.environ.get("SPARKDQ4ML_PROBE_CACHE_TTL", "600"))
    except ValueError:
        return 600.0


def _cached_probe_platform():
    """Recent healthy-probe platform from the cross-process cache, else
    None (missing, stale, disabled, or unreadable)."""
    import json
    import time

    ttl = _probe_cache_ttl()
    if ttl <= 0:
        return None
    try:
        with open(_probe_cache_path()) as f:
            rec = json.load(f)
        if time.time() - float(rec["t"]) < ttl:
            plat = rec.get("platform")
            return str(plat) if plat else None
    except Exception:
        pass
    return None


def _store_probe_platform(platform: str) -> None:
    import json
    import os
    import time

    if _probe_cache_ttl() <= 0:
        return
    try:
        path = _probe_cache_path()
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"platform": str(platform), "t": time.time()}, f)
        os.replace(tmp, path)  # atomic vs concurrent probers
    except Exception:
        pass
