"""Numeric-safety debug switches (SURVEY.md §5 "Race detection / sanitizers").

The reference stack has no sanitizers to mirror (no native code, no app-level
threads); the JAX-native equivalent is runtime NaN/Inf detection in compiled
programs — the numerics sanitizer for a pure-SPMD framework. Enable in test
or debugging sessions; it forces a device sync per op, so keep it out of
benchmarks.
"""

from __future__ import annotations

import jax


def enable_nan_checks(enable: bool = True) -> None:
    """Raise on any NaN produced inside jitted code (``jax_debug_nans``)."""
    jax.config.update("jax_debug_nans", enable)


def enable_inf_checks(enable: bool = True) -> None:
    jax.config.update("jax_debug_infs", enable)


class nan_checks:
    """Context manager: ``with nan_checks(): model = lr.fit(df)``."""

    def __init__(self, enable: bool = True):
        self.enable = enable
        self._saved = None

    def __enter__(self):
        self._saved = jax.config.jax_debug_nans
        jax.config.update("jax_debug_nans", self.enable)
        return self

    def __exit__(self, *exc):
        jax.config.update("jax_debug_nans", self._saved)
        return False


def probe_backend_platform(timeout_s: float = 150):
    """The default backend's platform name, probed in a THROWAWAY process —
    or ``None`` when the backend fails to come up.

    A tunneled-TPU pool can wedge (device claim blocks forever inside PJRT
    init — observed when a prior client dies mid-claim); probing in a
    subprocess lets callers fall back to CPU instead of hanging. Returning
    the platform (not just a bool) lets ``master="tpu[...]"`` distinguish
    "backend wedged" from "machine simply has no TPU".
    """
    import subprocess
    import sys
    import time

    try:
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print(jax.default_backend())"],
            capture_output=True, timeout=timeout_s, text=True)
        latency = time.monotonic() - t0
        if proc.returncode != 0:
            return None
        lines = proc.stdout.strip().splitlines()
        plat = lines[-1] if lines else None
        if plat:
            # every fresh success feeds the cross-process cache, so e.g.
            # bench's retry probe spares the TpuSession right after it
            # from paying a duplicate cold-import subprocess
            _store_probe_platform(plat, latency)
        return plat
    except (subprocess.TimeoutExpired, OSError):
        return None


def backend_initializes(timeout_s: float = 150) -> bool:
    """True when the default JAX backend comes up in a THROWAWAY process.
    Shared by ``bench.py``, ``__graft_entry__.dryrun_multichip`` and
    ``TpuSession``; see :func:`probe_backend_platform`."""
    return probe_backend_platform(timeout_s) is not None


def backend_initializes_retry(probe_timeout_s: int = 150,
                              deadline_s: float = 0.0,
                              interval_s: float = 60.0,
                              log=None) -> bool:
    """Bounded-retry probe: keep probing a wedged backend until it comes up
    or ``deadline_s`` of wall-clock elapses.

    A transient tunnel wedge must not cost an entire bench capture (it did
    in round 3 — one failed 150 s probe conceded the whole round to CPU).
    ``deadline_s=0`` degrades to the single probe. Returns as soon as a
    probe succeeds; sleeps ``interval_s`` between failed probes.
    """
    import time

    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        if backend_initializes(probe_timeout_s):
            if log is not None and attempt > 1:
                log("backend came up on probe attempt %d (%.0f s in)"
                    % (attempt, time.monotonic() - start))
            return True
        remaining = deadline_s - (time.monotonic() - start)
        if remaining <= 0:
            return False
        if log is not None:
            log("backend probe %d failed; retrying for another %.0f s"
                % (attempt, remaining))
        time.sleep(min(interval_s, max(remaining, 0.0)))


_ENSURED_PLATFORM: str = ""
_FELL_BACK: bool = False

# Single-flight latch for ensure_backend's slow path: reachable from any
# user thread via Frame.__init__, and concurrent first-touches must not
# race the probe + watchdog (see ensure_backend).
import threading as _threading

_ENSURE_LOCK = _threading.Lock()

# Set in the environment of a process that the init watchdog re-exec'd
# pinned to CPU after the REAL backend init wedged (see
# ``bounded_backend_init``); lets the fresh process know it is a fallback.
_REEXEC_MARKER = "SPARKDQ4ML_WEDGE_REEXECED"


def fell_back_to_cpu() -> bool:
    """True when :func:`ensure_backend` pinned CPU because the default
    backend was wedged (as opposed to CPU being forced or already live) —
    including via the init-watchdog re-exec, which lands in a fresh
    process carrying the re-exec marker."""
    import os

    return _FELL_BACK or os.environ.get(_REEXEC_MARKER) == "1"


def _banner(msg: str) -> None:
    """User-facing liveness line on stderr: session init can legitimately
    sit in a 150 s probe / backend claim, and silence there reads as a
    hang (VERDICT r4: 'minutes of dead silence before the hang even
    starts'). stderr, unconditional — logging may not be configured yet."""
    import sys

    try:
        print(f"[sparkdq4ml-tpu] {msg}", file=sys.stderr, flush=True)
    except Exception:
        pass


def _probe_timeout() -> float:
    """``SPARKDQ4ML_PROBE_TIMEOUT`` (seconds), default 150 — the env
    default for callers without a session config (the ``Frame`` boundary
    guard, the driver entry)."""
    import os

    try:
        return float(os.environ.get("SPARKDQ4ML_PROBE_TIMEOUT", "150"))
    except ValueError:
        return 150.0


def _probe_disabled() -> bool:
    """``SPARKDQ4ML_BACKEND_PROBE=off|0|false`` disables the subprocess
    probe + bounded init entirely — the env-level twin of the session's
    ``spark.backend.probe=off``. Required on multi-host pod ranks that
    build Frames BEFORE their session: a transient probe failure on one
    rank would pin it to CPU while its peers claim accelerators,
    desyncing the mesh (the session's multihost path skips the probe for
    the same reason)."""
    import os

    from ..config import CONF_FALSE

    return os.environ.get("SPARKDQ4ML_BACKEND_PROBE", "").lower() \
        in CONF_FALSE


def bounded_backend_init(timeout_s: "Optional[float]" = None) -> None:
    """First REAL backend touch in THIS process, bounded by a watchdog.

    A healthy probe subprocess does NOT guarantee this process's PJRT init
    returns: the wedge is intermittent, and the demonstrated round-4
    failure was exactly 'probe passes, then ``jax.devices()`` in the main
    process blocks forever'. A thread cannot rescue that — the stuck init
    holds the backend lock — so on expiry the watchdog logs loudly and
    **re-execs this process pinned ``JAX_PLATFORMS=cpu``** (state is lost,
    liveness is preserved; the fresh process sees ``fell_back_to_cpu()``
    True via the env marker). When re-exec is impossible (``python -c``,
    embedded interpreter), it exits with code 86 and a remediation line
    instead of hanging forever. Disable with
    ``SPARKDQ4ML_INIT_WATCHDOG=0`` (e.g. when embedding in a host app
    that must never be re-exec'd).

    This is the reference's session-liveness contract — init always
    succeeds (`DataQuality4MachineLearningApp.java:38-41`) — extended to
    'or degrades to CPU in bounded time'. ``timeout_s`` defaults to
    ``SPARKDQ4ML_PROBE_TIMEOUT`` (else 150 s), like ``ensure_backend``.
    """
    import os
    import sys
    import threading

    import jax as _jax

    if timeout_s is None:
        timeout_s = _probe_timeout()

    from ..config import CONF_FALSE

    if os.environ.get("SPARKDQ4ML_INIT_WATCHDOG", "1") in CONF_FALSE:
        _jax.devices()
        return
    done = threading.Event()

    def _watchdog():
        if done.wait(timeout_s):
            return
        _banner(
            f"backend init did not return within {timeout_s:.0f} s "
            "(wedged device tunnel?); re-executing pinned to "
            "JAX_PLATFORMS=cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ[_REEXEC_MARKER] = "1"
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        # sys.orig_argv preserves the interpreter's REAL command line —
        # including `-m pkg` and `-c src` forms that sys.argv mangles
        # (under `-m`, argv[0] is the resolved __main__.py and a naive
        # script re-exec would drop the package context and die on its
        # first relative import). No orig_argv (<3.10) falls back to the
        # plain-script form; stdin/interactive runs can't re-exec at all.
        orig = list(getattr(sys, "orig_argv", []) or [])
        if len(orig) > 1 and orig[1] not in ("", "-"):
            try:
                os.execv(sys.executable, [sys.executable] + orig[1:])
            except OSError:
                pass
        else:
            argv0 = sys.argv[0] if sys.argv else ""
            if argv0 and argv0 != "-c" and os.path.exists(argv0):
                try:
                    os.execv(sys.executable, [sys.executable] + sys.argv)
                except OSError:
                    pass
        _banner("cannot re-exec this process (no script argv); exiting 86 "
                "— re-run with JAX_PLATFORMS=cpu to skip the wedged device")
        os._exit(86)

    t = threading.Thread(target=_watchdog, daemon=True,
                         name="sparkdq4ml-init-watchdog")
    t.start()
    try:
        _jax.devices()
    finally:
        done.set()


def process_on_cpu() -> bool:
    """True when THIS process is already committed to the CPU backend —
    an earlier wedge fallback pinned it, or a CPU backend initialized
    first. Backends are per-process: once true, no accelerator probe can
    help this process; only a fresh one can claim the device."""
    import os

    if _FELL_BACK or os.environ.get(_REEXEC_MARKER) == "1":
        return True
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends) and jax.default_backend() == "cpu"
    except Exception:
        return False


def ensure_backend(timeout_s: "Optional[float]" = None) -> str:
    """Make THIS process safe to initialize a JAX backend, probing first.

    Entry-point guard (VERDICT r3 item 3): ``jax.devices()`` on a wedged
    tunneled-TPU pool blocks forever inside PJRT init, which made every
    user-facing entry point (``TpuSession``, the examples, and bare
    ``Frame`` construction in direct-library use) hang. This probes the
    default backend in a throwaway subprocess and, when the probe fails,
    pins this process to CPU *before* any backend init — the session
    then comes up degraded instead of never (the reference's session
    init always succeeds, ``DataQuality4MachineLearningApp.java:38-41``).

    ``timeout_s`` defaults to ``SPARKDQ4ML_PROBE_TIMEOUT`` (else 150 s) —
    callers without a session config (the ``Frame`` boundary guard) get
    an env-tunable bound.

    Returns the platform string this process will use (``"cpu"`` after a
    fallback, ``"default"`` when the stock backend is healthy). No-ops —
    cheaply — when a platform was already forced via ``JAX_PLATFORMS``,
    when a backend is already live in-process, or on a repeat call.
    """
    global _ENSURED_PLATFORM, _FELL_BACK

    if _ENSURED_PLATFORM:
        return _ENSURED_PLATFORM  # hot path: Frame.__init__ calls this
    # Slow path is single-flight: Frame.__init__ makes this reachable
    # from arbitrary user threads, and two concurrent first-Frames must
    # not each pay a probe subprocess — worse, the loser's init watchdog
    # would count down while jax's internal backend-init lock is held by
    # the winner's (healthy) init, expiring into a spurious CPU re-exec.
    with _ENSURE_LOCK:
        if _ENSURED_PLATFORM:
            return _ENSURED_PLATFORM
        return _ensure_backend_locked(timeout_s)


def _ensure_backend_locked(timeout_s: "Optional[float]") -> str:
    global _ENSURED_PLATFORM, _FELL_BACK
    import logging
    import os

    if timeout_s is None:
        timeout_s = _probe_timeout()
    if _probe_disabled():
        # Env-level probe opt-out (multi-host pod ranks, users who accept
        # the raw init): behave like the unguarded library — trust the
        # default backend init unconditionally.
        _ENSURED_PLATFORM = "default"
        return _ENSURED_PLATFORM
    if os.environ.get(_REEXEC_MARKER) == "1":
        # We ARE the init-watchdog's fallback process. Pin CPU in the
        # config too: a site hook (sitecustomize) re-forces the tunneled
        # platform in jax.config on EVERY interpreter start — including
        # this one — and jax.config outranks the env var, so without this
        # pin the fallback process would re-walk the very wedge it was
        # re-exec'd to escape (an infinite re-exec loop).
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        os.environ["JAX_PLATFORMS"] = "cpu"
        _ENSURED_PLATFORM = "cpu"
        _FELL_BACK = True
        return _ENSURED_PLATFORM
    try:
        from jax._src import xla_bridge

        if xla_bridge._backends:      # backend already up in-process:
            _ENSURED_PLATFORM = jax.default_backend()
            return _ENSURED_PLATFORM  # probing can't help, hanging is past
    except Exception:
        pass
    # jax.config.jax_platforms outranks the env var in JAX itself, but
    # only a CPU pin there is trusted here: a process that deliberately
    # config-pinned itself to CPU (test harnesses, notebooks) has made
    # its choice, and probing the env's accelerator would walk it into a
    # 150 s wedged-tunnel probe for a backend it will never use. An
    # ACCELERATOR in the config is NOT trusted — this box's sitecustomize
    # force-sets the tunneled platform there on every interpreter start,
    # which is exactly the init that can wedge.
    try:
        cfg = jax.config.jax_platforms or ""
    except Exception:
        cfg = ""
    if cfg == "cpu" or cfg.startswith("cpu,"):
        _ENSURED_PLATFORM = cfg
        return cfg
    forced = os.environ.get("JAX_PLATFORMS", "") or cfg
    if forced:
        # Make the choice authoritative IN-PROCESS too: a site hook
        # (sitecustomize force-registering a tunneled backend) can override
        # the env var, in which case trusting it alone would still hang.
        try:
            jax.config.update("jax_platforms", forced)
        except Exception:
            pass
        if forced == "cpu" or forced.startswith("cpu,"):
            _ENSURED_PLATFORM = forced
            return forced
        # A forced ACCELERATOR platform is NOT exempt from the liveness
        # contract: this box exports JAX_PLATFORMS=axon for the tunneled
        # TPU, and when the tunnel wedges the forced init hangs exactly
        # like the default one (the round-4 judge reproduced the hang 3/3
        # under default env). Fall through to probe-then-bounded-init —
        # the probe subprocess inherits the forced env, so it probes the
        # forced platform. Opt out of the guard entirely with
        # SPARKDQ4ML_INIT_WATCHDOG=0 + spark.backend.probe=off.
    plat = probe_platform_cached(timeout_s, banner=True)
    if plat is not None:
        # A healthy probe is necessary but NOT sufficient (the wedge is
        # intermittent — round 4's demonstrated failure was 'probe passes,
        # real init hangs'): the first REAL backend touch in this process
        # must carry its own deadline. On expiry this re-execs pinned to
        # CPU and never returns; on a fast failure it falls through to
        # the CPU pin below.
        _banner(f"probe healthy ({plat}); initializing backend in-process "
                f"(bounded at {timeout_s:.0f} s)…")
        try:
            bounded_backend_init(timeout_s)
            _ENSURED_PLATFORM = "default"
            return _ENSURED_PLATFORM
        except RuntimeError as e:
            # e.g. a site hook pinned a platform whose registration fails
            # fast in-process even though the throwaway probe succeeded
            logging.getLogger(__name__).warning(
                "in-process backend init failed (%s); falling back to cpu",
                e)
    else:
        logging.getLogger(__name__).warning(
            "default JAX backend did not initialize within %.0f s (wedged "
            "device tunnel?); falling back to backend=cpu", timeout_s)
    jax.config.update("jax_platforms", "cpu")
    # pin the env too: subprocesses this process spawns (steady-phase
    # re-runs, the dryrun's virtual mesh) must not re-walk into the wedge
    os.environ["JAX_PLATFORMS"] = "cpu"
    _ENSURED_PLATFORM = "cpu"
    _FELL_BACK = True
    return _ENSURED_PLATFORM


def probe_platform_cached(timeout_s: float = 150, banner: bool = False):
    """Cached-or-fresh probe: the default backend's platform, or None.

    Only HEALTHY verdicts are cached (TTL 600 s,
    ``SPARKDQ4ML_PROBE_CACHE_TTL=0`` disables): the probe subprocess pays
    a cold jax import + device claim, which short-lived scripts shouldn't
    each re-pay — but a cached *negative* would amplify one transient
    wedge into a TTL-long silent-CPU outage, so failures always re-probe.
    A cached verdict whose probe was SLOW (>half the timeout) is also
    skipped: a sluggish claim is the wedge's tell (the round-4 live hang
    began right at the ~150 s probe boundary), and serving it for a TTL
    would steer every process for 10 minutes toward the same near-wedged
    init (VERDICT r4 item 7).
    """
    plat = _cached_probe_platform(timeout_s)
    if plat is None:
        if banner:
            _banner(f"probing JAX backend in a subprocess "
                    f"(up to {timeout_s:.0f} s)…")
        plat = probe_backend_platform(timeout_s)  # stores on success
    return plat


def _probe_cache_path() -> str:
    import os
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else "u"  # windows: no getuid
    return os.path.join(tempfile.gettempdir(),
                        f"sparkdq4ml_probe_{uid}.json")


def _probe_cache_ttl() -> float:
    import os

    try:
        return float(os.environ.get("SPARKDQ4ML_PROBE_CACHE_TTL", "600"))
    except ValueError:
        return 600.0


def _cached_probe_platform(timeout_s: float = 150):
    """Recent healthy-probe platform from the cross-process cache, else
    None (missing, stale, disabled, unreadable — or recorded from a SLOW
    probe, latency > ``timeout_s``/2: the safety valve that keeps one
    near-wedged-but-successful claim from steering every process behind
    the TTL into an unguarded-feeling init)."""
    import json
    import time

    ttl = _probe_cache_ttl()
    if ttl <= 0:
        return None
    try:
        with open(_probe_cache_path()) as f:
            rec = json.load(f)
        if time.time() - float(rec["t"]) < ttl:
            latency = float(rec.get("latency_s", 0.0))
            if latency > timeout_s / 2.0:
                return None  # slow claim = the wedge's tell; re-probe
            plat = rec.get("platform")
            return str(plat) if plat else None
    except Exception:
        pass
    return None


def _store_probe_platform(platform: str, latency_s: float = 0.0) -> None:
    import json
    import os
    import time

    if _probe_cache_ttl() <= 0:
        return
    try:
        path = _probe_cache_path()
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"platform": str(platform), "t": time.time(),
                       "latency_s": round(float(latency_s), 3)}, f)
        os.replace(tmp, path)  # atomic vs concurrent probers
    except Exception:
        pass
