"""Device-memory accounting — live/peak buffer bytes at the XLA boundary.

"Memory Safe Computations with XLA Compiler" (PAPERS.md, arxiv 2206.14148)
makes the case this module answers: without buffer-level accounting at the
XLA boundary, "why did this query OOM / stall" is guesswork. Two sources,
merged best-effort:

* **Allocator statistics** — ``device.memory_stats()`` where the backend
  exposes them (TPU/GPU PJRT allocators report ``bytes_in_use`` /
  ``peak_bytes_in_use``). These are the ground truth for HBM pressure,
  including buffers XLA holds that no Python array references.
* **Live-array census** — ``jax.live_arrays()``: every jax Array the
  process still references, summed by static ``nbytes``. Portable to every
  backend (XLA:CPU reports no allocator stats) and attributable (per-dtype
  breakdown, largest buffers), at the cost of missing allocator-internal
  slack. Never a device sync: shapes/dtypes are host-side metadata.

Sampling feeds the observability registry (``mem.live_bytes`` /
``mem.peak_bytes`` gauges) and — when ``TRACER.mem_sample`` is on (EXPLAIN
ANALYZE turns it on for the duration of one query; ``spark.explain.memory``
gates it) — every finished span gets a ``peak_mem`` attribute: the max of
the live-bytes census at span entry and exit, improved to the allocator's
``peak_bytes_in_use`` delta where available.

Cost contract: nothing here runs on the default path. ``sample()`` walks
the live-array registry (O(#arrays), host-only) and is called only from
explicitly-enabled sampling sites or user-facing reports.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

#: Process-lifetime peak of the live-bytes census (monotone; reset_peak()).
_PEAK_LOCK = threading.Lock()
_PEAK_BYTES = 0


def _array_nbytes(a) -> int:
    """Static size of one jax Array — shape/dtype metadata, never a device
    read. Sharded arrays report the addressable footprint (nbytes covers
    the logical array; per-shard accounting would need addressable_shards,
    which this census deliberately avoids touching — shard iteration can
    materialize lazy views on some backends)."""
    try:
        return int(a.nbytes)
    except Exception:
        try:
            import numpy as np

            return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        except Exception:
            return 0


def live_bytes() -> int:
    """Total bytes of every live jax Array (host-side census, no sync)."""
    try:
        return sum(_array_nbytes(a) for a in jax.live_arrays())
    except Exception:
        return 0


def live_array_count() -> int:
    try:
        return len(jax.live_arrays())
    except Exception:
        return 0


def estimated_bytes(tree) -> int:
    """Static-shape byte estimate of a pytree (the portable fallback the
    fit/flush sites use to pre-size a dispatch): sum of
    ``prod(shape) * itemsize`` over array-like leaves. Never a device
    read — works on tracers, jax Arrays, and numpy alike."""
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape)) * np.dtype(dtype).itemsize
        except Exception:
            continue
    return total


def headroom(limit_bytes: int) -> int:
    """Bytes remaining under ``limit_bytes`` given the live-array census
    (0 when already over). Host-side metadata only — never a sync."""
    return max(0, int(limit_bytes) - live_bytes())


def would_fit(est_bytes: int, limit_bytes: int,
              live: Optional[int] = None) -> tuple[bool, int]:
    """Admission-gate predicate (the serving layer's memory gate): would a
    job estimated at ``est_bytes`` device bytes fit under ``limit_bytes``
    on top of what is live right now? Returns ``(fits, live_bytes_now)``
    so the caller can put the observed figure in its structured
    rejection. ``live`` lets a caller reuse a census it already took
    (e.g. before acquiring a scheduler lock); ``None`` = census here.
    The census is a lower bound on true allocator pressure (allocator
    slack is invisible on backends without memory_stats), so the gate is
    advisory, not a hard reservation — documented in README § Serving."""
    if live is None:
        live = live_bytes()
    live = int(live)
    return (live + max(int(est_bytes), 0) <= int(limit_bytes), live)


def device_stats() -> list[dict]:
    """Per-device allocator statistics where the backend exposes them
    (``[]`` on XLA:CPU). Keys mirror PJRT: ``bytes_in_use``,
    ``peak_bytes_in_use``, ``bytes_limit`` when present."""
    out = []
    try:
        for d in jax.devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            entry = {"device": str(d), "platform": d.platform}
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                      "largest_alloc_size", "num_allocs"):
                if k in stats:
                    entry[k] = int(stats[k])
            out.append(entry)
    except Exception:
        pass
    return out


def peak_bytes() -> int:
    """Process-lifetime peak of the live-bytes census (improved by the
    allocator peak where available)."""
    with _PEAK_LOCK:
        peak = _PEAK_BYTES
    alloc_peak = sum(s.get("peak_bytes_in_use", 0) for s in device_stats())
    return max(peak, alloc_peak)


def reset_peak() -> None:
    global _PEAK_BYTES
    with _PEAK_LOCK:
        _PEAK_BYTES = 0


def sample(update_gauges: bool = True) -> int:
    """One accounting sample: the live-bytes census, folded into the peak
    tracker and (by default) the ``mem.live_bytes`` / ``mem.peak_bytes``
    gauges. Returns the live-bytes figure."""
    global _PEAK_BYTES
    b = live_bytes()
    with _PEAK_LOCK:
        if b > _PEAK_BYTES:
            _PEAK_BYTES = b
        peak = _PEAK_BYTES
    if update_gauges:
        from . import observability as _obs

        _obs.METRICS.set_gauge("mem.live_bytes", b)
        _obs.METRICS.set_gauge("mem.peak_bytes", peak)
    return b


def memory_report(top: int = 5) -> dict:
    """One merged accounting view (``session.memory_report()``):

    * ``live_bytes`` / ``peak_bytes`` / ``live_arrays`` — the census,
    * ``by_dtype`` — live bytes per dtype string, descending,
    * ``largest`` — the ``top`` biggest live buffers (shape, dtype, bytes),
    * ``devices`` — allocator stats where the backend exposes them,
    * ``backend`` — the default backend name.
    """
    buffers = []
    try:
        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    by_dtype: dict[str, int] = {}
    total = 0
    for a in arrays:
        nb = _array_nbytes(a)
        total += nb
        dt = str(getattr(a, "dtype", "?"))
        by_dtype[dt] = by_dtype.get(dt, 0) + nb
        buffers.append((nb, tuple(getattr(a, "shape", ())), dt))
    buffers.sort(key=lambda t: t[0], reverse=True)
    global _PEAK_BYTES
    with _PEAK_LOCK:
        if total > _PEAK_BYTES:
            _PEAK_BYTES = total
        peak = _PEAK_BYTES
    stats = device_stats()
    alloc_peak = sum(s.get("peak_bytes_in_use", 0) for s in stats)
    return {
        "backend": jax.default_backend(),
        "live_bytes": total,
        "peak_bytes": max(peak, alloc_peak),
        "live_arrays": len(arrays),
        "by_dtype": dict(sorted(by_dtype.items(), key=lambda kv: -kv[1])),
        "largest": [{"bytes": nb, "shape": list(shape), "dtype": dt}
                    for nb, shape, dt in buffers[:max(int(top), 0)]],
        "devices": stats,
    }


class SpanSampler:
    """Entry/exit sampling pair for one span (created only when
    ``TRACER.mem_sample`` is on): ``peak_mem`` is the max of the census at
    the two boundaries, plus the allocator peak delta where stats exist."""

    __slots__ = ("entry_bytes", "entry_alloc_peak")

    def __init__(self):
        self.entry_bytes = sample(update_gauges=False)
        self.entry_alloc_peak = sum(
            s.get("peak_bytes_in_use", 0) for s in device_stats())

    def finish(self) -> dict:
        exit_bytes = sample()
        peak = max(self.entry_bytes, exit_bytes)
        alloc_peak = sum(
            s.get("peak_bytes_in_use", 0) for s in device_stats())
        if alloc_peak > self.entry_alloc_peak:
            peak = max(peak, alloc_peak)
        return {"peak_mem": peak, "mem_live_bytes": exit_bytes}


def span_sampler() -> Optional[SpanSampler]:
    try:
        return SpanSampler()
    except Exception:
        return None
