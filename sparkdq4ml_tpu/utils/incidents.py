"""Incident flight recorder — correlated evidence for the requests that
matter.

Counters tell an operator THAT a breaker tripped; they do not say which
request tree tripped it, what the recovery ladder did, or what the plan
cache looked like at that moment. On a trigger — a breaker transition, a
fault-ladder engagement, SLO burn crossing a threshold — the recorder
snapshots ONE self-contained incident bundle:

* the triggering request's span tree(s) from the tail sampler (joined by
  the wire trace id the client also holds),
* the metrics delta since the previous incident (what moved, not the
  whole registry),
* a bounded slice of the structured recovery log,
* plan evidence: statstore rows and device-cost-profile rows (bounded).

Bundles persist to a bounded on-disk incident dir (atomic tmp +
``os.replace``, oldest files pruned past ``spark.incident.maxBundles``)
behind the ``incident`` fault site with the standard degradation ladder:
a failed write falls back to in-memory retention (``incident.failed``),
and repeated failures disable the disk rung for the recorder's lifetime
so a dead volume cannot stall serving. With no dir configured the
recorder is purely in-memory.

Disabled-mode contract: every trigger hook guards on ``TRACER.enabled``
at the call site and :meth:`IncidentRecorder.record` re-checks
:meth:`active` first — with observability off (or ``spark.incident.*``
unset) no bundle is built, no disk is touched.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from . import faults as _faults
from . import observability as _obs
from . import profiling
from .recovery import RECOVERY_LOG

logger = logging.getLogger("sparkdq4ml_tpu.incidents")

#: Recovery-log events included per bundle (newest last).
RECOVERY_SLICE = 50
#: Statstore / cost-profile rows included per bundle.
PLAN_ROWS = 8
#: Consecutive disk-write failures before the disk rung is disabled.
DISK_FAIL_LIMIT = 3
#: In-memory bundle bound when disk is absent or degraded.
MEMORY_BUNDLES = 32


def _metrics_delta(mark: dict, now: dict) -> dict:
    """``{name: change}`` for every scalar metric that moved since
    ``mark`` (histogram summaries compare by their ``count``)."""
    out = {}
    for k, v in now.items():
        v0 = mark.get(k)
        if isinstance(v, dict):
            c0 = v0.get("count", 0) if isinstance(v0, dict) else 0
            d = v.get("count", 0) - c0
            if d:
                out[k] = {"count": d}
        elif isinstance(v, (int, float)):
            d = v - (v0 if isinstance(v0, (int, float)) else 0)
            if d:
                out[k] = d
    return out


class IncidentRecorder:
    """Bounded flight recorder; one process-global instance
    (:data:`RECORDER`). Thread-safe: triggers fire from worker threads,
    the asyncio wire thread, and the telemetry scrape thread."""

    def __init__(self):
        self.enabled = False
        self.directory = ""
        self.max_bundles = MEMORY_BUNDLES
        self.cooldown_s = 5.0
        self.slo_burn_threshold = 8.0
        self._memory: list = []       # bundles without a disk home
        self._index: dict = {}        # incident id -> "disk" | "memory"
        self._last_fire: dict = {}    # trigger -> monotonic seconds
        self._mark = None             # metrics snapshot at last bundle
        self._seq = 0
        self._disk_failures = 0
        self._disk_disabled = False
        self._lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------
    def configure(self, enabled: Optional[bool] = None,
                  directory: Optional[str] = None,
                  max_bundles: Optional[int] = None,
                  cooldown_s: Optional[float] = None,
                  slo_burn_threshold: Optional[float] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if directory is not None:
                self.directory = str(directory)
                self._disk_failures = 0
                self._disk_disabled = False
            if max_bundles is not None:
                self.max_bundles = max(1, int(max_bundles))
            if cooldown_s is not None:
                self.cooldown_s = max(0.0, float(cooldown_s))
            if slo_burn_threshold is not None:
                self.slo_burn_threshold = float(slo_burn_threshold)

    def reset(self) -> None:
        with self._lock:
            self._memory.clear()
            self._index.clear()
            self._last_fire.clear()
            self._mark = None
            self._seq = 0
            self._disk_failures = 0
            self._disk_disabled = False

    def active(self) -> bool:
        """Triggers only fire while observability is on AND the recorder
        is opted in (``spark.incident.enabled`` or a configured dir)."""
        return _obs.TRACER.enabled and (self.enabled
                                        or bool(self.directory))

    # -- recording --------------------------------------------------------
    def record(self, trigger: str, trace=None, detail: str = "",
               extra: Optional[dict] = None) -> Optional[str]:
        """Snapshot one incident bundle. Returns the incident id, or
        ``None`` when inactive or inside the trigger's cooldown window.
        Never raises — a broken recorder must not take serving down."""
        if not self.active():
            return None
        now_mono = time.monotonic()
        with self._lock:
            last = self._last_fire.get(trigger)
            if last is not None and now_mono - last < self.cooldown_s:
                return None
            self._last_fire[trigger] = now_mono
            self._seq += 1
            seq = self._seq
        try:
            return self._build_and_store(trigger, seq, trace, detail,
                                         extra)
        except Exception:
            logger.debug("incident recorder failed", exc_info=True)
            profiling.counters.increment("incident.failed")
            return None

    def _build_and_store(self, trigger, seq, trace, detail, extra):
        trace_id = getattr(trace, "trace_id", None) if trace is not None \
            else None
        incident_id = f"inc-{int(time.time())}-{seq:04d}-{trigger}"
        snap = _obs.metrics_snapshot()
        with self._lock:
            mark = self._mark or {}
            self._mark = snap
        bundle = {
            "id": incident_id,
            "time_s": time.time(),
            "trigger": trigger,
            "detail": detail,
            "trace_id": trace_id,
            # completed trees first; a trigger that fires mid-request
            # (breaker trip, requeue exhaustion) snapshots the still
            # in-flight bucket as a partial tree instead
            "trace_trees": (_obs.TAIL.lookup(trace_id)
                            or [t for t in
                                (_obs.TAIL.pending_tree(trace_id),)
                                if t])
            if trace_id else [],
            "retained_trace_ids": _obs.TAIL.retained_ids()[-16:],
            "metrics_delta": _metrics_delta(mark, snap),
            "recovery": [e.as_kv() for e in
                         RECOVERY_LOG.events()[-RECOVERY_SLICE:]],
            "plan_stats": self._plan_rows(),
            "cost_profile": self._cost_rows(),
            "dq": self._dq_rows(),
        }
        if extra:
            bundle.update(extra)
        where = self._persist(incident_id, bundle)
        with self._lock:
            self._index[incident_id] = where
            if where == "memory":
                self._memory.append(bundle)
                del self._memory[:max(0, len(self._memory)
                                      - self.max_bundles)]
        return incident_id

    @staticmethod
    def _plan_rows():
        try:
            from .statstore import STORE

            rep = STORE.report(drain=False)
            rows = rep.get("rows", rep) if isinstance(rep, dict) else rep
            if isinstance(rows, list):
                return rows[:PLAN_ROWS]
            return rows
        except Exception:
            return []

    @staticmethod
    def _cost_rows():
        try:
            from . import costprof

            rep = costprof.report(top=PLAN_ROWS, budget=0)
            rows = rep.get("rows", []) if isinstance(rep, dict) else []
            return rows[:PLAN_ROWS]
        except Exception:
            return []

    @staticmethod
    def _dq_rows():
        """DQ observatory snapshot (utils/dqprof.py) — drain_first=False:
        a dq-triggered incident fires DURING a drain, and the already-
        folded state is exactly the evidence worth capturing."""
        try:
            from . import dqprof

            rep = dqprof.report(top=PLAN_ROWS, drain_first=False)
            if not rep.get("enabled"):
                return {"enabled": False}
            return {"enabled": True,
                    "columns": rep.get("columns", [])[:PLAN_ROWS],
                    "rules": rep.get("rules", [])[:PLAN_ROWS]}
        except Exception:
            return {"enabled": False}

    # -- persistence ladder -----------------------------------------------
    def _persist(self, incident_id: str, bundle: dict) -> str:
        """Atomic disk write under the ``incident`` fault site; any
        failure degrades this bundle to in-memory retention, and repeated
        failures disable the disk rung entirely (the ladder's terminal
        rung — serving must never block on a dead volume)."""
        with self._lock:
            directory = self.directory
            disk_ok = bool(directory) and not self._disk_disabled
        if not disk_ok:
            return "memory"
        path = os.path.join(directory, f"{incident_id}.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            _faults.inject("incident")
            os.makedirs(directory, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=repr)
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            with self._lock:
                self._disk_failures += 1
                exhausted = self._disk_failures >= DISK_FAIL_LIMIT
                if exhausted:
                    self._disk_disabled = True
            profiling.counters.increment("incident.failed")
            RECOVERY_LOG.record(
                "incident", "fallback",
                rung="disabled" if exhausted else "memory",
                cause=f"{type(e).__name__}: {e}",
                detail=("disk rung disabled after "
                        f"{DISK_FAIL_LIMIT} consecutive failures"
                        if exhausted else
                        "bundle retained in-memory only"))
            return "memory"
        with self._lock:
            self._disk_failures = 0
        profiling.counters.increment("incident.written")
        self._prune(directory)
        return "disk"

    def _prune(self, directory: str) -> None:
        try:
            files = sorted(
                f for f in os.listdir(directory)
                if f.startswith("inc-") and f.endswith(".json"))
            for f in files[:max(0, len(files) - self.max_bundles)]:
                os.unlink(os.path.join(directory, f))
        except OSError:
            pass

    # -- views ------------------------------------------------------------
    def list(self) -> list:
        """Bounded listing, newest last: id, trigger, time, trace id,
        where the bundle lives."""
        out = []
        with self._lock:
            index = dict(self._index)
            memory = {b["id"]: b for b in self._memory}
            directory = self.directory
        for incident_id in sorted(index):
            row = {"id": incident_id, "stored": index[incident_id]}
            b = memory.get(incident_id)
            if b is None and index[incident_id] == "disk":
                b = self._load_disk(directory, incident_id)
            if b is not None:
                row.update({"trigger": b.get("trigger"),
                            "time_s": b.get("time_s"),
                            "trace_id": b.get("trace_id"),
                            "detail": b.get("detail")})
            out.append(row)
        return out[-self.max_bundles:]

    def get(self, incident_id: str) -> Optional[dict]:
        with self._lock:
            where = self._index.get(incident_id)
            memory = {b["id"]: b for b in self._memory}
            directory = self.directory
        if where is None:
            return None
        if incident_id in memory:
            return memory[incident_id]
        return self._load_disk(directory, incident_id)

    @staticmethod
    def _load_disk(directory: str, incident_id: str) -> Optional[dict]:
        if not directory:
            return None
        path = os.path.join(directory, f"{incident_id}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def report(self) -> dict:
        with self._lock:
            return {"active": self.active(),
                    "dir": self.directory,
                    "disk_disabled": self._disk_disabled,
                    "max_bundles": self.max_bundles,
                    "count": len(self._index),
                    "in_memory": len(self._memory)}


#: Process-global incident recorder.
RECORDER = IncidentRecorder()
