"""Device-cost observatory — per-plan AOT cost profiles + roofline math.

The engine measures *when* programs run (spans, EXPLAIN ANALYZE,
statstore wall-ms digests) and statically bounds *how much memory* they
may touch (dqaudit), but until this module no plan ever learned its
compute cost: achieved GFLOP/s, bytes moved, and collective traffic were
invisible. Here every ``observability.CACHES``-enumerable program
(pipeline plans, grouped lowerings, sharded stages/exchanges,
solver/fit programs) gets a :class:`CostProfile` extracted by the AOT
path in ``analysis/program/costs.py`` — ``jit(...).lower(...).compile()``
against the recorded abstract example args, zero device execution, zero
counted host syncs, zero counted compiles — cached per structural key
and persisted into the statstore so one extraction serves every later
session.

Joining a profile with the statstore's wall-ms history yields the
derived surfaces wired through four layers:

* EXPLAIN ANALYZE — ``est_flops`` / ``est_bytes`` / achieved ``gflops``
  / ``gbps`` and a roofline ``bound=compute|memory|sync|host`` verdict
  per operator node (``sql/parser.py``);
* sharded execution — the ``shard.skew`` balance gauge and
  ``shard.exchange_bytes[.<kind>]`` volume counters
  (``parallel/shard.py`` / ``ops/segments.py``);
* the TelemetryServer — ``/profile`` (per-plan cost + achieved JSON,
  top-N by device-time share) and ``/profile/trace?seconds=N`` (arms
  the managed ``utils/profiling`` jax-profiler capture);
* ``session.profile_report()`` — the fleet-wide roofline table.

Standing contracts honored: ``spark.costprof.enabled=false`` is a
one-flag-read no-op on every hook, the flush hot path never imports
this module (or ``analysis/``), extraction runs lazily on COLD surfaces
only (report/EXPLAIN/save/scrape) with a per-call budget so a scrape
never stalls behind an unbounded compile sweep, and the
``cost_profile`` fault site degrades extraction to "-" (unprofiled)
through the recovery engine instead of failing the surface.

Roofline semantics (see README "Device-cost observatory"): arithmetic
intensity = flops / bytes accessed, compared against the
``spark.costprof.ridge`` ridge point (flops/byte) — at/above is
``compute``-bound, below is ``memory``-bound; a program that pays a
host sync while moving almost nothing (< the sync floors) is
``sync``-bound; an operator with no device program at all is ``host``.
On the CPU sandbox the achieved numbers are structural (wall-clock is
host dispatch); TPU captures make them real.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..config import config
from .profiling import counters

logger = logging.getLogger("sparkdq4ml_tpu.costprof")

#: A profile below BOTH floors that still paid a host sync is verdicted
#: ``sync``-bound: the device work is too small for either roofline axis
#: to be the binding constraint — the boundary crossing is.
SYNC_FLOOR_BYTES = 1 << 16
SYNC_FLOOR_FLOPS = 1e5

#: Default extraction budget per cold-surface call (``/profile`` scrape,
#: EXPLAIN): at most this many NEW lower+compile extractions run; the
#: rest report as pending and fill in on later calls. Keeps a scrape's
#: latency bounded by a constant, not by the cache population.
EXTRACT_BUDGET = 8


class CostProfile:
    """One program's static cost profile — the ``cost_analysis()`` /
    ``memory_analysis()`` figures plus the trace-derived per-collective
    bytes. Structural per plan key: literals are hoisted out of keys, so
    one profile covers every literal/row-count the plan serves (at the
    recorded example bucket)."""

    __slots__ = ("flops", "transcendentals", "bytes_accessed",
                 "output_bytes", "collectives", "peak_bytes",
                 "argument_bytes", "devices", "extract_ms")

    def __init__(self, flops=0.0, transcendentals=0.0, bytes_accessed=0.0,
                 output_bytes=0.0, collectives=None, peak_bytes=None,
                 argument_bytes=None, devices=1, extract_ms=None):
        self.flops = float(flops)
        self.transcendentals = float(transcendentals)
        self.bytes_accessed = float(bytes_accessed)
        self.output_bytes = float(output_bytes)
        self.collectives = dict(collectives or {})
        self.peak_bytes = None if peak_bytes is None else int(peak_bytes)
        self.argument_bytes = (None if argument_bytes is None
                               else int(argument_bytes))
        self.devices = int(devices)
        self.extract_ms = extract_ms

    @property
    def collective_bytes(self) -> int:
        return int(sum(self.collectives.values()))

    @property
    def intensity(self) -> float:
        """Arithmetic intensity, flops per byte accessed."""
        return self.flops / max(self.bytes_accessed, 1.0)

    def to_doc(self) -> dict:
        doc = {
            "flops": self.flops,
            "transcendentals": self.transcendentals,
            "bytes_accessed": self.bytes_accessed,
            "output_bytes": self.output_bytes,
            "devices": self.devices,
        }
        if self.collectives:
            doc["collectives"] = dict(self.collectives)
        if self.peak_bytes is not None:
            doc["peak_bytes"] = self.peak_bytes
        if self.argument_bytes is not None:
            doc["argument_bytes"] = self.argument_bytes
        if self.extract_ms is not None:
            doc["extract_ms"] = self.extract_ms
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "CostProfile":
        return cls(
            flops=doc.get("flops", 0.0),
            transcendentals=doc.get("transcendentals", 0.0),
            bytes_accessed=doc.get("bytes_accessed", 0.0),
            output_bytes=doc.get("output_bytes", 0.0),
            collectives=doc.get("collectives"),
            peak_bytes=doc.get("peak_bytes"),
            argument_bytes=doc.get("argument_bytes"),
            devices=doc.get("devices", 1),
            extract_ms=doc.get("extract_ms"))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"CostProfile(flops={self.flops:g}, "
                f"bytes={self.bytes_accessed:g}, "
                f"collectives={self.collectives})")


#: Extraction-failed sentinel: cached so a program that cannot lower is
#: not re-compiled on every scrape; surfaces render "-" for it (the
#: cost_profile degradation ladder's terminal rung).
_FAILED = object()

_PROFILES: dict = {}
_LOCK = threading.Lock()


def enabled() -> bool:
    return bool(config.costprof_enabled)


def clear() -> None:
    """Drop every cached profile (tests; conf flips)."""
    with _LOCK:
        _PROFILES.clear()


def achieved(profile: Optional[CostProfile],
             wall_ms: Optional[float]) -> tuple:
    """``(gflops, gbps)`` achieved at a measured wall time — None/None
    when either side is unknown. Structural on the CPU sandbox,
    meaningful on TPU captures (module docstring)."""
    if profile is None or not wall_ms or wall_ms <= 0:
        return (None, None)
    secs = wall_ms / 1e3
    return (round(profile.flops / secs / 1e9, 3),
            round(profile.bytes_accessed / secs / 1e9, 3))


def roofline(profile: Optional[CostProfile],
             host_syncs: int = 0) -> Optional[str]:
    """The ``bound`` verdict: ``host`` when the operator ran without a
    device program, ``sync`` when it paid a host sync over near-zero
    device work, else ``compute``/``memory`` by arithmetic intensity vs
    the ``spark.costprof.ridge`` ridge point."""
    if profile is None:
        return "host"
    if host_syncs and profile.bytes_accessed < SYNC_FLOOR_BYTES \
            and profile.flops < SYNC_FLOOR_FLOPS:
        return "sync"
    if profile.intensity >= float(config.costprof_ridge):
        return "compute"
    return "memory"


def _record_statstore(key: str, cache: str, doc: dict) -> None:
    if not config.stats_enabled:
        return
    try:
        from . import statstore as _stats

        _stats.STORE.record_cost(key, f"cost:{cache}", doc)
    except Exception:
        logger.debug("cost-profile statstore hand-off failed",
                     exc_info=True)


def _stats_key(handle) -> str:
    """The statstore key this program's flushes record under — the
    producer declares it in ``meta["stats_key"]`` when it differs from
    the program key (the grouped engine keys stats by struct across its
    dense/sorted lowerings); the program key otherwise."""
    return handle.meta.get("stats_key") or handle.program_key


def _extract(handle) -> Optional[CostProfile]:
    """One extraction through the ``cost_profile`` fault site and its
    degradation ladder: ANY failure — injected or real — degrades to an
    unprofiled plan (surfaces render "-") with a recovery event; the
    observatory can go blind on a plan, never take a surface down."""
    from . import faults as _faults

    try:
        _faults.inject("cost_profile")
        from ..analysis.program import costs as _costs

        doc = _costs.extract(handle)
    except Exception as e:
        counters.increment("costprof.failed")
        from .recovery import RECOVERY_LOG

        RECOVERY_LOG.record(
            "cost_profile", "fallback", rung="unprofiled",
            cause=f"{type(e).__name__}: {e}",
            detail=f"cost extraction degraded; plan "
                   f"{handle.program_key[:80]!r} reports no profile")
        logger.debug("cost extraction failed for %r",
                     handle.program_key[:80], exc_info=True)
        return None
    if doc is None:
        return None
    counters.increment("costprof.extracted")
    # persist under the STATS key: that is the entry that accumulates
    # this program's wall/byte history, so the cost doc and the digests
    # it joins against live (and merge) together
    _record_statstore(_stats_key(handle), handle.cache, doc)
    return CostProfile.from_doc(doc)


def _cache_get(key: str):
    """(hit, profile) — hit False means never attempted."""
    with _LOCK:
        if key in _PROFILES:
            p = _PROFILES[key]
            return True, (None if p is _FAILED else p)
    return False, None


def _cache_put(key: str, profile: Optional[CostProfile]) -> None:
    with _LOCK:
        _PROFILES[key] = _FAILED if profile is None else profile


def _from_statstore(key: str) -> Optional[CostProfile]:
    """Persisted-profile fast path: a snapshot loaded at session init
    may already carry this key's cost doc — no lower+compile needed."""
    if not config.stats_enabled:
        return None
    try:
        from . import statstore as _stats

        doc = _stats.STORE.cost(key)
    except Exception:
        return None
    return CostProfile.from_doc(doc) if doc else None


def profiles_for(keys) -> dict:
    """``{key: CostProfile|None}`` for a batch of plan keys — cached,
    else adopted from the statstore, else extracted live; the registry
    is enumerated at most ONCE per call (EXPLAIN ANALYZE resolves every
    operator's key through one batch instead of one registry scan per
    node). COLD surfaces only: a miss can cost one XLA compile per key.
    A key with no live handle resolves None without being cached — its
    plan may land in a cache later (e.g. after an eviction cycle)."""
    out: dict = {}
    if not enabled():
        return {k: None for k in keys if k}
    missing: list = []
    for key in dict.fromkeys(k for k in keys if k):
        hit, prof = _cache_get(key)
        if hit:
            out[key] = prof
            continue
        prof = _from_statstore(key)
        if prof is not None:
            _cache_put(key, prof)
            out[key] = prof
        else:
            missing.append(key)
    if missing:
        from . import observability as _obs

        handles, _errors = _obs.CACHES.programs()
        by_key = {h.program_key: h for h in handles}
        for key in missing:
            h = by_key.get(key)
            if h is None:
                out[key] = None
                continue
            prof = _extract(h)
            _cache_put(key, prof)
            out[key] = prof
    return out


def profile_for(key: Optional[str]) -> Optional[CostProfile]:
    """The cost profile at one plan key (see :func:`profiles_for`).
    Returns None when disabled, unknown, or degraded."""
    if not key:
        return None
    return profiles_for((key,)).get(key)


def extract_all(budget: Optional[int] = None) -> dict:
    """Extract every registry-enumerable program's profile (cached keys
    are free; at most ``budget`` NEW extractions run — the rest stay
    pending for the next call). Returns ``{key: {"cache", "profile"}}``
    with ``profile`` None for degraded/pending entries, plus the
    pending count under ``extract_all.pending`` in :func:`report`."""
    out: dict = {}
    if not enabled():
        return out
    budget = EXTRACT_BUDGET if budget is None else max(int(budget), 0)
    from . import observability as _obs

    handles, _errors = _obs.CACHES.programs()
    fresh = 0
    for h in handles:
        key = h.program_key
        if key in out:
            continue
        hit, prof = _cache_get(key)
        pending = False
        if not hit:
            prof = _from_statstore(key) or _from_statstore(_stats_key(h))
            if prof is not None:
                _cache_put(key, prof)
            elif fresh < budget:
                prof = _extract(h)
                _cache_put(key, prof)
                fresh += 1
            else:
                pending = True
        out[key] = {"cache": h.cache, "profile": prof,
                    "pending": pending, "stats_key": _stats_key(h)}
    return out


def report(top: Optional[int] = None,
           budget: Optional[int] = None) -> dict:
    """The fleet-wide roofline view (``session.profile_report()`` and
    the HTTP ``/profile`` route): one row per enumerable program —
    static cost, statstore-joined achieved throughput, roofline verdict
    — ranked by device-time share (each key's recorded wall-ms mass over
    the fleet total). Cold surface: may extract (bounded by
    ``budget``) and drains the statstore's deferred observations."""
    if not enabled():
        return {"enabled": False, "entries": [], "size": 0, "pending": 0}
    entries = extract_all(budget=budget)
    stats_entry = None
    if config.stats_enabled:
        try:
            from . import statstore as _stats

            _stats.STORE.drain_pending()
            stats_entry = _stats.STORE.entry
        except Exception:
            stats_entry = None
    rows = []
    total_wall = 0.0
    for key, info in entries.items():
        prof = info["profile"]
        st = (stats_entry(info["stats_key"])
              if stats_entry is not None else None)
        wall = (st or {}).get("wall_ms") or {}
        wall_sum = float(wall.get("sum") or 0.0)
        wall_count = int(wall.get("count") or 0)
        wall_p50 = None
        if st is not None:
            try:
                from .statstore import Digest as _Digest

                wall_p50 = _Digest.from_doc(wall).p50() if wall_count \
                    else None
            except Exception:
                wall_p50 = None
        total_wall += wall_sum
        syncs = int((st or {}).get("host_syncs") or 0)
        gflops, gbps = achieved(prof, wall_p50)
        rows.append({
            "key": key[:160], "cache": info["cache"],
            "pending": info["pending"],
            "flops": None if prof is None else prof.flops,
            "transcendentals": (None if prof is None
                                else prof.transcendentals),
            "bytes": None if prof is None else prof.bytes_accessed,
            "output_bytes": (None if prof is None
                             else prof.output_bytes),
            "collectives": ({} if prof is None
                            else dict(prof.collectives)),
            "peak_bytes": None if prof is None else prof.peak_bytes,
            "devices": 1 if prof is None else prof.devices,
            "flushes": int((st or {}).get("flushes") or 0),
            "wall_ms_sum": round(wall_sum, 3),
            "wall_ms_p50": wall_p50,
            "gflops": gflops, "gbps": gbps,
            # every enumerable entry IS a device program, so a missing
            # profile here means pending/degraded — render null, never
            # the roofline's "host" verdict (that one is EXPLAIN's, for
            # operators that ran with no device program at all)
            "bound": (roofline(prof, syncs) if prof is not None
                      else None),
            "_wall": wall_sum,
        })
    for r in rows:
        wall_sum = r.pop("_wall")
        r["device_time_share"] = (round(wall_sum / total_wall, 4)
                                  if total_wall > 0 else None)
    rows.sort(key=lambda r: -(r["device_time_share"] or 0.0))
    pending = sum(1 for r in rows if r["pending"])
    if top is not None:
        rows = rows[:max(int(top), 0)]
    from .profiling import latest_capture

    return {"enabled": True, "entries": rows, "size": len(entries),
            "pending": pending, "total_wall_ms": round(total_wall, 3),
            "ridge_flops_per_byte": float(config.costprof_ridge),
            "capture": latest_capture()}
