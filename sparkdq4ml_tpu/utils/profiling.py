"""Tracing / profiling utilities (SURVEY.md §5 "Tracing / profiling").

The reference exposes nothing beyond post-hoc ``objectiveHistory`` prints
(`DataQuality4MachineLearningApp.java:133-136`). Here:

* :class:`PhaseTimer` — per-phase wall-clock for the pipeline runner (the
  observability the reference approximates with stdout banners),
* :func:`trace` — context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard/Perfetto, for the fit hot loop,
* :func:`block_until_ready` — honest timing helper (JAX dispatch is async;
  timings without a sync measure nothing),
* :data:`counters` — process-global named counters; the recovery layer
  (``utils.recovery.RECOVERY_LOG``) mirrors every retry/fallback/breaker
  event here as ``recovery.<action>``, so resilience activity shows up in
  the same place as performance telemetry.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Optional

import jax

logger = logging.getLogger("sparkdq4ml_tpu.profiling")


def block_until_ready(tree):
    return jax.block_until_ready(tree)


class Counters:
    """Thread-safe named monotonic counters (Spark-metrics analogue).

    The recovery subsystem increments ``recovery.retry``,
    ``recovery.fallback``, ``recovery.circuit_open``, … per structured
    event; anything else in the framework is free to add its own names.
    ``snapshot()`` returns a plain dict for reports/assertions."""

    def __init__(self):
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def increment(self, name: str, by: int = 1) -> int:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + by
            return self._counts[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self, prefix: str = "") -> dict:
        with self._lock:
            return {k: v for k, v in self._counts.items()
                    if k.startswith(prefix)}

    def clear(self, prefix: str = "") -> None:
        with self._lock:
            if not prefix:
                self._counts.clear()
            else:
                for k in [k for k in self._counts if k.startswith(prefix)]:
                    del self._counts[k]


#: Process-global counter registry (see :class:`Counters`).
counters = Counters()


class PhaseTimer:
    """Collects named phase durations; ``report()`` returns a dict.

    A first (cold) run through a jitted phase is dominated by XLA
    compilation; :meth:`steady` re-runs the phase against the compile
    cache so :meth:`report_pairs` can show (cold, steady) side by side —
    reading the cold number as throughput would be off by orders of
    magnitude (bench.py measures the same split).
    """

    def __init__(self):
        self.phases: dict[str, float] = {}
        self.steadies: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str, sync=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + dt
            logger.debug("phase %-20s %8.3f ms", name, dt * 1e3)

    def steady(self, name: str, fn, reps: int = 3, sync=None):
        """Median steady-state wall-clock of ``fn()`` over ``reps`` calls
        (run it AFTER the cold :meth:`phase` so compiles are cached);
        returns the last result.

        ``jax.block_until_ready`` only syncs jax pytrees — an opaque object
        (a Frame, a fitted model) passes through WITHOUT waiting for its
        pending dispatch. Pass ``sync`` to extract a device array from the
        result (e.g. ``lambda f: f.mask``) so the timing includes the async
        work; syncing is never a host read (bench.py's hygiene rule)."""
        times = []
        out = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(sync(out) if sync is not None else out)
            times.append(time.perf_counter() - t0)
        times.sort()
        self.steadies[name] = times[len(times) // 2]
        logger.debug("steady %-19s %8.3f ms", name,
                     self.steadies[name] * 1e3)
        return out

    def report(self) -> dict[str, float]:
        return dict(self.phases)

    def report_pairs(self) -> dict[str, dict[str, Optional[float]]]:
        """{phase: {"cold": s|None, "steady": s|None}} — cold includes
        compile. Steady-only names (no matching cold phase) are reported,
        not dropped."""
        names = list(self.phases) + [n for n in self.steadies
                                     if n not in self.phases]
        return {name: {"cold": self.phases.get(name),
                       "steady": self.steadies.get(name)}
                for name in names}


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None):
    """XLA profiler trace; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# Managed jax-profiler captures (the /profile/trace surface)
# ---------------------------------------------------------------------------
#
# :func:`trace` takes an explicit directory and manages nothing — fine
# for a one-off bench run, but the on-demand capture the telemetry
# endpoint arms (serve/http.py ``/profile/trace?seconds=N``) needs a
# bounded, discoverable home: captures land under one base directory,
# named ``cap-<timestamp>-<label>`` so a capture is attributable to the
# plan/context that armed it, retention is bounded by
# ``spark.profiling.maxCaptures`` (oldest pruned), and the newest path
# is surfaced in ``/profile`` for the operator to pull into
# TensorBoard/Perfetto. One capture at a time per process (the jax
# profiler is a process-global singleton).

#: Hard ceiling on an armed capture's duration (seconds) — a typo'd
#: ``?seconds=`` must not leave the profiler running for an hour.
MAX_CAPTURE_S = 60.0

_CAPTURE_LOCK = threading.Lock()
_CAPTURE_ACTIVE: Optional[str] = None     # path of the running capture


def capture_base_dir() -> str:
    """Home of managed captures: ``SPARKDQ4ML_CAPTURE_DIR`` env
    override, else ``~/.cache/sparkdq4ml_tpu/captures``."""
    import os

    env = os.environ.get("SPARKDQ4ML_CAPTURE_DIR")
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "sparkdq4ml_tpu", "captures")


def captures() -> list:
    """Managed capture directories, oldest first (timestamp-named, so
    lexicographic order IS age order)."""
    import os

    base = capture_base_dir()
    try:
        return sorted(
            os.path.join(base, d) for d in os.listdir(base)
            if d.startswith("cap-")
            and os.path.isdir(os.path.join(base, d)))
    except OSError:
        return []


def latest_capture() -> Optional[str]:
    """Newest managed capture path (``/profile`` surfaces it), or None."""
    caps = captures()
    return caps[-1] if caps else None


def prune_captures(keep: Optional[int] = None) -> int:
    """Drop the oldest managed captures past ``keep`` (default:
    ``spark.profiling.maxCaptures``); returns the pruned count.
    Best-effort — retention hygiene must never raise."""
    import shutil

    if keep is None:
        from ..config import config

        keep = int(config.profiling_max_captures)
    keep = max(int(keep), 1)
    pruned = 0
    for path in captures()[:-keep] if keep else captures():
        try:
            shutil.rmtree(path, ignore_errors=True)
            pruned += 1
        except OSError:
            pass
    return pruned


def capture_active() -> Optional[str]:
    with _CAPTURE_LOCK:
        return _CAPTURE_ACTIVE


def start_capture(seconds: float, label: str = "manual") -> str:
    """Arm one managed jax-profiler capture for ``seconds`` (clamped to
    :data:`MAX_CAPTURE_S`); a background timer stops it. Returns the
    capture path. Raises ``RuntimeError`` when a capture is already
    running — the profiler is process-global and two overlapping
    ``start_trace`` calls corrupt each other's sessions."""
    import os
    import re

    global _CAPTURE_ACTIVE
    seconds = min(max(float(seconds), 0.05), MAX_CAPTURE_S)
    safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", str(label))[:48] or "manual"
    name = f"cap-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}-{safe}"
    path = os.path.join(capture_base_dir(), name)
    with _CAPTURE_LOCK:
        if _CAPTURE_ACTIVE is not None:
            raise RuntimeError(
                f"a profiler capture is already running "
                f"({_CAPTURE_ACTIVE}); one capture at a time")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        _CAPTURE_ACTIVE = path
    counters.increment("profiling.captures")

    def _stop(armed=path):
        time.sleep(seconds)
        # bound to the capture THIS timer armed: a manual stop_capture
        # followed by a fresh arm must not be truncated by the stale
        # timer of the capture that already ended
        stop_capture(expected=armed)

    threading.Thread(target=_stop, daemon=True,
                     name="sparkdq4ml-capture-timer").start()
    return path


def stop_capture(expected: Optional[str] = None) -> Optional[str]:
    """Stop the running capture (idempotent); prunes retention and
    returns the finished capture's path (None when nothing ran).
    ``expected`` stops only when that specific capture is still the
    active one (the timer-thread contract)."""
    global _CAPTURE_ACTIVE
    with _CAPTURE_LOCK:
        if expected is not None and _CAPTURE_ACTIVE != expected:
            return None
        path, _CAPTURE_ACTIVE = _CAPTURE_ACTIVE, None
        if path is None:
            return None
        try:
            jax.profiler.stop_trace()
        except Exception:
            logger.debug("profiler stop_trace failed", exc_info=True)
    prune_captures()
    return path


@contextlib.contextmanager
def timed(label: str = "block", sync=None):
    """Log the wall-clock of a block.

    JAX dispatch is ASYNC: without ``sync`` this measures only enqueue
    time — pending device work is excluded, and a fused fit can "take"
    microseconds. Pass ``sync`` (a device array / pytree, same contract
    as ``PhaseTimer.phase``) to ``block_until_ready`` it before the clock
    stops, making the timing honest; syncing is a device wait, never a
    host read. A zero-arg callable ``sync`` is invoked at exit and its
    result blocked on — use that when the array only exists after the
    block runs (``timed("fit", sync=lambda: out["coef"])``)."""
    t0 = time.perf_counter()
    yield
    if sync is not None:
        jax.block_until_ready(sync() if callable(sync) else sync)
    logger.info("%s took %.3f ms", label, (time.perf_counter() - t0) * 1e3)
