"""Tracing / profiling utilities (SURVEY.md §5 "Tracing / profiling").

The reference exposes nothing beyond post-hoc ``objectiveHistory`` prints
(`DataQuality4MachineLearningApp.java:133-136`). Here:

* :class:`PhaseTimer` — per-phase wall-clock for the pipeline runner (the
  observability the reference approximates with stdout banners),
* :func:`trace` — context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard/Perfetto, for the fit hot loop,
* :func:`block_until_ready` — honest timing helper (JAX dispatch is async;
  timings without a sync measure nothing).
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Optional

import jax

logger = logging.getLogger("sparkdq4ml_tpu.profiling")


def block_until_ready(tree):
    return jax.block_until_ready(tree)


class PhaseTimer:
    """Collects named phase durations; ``report()`` returns a dict."""

    def __init__(self):
        self.phases: dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str, sync=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            dt = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + dt
            logger.debug("phase %-20s %8.3f ms", name, dt * 1e3)

    def report(self) -> dict[str, float]:
        return dict(self.phases)


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None):
    """XLA profiler trace; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def timed(label: str = "block"):
    t0 = time.perf_counter()
    yield
    logger.info("%s took %.3f ms", label, (time.perf_counter() - t0) * 1e3)
