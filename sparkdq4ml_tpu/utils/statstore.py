"""Plan-statistics observatory — the runtime-statistics memory.

ROADMAP item 4 (cost-based optimizer + adaptive re-planning) is blocked
on *memory*, not sensors: PR 5 measures per-operator rows/wall/compile/
peak-bytes and PR 9 enumerates every cached program under a stable
``program_key`` — but every observation died with the session. This
module is the store those observations accumulate INTO, keyed by the
structural plan key (literals hoisted, row counts bucketed away), so
"observed cardinalities" and "recorded compile costs" are things a
rewrite layer — and EXPLAIN, today — can actually read.

What accumulates per key (:class:`KeyStats`):

* **selectivity** — observed input row slots vs observed valid output
  rows. Output counts come from a DEFERRED device reduction (the flush
  enqueues ``sum(mask)`` as one tiny async dispatch; the scalar is pulled
  in a batched, counted drain on the cold paths — report/EXPLAIN/save —
  never on the flush hot path), or directly where the engine already
  holds the count on host (the grouped engine's one-sync group count).
* **wall-ms / compile-ms digests** — fixed-bucket histograms
  (:class:`Digest`) of replay dispatch time and traced-compile dispatch
  time. Flush timing inherits the PR-5 span caveat: jax dispatch is
  async, so on accelerators this measures enqueue+trace, not device
  wall; EXPLAIN ANALYZE remains the honest end-to-end instrument.
* **host syncs, est/measured peak bytes** — the memory-safety inputs of
  arxiv 2206.14148, remembered across sessions.

Persistence (``spark.stats.path``): an atomic, versioned JSONL snapshot
— header line carries ``version``/``saved_at``, one entry per line.
Writes go to a temp file promoted by ``os.replace``; a torn temp file
NEVER replaces the snapshot. ``save(merge=True)`` re-reads the file and
merges before writing (merge-don't-clobber: per key, the entry with more
observations wins — idempotent under repeated load/save cycles, safe
against a concurrent writer losing only finer increments). A corrupt or
version-skewed file degrades to an empty store with a structured
recovery event — history is an optimization, never a crash.

Chaos: the ``stats_persist`` fault site (``utils.faults.FAULT_SITES``)
schedules ``io_error`` (the write/read raises mid-flight) and
``torn_chunk`` (the temp file is truncated mid-write) faults; the ladder
degrades to in-memory-only operation with ``recovery.*`` /
``stats.persist_failed`` telemetry — exercised by ``scripts/
chaos_soak.py`` and the crash-safety tests.

Cost contract: ``spark.stats.enabled=false`` reduces every hook to one
flag read — zero allocations, zero device work (test-pinned, same style
as the chaos no-fault-plan pins).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from . import profiling

logger = logging.getLogger("sparkdq4ml_tpu.statstore")

#: Snapshot schema version — a mismatched file is STALE (the entry
#: layout may have changed) and degrades to empty with a recovery event.
SCHEMA_VERSION = 1

#: Wall/compile-time digest bucket bounds (milliseconds). Fixed at
#: module level so persisted digests from different sessions always
#: merge bucket-for-bucket.
DIGEST_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)

#: Bound on not-yet-drained deferred selectivity scalars (each is one
#: 0-d device array): past it the oldest observation is dropped and
#: counted, never an unbounded device-buffer leak.
MAX_PENDING = 4096


class Digest:
    """Fixed-bucket latency digest — the persistable cousin of the
    observability :class:`~.observability.Histogram`: same cumulative
    semantics, plus ``merge`` and a JSON document form so per-key
    distributions survive sessions. Thread-safety is the owning store's
    job (every mutation happens under the store lock)."""

    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self):
        self.counts = [0] * (len(DIGEST_BUCKETS_MS) + 1)  # +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, value_ms: float) -> None:
        v = float(value_ms)
        i = len(DIGEST_BUCKETS_MS)
        for j, b in enumerate(DIGEST_BUCKETS_MS):
            if v <= b:
                i = j
                break
        self.counts[i] += 1
        self.sum += v
        self.count += 1
        if v > self.max:
            self.max = v

    def merge(self, other: "Digest") -> None:
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count
        self.max = max(self.max, other.max)

    def mean(self) -> Optional[float]:
        return (self.sum / self.count) if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound estimate of the ``q`` quantile (the bucket upper
        edge the rank lands in; ``max`` for the overflow bucket)."""
        if not self.count:
            return None
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank and c:
                return (DIGEST_BUCKETS_MS[i]
                        if i < len(DIGEST_BUCKETS_MS) else self.max)
        return self.max

    # Named quantile accessors — THE numbers the cost model
    # (sql/optimizer.py, ops/compiler._split_point) and stats_report()
    # both read, so bucket math is derived in exactly one place.
    def p50(self) -> Optional[float]:
        return self.quantile(0.5)

    def p90(self) -> Optional[float]:
        return self.quantile(0.9)

    def to_doc(self) -> dict:
        return {"counts": list(self.counts), "sum": self.sum,
                "count": self.count, "max": self.max}

    @classmethod
    def from_doc(cls, doc: dict) -> "Digest":
        d = cls()
        counts = doc.get("counts") or []
        if len(counts) != len(d.counts):
            raise ValueError("digest bucket-count mismatch")
        d.counts = [int(c) for c in counts]
        d.sum = float(doc.get("sum", 0.0))
        d.count = int(doc.get("count", 0))
        d.max = float(doc.get("max", 0.0))
        return d


class KeyStats:
    """Running statistics for ONE structural plan key. ``rows_in`` /
    ``rows_out`` accumulate only over flushes whose output count was
    actually observed (``sel_observations``), so the selectivity ratio is
    never diluted by flushes that were dispatched but never counted."""

    __slots__ = ("key", "kind", "flushes", "compiles", "rows_in",
                 "rows_out", "sel_observations", "wall_ms", "compile_ms",
                 "host_syncs", "est_bytes_max", "peak_bytes_max",
                 "cost", "profile", "updated_at")

    def __init__(self, key: str, kind: str):
        self.key = key
        self.kind = kind
        self.flushes = 0
        self.compiles = 0
        self.rows_in = 0
        self.rows_out = 0
        self.sel_observations = 0
        self.wall_ms = Digest()
        self.compile_ms = Digest()
        self.host_syncs = 0
        self.est_bytes_max = 0
        self.peak_bytes_max = 0
        # AOT cost profile (utils/costprof.py CostProfile.to_doc():
        # flops / bytes / per-collective bytes / generated-code peak) —
        # structural per key, so one extraction serves every session
        # that loads this snapshot. None until an extraction lands.
        self.cost: Optional[dict] = None
        # DQ column-profile snapshot (utils/dqprof.py
        # ColumnProfile.to_doc(): versioned sketch fields + fixed-bucket
        # histogram) under ``dqprof|<column>`` keys — the cross-session
        # drift baseline. None until a profile drain lands. Optional
        # field: pre-dq snapshots load unchanged (back-compatible).
        self.profile: Optional[dict] = None
        self.updated_at = 0.0

    @property
    def selectivity(self) -> Optional[float]:
        """Observed valid-rows-out per row-slot-in (None until at least
        one output count landed; an all-filtered history reads 0.0)."""
        if not self.sel_observations or self.rows_in <= 0:
            return None
        return self.rows_out / self.rows_in

    def observations(self) -> int:
        """Total evidence weight — the merge tiebreaker."""
        return self.flushes + self.sel_observations + self.wall_ms.count

    def merge(self, other: "KeyStats") -> None:
        self.flushes += other.flushes
        self.compiles += other.compiles
        self.rows_in += other.rows_in
        self.rows_out += other.rows_out
        self.sel_observations += other.sel_observations
        self.wall_ms.merge(other.wall_ms)
        self.compile_ms.merge(other.compile_ms)
        self.host_syncs += other.host_syncs
        self.est_bytes_max = max(self.est_bytes_max, other.est_bytes_max)
        self.peak_bytes_max = max(self.peak_bytes_max, other.peak_bytes_max)
        if self.cost is None:
            self.cost = other.cost
        if self.profile is None:
            self.profile = other.profile
        self.updated_at = max(self.updated_at, other.updated_at)

    def to_doc(self) -> dict:
        doc = {
            "key": self.key, "kind": self.kind, "flushes": self.flushes,
            "compiles": self.compiles, "rows_in": self.rows_in,
            "rows_out": self.rows_out,
            "sel_observations": self.sel_observations,
            "wall_ms": self.wall_ms.to_doc(),
            "compile_ms": self.compile_ms.to_doc(),
            "host_syncs": self.host_syncs,
            "est_bytes_max": self.est_bytes_max,
            "peak_bytes_max": self.peak_bytes_max,
            "updated_at": self.updated_at,
        }
        if self.cost is not None:
            doc["cost"] = self.cost
        if self.profile is not None:
            doc["profile"] = self.profile
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "KeyStats":
        ks = cls(str(doc["key"]), str(doc.get("kind", "?")))
        ks.flushes = int(doc.get("flushes", 0))
        ks.compiles = int(doc.get("compiles", 0))
        ks.rows_in = int(doc.get("rows_in", 0))
        ks.rows_out = int(doc.get("rows_out", 0))
        ks.sel_observations = int(doc.get("sel_observations", 0))
        ks.wall_ms = Digest.from_doc(doc.get("wall_ms") or {})
        ks.compile_ms = Digest.from_doc(doc.get("compile_ms") or {})
        ks.host_syncs = int(doc.get("host_syncs", 0))
        ks.est_bytes_max = int(doc.get("est_bytes_max", 0))
        ks.peak_bytes_max = int(doc.get("peak_bytes_max", 0))
        cost = doc.get("cost")
        ks.cost = dict(cost) if isinstance(cost, dict) else None
        profile = doc.get("profile")
        ks.profile = dict(profile) if isinstance(profile, dict) else None
        ks.updated_at = float(doc.get("updated_at", 0.0))
        return ks


class StatStore:
    """The per-key running-statistics registry. Every mutation is
    lock-protected and lock-scoped (no device work, no I/O under the
    lock), so 16 serving workers hammering ``record_flush`` while a
    scraper reads ``report()`` lose no updates (test-pinned)."""

    def __init__(self):
        self._entries: dict[str, KeyStats] = {}
        self._lock = threading.Lock()
        # Serializes save(): the read-merge-write-replace cycle must be
        # one unit per process, or two threads sharing a tmp path could
        # tear the promoted snapshot (the exact failure the atomic
        # rename exists to prevent).
        self._persist_lock = threading.Lock()
        # (key, rows_in, device-scalar) observations awaiting ONE batched
        # host pull — drained on the cold paths only (see _drain).
        self._pending: list = []

    # -- recording (hot path: called only when spark.stats.enabled) -------
    def _entry_locked(self, key: str, kind: str) -> KeyStats:
        ks = self._entries.get(key)
        if ks is None:
            from ..config import config

            while len(self._entries) >= max(int(config.stats_max_entries),
                                            1):
                # evict the least-recently-updated entry — history is an
                # optimization; a bounded table is the contract
                victim = min(self._entries.values(),
                             key=lambda e: e.updated_at)
                del self._entries[victim.key]
                profiling.counters.increment("stats.evict")
            ks = self._entries[key] = KeyStats(key, kind)
        return ks

    def record_flush(self, key: str, kind: str,
                     wall_ms: Optional[float] = None,
                     compiled: bool = False,
                     host_syncs: int = 0,
                     est_bytes: Optional[int] = None,
                     peak_bytes: Optional[int] = None) -> None:
        """One program execution at ``key`` (pipeline flush / grouped
        flush / any future producer). ``compiled`` routes the timing into
        the compile digest (it includes trace+compile), replays into the
        wall digest."""
        now = time.time()
        with self._lock:
            ks = self._entry_locked(key, kind)
            ks.flushes += 1
            if compiled:
                ks.compiles += 1
                if wall_ms is not None:
                    ks.compile_ms.observe(wall_ms)
            elif wall_ms is not None:
                ks.wall_ms.observe(wall_ms)
            ks.host_syncs += int(host_syncs)
            if est_bytes is not None and est_bytes > ks.est_bytes_max:
                ks.est_bytes_max = int(est_bytes)
            if peak_bytes is not None and peak_bytes > ks.peak_bytes_max:
                ks.peak_bytes_max = int(peak_bytes)
            ks.updated_at = now
        profiling.counters.increment("stats.record")

    def record_rows(self, key: str, kind: str, rows_in: int,
                    rows_out: int) -> None:
        """One observed (input slots → valid output rows) pair — the
        selectivity evidence. Host-known counts only; the deferred path
        is :meth:`defer_rows`."""
        with self._lock:
            ks = self._entry_locked(key, kind)
            ks.rows_in += max(int(rows_in), 0)
            ks.rows_out += max(int(rows_out), 0)
            ks.sel_observations += 1
            ks.updated_at = time.time()

    def defer_rows(self, key: str, kind: str, rows_in: int,
                   out_scalar) -> None:
        """Queue a DEVICE scalar (the flush's ``sum(mask)`` — already
        dispatched, never synced here) for a later batched pull. The hot
        path pays one tiny async reduction and a list append; the host
        read happens in :meth:`_drain` on report/EXPLAIN/save."""
        with self._lock:
            self._pending.append((key, kind, int(rows_in), out_scalar))
            if len(self._pending) > MAX_PENDING:
                self._pending.pop(0)
                dropped = True
            else:
                dropped = False
        if dropped:
            profiling.counters.increment("stats.pending_dropped")

    def drain_pending(self) -> None:
        """Pull every queued deferred observation in ONE batched
        ``device_get`` (cold paths only — report/EXPLAIN/save/stop; the
        pull is counted ``stats.drain_sync``, never a silent sync)."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        try:
            import jax
            import numpy as np

            values = jax.device_get([p[3] for p in pending])
            profiling.counters.increment("stats.drain_sync")
        except Exception:
            # a dead backend must not take a stats report down; the
            # observations are lost, the store stays coherent
            logger.debug("deferred selectivity drain failed", exc_info=True)
            return
        for (key, kind, rows_in, _), v in zip(pending, values):
            try:
                # a deferred observation may be a scalar OR a per-shard
                # count vector (the sharded flush's (devices,) output) —
                # the sum is the valid-row total either way
                self.record_rows(key, kind, rows_in,
                                 int(np.asarray(v).sum()))
            except Exception:
                logger.debug("deferred observation discarded", exc_info=True)

    # -- queries -----------------------------------------------------------
    def selectivity(self, key: str) -> Optional[float]:
        with self._lock:
            ks = self._entries.get(key)
            return ks.selectivity if ks is not None else None

    def est_rows(self, key: str, rows_in: int) -> Optional[int]:
        """History-informed output-row estimate for ``rows_in`` input
        slots (None without selectivity evidence) — the EXPLAIN
        ``est rows`` column."""
        sel = self.selectivity(key)
        if sel is None:
            return None
        return int(round(sel * max(int(rows_in), 0)))

    # -- cost model (the optimizer's read surface) -------------------------
    def compile_ms_p50(self, key: str) -> Optional[float]:
        """Median recorded trace+compile cost at ``key`` — the fused-
        stage boundary-placement input (``ops/compiler._split_point``)."""
        with self._lock:
            ks = self._entries.get(key)
            return ks.compile_ms.p50() if ks is not None else None

    def wall_ms_p50(self, key: str) -> Optional[float]:
        """Median recorded replay-dispatch cost at ``key``."""
        with self._lock:
            ks = self._entries.get(key)
            return ks.wall_ms.p50() if ks is not None else None

    def bytes_bound(self, key: str) -> Optional[int]:
        """Remembered resident-byte bound at ``key``: the max of the
        static flush estimate, the MEASURED peak, and — when an AOT cost
        profile landed (``record_cost``) — XLA's own compiled-program
        peak (temp + output + generated code), across sessions — the
        memory-aware chunking input (arxiv 2206.14148 as a planned
        decision, see ``ops/compiler.run_pipeline``). Folding the cost
        profile in upgrades the optimizer's byte model from the coarse
        flush mirror to the compiler's accounting."""
        with self._lock:
            ks = self._entries.get(key)
            if ks is None:
                return None
            cost_peak = int((ks.cost or {}).get("peak_bytes") or 0)
            bound = max(ks.est_bytes_max, ks.peak_bytes_max, cost_peak)
            return bound or None

    def record_cost(self, key: str, kind: str, cost: dict) -> None:
        """Attach an AOT cost profile (``utils/costprof.py``) to the
        entry at ``key`` — structural, so later sessions loading the
        snapshot skip the lower+compile extraction entirely."""
        with self._lock:
            ks = self._entry_locked(key, kind)
            ks.cost = dict(cost)
            ks.updated_at = time.time()

    def cost(self, key: str) -> Optional[dict]:
        with self._lock:
            ks = self._entries.get(key)
            return dict(ks.cost) if ks is not None and ks.cost else None

    def record_profile(self, key: str, kind: str, profile: dict) -> None:
        """Attach a DQ column-profile snapshot (``utils/dqprof.py``) to
        the entry at ``key`` (``dqprof|<column>``) — the persisted drift
        baseline later sessions adopt instead of re-learning one."""
        with self._lock:
            ks = self._entry_locked(key, kind)
            ks.profile = dict(profile)
            ks.updated_at = time.time()

    def profile(self, key: str) -> Optional[dict]:
        with self._lock:
            ks = self._entries.get(key)
            return dict(ks.profile) \
                if ks is not None and ks.profile else None

    def flops_for_selectivity(self, sel_key: Optional[str]
                              ) -> Optional[float]:
        """Largest recorded AOT-profile flop count over the entries whose
        plan key reduces (:func:`selectivity_key`) to ``sel_key`` — the
        join-reorder flop-cost term. Cost profiles land on FULL plan keys
        (``record_cost``) while selectivity evidence lands on the reduced
        key, so this is the bridge between the two; a linear scan over a
        bounded table (``spark.stats.maxEntries``), paid once per plan.
        None until an extraction lands, so rows-only ranking stays in
        charge on cold history."""
        if sel_key is None:
            return None
        best = None
        with self._lock:
            for ks in self._entries.values():
                if not ks.cost:
                    continue
                if selectivity_key(ks.key) != sel_key:
                    continue
                flops = float(ks.cost.get("flops") or 0.0)
                if flops > 0.0 and (best is None or flops > best):
                    best = flops
        return best

    def record_miss(self, key: str) -> None:
        """One planning miss at ``key`` (e.g. the grouped engine's dense
        slot-table overflow): accumulates as a ``miss|``-prefixed entry
        whose flush count is the evidence :meth:`miss_count` reads —
        persisted like any entry, so the skip decision survives
        sessions."""
        self.record_flush(f"miss|{key}", "miss")

    def miss_count(self, key: str) -> int:
        with self._lock:
            ks = self._entries.get(f"miss|{key}")
            return ks.flushes if ks is not None else 0

    def entry(self, key: str) -> Optional[dict]:
        with self._lock:
            ks = self._entries.get(key)
            return ks.to_doc() if ks is not None else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def report(self, drain: bool = True) -> dict:
        """The programmatic view (``session.stats_report()`` / the HTTP
        ``/plans`` route): one summary row per key, selectivity and
        digest summaries precomputed."""
        if drain:
            self.drain_pending()
        with self._lock:
            entries = [ks for ks in self._entries.values()]
            rows = []
            for ks in sorted(entries, key=lambda e: -e.observations()):
                rows.append({
                    "key": ks.key[:160], "kind": ks.kind,
                    "flushes": ks.flushes, "compiles": ks.compiles,
                    "selectivity": (None if ks.selectivity is None
                                    else round(ks.selectivity, 6)),
                    "rows_in": ks.rows_in, "rows_out": ks.rows_out,
                    "sel_observations": ks.sel_observations,
                    "wall_ms_mean": ks.wall_ms.mean(),
                    "wall_ms_p50": ks.wall_ms.p50(),
                    "wall_ms_p90": ks.wall_ms.p90(),
                    "wall_ms_p99": ks.wall_ms.quantile(0.99),
                    "compile_ms_mean": ks.compile_ms.mean(),
                    "compile_ms_p50": ks.compile_ms.p50(),
                    "host_syncs": ks.host_syncs,
                    "est_bytes_max": ks.est_bytes_max,
                    "peak_bytes_max": ks.peak_bytes_max,
                    "cost": ks.cost,
                })
        return {"entries": rows, "size": len(rows),
                "version": SCHEMA_VERSION}

    def absorb_query_stats(self, qs) -> None:
        """Fold one finished ``observability.query_stats`` collection
        into the store: per-span-CATEGORY wall digests (``span:frame``,
        ``span:fit``, …) plus measured peak bytes — the coarse per-query
        memory EXPLAIN ANALYZE already gathered, remembered instead of
        discarded."""
        now = time.time()
        with self._lock:
            for s in getattr(qs, "spans", ()):
                cat = getattr(s, "cat", "") or "other"
                ks = self._entry_locked(f"span:{cat}", "span")
                ks.flushes += 1
                ks.wall_ms.observe((getattr(s, "dur_us", 0) or 0) / 1e3)
                peak = (getattr(s, "attrs", None) or {}).get("peak_mem")
                if peak and peak > ks.peak_bytes_max:
                    ks.peak_bytes_max = int(peak)
                ks.updated_at = now

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._pending = []

    # -- persistence -------------------------------------------------------
    def _snapshot_entries(self) -> list:
        with self._lock:
            return [KeyStats.from_doc(ks.to_doc())
                    for ks in self._entries.values()]

    @staticmethod
    def _merge_into(target: dict, entries) -> None:
        """Merge-don't-clobber: per key, the variant with MORE evidence
        wins whole (count-summing would double-count the shared history
        a load/save cycle copies back and forth — winner-take-key is
        idempotent under any repeat of load/merge/save)."""
        for ks in entries:
            cur = target.get(ks.key)
            if cur is None or ks.observations() > cur.observations() or (
                    ks.observations() == cur.observations()
                    and ks.updated_at > cur.updated_at):
                if cur is not None and ks.cost is None:
                    # the cost profile is structural per key — a winner
                    # that never extracted one must not drop the
                    # loser's (re-extraction costs a real XLA compile)
                    ks.cost = cur.cost
                if cur is not None and ks.profile is None:
                    # same for the DQ profile snapshot: dropping it
                    # would silently reset the drift baseline
                    ks.profile = cur.profile
                target[ks.key] = ks
            else:
                if cur.cost is None and ks.cost is not None:
                    cur.cost = ks.cost
                if cur.profile is None and ks.profile is not None:
                    cur.profile = ks.profile

    @staticmethod
    def _trim(target: dict, bound: int) -> int:
        """Evict least-recently-updated entries past ``bound`` (the
        ``spark.stats.maxEntries`` contract — enforced on the merge
        paths too, so a huge snapshot can neither blow the in-memory
        table nor grow the on-disk file monotonically across
        sessions). Returns the eviction count."""
        bound = max(int(bound), 1)
        excess = len(target) - bound
        if excess <= 0:
            return 0
        for ks in sorted(target.values(),
                         key=lambda e: e.updated_at)[:excess]:
            del target[ks.key]
        return excess

    def load(self, path: str) -> int:
        """Merge a persisted snapshot into the live store; returns the
        number of entries adopted. A missing file is a clean 0; a
        corrupt, torn, or version-skewed file degrades to EMPTY with a
        recovery event (``stats_persist``/``fallback`` rung ``empty``)
        and a ``stats.load_failed`` counter — persisted history is an
        optimization, never a crash."""
        from . import faults as _faults
        from .recovery import RECOVERY_LOG

        try:
            _faults.inject("stats_persist")
            with open(path) as f:
                header = json.loads(f.readline() or "null")
                if not isinstance(header, dict) \
                        or header.get("version") != SCHEMA_VERSION:
                    ver = (header.get("version")
                           if isinstance(header, dict) else header)
                    raise ValueError(
                        f"snapshot version {ver!r} != {SCHEMA_VERSION}")
                loaded = [KeyStats.from_doc(json.loads(line))
                          for line in f if line.strip()]
        except FileNotFoundError:
            return 0
        except Exception as e:
            profiling.counters.increment("stats.load_failed")
            RECOVERY_LOG.record(
                "stats_persist", "fallback", rung="empty",
                cause=f"{type(e).__name__}: {e}",
                detail=f"corrupt/stale stats snapshot {path!r}; "
                       "starting with empty history")
            logger.warning("stats snapshot %s unreadable (%s); starting "
                           "with empty history", path, e)
            return 0
        from ..config import config

        with self._lock:
            self._merge_into(self._entries, loaded)
            evicted = self._trim(self._entries, config.stats_max_entries)
        if evicted:
            profiling.counters.increment("stats.evict", evicted)
        if loaded:
            profiling.counters.increment("stats.loaded", len(loaded))
        return len(loaded)

    def save(self, path: str, merge: bool = True) -> bool:
        """Persist the store atomically; returns False (in-memory-only
        degrade, with a recovery event + ``stats.persist_failed``) on any
        I/O failure — including the injected ``stats_persist`` faults.
        ``merge=True`` folds the CURRENT file contents in first so a
        concurrent/previous writer is merged, not clobbered (the merged
        set is trimmed to ``maxEntries`` so the file cannot grow
        monotonically across sessions). The temp file is promoted by
        ``os.replace`` only after a full write+flush: a torn write never
        replaces the previous snapshot. In-process saves serialize on
        ``_persist_lock`` (and the temp name carries the thread id):
        without both, two racing saves could share the temp path and
        one's late writes would land inside the already-promoted live
        snapshot — exactly the torn file this method promises away."""
        from . import faults as _faults
        from ..config import config
        from .recovery import RECOVERY_LOG

        self.drain_pending()
        entries = {ks.key: ks for ks in self._snapshot_entries()}
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        try:
            with self._persist_lock:
                _faults.inject("stats_persist")
                if merge and os.path.exists(path):
                    disk: dict[str, KeyStats] = {}
                    try:
                        with open(path) as f:
                            header = json.loads(f.readline() or "null")
                            if isinstance(header, dict) \
                                    and header.get("version") \
                                    == SCHEMA_VERSION:
                                self._merge_into(
                                    disk,
                                    [KeyStats.from_doc(json.loads(line))
                                     for line in f if line.strip()])
                    except Exception:
                        disk = {}   # a corrupt file cannot poison the write
                    self._merge_into(disk, entries.values())
                    entries = disk
                self._trim(entries, config.stats_max_entries)
                lines = [json.dumps({"version": SCHEMA_VERSION,
                                     "saved_at": time.time(),
                                     "entries": len(entries)})]
                lines.extend(json.dumps(ks.to_doc(), sort_keys=True)
                             for ks in entries.values())
                payload = "\n".join(lines) + "\n"
                torn = _faults.fired("stats_persist", "torn_chunk")
                with open(tmp, "w") as f:
                    if torn:
                        # the torn-write fault: half the payload lands,
                        # then the write dies — the except arm below must
                        # leave the real snapshot untouched
                        f.write(payload[: max(len(payload) // 2, 1)])
                        f.flush()
                        raise _faults.InjectedIOError(
                            "injected torn write at 'stats_persist'")
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        except Exception as e:
            profiling.counters.increment("stats.persist_failed")
            RECOVERY_LOG.record(
                "stats_persist", "fallback", rung="memory",
                cause=f"{type(e).__name__}: {e}",
                detail=f"stats snapshot {path!r} not written; "
                       "continuing in-memory only")
            logger.warning("stats snapshot %s not written (%s); "
                           "continuing in-memory only", path, e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False
        profiling.counters.increment("stats.persisted")
        return True


#: Process-global statistics store. ``spark.stats.enabled`` (the
#: ``config.stats_enabled`` flag) gates every producer hook; the store
#: object itself always exists so readers never race a None.
STORE = StatStore()


def enabled() -> bool:
    from ..config import config

    return bool(config.stats_enabled)


def selectivity_key(plan_key: str) -> Optional[str]:
    """The FILTER-structural identity of a pipeline plan key: the engine
    dtype tag plus every ``F:`` component, namespace tag stripped. Two
    flushes whose filter stacks are structurally identical (literals
    hoisted, projections ignored) share one selectivity entry — and the
    SAME extraction applied to a key built from a parsed query's WHERE at
    EXPLAIN time (zero execution) addresses the SAME entry, which is what
    makes history-informed ``est rows`` possible on a fresh session."""
    parts = plan_key.split("|")
    if parts and parts[0].startswith("ns:"):
        parts = parts[1:]
    if parts and parts[0].startswith("shard["):
        # layout tags stay out of the selectivity identity: a filter's
        # observed selectivity is a data property, so sharded and
        # single-device flushes of the same WHERE share one entry (and
        # EXPLAIN's layout-agnostic probe keeps addressing it)
        parts = parts[1:]
    if not parts:
        return None
    fparts = [p for p in parts[1:] if p.startswith("F:")]
    if not fparts:
        return None
    return parts[0] + "|" + "|".join(fparts)
