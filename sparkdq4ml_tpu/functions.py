"""``org.apache.spark.sql.functions`` equivalent — one import surface for
column constructors, UDF invocation (the reference's
``import static ...functions.callUDF``, `DataQuality4MachineLearningApp.java:3`),
scalar builtins, CASE WHEN, and aggregate constructors."""

from .frame.aggregates import (avg, collect_list, collect_set, corr, count,
                               count_distinct, countDistinct, covar_pop,
                               covar_samp, first, kurtosis, last, max, mean,
                               min, skewness, stddev, sum, sum_distinct,
                               sumDistinct, variance)
from .frame.window import (Window, WindowSpec, cume_dist, dense_rank, lag,
                           lead, ntile, percent_rank, rank, row_number)
from .ops.expressions import (call_udf, callUDF, ceil, coalesce, col, concat,
                              exp, floor, fn, greatest, isnan, isnull, least,
                              length, lit, log, log10, lower, ltrim, pow,
                              rtrim, signum, sqrt, substring, trim, upper,
                              when)
from .ops.expressions import sql_abs as abs  # noqa: A001 - Spark name
from .ops.expressions import sql_round as round  # noqa: A001 - Spark name

__all__ = ["col", "lit", "call_udf", "callUDF", "count", "sum", "avg",
           "mean", "min", "max", "stddev", "variance",
           "count_distinct", "countDistinct", "sum_distinct", "sumDistinct",
           "collect_list", "collect_set", "first", "last",
           "skewness", "kurtosis", "corr", "covar_samp", "covar_pop",
           "abs", "sqrt", "exp", "log", "log10", "pow", "floor", "ceil",
           "round", "signum", "greatest", "least", "isnan", "isnull",
           "coalesce", "when", "fn",
           "upper", "lower", "trim", "ltrim", "rtrim", "length", "concat",
           "substring",
           "Window", "WindowSpec", "row_number", "rank", "dense_rank",
           "percent_rank", "cume_dist", "ntile", "lag", "lead"]
