"""``org.apache.spark.sql.functions`` equivalent — one import surface for
column constructors, UDF invocation (the reference's
``import static ...functions.callUDF``, `DataQuality4MachineLearningApp.java:3`),
scalar builtins, CASE WHEN, and aggregate constructors."""

from .frame.aggregates import (approx_count_distinct,
                               approxCountDistinct, avg, collect_list, collect_set, corr, count,
                               count_distinct, countDistinct, covar_pop,
                               covar_samp, first, kurtosis, last, max, mean,
                               median, min, mode, percentile_approx,
                               skewness, stddev, stddev_pop, sum,
                               sum_distinct, sumDistinct, var_pop, variance)
from .frame.window import (Window, WindowSpec, cume_dist, dense_rank,
                           first_value, lag, last_value, lead, nth_value,
                           ntile, percent_rank, rank, row_number)
from .ops.expressions import (acos, array_contains, asin, atan, atan2,
                              base64, call_udf, element_at, size,
                              callUDF, cbrt, ceil, coalesce, col, concat,
                              concat_ws, cos, cosh, degrees, exp, expm1,
                              floor, fn, greatest, hypot, initcap, instr,
                              isnan, isnull, least, length, lit, locate,
                              log, log1p, log2, log10, lower, lpad, ltrim,
                              explode, explode_outer, posexplode,
                              md5, nvl, pow, radians,
                              regexp_extract,
                              regexp_replace, repeat, reverse, rint, rpad,
                              rtrim, sha1, sha2, signum, sin, sinh, split,
                              sqrt, substring, tan, tanh, translate, trim,
                              unbase64, upper, when)
from .ops.expressions import (array, array_distinct, array_join, expr,
                              flatten, format_number, format_string,
                              levenshtein, monotonically_increasing_id,
                              nanvl, rand, randn, slice, sort_array,
                              spark_partition_id)
from .ops.expressions import (array_except, array_intersect, array_max,
                              array_min, array_position, array_remove,
                              array_repeat, array_union, arrays_overlap,
                              arrays_zip, sequence, shuffle)
from .ops.expressions import (current_date, date_add, date_format, date_sub,
                              datediff, dayofmonth, dayofweek, dayofyear,
                              from_unixtime, month, quarter, to_date,
                              unix_timestamp, year)
from .ops.expressions import (add_months, current_timestamp, date_trunc,
                              hour, last_day, minute, months_between,
                              next_day, second, to_timestamp, trunc,
                              weekofyear)
from .ops.expressions import sql_abs as abs  # noqa: A001 - Spark name
from .ops.expressions import sql_round as round  # noqa: A001 - Spark name
from .ops.expressions import (Lambda, aggregate, exists, filter,  # noqa: A004
                              transform)
from .ops.expressions import (ascii, bin, bit_length, bitwiseNOT, bround,
                              conv, crc32, decode, encode, factorial,
                              get_json_object, hash, hex, ifnull,
                              json_tuple, nullif, nvl2, octet_length,
                              shiftleft, shiftright, shiftrightunsigned,
                              soundex, substring_index, unhex, xxhash64)

__all__ = ["col", "lit", "call_udf", "callUDF", "count", "sum", "avg",
           "mean", "min", "max", "stddev", "variance",
           "count_distinct", "countDistinct", "approx_count_distinct",
           "approxCountDistinct", "sum_distinct", "sumDistinct",
           "collect_list", "collect_set", "first", "last",
           "skewness", "kurtosis", "corr", "covar_samp", "covar_pop",
           "abs", "sqrt", "exp", "log", "log10", "pow", "floor", "ceil",
           "round", "signum", "greatest", "least", "isnan", "isnull",
           "coalesce", "nvl", "when", "fn", "md5", "sha1", "sha2", "base64", "unbase64", "median", "mode", "percentile_approx", "stddev_pop", "var_pop", "array_contains", "element_at", "size", "explode", "explode_outer", "posexplode",
           "upper", "lower", "trim", "ltrim", "rtrim", "length", "concat",
           "substring",
           "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
           "sinh", "cosh", "tanh", "degrees", "radians", "cbrt",
           "expm1", "log1p", "log2", "hypot", "rint",
           "concat_ws", "split", "regexp_replace", "regexp_extract",
           "instr", "locate", "lpad", "rpad", "repeat", "reverse",
           "initcap", "translate",
           "to_date", "unix_timestamp", "from_unixtime", "date_format",
           "datediff", "date_add", "date_sub", "current_date",
           "year", "month", "dayofmonth", "dayofweek", "dayofyear",
           "quarter",
           "Window", "WindowSpec", "row_number", "rank", "dense_rank",
           "percent_rank", "cume_dist", "ntile", "lag", "lead",
           "array", "sort_array", "array_distinct", "array_join", "slice",
           "flatten", "nanvl", "format_number", "format_string",
           "levenshtein", "rand", "randn", "monotonically_increasing_id",
           "spark_partition_id", "expr", "broadcast",
           "array_position", "array_remove", "array_union",
           "array_intersect", "array_except", "arrays_overlap",
           "array_min", "array_max", "array_repeat", "sequence",
           "arrays_zip", "shuffle",
           "hour", "minute", "second", "weekofyear", "last_day",
           "add_months", "months_between", "next_day", "trunc",
           "date_trunc", "to_timestamp", "current_timestamp",
           "bround", "factorial", "hex", "unhex", "bin", "conv",
           "ascii", "crc32", "hash", "xxhash64", "shiftleft",
           "shiftright", "shiftrightunsigned", "bitwiseNOT", "nullif",
           "nvl2", "ifnull", "substring_index", "soundex", "encode",
           "decode", "bit_length", "octet_length", "get_json_object",
           "json_tuple",
           "transform", "filter", "exists", "aggregate", "Lambda"]


def broadcast(df):
    """Spark ``broadcast(df)`` join hint: a no-op here — XLA owns the
    execution strategy (see ``Frame.hint``)."""
    return df

