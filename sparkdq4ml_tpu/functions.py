"""``org.apache.spark.sql.functions`` equivalent — one import surface for
column constructors, UDF invocation (the reference's
``import static ...functions.callUDF``, `DataQuality4MachineLearningApp.java:3`),
and aggregate constructors."""

from .frame.aggregates import (avg, count, max, mean, min, stddev, sum,
                               variance)
from .ops.expressions import call_udf, callUDF, col, lit

__all__ = ["col", "lit", "call_udf", "callUDF", "count", "sum", "avg",
           "mean", "min", "max", "stddev", "variance"]
