"""Temp-view catalog backing ``createOrReplaceTempView`` + ``spark.sql``
(`DataQuality4MachineLearningApp.java:76-78,88-90`)."""

from __future__ import annotations

from typing import NamedTuple


class Table(NamedTuple):
    """Spark ``catalog.listTables()`` row shape (temp views only here)."""

    name: str
    isTemporary: bool = True


class Catalog:
    def __init__(self):
        self._views: dict[str, object] = {}

    def register(self, name: str, frame) -> None:
        self._views[name.lower()] = frame

    def lookup(self, name: str):
        try:
            return self._views[name.lower()]
        except KeyError:
            raise KeyError(f"temp view {name!r} not found "
                           f"(views: {sorted(self._views)})") from None

    def table_exists(self, name: str) -> bool:
        return name.lower() in self._views

    tableExists = table_exists

    def drop(self, name: str) -> bool:
        return self._views.pop(name.lower(), None) is not None

    dropTempView = drop  # Spark catalog name
    drop_temp_view = drop

    def list_views(self):
        return sorted(self._views)

    def list_tables(self) -> list["Table"]:
        """Spark's ``catalog.listTables()`` shape: objects with ``.name``
        (and ``.isTemporary``, always True — this catalog holds only temp
        views), so the ported idiom ``[t.name for t in listTables()]``
        works. ``list_views`` keeps the plain-string form."""
        return [Table(name=n, isTemporary=True) for n in sorted(self._views)]

    listTables = list_tables

    def clear(self) -> None:
        self._views.clear()


_DEFAULT = Catalog()


def default_catalog() -> Catalog:
    return _DEFAULT
