"""Minimal SQL subset: SELECT / alias / CAST / function calls / WHERE.

Exactly the surface the reference app exercises (SURVEY.md §2.2 "SQL over
temp view"):

    SELECT cast(guest as int) guest, price_no_min AS price
    FROM price WHERE price_no_min > 0

plus the obvious closures of that grammar (arithmetic, AND/OR/NOT, comparison
chains, parentheses, literals, registered UDF calls). Queries compile to the
same :mod:`~sparkdq4ml_tpu.ops.expressions` trees the fluent API builds, so SQL
filtering is mask-AND like ``Frame.filter`` — one fused XLA predicate, not a
row interpreter.

Grammar (recursive descent):

    query      := SELECT select_list FROM ident [WHERE or_expr]
    select_list:= '*' | item (',' item)*
    item       := expr [[AS] ident]
    or_expr    := and_expr (OR and_expr)*
    and_expr   := not_expr (AND not_expr)*
    not_expr   := NOT not_expr | cmp
    cmp        := add ((= | == | != | <> | < | <= | > | >=) add)?
    add        := mul (('+'|'-') mul)*
    mul        := unary (('*'|'/') unary)*
    unary      := '-' unary | atom
    atom       := number | 'string' | TRUE | FALSE | NULL
                | CAST '(' expr AS ident ')'
                | ident '(' [expr (',' expr)*] ')'     -- UDF call
                | ident | '(' or_expr ')'
"""

from __future__ import annotations

import math
import re
from typing import Optional

from ..ops import expressions as E

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)"
    r"|(?P<string>'(?:[^']|'')*')"
    r"|(?P<ident>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op><=|>=|<>|!=|==|=|<|>|\+|-|\*|/|\(|\)|,)"
    r")")

_KEYWORDS = {"select", "from", "where", "as", "and", "or", "not", "cast",
             "true", "false", "null"}


class _Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: str):
        self.kind = kind
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[_Token]:
    tokens, pos = [], 0
    while pos < len(sql):
        if sql[pos:].strip() == "":
            break
        m = _TOKEN_RE.match(sql, pos)
        if m is None or m.end() == pos:
            raise ValueError(f"SQL syntax error near: {sql[pos:pos+20]!r}")
        pos = m.end()
        if m.group("number") is not None:
            tokens.append(_Token("number", m.group("number")))
        elif m.group("string") is not None:
            tokens.append(_Token("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("ident") is not None:
            ident = m.group("ident")
            kind = "kw" if ident.lower() in _KEYWORDS else "ident"
            tokens.append(_Token(kind, ident))
        else:
            tokens.append(_Token("op", m.group("op")))
    tokens.append(_Token("eof", ""))
    return tokens


class _Parser:
    def __init__(self, tokens: list[_Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> _Token:
        return self.toks[self.i]

    def next(self) -> _Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[_Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value.lower() == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> _Token:
        t = self.accept(kind, value)
        if t is None:
            raise ValueError(f"SQL parse error: expected {value or kind}, "
                             f"got {self.peek().value!r}")
        return t

    # -- query -------------------------------------------------------------
    def parse_query(self):
        self.expect("kw", "select")
        items = self.parse_select_list()
        self.expect("kw", "from")
        view = self.expect("ident").value
        where = None
        if self.accept("kw", "where"):
            where = self.parse_or()
        self.expect("eof")
        return items, view, where

    def parse_select_list(self):
        if self.accept("op", "*"):
            return ["*"]
        items = [self.parse_item()]
        while self.accept("op", ","):
            items.append(self.parse_item())
        return items

    def parse_item(self):
        expr = self.parse_or()
        if self.accept("kw", "as"):
            return expr.alias(self.expect("ident").value)
        alias = self.accept("ident")
        if alias is not None:  # bare alias: `cast(guest as int) guest`
            return expr.alias(alias.value)
        return expr

    # -- expressions (precedence climbing) ----------------------------------
    def parse_or(self):
        left = self.parse_and()
        while self.accept("kw", "or"):
            left = E.BinOp("|", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept("kw", "and"):
            left = E.BinOp("&", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept("kw", "not"):
            return E.UnaryOp("!", self.parse_not())
        return self.parse_cmp()

    _CMP = {"=": "==", "==": "==", "!=": "!=", "<>": "!=",
            "<": "<", "<=": "<=", ">": ">", ">=": ">="}

    def parse_cmp(self):
        left = self.parse_add()
        t = self.peek()
        if t.kind == "op" and t.value in self._CMP:
            self.next()
            return E.BinOp(self._CMP[t.value], left, self.parse_add())
        return left

    def parse_add(self):
        left = self.parse_mul()
        while True:
            if self.accept("op", "+"):
                left = E.BinOp("+", left, self.parse_mul())
            elif self.accept("op", "-"):
                left = E.BinOp("-", left, self.parse_mul())
            else:
                return left

    def parse_mul(self):
        left = self.parse_unary()
        while True:
            if self.accept("op", "*"):
                left = E.BinOp("*", left, self.parse_unary())
            elif self.accept("op", "/"):
                left = E.BinOp("/", left, self.parse_unary())
            else:
                return left

    def parse_unary(self):
        if self.accept("op", "-"):
            return E.UnaryOp("-", self.parse_unary())
        return self.parse_atom()

    def parse_atom(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            text = t.value
            if re.fullmatch(r"\d+", text):
                return E.Lit(int(text))
            return E.Lit(float(text))
        if t.kind == "string":
            self.next()
            return E.Lit(t.value)
        if self.accept("kw", "true"):
            return E.Lit(True)
        if self.accept("kw", "false"):
            return E.Lit(False)
        if self.accept("kw", "null"):
            return E.Lit(math.nan)
        if self.accept("kw", "cast"):
            self.expect("op", "(")
            inner = self.parse_or()
            self.expect("kw", "as")
            tname = self.expect("ident").value
            self.expect("op", ")")
            return E.Cast(inner, tname)
        if t.kind == "ident":
            self.next()
            if self.accept("op", "("):
                args = []
                if not self.accept("op", ")"):
                    args.append(self.parse_or())
                    while self.accept("op", ","):
                        args.append(self.parse_or())
                    self.expect("op", ")")
                return E.UdfCall(t.value, args)
            return E.Col(t.value)
        if self.accept("op", "("):
            inner = self.parse_or()
            self.expect("op", ")")
            return inner
        raise ValueError(f"SQL parse error at {t.value!r}")


def parse(sql: str):
    """Parse a query → (select items, view name, where Expr|None)."""
    return _Parser(tokenize(sql)).parse_query()


def execute(sql: str, catalog=None):
    """Run a query against the catalog and return a Frame."""
    from .catalog import default_catalog

    cat = catalog if catalog is not None else default_catalog()
    items, view, where = parse(sql)
    frame = cat.lookup(view)
    if where is not None:
        frame = frame.filter(where)
    # NB: Expr overloads ==, so compare with identity-safe checks, never
    # `items == ["*"]` (a single-Expr list would compare truthy).
    if len(items) == 1 and isinstance(items[0], str) and items[0] == "*":
        return frame
    return frame.select(*items)
