"""Minimal SQL subset: SELECT / alias / CAST / function calls / WHERE.

Exactly the surface the reference app exercises (SURVEY.md §2.2 "SQL over
temp view"):

    SELECT cast(guest as int) guest, price_no_min AS price
    FROM price WHERE price_no_min > 0

plus the obvious closures of that grammar (arithmetic, AND/OR/NOT, comparison
chains, parentheses, literals, registered UDF calls). Queries compile to the
same :mod:`~sparkdq4ml_tpu.ops.expressions` trees the fluent API builds, so SQL
filtering is mask-AND like ``Frame.filter`` — one fused XLA predicate, not a
row interpreter.

Grammar (recursive descent):

    query      := [WITH ident AS '(' set ')' (',' ident AS '(' set ')')*] set
    set        := select ((UNION [ALL] | INTERSECT | EXCEPT) select)*
    select     := SELECT [DISTINCT] select_list FROM relation join*
                  [WHERE or_expr]
                  [GROUP BY (expr|position),* | ROLLUP/CUBE '(' ident,* ')']
                  [HAVING or_expr]
                  [ORDER BY (expr|position) [ASC|DESC]
                   [NULLS FIRST|LAST],*]
                  [LIMIT n] [OFFSET m]
    relation   := ident [[AS] ident] | '(' set ')' [AS] [ident]
                  -- derived table; aliases scope qualified refs a.col
    join       := [INNER|LEFT [OUTER|SEMI|ANTI]|RIGHT [OUTER]|FULL [OUTER]
                  |CROSS] JOIN relation
                  (ON ident '=' ident | USING '(' ident,* ')')
    select_list:= '*' | item (',' item)*
    item       := expr [OVER window] [[AS] ident]
    window     := '(' [PARTITION BY ident,*] [ORDER BY ident [ASC|DESC],*]
                      [(ROWS|RANGE) BETWEEN bound AND bound] ')'
    bound      := UNBOUNDED (PRECEDING|FOLLOWING) | CURRENT ROW
                  | int (PRECEDING|FOLLOWING)
                  -- after a ranking fn (ROW_NUMBER/RANK/DENSE_RANK/
                  -- PERCENT_RANK/CUME_DIST/NTILE/LAG/LEAD) or an aggregate;
                  -- default frame RANGE UNBOUNDED PRECEDING..CURRENT ROW
    or_expr    := and_expr (OR and_expr)*
    and_expr   := not_expr (AND not_expr)*
    not_expr   := NOT not_expr | cmp
    cmp        := add ((= | == | != | <> | < | <= | > | >=) add)?
                | add IS [NOT] NULL
                | add [NOT] IN '(' (or_expr,* | set) ')'
                | add [NOT] BETWEEN add AND add
                | add [NOT] LIKE 'pattern'
                | EXISTS '(' set ')'          -- uncorrelated subqueries
    add        := mul (('+'|'-') mul)*
    mul        := unary (('*'|'/') unary)*
    unary      := '-' unary | atom
    atom       := number | 'string' | TRUE | FALSE | NULL
                | CAST '(' expr AS ident ')'
                | CASE (WHEN or_expr THEN or_expr)+ [ELSE or_expr] END
                | ident '(' [expr (',' expr)*] ')'     -- UDF or builtin fn
                | ident | '(' or_expr ')'
                | '(' set ')'                 -- scalar subquery (1 col,
                                              -- <=1 row; null when empty)
"""

from __future__ import annotations

import math
import re
from typing import Optional

from ..ops import expressions as E
from ..utils import observability as _obs

_TOKEN_RE = re.compile(
    r"\s*(?:"
    r"(?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)"
    r"|(?P<string>'(?:[^']|'')*')"
    r"|(?P<ident>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<op>->|\|\||<=|>=|<>|!=|==|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)"
    r")")

_KEYWORDS = {"select", "from", "where", "as", "and", "or", "not", "cast",
             "true", "false", "null", "group", "by", "order", "limit",
             "asc", "desc", "join", "inner", "left", "right", "full",
             "outer", "cross", "on", "using", "case", "when", "then",
             "else", "end", "is", "in", "between", "like", "having",
             "distinct", "union", "all"}
# OVER / PARTITION are contextual (recognized only after a function call /
# inside a window spec), so columns named "over"/"partition" keep working.

_AGG_FNS = {"count", "sum", "avg", "mean", "min", "max", "stddev", "variance",
            "stddev_pop", "var_pop", "median", "mode",
            "collect_list", "collect_set", "first", "last",
            "skewness", "kurtosis"}
# percentile_approx(col, p[, accuracy]) takes a literal percentage
_AGG_FNS_PCT = {"percentile_approx", "approx_percentile"}
# two-column aggregates: CORR(a, b), COVAR_SAMP(a, b), COVAR_POP(a, b)
_AGG_FNS_2 = {"corr", "covar_samp", "covar_pop", "max_by", "min_by"}
# boolean/conditional aggregates desugared into agg + post-agg forms
_BOOL_AGGS = {"count_if", "any", "some", "every", "bool_or", "bool_and"}
_WINDOW_FNS = {"row_number", "rank", "dense_rank", "percent_rank",
               "cume_dist", "ntile", "lag", "lead",
               "first_value", "last_value", "nth_value"}


def _lit_value(expr, what: str):
    """Extract a literal value, accepting a leading unary minus (``-1``
    parses as UnaryOp('-', Lit) — still a literal to the user)."""
    if isinstance(expr, E.Lit):
        return expr.value
    if (isinstance(expr, E.UnaryOp) and expr.op == "-"
            and isinstance(expr.child, E.Lit)):
        return -expr.child.value
    raise ValueError(f"{what} must be a literal")


def _check_agg_args(fn: str, col, args) -> None:
    """Aggregate argument rule, shared by the plain and windowed (OVER)
    paths: a single column name, or bare ``*``/no args for COUNT only."""
    if col is None and not (fn.lower() == "count" and not args):
        raise ValueError(f"{fn} argument must be * or a column name")


class _AggRef(E.Expr):
    """A parsed aggregate appearing inside select-list arithmetic
    (``SELECT max(p) - min(p)``): carries the AggExpr; rewritten to a
    Col over the aggregated output before any eval."""

    def __init__(self, agg):
        self.agg = agg

    @property
    def name(self) -> str:
        return self.agg.name

    def __str__(self):
        return self.agg.name

    def eval(self, frame):
        raise ValueError(
            "aggregate expressions are only valid in a SQL select list — "
            "this tree still holds an unresolved aggregate reference")


class PostAggItem:
    """A select item that is an expression OVER aggregate results
    (``max(p) - min(p) AS spread``): ``expr`` references the aggregated
    output columns of ``aggs``, and is computed on the aggregated frame."""

    __slots__ = ("expr", "aggs", "_name")

    def __init__(self, expr, aggs, name=None):
        self.expr = expr
        self.aggs = list(aggs)
        self._name = name

    @property
    def name(self) -> str:
        return self._name if self._name is not None else str(self.expr)

    def alias(self, name: str) -> "PostAggItem":
        return PostAggItem(self.expr, self.aggs, name)


class _Token:
    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: str):
        self.kind = kind
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[_Token]:
    tokens, pos = [], 0
    while pos < len(sql):
        if sql[pos:].strip() == "":
            break
        m = _TOKEN_RE.match(sql, pos)
        if m is None or m.end() == pos:
            raise ValueError(f"SQL syntax error near: {sql[pos:pos+20]!r}")
        pos = m.end()
        if m.group("number") is not None:
            tokens.append(_Token("number", m.group("number")))
        elif m.group("string") is not None:
            tokens.append(_Token("string", m.group("string")[1:-1].replace("''", "'")))
        elif m.group("ident") is not None:
            ident = m.group("ident")
            kind = "kw" if ident.lower() in _KEYWORDS else "ident"
            tokens.append(_Token(kind, ident))
        else:
            tokens.append(_Token("op", m.group("op")))
    tokens.append(_Token("eof", ""))
    return tokens


class _Parser:
    def __init__(self, tokens: list[_Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> _Token:
        return self.toks[self.i]

    def next(self) -> _Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[_Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value.lower() == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> _Token:
        t = self.accept(kind, value)
        if t is None:
            raise ValueError(f"SQL parse error: expected {value or kind}, "
                             f"got {self.peek().value!r}")
        return t

    # -- query -------------------------------------------------------------
    def parse_relation(self):
        """A FROM/JOIN source: a view name (with optional ``[AS] alias``),
        or a parenthesized derived table ``(SELECT ...) [AS] alias``.
        Returns ``(source, alias)`` where source is a name or Query."""
        if (self.peek().kind == "op" and self.peek().value == "("
                and self.toks[self.i + 1].kind == "kw"
                and self.toks[self.i + 1].value.lower() == "select"):
            self.next()
            sub = self.parse_set_expr()
            self.expect("op", ")")
            self.accept("kw", "as")
            alias = None
            if (self.peek().kind == "ident"
                    and not self._ident_starts_clause()):
                alias = self.next().value
            return DerivedTable(sub, alias), alias
        view = self.expect("ident").value
        alias = None
        if self.accept("kw", "as"):
            alias = self.expect("ident").value
        elif self.peek().kind == "ident" and not self._ident_starts_clause():
            alias = self.next().value
        return view, alias

    def _ident_starts_clause(self) -> bool:
        """Contextual idents that begin a clause rather than alias a
        relation (ON/USING/keywords are kw-kind already; these are the
        ident-kind clause openers, so relations cannot be aliased to
        these names without AS)."""
        return self.peek().value.lower() in ("semi", "anti", "intersect",
                                             "except", "offset")

    def parse_query(self):
        self.expect("kw", "select")
        distinct = bool(self.accept("kw", "distinct"))
        items = self.parse_select_list()
        # Spark allows FROM-less SELECT (``SELECT 1``, ``SELECT
        # current_date()``): the projection runs over OneRowRelation.
        view = None
        view_alias = None
        joins = []
        if self.accept("kw", "from"):
            view, view_alias = self.parse_relation()
            while True:
                join = self.parse_join()
                if join is None:
                    break
                joins.append(join)
        where = None
        if self.accept("kw", "where"):
            where = self.parse_or()
        group_by = []
        group_mode = "group"
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            # GROUP BY ROLLUP(a, b) / CUBE(a, b) — Spark subtotal grouping
            nxt = self.peek()
            if (nxt.kind == "ident" and nxt.value.lower() in ("rollup", "cube")
                    and self.toks[self.i + 1].kind == "op"
                    and self.toks[self.i + 1].value == "("):
                group_mode = self.next().value.lower()
                self.expect("op", "(")
                group_by.append(self.expect("ident").value)
                while self.accept("op", ","):
                    group_by.append(self.expect("ident").value)
                self.expect("op", ")")
            else:
                group_by.append(self.parse_group_item())
                while self.accept("op", ","):
                    group_by.append(self.parse_group_item())
        having = None
        if self.accept("kw", "having"):
            having = self.parse_or()
        order_by = []
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            order_by.append(self.parse_sort_item())
            while self.accept("op", ","):
                order_by.append(self.parse_sort_item())
        limit = None
        offset = 0
        if self.accept("kw", "limit"):
            limit = int(self.expect("number").value)
        if self.accept("ident", "offset"):     # LIMIT n OFFSET m / OFFSET m
            offset = int(self.expect("number").value)
        q = Query(items, view, where, group_by, order_by, limit, joins,
                  distinct=distinct, having=having)
        q.group_mode = group_mode
        q.view_alias = view_alias
        q.offset = offset
        return q

    def parse_set_expr(self):
        """query ((UNION [ALL] | INTERSECT | EXCEPT) query)* — set
        operators over identical schemas, left-associative (standard
        SQL's higher INTERSECT precedence is not modeled; parenthesize
        to force grouping). No EOF expectation, so it also parses
        parenthesized subqueries."""
        q = self.parse_query()
        while True:
            if self.accept("kw", "union"):
                dedup = not self.accept("kw", "all")
                q.unions.append(("union_all" if not dedup else "union",
                                 self.parse_query()))
            elif (self.peek().kind == "ident"
                  and self.peek().value.lower() in ("intersect", "except")):
                op = self.next().value.lower()
                q.unions.append((op, self.parse_query()))
            else:
                return q

    def parse_union_query(self):
        """Top-level statement: ``[WITH name AS (query), ...] set_expr``.
        WITH is contextual (like OVER/PARTITION) so columns named "with"
        keep working: it is only recognized as the first token."""
        ctes = []
        if (self.peek().kind == "ident"
                and self.peek().value.lower() == "with"):
            self.next()
            while True:
                name = self.expect("ident").value
                self.expect("kw", "as")
                self.expect("op", "(")
                ctes.append((name, self.parse_set_expr()))
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        q = self.parse_set_expr()
        q.ctes = ctes
        self.expect("eof")
        return q

    def parse_join(self):
        """``[INNER|LEFT [OUTER]|RIGHT [OUTER]|FULL [OUTER]|CROSS] JOIN view
        (ON a = b | USING (k, ...))`` → ``(view, how, keys)``."""
        how = None
        for kw in ("inner", "left", "right", "full", "cross"):
            if self.accept("kw", kw):
                how = {"full": "outer"}.get(kw, kw)
                if kw == "left":
                    # LEFT SEMI / LEFT ANTI (contextual idents, so columns
                    # named "semi"/"anti" keep working elsewhere)
                    if self.accept("ident", "semi"):
                        how = "left_semi"
                    elif self.accept("ident", "anti"):
                        how = "left_anti"
                self.accept("kw", "outer")
                break
        if how is None:
            if not self.accept("kw", "join"):
                return None
            how = "inner"
        else:
            self.expect("kw", "join")
        view, alias = self.parse_relation()
        keys: list[str] = []
        if how != "cross":
            if self.accept("kw", "using"):
                self.expect("op", "(")
                keys.append(self.expect("ident").value)
                while self.accept("op", ","):
                    keys.append(self.expect("ident").value)
                self.expect("op", ")")
            else:
                self.expect("kw", "on")
                a = self._parse_maybe_dotted()
                self.expect("op", "=")
                b = self._parse_maybe_dotted()
                # qualified ON (``ON t.k = g.k``) reduces to the shared
                # base column — the engine's joins are USING-shaped
                a_col = a.rpartition(".")[2]
                b_col = b.rpartition(".")[2]
                if a_col != b_col:
                    raise ValueError(
                        f"JOIN ON supports equi-join on a shared column name; "
                        f"got {a!r} = {b!r} (use USING or rename first)")
                keys.append(a_col)
        return (view, how, keys, alias)

    def _parse_maybe_dotted(self) -> str:
        name = self.expect("ident").value
        while self.accept("op", "."):
            name += "." + self.expect("ident").value
        return name

    def parse_order_item(self):
        """Window-spec ORDER BY: plain column names only (a window's sort
        key is a physical column of the partition)."""
        name = self.expect("ident").value
        ascending = True
        if self.accept("kw", "desc"):
            ascending = False
        else:
            self.accept("kw", "asc")
        return (name, ascending)

    def parse_group_item(self):
        """GROUP BY key: a column name, a 1-based select-item position
        (``GROUP BY 1``), or any expression (``GROUP BY cast(p as int)``);
        non-name keys resolve at execute. ROLLUP/CUBE keep plain names."""
        expr = self.parse_or()
        if isinstance(expr, E.Col):
            return expr.name
        if (isinstance(expr, E.Lit) and isinstance(expr.value, int)
                and not isinstance(expr.value, bool)):
            return expr.value
        return expr

    def parse_sort_item(self):
        """Query-level ORDER BY key: a column name, a 1-based select-item
        position (``ORDER BY 2``), or any expression — including
        aggregates (``ORDER BY count(*) DESC``), resolved at execute.
        ``NULLS FIRST|LAST`` (contextual idents) pins null placement;
        the default is Spark's asc→first / desc→last."""
        expr = self.parse_or()
        ascending = True
        if self.accept("kw", "desc"):
            ascending = False
        else:
            self.accept("kw", "asc")
        nulls_first = None
        if self.accept("ident", "nulls"):
            if self.accept("ident", "first"):
                nulls_first = True
            elif self.accept("ident", "last"):
                nulls_first = False
            else:
                raise ValueError("expected FIRST or LAST after NULLS")
        if (isinstance(expr, E.Lit) and isinstance(expr.value, int)
                and not isinstance(expr.value, bool)):
            if nulls_first is not None:
                raise ValueError("NULLS FIRST/LAST with a positional "
                                 "ORDER BY key is not supported")
            return (expr.value, ascending)
        if nulls_first is not None:
            return (E.SortOrder(expr, ascending, nulls_first), ascending)
        if isinstance(expr, E.Col):
            return (expr.name, ascending)
        return (expr, ascending)

    def parse_select_list(self):
        items = [self.parse_select_item()]
        while self.accept("op", ","):
            items.append(self.parse_select_item())
        return items

    def parse_select_item(self):
        # ``*`` may appear alongside other items (``SELECT *, a+b AS c``)
        if self.accept("op", "*"):
            return "*"
        return self.parse_item()

    def parse_window_spec(self):
        """``( [PARTITION BY ident,*] [ORDER BY item,*]
        [ROWS|RANGE BETWEEN bound AND bound] )`` after OVER, with
        ``bound := UNBOUNDED PRECEDING|FOLLOWING | CURRENT ROW |
        <n> PRECEDING|FOLLOWING`` — the same frames as the fluent
        ``rowsBetween``/``rangeBetween`` API."""
        from ..frame.window import WindowSpec

        self.expect("op", "(")
        partition, order = [], []
        if self.accept("ident", "partition"):
            self.expect("kw", "by")
            partition.append(self.expect("ident").value)
            while self.accept("op", ","):
                partition.append(self.expect("ident").value)
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            order.append(self.parse_order_item())
            while self.accept("op", ","):
                order.append(self.parse_order_item())
        spec = WindowSpec(partition, order)
        kind = None
        if self.accept("ident", "rows"):
            kind = "rows"
        elif self.accept("ident", "range"):
            kind = "range"
        if kind is not None:
            self.expect("kw", "between")
            lo = self._parse_frame_bound()
            self.expect("kw", "and")
            hi = self._parse_frame_bound()
            spec = (spec.rows_between(lo, hi) if kind == "rows"
                    else spec.range_between(lo, hi))
        self.expect("op", ")")
        return spec

    def _parse_frame_bound(self) -> int:
        from ..frame.window import Window

        if self.accept("ident", "unbounded"):
            if self.accept("ident", "preceding"):
                return Window.unbounded_preceding
            self.expect("ident", "following")
            return Window.unbounded_following
        if self.accept("ident", "current"):
            self.expect("ident", "row")
            return 0
        n = self.expect("number").value
        if float(n) != int(float(n)):
            raise ValueError(f"SQL parse error: frame bound must be an "
                             f"integer, got {n!r}")
        off = int(float(n))
        if self.accept("ident", "preceding"):
            return -off
        self.expect("ident", "following")
        return off

    def _build_window_fn(self, fn: str, col, args: list):
        """Bind a parsed ``fn(args...)`` to a WindowFunction (pre-OVER)."""
        from ..frame import window as W

        fl = fn.lower()
        if fl in _AGG_FNS:
            from ..frame.aggregates import AggExpr

            _check_agg_args(fn, col, args)
            return AggExpr(fn, col).over  # bound later by caller
        if fl == "ntile":
            if len(args) != 1 or not isinstance(args[0], E.Lit):
                raise ValueError("ntile(n) requires an integer literal")
            return W.ntile(int(args[0].value)).over
        if fl in _AGG_FNS_PCT:
            raise ValueError(
                f"windowed {fl}() is not supported (Spark <=2.x SQL "
                "windows the running aggregates only)")
        if fl in ("first_value", "last_value"):
            if len(args) != 1 or not isinstance(args[0], E.Col):
                raise ValueError(f"{fl}(col) requires a column argument")
            return getattr(W, fl)(args[0].name).over
        if fl == "nth_value":
            if (len(args) != 2 or not isinstance(args[0], E.Col)):
                raise ValueError("nth_value(col, n) requires a column and "
                                 "an integer literal")
            return W.nth_value(args[0].name,
                               int(_lit_value(args[1], "nth_value n"))).over
        if fl in ("lag", "lead"):
            if not args or not isinstance(args[0], E.Col):
                raise ValueError(f"{fl}(col[, offset[, default]]) requires a "
                                 "column first argument")
            offset = 1
            default = None
            if len(args) > 1:
                offset = int(_lit_value(args[1], f"{fl} offset"))
            if len(args) > 2:
                default = _lit_value(args[2], f"{fl} default")
            builder = W.lag if fl == "lag" else W.lead
            return builder(args[0].name, offset, default).over
        if args:
            raise ValueError(f"{fl}() takes no arguments")
        return getattr(W, fl)().over

    def parse_item(self):
        # aggregate or window fn at top level: COUNT(*), AVG(price),
        # COUNT(DISTINCT guest), CORR(a, b), ROW_NUMBER() OVER (...),
        # SUM(price) OVER (...), ...
        t = self.peek()
        if (t.kind == "ident"
                and t.value.lower() in (_AGG_FNS | _AGG_FNS_2
                                        | _AGG_FNS_PCT | _WINDOW_FNS
                                        | _BOOL_AGGS
                                        | {"approx_count_distinct"})
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].value == "("):
            from ..frame.aggregates import AggExpr, AggOfExpr

            fn = self.next().value
            self.expect("op", "(")
            col = None
            args: list = []
            distinct = False
            if not self.accept("op", ")"):
                if self.accept("op", "*"):
                    pass
                else:
                    distinct = bool(self.accept("kw", "distinct"))
                    args.append(self.parse_or())
                    while self.accept("op", ","):
                        args.append(self.parse_or())
                self.expect("op", ")")
            if len(args) == 1 and isinstance(args[0], E.Col):
                col = args[0].name
            if distinct:
                if fn.lower() not in ("count", "sum") or col is None:
                    raise ValueError(
                        "DISTINCT is supported in COUNT(DISTINCT col) and "
                        "SUM(DISTINCT col)")
                expr = AggExpr(f"{fn.lower()}_distinct", col)
            elif self.accept("ident", "over"):
                make = self._build_window_fn(fn, col, args)
                expr = make(self.parse_window_spec())
            elif fn.lower() in _AGG_FNS_2:
                if (len(args) != 2 or not all(isinstance(a, E.Col)
                                              for a in args)):
                    raise ValueError(f"{fn}(col1, col2) takes two columns")
                expr = AggExpr(fn, args[0].name, column2=args[1].name)
            elif fn.lower() == "approx_count_distinct":
                if not args or not isinstance(args[0], E.Col):
                    raise ValueError(
                        "approx_count_distinct(col[, rsd]) takes a column")
                from ..frame.aggregates import \
                    approx_count_distinct as _acd

                rsd = (float(_lit_value(args[1], "rsd"))
                       if len(args) > 1 else 0.05)
                expr = _acd(args[0].name, rsd)
            elif fn.lower() in _BOOL_AGGS:
                if len(args) != 1:
                    raise ValueError(f"{fn}(predicate) takes one argument")
                pred = args[0]
                flag = E.CaseWhen([(pred, E.Lit(1))], E.Lit(0))
                low = fn.lower()
                if low == "count_if":
                    expr = _AggRef(AggOfExpr(
                        "sum", flag, alias=f"count_if({pred})"))
                else:
                    # any/some/bool_or ≡ max(flag) > 0;
                    # every/bool_and ≡ min(flag) > 0
                    red = "max" if low in ("any", "some", "bool_or")                         else "min"
                    expr = E.BinOp(">", _AggRef(AggOfExpr(red, flag)),
                                   E.Lit(0))
            elif fn.lower() in _AGG_FNS:
                if col is None and len(args) == 1                         and isinstance(args[0], E.Expr):
                    # aggregate over an expression: sum(price * qty)
                    expr = AggOfExpr(fn, args[0])
                else:
                    _check_agg_args(fn, col, args)
                    expr = AggExpr(fn, col)
            elif fn.lower() in _AGG_FNS_PCT:
                if (len(args) not in (2, 3) or not isinstance(args[0], E.Col)
                        or not isinstance(args[1], E.Lit)):
                    raise ValueError(
                        f"{fn}(col, percentage[, accuracy]) requires a "
                        "column and a literal percentage")
                from ..frame.aggregates import percentile_approx as _pa

                expr = _pa(args[0].name, float(args[1].value))
            else:
                raise ValueError(f"window function {fn}() requires an "
                                 "OVER clause")
            from ..frame.aggregates import AggExpr as _AggE

            # Aggregate arithmetic in the select list (``SELECT max(p) -
            # min(p) AS spread``): continue precedence climbing with the
            # parsed aggregate as the left operand, then detect below.
            if (isinstance(expr, _AggE)
                    and self.peek().kind == "op"
                    and self.peek().value in ("+", "-", "*", "/")):
                expr = self.parse_add(_AggRef(expr))
            elif isinstance(expr, _AggE) or not isinstance(expr, E.Expr):
                # plain aggregate / percentile item — no detection needed
                if self.accept("kw", "as"):
                    return expr.alias(self.expect("ident").value)
                alias = self.accept("ident")
                if alias is not None:
                    return expr.alias(alias.value)
                return expr
            elif (self.peek().kind == "op"
                  and self.peek().value in ("+", "-", "*", "/")):
                # desugared bool-agg forms compose arithmetically too
                expr = self.parse_add(expr)
        else:
            expr = self.parse_or()
        # Post-aggregate detection: an expression whose tree contains
        # aggregate calls projects over the aggregated frame.
        collected: list = []
        rewritten = _rewrite_having(expr, collected)
        item = PostAggItem(rewritten, collected) if collected else expr
        if self.accept("kw", "as"):
            return item.alias(self.expect("ident").value)
        alias = self.accept("ident")
        if alias is not None:  # bare alias: `cast(guest as int) guest`
            return item.alias(alias.value)
        return item

    # -- expressions (precedence climbing) ----------------------------------
    def parse_or(self):
        left = self.parse_and()
        while self.accept("kw", "or"):
            left = E.BinOp("|", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept("kw", "and"):
            left = E.BinOp("&", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept("kw", "not"):
            return E.UnaryOp("!", self.parse_not())
        return self.parse_cmp()

    _CMP = {"=": "==", "==": "==", "!=": "!=", "<>": "!=",
            "<": "<", "<=": "<=", ">": ">", ">=": ">="}

    def parse_cmp(self):
        left = self.parse_add()
        t = self.peek()
        if t.kind == "op" and t.value in self._CMP:
            self.next()
            return E.BinOp(self._CMP[t.value], left, self.parse_add())
        if self.accept("kw", "is"):
            negated = bool(self.accept("kw", "not"))
            self.expect("kw", "null")
            return left.is_not_null() if negated else left.is_null()
        # [NOT] IN / BETWEEN / LIKE
        negated = False
        if (self.peek().kind == "kw" and self.peek().value.lower() == "not"
                and self.toks[self.i + 1].kind == "kw"
                and self.toks[self.i + 1].value.lower() in ("in", "between",
                                                            "like")):
            self.next()
            negated = True
        if self.accept("kw", "in"):
            self.expect("op", "(")
            if (self.peek().kind == "kw"
                    and self.peek().value.lower() == "select"):
                sub = self.parse_set_expr()
                self.expect("op", ")")
                return SubqueryIn(left, sub, negated)
            values = [self.parse_or()]
            while self.accept("op", ","):
                values.append(self.parse_or())
            self.expect("op", ")")
            return E.InList(left, values, negated=negated)
        if self.accept("kw", "between"):
            lo = self.parse_add()
            self.expect("kw", "and")
            hi = self.parse_add()
            expr = left.between(lo, hi)
            return E.UnaryOp("!", expr) if negated else expr
        if self.accept("kw", "like"):
            pat = self.expect("string").value
            return E.StringMatch("like", left, pat, negated=negated)
        return left

    def parse_add(self, left=None):
        left = self.parse_mul(left)
        while True:
            if self.accept("op", "+"):
                left = E.BinOp("+", left, self.parse_mul())
            elif self.accept("op", "-"):
                left = E.BinOp("-", left, self.parse_mul())
            elif self.accept("op", "||"):
                # SQL || = concat (Spark: strings; null-propagating)
                left = E.UdfCall("concat", [left, self.parse_mul()])
            else:
                return left

    def parse_mul(self, left=None):
        left = self.parse_unary() if left is None else left
        while True:
            if self.accept("op", "*"):
                left = E.BinOp("*", left, self.parse_unary())
            elif self.accept("op", "/"):
                left = E.BinOp("/", left, self.parse_unary())
            elif self.accept("op", "%"):
                left = E.BinOp("%", left, self.parse_unary())
            else:
                return left

    def parse_unary(self):
        if self.accept("op", "-"):
            return E.UnaryOp("-", self.parse_unary())
        return self.parse_atom()

    def parse_atom(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            text = t.value
            if re.fullmatch(r"\d+", text):
                return E.Lit(int(text))
            return E.Lit(float(text))
        if t.kind == "string":
            self.next()
            return E.Lit(t.value)
        if self.accept("kw", "true"):
            return E.Lit(True)
        if self.accept("kw", "false"):
            return E.Lit(False)
        if self.accept("kw", "null"):
            return E.Lit(math.nan)
        if self.accept("kw", "cast"):
            self.expect("op", "(")
            inner = self.parse_or()
            self.expect("kw", "as")
            tname = self.expect("ident").value
            self.expect("op", ")")
            return E.Cast(inner, tname)
        if (t.kind == "ident" and t.value.lower() == "extract"
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].value == "("):
            # extract(FIELD FROM expr) — sugar over the field functions
            self.next()
            self.expect("op", "(")
            field = self.expect("ident").value.lower()
            aliases = {"day": "dayofmonth", "dow": "dayofweek",
                       "doy": "dayofyear", "week": "weekofyear"}
            field = aliases.get(field, field)
            self.expect("kw", "from")
            inner = self.parse_or()
            self.expect("op", ")")
            return E.UdfCall(field, [inner])
        if self.accept("kw", "case"):
            # simple form: CASE operand WHEN v THEN r ... — each WHEN
            # value compares against the operand by equality
            operand = None
            if not (self.peek().kind == "kw"
                    and self.peek().value.lower() == "when"):
                operand = self.parse_or()
            branches = []
            while self.accept("kw", "when"):
                cond = self.parse_or()
                if operand is not None:
                    cond = E.BinOp("==", operand, cond)
                self.expect("kw", "then")
                branches.append((cond, self.parse_or()))
            if not branches:
                raise ValueError("CASE requires at least one WHEN branch")
            otherwise = self.parse_or() if self.accept("kw", "else") else None
            self.expect("kw", "end")
            return E.CaseWhen(branches, otherwise)
        # LEFT(s, n) / RIGHT(s, n): the string functions named by join
        # keywords — recognized only in call position
        if (t.kind == "kw" and t.value.lower() in ("left", "right")
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].value == "("):
            self.next()
            self.expect("op", "(")
            args = [self.parse_or()]
            while self.accept("op", ","):
                args.append(self.parse_or())
            self.expect("op", ")")
            return E.UdfCall(t.value.lower(), args)
        if t.kind == "ident":
            self.next()
            if self.accept("op", "("):
                # COUNT(*) in expression position (e.g. HAVING COUNT(*) > 2)
                if t.value.lower() in _AGG_FNS and self.accept("op", "*"):
                    self.expect("op", ")")
                    return E.UdfCall(t.value, [E.Lit("*")])
                # COUNT(DISTINCT x)/SUM(DISTINCT x) inside an expression
                # context (HAVING): encode as the _distinct aggregate name
                fn_name = t.value
                if (t.value.lower() in ("count", "sum")
                        and self.accept("kw", "distinct")):
                    fn_name = f"{t.value.lower()}_distinct"
                # if(cond, a, b) — Spark's CASE sugar
                if fn_name.lower() == "if":
                    cond = self.parse_or()
                    self.expect("op", ",")
                    then = self.parse_or()
                    self.expect("op", ",")
                    other = self.parse_or()
                    self.expect("op", ")")
                    return E.CaseWhen([(cond, then)], other)
                # EXISTS (SELECT ...) — the predicate form; EXISTS(arr,
                # x -> ...) remains the higher-order array function.
                if (fn_name.lower() == "exists" and self.peek().kind == "kw"
                        and self.peek().value.lower() == "select"):
                    sub = self.parse_set_expr()
                    self.expect("op", ")")
                    return SubqueryExists(sub)
                if fn_name.lower() in ("transform", "filter", "exists",
                                       "aggregate"):
                    return self.parse_higher_order(fn_name.lower())
                args = []
                if not self.accept("op", ")"):
                    args.append(self.parse_or())
                    while self.accept("op", ","):
                        args.append(self.parse_or())
                    self.expect("op", ")")
                # fn(...) OVER (...) in EXPRESSION position (e.g.
                # ``price - first_value(price) OVER (...)``): a window
                # expr is a regular column Expr, so it composes
                if (self.peek().kind == "ident"
                        and self.peek().value.lower() == "over"
                        and fn_name.lower() in (_WINDOW_FNS | _AGG_FNS)):
                    self.next()
                    col = (args[0].name if len(args) == 1
                           and isinstance(args[0], E.Col) else None)
                    make = self._build_window_fn(fn_name, col, args)
                    return make(self.parse_window_spec())
                return E.UdfCall(fn_name, args)
            # qualified column ref: alias.col (resolved at execute
            # against the relation scope; a literal dotted column name
            # on the frame wins first)
            name = t.value
            while (self.peek().kind == "op" and self.peek().value == "."):
                self.next()
                name += "." + self.expect("ident").value
            return E.Col(name)
        if self.accept("op", "("):
            if (self.peek().kind == "kw"
                    and self.peek().value.lower() == "select"):
                sub = self.parse_set_expr()
                self.expect("op", ")")
                return ScalarSubquery(sub)
            inner = self.parse_or()
            self.expect("op", ")")
            return inner
        raise ValueError(f"SQL parse error at {t.value!r}")

    def parse_lambda(self):
        """``x -> expr`` / ``(acc, x) -> expr`` — Spark 2.4's SQL lambda.
        Parameters surface as Col refs in the body; the higher-order
        evaluator's scope frame binds them (shadowing outer columns)."""
        params = []
        if self.accept("op", "("):
            params.append(self.expect("ident").value)
            while self.accept("op", ","):
                params.append(self.expect("ident").value)
            self.expect("op", ")")
        else:
            params.append(self.expect("ident").value)
        self.expect("op", "->")
        return E.Lambda(params, self.parse_or())

    def parse_higher_order(self, fn: str):
        """transform/filter/exists (col, lambda); aggregate
        (col, init, merge[, finish]) — '(' already consumed."""
        source = self.parse_or()
        self.expect("op", ",")
        if fn == "aggregate":
            init = self.parse_or()
            self.expect("op", ",")
            merge = self.parse_lambda()
            finish = self.parse_lambda() if self.accept("op", ",") else None
            self.expect("op", ")")
            return E.HigherOrder("aggregate", source, merge, init=init,
                                 finish=finish)
        lam = self.parse_lambda()
        self.expect("op", ")")
        return E.HigherOrder(fn, source, lam)


class DerivedTable:
    """A parenthesized subquery in relation position: ``FROM (SELECT
    ...) [AS] alias`` — executed into a Frame at lookup time."""

    __slots__ = ("query", "alias")

    def __init__(self, query, alias=None):
        self.query = query
        self.alias = alias


class _AliasableSubquery(E.Expr):
    """Subquery placeholders are Expr subclasses so every grammar position
    a column can take — ``(SELECT ...) IS NULL``, ``BETWEEN``, ``LIKE``,
    ``AS name`` — composes; the resolution walk replaces them with
    literals before any eval. eval() itself is unreachable after
    resolution and raises a clear error if a placeholder escapes."""

    __slots__ = ()

    def eval(self, frame):
        raise ValueError(
            "subqueries are only supported inside session.sql() — this "
            "expression still holds an unresolved subquery placeholder")


class ScalarSubquery(_AliasableSubquery):
    """``(SELECT agg FROM ...)`` in expression position. Uncorrelated
    only; resolved to a literal (its single value, null when empty)
    before the enclosing query runs."""

    __slots__ = ("query",)

    def __init__(self, query):
        self.query = query


class SubqueryIn(_AliasableSubquery):
    """``expr [NOT] IN (SELECT col FROM ...)`` — resolved to an InList
    over the subquery's materialized (uncorrelated) value set."""

    __slots__ = ("child", "query", "negated")

    def __init__(self, child, query, negated=False):
        self.child = child
        self.query = query
        self.negated = negated


class SubqueryExists(_AliasableSubquery):
    """``EXISTS (SELECT ...)`` — uncorrelated; resolved to a boolean
    literal (row count > 0)."""

    __slots__ = ("query",)

    def __init__(self, query):
        self.query = query


class Query:
    """Parsed query: select items, view, joins, where, group/having/order/
    limit, distinct flag, trailing UNION branches, and WITH CTEs."""

    def __init__(self, items, view, where, group_by=(), order_by=(),
                 limit=None, joins=(), distinct=False, having=None,
                 unions=()):
        self.items = items
        self.view = view
        self.where = where
        self.group_by = list(group_by)
        self.order_by = list(order_by)
        self.limit = limit
        self.joins = list(joins)
        self.distinct = distinct
        self.having = having
        self.unions = list(unions)  # [(op, Query)] op ∈ union[_all]/
        #                             intersect/except, left-assoc
        self.group_mode = "group"   # "group" | "rollup" | "cube"
        self.ctes = []              # [(name, Query), ...]
        self.view_alias = None      # FROM-relation alias (qualified refs)
        self.offset = 0             # rows skipped before LIMIT applies


def parse(sql: str) -> Query:
    """Parse a query into a Query plan object."""
    return _Parser(tokenize(sql)).parse_union_query()


def _rewrite_having(expr, extra_aggs: list):
    """HAVING may reference aggregates directly (``HAVING COUNT(*) > 2``).
    Rewrite agg-function calls into references to the aggregated output
    column, collecting aggs that must be computed but aren't in SELECT."""
    from ..frame.aggregates import AggExpr

    having_aggs = _AGG_FNS | _AGG_FNS_2 | {"count_distinct", "sum_distinct"}
    if isinstance(expr, _AggRef):
        extra_aggs.append(expr.agg)
        return E.Col(expr.agg.name)
    if (isinstance(expr, E.UdfCall)
            and expr.udf_name.lower() in _BOOL_AGGS
            and len(expr.args) == 1):
        from ..frame.aggregates import AggOfExpr

        low = expr.udf_name.lower()
        flag = E.CaseWhen([(expr.args[0], E.Lit(1))], E.Lit(0))
        if low == "count_if":
            agg = AggOfExpr("sum", flag,
                            alias=f"count_if({expr.args[0]})")
            extra_aggs.append(agg)
            return E.Col(agg.name)
        red = ("max" if low in ("any", "some", "bool_or") else "min")
        agg = AggOfExpr(red, flag)
        extra_aggs.append(agg)
        return E.BinOp(">", E.Col(agg.name), E.Lit(0))
    if (isinstance(expr, E.UdfCall) and expr.udf_name.lower() in having_aggs
            and (len(expr.args) <= 1
                 or expr.udf_name.lower() in _AGG_FNS_2)):
        fn = expr.udf_name.lower()
        if fn in _AGG_FNS_2:
            if (len(expr.args) != 2
                    or not all(isinstance(a, E.Col) for a in expr.args)):
                raise ValueError(f"{fn}(col1, col2) takes two columns")
            agg = AggExpr(fn, expr.args[0].name, column2=expr.args[1].name)
            extra_aggs.append(agg)
            return E.Col(agg.name)
        arg = expr.args[0] if expr.args else None
        if arg is None or (isinstance(arg, E.Lit) and arg.value == "*"):
            col = None
        elif isinstance(arg, E.Col):
            col = arg.name
        else:
            from ..frame.aggregates import AggOfExpr

            agg = AggOfExpr(expr.udf_name, arg)
            extra_aggs.append(agg)
            return E.Col(agg.name)
        agg = AggExpr(expr.udf_name, col)
        extra_aggs.append(agg)
        return E.Col(agg.name)
    if isinstance(expr, E.BinOp):
        return E.BinOp(expr.op, _rewrite_having(expr.left, extra_aggs),
                       _rewrite_having(expr.right, extra_aggs))
    if isinstance(expr, E.UnaryOp):
        return E.UnaryOp(expr.op, _rewrite_having(expr.child, extra_aggs))
    if isinstance(expr, E.InList):
        return E.InList(_rewrite_having(expr.child, extra_aggs),
                        [_rewrite_having(v, extra_aggs) for v in expr.values],
                        expr.negated)
    if isinstance(expr, E.UdfCall):     # non-aggregate call: recurse args
        return E.UdfCall(expr.udf_name,
                         [_rewrite_having(a, extra_aggs) for a in expr.args],
                         registry=expr._registry)
    if isinstance(expr, E.Cast):
        return E.Cast(_rewrite_having(expr.child, extra_aggs),
                      expr.type_name)
    if isinstance(expr, E.CaseWhen):
        return E.CaseWhen(
            [(_rewrite_having(c, extra_aggs), _rewrite_having(v, extra_aggs))
             for c, v in expr.branches],
            None if expr.otherwise_expr is None
            else _rewrite_having(expr.otherwise_expr, extra_aggs))
    return expr


class _OverlayCatalog:
    """CTE scope: WITH-bound names shadow the base catalog for the
    duration of one statement, without mutating it."""

    def __init__(self, base):
        self._base = base
        self._views: dict[str, object] = {}

    def register(self, name: str, frame) -> None:
        self._views[name.lower()] = frame

    def lookup(self, name: str):
        try:
            return self._views[name.lower()]
        except KeyError:
            return self._base.lookup(name)


def _pyval(v):
    """numpy scalar → python scalar (Lit dispatches on python types)."""
    # dqlint: ok(host-sync): SQL literal folding — the values are parsed
    # host scalars (numpy or python), never device arrays
    return v.item() if hasattr(v, "item") else v


def _conjuncts(e) -> list:
    """Flatten an AND tree into its conjuncts."""
    if isinstance(e, E.BinOp) and e.op == "&":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _conjoin(parts):
    out = None
    for p in parts:
        out = p if out is None else E.BinOp("&", out, p)
    return out


def _relation_aliases(q: Query) -> set:
    """The relation aliases a query's own FROM/JOIN clause binds."""
    names = set()
    if isinstance(q.view, str):
        names.add((q.view_alias or q.view).lower())
    elif isinstance(q.view, DerivedTable) and q.view.alias:
        names.add(q.view.alias.lower())
    for view, _how, _keys, jalias in q.joins:
        nm = jalias or (view if isinstance(view, str) else None)
        if nm:
            names.add(nm.lower())
    return names


def _outer_refs(expr, outer_scope: dict, inner_aliases: set) -> set:
    """Qualified names in ``expr`` whose alias binds in the OUTER scope
    but not in the subquery's own relations — the correlation points."""
    cols: set = set()
    _referenced_cols(expr, cols)
    out = set()
    for name in cols:
        if "." not in name or "(" in name:
            continue
        alias = name.partition(".")[0].lower()
        if alias in outer_scope and alias not in inner_aliases:
            out.add(name)
    return out


def _decorrelate_one(sub: Query, extra_outer_cols, outer_scope, cat):
    """Rewrite one correlated predicate subquery into a semi-join input.

    Returns ``(right_frame, keys)`` where ``right_frame``'s columns are
    named after the OUTER flat columns and ``keys`` joins it left-semi
    (EXISTS/IN) or left-anti (negations) — Spark's own decorrelation.
    ``extra_outer_cols`` carries the IN form's outer expression paired
    with the subquery's select item. Only conjunctive equi-correlation
    is supported; anything else raises the unsupported-correlation error.
    """
    inner_aliases = _relation_aliases(sub)

    def unsupported(why):
        return ValueError(
            f"unsupported correlated subquery ({why}); only conjunctive "
            "equality correlation decorrelates (the Spark semi/anti-join "
            "rewrite) — rewrite the query as an explicit JOIN")

    if sub.unions or sub.group_by or sub.having or sub.limit is not None \
            or getattr(sub, "offset", 0) or sub.ctes:
        raise unsupported("the subquery uses set ops, grouping, or limits")
    eq_pairs = []      # (outer flat col, inner expr)
    rest = []
    for c in _conjuncts(sub.where) if sub.where is not None else []:
        refs = _outer_refs(c, outer_scope, inner_aliases)
        if not refs:
            rest.append(c)
            continue
        if (isinstance(c, E.BinOp) and c.op == "=="
                and isinstance(c.left, E.Col) and isinstance(c.right, E.Col)):
            l_out = c.left.name in refs
            r_out = c.right.name in refs
            if l_out != r_out:
                outer_name = c.left.name if l_out else c.right.name
                inner_col = c.right if l_out else c.left
                eq_pairs.append((
                    _resolve_name(outer_name, outer_scope, ()), inner_col))
                continue
        raise unsupported(f"non-equi correlated predicate {c}")
    for outer_expr, item in extra_outer_cols:
        if not isinstance(outer_expr, E.Col):
            raise unsupported("the IN operand must be a plain column")
        eq_pairs.append((outer_expr.name, item))
    if not eq_pairs:
        raise unsupported("no equality correlation found")

    def _inner_key(ie):
        # normalized inner-column identity: strip the subquery's own
        # relation qualifier so ``g.guest`` and ``guest`` compare equal
        if isinstance(ie, E.Col):
            alias, _, col = ie.name.partition(".")
            return col if alias.lower() in inner_aliases else ie.name
        return str(ie)

    deduped: dict = {}
    for o, ie in eq_pairs:
        k = _inner_key(ie)
        if o in deduped and deduped[o][1] != k:
            raise unsupported("two different correlation keys target one "
                              "outer column")
        deduped.setdefault(o, (ie, k))
    eq_pairs = [(o, ie) for o, (ie, _) in deduped.items()]
    names = [o for o, _ in eq_pairs]
    inner = Query([E.Alias(ie if isinstance(ie, E.Expr) else E.Col(ie), o)
                   for o, ie in eq_pairs],
                  sub.view, _conjoin(rest), joins=sub.joins, distinct=True)
    inner.view_alias = sub.view_alias
    # Decorrelation-aware pushdown: the subquery branch is a full SELECT
    # over its own relation scope (correlated conjuncts are already
    # lifted into ``eq_pairs`` above, so only decorrelated predicates
    # remain) — route it through the cost-based optimizer like any other
    # executed query so its residual filters push into the scans and its
    # projection prunes, instead of scanning the branch unoptimized.
    return _execute_set(_maybe_optimize(inner, cat), cat), names


def _decorrelate_where(where, scope: dict, cat):
    """Split WHERE into plain conjuncts and correlated predicate
    subqueries; the latter become (right_frame, keys, how) semi/anti
    joins. Uncorrelated subqueries stay put (literal resolution handles
    them, preserving their null semantics)."""
    keep = []
    joins = []
    for c in _conjuncts(where):
        neg = False
        target = c
        if (isinstance(c, E.UnaryOp) and c.op == "!"
                and isinstance(c.child, (SubqueryExists, SubqueryIn))):
            neg, target = True, c.child
        if isinstance(target, SubqueryExists):
            sub, extra = target.query, []
        elif isinstance(target, SubqueryIn):
            from ..frame.aggregates import AggExpr

            sub = target.query
            neg = neg != target.negated
            if len(sub.items) != 1 or isinstance(sub.items[0],
                                                 (str, AggExpr)):
                keep.append(c)
                continue
            extra = [(target.child, sub.items[0])]
        else:
            keep.append(c)
            continue
        inner_aliases = _relation_aliases(sub)
        correlated = bool(_outer_refs(sub.where, scope, inner_aliases)
                          if sub.where is not None else False)
        if not correlated:
            keep.append(c)          # uncorrelated: existing literal path
            continue
        right, keys = _decorrelate_one(sub, extra, scope, cat)
        joins.append((right, keys, "left_anti" if neg else "left_semi"))
    return _conjoin(keep), joins


def _execute_subquery(q: Query, cat):
    """Run a subquery, converting an outer-alias reference into the
    clear diagnosis: correlation is not supported — Spark itself
    rewrites correlated EXISTS/IN into semi/anti joins, and those are
    first-class here."""
    try:
        return _execute_set(q, cat)
    except ValueError as e:
        if "unknown relation alias" in str(e):
            raise ValueError(
                "correlated subqueries are not supported (the subquery "
                f"references an outer relation: {e}); rewrite as a join "
                "— LEFT SEMI for EXISTS/IN, LEFT ANTI for NOT "
                "EXISTS/NOT IN") from e
        raise


def _resolve_subqueries(expr, cat):
    """Replace uncorrelated subquery placeholders with literal values by
    executing them against the catalog, rebuilding the expression tree."""
    if isinstance(expr, ScalarSubquery):
        frame = _execute_subquery(expr.query, cat)
        cols = frame.columns
        if len(cols) != 1:
            raise ValueError("scalar subquery must return exactly one "
                             f"column, got {len(cols)}: {cols}")
        values = [_pyval(v) for v in frame.to_pydict()[cols[0]]]
        if len(values) > 1:
            raise ValueError("scalar subquery returned more than one row")
        return E.Lit(values[0] if values else math.nan)
    if isinstance(expr, SubqueryIn):
        frame = _execute_subquery(expr.query, cat)
        cols = frame.columns
        if len(cols) != 1:
            raise ValueError("IN (subquery) must select exactly one "
                             f"column, got {len(cols)}: {cols}")
        values = frame.to_pydict()[cols[0]]
        return E.InList(_resolve_subqueries(expr.child, cat),
                        [E.Lit(_pyval(v)) for v in values], expr.negated)
    if isinstance(expr, SubqueryExists):
        return E.Lit(_execute_subquery(expr.query, cat).count() > 0)
    if isinstance(expr, E.BinOp):
        return E.BinOp(expr.op, _resolve_subqueries(expr.left, cat),
                       _resolve_subqueries(expr.right, cat))
    if isinstance(expr, E.UnaryOp):
        return E.UnaryOp(expr.op, _resolve_subqueries(expr.child, cat))
    if isinstance(expr, E.InList):
        return E.InList(_resolve_subqueries(expr.child, cat),
                        [_resolve_subqueries(v, cat) for v in expr.values],
                        expr.negated)
    if isinstance(expr, E.UdfCall):
        return E.UdfCall(expr.udf_name,
                         [_resolve_subqueries(a, cat) for a in expr.args],
                         registry=expr._registry)
    if isinstance(expr, E.Cast):
        return E.Cast(_resolve_subqueries(expr.child, cat), expr.type_name)
    if isinstance(expr, E.StringMatch):
        return E.StringMatch(expr.kind,
                             _resolve_subqueries(expr.child, cat),
                             expr.pattern, negated=expr.negated)
    if isinstance(expr, E.CaseWhen):
        return E.CaseWhen(
            [(_resolve_subqueries(c, cat), _resolve_subqueries(v, cat))
             for c, v in expr.branches],
            None if expr.otherwise_expr is None
            else _resolve_subqueries(expr.otherwise_expr, cat))
    if isinstance(expr, E.Alias):
        return E.Alias(_resolve_subqueries(expr.child, cat), expr._name)
    if isinstance(expr, PostAggItem):
        return PostAggItem(_resolve_subqueries(expr.expr, cat),
                           expr.aggs, expr._name)
    return expr


def _execute_set(q: Query, cat):
    """Run one set expression: a SELECT plus trailing UNION [ALL] /
    INTERSECT / EXCEPT branches (left-associative)."""
    frame = _execute_single(q, cat)
    for op, sub in q.unions:
        rhs = _execute_single(sub, cat)
        if op == "union_all":
            frame = frame.union(rhs)
        elif op == "union":
            frame = frame.union(rhs).distinct()
        elif op == "intersect":
            frame = frame.intersect(rhs)
        else:                              # except
            frame = frame.subtract(rhs)
    return frame


class _AnyColSchema(dict):
    """Optimistic column schema for plan_summary's structural fused-stage
    check: every column resolves as a device column of unknown dtype
    (``p``), so the check keys on expression FORM only."""

    def get(self, key, default=None):  # noqa: ARG002 - dict signature
        return "p"


_OPTIMISTIC_SCHEMA = _AnyColSchema()


def _segment_lowerable_aggs(items) -> bool:
    """Structural check for the ``SegmentedAggregate`` plan marker: every
    aggregate in the select list (including the components of post-agg
    expressions) passes the executor's OWN eligibility predicate
    (``segments.agg_lowerable`` — one definition, marker and executor in
    lockstep) — same optimistic-dtype convention as the FusedStage
    check."""
    from ..frame.aggregates import AggExpr
    from ..ops.segments import agg_lowerable

    found = False
    for it in items:
        aggs = (it.aggs if isinstance(it, PostAggItem)
                else [it] if isinstance(it, AggExpr) else [])
        for a in aggs:
            found = True
            if not agg_lowerable(a):
                return False
    return found


_DDL_RE = re.compile(
    r"^\s*create\s+(?:or\s+replace\s+)?(?:temp(?:orary)?\s+)?view\s+"
    r"([A-Za-z_][A-Za-z_0-9]*)\s+as\s+(.*)$",
    re.IGNORECASE | re.DOTALL)
_DROP_RE = re.compile(
    r"^\s*drop\s+(?:temp(?:orary)?\s+)?view\s+(if\s+exists\s+)?"
    r"([A-Za-z_][A-Za-z_0-9]*)\s*$", re.IGNORECASE)


def plan_summary(q: Query) -> str:
    """``explain()``-style one-line plan for a parsed query — the operator
    chain root-first (the shape Spark's ``explain`` prints), attached to
    every ``sql.query`` span so traces show WHAT a query did, not just its
    text.

    When the pipeline compiler is on (``spark.pipeline.enabled``, the
    default) and the WHERE predicate plus every projection expression is
    *structurally* compilable, the Project+Filter pair of a
    non-aggregating query prints as ``FusedStage(Project[n] <- Filter)``
    — one compiled XLA program. Structural means column dtypes are
    assumed numeric (the plan is summarized before execution binds the
    frame): a string-COLUMN reference still executes eagerly, but
    string/UDF/subquery expression forms are detected and keep the
    unfused ``Project <- Filter`` rendering.

    Grouped execution markers follow the same structural rule: with
    ``spark.groupedExec.enabled`` (the default), ``ORDER BY`` prints as
    ``DeviceSort[n]`` (one on-device ``lax.sort`` program) and a plain
    ``GROUP BY`` whose aggregates are all segment-lowerable prints as
    ``SegmentedAggregate[groupBy:n]`` (one sort + segment-reduce
    program, see ``ops/segments.py``); a string key discovered at
    execution time silently takes the host fallback, exactly like a
    string column under ``FusedStage``."""
    chain = plan_tree(q).main_chain()
    s = " <- ".join(n.label for n in chain)
    if q.unions:
        s += f" (+{len(q.unions)} set-op)"
    if q.ctes:
        s = f"With[{len(q.ctes)}] " + s
    return s


def _structurally_fusable(q: Query) -> bool:
    """The FusedStage predicate — one definition for the plan-summary
    marker, the plan tree, and EXPLAIN (the pipeline compiler re-checks
    against real dtypes at flush time; see :func:`plan_summary`)."""
    from ..config import config as _cfg
    from ..frame.aggregates import AggExpr
    from ..ops.compiler import is_compilable

    aggregating = bool(q.group_by) or any(
        isinstance(it, (AggExpr, PostAggItem)) for it in q.items)
    return (_cfg.pipeline and q.where is not None and not aggregating
            and is_compilable(q.where, _OPTIMISTIC_SCHEMA)
            and all(isinstance(it, str)
                    or is_compilable(it, _OPTIMISTIC_SCHEMA)
                    or isinstance(it, E.Col)
                    for it in q.items))


def _structurally_segmented(q: Query) -> bool:
    from ..config import config as _cfg

    return (_cfg.grouped_exec and q.group_mode == "group"
            and _segment_lowerable_aggs(q.items))


class PlanNode:
    """One operator of the structural query plan — the per-operator node
    tree ``plan_summary``'s flat chain is derived from, and the carrier
    of EXPLAIN ANALYZE's measured stats (``stats`` stays empty on the
    un-executed ``plan_tree`` output; EXPLAIN adds the static
    ``est_peak`` column, ANALYZE the measured schema). ``children[0]``
    is the operator's input; a Join's ``children[1]`` is the probe-side
    Scan. ``meta`` carries structural facts the static-memory estimator
    needs (Scan view name, the FusedStage's parsed query) — never
    rendered."""

    __slots__ = ("op", "detail", "children", "stats", "meta")

    def __init__(self, op: str, detail: str = "", children=()):
        self.op = op
        self.detail = detail
        self.children = list(children)
        self.stats: dict = {}
        self.meta: dict = {}

    @property
    def label(self) -> str:
        return f"{self.op}{self.detail}"

    def walk(self):
        """Preorder traversal over every node."""
        yield self
        for c in self.children:
            yield from c.walk()

    def execution_order(self):
        """Nodes in the order the engine RUNS them (inputs before
        consumers) — the order their spans arrive in, which is what FIFO
        span attribution must follow (a root-first walk would hand the
        WHERE filter's span to the Having node). Postorder — which is
        already execution order for chains, Join probe sides, and SetOps
        union branches — except ``With``, whose CTEs (children[1:]) run
        BEFORE the main query (children[0])."""
        if self.op == "With":
            for c in self.children[1:]:
                yield from c.execution_order()
            if self.children:
                yield from self.children[0].execution_order()
            yield self
            return
        for c in self.children:
            yield from c.execution_order()
        yield self

    def main_chain(self) -> list:
        """Root-first operator chain down ``children[0]``, ending at the
        Scan — exactly the shape :func:`plan_summary` prints. (A Scan
        may carry a derived-table subquery plan as its child; the chain
        does not descend into it.)"""
        out, node = [], self
        while node is not None:
            out.append(node)
            node = (node.children[0]
                    if node.children and node.op != "Scan" else None)
        return out

    def render(self, analyze: bool = False) -> str:
        """Indented operator tree; any annotated stats (the static
        ``est_peak`` column on EXPLAIN, the full measured schema on
        ANALYZE) print as a logfmt suffix."""
        from ..utils.logging import format_kv

        lines: list[str] = []

        def emit(node, depth):
            pad = "" if depth == 0 else "   " * (depth - 1) + "+- "
            line = pad + node.label
            if node.stats:
                # unknowns render as "-" so every node shows the full
                # stat schema (format_kv would elide None)
                stats = {k: ("-" if node.stats[k] is None
                             else node.stats[k]) for k in node.stats}
                kv = format_kv(**stats)
                if kv:
                    line += f"  ({kv})"
            lines.append(line)
            for c in node.children:
                emit(c, depth + 1)

        emit(self, 0)
        return "\n".join(lines)


def plan_tree(q: Query) -> PlanNode:
    """Build the per-operator plan-node tree for a parsed query (the
    structural plan: built before execution binds the frame, so markers
    follow the same optimistic-dtype convention as ``plan_summary``)."""
    def scan_node(view):
        """Scan leaf; a derived table carries its subquery's plan as a
        child (outside the main chain) so EXPLAIN shows it and span
        attribution consumes the subquery's spans at the right point
        instead of handing them to outer same-named operators."""
        if isinstance(view, DerivedTable):
            return PlanNode("Scan", "[(subquery)]",
                            [plan_tree(view.query)])
        if isinstance(view, str):
            n = PlanNode("Scan", f"[{view}]")
            n.meta["view"] = view      # static-memory estimator lookup
            return n
        return PlanNode("Scan", "[(subquery)]")  # OneRowRelation et al.

    node = scan_node(q.view)
    hints = list(getattr(q, "join_build", ()) or ())
    hints += [None] * (len(q.joins) - len(hints))
    for (view, how, _keys, _alias), hint in zip(reversed(q.joins),
                                                reversed(hints)):
        how = how if isinstance(how, str) else "inner"
        detail = f"[{how},build={hint}]" if hint else f"[{how}]"
        node = PlanNode("Join", detail, [node, scan_node(view)])
    if _structurally_fusable(q):
        node = PlanNode("FusedStage",
                        f"(Project[{len(q.items)}] <- Filter)", [node])
        node.meta["query"] = q         # abstract-traceable stage
    else:
        if q.where is not None:
            node = PlanNode("Filter", "", [node])
            node.meta["query"] = q     # est-rows history lookup
        node = PlanNode("Project", f"[{len(q.items)}]", [node])
    if q.group_by:
        mode = q.group_mode if q.group_mode != "group" else "groupBy"
        op = ("SegmentedAggregate" if _structurally_segmented(q)
              else "Aggregate")
        node = PlanNode(op, f"[{mode}:{len(q.group_by)}]", [node])
        node.meta["query"] = q         # cardinality-history lookup
    if q.having is not None:
        node = PlanNode("Having", "", [node])
    if q.distinct:
        node = PlanNode("Distinct", "", [node])
        node.meta["query"] = q         # cardinality-history lookup
    if q.order_by:
        from ..config import config as _cfg

        node = PlanNode("DeviceSort" if _cfg.grouped_exec else "Sort",
                        f"[{len(q.order_by)}]", [node])
    if q.offset:
        node = PlanNode("Offset", f"[{q.offset}]", [node])
        node.meta["offset"] = q.offset
    if q.limit is not None:
        node = PlanNode("Limit", f"[{q.limit}]", [node])
        node.meta["limit"] = q.limit
    return node


_EXPLAIN_RE = re.compile(r"^\s*explain(\s+analyze)?\b(.*)$",
                         re.IGNORECASE | re.DOTALL)

#: Plan-node op → the span names that measure it, most specific first.
#: ``frame.grouped.flush:<op>`` keys the grouped-engine flush spans by
#: their ``op`` attribute. Spans are consumed FIFO, so a query with two
#: joins attributes the first ``frame.join`` span to the first Join node.
_NODE_SPAN_CANDIDATES = {
    "FusedStage": ("frame.pipeline.flush", "frame.filter", "frame.select"),
    "ShardedStage": ("frame.pipeline.flush", "frame.filter",
                     "frame.select"),
    "Filter": ("frame.filter",),
    "Project": ("frame.select",),
    "Aggregate": ("frame.agg",),
    "SegmentedAggregate": ("frame.grouped.flush:group_by", "frame.agg"),
    "Having": ("frame.filter",),
    "Sort": ("frame.sort",),
    "DeviceSort": ("frame.sort", "frame.grouped.flush:sort"),
    "Distinct": ("frame.distinct", "frame.drop_duplicates",
                 "frame.grouped.flush:distinct"),
    "Join": ("frame.join",),
}

#: Nodes whose program (if any) is the pipeline compiler's — a deferred
#: filter/projection flushes OUTSIDE its own op span (at the next
#: materialization point), so the verdict may ride an unconsumed
#: ``frame.pipeline.flush`` span at query level. The predicate keys on
#: the flush span's shape: ``steps`` are with_column/filter steps (the
#: Filter node's program), ``outputs`` are fused select projections (the
#: Project node's program); FusedStage owns both.
_PIPELINE_NODE_PRED = {
    "FusedStage": lambda a: True,
    "ShardedStage": lambda a: True,
    "Filter": lambda a: a.get("steps", 0) > 0,
    "Project": lambda a: a.get("outputs", 0) > 0,
}

#: The acceptance contract: EVERY operator node carries these keys after
#: an ANALYZE pass (measured where a span matched, defaults otherwise).
_ANALYZE_DEFAULTS = (("rows_in", None), ("rows_out", None),
                     ("wall_ms", 0.0), ("compile", "none"),
                     ("host_syncs", 0), ("peak_mem", None))


def _annotate_plan(tree: PlanNode, qs) -> None:
    """Attribute one query's collected spans to plan-tree operators.

    ``qs`` is an ``observability.QueryStatsCollector`` whose window was
    exactly this query's execution. Attribution is name-based and FIFO
    (frame ops execute in plan order within one query); the compile-vs-
    cache-hit verdict comes from the operator's own flush span or the
    flush span nested directly under it. After the walk every node holds
    the full stat schema (:data:`_ANALYZE_DEFAULTS`)."""
    by_name: dict[str, list] = {}
    children_of: dict = {}
    for s in qs.spans:
        by_name.setdefault(s.name, []).append(s)
        children_of.setdefault(s.parent_id, []).append(s)
        if s.name == "frame.grouped.flush":
            by_name.setdefault(
                f"frame.grouped.flush:{s.attrs.get('op')}", []).append(s)

    def pop(name, pred=None):
        lst = by_name.get(name)
        for s in list(lst or ()):
            if pred is not None and not pred(s.attrs):
                continue
            for other in by_name.values():   # one span feeds ONE node
                if s in other:
                    other.remove(s)
            return s
        return None

    peak_attr = max((s.attrs.get("peak_mem", 0) for s in qs.spans),
                    default=0) or None
    # EXECUTION order, not render order: spans arrive input-side-first,
    # and FIFO queues must be consumed the same way (a root-first walk
    # would hand the WHERE filter's span to the Having node).
    for node in tree.execution_order():
        primary = None
        for name in _NODE_SPAN_CANDIDATES.get(node.op, ()):
            primary = pop(name)
            if primary is not None:
                break
        stats = node.stats
        if primary is not None:
            a = primary.attrs
            # cost-observatory join handles: the plan key (when the
            # span's program has one) addresses the CostProfile cache;
            # "measured" marks operators that actually ran (the roofline
            # `host` verdict's evidence). meta, never rendered.
            node.meta["measured"] = True
            if a.get("plan_key"):
                node.meta["plan_key"] = a["plan_key"]
            if "rows_in" in a:
                stats["rows_in"] = a.get("rows_in")
                stats["rows_out"] = a.get("rows_out")
            else:                 # a flush span: rows/groups vocabulary
                stats["rows_in"] = a.get("rows")
                stats["rows_out"] = a.get("groups", a.get("rows"))
            stats["wall_ms"] = round((primary.dur_us or 0) / 1e3, 3)
            stats["host_syncs"] = a.get("host_syncs", 0)
            if a.get("peak_mem") is not None:
                stats["peak_mem"] = a["peak_mem"]
            if a.get("lowering"):
                stats["lowering"] = a["lowering"]
            verdict = a.get("cache")
            if verdict is None:
                # the flush program ran nested under this op's span
                # (grouped sort/distinct on accelerators)
                for c in children_of.get(primary.sid, ()):
                    if c.name in ("frame.pipeline.flush",
                                  "frame.grouped.flush") \
                            and c.attrs.get("cache"):
                        verdict = c.attrs["cache"]
                        break
            pred = _PIPELINE_NODE_PRED.get(node.op)
            if verdict is None and pred is not None:
                # deferred pipeline steps flush at the next
                # materialization point, outside the op's own span
                flush = pop("frame.pipeline.flush", pred)
                if flush is not None:
                    verdict = flush.attrs.get("cache")
                    stats["flush_ms"] = round((flush.dur_us or 0) / 1e3, 3)
                    if flush.attrs.get("plan_key"):
                        node.meta["plan_key"] = flush.attrs["plan_key"]
            if verdict is not None:
                stats["compile"] = verdict
            for k, v in a.items():
                if k.startswith("recovery_"):
                    stats[k] = v
        for key, default in _ANALYZE_DEFAULTS:
            stats.setdefault(key, default)
        if stats["peak_mem"] is None:
            stats["peak_mem"] = peak_attr
    # Row counts flow along edges: an operator with no span of its own
    # (Scan, Limit, Offset) inherits its input's output count and its
    # consumer's input count — static shape info, never a device read.
    chain = tree.main_chain()
    for parent, child in zip(chain, chain[1:]):
        if child.stats.get("rows_out") is None \
                and parent.stats.get("rows_in") is not None:
            child.stats["rows_out"] = parent.stats["rows_in"]
        if parent.stats.get("rows_in") is None \
                and child.stats.get("rows_out") is not None:
            parent.stats["rows_in"] = child.stats["rows_out"]


def _filter_history_key(q, cat) -> Optional[str]:
    """The statstore selectivity key a flush of this query's WHERE would
    record under — computed from the parsed predicate plus the scanned
    view's REAL column dtypes (catalog lookup; zero execution, zero
    device reads). None when the view is unregistered, the predicate is
    not structurally compilable (those flushes run eager and record no
    history), or the query joins (the flush-time schema then carries
    joined columns this static walk cannot see)."""
    view = q.view if isinstance(q.view, str) else None
    if view is None or q.where is None or q.joins:
        return None
    try:
        frame = cat.lookup(view)
    except Exception:
        return None
    # Mirror the executor's name resolution (qualified ``t.x`` refs
    # rewrite to flat columns BEFORE the filter defers — the flush-time
    # history key is recorded against the RESOLVED predicate). Subquery
    # markers are deliberately NOT resolved here (that would execute
    # them); they fail the compilability walk below and yield None,
    # exactly like their flushes record nothing.
    where = q.where
    try:
        scope = {(q.view_alias or view).lower():
                 {c: c for c in frame.columns}}
        where = _resolve_qualified(where, scope, frame.columns)
    except Exception:
        return None
    from ..ops import compiler as C

    schema = C.LazySchema(frame._data_store, frame._pending_names())
    return C.selectivity_key_for((("filter", where),), schema)


def _annotate_est_rows(tree: PlanNode, cat) -> None:
    """History-informed cardinality column (``est_rows``) — the plan-
    stats observatory's EXPLAIN surface, next to dqaudit's ``est_peak``:
    Scan rows are static slot counts, Filter/FusedStage apply the
    HISTORICAL selectivity recorded for the structurally-same filter
    stack (``utils.statstore``; persisted across sessions), and
    row-preserving operators propagate. Unknowns stay None and render as
    ``-``. Zero execution: catalog lookups + one ``_linearize`` walk per
    filter, never a compile or device read (the deferred-observation
    drain is a host pull of already-dispatched scalars). Never raises —
    estimation is advisory."""
    from ..utils import statstore as _stats

    try:
        _stats.STORE.drain_pending()
    except Exception:
        pass
    #: CTE-name -> estimated rows (filled from the With wrapper's CTE
    #: bodies BEFORE the main query annotates, so a Scan of a CTE name
    #: resolves history-informed cardinality instead of going "-")
    cte_est: dict[str, int] = {}

    def est(node) -> Optional[int]:
        try:
            child = est(node.children[0]) if node.children else None
        except RecursionError:   # pathological depth: stop annotating
            return None
        out: Optional[int] = None
        op = node.op
        if op == "Scan":
            view = node.meta.get("view")
            if isinstance(view, str):
                if view.lower() in cte_est:
                    out = cte_est[view.lower()]
                else:
                    try:
                        out = int(cat.lookup(view).num_slots)
                    except Exception:
                        out = None
            else:
                out = child      # derived table: its subquery's estimate
        elif op in ("FusedStage", "ShardedStage", "Filter"):
            q = node.meta.get("query")
            if child is not None and q is not None:
                skey = _filter_history_key(q, cat)
                if skey is not None:
                    sel = _stats.STORE.selectivity(skey)
                    if sel is not None:
                        out = int(round(sel * child))
        elif op in ("Project", "Sort", "DeviceSort", "Exchange"):
            out = child
        elif op == "Limit":
            lim = node.meta.get("limit")
            out = (min(child, int(lim)) if child is not None
                   and lim is not None else None)
        elif op == "Offset":
            off = node.meta.get("offset")
            out = (max(child - int(off), 0) if child is not None
                   and off is not None else None)
        elif op in ("Aggregate", "SegmentedAggregate", "Distinct"):
            # output-cardinality history (ROADMAP item 4's named
            # headroom): the grouped engine records observed
            # rows-in → groups-out under a name+dtype-addressed key
            # (ops/segments.cardinality_history_key), so aggregates no
            # longer estimate blind — the recorded group ratio scales
            # the input estimate. Still advisory; unknown stays "-".
            q = node.meta.get("query")
            if child is not None and q is not None:
                ckey = _cardinality_history_key(q, cat,
                                                op == "Distinct")
                if ckey is not None:
                    sel = _stats.STORE.selectivity(ckey)
                    if sel is not None:
                        out = int(round(sel * child))
        # Join/SetOps output cardinality has no history key yet —
        # stays unknown rather than a guess. DDL and wrapper nodes
        # have no cardinality at all and stay unannotated.
        if op not in ("CreateView", "DropView", "With", "SetOps"):
            node.stats["est_rows"] = out
        # cardinality propagates along children[0], but side arms (a
        # Join's probe-side Scan) still deserve their own annotation —
        # the column must not silently disappear on the right arm
        for side in node.children[1:]:
            est(side)
        return out

    def annotate(node) -> Optional[int]:
        """Wrapper-aware walk: With annotates its CTE bodies first (in
        registration order — later CTEs may scan earlier ones) and
        propagates the main query's estimate onto the wrapper; SetOps
        annotates every branch and folds branch estimates through the
        operator chain (UNION sums — an upper bound under dedup —,
        INTERSECT takes the min, EXCEPT keeps the left bound)."""
        if node.op == "With":
            for name, sub in zip(node.meta.get("cte_names") or (),
                                 node.children[1:]):
                v = annotate(sub)
                if v is not None:
                    cte_est[str(name).lower()] = v
            out = annotate(node.children[0]) if node.children else None
            node.stats["est_rows"] = out
            return out
        if node.op == "SetOps":
            vals = [annotate(c) for c in node.children]
            out = vals[0] if vals else None
            for op, v in zip(node.meta.get("set_ops") or (), vals[1:]):
                if op in ("union", "union_all"):
                    out = out + v if out is not None and v is not None \
                        else None
                elif op == "intersect":
                    out = min(out, v) if out is not None and v is not None \
                        else None
                # except: the left branch bound stands
            node.stats["est_rows"] = out
            return out
        if node.op == "CreateView":
            for c in node.children:
                annotate(c)
            return None
        return est(node)

    try:
        annotate(tree)
    except Exception:
        pass


def _cardinality_history_key(q, cat, distinct: bool):
    """The statstore output-cardinality key a grouped/distinct flush of
    this query would record under (``ops/segments.
    cardinality_history_key`` — name+dtype addressed, zero execution).
    None when the view is unregistered, the query joins (the flush-time
    frame carries joined columns this static walk cannot see), or any
    key is not a plain resolvable column."""
    view = q.view if isinstance(q.view, str) else None
    if view is None or q.joins:
        return None
    try:
        frame = cat.lookup(view)
    except Exception:
        return None
    if distinct:
        names = []
        for it in q.items:
            # plain column projections only (str or a bare Col ref) —
            # computed items change the distinct key surface in ways
            # this static probe cannot mirror
            if isinstance(it, str) and it != "*":
                names.append(it)
            elif isinstance(it, E.Col):
                names.append(it.name)
            else:
                return None
        if not names:
            return None
    else:
        names = [k for k in q.group_by if isinstance(k, str)]
        if len(names) != len(q.group_by) or not names:
            return None
    arrs = [frame._data_store.get(n) for n in names]
    if any(a is None for a in arrs):
        return None
    from ..ops import segments as _segments

    return _segments.cardinality_history_key(
        "d" if distinct else "g", names, arrs)


def _annotate_costs(tree: PlanNode) -> None:
    """Device-cost observatory columns (``utils/costprof.py``) for
    EXPLAIN ANALYZE: per operator node, the AOT cost profile addressed
    by the plan key its flush span carried (``est_flops``/``est_bytes``),
    achieved throughput against the node's measured wall
    (``gflops``/``gbps`` — structural on the CPU sandbox, meaningful on
    TPU captures), and the roofline ``bound`` verdict
    (compute|memory|sync|host). COLD surface: a cache-miss profile can
    cost one XLA compile of the un-counted trace body — zero device
    execution, zero counted host syncs, zero counted compiles
    (test-pinned). A degraded extraction (the ``cost_profile`` fault
    ladder) leaves every column "-". Never raises — cost annotation is
    advisory."""
    from ..utils import costprof as _costprof

    try:
        # ONE batched resolution (one registry enumeration) for every
        # keyed node, then a second walk annotates
        profiles = _costprof.profiles_for(
            n.meta.get("plan_key") for n in tree.execution_order())
        for node in tree.execution_order():
            stats = node.stats
            if "wall_ms" not in stats:
                continue              # un-analyzed node (no stat schema)
            key = node.meta.get("plan_key")
            prof = profiles.get(key) if key else None
            wall = stats.get("flush_ms") or stats.get("wall_ms")
            gflops, gbps = _costprof.achieved(prof, wall)
            if prof is not None:
                bound = _costprof.roofline(
                    prof, int(stats.get("host_syncs") or 0))
            elif key:
                bound = None          # extraction degraded: render "-"
            elif node.meta.get("measured"):
                bound = "host"        # ran, but with no device program
            else:
                bound = None
            stats["est_flops"] = (None if prof is None
                                  else int(prof.flops))
            stats["est_bytes"] = (None if prof is None
                                  else int(prof.bytes_accessed))
            stats["gflops"] = gflops
            stats["gbps"] = gbps
            stats["bound"] = bound
    except Exception:
        pass


def _annotate_sharded(tree: PlanNode, cat) -> None:
    """Sharded-frames EXPLAIN markers (``spark.shard.enabled``): when a
    scanned view's frame is row-sharded, Scan nodes carry the per-shard
    row counts, the fused stage renders as ``ShardedStage[k]`` (one
    ``shard_map`` program over ``k`` shards, zero cross-shard traffic),
    and operators that move rows across shards gain an ``Exchange``
    child — ``[merge:psum]`` under grouped aggregation (the per-shard
    slot-table merge collective), ``[hash:all_to_all]`` under DISTINCT
    and join (the shuffle lowering), ``[gather]`` under a total sort.
    Pure annotation: zero execution, never raises."""
    from ..parallel.shard import active_mesh

    mesh = active_mesh()
    if mesh is None:
        return
    k = int(mesh.devices.size)

    def store_of(node):
        view = node.meta.get("view")
        if not isinstance(view, str):
            return None
        try:
            return getattr(cat.lookup(view), "_shard", None)
        except Exception:
            return None

    def exchange(node, kind):
        node.children[0] = PlanNode("Exchange", f"[{kind}]",
                                    [node.children[0]])

    def visit(node) -> bool:
        """Returns whether the node's OUTPUT rows are shard-resident."""
        child_sharded = [visit(c) for c in node.children]
        if node.op == "Scan":
            store = store_of(node)
            if store is not None:
                node.stats["shards"] = store.devices
                node.stats["rows_per_shard"] = "/".join(
                    str(c) for c in store.shard_counts())
                return True
            return bool(child_sharded) and child_sharded[0]
        inp = bool(child_sharded) and child_sharded[0]
        if node.op == "FusedStage" and inp:
            node.op = "ShardedStage"
            node.detail = f"[{k}]" + node.detail
            return True
        if node.op in ("Filter", "Project", "Having", "Offset",
                       "Limit") and inp:
            return True
        if node.op in ("Aggregate", "SegmentedAggregate") and inp:
            exchange(node, "merge:psum")
            return False
        if node.op == "Distinct" and inp:
            exchange(node, "hash:all_to_all")
            return False
        if node.op in ("Sort", "DeviceSort") and inp:
            exchange(node, "gather")
            return False
        if node.op == "Join" and any(child_sharded):
            for i, sharded in enumerate(child_sharded):
                if sharded:
                    node.children[i] = PlanNode(
                        "Exchange", "[hash:all_to_all]",
                        [node.children[i]])
            return False
        return False

    try:
        visit(tree)
    except Exception:
        pass


def _parse_explain_tree(body: str):
    """Parse an EXPLAIN'd statement into ``(plan_tree, kind, payload)``:
    ``("query", Query)`` for a SELECT statement, ``("create"|"drop",
    body)`` for the DDL forms (their child tree is the materializing
    query's plan)."""
    m = _DDL_RE.match(body)
    if m:
        name, inner = m.group(1), m.group(2)
        sub = _EXPLAIN_RE.match(inner)
        if sub:       # EXPLAIN CREATE VIEW v AS EXPLAIN ... is nonsense
            raise ValueError("nested EXPLAIN is not supported")
        tree = PlanNode("CreateView", f"[{name}]",
                        [plan_tree(parse(inner))])
        return tree, "create", body
    m = _DROP_RE.match(body)
    if m:
        return PlanNode("DropView", f"[{m.group(2)}]"), "drop", body
    q = parse(body)
    return _wrap_plan_tree(q), "query", q


def _wrap_plan_tree(q: Query) -> PlanNode:
    """Plan tree for a full statement: the main query's tree plus the
    With/SetOps wrapper nodes. ``meta`` carries the CTE names and the
    set-operator list so ``_annotate_est_rows`` can propagate
    cardinality through the wrappers (a Scan of a CTE name resolves
    against the CTE body's estimate, not the catalog)."""
    tree = plan_tree(q)
    if q.ctes:
        # children[0] = main query; children[1:] = the CTE bodies in
        # registration order (execution_order runs them first)
        tree = PlanNode("With", f"[{len(q.ctes)}]",
                        [tree] + [plan_tree(sub) for _name, sub in q.ctes])
        tree.meta["cte_names"] = [name for name, _sub in q.ctes]
    if q.unions:
        tree = PlanNode("SetOps", f"[+{len(q.unions)}]",
                        [tree] + [plan_tree(sub) for _op, sub in q.unions])
        tree.meta["set_ops"] = [op for op, _sub in q.unions]
    return tree


def _cache_lines(before: dict, after: dict) -> list[str]:
    """One line per cache (and per cached program) the query touched —
    the diff of two ``observability.cache_report()`` snapshots."""
    lines: list[str] = []
    for name, post in sorted(after.items()):
        pre = before.get(name, {})
        if not isinstance(post, dict) or not isinstance(pre, dict):
            continue
        deltas = {}
        for k in ("hits", "misses", "evictions", "fallbacks",
                  "dense_misses"):
            d = (post.get(k) or 0) - (pre.get(k) or 0)
            if d:
                deltas[k] = d
        if not deltas and post.get("entries") == pre.get("entries"):
            continue
        summary = " ".join(f"{k}+{v}" for k, v in deltas.items())
        lines.append(f"{name}: size={post.get('size', '?')}"
                     + (f" {summary}" if summary else ""))
        pre_entries = {e.get("key"): e for e in pre.get("entries") or ()}
        for e in post.get("entries") or ():
            p = pre_entries.get(e.get("key"), {})
            touched = any((e.get(k) or 0) > (p.get(k) or 0)
                          for k in ("hits", "compiles", "builds"))
            if not touched:
                continue
            # program_key duplicates key= (it is the un-truncated form
            # the program auditor addresses) — one rendering is enough
            detail = {k: v for k, v in e.items()
                      if k not in ("key", "program_key")}
            from ..utils.logging import format_kv

            lines.append(f"  program {format_kv(**detail)} key="
                         f"{e.get('key', '')!r}")
    return lines


def _execute_explain(body: str, cat, analyze: bool):
    """Run an ``EXPLAIN [ANALYZE]`` statement. EXPLAIN renders the
    structural plan tree WITHOUT executing (zero compiles, zero device
    work — pure parsing); EXPLAIN ANALYZE executes the statement under a
    per-query stats collector (``observability.query_stats``) and
    annotates every operator with measured rows, wall ms, compile/hit
    verdicts, host syncs, recovery events, and peak device bytes, plus a
    cache section (one line per compiled program touched). Returns a
    one-row Frame with the plan text in a ``plan`` column (the Spark
    ``EXPLAIN`` result shape)."""
    from ..config import config as _cfg
    from ..frame.frame import Frame

    tree, kind, payload = _parse_explain_tree(body)
    # Cost-based optimizer (sql/optimizer.py): rewrite the parsed query
    # exactly as execution would — zero execution, static metadata +
    # statstore history only — and render the before/after plan diff
    # plus one line per applied rewrite. The optimized payload is what
    # ANALYZE then executes, so the annotated tree matches the plan
    # that actually ran.
    opt_rewrites: list[str] = []
    before_text: Optional[str] = None
    if kind == "query" and _cfg.optimizer_enabled:
        from . import optimizer as _optimizer

        q_opt, rewrites = _optimizer.optimize_or_fallback(payload, cat)
        if rewrites:
            before_text = tree.render()
            tree = _wrap_plan_tree(q_opt)
            payload = q_opt
            opt_rewrites.extend(str(r) for r in rewrites)
    _annotate_sharded(tree, cat)
    _obs.current_span().set(
        plan=("ExplainAnalyze" if analyze else "Explain"))
    # Static memory bounds (dqaudit tier, analysis/program/static_mem):
    # the `est peak` column — computed BEFORE execution from shape
    # metadata + one abstract trace of the fused stage (zero compiles,
    # zero device work), where EXPLAIN ANALYZE only measures after the
    # fact. Gated on spark.audit.enabled; the audit package imports
    # lazily so the default query path never loads it.
    budget_line = None
    if _cfg.audit_enabled:
        from ..analysis.program import static_mem as _static_mem
        from ..analysis.program.detectors import audit_budget_bytes

        root_est = _static_mem.annotate_plan(tree, cat)
        if root_est is not None:
            # the SAME budget policy as the audit-memory detector —
            # EXPLAIN and session.audit_report() must agree on one plan
            budget = audit_budget_bytes(int(_cfg.audit_device_budget))
            if budget is not None and \
                    root_est > _cfg.audit_memory_fraction * budget:
                budget_line = (
                    f"!! est peak {root_est} bytes exceeds "
                    f"{_cfg.audit_memory_fraction:g} x device limit "
                    f"{budget} bytes (spark.audit.memoryFraction)")
                if _cfg.optimizer_enabled:
                    # the PR-9 static bound, promoted to a PLANNED
                    # decision: over-budget flushes run row-chunked
                    # up front (ops/compiler.run_pipeline), not as an
                    # allocator-fault ladder rung
                    opt_rewrites.append(
                        "mem-chunk: planned row-chunked execution "
                        f"(est peak {root_est} B vs budget {budget} B)")
    # History-informed `est rows` (plan-stats observatory,
    # utils/statstore.py): annotated BEFORE any execution — on plain
    # EXPLAIN this is the whole point (zero-execution cardinality from
    # persisted history), on ANALYZE it is the *pre-query* historical
    # view the measured rows are then compared against (drift).
    if _cfg.stats_enabled:
        _annotate_est_rows(tree, cat)
    def _opt_sections() -> list[str]:
        out: list[str] = []
        if opt_rewrites:
            out.append("== Rewrites ==")
            out.extend(opt_rewrites)
        if before_text is not None:
            out.append("== Before Optimization ==")
            out.append(before_text)
        return out

    if not analyze:
        text = "== Physical Plan ==\n" + tree.render()
        if budget_line:
            text += "\n" + budget_line
        for ln in _opt_sections():
            text += "\n" + ln
        return Frame({"plan": [text]})

    import time as _time

    import jax as _jax

    from . import adaptive as _adaptive

    caches_before = _obs.cache_report() if _cfg.explain_caches else {}
    # Data-quality observatory marks (utils/dqprof.py) — gated on ONE
    # flag read; disabled restores the exact pre-observatory ANALYZE
    # schema (acceptance-pinned byte-identical). The pre-execution
    # drain is this cold surface's own counted sync, outside the
    # query-stats window so per-query attribution is untouched.
    dq_marks = None
    if _cfg.dq_profile_enabled:
        from ..utils import dqprof as _dqprof

        dq_marks = _dqprof.rule_marks()
    # ANALYZE executes under the adaptive capture scope: any mid-query
    # re-plan the hooks apply (sql/adaptive.py) records an event here
    # and renders as the `== Adaptive ==` section. No events (AQE off,
    # or simply no drift) -> no section — output stays byte-identical
    # to the static engine.
    with _adaptive.capture() as aqe_events, \
            _obs.query_stats(sample_memory=_cfg.explain_memory) as qs:
        t0 = _time.perf_counter()
        if kind == "query":
            out = _run_parsed(payload, cat)
        else:
            out = _execute_statement(payload, cat)
        # honest wall-clock: flush any pending fused pipeline and wait
        # for the async dispatches the query enqueued
        _jax.block_until_ready(out._mask)
        wall_ms = (_time.perf_counter() - t0) * 1e3
    _annotate_plan(tree, qs)
    # Device-cost observatory columns (utils/costprof.py) — gated on
    # ONE flag read; disabled restores the exact pre-observatory
    # ANALYZE schema (acceptance-pinned byte-identical).
    if _cfg.costprof_enabled:
        _annotate_costs(tree)
    top = tree.main_chain()[0]
    if top.stats.get("rows_out") is None:
        top.stats["rows_out"] = out.num_slots
    rows_valid = None
    if _cfg.stats_enabled:
        # Observed-vs-historical drift: the query's TRUE valid-row count
        # (one mask reduction, outside the stats window so per-operator
        # attribution is untouched) against the pre-query est_rows. The
        # same execution's own deferred observation lands in the store,
        # so the NEXT estimate has already absorbed this drift.
        try:
            rows_valid = int(out.count())
        except Exception:
            rows_valid = None
        top.stats["rows_valid"] = rows_valid
        est = top.stats.get("est_rows")
        if est is not None and rows_valid is not None:
            top.stats["est_drift"] = (
                f"x{est / rows_valid:.2f}" if rows_valid
                else f"+{est}")
        from ..utils import statstore as _statstore

        _statstore.STORE.absorb_query_stats(qs)
    delta = qs.counter_delta()
    lines = ["== Analyzed Plan ==", tree.render(analyze=True),
             "== Query Stats =="]
    from ..utils.logging import format_kv

    totals = {
        "wall_ms": round(wall_ms, 3),
        "rows_out": out.num_slots,
        "host_syncs": delta.get("frame.host_sync", 0),
        "compiles": (delta.get("pipeline.compile", 0)
                     + delta.get("grouped.compile", 0)),
        "cache_hits": (delta.get("pipeline.hit", 0)
                       + delta.get("grouped.hit", 0)),
        "fallbacks": (delta.get("pipeline.fallback", 0)
                      + delta.get("grouped.fallback", 0)),
        # action-level keys only: the per-site mirrors
        # (recovery.retry.<site>) would double-count every event
        "recovery_events": sum(v for k, v in delta.items()
                               if k.startswith("recovery.")
                               and "." not in k[len("recovery."):]),
    }
    if _cfg.explain_memory:
        from ..utils import meminfo as _meminfo

        totals["live_bytes"] = _meminfo.sample()
        totals["peak_bytes"] = _meminfo.peak_bytes()
    lines.append(format_kv(**totals))
    if _cfg.explain_caches:
        cl = _cache_lines(caches_before, _obs.cache_report())
        if cl:
            lines.append("== Caches ==")
            lines.extend(cl)
    if budget_line:
        lines.append(budget_line)
    lines.extend(_opt_sections())
    if aqe_events:
        lines.append("== Adaptive ==")
        lines.extend(_adaptive.render(aqe_events))
    if dq_marks is not None:
        from ..utils import dqprof as _dqprof

        # renders only when this query evaluated a registered DQ rule
        # (delta over dq_marks) — rule-free ANALYZE stays byte-identical
        lines.extend(_dqprof.explain_lines(dq_marks))
    return Frame({"plan": ["\n".join(lines)]})


def execute(sql: str, catalog=None):
    """Run a statement (WITH CTEs + query + UNIONs) against the catalog.

    Besides queries, two DDL forms Spark users reach for from
    ``session.sql``: ``CREATE [OR REPLACE] [TEMP] VIEW name AS query``
    (materializes the query and registers it — all views here are temp
    views over device-resident Frames) and ``DROP [TEMP] VIEW
    [IF EXISTS] name``. Both return an empty no-column Frame like
    Spark's DDL commands.

    When observability is enabled, each statement runs inside an
    ``sql.query`` span carrying the query text, the plan summary
    (:func:`plan_summary`), and the output row count.
    """
    if not _obs.TRACER.enabled:
        return _execute_statement(sql, catalog)
    with _obs.TRACER.span("sql.query", cat="sql",
                          query=" ".join(sql.split())[:300]) as s:
        out = _execute_statement(sql, catalog)
        n = getattr(out, "_n", None)
        if n is not None:
            s.set(rows_out=n)
        return out


def _maybe_optimize(q: Query, cat):
    """Cost-based rewrite hook (``sql/optimizer.py``), gated on
    ``spark.optimizer.enabled`` — ONE flag read when disabled. Any
    optimizer failure (including the injected ``optimizer`` fault)
    degrades to the unrewritten plan inside ``optimize_or_fallback``."""
    from ..config import config as _cfg

    if not _cfg.optimizer_enabled or getattr(q, "_optimized", False):
        return q
    from . import optimizer as _optimizer

    q2, _rewrites = _optimizer.optimize_or_fallback(q, cat)
    return q2


def _run_parsed(q: Query, cat):
    """Execute an already-parsed query: CTE overlay + set expression.
    Each CTE body and the main set expression pass through the
    cost-based optimizer first (CTE frames are registered in the overlay
    before the main query optimizes, so its relation metadata resolves
    CTE names like any view)."""
    if q.ctes:
        cat = _OverlayCatalog(cat)
        for name, sub in q.ctes:
            # Later CTEs may reference earlier ones (executed in order).
            cat.register(name, _execute_set(_maybe_optimize(sub, cat),
                                            cat))
    return _execute_set(_maybe_optimize(q, cat), cat)


def _execute_statement(sql: str, catalog=None):
    from .catalog import default_catalog

    cat = catalog if catalog is not None else default_catalog()
    m = _EXPLAIN_RE.match(sql)
    if m and m.group(2).strip():
        return _execute_explain(m.group(2), cat, analyze=bool(m.group(1)))
    m = _DDL_RE.match(sql)
    if m:
        name, body = m.group(1), m.group(2)
        if _obs.TRACER.enabled:
            # format only when the span is live (disabled-mode no-op)
            _obs.current_span().set(plan=f"CreateView[{name}]")
        frame = execute(body, cat)
        cat.register(name, frame)
        from ..frame.frame import Frame

        return Frame({"__one_row__": [0.0]}).drop("__one_row__").limit(0)
    m = _DROP_RE.match(sql)
    if m:
        if_exists, name = bool(m.group(1)), m.group(2)
        if _obs.TRACER.enabled:
            # format only when the span is live (disabled-mode no-op)
            _obs.current_span().set(plan=f"DropView[{name}]")
        existed = cat.drop(name)
        if not existed and not if_exists:
            raise KeyError(f"temp view {name!r} not found")
        from ..frame.frame import Frame

        return Frame({"__one_row__": [0.0]}).drop("__one_row__").limit(0)
    q = parse(sql)
    if _obs.TRACER.enabled:
        # plan_summary walks the WHERE/projection trees — skip the build
        # entirely when the span is a no-op (the SQL hot path)
        _obs.current_span().set(plan=plan_summary(q))
    return _run_parsed(q, cat)


def _map_cols(expr, fn):
    """Rebuild an expression tree with ``fn`` applied to every Col leaf
    (the shared walk under qualified-ref resolution and agg renaming)."""
    if isinstance(expr, E.Col):
        new = fn(expr.name)
        return expr if new == expr.name else E.Col(new)
    if isinstance(expr, E.SortOrder):
        return E.SortOrder(_map_cols(expr.child, fn), expr.ascending,
                           expr.nulls_first)
    if isinstance(expr, E.BinOp):
        return E.BinOp(expr.op, _map_cols(expr.left, fn),
                       _map_cols(expr.right, fn))
    if isinstance(expr, E.UnaryOp):
        return E.UnaryOp(expr.op, _map_cols(expr.child, fn))
    if isinstance(expr, E.InList):
        return E.InList(_map_cols(expr.child, fn),
                        [_map_cols(v, fn) for v in expr.values],
                        expr.negated)
    if isinstance(expr, E.UdfCall):
        return E.UdfCall(expr.udf_name,
                         [_map_cols(a, fn) for a in expr.args],
                         registry=expr._registry)
    if isinstance(expr, E.Cast):
        return E.Cast(_map_cols(expr.child, fn), expr.type_name)
    if isinstance(expr, E.StringMatch):
        return E.StringMatch(expr.kind, _map_cols(expr.child, fn),
                             expr.pattern, negated=expr.negated)
    if isinstance(expr, E.CaseWhen):
        return E.CaseWhen(
            [(_map_cols(c, fn), _map_cols(v, fn))
             for c, v in expr.branches],
            None if expr.otherwise_expr is None
            else _map_cols(expr.otherwise_expr, fn))
    if isinstance(expr, E.Alias):
        return E.Alias(_map_cols(expr.child, fn), expr._name)
    if isinstance(expr, SubqueryIn):
        # only the OUTER-scope side is mapped; the subquery resolves in
        # its own scope when it executes
        return SubqueryIn(_map_cols(expr.child, fn), expr.query,
                          expr.negated)
    if isinstance(expr, E.HigherOrder):
        # lambda params shadow columns inside the body, so the body's
        # Col refs are left alone; only the source array is mapped
        return E.HigherOrder(expr.kind, _map_cols(expr.source, fn),
                             expr.lam, init=expr.init, finish=expr.finish)
    return expr


def _resolve_agg_cols(agg, scope: dict, columns):
    """Resolve dotted column names inside an AggExpr (mutating the
    parse-fresh object is safe: every Query executes exactly once)."""
    if getattr(agg, "column", None) is not None:
        agg.column = _resolve_name(agg.column, scope, columns)
    if getattr(agg, "column2", None) is not None:
        agg.column2 = _resolve_name(agg.column2, scope, columns)
    return agg


def _resolve_name(name: str, scope: dict, columns) -> str:
    """Resolve a possibly-qualified name against the relation scope.
    A literal column of that (dotted) name wins first — frames may carry
    dotted names from CSV headers; Spark needs backticks there, here the
    literal match is the tiebreak. Names with parens are aggregate-output
    references, never qualified refs."""
    if "." not in name or "(" in name or name in columns:
        return name
    alias, _, col = name.partition(".")
    m = scope.get(alias.lower())
    if m is None:
        raise ValueError(
            f"unknown relation alias {alias!r} in {name!r} "
            f"(aliases in scope: {sorted(scope)})")
    if col not in m:
        raise ValueError(f"column {col!r} not found in relation "
                         f"{alias!r} (has: {sorted(m)})")
    return m[col]


def _resolve_qualified(expr, scope: dict, columns):
    """Rewrite qualified Col refs (``t.price``) to flat output columns;
    inside post-aggregate items, also re-point references at the
    aggregates' renamed output columns (``max(t.p)`` → ``max(p)``)."""
    if not scope:
        return expr
    if isinstance(expr, PostAggItem):
        renames = {}
        aggs = []
        for a in expr.aggs:
            old = a.name
            a = _resolve_agg_cols(a, scope, columns)
            if a.name != old:
                renames[old] = a.name
            aggs.append(a)
        inner = expr.expr
        if renames:
            inner = _map_cols(inner, lambda n: renames.get(n, n))
        inner = _map_cols(inner,
                          lambda n: _resolve_name(n, scope, columns))
        return PostAggItem(inner, aggs, expr._name)
    return _map_cols(expr, lambda n: _resolve_name(n, scope, columns))


def _referenced_cols(expr, out: set) -> None:
    """Collect every column name an expression tree references."""
    if isinstance(expr, E.Col):
        out.add(expr.name)
    for attr in ("left", "right", "child", "otherwise_expr"):
        v = getattr(expr, attr, None)
        if v is not None:
            _referenced_cols(v, out)
    for v in getattr(expr, "args", None) or ():
        _referenced_cols(v, out)
    for v in getattr(expr, "values", None) or ():
        _referenced_cols(v, out)
    for c, v in getattr(expr, "branches", None) or ():
        _referenced_cols(c, out)
        _referenced_cols(v, out)


def _sort_with_exprs(frame, order_by, extra_drops=()):
    """Sort by a mix of column names, SortOrder markers (direction +
    NULLS FIRST/LAST), and expressions: expression keys materialize as
    temp columns (one fused device pass each), sort, then drop the temps
    plus any caller-supplied post-sort columns."""
    cols, asc, temps = [], [], []
    for i, (key, a) in enumerate(order_by):
        if isinstance(key, str):
            cols.append(key)
        elif isinstance(key, E.SortOrder):
            if not isinstance(key.child, E.Col):
                tmp = f"__ord_{i}"
                frame = frame.with_column(tmp, key.child)
                temps.append(tmp)
                key = E.SortOrder(E.Col(tmp), key.ascending,
                                  key.nulls_first)
            cols.append(key)
        else:
            tmp = f"__ord_{i}"
            frame = frame.with_column(tmp, key)
            temps.append(tmp)
            cols.append(tmp)
        asc.append(a)
    frame = frame.sort(*cols, ascending=asc)
    drops = temps + [c for c in extra_drops if c in frame.columns]
    return frame.drop(*drops) if drops else frame


def _execute_single(q: Query, cat):
    """Run one SELECT (no union handling) and return a Frame."""
    from ..frame.aggregates import AggExpr

    scope: dict = {}       # relation alias → {source col: output col}
    if q.view is None:
        # OneRowRelation: a single anonymous row for literal projections
        from ..frame.frame import Frame

        frame = Frame({"__one_row__": [0.0]}).drop("__one_row__")
    elif isinstance(q.view, DerivedTable):
        frame = _execute_set(q.view.query, cat)
        if q.view.alias:
            scope[q.view.alias.lower()] = {c: c for c in frame.columns}
    else:
        frame = cat.lookup(q.view)
        # the alias replaces the name when given (Spark scoping)
        scope[(q.view_alias or q.view).lower()] = \
            {c: c for c in frame.columns}
    build_hints = list(getattr(q, "join_build", ()) or ())
    # optimizer-attached (left, right) row-estimate pairs per join — the
    # drift baseline the adaptive hooks compare observed counts against
    join_ests = list(getattr(q, "join_est", ()) or ())
    for jidx, (view, how, keys, jalias) in enumerate(q.joins):
        right = (_execute_set(view.query, cat)
                 if isinstance(view, DerivedTable) else cat.lookup(view))
        rcols = list(right.columns)
        pre = set(frame.columns)
        frame = frame.join(right, on=keys or None, how=how,
                           build=(build_hints[jidx]
                                  if jidx < len(build_hints) else None),
                           est=(join_ests[jidx]
                                if jidx < len(join_ests) else None))
        name = jalias or (view if isinstance(view, str) else None)
        if name:
            post = set(frame.columns)
            if how in ("left_semi", "left_anti"):
                # semi/anti output carries left columns only; the right
                # side is addressable just through the join keys
                mapping = {k: k for k in keys}
            else:
                mapping = {c: (f"{c}_right" if c not in keys and c in pre
                               and f"{c}_right" in post else c)
                           for c in rcols}
            scope[name.lower()] = mapping
    # Qualified refs (``t.price``) resolve to flat output columns now
    # that the join scope is known.
    if scope:
        cols_now = frame.columns
        if q.where is not None:
            q.where = _resolve_qualified(q.where, scope, cols_now)
        if q.having is not None:
            q.having = _resolve_qualified(q.having, scope, cols_now)
        q.items = [_resolve_agg_cols(it, scope, cols_now)
                   if isinstance(it, AggExpr)
                   else it if isinstance(it, str)
                   else _resolve_qualified(it, scope, cols_now)
                   for it in q.items]
        q.group_by = [_resolve_name(k, scope, cols_now)
                      if isinstance(k, str) else k for k in q.group_by]
        q.order_by = [(_resolve_name(k, scope, cols_now)
                       if isinstance(k, str)
                       else _resolve_qualified(k, scope, cols_now), a)
                      for k, a in q.order_by]
    # Correlated EXISTS/IN predicates decorrelate into semi/anti joins
    # (the rewrite Spark itself performs). CORRELATED NOT IN keeps the
    # anti-join's null semantics (a null key never matches, so its row
    # survives), not SQL's three-valued NOT IN. The UNCORRELATED path
    # below implements the full three-valued rule: subquery/literal value
    # sets materialize into an InList, whose eval makes NOT IN filter
    # every row when the set contains a NULL/NaN and drops the NULL for
    # plain IN (ops/expressions.InList).
    if q.where is not None and scope:
        q.where, corr_joins = _decorrelate_where(q.where, scope, cat)
        for right, keys, how in corr_joins:
            frame = frame.join(right, on=keys, how=how)
    # Uncorrelated subqueries (scalar / IN / EXISTS) resolve to literals
    # against the same catalog before the enclosing query evaluates.
    if q.where is not None:
        q.where = _resolve_subqueries(q.where, cat)
    if q.having is not None:
        q.having = _resolve_subqueries(q.having, cat)
    q.items = [it if isinstance(it, (str, AggExpr))
               else _resolve_subqueries(it, cat) for it in q.items]
    if q.where is not None:
        frame = frame.filter(q.where)
        # Stage boundary (sql/adaptive.py): the WHERE filter just
        # defined the TRUE survivor set behind the mask. When history
        # says far fewer rows survive than the static slot count and a
        # downstream stage exists to profit, compact into the smaller
        # power-of-two bucket so grouping/sort/distinct run with fewer
        # padded slots. ONE conf read when AQE is off.
        from ..config import config as _aqe_cfg

        if _aqe_cfg.aqe_enabled and isinstance(q.view, str) \
                and not q.joins \
                and (q.group_by or q.order_by or q.distinct
                     or any(isinstance(it, AggExpr) for it in q.items)):
            from ..utils import statstore as _statstore
            from . import adaptive as _adaptive

            _skey = _filter_history_key(q, cat)
            if _skey is not None:
                frame = _adaptive.maybe_rebucket(
                    frame,
                    _statstore.STORE.est_rows(_skey, frame.num_slots))

    # ORDER BY <position>: 1-based index into the select list (Spark/ANSI)
    if any(isinstance(k, int) for k, _ in q.order_by):
        resolved = []
        for key, asc in q.order_by:
            if isinstance(key, int):
                if not 1 <= key <= len(q.items):
                    raise ValueError(f"ORDER BY position {key} is not in "
                                     f"the select list (1..{len(q.items)})")
                item = q.items[key - 1]
                if isinstance(item, str):
                    raise ValueError(
                        "ORDER BY position cannot reference *")
                key = item.name
            resolved.append((key, asc))
        q.order_by = resolved

    # GROUP BY <position> / <expression>: positions resolve against the
    # select list; expression keys materialize as device columns before
    # grouping — under the select item's name when the same expression
    # appears there (``SELECT cast(p as int) pi ... GROUP BY cast(p as
    # int)`` groups as ``pi``), else under a temp name the projection
    # drops. Matched select items become plain Col refs so they are not
    # re-evaluated against the aggregated frame.
    if q.group_by and any(not isinstance(k, str) for k in q.group_by):
        keys = []
        for j, key in enumerate(q.group_by):
            if isinstance(key, str):
                keys.append(key)
                continue
            if isinstance(key, int):
                if not 1 <= key <= len(q.items):
                    raise ValueError(f"GROUP BY position {key} is not in "
                                     f"the select list (1..{len(q.items)})")
                item = q.items[key - 1]
                if isinstance(item, str):
                    raise ValueError("GROUP BY position cannot reference *")
                if isinstance(item, AggExpr):
                    raise ValueError(
                        "GROUP BY position cannot reference an aggregate")
                if isinstance(item, E.Col):
                    keys.append(item.name)
                    continue
                name = item.name
                frame = frame.with_column(name, item)
                q.items[key - 1] = E.Col(name)
                keys.append(name)
                continue
            matched = next(
                (idx for idx, it in enumerate(q.items)
                 if not isinstance(it, (str, AggExpr))
                 and (str(it) == str(key)
                      or (isinstance(it, E.Alias)
                          and str(it.child) == str(key)))), None)
            if matched is not None:
                name = q.items[matched].name
                frame = frame.with_column(name, q.items[matched])
                q.items[matched] = E.Col(name)
            else:
                name = f"__grp_{j}"
                frame = frame.with_column(name, key)
            keys.append(name)
        q.group_by = keys

    aggs = [it for it in q.items if isinstance(it, AggExpr)]
    post_items = [it for it in q.items if isinstance(it, PostAggItem)]
    # Component aggregates a post-agg expression needs, minus those the
    # select list already computes (dedup by output-column name).
    known_names = {a.name for a in aggs}
    component_aggs = []
    for it in post_items:
        for a in it.aggs:
            if a.name not in known_names:
                known_names.add(a.name)
                component_aggs.append(a)
    having = q.having
    if (having is not None and not q.group_by
            and not (aggs or post_items)):
        # Spark allows HAVING without GROUP BY only over an aggregate
        # projection (it filters the single global-aggregate row).
        raise ValueError("HAVING requires GROUP BY or an aggregate "
                         "select list")
    if aggs or post_items or q.group_by:
        if any(isinstance(it, str) and it == "*" for it in q.items):
            raise ValueError(
                "SELECT * cannot be combined with aggregates/GROUP BY; "
                "list the grouped columns explicitly")
        non_aggs = [it for it in q.items
                    if not isinstance(it, (AggExpr, PostAggItem, str))]
        for it in non_aggs:
            if not isinstance(it, E.Col) or (q.group_by
                                             and it.name not in q.group_by):
                raise ValueError(
                    f"non-aggregate select item {it} must be a GROUP BY key")
        if q.group_by:
            extra_aggs: list = []
            if having is not None:
                having = _rewrite_having(having, extra_aggs)
            # ORDER BY over aggregates (``ORDER BY count(*) DESC``):
            # rewrite agg calls into references to aggregated output
            # columns, computing any that aren't already in SELECT and
            # dropping them again after the final sort.
            order_by = []
            for key, asc in q.order_by:
                if isinstance(key, E.SortOrder):
                    key = E.SortOrder(_rewrite_having(key.child, extra_aggs),
                                      key.ascending, key.nulls_first)
                elif not isinstance(key, str):
                    key = _rewrite_having(key, extra_aggs)
                    if isinstance(key, E.Col):
                        key = key.name
                order_by.append((key, asc))
            q.order_by = order_by
            known = {a.name for a in aggs} \
                | {a.name for a in component_aggs}
            seen: set = set()
            extra_aggs = [a for a in extra_aggs
                          if a.name not in known and a.name not in seen
                          and not seen.add(a.name)]
            grouped = (frame.rollup(*q.group_by)
                       if q.group_mode == "rollup"
                       else frame.cube(*q.group_by)
                       if q.group_mode == "cube"
                       else frame.group_by(*q.group_by))
            frame = grouped.agg(*aggs, *component_aggs, *extra_aggs)
            if having is not None:
                frame = frame.filter(having)
            for it in post_items:
                frame = frame.with_column(it.name, it.expr)
            keep = [it.name for it in q.items
                    if isinstance(it, (E.Col, AggExpr, PostAggItem))]
            # Columns the final sort still needs (extra aggs referenced
            # by ORDER BY) survive the projection and drop after sorting.
            order_needs: set = set()
            for key, _ in q.order_by:
                if isinstance(key, str):
                    order_needs.add(key)
                else:
                    _referenced_cols(key, order_needs)
            drop_after = [c for c in order_needs
                          if c in frame.columns and c not in keep]
            frame = frame.select(*keep, *drop_after)
            q.drop_after_sort = drop_after
        else:
            if non_aggs:
                raise ValueError("plain columns in an aggregate query "
                                 "require GROUP BY")
            # Global aggregate: HAVING filters the single result row
            # (Spark's groupless HAVING), using component aggregates
            # that are computed then dropped by the final projection.
            having_extras: list = []
            if having is not None:
                having = _rewrite_having(having, having_extras)
                names = {a.name for a in aggs} \
                    | {a.name for a in component_aggs}
                having_extras = [a for a in having_extras
                                 if a.name not in names]
            frame = frame.agg(*aggs, *component_aggs, *having_extras)
            if having is not None:
                frame = frame.filter(having)
            if post_items or having_extras or component_aggs:
                for it in post_items:
                    frame = frame.with_column(it.name, it.expr)
                frame = frame.select(*[it.name for it in q.items])
    else:
        # NB: Expr overloads ==, so compare with identity-safe checks, never
        # `items == ["*"]` (a single-Expr list would compare truthy).
        if (len(q.items) > 1
                and any(isinstance(it, str) and it == "*" for it in q.items)):
            # ``SELECT *, expr`` — expand the star against the (joined,
            # filtered) source columns in place
            expanded: list = []
            for it in q.items:
                if isinstance(it, str) and it == "*":
                    expanded.extend(E.Col(c) for c in frame.columns)
                else:
                    expanded.append(it)
            q2 = Query(expanded, q.view, None, [], q.order_by, q.limit,
                       distinct=q.distinct)
            q2.offset = q.offset
            q = q2
        star = (len(q.items) == 1 and isinstance(q.items[0], str)
                and q.items[0] == "*")
        if q.order_by and not star:
            # SQL sorts before projecting, so ORDER BY may reference columns
            # the SELECT drops — sort first when the source has them all
            # (otherwise fall through: some key must be a SELECT alias).
            # Expression keys materialize as temp columns on the source
            # frame here (they reference source columns); the projection
            # below drops the temps for free.
            keys = []
            for i, (key, asc) in enumerate(q.order_by):
                if isinstance(key, E.SortOrder):
                    if not isinstance(key.child, E.Col):
                        tmp = f"__ord_{i}"
                        frame = frame.with_column(tmp, key.child)
                        key = E.SortOrder(E.Col(tmp), key.ascending,
                                          key.nulls_first)
                elif not isinstance(key, str):
                    tmp = f"__ord_{i}"
                    frame = frame.with_column(tmp, key)
                    key = tmp
                keys.append((key, asc))
            q.order_by = keys
            if all((c if isinstance(c, str) else c.name) in frame.columns
                   for c, _ in q.order_by):
                frame = frame.sort(*[c for c, _ in q.order_by],
                                   ascending=[a for _, a in q.order_by])
                q2 = Query(q.items, q.view, None, [], [], q.limit,
                           distinct=q.distinct)
                q2.offset = q.offset
                q = q2
        if not star:
            keep_for_sort: list = []
            if q.order_by:
                # Post-projection sort (a key is a SELECT alias): any
                # other key column the projection would drop — the
                # __ord_N temps materialized above, or a plain source
                # column — must survive the projection and be dropped
                # after _sort_with_exprs (same drop_after_sort protocol
                # as the aggregate path; ADVICE.md #1).
                produced = {it.name for it in q.items
                            if not isinstance(it, str)}
                needed: set = set()
                for key, _ in q.order_by:
                    if isinstance(key, str):
                        needed.add(key)
                    else:
                        _referenced_cols(key, needed)
                keep_for_sort = [c for c in frame.columns
                                 if c in needed and c not in produced]
                if keep_for_sort and q.distinct:
                    raise ValueError(
                        "SELECT DISTINCT: ORDER BY keys must appear in "
                        "the select list (sorting by "
                        f"{sorted(needed - produced)} would change the "
                        "distinct rows)")
            frame = frame.select(*q.items, *keep_for_sort)
            if keep_for_sort:
                q.drop_after_sort = keep_for_sort

    if q.distinct:
        # SELECT DISTINCT dedups the projected rows (mask-based: keeps the
        # first occurrence, so any pre-projection sort order is preserved).
        frame = frame.distinct()
    if q.order_by:
        frame = _sort_with_exprs(frame, q.order_by,
                                 getattr(q, "drop_after_sort", ()))
    elif getattr(q, "drop_after_sort", ()):
        frame = frame.drop(*q.drop_after_sort)
    if q.offset:
        frame = frame.offset(q.offset)
    if q.limit is not None:
        frame = frame.limit(q.limit)
    return frame
