"""Cost-based plan optimizer — statstore-driven rewrites over the parsed
``Query`` surface (ROADMAP item 4).

The engine has carried every sensor an optimizer needs for three PRs —
per-operator runtime profiles (PR 5), static peak-bytes bounds (PR 9),
and a persisted per-plan-key statistics store with observed
selectivities and compile-cost digests (PR 12) — but until now every
query executed its literal parse shape. This module closes the loop:
``optimize`` transforms a parsed :class:`~.parser.Query` BEFORE
execution, using only static catalog metadata (column lists, slot
counts — never a device read, never a compile) plus the statstore's
persisted history, so the same walk is safe for plain ``EXPLAIN``'s
zero-execution before/after diff.

Rewrite catalog (each annotated in EXPLAIN's ``== Rewrites ==`` section):

* **predicate pushdown** (level >= 1) — WHERE conjuncts that reference
  exactly one relation of a join move into a derived-table wrapper
  around that relation, so the join's host-side hash plan sees only
  surviving rows and the filter still lowers as one fused device
  program on the scan. Join-type gates keep null-extension semantics
  exact: base-side pushes require every join to preserve right-side
  row identity (inner/left/semi/anti/cross), a joined relation accepts
  pushes only under inner/cross with no later right/outer join.
  Emission order is untouched (filtering a side removes exactly the
  pairs the post-join filter would have removed, in place).

* **projection pushdown / column pruning** (level >= 1) — relations of
  a join keep only the columns the query references (+ every join
  key), so the join materializes (one device gather per column!) only
  what the query can observe. Names that collide across sides keep
  their columns everywhere, preserving the ``_right``-suffix structure
  exactly; any expression outside the statically-analyzable subset
  (subqueries, window functions) disables pruning for the query.

* **join reordering** (level >= 2) — consecutive INNER joins re-order
  smallest-estimated-first (history-informed ``est_rows``: statstore
  selectivity of the pushed filter stack x static slot count, falling
  back to static slots when history is cold). Gated to plans where the
  row MULTISET is provably preserved and no operator observes input
  order (no LIMIT/OFFSET, unique non-key column names); SQL imposes no
  row order without ORDER BY, but level 2 is opt-in because the
  physical emission order may legally change.

* **build-side selection** (level >= 1) — an inner join whose
  accumulated left side is estimated well under half the right side
  carries a ``build=left`` hint: ``Frame.join`` then sorts the SMALL
  side and re-canonicalizes the pair order, which is bit-identical to
  the default plan's emission order (inner-join emission is exactly
  the (left,row)-lexicographic pair order).

Two further cost decisions live at the lowering layer (the plan shape
is not known until flush time): fused-stage boundary splitting and
history-informed memory chunking in ``ops/compiler.run_pipeline``, and
the grouped engine's dense-lowering skip in ``ops/segments.grouped_agg``
— see those modules; they share this module's conf gates.

Degradation: the ``optimizer`` fault site (``utils.faults``) injects at
the top of :func:`optimize_or_fallback`; ANY optimizer failure —
injected or real — degrades to the unrewritten plan with a
``recovery.fallback`` event (rung ``unrewritten``) and an
``optimizer.fallback`` counter. The optimizer can slow a query, never
change or lose it.

Conf: ``spark.optimizer.enabled`` (default true) /
``spark.optimizer.level`` (default 1; 2 adds join reordering and
stage-boundary splitting). Disabled mode costs one flag read per query.
"""

from __future__ import annotations

import logging
from typing import Optional

from ..config import config
from ..ops import expressions as E
from ..utils.profiling import counters

logger = logging.getLogger("sparkdq4ml_tpu.sql.optimizer")

#: Join types under which filtering the ACCUMULATED LEFT side before the
#: join equals filtering after it: the join must never null-extend left
#: columns (right/outer joins append unmatched right rows whose left
#: columns are NaN — a pushed predicate would keep them, the post-join
#: filter would drop them).
_SAFE_LEFT = ("inner", "left", "left_semi", "left_anti", "cross")

#: Build-side hysteresis: hint ``build=left`` only when the accumulated
#: left estimate is under half the right side — the canonicalizing pair
#: sort costs O(P log P), so a marginal size gap must not flip the plan.
_BUILD_RATIO = 2


class Rewrite:
    """One applied rewrite — the EXPLAIN ``== Rewrites ==`` line."""

    __slots__ = ("rule", "detail")

    def __init__(self, rule: str, detail: str):
        self.rule = rule
        self.detail = detail

    def __str__(self):
        return f"{self.rule}: {self.detail}"


def enabled() -> bool:
    return bool(config.optimizer_enabled)


# ---------------------------------------------------------------------------
# Static expression analysis (whitelist walk — anything outside the
# known subset disables the rewrite that needed it, never guesses)
# ---------------------------------------------------------------------------

def _walk(expr, refs: set, shadow: frozenset = frozenset()) -> bool:
    """Collect every column name ``expr`` references into ``refs``;
    returns False when the tree contains any node outside the
    statically-analyzable subset (subquery placeholders, window
    expressions, generators) — callers must then skip the rewrite."""
    if isinstance(expr, E.Col):
        if expr.name not in shadow:
            refs.add(expr.name)
        return True
    if isinstance(expr, E.Lit):
        return True
    if isinstance(expr, E.Alias):
        return _walk(expr.child, refs, shadow)
    if isinstance(expr, E.BinOp):
        return (_walk(expr.left, refs, shadow)
                and _walk(expr.right, refs, shadow))
    if isinstance(expr, E.UnaryOp):
        return _walk(expr.child, refs, shadow)
    if isinstance(expr, E.Cast):
        return _walk(expr.child, refs, shadow)
    if isinstance(expr, E.InList):
        return (_walk(expr.child, refs, shadow)
                and all(_walk(v, refs, shadow) for v in expr.values))
    if isinstance(expr, E.CaseWhen):
        return (all(_walk(c, refs, shadow) and _walk(v, refs, shadow)
                    for c, v in expr.branches)
                and (expr.otherwise_expr is None
                     or _walk(expr.otherwise_expr, refs, shadow)))
    if isinstance(expr, E.StringMatch):
        return _walk(expr.child, refs, shadow)
    if isinstance(expr, (E.UdfCall, E.Func)):
        return all(_walk(a, refs, shadow) for a in expr.args)
    if isinstance(expr, E.SortOrder):
        return _walk(expr.child, refs, shadow)
    if isinstance(expr, E.HigherOrder):
        # lambda params shadow outer columns inside the body
        inner = shadow | frozenset(expr.lam.params)
        ok = _walk(expr.source, refs, shadow) and _walk(
            expr.lam.body, refs, inner)
        if expr.init is not None:
            ok = ok and _walk(expr.init, refs, shadow)
        if expr.finish is not None:
            ok = ok and _walk(expr.finish.body, refs,
                              shadow | frozenset(expr.finish.params))
        return ok
    # ScalarSubquery / SubqueryIn / SubqueryExists / _AggRef / window
    # expressions / anything future: not statically analyzable here
    return False


def _agg_refs(agg, refs: set) -> bool:
    from ..frame.aggregates import AggExpr, AggOfExpr

    if isinstance(agg, AggOfExpr):
        return _walk(agg.expr, refs)
    if isinstance(agg, AggExpr):
        if agg.column is not None:
            refs.add(agg.column)
        if agg.column2 is not None:
            refs.add(agg.column2)
        return True
    return False


def _item_refs(item, refs: set) -> bool:
    """Column references of one select item; False = not analyzable."""
    from ..frame.aggregates import AggExpr
    from .parser import PostAggItem

    if isinstance(item, str):
        return item != "*"
    if isinstance(item, PostAggItem):
        return (_walk(item.expr, refs)
                and all(_agg_refs(a, refs) for a in item.aggs))
    if isinstance(item, AggExpr):
        return _agg_refs(item, refs)
    if isinstance(item, E.Expr):
        return _walk(item, refs)
    return False


# ---------------------------------------------------------------------------
# Relation model
# ---------------------------------------------------------------------------

class _Rel:
    """One FROM/JOIN relation: ``idx`` -1 = the base relation, >= 0 =
    ``q.joins[idx]``. ``bind`` is the scope name qualified refs resolve
    against (the alias, else the view name)."""

    __slots__ = ("idx", "view", "bind", "cols", "how", "keys", "pushed",
                 "keep")

    def __init__(self, idx, view, bind, cols, how=None, keys=()):
        self.idx = idx
        self.view = view
        self.bind = bind
        self.cols = cols              # list[str] | None (unknown)
        self.how = how
        self.keys = list(keys)
        self.pushed: list = []        # conjuncts moved into this scan
        self.keep: Optional[list] = None   # pruned column list


def _view_columns(view, cat) -> Optional[list]:
    """Static column list of a plain-view relation (None for derived
    tables and unregistered names). Uses ``Frame.columns`` — pending
    names included, NO flush, no device read."""
    if not isinstance(view, str):
        return None
    try:
        return list(cat.lookup(view).columns)
    except Exception:
        return None


def _relations(q, cat) -> Optional[list]:
    """The query's relation table, base first; None when the shape is
    outside the rewriter's reach (FROM-less, duplicate binding names)."""
    from .parser import DerivedTable

    if q.view is None:
        return None
    rels: list[_Rel] = []
    if isinstance(q.view, str):
        bind = (q.view_alias or q.view).lower()
        rels.append(_Rel(-1, q.view, bind, _view_columns(q.view, cat)))
    elif isinstance(q.view, DerivedTable):
        bind = (q.view.alias or "").lower()
        rels.append(_Rel(-1, q.view, bind, None))
    else:
        return None
    for i, (view, how, keys, alias) in enumerate(q.joins):
        bind = (alias or (view if isinstance(view, str) else "")).lower()
        rels.append(_Rel(i, view, bind,
                         _view_columns(view, cat), how, keys))
    binds = [r.bind for r in rels if r.bind]
    if len(binds) != len(set(binds)):
        return None                   # ambiguous scope: stay literal
    return rels


def _resolve_ref(name: str, rels: list) -> Optional[_Rel]:
    """The relation a column reference binds to, mirroring the
    executor's resolution: a literal column of that (dotted) name wins
    first, then ``alias.col`` against the relation scope, then the
    first relation carrying the plain name. None = unresolvable (an
    aggregate-output or select-alias reference, or an unknown alias)."""
    if "(" in name:
        return None
    for r in rels:
        if r.cols is not None and name in r.cols:
            return r
    if "." in name:
        alias = name.partition(".")[0].lower()
        for r in rels:
            if r.bind == alias:
                return r
    return None


def _strip_qualifier(expr, rel: _Rel):
    """Rewrite ``alias.col`` references bound to ``rel`` into plain
    ``col`` names valid inside the relation's own scan scope."""
    from .parser import _map_cols

    cols = rel.cols or ()

    def fn(name: str) -> str:
        if "." not in name or "(" in name or name in cols:
            return name
        alias, _, col = name.partition(".")
        return col if alias.lower() == rel.bind else name

    return _map_cols(expr, fn)


def _pushable(rel: _Rel, rels: list) -> bool:
    """Whether a single-relation conjunct may move into ``rel``'s scan
    (see module docstring for the join-type gates)."""
    if rel.cols is None or not isinstance(rel.view, str):
        return False
    joins = [r for r in rels if r.idx >= 0]
    if rel.idx < 0:
        return all(r.how in _SAFE_LEFT for r in joins)
    if rel.how not in ("inner", "cross"):
        return False
    return all(r.how in _SAFE_LEFT for r in joins if r.idx > rel.idx)


# ---------------------------------------------------------------------------
# Cost model (statstore-informed, static fallback)
# ---------------------------------------------------------------------------

def _rel_sel_key(rel: _Rel, cat) -> Optional[str]:
    """The filter-structural statstore key for a relation's pushed
    filter stack — the address both the selectivity estimate and the
    flop-cost term read. None when nothing was pushed."""
    if not rel.pushed:
        return None
    from .parser import Query, _conjoin, _filter_history_key

    probe = Query(["*"], rel.view,
                  _conjoin([_strip_qualifier(c, rel) for c in rel.pushed]))
    return _filter_history_key(probe, cat)


def _est_rel_rows(rel: _Rel, cat) -> Optional[int]:
    """History-informed output-row estimate for one relation AFTER its
    pushed filters: the statstore selectivity recorded for the same
    filter structure (the key EXPLAIN's ``est_rows`` uses) x the view's
    static slot count; cold history falls back to static slots. Zero
    execution: a catalog lookup + one ``_linearize`` walk."""
    if rel.cols is None or not isinstance(rel.view, str):
        return None
    try:
        slots = int(cat.lookup(rel.view).num_slots)
    except Exception:
        return None
    if not rel.pushed:
        return slots
    from ..utils import statstore as _stats

    skey = _rel_sel_key(rel, cat)
    sel = _stats.STORE.selectivity(skey) if skey is not None else None
    if sel is None:
        return slots
    return int(round(sel * slots))


def _est_rel_flops(rel: _Rel, cat) -> Optional[float]:
    """The PR-15 AOT cost profile's flop count for the relation's pushed
    filter-stack program (largest recorded extraction at the same
    filter-structural key the selectivity estimate uses). None when cold
    or nothing was pushed — the reorder's flop term then contributes
    zero and ranking degrades to rows alone, exactly the pre-flop
    behavior."""
    if rel.cols is None or not isinstance(rel.view, str):
        return None
    from ..utils import statstore as _stats

    return _stats.STORE.flops_for_selectivity(_rel_sel_key(rel, cat))


# ---------------------------------------------------------------------------
# The rewrite passes
# ---------------------------------------------------------------------------

def _split_where(q, rels: list, rewrites: list) -> Optional[object]:
    """Predicate pushdown: assign single-relation conjuncts to their
    relation's ``pushed`` list; returns the residual WHERE."""
    from .parser import _conjoin, _conjuncts

    if q.where is None or not q.joins:
        return q.where
    keep = []
    pushed_any = False
    for c in _conjuncts(q.where):
        refs: set = set()
        if not _walk(c, refs) or not refs:
            keep.append(c)
            continue
        targets = [_resolve_ref(name, rels) for name in refs]
        if any(t is None for t in targets) \
                or len({id(t) for t in targets}) != 1:
            keep.append(c)
            continue
        rel = targets[0]
        if not _pushable(rel, rels):
            keep.append(c)
            continue
        rel.pushed.append(c)
        pushed_any = True
        rewrites.append(Rewrite(
            "pushdown", f"{c} -> Scan[{rel.view}]"))
    return _conjoin(keep) if pushed_any else q.where


def _needed_columns(q, rels: list, residual_where) -> bool:
    """Column pruning analysis: fill each relation's ``keep`` list with
    the columns the query can observe (+ every join key). Returns False
    — and leaves every ``keep`` None — when any referenced expression
    is outside the analyzable subset or any reference is ambiguous."""
    refs: set = set()
    for it in q.items:
        if isinstance(it, str) and it == "*":
            return False
        if not _item_refs(it, refs):
            return False
    for part in (residual_where, q.having):
        if part is not None and not _walk(part, refs):
            return False
    for key in q.group_by:
        if isinstance(key, str):
            refs.add(key)
        elif not isinstance(key, int) and not _walk(key, refs):
            return False
    for key, _asc in q.order_by:
        if isinstance(key, str):
            refs.add(key)
        elif not isinstance(key, int) and not _walk(key, refs):
            return False
    # pushed conjuncts filter INSIDE the wrapped scan, before its
    # projection — their references need no keep slot; join keys do.
    all_keys = {k for r in rels for k in r.keys}
    needed = {r.idx: set() for r in rels}
    for name in refs:
        if "(" in name:
            continue                  # aggregate-output reference
        literal_hit = any(r.cols is not None and name in r.cols
                          for r in rels)
        if "." in name and not literal_hit:
            alias, _, col = name.partition(".")
            rel = next((r for r in rels if r.bind == alias.lower()), None)
            if rel is None:
                return False          # unknown alias: stay literal
            # keep the column on EVERY relation carrying it, not just
            # the bound one: pruning a collision twin would un-fire the
            # ``_right`` rename and change the output column NAME
            for r in rels:
                if r.cols is not None and col in r.cols:
                    needed[r.idx].add(col)
            needed[rel.idx].add(col)
            continue
        base = name
        if name.endswith("_right") and not literal_hit:
            base = name[: -len("_right")]
        for r in rels:
            if r.cols is not None and base in r.cols:
                needed[r.idx].add(base)
        # an unmatched plain name is a select-alias or pending-column
        # reference — not a scan column, nothing to keep
    for r in rels:
        if r.cols is None or not isinstance(r.view, str):
            continue
        keep = [c for c in r.cols if c in needed[r.idx] or c in all_keys]
        if keep and len(keep) < len(r.cols):
            r.keep = keep
    return True


#: Relative weight of the flop-cost term in the join-reorder ranking:
#: with profiles present, a relation's rank is its row estimate scaled
#: by up to 1 + _FLOP_WEIGHT depending on how its filter-program flops
#: compare to the heaviest candidate's. Row estimates stay dominant —
#: the flop term only breaks near-ties toward the cheaper scan.
_FLOP_WEIGHT = 0.5


def _maybe_reorder(q, rels: list, ests: dict, flops: dict,
                   rewrites: list) -> Optional[list]:
    """Join reordering (level >= 2): greedy smallest-cost-first over
    INNER joins, honoring key availability — cost is the row estimate
    scaled by the relation's recorded filter-program flops (the PR-15
    AOT cost profiles) when any candidate has one, rows alone otherwise.
    Returns the new join order (indices into ``q.joins``) or None. Gated
    to shapes where the output row multiset is provably preserved and
    nothing downstream observes physical order (no LIMIT/OFFSET) and the
    ``_right``-suffix structure cannot change (non-key column names
    unique across relations)."""
    joins = [r for r in rels if r.idx >= 0]
    if len(joins) < 2 or q.limit is not None or getattr(q, "offset", 0):
        return None
    if any(r.how != "inner" or not r.keys or r.cols is None
           or not isinstance(r.view, str) for r in joins):
        return None
    base = rels[0]
    if base.cols is None:
        return None
    all_keys = {k for r in joins for k in r.keys}
    seen: dict[str, int] = {}
    for r in rels:
        for c in r.cols:
            if c in all_keys:
                continue
            if c in seen:
                return None           # cross-relation collision
            seen[c] = r.idx
    if any(ests.get(r.idx) is None for r in joins):
        return None
    fmax = max((flops.get(r.idx) or 0.0) for r in joins)

    def _rank(r: _Rel) -> float:
        rows = float(ests[r.idx])
        if fmax <= 0.0:
            return rows
        return rows * (1.0 + _FLOP_WEIGHT * (flops.get(r.idx) or 0.0)
                       / fmax)

    available = set(base.cols)
    order: list[int] = []
    remaining = list(joins)
    while remaining:
        cands = [r for r in remaining if set(r.keys) <= available]
        if not cands:
            return None
        pick = min(cands, key=_rank)
        order.append(pick.idx)
        available |= set(pick.cols)
        remaining.remove(pick)
    if order == [r.idx for r in joins]:
        return None
    rewrites.append(Rewrite(
        "join-reorder",
        ", ".join(f"{rels[i + 1].view}~{ests[i]}r" for i in order)
        + (" (smallest rows x flop cost first)" if fmax > 0.0
           else " (smallest estimate first)")))
    return order


def _wrap(rel: _Rel):
    """Materialize a relation's pushed filters / pruned projection as a
    derived-table wrapper (an existing, fully-tested executor path)."""
    from .parser import DerivedTable, Query, _conjoin

    if not rel.pushed and rel.keep is None:
        return None
    items = ([E.Col(c) for c in rel.keep]
             if rel.keep is not None else ["*"])
    where = (_conjoin([_strip_qualifier(c, rel) for c in rel.pushed])
             if rel.pushed else None)
    return DerivedTable(Query(items, rel.view, where), rel.bind)


def _clone(q):
    """Shallow Query copy — the rewritten plan must never mutate the
    parse result (EXPLAIN renders the original as the 'before' tree)."""
    from .parser import Query

    q2 = Query(list(q.items), q.view, q.where, list(q.group_by),
               list(q.order_by), q.limit, list(q.joins),
               distinct=q.distinct, having=q.having,
               unions=list(q.unions))
    q2.group_mode = q.group_mode
    q2.view_alias = q.view_alias
    q2.offset = getattr(q, "offset", 0)
    q2.ctes = list(getattr(q, "ctes", ()))
    return q2


def _optimize_single(q, cat, rewrites: list):
    """Optimize ONE SELECT (no set-op handling); returns a rewritten
    shallow copy, or ``q`` itself when nothing applies."""
    from .parser import DerivedTable

    rels = _relations(q, cat)
    # recurse into derived tables first (their inner queries are full
    # SELECTs); CTE bodies are optimized by the executor at registration
    new_view = q.view
    if isinstance(q.view, DerivedTable):
        inner = _optimize_single(q.view.query, cat, rewrites)
        if inner is not q.view.query:
            new_view = DerivedTable(inner, q.view.alias)
    new_joins = list(q.joins)
    for i, (view, how, keys, alias) in enumerate(new_joins):
        if isinstance(view, DerivedTable):
            inner = _optimize_single(view.query, cat, rewrites)
            if inner is not view.query:
                new_joins[i] = (DerivedTable(inner, view.alias), how,
                                keys, alias)
    changed = new_view is not q.view or new_joins != list(q.joins)

    where = q.where
    order = None
    hints: list = []
    join_ests: list = []
    if rels is not None:
        n_rw = len(rewrites)
        where = _split_where(q, rels, rewrites)
        if q.joins:
            # pruning pays at the join boundary (one device gather per
            # materialized column); a single-relation query's unused
            # columns are never touched by the flush anyway
            _needed_columns(q, rels, where)
        ests = {r.idx: _est_rel_rows(r, cat) for r in rels}
        if int(config.optimizer_level) >= 2:
            flops = {r.idx: _est_rel_flops(r, cat) for r in rels}
            order = _maybe_reorder(q, rels, ests, flops, rewrites)
        # build-side hints over the FINAL join order; the per-join
        # (left, right) estimate pairs ride along as ``join_est`` — the
        # drift baseline the adaptive hooks (sql/adaptive.py) compare
        # observed counts against at run time
        joined = ([next(r for r in rels if r.idx == i) for i in order]
                  if order is not None
                  else [r for r in rels if r.idx >= 0])
        left_est = ests.get(-1)
        for r in joined:
            hint = None
            right_est = ests.get(r.idx)
            join_ests.append((left_est, right_est))
            if (r.how == "inner" and r.keys and left_est is not None
                    and right_est is not None
                    and left_est * _BUILD_RATIO <= right_est):
                hint = "left"
                rewrites.append(Rewrite(
                    "build-side",
                    f"Join[{r.view}] build=left "
                    f"(est {left_est} vs {right_est} rows)"))
            hints.append(hint)
            if left_est is not None and right_est is not None:
                left_est = max(left_est, right_est)
            else:
                left_est = None
        for r in rels:
            if r.keep is not None:
                rewrites.append(Rewrite(
                    "prune",
                    f"Scan[{r.view}] keeps {len(r.keep)}/"
                    f"{len(r.cols)} cols ({', '.join(r.keep)})"))
        # apply wrappers in the final order
        base_wrap = _wrap(rels[0])
        if base_wrap is not None:
            new_view = base_wrap
        joins_out = []
        for r in joined:
            # new_joins, not q.joins: a joined derived table's entry may
            # already hold its recursively optimized inner query
            view, how, keys, alias = new_joins[r.idx]
            w = _wrap(r)
            if w is not None:
                joins_out.append((w, how, keys, r.bind or alias))
            else:
                joins_out.append((view, how, keys, alias))
        if joins_out:
            new_joins = joins_out
        changed = (changed or len(rewrites) > n_rw
                   or where is not q.where)
    has_ests = any(e is not None
                   for pair in join_ests for e in pair)
    if not changed:
        if has_ests:
            # advisory only — never affects planning or EXPLAIN, just
            # gives the runtime hooks a drift baseline
            q.join_est = join_ests
        return q
    q2 = _clone(q)
    q2.view = new_view
    q2.where = where
    q2.joins = new_joins
    if isinstance(new_view, DerivedTable) and new_view is not q.view:
        q2.view_alias = None
    if any(hints):
        q2.join_build = hints
    if has_ests:
        q2.join_est = join_ests
    return q2


def optimize(q, cat):
    """Rewrite a parsed query (and its set-operation branches) for
    execution; returns ``(query, rewrites)``. Pure planning: static
    catalog metadata + statstore history, zero execution — callers
    wanting the degradation ladder use :func:`optimize_or_fallback`."""
    rewrites: list[Rewrite] = []
    q2 = _optimize_single(q, cat, rewrites)
    if q.unions:
        new_unions = []
        changed = False
        for op, sub in q.unions:
            sub2 = _optimize_single(sub, cat, rewrites)
            changed = changed or sub2 is not sub
            new_unions.append((op, sub2))
        if changed:
            if q2 is q:
                q2 = _clone(q)
            q2.unions = new_unions
    q2._optimized = True
    if rewrites:
        counters.increment("optimizer.rewrite", len(rewrites))
    return q2, rewrites


def optimize_or_fallback(q, cat):
    """The production entry: :func:`optimize` behind the ``optimizer``
    fault site and the unrewritten-plan degradation ladder. Returns
    ``(query, rewrites)`` — on ANY failure the original query and an
    empty rewrite list, with a recovery event; the optimizer can slow a
    query, never change or lose it."""
    if not config.optimizer_enabled or getattr(q, "_optimized", False):
        return q, []
    from ..utils import faults as _faults

    try:
        _faults.inject("optimizer")
        return optimize(q, cat)
    except Exception as e:
        from ..utils.recovery import RECOVERY_LOG

        counters.increment("optimizer.fallback")
        RECOVERY_LOG.record(
            "optimizer", "fallback", rung="unrewritten",
            cause=f"{type(e).__name__}: {e}",
            detail="query runs its literal parse shape")
        logger.debug("optimizer degraded to the unrewritten plan",
                     exc_info=True)
        return q, []
