"""Adaptive query execution — mid-query re-planning from observed stats.

ROADMAP item 4's second half (the first half is ``sql/optimizer.py``):
the cost-based optimizer picks a plan from *persisted* history, but
until now the plan chosen before execution was the plan executed to the
end — even when the first stage just proved its cardinality estimates
wrong. This module is the decision layer the stage-boundary hooks call
at every point where a running query already holds fresh evidence on
host (classic Spark-AQE territory, applied to this engine's
static-shape discipline):

* **build-side flip** (``Frame.join``) — the join's host plan knows the
  TRUE valid-row counts of both sides (``li``/``ri`` — host-known, zero
  extra syncs) before it builds anything. When either side drifted past
  ``spark.aqe.driftFactor`` from the optimizer's estimate, the build
  hint is re-decided from the observed counts. Bit-identical: both
  build directions re-canonicalize to the same emission order.
* **broadcast shuffle-skip** (``Frame.join``) — when drift fired and
  the observed build side fits ``spark.aqe.broadcastThreshold`` bytes,
  the hash-partition Exchange is skipped entirely and the single
  (broadcast-style) plan runs. Bit-identical by construction: the
  partitioned plan merges back into EXACTLY the unpartitioned plan's
  order, so not partitioning is the identity transform.
* **skew split** (``parallel/shard.partitioned_join_plan``) — a probe-
  side partition whose row count crosses ``spark.aqe.skewFactor`` x the
  mean splits into balanced chunks, each planned against the partition's
  full build side; the PR-13 stable left-index merge re-sorts the chunk
  plans into the exact global order (gated to join types whose
  unmatched-right detection is not cross-chunk).
* **downstream re-bucket** (``sql/parser._execute_single`` after the
  WHERE filter) — when the observed valid-row count lands a power-of-two
  bucket (``ops/compiler.bucket_size``) below the static slot count and
  past the drift factor, the surviving rows compact into the smaller
  bucket so every downstream stage (grouped lowering, device sort,
  distinct) runs with fewer padded slots — the arxiv 2206.14148 memory
  bound applied *during* the query; the static flush-byte bound is
  re-checked against the device budget at the boundary. Semantics-
  preserving by the masked-slot invariant (padded tails ride ``False``
  masks everywhere already).
* **grouped lowering choice** (``ops/segments.grouped_agg``) — when the
  recorded output-cardinality history says the group count exceeds the
  dense slot-table range, the doomed dense dispatch (and its extra host
  sync) is skipped for THIS query, not just after two recorded misses.

Every decision point runs behind :func:`guard` — the ``aqe`` fault site
(``device_error`` raises, ``stall`` is a due-test) degrades the
DECISION to the static plan with a ``recovery.fallback`` event (rung
``static``) and an ``aqe.fallback`` counter; results stay golden on
every rung because the static plan is always the fallback, never an
error. Re-planned remainders compile through the normal
``ProgramHandle``-registered caches (a re-bucketed stage is just a
smaller-bucket entry of the same registered cache, warm across queries
with the same drift signature).

EXPLAIN ANALYZE renders applied events as an ``== Adaptive ==`` section
(the :func:`capture` scope); ``aqe.replans``/``aqe.replans.<trigger>``
count them. ``spark.aqe.enabled=false`` reduces every hook to one conf
read and pins EXPLAIN output byte-identical to the static engine.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
from typing import Optional

import numpy as np

from ..config import config
from ..utils.profiling import counters

logger = logging.getLogger("sparkdq4ml_tpu.sql.adaptive")

__all__ = [
    "enabled", "guard", "drift", "record", "capture", "render",
    "rebucket_candidate", "maybe_rebucket", "row_nbytes",
    "BUILD_RATIO",
]

#: Build-side hysteresis, mirroring the static optimizer's
#: ``_BUILD_RATIO``: the observed-count re-decision must clear the same
#: bar the estimate-based hint did, or drift would flip marginal joins
#: back and forth between runs.
BUILD_RATIO = 2


class ReplanEvent:
    """One applied mid-query re-plan — the ``== Adaptive ==`` line."""

    __slots__ = ("trigger", "detail", "est_before", "est_after")

    def __init__(self, trigger: str, detail: str,
                 est_before: Optional[int], est_after: Optional[int]):
        self.trigger = trigger
        self.detail = detail
        self.est_before = est_before
        self.est_after = est_after

    def __str__(self):
        def fmt(v):
            return "-" if v is None else str(v)

        return (f"{self.trigger}: {self.detail} "
                f"(est_rows {fmt(self.est_before)} -> "
                f"{fmt(self.est_after)})")


#: EXPLAIN ANALYZE's capture scope: a list the execution under
#: :func:`capture` appends applied events into. Context-local so
#: concurrent serving queries never interleave sections.
_CAPTURE: contextvars.ContextVar = contextvars.ContextVar(
    "aqe_capture", default=None)


def enabled() -> bool:
    return bool(config.aqe_enabled)


def guard(decision: str) -> bool:
    """Fault-laddered admission of ONE re-plan decision point: returns
    True when the adaptive decision may proceed. The ``aqe`` fault site
    injects here — ``device_error`` raises, ``stall`` fires the due-test
    — and EITHER kind degrades this decision to the static plan (rung
    ``static``: the query finishes on the plan it already had, results
    golden) with an ``aqe.fallback`` counter. Never raises."""
    from ..utils import faults as _faults

    try:
        _faults.inject("aqe")
        if _faults.fired("aqe", "stall"):
            raise TimeoutError("injected stall at 'aqe'")
        return True
    except Exception as e:
        from ..utils.recovery import RECOVERY_LOG

        counters.increment("aqe.fallback")
        RECOVERY_LOG.record(
            "aqe", "fallback", rung="static",
            cause=f"{type(e).__name__}: {e}",
            detail=f"{decision} re-plan skipped; the static plan "
                   "finishes the query")
        logger.debug("aqe %s decision degraded to the static plan",
                     decision, exc_info=True)
        return False


def drift(est: Optional[int], observed: int) -> bool:
    """Whether ``observed`` crossed ``spark.aqe.driftFactor`` away from
    ``est`` in EITHER direction (an estimate can be wrong both ways; a
    too-small estimate flips build sides, a too-large one shrinks
    buckets). A cold estimate (None) never triggers — adaptivity needs
    an expectation to drift FROM."""
    if est is None:
        return False
    f = max(float(config.aqe_drift_factor), 1.0)
    a = max(int(observed), 1)
    b = max(int(est), 1)
    return a >= b * f or b >= a * f


def record(trigger: str, detail: str, est_before: Optional[int],
           est_after: Optional[int]) -> None:
    """Count one APPLIED re-plan and surface it: ``aqe.replans`` (+ the
    per-trigger mirror), the active span's ``aqe`` annotation, and the
    EXPLAIN ANALYZE capture scope when one is open."""
    counters.increment("aqe.replans")
    counters.increment(f"aqe.replans.{trigger}")
    try:
        from ..utils import observability as _obs

        _obs.current_span().set(aqe=trigger)
    except Exception:
        pass
    events = _CAPTURE.get()
    if events is not None:
        events.append(ReplanEvent(trigger, detail, est_before, est_after))


@contextlib.contextmanager
def capture():
    """Scope under which applied re-plan events collect into the yielded
    list — EXPLAIN ANALYZE's ``== Adaptive ==`` source."""
    events: list = []
    token = _CAPTURE.set(events)
    try:
        yield events
    finally:
        _CAPTURE.reset(token)


def render(events) -> list[str]:
    """The ``== Adaptive ==`` body lines (header is the caller's)."""
    return [str(e) for e in events]


# ---------------------------------------------------------------------------
# Byte model (host metadata only — never a device read)
# ---------------------------------------------------------------------------

def row_nbytes(frame) -> int:
    """Per-row resident-byte width of a frame: column itemsizes (2-D
    columns count their row width) + the mask byte; host/object columns
    count one pointer. Shape metadata only — the broadcast decision must
    never sync."""
    total = 1    # bool mask
    for name in frame.columns:
        arr = frame._data[name]
        if isinstance(arr, np.ndarray) and arr.dtype == object:
            total += 8
        else:
            width = arr.shape[1] if getattr(arr, "ndim", 1) == 2 else 1
            total += width * np.dtype(arr.dtype).itemsize
    return total


# ---------------------------------------------------------------------------
# Downstream re-bucketing (the stage-boundary memory re-plan)
# ---------------------------------------------------------------------------

def rebucket_candidate(est: Optional[int], slots: int) -> bool:
    """Cheap pre-check (no sync): history estimates enough shrink that
    observing the true count could pay — the estimate drifted below the
    slot count AND lands a strictly smaller power-of-two bucket."""
    if est is None or slots <= 0:
        return False
    from ..ops.compiler import bucket_size

    if not drift(est, slots):
        return False
    return bucket_size(max(int(est), 1)) < bucket_size(slots)


def maybe_rebucket(frame, est: Optional[int]):
    """Re-bucket a just-filtered frame to its OBSERVED valid-row count
    when the static slot count drifted past ``spark.aqe.driftFactor``
    above it and a strictly smaller power-of-two bucket results: the
    surviving rows compact (device ``take`` in mask order — row order
    preserved exactly) into an all-valid frame, so every downstream
    stage runs with fewer padded slots and its static flush-byte bound
    (re-checked here against the device budget, arxiv 2206.14148)
    shrinks to what the data actually needs.

    Semantics-preserving by the masked-slot invariant: masked rows are
    invisible to every consumer already, so dropping their slots cannot
    change any downstream result. Sharded frames pass through untouched
    (their layout owns slot placement). Costs ONE counted host sync —
    paid only after :func:`rebucket_candidate` said the shrink is
    plausible. Returns the (possibly new) frame."""
    from ..frame.frame import Frame
    from ..ops.compiler import bucket_size

    slots = frame.num_slots
    if getattr(frame, "_shard", None) is not None or slots <= 0:
        return frame
    if not rebucket_candidate(est, slots):
        return frame
    if not guard("re-bucket"):
        return frame
    host_mask = frame._host_mask()        # counted device->host pull
    keep = np.nonzero(host_mask)[0]
    observed = int(keep.size)
    new_bucket = bucket_size(max(observed, 1))
    if new_bucket >= bucket_size(slots) or not drift(observed, slots):
        return frame                      # history lied small: keep plan
    import jax.numpy as jnp

    from ..ops.compiler import flush_budget

    per_row = row_nbytes(frame)
    budget = flush_budget()
    if budget is not None and new_bucket * per_row > budget:
        # the shrunk stage STILL exceeds the device budget — the
        # compiler's row-chunked ladder owns that regime; re-bucketing
        # on top would just add a compaction gather
        return frame
    keep_dev = jnp.asarray(keep)
    data = {}
    for name in frame.columns:
        arr = frame._data[name]
        if isinstance(arr, np.ndarray) and arr.dtype == object:
            data[name] = arr[keep]
        else:
            data[name] = jnp.take(jnp.asarray(arr), keep_dev, axis=0)
    record("re-bucket",
           f"{slots} -> {new_bucket} padded slots "
           f"(observed {observed} rows; est {est})",
           est_before=slots, est_after=observed)
    return Frame(data)
