from .catalog import Catalog, default_catalog
from .parser import execute, parse
