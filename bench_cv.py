"""Config (e) of BASELINE.json: CrossValidator grid (regParam ×
elasticNetParam) on the DQ-cleaned dataset, vs sklearn GridSearchCV.

Runs as a SUBPROCESS of bench.py so its timing starts in a fresh process:
CrossValidator.fit materializes fold metrics and the best model (host
reads), and on the axon-tunneled TPU the first host read drops the whole
process into ~67 ms-per-dispatch synchronous mode — inside a fresh process
that cost lands where it truly belongs (in this config's own wall-clock),
not on the other configs' timings.

Prints ONE JSON line on stdout; diagnostics on stderr.
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

REPS = 2 if os.environ.get("BENCH_SMOKE") == "1" else 5
GRID_REG = [0.1, 0.5, 1.0]
GRID_EN = [0.0, 0.5, 1.0]
FOLDS = 3


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def main():
    import numpy as np

    import sparkdq4ml_tpu as dq
    from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler
    from sparkdq4ml_tpu.models.evaluation import RegressionEvaluator
    from sparkdq4ml_tpu.models.tuning import CrossValidator, ParamGridBuilder

    path = os.path.join(REPO, "data", "dataset-full.csv")
    session = dq.TpuSession.builder().app_name("bench-cv").master("local[*]").get_or_create()

    dq.register_builtin_rules()
    df = (session.read.format("csv").option("inferSchema", "true")
          .option("header", "false").load(path))
    df = df.with_column_renamed("_c0", "guest").with_column_renamed("_c1", "price")
    df = df.with_column("price_no_min", dq.call_udf("minimumPriceRule", dq.col("price")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT cast(guest as int) guest, price_no_min AS price "
                     "FROM price WHERE price_no_min > 0")
    df = df.with_column("price_correct_correl",
                        dq.call_udf("priceCorrelationRule", dq.col("price"), dq.col("guest")))
    df.create_or_replace_temp_view("price")
    df = session.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
    df = df.with_column("label", df.col("price"))
    df = VectorAssembler(["guest"], "features").transform(df)

    grid = (ParamGridBuilder()
            .add_grid("reg_param", GRID_REG)
            .add_grid("elastic_net_param", GRID_EN)
            .build())
    cv = CrossValidator(
        estimator=LinearRegression(max_iter=40, tol=1e-6),
        estimator_param_maps=grid,
        evaluator=RegressionEvaluator(metric_name="rmse"),
        num_folds=FOLDS, seed=7)

    model = cv.fit(df)          # warm: compiles cached; process now in
    times = []                  # whatever dispatch mode production runs in
    for _ in range(REPS):
        t0 = time.perf_counter()
        model = cv.fit(df)
        times.append(time.perf_counter() - t0)
    t_dev = statistics.median(times)
    log(f"CV grid {len(grid)} params x {FOLDS} folds: {t_dev*1e3:.2f} ms; "
        f"best rmse={float(np.min(model.avg_metrics)):.4f}")

    # sklearn baseline: same 3x3 grid, same folds, same family
    d = df.to_pydict()
    Xh = np.asarray(d["guest"], np.float64).reshape(-1, 1)
    yh = np.asarray(d["label"], np.float64)
    sy = yh.std(ddof=1)
    Xs = (Xh - Xh.mean()) / Xh.std(ddof=1)
    ys = (yh - yh.mean()) / sy

    from sklearn.linear_model import ElasticNet
    from sklearn.model_selection import GridSearchCV

    def cpu_fit():
        GridSearchCV(ElasticNet(max_iter=40, tol=1e-6),
                     {"alpha": [r / sy for r in GRID_REG],
                      "l1_ratio": GRID_EN},
                     cv=FOLDS, scoring="neg_root_mean_squared_error",
                     n_jobs=1).fit(Xs, ys)

    cpu_fit()
    cpu_times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        cpu_fit()
        cpu_times.append(time.perf_counter() - t0)
    t_cpu = statistics.median(cpu_times)
    log(f"GridSearchCV baseline: {t_cpu*1e3:.2f} ms")

    print(json.dumps({
        "config": "e_crossvalidator_grid",
        "device_ms": round(t_dev * 1e3, 4),
        "baseline": f"sklearn GridSearchCV(ElasticNet) {len(grid)}x{FOLDS}",
        "baseline_ms": round(t_cpu * 1e3, 4),
        "vs_baseline": round(t_cpu / t_dev, 2),
    }))


if __name__ == "__main__":
    main()
