"""The driver contract: ``entry()`` compiles, ``dryrun_multichip`` runs.

``dryrun_multichip`` must work from any host — with enough devices it runs
in-process; with fewer it must *self-provision* a virtual CPU mesh in a
subprocess (the analogue of the reference's one-machine multi-partition
``master("local[*]")``, `DataQuality4MachineLearningApp.java:40`).
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

import __graft_entry__ as graft


def test_entry_jits():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (args[2].shape[0],)


def test_dryrun_inprocess_path():
    # conftest provisions 8 fake CPU devices, so n=4 runs in-process.
    graft.dryrun_multichip(4)


@pytest.mark.slow
def test_dryrun_self_provisions_subprocess():
    # More devices than this process has → must re-exec with a bigger
    # virtual mesh rather than raising "need N devices".
    assert len(jax.devices()) < 16
    graft.dryrun_multichip(16)
