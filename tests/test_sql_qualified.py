"""Qualified column references: ``t.col``, relation aliases, dotted ON.

Resolution is scope-based: each FROM/JOIN relation contributes an alias
(explicit ``[AS] alias`` or its view name) mapping source columns to the
flat join-output columns — USING keys keep their name, a non-key column
present on both sides resolves the right relation's ref to Spark's
``<name>_right`` rename. A literal dotted column name on the frame wins
over qualified interpretation (CSV headers may contain dots).
"""

import pytest

from sparkdq4ml_tpu import Frame


@pytest.fixture
def views(session):
    t = Frame({"guest": [2.0, 10.0, 14.0], "price": [30.0, 95.0, 120.0]})
    t.create_or_replace_temp_view("t")
    g = Frame({"guest": [10.0, 14.0], "price": [1.0, 2.0],
               "tag": [7.0, 8.0]})
    g.create_or_replace_temp_view("g")
    return t, g


class TestQualifiedRefs:
    def test_view_name_qualifier(self, session, views):
        out = session.sql("SELECT t.price FROM t WHERE t.guest > 5")
        assert out.to_pydict()["price"].tolist() == [95.0, 120.0]
        assert out.columns == ["price"]        # output name is flat

    def test_as_alias_and_bare_alias(self, session, views):
        for sql in ("SELECT x.price FROM t AS x WHERE x.guest > 5",
                    "SELECT x.price FROM t x WHERE x.guest > 5"):
            assert session.sql(sql).to_pydict()["price"].tolist() == \
                [95.0, 120.0]

    def test_alias_replaces_view_name(self, session, views):
        with pytest.raises(ValueError, match="unknown relation alias"):
            session.sql("SELECT t.price FROM t AS x")

    def test_join_disambiguation(self, session, views):
        out = session.sql(
            "SELECT t.price, g.price, g.tag FROM t JOIN g USING (guest)")
        d = out.to_pydict()
        assert d["price"].tolist() == [95.0, 120.0]       # left side
        assert d["price_right"].tolist() == [1.0, 2.0]    # right side
        assert d["tag"].tolist() == [7.0, 8.0]

    def test_qualified_on_clause(self, session, views):
        out = session.sql("SELECT t.price FROM t JOIN g "
                          "ON t.guest = g.guest")
        assert out.to_pydict()["price"].tolist() == [95.0, 120.0]

    def test_qualified_on_different_columns_rejected(self, session, views):
        with pytest.raises(ValueError, match="shared column name"):
            session.sql("SELECT t.price FROM t JOIN g ON t.guest = g.tag")

    def test_aggregates_and_post_agg(self, session, views):
        assert session.sql("SELECT max(t.price) AS mp FROM t") \
            .to_pydict()["mp"].tolist() == [120.0]
        assert session.sql(
            "SELECT max(t.price) - min(t.price) AS sp FROM t") \
            .to_pydict()["sp"].tolist() == [90.0]

    def test_group_and_order_qualified(self, session, views):
        out = session.sql("SELECT t.guest, count(*) AS n FROM t "
                          "GROUP BY t.guest ORDER BY t.guest DESC")
        assert out.to_pydict()["guest"].tolist() == [14.0, 10.0, 2.0]

    def test_unknown_alias_and_column_errors(self, session, views):
        with pytest.raises(ValueError, match="unknown relation alias"):
            session.sql("SELECT z.price FROM t")
        with pytest.raises(ValueError, match="not found in relation"):
            session.sql("SELECT t.nope FROM t")

    def test_semi_join_right_limited_to_keys(self, session, views):
        out = session.sql("SELECT t.price FROM t LEFT SEMI JOIN g "
                          "USING (guest)")
        assert out.to_pydict()["price"].tolist() == [95.0, 120.0]
        with pytest.raises(ValueError, match="not found in relation"):
            session.sql("SELECT g.tag FROM t LEFT SEMI JOIN g USING (guest)")

    def test_literal_dotted_column_wins(self, session):
        f = Frame({"a.b": [1.0, 2.0], "c": [3.0, 4.0]})
        f.create_or_replace_temp_view("dotted")
        out = session.sql("SELECT a.b FROM dotted WHERE a.b > 1")
        assert out.to_pydict()["a.b"].tolist() == [2.0]

    def test_qualified_inside_in_subquery(self, session, views):
        out = session.sql("SELECT t.price FROM t WHERE t.guest IN "
                          "(SELECT guest FROM g)")
        assert out.to_pydict()["price"].tolist() == [95.0, 120.0]

    def test_unaliased_derived_before_setop_and_offset(self, session, views):
        # INTERSECT/OFFSET after an unaliased derived table must start
        # the clause, not become the table's alias.
        assert session.sql("SELECT price FROM (SELECT price FROM t) "
                           "INTERSECT SELECT price FROM t").count() == 3
        assert session.sql("SELECT price FROM (SELECT price FROM t) "
                           "OFFSET 2").count() == 1

    def test_derived_table_alias(self, session, views):
        out = session.sql("SELECT s.price FROM "
                          "(SELECT guest, price FROM t) s "
                          "WHERE s.guest > 5")
        assert out.to_pydict()["price"].tolist() == [95.0, 120.0]

    def test_join_derived_alias(self, session, views):
        out = session.sql(
            "SELECT t.price, x.tag FROM t JOIN "
            "(SELECT guest, tag FROM g) x USING (guest)")
        assert out.to_pydict()["tag"].tolist() == [7.0, 8.0]
