"""Frame wide-surface ops: groupBy/agg, sort, distinct, dropna/fillna,
describe, CSV writer, SQL aggregates/ORDER BY/LIMIT, functions module."""

import os

import numpy as np
import pytest

import sparkdq4ml_tpu.functions as F
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.frame.csv import read_csv
from sparkdq4ml_tpu.sql.parser import execute


@pytest.fixture
def df():
    return Frame({"g": [1, 1, 2, 2, 3],
                  "p": [10.0, 20.0, 30.0, 40.0, 50.0],
                  "s": np.asarray(["a", "b", "a", None, "c"], dtype=object)})


class TestGlobalAgg:
    def test_agg_basics(self, df):
        out = df.agg(F.count(), F.sum("p"), F.avg("p"), F.min("p"),
                     F.max("p"), F.stddev("p"))
        d = out.to_pydict()
        assert d["count"][0] == 5
        assert d["sum(p)"][0] == pytest.approx(150.0)
        assert d["avg(p)"][0] == pytest.approx(30.0)
        assert d["min(p)"][0] == 10.0
        assert d["max(p)"][0] == 50.0
        assert d["stddev(p)"][0] == pytest.approx(np.std([10, 20, 30, 40, 50],
                                                         ddof=1))

    def test_agg_respects_mask(self, df):
        out = df.filter(F.col("p") > 20).agg(F.count(), F.avg("p"))
        d = out.to_pydict()
        assert d["count"][0] == 3
        assert d["avg(p)"][0] == pytest.approx(40.0)

    def test_unknown_aggregate(self):
        from sparkdq4ml_tpu.frame.aggregates import AggExpr

        with pytest.raises(ValueError):
            AggExpr("zorblify", "p")


class TestGroupBy:
    def test_group_count_avg(self, df):
        out = df.group_by("g").agg(F.count(), F.avg("p")).sort("g")
        d = out.to_pydict()
        assert list(d["g"]) == [1, 2, 3]
        assert list(d["count"]) == [2, 2, 1]
        assert list(d["avg(p)"]) == [15.0, 35.0, 50.0]

    def test_group_by_respects_mask(self, df):
        out = df.filter(F.col("p") >= 20).group_by("g").count().sort("g")
        assert list(out.to_pydict()["count"]) == [1, 2, 1]

    def test_group_by_string_key(self, df):
        out = df.filter(F.col("s").is_not_null()).group_by("s").count().sort("s")
        d = out.to_pydict()
        assert list(d["s"]) == ["a", "b", "c"]
        assert list(d["count"]) == [2, 1, 1]

    def test_terminal_helpers(self, df):
        assert "sum(p)" in df.group_by("g").sum("p").columns
        assert "max(p)" in df.group_by("g").max("p").columns

    def test_missing_key_raises(self, df):
        with pytest.raises(KeyError):
            df.group_by("nope")

    def test_empty_group_frame(self, df):
        out = df.filter(F.col("p") > 1000).group_by("g").count()
        assert out.count() == 0


class TestSortDistinctNa:
    def test_sort_asc_desc(self, df):
        assert [r[1] for r in df.sort("p", ascending=False).collect()] == [
            50.0, 40.0, 30.0, 20.0, 10.0]
        assert [r[0] for r in df.sort("g").collect()] == [1, 1, 2, 2, 3]

    def test_sort_multi_key(self):
        f = Frame({"a": [2, 1, 2, 1], "b": [1.0, 2.0, 0.0, 1.0]})
        out = f.sort("a", "b")
        assert out.collect() == [(1, 1.0), (1, 2.0), (2, 0.0), (2, 1.0)]

    def test_sort_drops_masked_rows(self, df):
        out = df.filter(F.col("p") > 20).sort("p")
        assert out.count() == 3
        assert out.num_slots == 3  # compacted

    def test_distinct(self):
        f = Frame({"x": [1, 2, 1, 3, 2]})
        assert sorted(r[0] for r in f.distinct().collect()) == [1, 2, 3]

    def test_dropna_float_and_string(self, df):
        f = df.with_column("p2", [1.0, float("nan"), 3.0, 4.0, 5.0])
        assert f.dropna(["p2"]).count() == 4
        assert df.dropna(["s"]).count() == 4
        assert df.dropna().count() == 4

    def test_fillna(self, df):
        f = df.with_column("p2", [1.0, float("nan"), 3.0, 4.0, 5.0])
        d = f.fillna(0.0, ["p2"]).to_pydict()
        assert d["p2"][1] == 0.0
        d2 = df.fillna("?", ["s"]).to_pydict()
        assert d2["s"][3] == "?"

    def test_describe(self, df):
        out = df.describe("p")
        d = out.to_pydict()
        assert list(d["summary"]) == ["count", "mean", "stddev", "min", "max"]
        assert float(d["p"][1]) == pytest.approx(30.0)


class TestWriter:
    def test_roundtrip(self, df, tmp_path):
        path = str(tmp_path / "out.csv")
        num = df.select("g", "p").filter(F.col("p") > 15)
        num.write.format("csv").option("header", "true").save(path)
        back = read_csv(path, header=True, infer_schema=True)
        assert back.count() == num.count()
        assert back.columns == ["g", "p"]
        np.testing.assert_allclose(back.to_pydict()["p"],
                                   num.to_pydict()["p"])

    def test_mode_errorifexists(self, df, tmp_path):
        path = str(tmp_path / "x.csv")
        df.select("g").to_csv(path)
        with pytest.raises(FileExistsError):
            df.select("g").write.save(path)
        df.select("g").write.mode("overwrite").save(path)  # no raise

    def test_quoting_and_nulls(self, tmp_path):
        f = Frame({"s": np.asarray(['a,b', 'q"q', None], dtype=object),
                   "x": [1.0, float("nan"), 3.0]})
        path = str(tmp_path / "q.csv")
        f.to_csv(path)
        text = open(path).read()
        assert '"a,b"' in text
        assert '"q""q"' in text
        back = read_csv(path, infer_schema=True)
        assert back.count() == 3

    def test_masked_rows_not_written(self, df, tmp_path):
        path = str(tmp_path / "m.csv")
        df.select("g", "p").filter(F.col("g") == 1).to_csv(path)
        assert len(open(path).read().strip().splitlines()) == 2


class TestSqlAggregates:
    @pytest.fixture(autouse=True)
    def _view(self, session, df):
        df.create_or_replace_temp_view("t")

    def test_group_by(self, session):
        out = session.sql("SELECT g, COUNT(*) AS n, AVG(p) AS m FROM t "
                          "GROUP BY g ORDER BY g")
        d = out.to_pydict()
        assert list(d["n"]) == [2, 2, 1]
        assert list(d["m"]) == [15.0, 35.0, 50.0]

    def test_global_agg(self, session):
        d = session.sql("SELECT SUM(p) AS s, MIN(p) AS lo FROM t "
                        "WHERE g < 3").to_pydict()
        assert d["s"][0] == pytest.approx(100.0)
        assert d["lo"][0] == 10.0

    def test_order_by_desc_limit(self, session):
        out = session.sql("SELECT g, p FROM t ORDER BY p DESC LIMIT 2")
        assert [r[1] for r in out.collect()] == [50.0, 40.0]

    def test_plain_col_without_group_by_rejected(self, session):
        with pytest.raises(ValueError):
            session.sql("SELECT g, COUNT(*) FROM t")

    def test_non_key_col_rejected(self, session):
        with pytest.raises(ValueError):
            session.sql("SELECT p, COUNT(*) FROM t GROUP BY g")

    def test_count_star_where(self, session):
        d = session.sql("SELECT COUNT(*) AS n FROM t WHERE p >= 30").to_pydict()
        assert d["n"][0] == 3


class TestAggNullAndOverflowSemantics:
    def test_int_sum_exact_beyond_float32(self):
        f = Frame({"x": np.arange(1, 3_000_001, dtype=np.int32)})
        d = f.agg(F.sum("x")).to_pydict()
        assert int(d["sum(x)"][0]) == 4_500_001_500_000  # Spark widens to long

    def test_count_col_skips_nulls(self, df):
        f = df.with_column("p2", [1.0, float("nan"), 3.0, 4.0, 5.0])
        d = f.agg(F.count("p2"), F.count("s"), F.count()).to_pydict()
        assert int(d["count(p2)"][0]) == 4
        assert int(d["count(s)"][0]) == 4      # one None
        assert int(d["count"][0]) == 5         # COUNT(*) keeps all rows

    def test_avg_skips_nans(self, df):
        f = df.with_column("p2", [2.0, float("nan"), 4.0, float("nan"), 6.0])
        assert f.agg(F.avg("p2")).to_pydict()["avg(p2)"][0] == pytest.approx(4.0)

    def test_stddev_single_row_is_nan(self):
        f = Frame({"x": [5.0]})
        assert np.isnan(f.agg(F.stddev("x")).to_pydict()["stddev(x)"][0])

    def test_grouped_agg_skips_nulls(self):
        f = Frame({"g": [1, 1, 2], "x": [1.0, float("nan"), 2.0]})
        out = f.group_by("g").agg(F.count("x"), F.avg("x")).sort("g")
        d = out.to_pydict()
        assert list(d["count(x)"]) == [1, 1]
        assert d["avg(x)"][0] == pytest.approx(1.0)

    def test_distinct_with_vector_column(self):
        from sparkdq4ml_tpu.models import VectorAssembler

        f = Frame({"x": [1.0, 1.0, 2.0]})
        f = VectorAssembler(["x"], "features").transform(f)
        assert f.distinct().count() == 2

    def test_sort_string_nulls_first(self):
        f = Frame({"s": np.asarray(["b", None, "a"], dtype=object)})
        assert [r[0] for r in f.sort("s").collect()] == [None, "a", "b"]

    def test_sql_order_by_unprojected_column(self, session):
        Frame({"name": np.asarray(["x", "y"], dtype=object),
               "age": [30, 20]}).create_or_replace_temp_view("people")
        out = session.sql("SELECT name FROM people ORDER BY age")
        assert [r[0] for r in out.collect()] == ["y", "x"]

    def test_cv_fast_path_refit_uses_gram(self, session):
        """fit_from_gram must equal a regular fit on the same frame."""
        from conftest import dataset_path, prepare_features, run_dq_pipeline
        from sparkdq4ml_tpu.models import LinearRegression
        from sparkdq4ml_tpu.models.solvers import augmented_gram
        from sparkdq4ml_tpu.models.regression import _extract_xy
        import jax.numpy as jnp

        frame = prepare_features(run_dq_pipeline(session, dataset_path("small")))
        lr = LinearRegression(max_iter=40, reg_param=1.0, elastic_net_param=1.0)
        X, y, mask = _extract_xy(frame, "features", "label")
        A = augmented_gram(jnp.asarray(X), jnp.asarray(y), mask)
        m1 = lr.fit_from_gram(A, frame)
        m2 = lr.fit(frame)
        np.testing.assert_allclose(m1.coefficients, m2.coefficients, rtol=1e-12)
        assert m1.summary.root_mean_squared_error == pytest.approx(
            m2.summary.root_mean_squared_error, rel=1e-12)


class TestDebugUtils:
    def test_nan_checks_context(self):
        import jax
        import jax.numpy as jnp

        from sparkdq4ml_tpu.utils.debug import nan_checks

        with nan_checks():
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()
        # restored afterwards
        jax.jit(lambda x: jnp.log(x))(jnp.asarray(-1.0)).block_until_ready()


class TestSampleSplitCache:
    def _frame(self):
        import jax.numpy as jnp

        from sparkdq4ml_tpu import Frame

        return Frame({"x": jnp.arange(1000.0)})

    def test_random_split_partitions_rows(self):
        f = self._frame()
        parts = f.random_split([0.7, 0.3], seed=42)
        assert len(parts) == 2
        n = [p.count() for p in parts]
        assert sum(n) == 1000          # disjoint and exhaustive
        assert 600 < n[0] < 800        # roughly 70/30
        # disjointness: no row valid in both
        import jax.numpy as jnp
        assert not bool(jnp.any(jnp.logical_and(parts[0].mask, parts[1].mask)))

    def test_random_split_normalizes_weights(self):
        f = self._frame()
        a, b = f.random_split([8, 2], seed=0)
        assert a.count() + b.count() == 1000
        assert a.count() > b.count()

    def test_random_split_respects_existing_mask(self):
        f = self._frame().filter(self._frame().col("x") < 100)
        parts = f.random_split([0.5, 0.5], seed=1)
        assert sum(p.count() for p in parts) == 100

    def test_random_split_rejects_bad_weights(self):
        import pytest

        with pytest.raises(ValueError):
            self._frame().random_split([0.5, -0.5])

    def test_sample_fraction(self):
        f = self._frame()
        s = f.sample(0.25, seed=7)
        assert 150 < s.count() < 350
        import pytest

        with pytest.raises(ValueError):
            f.sample(1.5)

    def test_cache_and_explain(self, capsys):
        f = self._frame()
        assert f.cache() is f
        assert f.persist() is f
        assert f.unpersist() is f
        f.explain(extended=True)
        out = capsys.readouterr().out
        assert "Physical Frame" in out
        assert "row slots: 1000" in out
        assert "x: device/" in out


class TestSampleWithReplacement:
    def test_poisson_bootstrap_counts(self):
        f = Frame({"x": np.arange(1000, dtype=np.float64)})
        out = f.sample(1.0, seed=7, with_replacement=True)
        # expected count ≈ n, and duplicates must exist
        assert 850 < out.count() < 1150
        xs = out.to_pydict()["x"]
        assert len(np.unique(xs)) < len(xs)

    def test_fraction_above_one(self):
        f = Frame({"x": np.arange(200, dtype=np.float64)})
        out = f.sample(3.0, seed=1, with_replacement=True)
        assert 450 < out.count() < 750

    def test_masked_rows_never_sampled(self):
        import jax.numpy as jnp

        x = np.arange(100, dtype=np.float64)
        f = Frame({"x": x}).filter(jnp.asarray(x < 50))
        out = f.sample(2.0, seed=3, with_replacement=True)
        assert out.count() > 0
        assert np.max(out.to_pydict()["x"]) < 50

    def test_string_columns_gather(self):
        f = Frame({"x": np.asarray([1.0, 2.0, 3.0]),
                   "s": np.asarray(["a", "b", "c"], object)})
        out = f.sample(2.0, seed=5, with_replacement=True)
        d = out.to_pydict()
        lut = {1.0: "a", 2.0: "b", 3.0: "c"}
        assert all(lut[v] == s for v, s in zip(d["x"], d["s"]))

    def test_deterministic_by_seed(self):
        f = Frame({"x": np.arange(50, dtype=np.float64)})
        a = f.sample(1.0, seed=9, with_replacement=True).to_pydict()["x"]
        b = f.sample(1.0, seed=9, with_replacement=True).to_pydict()["x"]
        np.testing.assert_array_equal(a, b)

    def test_negative_fraction_rejected(self):
        f = Frame({"x": [1.0]})
        with pytest.raises(ValueError):
            f.sample(-0.5, with_replacement=True)
