"""Window functions: ranking, offsets, windowed aggregates, SQL OVER.

Cross-checked against Spark/SQL window semantics: default frame for ordered
windows is RANGE UNBOUNDED PRECEDING..CURRENT ROW (running aggregates include
peer rows); ranking functions follow SQL RANK/DENSE_RANK tie rules.
"""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu import functions as F


@pytest.fixture
def sales():
    # dept, name, amount — with a tie inside dept "a" (30 twice)
    return Frame({
        "dept": np.asarray(["a", "a", "a", "b", "b", "a"], dtype=object),
        "name": np.asarray(["u", "v", "w", "x", "y", "z"], dtype=object),
        "amount": [10.0, 30.0, 30.0, 5.0, 7.0, 50.0],
    })


def _by_name(frame, value_col):
    d = frame.to_pydict()
    return {n: v for n, v in zip(d["name"], d[value_col])}


class TestValueFunctions:
    """first_value/last_value/nth_value — frame-positional value picks."""

    def test_first_value_partition_start(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        got = _by_name(sales.withColumn("fv", F.first_value("amount").over(w)),
                       "fv")
        assert got["u"] == got["z"] == 10.0
        assert got["x"] == got["y"] == 5.0

    def test_last_value_default_frame_tracks_peers(self, sales):
        # Spark's famous default-frame semantics: the frame ends at the
        # current row's LAST PEER, so ties (30, 30) see each other.
        w = F.Window.partitionBy("dept").orderBy("amount")
        got = _by_name(sales.withColumn("lv", F.last_value("amount").over(w)),
                       "lv")
        assert got["u"] == 10.0
        assert got["v"] == got["w"] == 30.0   # peer group of the tie
        assert got["z"] == 50.0

    def test_last_value_unbounded_frame(self, sales):
        w = (F.Window.partitionBy("dept").orderBy("amount")
             .rowsBetween(F.Window.unboundedPreceding,
                          F.Window.unboundedFollowing))
        got = _by_name(sales.withColumn("lv", F.last_value("amount").over(w)),
                       "lv")
        assert got["u"] == got["z"] == 50.0
        assert got["x"] == got["y"] == 7.0

    def test_nth_value_null_before_n_rows(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        got = _by_name(sales.withColumn("nv",
                                        F.nth_value("amount", 2).over(w)),
                       "nv")
        assert np.isnan(got["u"])              # frame has 1 row
        assert got["z"] == 30.0
        assert np.isnan(got["x"]) and got["y"] == 7.0

    def test_first_agg_maps_to_first_value(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        got = _by_name(sales.withColumn("fv", F.first("amount").over(w)),
                       "fv")
        assert got["u"] == got["z"] == 10.0

    def test_sql_forms(self, session, sales):
        sales.create_or_replace_temp_view("sales_vw")
        out = session.sql(
            "SELECT name, first_value(amount) OVER "
            "(PARTITION BY dept ORDER BY amount) AS fv, "
            "nth_value(amount, 2) OVER "
            "(PARTITION BY dept ORDER BY amount "
            "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS nv "
            "FROM sales_vw")
        got_fv = _by_name(out, "fv")
        assert got_fv["u"] == 10.0 and got_fv["x"] == 5.0

    def test_string_column_values(self, session):
        f = Frame({"k": [1.0, 1.0, 2.0],
                   "s": np.asarray(["b", "a", "c"], dtype=object),
                   "v": [2.0, 1.0, 3.0]})
        w = F.Window.partitionBy("k").orderBy("v")
        out = f.withColumn("fv", F.first_value("s").over(w)).to_pydict()
        by_v = dict(zip(out["v"].tolist(), out["fv"]))
        assert by_v[1.0] == "a" and by_v[2.0] == "a" and by_v[3.0] == "c"


class TestRanking:
    def test_row_number(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("rn", F.row_number().over(w))
        got = _by_name(out, "rn")
        assert got["u"] == 1 and got["z"] == 4          # dept a: 10,30,30,50
        assert {got["v"], got["w"]} == {2, 3}           # tie broken arbitrarily
        assert got["x"] == 1 and got["y"] == 2          # dept b: 5,7

    def test_rank_and_dense_rank_ties(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("r", F.rank().over(w)) \
                   .withColumn("dr", F.dense_rank().over(w))
        r, dr = _by_name(out, "r"), _by_name(out, "dr")
        assert r["u"] == 1 and r["v"] == 2 and r["w"] == 2 and r["z"] == 4
        assert dr["u"] == 1 and dr["v"] == 2 and dr["w"] == 2 and dr["z"] == 3

    def test_percent_rank(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("pr", F.percent_rank().over(w))
        pr = _by_name(out, "pr")
        assert pr["u"] == pytest.approx(0.0)
        assert pr["v"] == pytest.approx(1 / 3) == pr["w"]
        assert pr["z"] == pytest.approx(1.0)

    def test_cume_dist(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        cd = _by_name(sales.withColumn("cd", F.cume_dist().over(w)), "cd")
        assert cd["u"] == pytest.approx(0.25)
        assert cd["v"] == pytest.approx(0.75) == cd["w"]  # peers included
        assert cd["z"] == pytest.approx(1.0)
        assert cd["x"] == pytest.approx(0.5) and cd["y"] == pytest.approx(1.0)

    def test_ntile(self):
        f = Frame({"k": np.asarray(["g"] * 5, dtype=object),
                   "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
        w = F.Window.partitionBy("k").orderBy("v")
        out = f.withColumn("t", F.ntile(2).over(w)).to_pydict()
        assert out["t"].tolist() == [1, 1, 1, 2, 2]  # first bucket gets extra

    def test_desc_order(self, sales):
        w = F.Window.partitionBy("dept").orderBy(("amount", False))
        rn = _by_name(sales.withColumn("rn", F.row_number().over(w)), "rn")
        assert rn["z"] == 1 and rn["u"] == 4

    def test_ranking_requires_order(self):
        with pytest.raises(ValueError, match="ORDER BY"):
            F.row_number().over(F.Window.partitionBy("dept"))

    def test_no_partition_is_one_global_partition(self, sales):
        w = F.Window.orderBy("amount")
        rn = _by_name(sales.withColumn("rn", F.row_number().over(w)), "rn")
        assert sorted(rn.values()) == [1, 2, 3, 4, 5, 6]
        assert rn["x"] == 1 and rn["z"] == 6


class TestOffsets:
    def test_lag_lead(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("prev", F.lag("amount").over(w)) \
                   .withColumn("next", F.lead("amount").over(w))
        prev, nxt = _by_name(out, "prev"), _by_name(out, "next")
        assert np.isnan(prev["u"]) and np.isnan(prev["x"])  # partition edge
        assert prev["z"] == pytest.approx(30.0)
        assert nxt["u"] == pytest.approx(30.0)
        assert np.isnan(nxt["z"]) and np.isnan(nxt["y"])

    def test_lag_default_and_offset(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("p2", F.lag("amount", 2, -1.0).over(w))
        p2 = _by_name(out, "p2")
        assert p2["u"] == pytest.approx(-1.0)   # beyond edge → default
        assert p2["v"] == pytest.approx(-1.0) or p2["w"] == pytest.approx(-1.0)
        assert p2["z"] == pytest.approx(30.0)   # two rows back from 50

    def test_lag_string_column(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("pn", F.lag("name").over(w))
        pn = _by_name(out, "pn")
        assert pn["u"] is None
        assert pn["y"] == "x"


class TestWindowedAggregates:
    def test_running_sum_includes_peers(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        rs = _by_name(sales.withColumn("rs", F.sum("amount").over(w)), "rs")
        assert rs["u"] == pytest.approx(10.0)
        # RANGE frame: both 30-peers see 10+30+30
        assert rs["v"] == pytest.approx(70.0) == rs["w"]
        assert rs["z"] == pytest.approx(120.0)

    def test_unordered_whole_partition(self, sales):
        w = F.Window.partitionBy("dept")
        tot = _by_name(sales.withColumn("tot", F.sum("amount").over(w)), "tot")
        assert tot["u"] == pytest.approx(120.0) == tot["z"]
        assert tot["x"] == pytest.approx(12.0) == tot["y"]

    def test_running_min_max_avg_count(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.withColumn("mn", F.min("amount").over(w)) \
                   .withColumn("mx", F.max("amount").over(w)) \
                   .withColumn("av", F.avg("amount").over(w)) \
                   .withColumn("ct", F.count("amount").over(w))
        mn, mx = _by_name(out, "mn"), _by_name(out, "mx")
        av, ct = _by_name(out, "av"), _by_name(out, "ct")
        assert mn["z"] == pytest.approx(10.0) and mx["v"] == pytest.approx(30.0)
        assert av["v"] == pytest.approx(70.0 / 3)
        assert ct["v"] == 3 and ct["z"] == 4

    def test_masked_rows_excluded(self, sales):
        from sparkdq4ml_tpu import col

        w = F.Window.partitionBy("dept").orderBy("amount")
        filtered = sales.filter(col("amount") > 9.0)  # drops x(5), y(7)
        out = filtered.withColumn("rn", F.row_number().over(w))
        rn = _by_name(out, "rn")
        assert "x" not in rn and "y" not in rn
        assert sorted(v for k, v in rn.items()) == [1, 2, 3, 4]

    def test_null_values_skipped_in_agg(self):
        f = Frame({"k": np.asarray(["g", "g", "g"], dtype=object),
                   "t": [1.0, 2.0, 3.0],
                   "v": [5.0, float("nan"), 7.0]})
        w = F.Window.partitionBy("k").orderBy("t")
        out = f.withColumn("s", F.sum("v").over(w)).to_pydict()
        assert out["s"].tolist() == pytest.approx([5.0, 5.0, 12.0])


class TestEdgeCases:
    def test_two_unaliased_window_exprs_do_not_collide(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        out = sales.select("name", F.lag("amount").over(w),
                           F.lag("name").over(w))
        assert len(out.columns) == 3  # distinct generated names

    def test_nan_partition_keys_form_one_group(self):
        f = Frame({"k": [1.0, float("nan"), float("nan")],
                   "x": [1.0, 2.0, 3.0]})
        w = F.Window.partitionBy("k")
        out = f.withColumn("s", F.sum("x").over(w)).to_pydict()
        assert out["s"].tolist() == pytest.approx([1.0, 5.0, 5.0])

    def test_null_and_empty_string_keys_are_distinct_groups(self):
        # Spark groups nulls separately from the empty string
        f = Frame({"k": np.asarray(["", None, "", None], dtype=object),
                   "x": [1.0, 2.0, 4.0, 8.0]})
        w = F.Window.partitionBy("k")
        out = f.withColumn("s", F.sum("x").over(w)).to_pydict()
        assert out["s"].tolist() == pytest.approx([5.0, 10.0, 5.0, 10.0])

    def test_running_max_with_legit_infinity(self):
        f = Frame({"v": [1.0, 2.0], "x": [float("inf"), 5.0]})
        w = F.Window.orderBy("v")
        out = f.withColumn("m", F.max("x").over(w)).to_pydict()
        assert out["m"].tolist() == [float("inf"), float("inf")]

    def test_nan_order_key_sorts_first_ascending(self):
        # SQL NULLS FIRST for ascending order, both dtypes
        f = Frame({"v": [float("nan"), 1.0, 2.0]})
        w = F.Window.orderBy("v")
        out = f.withColumn("rn", F.row_number().over(w)).to_pydict()
        assert out["rn"].tolist()[0] == 1     # the NaN row
        f2 = Frame({"v": [2.0, float("nan"), 1.0]})
        w2 = F.Window.orderBy(("v", False))   # DESC → NULLS LAST
        out2 = f2.withColumn("rn", F.row_number().over(w2)).to_pydict()
        assert out2["rn"].tolist() == [1, 3, 2]

    def test_descending_bool_order_key(self):
        f = Frame({"b": np.asarray([True, False, True]),
                   "x": [1.0, 2.0, 3.0]})
        w = F.Window.orderBy(("b", False))
        out = f.withColumn("rn", F.row_number().over(w)).to_pydict()
        # True rows first under DESC
        by_x = dict(zip(out["x"].tolist(), out["rn"].tolist()))
        assert by_x[2.0] == 3 and {by_x[1.0], by_x[3.0]} == {1, 2}

    def test_lag_offset_zero_is_current_row(self):
        f = Frame({"x": [1.0, 2.0, 3.0]})
        w = F.Window.orderBy("x")
        out = f.withColumn("c", F.lag("x", 0).over(w)).to_pydict()
        assert out["c"].tolist() == pytest.approx([1.0, 2.0, 3.0])

    def test_windowed_count_over_string_column(self, sales):
        w = F.Window.partitionBy("dept").orderBy("amount")
        ct = _by_name(sales.withColumn("ct", F.count("name").over(w)), "ct")
        assert ct["z"] == 4 and ct["y"] == 2


class TestSqlOver:
    def test_sql_row_number(self, sales, session):
        s = session
        sales.createOrReplaceTempView("sales")
        out = s.sql("SELECT name, ROW_NUMBER() OVER "
                    "(PARTITION BY dept ORDER BY amount) AS rn FROM sales")
        rn = _by_name(out, "rn")
        assert rn["u"] == 1 and rn["z"] == 4 and rn["x"] == 1

    def test_sql_windowed_agg_and_lag(self, sales, session):
        s = session
        sales.createOrReplaceTempView("sales")
        out = s.sql("SELECT name, SUM(amount) OVER (PARTITION BY dept "
                    "ORDER BY amount) AS rs, LAG(amount, 1) OVER "
                    "(PARTITION BY dept ORDER BY amount) AS prev FROM sales")
        rs, prev = _by_name(out, "rs"), _by_name(out, "prev")
        assert rs["z"] == pytest.approx(120.0)
        assert np.isnan(prev["u"]) and prev["z"] == pytest.approx(30.0)

    def test_sql_desc_and_where(self, sales, session):
        s = session
        sales.createOrReplaceTempView("sales")
        out = s.sql("SELECT name, RANK() OVER (PARTITION BY dept ORDER BY "
                    "amount DESC) AS r FROM sales WHERE amount > 9")
        r = _by_name(out, "r")
        assert r["z"] == 1 and r["u"] == 4 and "x" not in r

    def test_sql_window_fn_without_over_errors(self, sales, session):
        s = session
        sales.createOrReplaceTempView("sales")
        with pytest.raises(ValueError, match="OVER"):
            s.sql("SELECT ROW_NUMBER() FROM sales")

    def test_sql_zero_arg_aggregate_is_a_parse_error(self, sales, session):
        sales.createOrReplaceTempView("sales")
        with pytest.raises(ValueError, match="column name"):
            session.sql("SELECT SUM() FROM sales")

    def test_sql_negative_lag_offset_and_default(self, sales, session):
        sales.createOrReplaceTempView("sales")
        out = session.sql("SELECT name, LAG(amount, -1, -1.0) OVER "
                          "(PARTITION BY dept ORDER BY amount) AS nxt "
                          "FROM sales")
        nxt = _by_name(out, "nxt")
        assert nxt["u"] == pytest.approx(30.0)   # lag -1 ≡ lead 1
        assert nxt["z"] == pytest.approx(-1.0)   # edge → default

    def test_over_and_partition_are_not_reserved(self, session):
        f = Frame({"partition": [1.0, 2.0], "over": [3.0, 4.0]})
        f.createOrReplaceTempView("weird")
        out = session.sql("SELECT partition, over FROM weird "
                          "WHERE partition > 1")
        assert out.to_pydict()["over"].tolist() == [4.0]


class TestExplicitFrames:
    """rowsBetween / rangeBetween (Spark Window frame API)."""

    def _frame(self):
        return Frame({
            "g": np.asarray(["a"] * 5 + ["b"] * 3, dtype=object),
            "t": np.asarray([1, 2, 3, 4, 5, 1, 2, 3], np.int64),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0, 10.0, 20.0, 30.0]),
        })

    def test_moving_average_rows(self):
        # 3-row centered moving average, clipped at partition edges
        f = self._frame()
        w = (F.Window.partitionBy("g").orderBy("t").rowsBetween(-1, 1))
        out = f.withColumn("ma", F.mean("v").over(w)).to_pydict()
        got = dict(zip(zip(out["g"], out["t"]), out["ma"]))
        assert got[("a", 1)] == pytest.approx((1 + 2) / 2)
        assert got[("a", 3)] == pytest.approx((2 + 3 + 4) / 3)
        assert got[("a", 5)] == pytest.approx((4 + 5) / 2)
        assert got[("b", 2)] == pytest.approx((10 + 20 + 30) / 3)

    def test_rows_unbounded_preceding_running_sum_excludes_peers(self):
        # ROWS (not RANGE): peers do NOT ride along
        f = Frame({"g": np.asarray(["a"] * 3, dtype=object),
                   "t": np.asarray([1, 1, 2], np.int64),
                   "v": np.asarray([1.0, 10.0, 100.0])})
        w = (F.Window.partitionBy("g").orderBy("t")
             .rowsBetween(F.Window.unboundedPreceding, F.Window.currentRow))
        out = f.withColumn("rs", F.sum("v").over(w)).to_pydict()
        # the two t=1 peers get DIFFERENT running sums under ROWS
        sums = sorted(out["rs"][:2])
        assert sums[1] - sums[0] in (1.0, 10.0)
        assert out["rs"][2] == pytest.approx(111.0)

    def test_range_current_to_unbounded_following(self):
        f = self._frame()
        w = (F.Window.partitionBy("g").orderBy("t")
             .rangeBetween(F.Window.currentRow,
                           F.Window.unboundedFollowing))
        out = f.withColumn("s", F.sum("v").over(w)).to_pydict()
        got = dict(zip(zip(out["g"], out["t"]), out["s"]))
        assert got[("a", 1)] == pytest.approx(15.0)
        assert got[("a", 4)] == pytest.approx(9.0)
        assert got[("b", 3)] == pytest.approx(30.0)

    def test_bounded_following_only_window_can_be_empty(self):
        f = self._frame()
        w = (F.Window.partitionBy("g").orderBy("t").rowsBetween(1, 2))
        out = f.withColumn("s", F.sum("v").over(w)) \
               .withColumn("c", F.count("v").over(w)).to_pydict()
        got = dict(zip(zip(out["g"], out["t"]),
                       zip(out["s"], out["c"])))
        assert got[("a", 1)][0] == pytest.approx(2 + 3)
        assert got[("a", 4)][0] == pytest.approx(5.0)
        s5, c5 = got[("a", 5)]
        assert np.isnan(s5) and c5 == 0          # empty frame: sum null
        assert got[("b", 2)][0] == pytest.approx(30.0)

    def test_min_max_bounded_frame(self):
        f = self._frame()
        w = (F.Window.partitionBy("g").orderBy("t").rowsBetween(-1, 1))
        out = f.withColumn("lo", F.min("v").over(w)) \
               .withColumn("hi", F.max("v").over(w)).to_pydict()
        got = dict(zip(zip(out["g"], out["t"]),
                       zip(out["lo"], out["hi"])))
        assert got[("a", 3)] == (2.0, 4.0)
        assert got[("a", 1)] == (1.0, 2.0)
        assert got[("b", 3)] == (20.0, 30.0)

    def test_rows_frame_requires_order(self):
        f = self._frame()
        w = F.Window.partitionBy("g").rowsBetween(-1, 1)
        with pytest.raises(ValueError, match="ORDER BY"):
            f.withColumn("x", F.sum("v").over(w)).to_pydict()

    def test_invalid_frame_rejected(self):
        with pytest.raises(ValueError, match="start"):
            F.Window.partitionBy("g").orderBy("t").rowsBetween(2, 1)
        with pytest.raises(NotImplementedError):
            F.Window.partitionBy("g").orderBy("t").rangeBetween(-5, 5)

    def test_ranking_ignores_frame(self):
        # SQL: ranking functions are frame-insensitive
        f = self._frame()
        w0 = F.Window.partitionBy("g").orderBy("t")
        w1 = w0.rowsBetween(-1, 1)
        a = f.withColumn("r", F.row_number().over(w0)).to_pydict()["r"]
        b = f.withColumn("r", F.row_number().over(w1)).to_pydict()["r"]
        assert list(a) == list(b)


class TestSqlFrames:
    """ROWS/RANGE BETWEEN in the SQL OVER clause."""

    def _cat(self):
        from sparkdq4ml_tpu.sql.catalog import Catalog
        cat = Catalog()
        f = Frame({"g": np.asarray(["a", "a", "a", "b", "b"], dtype=object),
                   "t": np.asarray([1, 2, 3, 1, 2], np.int64),
                   "v": np.asarray([1.0, 2.0, 3.0, 10.0, 20.0])})
        cat.register("t1", f)
        return cat

    def test_rows_between_preceding_current(self):
        from sparkdq4ml_tpu.sql.parser import execute
        out = execute(
            "SELECT g, t, SUM(v) OVER (PARTITION BY g ORDER BY t "
            "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS rs FROM t1",
            self._cat())
        assert list(np.asarray(out.to_pydict()["rs"], np.float64)) == \
            [1.0, 3.0, 5.0, 10.0, 30.0]

    def test_range_unbounded_both(self):
        from sparkdq4ml_tpu.sql.parser import execute
        out = execute(
            "SELECT g, AVG(v) OVER (PARTITION BY g RANGE BETWEEN "
            "UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS m FROM t1",
            self._cat())
        assert list(np.asarray(out.to_pydict()["m"], np.float64)) == \
            [2.0, 2.0, 2.0, 15.0, 15.0]

    def test_rows_following_window(self):
        from sparkdq4ml_tpu.sql.parser import execute
        out = execute(
            "SELECT g, SUM(v) OVER (PARTITION BY g ORDER BY t "
            "ROWS BETWEEN CURRENT ROW AND 1 FOLLOWING) AS s FROM t1",
            self._cat())
        assert list(np.asarray(out.to_pydict()["s"], np.float64)) == \
            [3.0, 5.0, 3.0, 30.0, 20.0]

    def test_bad_frame_syntax_raises(self):
        from sparkdq4ml_tpu.sql.parser import execute
        with pytest.raises(ValueError):
            execute("SELECT SUM(v) OVER (PARTITION BY g ORDER BY t "
                    "ROWS BETWEEN garbage AND CURRENT ROW) AS s FROM t1",
                    self._cat())

    def test_non_integer_bound_rejected(self):
        from sparkdq4ml_tpu.sql.parser import execute
        with pytest.raises(ValueError, match="integer"):
            execute("SELECT SUM(v) OVER (PARTITION BY g ORDER BY t "
                    "ROWS BETWEEN 1.7 PRECEDING AND CURRENT ROW) AS s "
                    "FROM t1", self._cat())


class TestRunningSumNullPrefix:
    def test_all_null_prefix_is_null_not_zero(self):
        # Spark: SUM OVER an ordered frame with zero non-null rows so far
        # is NULL; found by the pandas differential sweep.
        import math
        f = Frame({"k": [1.0, 1.0, 1.0], "o": [1.0, 2.0, 3.0],
                   "v": [math.nan, 2.0, 3.0]})
        w = F.Window.partitionBy("k").orderBy("o")
        rs = f.withColumn("rs", F.sum("v").over(w)).sort("o") \
            .to_pydict()["rs"]
        assert math.isnan(rs[0])
        assert rs[1] == 2.0 and rs[2] == 5.0


class TestWindowInExpressionPosition:
    def test_share_of_total(self, session):
        f = Frame({"k": [1.0, 1.0], "v": [3.0, 5.0]})
        f.create_or_replace_temp_view("wexp")
        out = session.sql("SELECT v / sum(v) OVER (PARTITION BY k) "
                          "AS share FROM wexp")
        assert out.to_pydict()["share"].tolist() == [0.375, 0.625]
        session.catalog.drop("wexp")

    def test_difference_from_first(self, session):
        f = Frame({"g": [2.0, 10.0], "p": [30.0, 95.0]})
        f.create_or_replace_temp_view("wexp2")
        out = session.sql("SELECT p - first_value(p) OVER (ORDER BY g) "
                          "AS uplift FROM wexp2")
        assert out.to_pydict()["uplift"].tolist() == [0.0, 65.0]
        session.catalog.drop("wexp2")

    def test_sql_transformer_uses_full_grammar(self, session):
        from sparkdq4ml_tpu.models import SQLTransformer
        f = Frame({"g": [2.0, 10.0, 14.0], "p": [30.0, 95.0, 120.0]})
        t = SQLTransformer(statement="SELECT g, p FROM __THIS__ WHERE "
                           "p > (SELECT avg(p) FROM __THIS__)")
        assert t.transform(f).to_pydict()["p"].tolist() == [95.0, 120.0]
