"""Query-level ORDER BY: names, positions, expressions, aggregates.

Spark/ANSI forms beyond the bare column-name key: ``ORDER BY 2`` (select
position), ``ORDER BY p*-1`` (expression over source columns, projected
or not), ``ORDER BY count(*) DESC`` (aggregate rewritten to the
aggregated output column and dropped after the sort). Expression keys
materialize as one fused device pass each (temp column → sort → drop).
"""

import pytest

from sparkdq4ml_tpu import Frame


@pytest.fixture
def view(session):
    f = Frame({"g": [3.0, 1.0, 2.0, 1.0], "p": [10.0, 40.0, 20.0, 5.0]})
    f.create_or_replace_temp_view("ob")
    return f


class TestOrderByForms:
    def test_position(self, session, view):
        out = session.sql("SELECT g, p FROM ob ORDER BY 2")
        assert out.to_pydict()["p"].tolist() == [5.0, 10.0, 20.0, 40.0]

    def test_position_desc_multi(self, session, view):
        out = session.sql("SELECT g, p FROM ob ORDER BY 1 DESC, 2 ASC")
        d = out.to_pydict()
        assert d["g"].tolist() == [3.0, 2.0, 1.0, 1.0]
        assert d["p"].tolist() == [10.0, 20.0, 5.0, 40.0]

    def test_position_out_of_range(self, session, view):
        with pytest.raises(ValueError, match="position 3"):
            session.sql("SELECT g, p FROM ob ORDER BY 3")

    def test_position_cannot_reference_star(self, session, view):
        with pytest.raises(ValueError, match="reference"):
            session.sql("SELECT * FROM ob ORDER BY 1")

    def test_expression_key(self, session, view):
        out = session.sql("SELECT g, p FROM ob ORDER BY p * -1")
        assert out.to_pydict()["p"].tolist() == [40.0, 20.0, 10.0, 5.0]

    def test_expression_over_unselected_column(self, session, view):
        # SQL sorts before projecting: p+g is legal even when only g
        # survives the SELECT.
        out = session.sql("SELECT g FROM ob ORDER BY p + g DESC")
        assert out.to_pydict()["g"].tolist() == [1.0, 2.0, 3.0, 1.0]
        assert out.columns == ["g"]

    def test_expression_with_star(self, session, view):
        out = session.sql("SELECT * FROM ob ORDER BY p - g")
        assert out.to_pydict()["p"].tolist() == [5.0, 10.0, 20.0, 40.0]
        assert out.columns == ["g", "p"]  # temp sort column dropped

    def test_alias_key_still_works(self, session, view):
        out = session.sql("SELECT p * 2 AS dp FROM ob ORDER BY dp")
        assert out.to_pydict()["dp"].tolist() == [10.0, 20.0, 40.0, 80.0]


class TestNullOrdering:
    """NULLS FIRST/LAST + Spark's defaults (asc→first, desc→last)."""

    @pytest.fixture
    def nulled(self, session):
        f = Frame({"x": [3.0, float("nan"), 1.0, float("nan")],
                   "y": [1.0, 2.0, 3.0, 4.0]})
        f.create_or_replace_temp_view("nl")
        return f

    def _xs(self, out):
        return [None if v != v else v for v in out.to_pydict()["x"].tolist()]

    def test_defaults(self, session, nulled):
        assert self._xs(session.sql("SELECT x FROM nl ORDER BY x")) == \
            [None, None, 1.0, 3.0]
        assert self._xs(session.sql("SELECT x FROM nl ORDER BY x DESC")) == \
            [3.0, 1.0, None, None]

    def test_explicit_placement(self, session, nulled):
        assert self._xs(session.sql(
            "SELECT x FROM nl ORDER BY x NULLS LAST")) == \
            [1.0, 3.0, None, None]
        assert self._xs(session.sql(
            "SELECT x FROM nl ORDER BY x DESC NULLS FIRST")) == \
            [None, None, 3.0, 1.0]

    def test_expression_key_with_nulls(self, session, nulled):
        assert self._xs(session.sql(
            "SELECT x FROM nl ORDER BY x * 2 NULLS LAST")) == \
            [1.0, 3.0, None, None]

    def test_fluent_markers(self, session, nulled):
        f = nulled
        assert self._xs(f.sort(f["x"].asc_nulls_last()).select("x")) == \
            [1.0, 3.0, None, None]
        assert self._xs(f.sort(f["x"].desc_nulls_first()).select("x")) == \
            [None, None, 3.0, 1.0]

    def test_secondary_key_within_nulls(self, session, nulled):
        out = session.sql("SELECT x, y FROM nl ORDER BY x NULLS LAST, "
                          "y DESC")
        assert out.to_pydict()["y"].tolist() == [3.0, 1.0, 4.0, 2.0]

    def test_positional_with_nulls_rejected(self, session, nulled):
        with pytest.raises(ValueError, match="positional"):
            session.sql("SELECT x FROM nl ORDER BY 1 NULLS LAST")


class TestPostAggregateSelect:
    """Arithmetic over aggregates in the select list — computed on the
    aggregated frame from component aggregates (deduped by name)."""

    def test_grouped_spread(self, session, view):
        out = session.sql("SELECT g, max(p) - min(p) AS spread "
                          "FROM ob GROUP BY g")
        d = out.to_pydict()
        assert dict(zip(d["g"].tolist(), d["spread"].tolist())) == \
            {1.0: 35.0, 2.0: 0.0, 3.0: 0.0}

    def test_global_aggregate_expression(self, session, view):
        out = session.sql("SELECT max(p) - min(p) AS spread FROM ob")
        assert out.to_pydict()["spread"].tolist() == [35.0]

    def test_component_reuse_with_bare_agg(self, session, view):
        # sum(p)/count(*) shares nothing with avg(p) but both compute
        out = session.sql("SELECT sum(p) / count(*) AS m, avg(p) AS a "
                          "FROM ob")
        d = out.to_pydict()
        assert d["m"][0] == pytest.approx(d["a"][0])

    def test_scalar_on_left(self, session, view):
        out = session.sql("SELECT 100 * count(*) AS c FROM ob")
        assert out.to_pydict()["c"].tolist() == [400]

    def test_nested_in_scalar_fn(self, session, view):
        out = session.sql("SELECT abs(min(p) - 15) AS a FROM ob")
        assert out.to_pydict()["a"].tolist() == [10.0]

    def test_groupless_having(self, session, view):
        # Spark: HAVING without GROUP BY filters the global-agg row.
        assert session.sql("SELECT count(*) AS n FROM ob "
                           "HAVING count(*) > 2").to_pydict()["n"] \
            .tolist() == [4]
        assert session.sql("SELECT count(*) AS n FROM ob "
                           "HAVING count(*) > 9").count() == 0
        out = session.sql("SELECT avg(p) AS a FROM ob HAVING max(p) > 30")
        assert out.columns == ["a"]          # having's max(p) dropped
        assert out.count() == 1
        with pytest.raises(ValueError, match="HAVING requires"):
            session.sql("SELECT g FROM ob HAVING count(*) > 1")

    def test_order_and_having_interplay(self, session, view):
        out = session.sql("SELECT g, max(p) - min(p) AS spread FROM ob "
                          "GROUP BY g HAVING count(*) > 1 "
                          "ORDER BY spread DESC")
        d = out.to_pydict()
        assert d["g"].tolist() == [1.0]
        assert d["spread"].tolist() == [35.0]
        assert out.columns == ["g", "spread"]   # components dropped


class TestOrderByAggregates:
    def test_count_star_desc(self, session, view):
        out = session.sql(
            "SELECT g FROM ob GROUP BY g ORDER BY count(*) DESC")
        assert out.to_pydict()["g"].tolist() == [1.0, 2.0, 3.0]
        assert out.columns == ["g"]  # the helper count column is dropped

    def test_agg_not_in_select(self, session, view):
        out = session.sql("SELECT g, count(*) AS n FROM ob "
                          "GROUP BY g ORDER BY sum(p) DESC")
        d = out.to_pydict()
        assert d["g"].tolist() == [1.0, 2.0, 3.0]   # sums 45, 20, 10
        assert d["n"].tolist() == [2, 1, 1]
        assert out.columns == ["g", "n"]

    def test_agg_expression(self, session, view):
        out = session.sql("SELECT g FROM ob GROUP BY g "
                          "ORDER BY max(p) - min(p) DESC")
        assert out.to_pydict()["g"].tolist() == [1.0, 2.0, 3.0]

    def test_group_by_position(self, session, view):
        out = session.sql("SELECT cast(g as int) gi, count(*) AS n "
                          "FROM ob GROUP BY 1")
        d = out.to_pydict()
        assert d["gi"].tolist() == [1, 2, 3]
        assert d["n"].tolist() == [2, 1, 1]

    def test_group_by_expression(self, session, view):
        out = session.sql("SELECT cast(g as int) gi, count(*) AS n "
                          "FROM ob GROUP BY cast(g as int)")
        assert out.to_pydict()["n"].tolist() == [2, 1, 1]

    def test_group_by_expression_not_selected(self, session, view):
        out = session.sql("SELECT count(*) AS n FROM ob "
                          "GROUP BY cast(g as int)")
        assert out.to_pydict()["n"].tolist() == [2, 1, 1]
        assert out.columns == ["n"]  # temp group column dropped

    def test_group_by_position_rejects_star_and_agg(self, session, view):
        with pytest.raises(ValueError, match="aggregate"):
            session.sql("SELECT g, count(*) AS n FROM ob GROUP BY 2")
        with pytest.raises(ValueError, match="position 5"):
            session.sql("SELECT g FROM ob GROUP BY 5")

    def test_group_by_expr_with_order_by(self, session, view):
        out = session.sql("SELECT cast(g as int) gi, sum(p) AS sp "
                          "FROM ob GROUP BY 1 ORDER BY sp DESC")
        d = out.to_pydict()
        assert d["gi"].tolist() == [1, 2, 3]
        assert d["sp"].tolist() == [45.0, 20.0, 10.0]

    def test_agg_in_select_reused(self, session, view):
        # count(*) appears in SELECT; ORDER BY reuses that column rather
        # than computing a duplicate aggregate.
        out = session.sql("SELECT g, count(*) AS n FROM ob "
                          "GROUP BY g ORDER BY count(*) DESC, g ASC")
        d = out.to_pydict()
        assert d["n"].tolist() == [2, 1, 1]
        assert d["g"].tolist() == [1.0, 2.0, 3.0]


class TestOrderByAliasWithExpressionKeys:
    """Regression (ADVICE.md #1): mixing a SELECT alias with an expression
    or dropped-column key forces the post-projection sort, which used to
    drop the materialized __ord_N temps (and any dropped source column the
    sort still needed) in the projection and crash. The temps now survive
    the projection and drop after the sort."""

    @pytest.fixture
    def two_col(self, session):
        f = Frame({"a": [3.0, 1.0, 2.0, 4.0], "b": [0.0, 1.0, 0.0, 1.0]})
        f.create_or_replace_temp_view("oax")
        return f

    def test_alias_plus_expression_key(self, session, two_col):
        out = session.sql("SELECT a + b AS x FROM oax ORDER BY x, a % 2")
        assert out.columns == ["x"]
        assert out.to_pydict()["x"].tolist() == [2.0, 2.0, 3.0, 5.0]

    def test_alias_plus_expression_key_breaks_ties(self, session, two_col):
        # a%2 orders the x-ties: a=2 (even, 0) before a=1 (odd, 1)
        out = session.sql(
            "SELECT a + b AS x, a FROM oax ORDER BY x, a % 2")
        d = out.to_pydict()
        assert d["x"].tolist() == [2.0, 2.0, 3.0, 5.0]
        assert d["a"].tolist() == [2.0, 1.0, 3.0, 4.0]
        assert out.columns == ["x", "a"]   # no __ord leak

    def test_alias_nulls_last_plus_dropped_column(self, session):
        import numpy as np

        Frame({"a": [np.nan, 2.0, 1.0, 2.0],
               "b": [9.0, 4.0, 7.0, 3.0]}) \
            .create_or_replace_temp_view("oan")
        out = session.sql(
            "SELECT a AS x FROM oan ORDER BY x NULLS LAST, b")
        vals = out.to_pydict()["x"]
        assert vals[:3].tolist() == [1.0, 2.0, 2.0]
        assert np.isnan(vals[3])
        assert out.columns == ["x"]        # b kept for the sort, then dropped
        session.catalog.drop("oan")

    def test_distinct_with_hidden_key_raises_clearly(self, session, two_col):
        with pytest.raises(ValueError, match="DISTINCT"):
            session.sql("SELECT DISTINCT a AS x FROM oax ORDER BY x, b")
