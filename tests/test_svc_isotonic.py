"""LinearSVC + IsotonicRegression + small vector transformers
(ElementwiseProduct/VectorSlicer/DCT/FeatureHasher) — MLlib surface
shipped by the reference's mllib dependency (pom.xml:29-32). Oracles:
sklearn/scipy on the same data (SURVEY.md §4 pattern)."""

import numpy as np
import pytest

from conftest import assert_devices
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (DCT, ElementwiseProduct, FeatureHasher,
                                   IsotonicRegression,
                                   IsotonicRegressionModel, LinearSVC,
                                   LinearSVCModel, VectorAssembler,
                                   VectorSlicer)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def svc_frame(n=300, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = (X @ np.asarray([2.0, -1.0, 0.5]) + 0.3
         + 0.2 * rng.normal(size=n) > 0).astype(np.float64)
    cols = {f"x{j}": X[:, j] for j in range(3)}
    cols["label"] = y
    f = VectorAssembler([f"x{j}" for j in range(3)],
                        "features").transform(Frame(cols))
    return f, X, y


class TestLinearSVC:
    def test_separates_linear_data(self):
        f, X, y = svc_frame()
        model = LinearSVC(max_iter=200, reg_param=0.01).fit(f)
        pred = np.asarray(model.transform(f).to_pydict()["prediction"])
        assert np.mean(pred == y) > 0.93
        assert model.objective_history[-1] < model.objective_history[0]

    def test_sklearn_quality_parity(self):
        pytest.importorskip("sklearn")
        from sklearn.svm import LinearSVC as SkSVC

        f, X, y = svc_frame(seed=3)
        ours = LinearSVC(max_iter=300, reg_param=0.01).fit(f)
        pred = np.asarray(ours.transform(f).to_pydict()["prediction"])
        sk = SkSVC(C=100.0, max_iter=5000).fit(X, y)
        acc_ours = np.mean(pred == y)
        acc_sk = sk.score(X, y)
        assert acc_ours >= acc_sk - 0.03

    def test_raw_prediction_and_threshold(self):
        f, _, _ = svc_frame()
        model = LinearSVC(max_iter=50).fit(f)
        d = model.transform(f).to_pydict()
        raw = np.asarray(d["rawPrediction"])
        assert raw.shape[1] == 2
        np.testing.assert_allclose(raw[:, 0], -raw[:, 1], rtol=1e-6)
        # prediction == margin > threshold
        np.testing.assert_array_equal(
            np.asarray(d["prediction"]), (raw[:, 1] > 0).astype(np.float64))

    @pytest.mark.parametrize("labels", ["multiclass", "all_twos"])
    def test_rejects_nonbinary(self, labels):
        rng = np.random.default_rng(0)
        n = 50
        y = rng.integers(0, 3, size=n).astype(np.float64) \
            if labels == "multiclass" else np.full(n, 2.0)
        h = VectorAssembler(["x"], "features").transform(
            Frame({"x": rng.normal(size=n), "label": y}))
        with pytest.raises(ValueError, match="binary"):
            LinearSVC().fit(h)

    def test_sharded_equals_single(self):
        assert_devices(8)
        f, _, _ = svc_frame(seed=5)
        kw = dict(max_iter=60, reg_param=0.1)
        single = LinearSVC(**kw).fit(f, mesh=make_mesh(1))
        sharded = LinearSVC(**kw).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(sharded.coefficients, single.coefficients,
                                   rtol=1e-8, atol=1e-10)
        assert sharded.intercept == pytest.approx(single.intercept,
                                                  rel=1e-8, abs=1e-10)

    def test_masked_rows_excluded(self):
        """A fit on (clean rows + masked poisoned rows) must equal the fit
        on the clean subset alone — masked rows may not vote."""
        rng = np.random.default_rng(7)
        n = 120
        X = rng.normal(size=(n, 2))
        y = (X[:, 0] > 0).astype(np.float64)
        keep = np.ones(n, bool)
        keep[::7] = False
        Xp = X.copy()
        Xp[~keep] *= 1e6          # poisoned features on masked rows
        yp = y.copy()
        yp[~keep] = 1.0 - yp[~keep]

        def build(Xa, ya, mask=None):
            f = VectorAssembler(["x0", "x1"], "features").transform(
                Frame({"x0": Xa[:, 0], "x1": Xa[:, 1], "label": ya}))
            return f.filter(mask) if mask is not None else f

        kw = dict(max_iter=80, reg_param=0.05)
        m_masked = LinearSVC(**kw).fit(build(Xp, yp, keep))
        m_clean = LinearSVC(**kw).fit(build(X[keep], y[keep]))
        np.testing.assert_allclose(m_masked.coefficients,
                                   m_clean.coefficients,
                                   rtol=1e-6, atol=1e-9)
        assert m_masked.intercept == pytest.approx(m_clean.intercept,
                                                   rel=1e-6, abs=1e-9)

    def test_persistence_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, _, _ = svc_frame()
        model = LinearSVC(max_iter=30).fit(f)
        model.save(str(tmp_path / "svc"))
        loaded = load_stage(str(tmp_path / "svc"))
        assert isinstance(loaded, LinearSVCModel)
        np.testing.assert_array_equal(loaded.coefficients,
                                      model.coefficients)
        assert loaded.predict([1.0, 0.0, 0.0]) == \
            model.predict([1.0, 0.0, 0.0])


class TestIsotonicRegression:
    def test_matches_sklearn(self):
        pytest.importorskip("sklearn")
        from sklearn.isotonic import IsotonicRegression as SkIso

        rng = np.random.default_rng(0)
        x = rng.uniform(0, 10, size=200)
        y = np.sqrt(x) + 0.3 * rng.normal(size=200)
        f = Frame({"features": x, "label": y})
        ours = IsotonicRegression().fit(f)
        pred = np.asarray(ours.transform(f).to_pydict()["prediction"],
                          np.float64)
        sk = SkIso(out_of_bounds="clip").fit(x, y)
        np.testing.assert_allclose(pred, sk.predict(x), rtol=1e-6,
                                   atol=1e-8)

    def test_antitonic(self):
        rng = np.random.default_rng(1)
        x = rng.uniform(0, 5, size=100)
        y = -2 * x + 0.1 * rng.normal(size=100)
        f = Frame({"features": x, "label": y})
        m = IsotonicRegression(isotonic=False).fit(f)
        pred = np.asarray(m.transform(f).to_pydict()["prediction"])
        order = np.argsort(x)
        assert np.all(np.diff(pred[order]) <= 1e-9)   # non-increasing

    def test_weighted_and_duplicates(self):
        pytest.importorskip("sklearn")
        from sklearn.isotonic import IsotonicRegression as SkIso

        x = np.asarray([1.0, 1.0, 2.0, 3.0, 3.0, 4.0])
        y = np.asarray([2.0, 4.0, 1.0, 5.0, 7.0, 6.0])
        w = np.asarray([1.0, 3.0, 2.0, 1.0, 1.0, 2.0])
        f = Frame({"features": x, "label": y, "w": w})
        m = IsotonicRegression(weight_col="w").fit(f)
        sk = SkIso(out_of_bounds="clip").fit(x, y, sample_weight=w)
        for q in [0.5, 1.0, 2.5, 3.0, 10.0]:
            assert m.predict(q) == pytest.approx(float(sk.predict([q])[0]),
                                                 rel=1e-9)

    def test_constant_extrapolation(self):
        f = Frame({"features": np.asarray([1.0, 2.0, 3.0]),
                   "label": np.asarray([1.0, 2.0, 3.0])})
        m = IsotonicRegression().fit(f)
        assert m.predict(-5.0) == pytest.approx(1.0)
        assert m.predict(99.0) == pytest.approx(3.0)
        assert m.predict(1.5) == pytest.approx(1.5)   # linear interpolation

    def test_feature_index_on_vector(self):
        rng = np.random.default_rng(2)
        x = rng.uniform(0, 5, size=60)
        f = Frame({"a": rng.normal(size=60), "b": x,
                   "label": 2 * x})
        f = VectorAssembler(["a", "b"], "features").transform(f)
        m = IsotonicRegression(feature_index=1).fit(f)
        assert m.predict(2.0) == pytest.approx(4.0, rel=0.2)

    def test_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f = Frame({"features": np.asarray([1.0, 2.0, 3.0]),
                   "label": np.asarray([3.0, 1.0, 5.0])})
        m = IsotonicRegression().fit(f)
        m.save(str(tmp_path / "iso"))
        loaded = load_stage(str(tmp_path / "iso"))
        assert isinstance(loaded, IsotonicRegressionModel)
        assert loaded.predict(2.5) == m.predict(2.5)


class TestVectorTransformers:
    def _vec_frame(self, n=10, d=4, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d))
        cols = {f"x{j}": X[:, j] for j in range(d)}
        return (VectorAssembler([f"x{j}" for j in range(d)],
                                "features").transform(Frame(cols)), X)

    def test_elementwise_product(self):
        f, X = self._vec_frame()
        v = np.asarray([1.0, 0.0, -2.0, 0.5])
        out = ElementwiseProduct(v, "features", "o").transform(f)
        np.testing.assert_allclose(
            np.asarray(out.to_pydict()["o"], np.float64), X * v, rtol=1e-6)

    def test_vector_slicer(self):
        f, X = self._vec_frame()
        out = VectorSlicer([2, 0], "features", "o").transform(f)
        np.testing.assert_allclose(
            np.asarray(out.to_pydict()["o"], np.float64), X[:, [2, 0]],
            rtol=1e-6)
        with pytest.raises(ValueError, match="out of range"):
            VectorSlicer([9], "features", "o").transform(f)

    def test_dct_matches_scipy(self):
        pytest.importorskip("scipy")
        from scipy.fft import dct as sdct

        f, X = self._vec_frame(d=8)
        out = DCT(input_col="features", output_col="o").transform(f)
        ref = sdct(X, type=2, norm="ortho", axis=1)
        np.testing.assert_allclose(
            np.asarray(out.to_pydict()["o"], np.float64), ref,
            rtol=1e-5, atol=1e-7)

    def test_dct_inverse_roundtrip(self):
        f, X = self._vec_frame(d=8)
        fwd = DCT(input_col="features", output_col="y").transform(f)
        back = DCT(inverse=True, input_col="y", output_col="z").transform(fwd)
        np.testing.assert_allclose(
            np.asarray(back.to_pydict()["z"], np.float64), X,
            rtol=1e-5, atol=1e-7)

    def test_feature_hasher(self):
        from sparkdq4ml_tpu.models.text import _stable_hash

        cats = np.asarray(["a", "b", "a", None], object)
        nums = np.asarray([1.5, 2.0, -1.0, 3.0])
        f = Frame({"cat": cats, "num": nums})
        out = FeatureHasher(num_features=16, input_cols=["cat", "num"],
                            output_col="h").transform(f)
        M = np.asarray(out.to_pydict()["h"], np.float64)
        assert M.shape == (4, 16)
        # full naive reference (collision-aware): string col adds 1 at
        # hash(name=value), numeric col adds the value at hash(name)
        expected = np.zeros_like(M)
        for i, c in enumerate(cats):
            if c is not None:
                expected[i, _stable_hash(f"cat={c}", 16)] += 1.0
        for i, v in enumerate(nums):
            expected[i, _stable_hash("num", 16)] += v
        np.testing.assert_allclose(M, expected, rtol=1e-6)
