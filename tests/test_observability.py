"""Observability subsystem: spans, metrics, exporters, satellites (tier-1).

Covers the PR-2 tentpole (``utils.observability``) AND the telemetry seeds
PR 1 left untested: ``Counters`` under threads, ``snapshot``/``clear``
prefix semantics, ``PhaseTimer.report_pairs`` steady-only phases — plus the
acceptance criterion: the headline Lasso fit (dataset-full.csv, maxIter=40)
with ``spark.observability.enabled=true`` produces a valid nested Chrome
trace, one merged metrics registry (solver + ``recovery.*``), and a
Prometheus text dump that round-trips; with observability disabled, the
instrumented paths add zero host syncs and allocate no span objects.
"""

import json
import logging
import math
import os
import re
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdq4ml_tpu.utils import observability as obs
from sparkdq4ml_tpu.utils import profiling
from sparkdq4ml_tpu.utils.logging import configure_logging, format_kv
from sparkdq4ml_tpu.utils.profiling import Counters, PhaseTimer, timed

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _obs_clean():
    """Every test starts and ends with the tracer off and buffers empty."""
    obs.disable()
    obs.reset()
    profiling.counters.clear()
    yield
    obs.disable()
    obs.reset()
    profiling.counters.clear()


# ---------------------------------------------------------------------------
# PR-1 telemetry seeds (previously untested)
# ---------------------------------------------------------------------------


class TestCountersSeed:
    def test_concurrent_increments_are_lossless(self):
        c = Counters()
        n_threads, per_thread = 8, 500

        def worker():
            for _ in range(per_thread):
                c.increment("hot")
                c.increment("cold", by=2)

        threads = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get("hot") == n_threads * per_thread
        assert c.get("cold") == 2 * n_threads * per_thread

    def test_snapshot_prefix_filters(self):
        c = Counters()
        c.increment("recovery.retry")
        c.increment("recovery.fallback", by=3)
        c.increment("solver.fits")
        snap = c.snapshot("recovery.")
        assert snap == {"recovery.retry": 1, "recovery.fallback": 3}
        assert c.snapshot() == {"recovery.retry": 1, "recovery.fallback": 3,
                                "solver.fits": 1}
        # snapshot is a copy, not a view
        snap["recovery.retry"] = 99
        assert c.get("recovery.retry") == 1

    def test_clear_prefix_leaves_the_rest(self):
        c = Counters()
        c.increment("a.x")
        c.increment("a.y")
        c.increment("b.z")
        c.clear("a.")
        assert c.snapshot() == {"b.z": 1}
        c.clear()
        assert c.snapshot() == {}


class TestPhaseTimerSeed:
    def test_report_pairs_includes_steady_only_phases(self):
        t = PhaseTimer()
        with t.phase("cold_and_steady"):
            pass
        t.steady("cold_and_steady", lambda: jnp.zeros((2,)))
        t.steady("steady_only", lambda: jnp.ones((2,)))
        pairs = t.report_pairs()
        assert pairs["cold_and_steady"]["cold"] is not None
        assert pairs["cold_and_steady"]["steady"] is not None
        assert pairs["steady_only"]["cold"] is None
        assert pairs["steady_only"]["steady"] is not None

    def test_phase_accumulates_across_entries(self):
        t = PhaseTimer()
        with t.phase("p"):
            pass
        first = t.report()["p"]
        with t.phase("p"):
            pass
        assert t.report()["p"] >= first


# ---------------------------------------------------------------------------
# Satellites: format_kv zeros, timed sync, configure_logging force
# ---------------------------------------------------------------------------


class TestFormatKvZeros:
    def test_meaningful_zeros_survive(self):
        line = format_kv(retries=0, duration_ms=0.0, site="s")
        assert "retries=0" in line
        assert "duration_ms=0.0" in line

    def test_none_and_empty_string_still_elided(self):
        assert format_kv(a=None, b="", c=1) == "c=1"

    def test_quoting_unchanged(self):
        assert format_kv(msg="two words") == 'msg="two words"'

    def test_false_survives(self):
        # False is a value, not an absence (bool is an int subclass — the
        # old zero-ish elision dropped it too)
        assert "ok=False" in format_kv(ok=False)


class TestTimedSync:
    def test_sync_object_blocked(self, monkeypatch):
        blocked = []
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda t: blocked.append(t) or t)
        x = jnp.ones((4,))
        with timed("t", sync=x):
            pass
        assert len(blocked) == 1

    def test_sync_callable_evaluated_at_exit(self, monkeypatch):
        blocked = []
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda t: blocked.append(t) or t)
        out = {}
        with timed("t", sync=lambda: out["r"]):
            out["r"] = jnp.zeros((2,))
        assert blocked and blocked[0] is out["r"]

    def test_no_sync_means_no_block(self, monkeypatch):
        calls = []
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda t: calls.append(t) or t)
        with timed("t"):
            jnp.ones((2,))
        assert calls == []


class TestConfigureLoggingForce:
    def _with_root_handler(self):
        root = logging.getLogger()
        sentinel = logging.NullHandler()
        saved = list(root.handlers)
        return root, sentinel, saved

    def test_default_appends_when_handlers_exist(self):
        root, sentinel, saved = self._with_root_handler()
        try:
            root.addHandler(sentinel)
            configure_logging()
            assert sentinel in root.handlers  # caplog-style handler survives
            assert len(root.handlers) >= 2
        finally:
            root.handlers = saved

    def test_force_replaces(self):
        root, sentinel, saved = self._with_root_handler()
        try:
            root.addHandler(sentinel)
            configure_logging(force=True)
            assert sentinel not in root.handlers
            assert len(root.handlers) == 1
        finally:
            root.handlers = saved

    def test_repeated_calls_are_idempotent(self):
        root, sentinel, saved = self._with_root_handler()
        try:
            root.addHandler(sentinel)
            configure_logging()
            configure_logging()
            configure_logging()
            ours = [h for h in root.handlers
                    if getattr(h, "_sparkdq4ml", False)]
            assert len(ours) == 1           # no duplicate log lines
            assert sentinel in root.handlers
        finally:
            root.handlers = saved


# ---------------------------------------------------------------------------
# Tracer: spans, nesting, disabled-mode no-op
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_via_contextvar(self):
        obs.enable()
        with obs.span("outer", cat="t") as o:
            with obs.span("inner", cat="t") as i:
                pass
        spans = {s.name: s for s in obs.TRACER.spans()}
        assert spans["inner"].parent_id == spans["outer"].sid
        assert spans["outer"].parent_id is None
        assert o.dur_us >= i.dur_us

    def test_attributes_and_error_capture(self):
        obs.enable()
        with pytest.raises(ValueError):
            with obs.span("boom", cat="t", a=1) as s:
                s.set(b=2)
                raise ValueError("x")
        (sp,) = obs.TRACER.spans()
        assert sp.attrs["a"] == 1 and sp.attrs["b"] == 2
        assert sp.attrs["error"] == "ValueError"

    def test_begin_end_long_lived_span(self):
        obs.enable()
        root = obs.TRACER.begin("root", cat="session")
        with obs.span("child", cat="t"):
            pass
        assert any(s.dur_us is None for s in obs.TRACER.spans())  # still open
        obs.TRACER.end(root)
        spans = {s.name: s for s in obs.TRACER.spans()}
        assert spans["child"].parent_id == spans["root"].sid
        assert spans["root"].dur_us is not None

    def test_begun_root_survives_enclosing_span_exit(self):
        # begin() inside a `with span` must keep parenting AFTER that
        # span exits (the contextvar reset would otherwise orphan every
        # later span) — the ambient-root fallback.
        obs.enable()
        with obs.span("startup", cat="t"):
            root = obs.TRACER.begin("root", cat="session")
        with obs.span("later", cat="t"):
            pass
        obs.TRACER.end(root)
        spans = {s.name: s for s in obs.TRACER.spans()}
        assert spans["later"].parent_id == spans["root"].sid

    def test_worker_thread_spans_nest_under_begun_root(self):
        obs.enable()
        root = obs.TRACER.begin("root", cat="session")
        seen = {}

        def worker():
            with obs.span("in_thread", cat="t") as s:
                seen["parent"] = s.parent_id

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        obs.TRACER.end(root)
        assert seen["parent"] == root.sid

    def test_buffer_is_bounded(self):
        obs.enable(max_spans=5)
        for i in range(12):
            with obs.span(f"s{i}", cat="t"):
                pass
        assert len(obs.TRACER.spans()) == 5

    def test_span_durations_feed_histograms(self):
        obs.enable()
        with obs.span("x", cat="mycat"):
            pass
        snap = obs.METRICS.snapshot()
        assert snap["span_ms.mycat"]["count"] == 1

    def test_threads_get_independent_parents(self):
        obs.enable()
        seen = {}

        def worker():
            with obs.span("in_thread", cat="t") as s:
                seen["parent"] = s.parent_id

        with obs.span("main_span", cat="t"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        # a fresh thread starts a fresh context: no cross-thread parent
        assert seen["parent"] is None


class TestDisabledNoOp:
    def test_span_returns_shared_singleton(self):
        a = obs.span("x", cat="t")
        b = obs.TRACER.span("y")
        assert a is b is obs._NOOP          # no allocation, one flag check
        assert obs.current_span() is obs._NOOP
        with a as s:
            s.set(anything=1)               # all methods are no-ops
        assert obs.TRACER.spans() == []

    def test_frame_ops_record_nothing_and_never_sync(self, monkeypatch):
        from sparkdq4ml_tpu.frame.frame import Frame

        syncs = []
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda t: syncs.append(1) or t)
        f = Frame({"a": [1.0, 2.0, 3.0], "b": [4.0, 5.0, 6.0]})
        g = f.with_column("c", f["a"] + f["b"]).filter(f["a"] > 1).select(
            "a", "c")
        assert g.columns == ["a", "c"]
        assert obs.TRACER.spans() == []
        assert syncs == []                  # zero additional host syncs

    def test_disabled_fit_adds_no_spans_or_syncs(self, monkeypatch):
        from sparkdq4ml_tpu.frame.frame import Frame
        from sparkdq4ml_tpu.models.regression import LinearRegression

        f = Frame({"features": np.arange(8.0)[:, None],
                   "label": 2.0 * np.arange(8.0) + 1.0})
        syncs = []
        orig = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda t: syncs.append(1) or orig(t))
        m = LinearRegression(max_iter=5).fit(f, mesh=None)
        assert np.isfinite(m.coefficients).all()
        assert obs.TRACER.spans() == []
        # Exactly ONE sync, and it predates this subsystem: the recovery
        # validator blocks inside the attempt (utils/recovery.py,
        # resilient_call) so non-finite results are caught while retries
        # can still help. Observability-disabled mode adds zero on top.
        assert len(syncs) == 1


# ---------------------------------------------------------------------------
# Metrics registry: gauges + histograms
# ---------------------------------------------------------------------------


class TestMetricsRegistry:
    def test_gauge_set_get(self):
        obs.METRICS.set_gauge("g", 3.5)
        assert obs.METRICS.get_gauge("g") == 3.5
        obs.METRICS.set_gauge("g", 1.0)     # gauges move both ways
        assert obs.METRICS.snapshot()["g"] == 1.0

    def test_histogram_fixed_buckets_cumulative(self):
        h = obs.Histogram("h", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["buckets"][1.0] == 1
        assert snap["buckets"][10.0] == 2
        assert snap["buckets"][100.0] == 3
        assert snap["buckets"][float("inf")] == 4
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(555.5)

    def test_boundary_value_lands_in_its_bucket(self):
        h = obs.Histogram("h", buckets=(1.0, 10.0))
        h.observe(10.0)                     # le semantics: 10.0 ≤ 10.0
        assert h.snapshot()["buckets"][10.0] == 1
        assert h.snapshot()["buckets"][1.0] == 0

    def test_registry_histogram_get_or_create(self):
        h1 = obs.METRICS.histogram("lat")
        h2 = obs.METRICS.histogram("lat")
        assert h1 is h2

    def test_merged_snapshot_spans_all_three_kinds(self):
        profiling.counters.increment("solver.fits")
        obs.METRICS.set_gauge("mesh.devices", 8)
        obs.METRICS.observe("lat_ms", 3.0)
        snap = obs.metrics_snapshot()
        assert snap["solver.fits"] == 1
        assert snap["mesh.devices"] == 8.0
        assert snap["lat_ms"]["count"] == 1


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------


def _parse_prometheus(text: str) -> dict:
    """Minimal Prometheus text-format parser: {name or name{labels}: value}.
    Raises on any malformed line — the round-trip assertion."""
    out = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)"
                            r"|HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S.*)$",
                            line), line
            continue
        m = line_re.match(line)
        assert m, f"malformed Prometheus line: {line!r}"
        val = float(m.group(3)) if m.group(3) != "+Inf" else math.inf
        out[m.group(1) + (m.group(2) or "")] = val
    return out


class TestExporters:
    def test_chrome_trace_shape(self):
        obs.enable()
        with obs.span("parent", cat="sql", q=1):
            with obs.span("child", cat="frame"):
                pass
        doc = obs.chrome_trace()
        all_events = doc["traceEvents"]
        # span events; counter ("C") resource tracks ride alongside
        events = [e for e in all_events if e["ph"] == "X"]
        assert {e["name"] for e in events} == {"parent", "child"}
        for e in all_events:
            assert e["ph"] in ("X", "C")
        for e in events:
            assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
            assert e["dur"] >= 1
        child = next(e for e in events if e["name"] == "child")
        parent = next(e for e in events if e["name"] == "parent")
        assert child["args"]["parent_id"] == parent["args"]["span_id"]
        # time containment (same thread ⇒ chrome nests by ts/dur)
        assert parent["ts"] <= child["ts"]
        assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1
        json.dumps(doc)                      # serializable

    def test_dump_chrome_trace_roundtrip(self, tmp_path):
        obs.enable()
        with obs.span("a", cat="t"):
            pass
        p = obs.dump_chrome_trace(str(tmp_path / "trace.json"))
        doc = json.load(open(p))
        assert doc["traceEvents"][0]["name"] == "a"

    def test_trace_report_tree(self):
        obs.enable()
        with obs.span("outer", cat="t"):
            with obs.span("inner", cat="t", rows=3):
                pass
        rep = obs.trace_report()
        lines = rep.splitlines()
        assert lines[0].startswith("outer")
        assert lines[1].startswith("  inner")
        assert "rows=3" in lines[1]

    def test_prometheus_roundtrip(self):
        profiling.counters.increment("recovery.retry", by=2)
        obs.METRICS.set_gauge("mesh.devices", 8)
        obs.METRICS.observe("lat_ms", 7.0, buckets=(5.0, 50.0))
        parsed = _parse_prometheus(obs.prometheus_text())
        assert parsed["sparkdq4ml_recovery_retry"] == 2
        assert parsed["sparkdq4ml_mesh_devices"] == 8
        assert parsed['sparkdq4ml_lat_ms_bucket{le="5"}'] == 0
        assert parsed['sparkdq4ml_lat_ms_bucket{le="50"}'] == 1
        assert parsed['sparkdq4ml_lat_ms_bucket{le="+Inf"}'] == 1
        assert parsed["sparkdq4ml_lat_ms_count"] == 1
        assert parsed["sparkdq4ml_lat_ms_sum"] == 7.0

    def test_logfmt_span_lines(self, caplog):
        obs.enable(log_spans=True)
        with caplog.at_level(logging.DEBUG,
                             logger="sparkdq4ml_tpu.observability"):
            with obs.span("op", cat="frame", rows=4):
                pass
        line = next(r.getMessage() for r in caplog.records
                    if "name=op" in r.getMessage())
        assert "cat=frame" in line and "rows=4" in line
        assert "dur_ms=" in line


# ---------------------------------------------------------------------------
# Wiring: SQL plan spans, parallel gram, session surface
# ---------------------------------------------------------------------------


class TestSqlSpans:
    def test_query_span_carries_text_plan_and_rows(self, session):
        from sparkdq4ml_tpu.frame.frame import Frame

        obs.enable()
        Frame({"a": [1.0, 2.0, 3.0]}).create_or_replace_temp_view("t")
        out = session.sql("SELECT a FROM t WHERE a > 1 ORDER BY a LIMIT 5")
        assert out.count() == 2
        sql_spans = [s for s in obs.TRACER.spans() if s.name == "sql.query"]
        assert len(sql_spans) == 1
        s = sql_spans[0]
        assert "SELECT a FROM t" in s.attrs["query"]
        # Project+Filter print as one FusedStage when the pipeline
        # compiler is on (the default) — the stage boundary marker; the
        # ORDER BY prints as DeviceSort under grouped execution (PR 4)
        assert s.attrs["plan"] == (
            "Limit[5] <- DeviceSort[1] <- FusedStage(Project[1] <- Filter) "
            "<- Scan[t]")
        assert s.attrs["rows_out"] == out.num_slots
        # frame ops executed by the query nest under it
        frame_children = [c for c in obs.TRACER.spans()
                          if c.cat == "frame" and c.parent_id == s.sid]
        assert frame_children

    def test_ddl_spans(self, session):
        from sparkdq4ml_tpu.frame.frame import Frame

        obs.enable()
        Frame({"a": [1.0]}).create_or_replace_temp_view("src")
        session.sql("CREATE OR REPLACE TEMP VIEW v AS SELECT a FROM src")
        session.sql("DROP VIEW v")
        plans = [s.attrs.get("plan") for s in obs.TRACER.spans()
                 if s.name == "sql.query"]
        assert "CreateView[v]" in plans
        assert "DropView[v]" in plans


class TestParallelSpans:
    def test_sharded_gram_span_and_counters(self, session):
        from sparkdq4ml_tpu.parallel.distributed import compute_gram

        obs.enable()
        n0 = profiling.counters.get("parallel.psum_dispatches")
        X = np.arange(16.0).reshape(8, 2)
        y = np.arange(8.0)
        mask = np.ones(8, bool)
        A = compute_gram(X, y, mask, mesh=session.mesh)
        assert np.asarray(A).shape == (4, 4)
        assert profiling.counters.get("parallel.psum_dispatches") == n0 + 1
        spans = {s.name: s for s in obs.TRACER.spans()}
        outer, inner = spans["parallel.gram"], spans["parallel.gram_shard"]
        assert inner.parent_id == outer.sid
        assert outer.attrs["shards"] == session.num_devices
        assert inner.attrs["rows_per_shard"] == 8 // session.num_devices
        assert inner.attrs["device"] == "cpu"

    def test_mesh_gauge_set(self, session):
        assert obs.METRICS.get_gauge("mesh.devices") == session.num_devices


class TestSessionSurface:
    CONF = {"spark.backend.probe": "off",
            "spark.compilation.cache": "off"}

    def _session(self, **conf):
        from sparkdq4ml_tpu import TpuSession

        b = TpuSession.builder().app_name("obs").master("local[*]")
        for k, v in {**self.CONF, **conf}.items():
            b = b.config(k, v)
        return b.get_or_create()

    def test_conf_enables_and_opens_root_span(self):
        s = self._session(**{"spark.observability.enabled": "true"})
        try:
            assert obs.enabled()
            roots = [sp for sp in obs.TRACER.spans() if sp.name == "session"]
            assert len(roots) == 1
            assert roots[0].attrs["app"] == "obs"
            assert roots[0].attrs["devices"] == s.num_devices
            assert roots[0].dur_us is None          # still open
        finally:
            s.stop()
        assert not obs.enabled()                    # session-scoped opt-in
        roots = [sp for sp in obs.TRACER.spans() if sp.name == "session"]
        assert roots[0].dur_us is not None          # closed by stop()

    def test_env_var_enables(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        s = self._session()
        try:
            assert obs.enabled()
        finally:
            s.stop()

    def test_env_off_spellings_do_not_enable(self, monkeypatch):
        for off in ("off", "False", "no", "0"):
            monkeypatch.setenv(obs.ENV_VAR, off)
            s = self._session()
            try:
                assert not obs.enabled(), off
            finally:
                s.stop()

    def test_conf_off_beats_env(self, monkeypatch):
        monkeypatch.setenv(obs.ENV_VAR, "1")
        s = self._session(**{"spark.observability.enabled": "false"})
        try:
            assert not obs.enabled()
        finally:
            s.stop()

    def test_default_is_disabled(self):
        s = self._session()
        try:
            assert not obs.enabled()
            assert s.trace_report() == ""
        finally:
            s.stop()

    def test_metrics_and_text_surface(self):
        s = self._session()
        try:
            profiling.counters.increment("solver.fits")
            assert s.metrics()["solver.fits"] == 1
            assert "sparkdq4ml_solver_fits 1" in s.metrics_text()
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# Acceptance: headline Lasso fit, end to end
# ---------------------------------------------------------------------------


class TestHeadlineAcceptance:
    def test_lasso_fit_full_observability(self, tmp_path):
        from sparkdq4ml_tpu import TpuSession
        from sparkdq4ml_tpu.models.regression import LinearRegression
        from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

        RECOVERY_LOG.record("obs_test", "retry")  # recovery.* pre-seeded
        session = (TpuSession.builder().app_name("headline")
                   .master("local[*]")
                   .config("spark.backend.probe", "off")
                   .config("spark.compilation.cache", "off")
                   .config("spark.observability.enabled", "true")
                   .get_or_create())
        try:
            df = run_dq_pipeline(session, dataset_path("full"))
            df = prepare_features(df)
            lr = (LinearRegression().setMaxIter(40).setRegParam(0.01)
                  .setElasticNetParam(1.0))
            model = lr.fit(df)
            assert np.isfinite(model.coefficients).all()

            # (a) valid Chrome trace with nested session/query/fit/solver
            path = session.dump_trace(str(tmp_path / "lasso_trace.json"))
            doc = json.load(open(path))
            events = doc["traceEvents"]
            by_name = {}
            for e in events:
                by_name.setdefault(e["name"], []).append(e)
            assert "session" in by_name
            assert "sql.query" in by_name
            assert "fit.linear_regression" in by_name
            assert "fit.solve" in by_name
            root_id = by_name["session"][0]["args"]["span_id"]
            assert all(e["args"]["parent_id"] == root_id
                       for e in by_name["sql.query"])
            fit = by_name["fit.linear_regression"][0]
            assert fit["args"]["parent_id"] == root_id
            solve = by_name["fit.solve"][0]
            assert solve["args"]["parent_id"] == fit["args"]["span_id"]
            assert fit["args"]["solver"] == "fista"       # L1 ⇒ proximal
            assert fit["args"]["compile"] in ("miss", "hit")
            assert fit["args"]["iterations"] >= 1
            assert math.isfinite(fit["args"]["objective_final"])
            q = by_name["sql.query"][0]["args"]
            assert "plan" in q and "Scan[price]" in q["plan"]

            # (b) one merged registry: solver counters AND recovery.*
            met = session.metrics()
            assert met["solver.fits"] >= 1
            assert met["solver.iterations"] >= 1
            assert met["recovery.retry"] >= 1
            assert met["mesh.devices"] == session.num_devices
            assert met["span_ms.fit"]["count"] >= 1

            # (c) Prometheus text round-trips through the parser
            parsed = _parse_prometheus(session.metrics_text())
            assert parsed["sparkdq4ml_solver_fits"] >= 1
            assert parsed["sparkdq4ml_recovery_retry"] >= 1
            buckets = [k for k in parsed
                       if k.startswith("sparkdq4ml_span_ms_fit_bucket")]
            assert buckets
            # cumulative monotone buckets
            vals = [parsed[k] for k in sorted(
                buckets, key=lambda k: math.inf if "+Inf" in k
                else float(k.split('le="')[1].rstrip('"}')))]
            assert vals == sorted(vals)
        finally:
            session.stop()


# ---------------------------------------------------------------------------
# CI/tooling satellite: logger-namespace lint
# ---------------------------------------------------------------------------


class TestLoggerNamespaceLint:
    REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    SCRIPT = os.path.join(REPO, "scripts", "check_logger_ns.py")

    def test_framework_is_clean(self):
        proc = subprocess.run([sys.executable, self.SCRIPT, self.REPO],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_lint_catches_offender(self, tmp_path):
        pkg = tmp_path / "sparkdq4ml_tpu"
        pkg.mkdir()
        (pkg / "bad.py").write_text(
            'import logging\nlog = logging.getLogger("rogue.ns")\n')
        (pkg / "wrapped.py").write_text(
            'import logging\nlog = logging.getLogger(\n'
            '    "rogue.wrapped")\n')
        (pkg / "aliased.py").write_text(
            'from logging import getLogger\nlog = getLogger("rogue")\n')
        (pkg / "good.py").write_text(
            'import logging\n'
            'a = logging.getLogger("sparkdq4ml_tpu.good")\n'
            'b = logging.getLogger(__name__)\n'
            'c = logging.getLogger("jax")  # logger-ns: ok\n'
            '"""docstring mentioning logging.getLogger("rogue") is text"""\n'
            '# comment: logging.getLogger("rogue") never executes\n'
            'mylogging = logging\n'
            's = "logging.getLogger(\'rogue\')"\n')
        proc = subprocess.run([sys.executable, self.SCRIPT, str(tmp_path)],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "bad.py:2" in proc.stdout
        assert "wrapped.py:2" in proc.stdout       # line-wrapped call caught
        assert "aliased.py:1" in proc.stdout       # bare-name import caught
        assert "good.py" not in proc.stdout
