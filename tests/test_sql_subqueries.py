"""CTEs, derived tables, and uncorrelated subqueries.

The reference app's SQL surface is two flat SELECTs
(`DataQuality4MachineLearningApp.java:77-78,89-90`); WITH / derived
tables / IN-EXISTS-scalar subqueries are the grammar closure a Spark
user expects from the same engine. All subqueries here are uncorrelated
(resolved against the catalog before the outer query evaluates — one
extra fused XLA program per subquery, zero per-row interpretation).
"""

import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame


@pytest.fixture
def views(session):
    t = Frame({"guest": [2.0, 10.0, 14.0, 20.0],
               "price": [30.0, 95.0, 120.0, 200.0]})
    t.create_or_replace_temp_view("t")
    g = Frame({"guest": [10.0, 20.0], "tag": [1.0, 2.0]})
    g.create_or_replace_temp_view("g")
    return t, g


class TestCte:
    def test_single_cte(self, session, views):
        out = session.sql("WITH big AS (SELECT guest, price FROM t "
                          "WHERE price > 90) SELECT count(*) AS n FROM big")
        assert out.to_pydict()["n"][0] == 3

    def test_chained_ctes_reference_earlier(self, session, views):
        out = session.sql(
            "WITH a AS (SELECT guest FROM t WHERE price > 90), "
            "b AS (SELECT guest FROM a WHERE guest > 12) "
            "SELECT count(*) AS n FROM b")
        assert out.to_pydict()["n"][0] == 2

    def test_cte_shadows_catalog_view(self, session, views):
        # A CTE named like an existing view wins inside the statement...
        out = session.sql("WITH t AS (SELECT guest FROM g) "
                          "SELECT count(*) AS n FROM t")
        assert out.to_pydict()["n"][0] == 2
        # ...and the catalog view is untouched afterwards.
        assert session.sql("SELECT count(*) AS n FROM t").to_pydict()["n"][0] == 4

    def test_cte_with_union_inside(self, session, views):
        out = session.sql(
            "WITH u AS (SELECT guest FROM t UNION ALL SELECT guest FROM g) "
            "SELECT count(*) AS n FROM u")
        assert out.to_pydict()["n"][0] == 6

    def test_column_named_with_still_works(self, session):
        # WITH is contextual: only the first token starts a CTE clause.
        f = Frame({"with": [1.0, 2.0]})
        f.create_or_replace_temp_view("w")
        # Quoting isn't supported, but selecting the column is fine.
        assert session.sql("SELECT count(*) AS n FROM w").to_pydict()["n"][0] == 2


class TestDerivedTables:
    def test_from_subquery(self, session, views):
        out = session.sql("SELECT avg(price) AS ap FROM "
                          "(SELECT price FROM t WHERE guest > 5) sub")
        assert out.to_pydict()["ap"][0] == pytest.approx(138.3333, rel=1e-4)

    def test_alias_optional(self, session, views):
        out = session.sql("SELECT count(*) AS n FROM "
                          "(SELECT guest FROM t WHERE price > 90)")
        assert out.to_pydict()["n"][0] == 3

    def test_join_derived_table(self, session, views):
        out = session.sql("SELECT price, tag FROM t JOIN "
                          "(SELECT guest, tag FROM g) x USING (guest)")
        d = out.to_pydict()
        assert sorted(d["price"].tolist()) == [95.0, 200.0]
        assert sorted(d["tag"].tolist()) == [1.0, 2.0]

    def test_union_inside_derived(self, session, views):
        out = session.sql("SELECT count(*) AS n FROM "
                          "(SELECT guest FROM t UNION ALL SELECT guest FROM g) u")
        assert out.to_pydict()["n"][0] == 6

    def test_nested_derived(self, session, views):
        out = session.sql(
            "SELECT count(*) AS n FROM (SELECT guest FROM "
            "(SELECT guest, price FROM t WHERE price > 90) i "
            "WHERE guest > 12) o")
        assert out.to_pydict()["n"][0] == 2


class TestScalarSubquery:
    def test_where_above_average(self, session, views):
        out = session.sql(
            "SELECT guest FROM t WHERE price > (SELECT avg(price) FROM t)")
        assert out.to_pydict()["guest"].tolist() == [14.0, 20.0]

    def test_select_list(self, session, views):
        out = session.sql(
            "SELECT guest, (SELECT max(price) FROM t) AS mp FROM t LIMIT 2")
        assert out.to_pydict()["mp"].tolist() == [200.0, 200.0]

    def test_empty_result_is_null(self, session, views):
        # Spark: scalar subquery over zero rows yields NULL; NULL
        # comparisons are never true.
        out = session.sql("SELECT guest FROM t WHERE price > "
                          "(SELECT avg(price) FROM t WHERE guest > 100)")
        assert out.count() == 0

    def test_multi_row_is_error(self, session, views):
        with pytest.raises(ValueError, match="more than one row"):
            session.sql("SELECT guest FROM t WHERE price > "
                        "(SELECT price FROM t)")

    def test_multi_column_is_error(self, session, views):
        with pytest.raises(ValueError, match="exactly one column"):
            session.sql("SELECT guest FROM t WHERE price > "
                        "(SELECT guest, price FROM t)")

    def test_subquery_in_predicate_positions(self, session, views):
        # Placeholders are Expr subclasses: IS NULL / BETWEEN compose.
        assert session.sql("SELECT guest FROM t WHERE "
                           "(SELECT max(price) FROM t) IS NOT NULL").count() == 4
        assert session.sql("SELECT guest FROM t WHERE "
                           "(SELECT max(price) FROM t) "
                           "BETWEEN 150 AND 250").count() == 4
        assert session.sql("SELECT guest FROM t WHERE "
                           "(SELECT max(price) FROM t) "
                           "BETWEEN 0 AND 100").count() == 0

    def test_unresolved_placeholder_eval_is_clear_error(self, session, views):
        from sparkdq4ml_tpu.sql.parser import ScalarSubquery, parse
        t, _ = views
        ph = ScalarSubquery(parse("SELECT max(price) FROM t"))
        with pytest.raises(ValueError, match="unresolved subquery"):
            t.filter(ph)


class TestInSubquery:
    def test_in(self, session, views):
        out = session.sql(
            "SELECT price FROM t WHERE guest IN (SELECT guest FROM g)")
        assert out.to_pydict()["price"].tolist() == [95.0, 200.0]

    def test_not_in(self, session, views):
        out = session.sql(
            "SELECT price FROM t WHERE guest NOT IN (SELECT guest FROM g)")
        assert out.to_pydict()["price"].tolist() == [30.0, 120.0]

    def test_in_literal_list_still_works(self, session, views):
        out = session.sql("SELECT price FROM t WHERE guest IN (2, 14)")
        assert out.to_pydict()["price"].tolist() == [30.0, 120.0]

    def test_one_column_enforced(self, session, views):
        with pytest.raises(ValueError, match="exactly one"):
            session.sql("SELECT price FROM t WHERE guest IN "
                        "(SELECT guest, tag FROM g)")

    def test_matches_fluent_isin(self, session, views):
        t, g = views
        sql = session.sql(
            "SELECT price FROM t WHERE guest IN (SELECT guest FROM g)")
        vals = [float(v) for v in g.to_pydict()["guest"]]
        fluent = t.filter(t["guest"].isin(vals)).select("price")
        np.testing.assert_allclose(sql.to_pydict()["price"],
                                   fluent.to_pydict()["price"])


class TestCorrelatedSubqueries:
    """Equi-correlated EXISTS/IN decorrelate into semi/anti joins — the
    rewrite Spark itself performs. Non-equi correlation raises with the
    rewrite named."""

    def test_correlated_exists(self, session, views):
        out = session.sql("SELECT price FROM t WHERE EXISTS "
                          "(SELECT 1 FROM g WHERE g.guest = t.guest)")
        assert sorted(out.to_pydict()["price"].tolist()) == [95.0, 200.0]

    def test_correlated_not_exists(self, session, views):
        out = session.sql("SELECT price FROM t WHERE NOT EXISTS "
                          "(SELECT 1 FROM g WHERE g.guest = t.guest)")
        assert sorted(out.to_pydict()["price"].tolist()) == [30.0, 120.0]

    def test_correlated_exists_with_inner_filter(self, session, views):
        out = session.sql("SELECT price FROM t WHERE EXISTS "
                          "(SELECT 1 FROM g WHERE g.guest = t.guest "
                          "AND g.tag > 1)")
        assert out.to_pydict()["price"].tolist() == [200.0]

    def test_correlated_exists_composes_with_outer_predicates(
            self, session, views):
        out = session.sql("SELECT price FROM t WHERE EXISTS "
                          "(SELECT 1 FROM g WHERE g.guest = t.guest) "
                          "AND price < 100")
        assert out.to_pydict()["price"].tolist() == [95.0]

    def test_correlated_in(self, session, views):
        out = session.sql("SELECT price FROM t WHERE guest IN "
                          "(SELECT guest FROM g WHERE g.guest = t.guest "
                          "AND tag > 1)")
        assert out.to_pydict()["price"].tolist() == [200.0]

    def test_correlated_not_in(self, session, views):
        out = session.sql("SELECT price FROM t WHERE guest NOT IN "
                          "(SELECT guest FROM g WHERE g.guest = t.guest)")
        assert sorted(out.to_pydict()["price"].tolist()) == [30.0, 120.0]

    def test_agrees_with_explicit_semi_join(self, session, views):
        corr = session.sql("SELECT price FROM t WHERE EXISTS "
                           "(SELECT 1 FROM g WHERE g.guest = t.guest)")
        semi = session.sql(
            "SELECT price FROM t LEFT SEMI JOIN g USING (guest)")
        assert sorted(corr.to_pydict()["price"].tolist()) == \
            sorted(semi.to_pydict()["price"].tolist())

    def test_non_equi_correlation_gets_clear_error(self, session, views):
        with pytest.raises(ValueError, match="non-equi"):
            session.sql("SELECT guest FROM t WHERE EXISTS "
                        "(SELECT 1 FROM g WHERE g.tag > t.guest)")

    def test_correlated_grouped_subquery_unsupported(self, session, views):
        with pytest.raises(ValueError, match="set ops, grouping"):
            session.sql("SELECT guest FROM t WHERE EXISTS "
                        "(SELECT count(*) FROM g WHERE g.guest = t.guest "
                        "GROUP BY tag)")

    def test_create_temp_view_raises_on_duplicate(self, session, views):
        t, _ = views
        t.create_temp_view("ctv_once")
        try:
            with pytest.raises(ValueError, match="already exists"):
                t.create_temp_view("ctv_once")
        finally:
            session.catalog.drop("ctv_once")


class TestSetOpsAndOffset:
    """INTERSECT / EXCEPT set operators and LIMIT ... OFFSET."""

    @pytest.fixture
    def ab(self, session):
        Frame({"x": [1.0, 2.0, 3.0, 2.0]}).create_or_replace_temp_view("sa")
        Frame({"x": [2.0, 3.0, 5.0]}).create_or_replace_temp_view("sb")

    def test_intersect(self, session, ab):
        out = session.sql("SELECT x FROM sa INTERSECT SELECT x FROM sb")
        assert sorted(out.to_pydict()["x"].tolist()) == [2.0, 3.0]

    def test_except(self, session, ab):
        out = session.sql("SELECT x FROM sa EXCEPT SELECT x FROM sb")
        assert out.to_pydict()["x"].tolist() == [1.0]

    def test_left_assoc_chain(self, session, ab):
        out = session.sql("SELECT x FROM sa UNION ALL SELECT x FROM sb "
                          "EXCEPT SELECT x FROM sb")
        assert out.to_pydict()["x"].tolist() == [1.0]

    def test_limit_offset(self, session, ab):
        out = session.sql("SELECT x FROM sa ORDER BY x LIMIT 2 OFFSET 1")
        assert out.to_pydict()["x"].tolist() == [2.0, 2.0]

    def test_offset_alone(self, session, ab):
        out = session.sql("SELECT x FROM sa ORDER BY x OFFSET 2")
        assert out.to_pydict()["x"].tolist() == [2.0, 3.0]

    def test_offset_with_star(self, session, ab):
        out = session.sql("SELECT * FROM sa ORDER BY x LIMIT 1 OFFSET 3")
        assert out.to_pydict()["x"].tolist() == [3.0]

    def test_fluent_offset(self, session):
        assert Frame({"x": [1.0, 2.0, 3.0]}).offset(1) \
            .to_pydict()["x"].tolist() == [2.0, 3.0]

    def test_intersect_matches_fluent(self, session, ab):
        sql = session.sql("SELECT x FROM sa INTERSECT SELECT x FROM sb")
        a = Frame({"x": [1.0, 2.0, 3.0, 2.0]})
        b = Frame({"x": [2.0, 3.0, 5.0]})
        fluent = a.intersect(b)
        assert sorted(sql.to_pydict()["x"].tolist()) == \
            sorted(fluent.to_pydict()["x"].tolist())


class TestViewDdl:
    """CREATE [OR REPLACE] TEMP VIEW ... AS / DROP VIEW [IF EXISTS]."""

    def test_create_and_query(self, session, views):
        r = session.sql("CREATE OR REPLACE TEMP VIEW big AS "
                        "SELECT guest FROM t WHERE price > 90")
        assert r.count() == 0 and r.columns == []   # Spark DDL shape
        assert session.sql("SELECT count(*) AS n FROM big") \
            .to_pydict()["n"][0] == 3
        session.catalog.drop("big")

    def test_create_with_cte_body(self, session, views):
        session.sql("CREATE TEMP VIEW v2 AS WITH a AS "
                    "(SELECT guest FROM t WHERE price > 90) "
                    "SELECT guest FROM a WHERE guest > 12")
        assert session.sql("SELECT count(*) AS n FROM v2") \
            .to_pydict()["n"][0] == 2
        session.catalog.drop("v2")

    def test_drop_view(self, session, views):
        session.sql("CREATE TEMP VIEW dv AS SELECT guest FROM t")
        session.sql("DROP VIEW dv")
        assert not session.catalog.table_exists("dv")

    def test_drop_missing(self, session, views):
        session.sql("DROP VIEW IF EXISTS nope")   # silent
        with pytest.raises(KeyError):
            session.sql("DROP VIEW nope")


class TestSemiAntiJoin:
    """LEFT SEMI / LEFT ANTI — the join forms Spark rewrites correlated
    EXISTS / NOT EXISTS into; here they are first-class SQL."""

    def test_left_semi(self, session, views):
        out = session.sql("SELECT price FROM t LEFT SEMI JOIN g USING (guest)")
        assert sorted(out.to_pydict()["price"].tolist()) == [95.0, 200.0]

    def test_left_anti(self, session, views):
        out = session.sql("SELECT price FROM t LEFT ANTI JOIN g USING (guest)")
        assert sorted(out.to_pydict()["price"].tolist()) == [30.0, 120.0]

    def test_semi_matches_in_subquery(self, session, views):
        semi = session.sql(
            "SELECT price FROM t LEFT SEMI JOIN g USING (guest)")
        inq = session.sql(
            "SELECT price FROM t WHERE guest IN (SELECT guest FROM g)")
        assert sorted(semi.to_pydict()["price"].tolist()) == \
            sorted(inq.to_pydict()["price"].tolist())

    def test_semi_join_derived_table(self, session, views):
        out = session.sql("SELECT price FROM t LEFT SEMI JOIN "
                          "(SELECT guest FROM g WHERE tag > 1) x USING (guest)")
        assert out.to_pydict()["price"].tolist() == [200.0]


class TestExists:
    def test_exists_true(self, session, views):
        out = session.sql("SELECT count(*) AS n FROM t WHERE "
                          "EXISTS (SELECT guest FROM g WHERE guest > 15)")
        assert out.to_pydict()["n"][0] == 4

    def test_exists_false(self, session, views):
        out = session.sql("SELECT count(*) AS n FROM t WHERE "
                          "EXISTS (SELECT guest FROM g WHERE guest > 100)")
        assert out.to_pydict()["n"][0] == 0

    def test_not_exists(self, session, views):
        out = session.sql("SELECT count(*) AS n FROM t WHERE NOT "
                          "EXISTS (SELECT guest FROM g WHERE guest > 100)")
        assert out.to_pydict()["n"][0] == 4

    def test_higher_order_exists_unaffected(self, session):
        # EXISTS(arr, x -> ...) remains the array function.
        f = Frame({"xs": [[1.0, 5.0], [2.0, 3.0]]})
        f.create_or_replace_temp_view("hx")
        out = session.sql(
            "SELECT exists(xs, x -> x > 4) AS e FROM hx")
        assert [bool(v) for v in out.to_pydict()["e"]] == [True, False]


class TestNotInNullSemantics:
    """SQL three-valued logic (ADVICE.md #2): a NULL in the IN/NOT IN
    value set — literal or materialized from an uncorrelated subquery —
    makes NOT IN unable to return TRUE (``x <> NULL`` is unknown), so it
    filters every row; plain IN drops the NULL from the list (matches
    still pass, non-matches become unknown and filter)."""

    @pytest.fixture
    def null_views(self, session):
        t = Frame({"k": [1.0, 2.0, 3.0]})
        t.create_or_replace_temp_view("tvl_t")
        s = Frame({"v": [2.0, np.nan]})
        s.create_or_replace_temp_view("tvl_s")
        yield t, s
        session.catalog.drop("tvl_t")
        session.catalog.drop("tvl_s")

    def test_not_in_subquery_with_null_filters_all(self, session, null_views):
        out = session.sql(
            "SELECT k FROM tvl_t WHERE k NOT IN (SELECT v FROM tvl_s)")
        assert out.count() == 0          # Spark: zero rows, not [1, 3]

    def test_in_subquery_with_null_keeps_matches(self, session, null_views):
        out = session.sql(
            "SELECT k FROM tvl_t WHERE k IN (SELECT v FROM tvl_s)")
        assert out.to_pydict()["k"].tolist() == [2.0]

    def test_not_in_literal_list_with_null(self, session, null_views):
        out = session.sql("SELECT k FROM tvl_t WHERE k NOT IN (2, NULL)")
        assert out.count() == 0

    def test_in_literal_list_with_null(self, session, null_views):
        out = session.sql("SELECT k FROM tvl_t WHERE k IN (2, NULL)")
        assert out.to_pydict()["k"].tolist() == [2.0]

    def test_not_in_without_null_unchanged(self, session, null_views):
        out = session.sql("SELECT k FROM tvl_t WHERE k NOT IN (2)")
        assert out.to_pydict()["k"].tolist() == [1.0, 3.0]

    def test_fluent_isin_matches(self, session, null_views):
        t, _ = null_views
        assert t.filter(t["k"].isin([2.0, float("nan")])) \
            .to_pydict()["k"].tolist() == [2.0]
