"""SQL subset: the reference's exact queries plus grammar closure."""

import jax.numpy as jnp
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.sql.parser import execute, parse, tokenize


@pytest.fixture
def view(session):
    f = Frame({"guest": jnp.asarray([1.0, 2.0, 3.0]),
               "price": jnp.asarray([10.0, -1.0, 30.0])})
    f.create_or_replace_temp_view("price")
    return f


class TestReferenceQueries:
    """The two statements at `DataQuality4MachineLearningApp.java:77-78,89-90`."""

    def test_first_cleanup_query(self, session, view):
        out = session.sql("SELECT cast(guest as int) guest, price AS price_x "
                          "FROM price WHERE price > 0")
        assert out.columns == ["guest", "price_x"]
        assert out.count() == 2
        assert dict(out.dtypes())["guest"] == "integer"

    def test_second_cleanup_query(self, session, view):
        out = session.sql("SELECT guest, price FROM price WHERE price > 0")
        assert out.count() == 2


class TestGrammar:
    def test_select_star(self, session, view):
        assert session.sql("SELECT * FROM price").count() == 3

    def test_where_and_or_not(self, session, view):
        assert session.sql("SELECT * FROM price WHERE price > 0 AND guest < 3").count() == 1
        assert session.sql("SELECT * FROM price WHERE price < 0 OR guest = 1").count() == 2
        assert session.sql("SELECT * FROM price WHERE NOT price > 0").count() == 1

    def test_arithmetic(self, session, view):
        out = session.sql("SELECT price * 2 + 1 AS p2 FROM price")
        assert out.to_pydict()["p2"][0] == pytest.approx(21.0)

    def test_comparison_operators(self, session, view):
        for op, n in [("=", 1), ("==", 1), ("!=", 2), ("<>", 2), ("<=", 2),
                      (">=", 2), ("<", 1), (">", 1)]:
            assert session.sql(f"SELECT * FROM price WHERE guest {op} 2").count() == n, op

    def test_parentheses(self, session, view):
        q = "SELECT * FROM price WHERE (guest = 1 OR guest = 3) AND price > 0"
        assert session.sql(q).count() == 2

    def test_string_literal(self, session):
        Frame({"s": np.asarray(["a", "b"], dtype=object)}).create_or_replace_temp_view("t")
        # string equality is host-side numpy compare
        out = execute("SELECT * FROM t WHERE s = 'a'")
        assert out.count() == 1

    def test_udf_call_in_sql(self, session, view):
        dq.register_builtin_rules()
        out = session.sql("SELECT minimumPriceRule(price) AS p FROM price")
        assert list(out.to_pydict()["p"]) == [-1.0, -1.0, 30.0]

    def test_negative_literal(self, session, view):
        assert session.sql("SELECT * FROM price WHERE price = -1").count() == 1

    def test_float_literals(self):
        q = parse("SELECT 1.5 AS x FROM t WHERE y > 1e3")
        assert q.view == "t"
        assert q.where is not None

    def test_bare_alias(self, session, view):
        out = session.sql("SELECT cast(guest as int) g FROM price")
        assert out.columns == ["g"]


class TestErrors:
    def test_unknown_view(self, session):
        with pytest.raises(KeyError):
            session.sql("SELECT * FROM nope")

    def test_syntax_error(self, session, view):
        with pytest.raises(ValueError):
            session.sql("SELECT FROM price")

    def test_garbage(self):
        with pytest.raises(ValueError):
            tokenize("SELECT ยง FROM x")

    def test_trailing_tokens(self, session, view):
        with pytest.raises(ValueError):
            session.sql("SELECT * FROM price WHERE price > 0 extra nonsense")

    def test_case_insensitive_keywords(self, session, view):
        assert execute("select * from PRICE where price > 0").count() == 2
