"""SQL subset: the reference's exact queries plus grammar closure."""

import jax.numpy as jnp
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.sql.parser import execute, parse, tokenize


@pytest.fixture
def view(session):
    f = Frame({"guest": jnp.asarray([1.0, 2.0, 3.0]),
               "price": jnp.asarray([10.0, -1.0, 30.0])})
    f.create_or_replace_temp_view("price")
    return f


class TestReferenceQueries:
    """The two statements at `DataQuality4MachineLearningApp.java:77-78,89-90`."""

    def test_first_cleanup_query(self, session, view):
        out = session.sql("SELECT cast(guest as int) guest, price AS price_x "
                          "FROM price WHERE price > 0")
        assert out.columns == ["guest", "price_x"]
        assert out.count() == 2
        assert dict(out.dtypes())["guest"] == "integer"

    def test_second_cleanup_query(self, session, view):
        out = session.sql("SELECT guest, price FROM price WHERE price > 0")
        assert out.count() == 2


class TestGrammar:
    def test_select_star(self, session, view):
        assert session.sql("SELECT * FROM price").count() == 3

    def test_where_and_or_not(self, session, view):
        assert session.sql("SELECT * FROM price WHERE price > 0 AND guest < 3").count() == 1
        assert session.sql("SELECT * FROM price WHERE price < 0 OR guest = 1").count() == 2
        assert session.sql("SELECT * FROM price WHERE NOT price > 0").count() == 1

    def test_arithmetic(self, session, view):
        out = session.sql("SELECT price * 2 + 1 AS p2 FROM price")
        assert out.to_pydict()["p2"][0] == pytest.approx(21.0)

    def test_comparison_operators(self, session, view):
        for op, n in [("=", 1), ("==", 1), ("!=", 2), ("<>", 2), ("<=", 2),
                      (">=", 2), ("<", 1), (">", 1)]:
            assert session.sql(f"SELECT * FROM price WHERE guest {op} 2").count() == n, op

    def test_parentheses(self, session, view):
        q = "SELECT * FROM price WHERE (guest = 1 OR guest = 3) AND price > 0"
        assert session.sql(q).count() == 2

    def test_string_literal(self, session):
        Frame({"s": np.asarray(["a", "b"], dtype=object)}).create_or_replace_temp_view("t")
        # string equality is host-side numpy compare
        out = execute("SELECT * FROM t WHERE s = 'a'")
        assert out.count() == 1

    def test_udf_call_in_sql(self, session, view):
        dq.register_builtin_rules()
        out = session.sql("SELECT minimumPriceRule(price) AS p FROM price")
        assert list(out.to_pydict()["p"]) == [-1.0, -1.0, 30.0]

    def test_negative_literal(self, session, view):
        assert session.sql("SELECT * FROM price WHERE price = -1").count() == 1

    def test_float_literals(self):
        q = parse("SELECT 1.5 AS x FROM t WHERE y > 1e3")
        assert q.view == "t"
        assert q.where is not None

    def test_bare_alias(self, session, view):
        out = session.sql("SELECT cast(guest as int) g FROM price")
        assert out.columns == ["g"]


class TestErrors:
    def test_unknown_view(self, session):
        with pytest.raises(KeyError):
            session.sql("SELECT * FROM nope")

    def test_syntax_error(self, session, view):
        with pytest.raises(ValueError):
            session.sql("SELECT FROM price")

    def test_garbage(self):
        with pytest.raises(ValueError):
            tokenize("SELECT ยง FROM x")

    def test_trailing_tokens(self, session, view):
        with pytest.raises(ValueError):
            session.sql("SELECT * FROM price WHERE price > 0 extra nonsense")

    def test_case_insensitive_keywords(self, session, view):
        assert execute("select * from PRICE where price > 0").count() == 2


class TestPredicateExtensions:
    """IN / BETWEEN / LIKE / NOT variants (cmp grammar extensions)."""

    @pytest.fixture
    def tbl(self, session):
        f = Frame({"g": jnp.asarray([1.0, 2.0, 3.0, 4.0, 5.0]),
                   "name": np.asarray(["alice", "bob", "carol", None, "abe"],
                                      object)})
        f.create_or_replace_temp_view("t")
        return f

    def test_in_list(self, session, tbl):
        out = execute("SELECT g FROM t WHERE g IN (1, 3, 5)", session.catalog)
        assert sorted(r[0] for r in out.collect()) == [1.0, 3.0, 5.0]

    def test_not_in_list(self, session, tbl):
        out = execute("SELECT g FROM t WHERE g NOT IN (1, 3, 5)",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == [2.0, 4.0]

    def test_in_strings(self, session, tbl):
        out = execute("SELECT name FROM t WHERE name IN ('bob', 'abe')",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["abe", "bob"]

    def test_between(self, session, tbl):
        out = execute("SELECT g FROM t WHERE g BETWEEN 2 AND 4",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == [2.0, 3.0, 4.0]

    def test_not_between(self, session, tbl):
        out = execute("SELECT g FROM t WHERE g NOT BETWEEN 2 AND 4",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == [1.0, 5.0]

    def test_like_prefix(self, session, tbl):
        out = execute("SELECT name FROM t WHERE name LIKE 'a%'",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["abe", "alice"]

    def test_like_underscore(self, session, tbl):
        out = execute("SELECT name FROM t WHERE name LIKE '_ob'",
                      session.catalog)
        assert [r[0] for r in out.collect()] == ["bob"]

    def test_not_like_null_is_dropped(self, session, tbl):
        # SQL: NULL NOT LIKE ... is NULL -> row filtered out of WHERE
        out = execute("SELECT name FROM t WHERE name NOT LIKE 'a%'",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["bob", "carol"]

    def test_fluent_isin_between(self, session, tbl):
        assert tbl.filter(tbl.col("g").isin(2, 5)).count() == 2
        assert tbl.filter(tbl.col("g").between(1, 2)).count() == 2
        assert tbl.filter(tbl.col("name").contains("o")).count() == 2
        assert tbl.filter(tbl.col("name").startswith("a")).count() == 2
        assert tbl.filter(tbl.col("name").endswith("e")).count() == 2
        assert tbl.filter(tbl.col("name").rlike("^[ab]")).count() == 3


class TestDistinctHavingUnion:
    @pytest.fixture
    def sales(self, session):
        f = Frame({"dept": np.asarray(["a", "a", "b", "b", "b", "c"], object),
                   "amt": jnp.asarray([10.0, 20.0, 5.0, 5.0, 10.0, 7.0])})
        f.create_or_replace_temp_view("sales")
        return f

    def test_select_distinct(self, session, sales):
        out = execute("SELECT DISTINCT dept FROM sales", session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["a", "b", "c"]

    def test_select_distinct_multi_col(self, session, sales):
        out = execute("SELECT DISTINCT dept, amt FROM sales", session.catalog)
        assert out.count() == 5  # (b, 5.0) dup collapses

    def test_having_on_select_agg(self, session, sales):
        out = execute("SELECT dept, SUM(amt) AS total FROM sales "
                      "GROUP BY dept HAVING SUM(amt) > 15", session.catalog)
        rows = dict(out.collect())
        assert rows == {"a": 30.0, "b": 20.0}

    def test_having_count_star(self, session, sales):
        out = execute("SELECT dept FROM sales GROUP BY dept "
                      "HAVING COUNT(*) >= 2", session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["a", "b"]

    def test_having_without_group_by(self, session, sales):
        # Spark: groupless HAVING filters the global-aggregate row; it is
        # rejected only when the select list has no aggregate at all.
        out = execute("SELECT SUM(amt) FROM sales HAVING SUM(amt) > 0",
                      session.catalog)
        assert out.count() == 1
        with pytest.raises(ValueError, match="HAVING requires"):
            execute("SELECT dept FROM sales HAVING SUM(amt) > 0",
                    session.catalog)

    def test_union_all(self, session, sales):
        out = execute("SELECT dept FROM sales WHERE amt > 15 "
                      "UNION ALL SELECT dept FROM sales WHERE amt > 15",
                      session.catalog)
        assert [r[0] for r in out.collect()] == ["a", "a"]

    def test_union_dedups(self, session, sales):
        out = execute("SELECT dept FROM sales UNION SELECT dept FROM sales",
                      session.catalog)
        assert sorted(r[0] for r in out.collect()) == ["a", "b", "c"]

    def test_not_in_null_semantics(self, session):
        f = Frame({"x": jnp.asarray([1.0, float("nan"), 3.0]),
                   "s": np.asarray(["a", None, "c"], object)})
        f.create_or_replace_temp_view("nulls")
        out = execute("SELECT x FROM nulls WHERE x NOT IN (1)", session.catalog)
        assert [r[0] for r in out.collect()] == [3.0]  # NaN row drops
        out = execute("SELECT s FROM nulls WHERE s NOT IN ('a')",
                      session.catalog)
        assert [r[0] for r in out.collect()] == ["c"]  # None row drops


class TestSimpleCaseAndNvl:
    def test_simple_case_form(self, session, view):
        out = session.sql("SELECT CASE guest WHEN 1 THEN 10 WHEN 2 THEN 20 "
                          "ELSE 99 END AS c FROM price")
        assert out.to_pydict()["c"].tolist() == [10, 20, 99]

    def test_searched_case_still_works(self, session, view):
        out = session.sql("SELECT CASE WHEN guest > 2 THEN 1 ELSE 0 END AS c "
                          "FROM price")
        assert out.to_pydict()["c"].tolist() == [0, 0, 1]

    def test_nvl_alias(self, session, view):
        out = session.sql("SELECT nvl(nullif(guest, 2), -1) AS c FROM price")
        assert out.to_pydict()["c"].tolist() == [1.0, -1.0, 3.0]


class TestSqlSugar:
    def test_concat_pipes(self, session, view):
        d = session.sql("SELECT 'a' || 'b' || 'c' AS c, "
                        "'x' || NULL AS n").to_pydict()
        assert list(d["c"]) == ["abc"]
        assert list(d["n"]) == [None]     # null-propagating like concat

    def test_if_function(self, session, view):
        out = session.sql("SELECT if(guest > 2, 'big', 'small') AS c "
                          "FROM price")
        assert list(out.to_pydict()["c"]) == ["small", "small", "big"]

    def test_extract(self, session):
        d = session.sql("SELECT extract(year FROM to_date('2026-07-31')) "
                        "AS y, extract(month FROM to_date('2026-07-31')) "
                        "AS m, extract(day FROM to_date('2026-07-31')) "
                        "AS d").to_pydict()
        assert (d["y"][0], d["m"][0], d["d"][0]) == (2026.0, 7.0, 31.0)


class TestParserRobustness:
    def test_random_token_soup_raises_cleanly(self):
        # the parser's error contract: ValueError/KeyError with a
        # message, never an AttributeError/IndexError crash
        import numpy as np

        from sparkdq4ml_tpu.sql.parser import parse
        toks = ["SELECT", "FROM", "WHERE", "GROUP", "BY", "ORDER",
                "HAVING", "a", "b", "v", "(", ")", ",", "+", "-", "*",
                "/", "%", "||", "1", "2.5", "'s'", "AND", "OR", "NOT",
                "IN", "BETWEEN", "LIKE", "AS", "JOIN", "ON", "USING",
                "UNION", "ALL", "CASE", "WHEN", "THEN", "ELSE", "END",
                "CAST", "INT", "NULL", "DISTINCT", "LIMIT", "OFFSET",
                "count", "sum", ".", "=", ">", "<", "max"]
        rng = np.random.default_rng(42)
        for _ in range(500):
            q = " ".join(rng.choice(toks, rng.integers(1, 15)))
            try:
                parse(q)
            except (ValueError, KeyError):
                pass
