"""MultilayerPerceptronClassifier: nonlinear boundary a linear model
cannot learn, sklearn MLP quality parity, sharded≡single, validations,
persistence."""

import numpy as np
import pytest

from conftest import assert_devices
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (MultilayerPerceptronClassificationModel,
                                   MultilayerPerceptronClassifier,
                                   VectorAssembler)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def xor_frame(n=400, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float64)
    cols = {"a": X[:, 0], "b": X[:, 1], "label": y}
    return (VectorAssembler(["a", "b"], "features").transform(Frame(cols)),
            X, y)


class TestMLP:
    def test_learns_xor(self):
        f, X, y = xor_frame()
        mlp = MultilayerPerceptronClassifier(layers=[2, 8, 2],
                                             max_iter=800, step_size=0.05,
                                             seed=1)
        model = mlp.fit(f)
        d = model.transform(f).to_pydict()
        acc = np.mean(np.asarray(d["prediction"]) == y)
        assert acc > 0.95
        prob = np.asarray(d["probability"])
        np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-5)
        assert model.loss_history[-1] < model.loss_history[0] * 0.3

    def test_sklearn_quality_parity(self):
        pytest.importorskip("sklearn")
        from sklearn.neural_network import MLPClassifier as SkMLP

        f, X, y = xor_frame(seed=3)
        ours = MultilayerPerceptronClassifier(layers=[2, 8, 2],
                                              max_iter=800, step_size=0.05,
                                              seed=1).fit(f)
        acc = np.mean(np.asarray(
            ours.transform(f).to_pydict()["prediction"]) == y)
        sk = SkMLP(hidden_layer_sizes=(8,), max_iter=2000,
                   random_state=0).fit(X, y)
        assert acc >= sk.score(X, y) - 0.05

    def test_multiclass(self):
        rng = np.random.default_rng(5)
        n = 450
        X = rng.normal(size=(n, 2))
        y = (np.arctan2(X[:, 1], X[:, 0]) // (2 * np.pi / 3)
             % 3).astype(np.float64)           # angular thirds
        f = VectorAssembler(["a", "b"], "features").transform(
            Frame({"a": X[:, 0], "b": X[:, 1], "label": y}))
        model = MultilayerPerceptronClassifier(
            layers=[2, 16, 3], max_iter=800, step_size=0.05, seed=2).fit(f)
        acc = np.mean(np.asarray(
            model.transform(f).to_pydict()["prediction"]) == y)
        assert acc > 0.9

    def test_layer_validations(self):
        f, X, y = xor_frame(n=50)
        with pytest.raises(ValueError, match="layers\\[0\\]"):
            MultilayerPerceptronClassifier(layers=[5, 2],
                                           max_iter=5).fit(f)
        with pytest.raises(ValueError, match="observed classes"):
            MultilayerPerceptronClassifier(layers=[2, 4, 1],
                                           max_iter=5).fit(f)

    def test_default_layers_logistic_like(self):
        f, X, y = xor_frame(n=60)
        model = MultilayerPerceptronClassifier(max_iter=20).fit(f)
        assert model.layers == [2, 2]          # [input, classes]

    def test_sharded_equals_single(self):
        assert_devices(8)
        f, _, _ = xor_frame(n=203, seed=7)
        kw = dict(layers=[2, 4, 2], max_iter=120, step_size=0.05, seed=3)
        single = MultilayerPerceptronClassifier(**kw).fit(
            f, mesh=make_mesh(1))
        sharded = MultilayerPerceptronClassifier(**kw).fit(
            f, mesh=make_mesh(8))
        for (W1, b1), (W2, b2) in zip(single.weights, sharded.weights):
            np.testing.assert_allclose(W2, W1, rtol=1e-6, atol=1e-9)
            np.testing.assert_allclose(b2, b1, rtol=1e-6, atol=1e-9)

    def test_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, X, _ = xor_frame(n=80)
        model = MultilayerPerceptronClassifier(layers=[2, 4, 2],
                                               max_iter=50, seed=1).fit(f)
        model.save(str(tmp_path / "mlp"))
        loaded = load_stage(str(tmp_path / "mlp"))
        assert isinstance(loaded,
                          MultilayerPerceptronClassificationModel)
        assert loaded.predict(X[0]) == model.predict(X[0])
        np.testing.assert_allclose(
            np.asarray(loaded.transform(f).to_pydict()["probability"]),
            np.asarray(model.transform(f).to_pydict()["probability"]),
            rtol=1e-6)
