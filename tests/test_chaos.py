"""Chaos-complete engine suite (ISSUE 11).

Every post-PR-1 subsystem now carries seeded fault sites and a
degradation ladder through the PR-1 recovery engine: the fused pipeline
flush (retry → eager replay, NaN detection, OOM → row-chunked), the
grouped segment-reduce program (device → host lowering), the native
streaming ingest (io error / torn chunk / dead prefetch producer / pool
exhaustion → python engine or chunked body, pooled buffers always
returned), and the QueryServer (worker fault → deadline-aware requeue,
admission breaker trips + census-OOM rejections). Plus: cross-thread
fault determinism (the ``_det_uniform`` pure-function contract from 16
concurrent serve workers), the trip → shed → half-open → closed breaker
lifecycle, recovery telemetry (per-site ``recovery.*`` counters in the
Prometheus scrape, ``recovery_fault`` span annotation in EXPLAIN
ANALYZE), the no-fault-plan hot-path overhead pins, and the
``scripts/chaos_soak.py`` smoke (≥ 5 seeds over the concurrent serving
workload; the full ``--seeds 50`` arm is slow-marked).
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

import jax

from conftest import dataset_path
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame import native_csv
from sparkdq4ml_tpu.frame.csv import read_csv
from sparkdq4ml_tpu.serve import QueryServer
from sparkdq4ml_tpu.utils import faults, profiling, recovery
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

pytestmark = pytest.mark.chaos

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SOAK = os.path.join(REPO, "scripts", "chaos_soak.py")


@pytest.fixture(autouse=True)
def _clean_chaos_state():
    """Chaos state is process-global: scrub the plan, the event log, the
    device breaker, and the chaos-relevant counters around every test."""
    faults.clear()
    RECOVERY_LOG.clear()
    recovery.DEVICE_BREAKER.reset()
    profiling.counters.clear("recovery.")
    profiling.counters.clear("faults.")
    yield
    faults.clear()
    RECOVERY_LOG.clear()
    recovery.DEVICE_BREAKER.reset()
    profiling.counters.clear("recovery.")
    profiling.counters.clear("faults.")


def _eq(a: dict, b: dict) -> None:
    assert list(a) == list(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def _chain(n=64):
    f = Frame({"x": np.arange(float(n)), "y": np.arange(float(n)) * 2})
    return f.with_column("z", f["x"] * 2 + 1).filter(f["x"] > 10)


REF = _chain().to_pydict()


# ---------------------------------------------------------------------------
# fault-plan mechanics of the new sites/kinds
# ---------------------------------------------------------------------------

class TestNewFaultKinds:
    def test_fault_sites_registry_covers_all_hooked_sites(self):
        assert set(faults.FAULT_SITES) >= {
            "pipeline_flush", "grouped_flush", "ingest_native",
            "serve_exec", "serve_admit", "oom",
            "gram_sharded", "fit_packed", "solver", "fit", "mesh"}
        for kinds in faults.FAULT_SITES.values():
            assert set(kinds) <= set(faults.KINDS)

    def test_inject_io_error_raises_oserror_not_filenotfound(self):
        with faults.inject_faults("ingest_native:io_error:1"):
            with pytest.raises(OSError) as ei:
                faults.inject("ingest_native")
            assert not isinstance(ei.value, FileNotFoundError)

    def test_fired_ticks_per_kind_independently(self):
        with faults.inject_faults("ingest_native:torn_chunk:1",
                                  "ingest_native:pool_exhaust:2") as plan:
            assert faults.fired("ingest_native", "torn_chunk")
            assert not faults.fired("ingest_native", "pool_exhaust")
            assert faults.fired("ingest_native", "pool_exhaust")
        assert set(plan.fired) == {
            ("ingest_native", "torn_chunk", 1),
            ("ingest_native", "pool_exhaust", 2)}

    def test_fired_is_noop_without_plan(self):
        assert faults.fired("serve_admit", "breaker_trip") is False

    def test_shrunk_budget_carries_spec_n(self):
        with faults.inject_faults("oom:oom:1:n=4096"):
            assert faults.shrunk_budget("oom") == 4096
            assert faults.shrunk_budget("oom") is None   # attempt 2
        assert faults.shrunk_budget("oom") is None       # no plan

    def test_injected_fault_counts(self):
        with faults.inject_faults("serve_admit:breaker_trip:1"):
            faults.fired("serve_admit", "breaker_trip")
        assert profiling.counters.get("faults.injected") >= 1
        assert profiling.counters.get("faults.injected.serve_admit") >= 1


# ---------------------------------------------------------------------------
# cross-thread determinism (the _det_uniform pure-function contract)
# ---------------------------------------------------------------------------

class TestCrossThreadDeterminism:
    def test_det_uniform_pure_across_16_threads(self):
        grid = [(s, site, a) for s in (0, 7) for site in ("a", "serve_exec")
                for a in range(1, 40)]
        ref = {g: faults._det_uniform(*g) for g in grid}
        errs: list = []

        def worker():
            for g, want in ref.items():
                if faults._det_uniform(*g) != want:
                    errs.append(g)

        threads = [threading.Thread(target=worker) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs

    def _serve_fire_run(self, seed, jobs):
        """Drive ``jobs`` trivial queries through 16 workers under a
        p-spec at serve_exec; returns (fired list, total attempts)."""
        spec = faults.parse_spec("serve_exec:device_error:p=0.3")
        plan = faults.install_plan(faults.FaultPlan([spec], seed=seed))
        # threshold high enough that shedding never perturbs the run
        srv = QueryServer(workers=16, max_queue=4 * jobs,
                          breaker_threshold=10 ** 6).start()
        try:
            futs = [srv.submit(lambda ctx: 1, tenant=f"t{i % 4}")
                    for i in range(jobs)]
            for f in futs:
                f.result(timeout=60)
        finally:
            srv.stop()
            faults.clear()
        return sorted(plan.fired), plan.attempts_at("serve_exec")

    def test_16_worker_fire_set_matches_pure_function(self):
        """The per-site fire set from 16 concurrent serve workers is
        exactly the pure function of (seed, site, attempt) — thread
        interleaving cannot perturb which attempts fire."""
        fired, attempts = self._serve_fire_run(seed=11, jobs=32)
        expect = sorted(
            ("serve_exec", "device_error", a)
            for a in range(1, attempts + 1)
            if faults._det_uniform(11, "serve_exec", a) < 0.3)
        assert fired == expect
        # and a second concurrent run agrees on the common attempt range
        fired2, attempts2 = self._serve_fire_run(seed=11, jobs=32)
        k = min(attempts, attempts2)
        assert [f for f in fired if f[2] <= k] == \
            [f for f in fired2 if f[2] <= k]


# ---------------------------------------------------------------------------
# pipeline_flush: retry -> eager ladder, NaN detection, select path
# ---------------------------------------------------------------------------

class TestPipelineFlushLadder:
    def test_device_error_retries_and_recovers(self):
        with faults.inject_faults("pipeline_flush:device_error:1",
                                  seed=3) as plan:
            _eq(_chain().to_pydict(), REF)
        assert plan.fired == [("pipeline_flush", "device_error", 1)]
        assert RECOVERY_LOG.count("retry", site="pipeline_flush") >= 1
        # recovered on the retry — the eager rung never ran
        assert profiling.counters.get("pipeline.fault_fallback") == 0 or \
            not RECOVERY_LOG.events(site="pipeline_flush",
                                    action="fallback")

    def test_persistent_device_error_degrades_to_eager(self):
        before = profiling.counters.get("pipeline.fault_fallback")
        with faults.inject_faults(
                "pipeline_flush:device_error:1,2,3,4,5,6,7,8", seed=3):
            _eq(_chain().to_pydict(), REF)
        assert profiling.counters.get("pipeline.fault_fallback") \
            == before + 1
        acts = {e.action for e in RECOVERY_LOG.events(
            site="pipeline_flush")}
        assert {"retry", "exhausted", "fallback"} <= acts
        ev = RECOVERY_LOG.events(site="pipeline_flush",
                                 action="fallback")[-1]
        assert ev.rung == "eager"

    def test_pending_steps_survive_failed_rungs(self):
        """A failed fused attempt must not half-apply: the frame's
        pending steps stay queued until a rung succeeds, so the eventual
        result is exactly the eager result."""
        f = Frame({"x": np.arange(64.0)})
        g = f.with_column("a", f["x"] + 1)
        g = g.with_column("b", g["a"] * 3)
        g = g.filter(g["x"] > 5)
        assert len(g._pending) == 3
        with faults.inject_faults("pipeline_flush:device_error:1,2,3,4,5",
                                  seed=9):
            out = g.to_pydict()
        assert g._pending == ()
        h = Frame({"x": np.arange(64.0)})
        h = h.with_column("a", h["x"] + 1)
        h = h.with_column("b", h["a"] * 3).filter(h["x"] > 5)
        _eq(out, h.to_pydict())

    def test_nan_corruption_detected_and_replayed(self):
        with faults.inject_faults("pipeline_flush:nan:1", seed=3) as plan:
            out = _chain().to_pydict()
        _eq(out, REF)
        assert plan.fired == [("pipeline_flush", "nan", 1)]
        assert any(e.cause == "non-finite result"
                   for e in RECOVERY_LOG.events(site="pipeline_flush"))

    def test_fused_select_device_error_falls_back_correct(self):
        f = Frame({"x": np.arange(64.0), "y": np.arange(64.0) * 3})
        ref = f.select((f["x"] * 2).alias("a"),
                       (f["y"] + 1).alias("b")).to_pydict()
        with faults.inject_faults("pipeline_flush:device_error:1", seed=5):
            g = Frame({"x": np.arange(64.0), "y": np.arange(64.0) * 3})
            out = g.select((g["x"] * 2).alias("a"),
                           (g["y"] + 1).alias("b")).to_pydict()
        _eq(out, ref)

    def test_no_fault_plan_hot_path_never_touches_recovery(self,
                                                           monkeypatch):
        """The no-fault-plan overhead contract: one ``is None`` check —
        the ladder, the corrupt hook, and the event log are never even
        called."""
        def boom(*a, **kw):
            raise AssertionError("recovery machinery on the clean path")

        monkeypatch.setattr(recovery, "resilient_call", boom)
        monkeypatch.setattr(faults, "corrupt", boom)
        monkeypatch.setattr(faults, "fired", boom)
        _eq(_chain().to_pydict(), REF)
        assert len(RECOVERY_LOG) == 0


# ---------------------------------------------------------------------------
# oom: est-peak-over-budget -> row-chunked execution
# ---------------------------------------------------------------------------

class TestOomChunkedExecution:
    def _big_chain(self, n=4096):
        f = Frame({"x": np.arange(float(n)), "y": np.arange(float(n)) * 2})
        return f.with_column("z", f["x"] * 2 + 1).filter(f["x"] > 10)

    def test_injected_oom_chunks_and_matches(self):
        ref = self._big_chain().to_pydict()
        before = profiling.counters.get("pipeline.oom_chunked")
        with faults.inject_faults("oom:oom:1:n=64", seed=3) as plan:
            out = self._big_chain().to_pydict()
        _eq(out, ref)
        assert plan.fired == [("oom", "oom", 1)]
        assert profiling.counters.get("pipeline.oom_chunked") == before + 1
        ev = RECOVERY_LOG.events(site="oom", action="fallback")
        assert ev and ev[-1].rung == "chunked"

    def test_oom_fault_is_one_shot(self):
        with faults.inject_faults("oom:oom:1:n=64", seed=3):
            before = profiling.counters.get("pipeline.oom_chunked")
            _eq(self._big_chain().to_pydict(),
                self._big_chain().to_pydict())   # 2 flushes, 1 fault
            assert profiling.counters.get("pipeline.oom_chunked") \
                == before + 1

    def test_conf_budget_triggers_chunked(self):
        ref = self._big_chain().to_pydict()
        before = profiling.counters.get("pipeline.oom_chunked")
        config.audit_device_budget = 2048
        try:
            out = self._big_chain().to_pydict()
        finally:
            config.audit_device_budget = 0
        _eq(out, ref)
        assert profiling.counters.get("pipeline.oom_chunked") > before

    def test_no_budget_no_chunking(self):
        before = profiling.counters.get("pipeline.oom_chunked")
        self._big_chain().to_pydict()
        assert profiling.counters.get("pipeline.oom_chunked") == before


# ---------------------------------------------------------------------------
# grouped_flush: device -> host lowering
# ---------------------------------------------------------------------------

class TestGroupedFlushLadder:
    def _agg(self):
        f = Frame({"k": np.array([1, 1, 2, 2, 3]),
                   "v": np.array([1.0, 2, 3, 4, 5])})
        return f.group_by("k").agg({"v": "sum"}).to_pydict()

    def test_device_error_degrades_to_host(self):
        ref = self._agg()
        before = profiling.counters.get("grouped.fault_fallback")
        with faults.inject_faults("grouped_flush:device_error:1",
                                  seed=3) as plan:
            out = self._agg()
        _eq(out, ref)
        assert plan.fired == [("grouped_flush", "device_error", 1)]
        assert profiling.counters.get("grouped.fault_fallback") \
            == before + 1
        ev = RECOVERY_LOG.events(site="grouped_flush", action="fallback")
        assert ev and ev[-1].rung == "host"

    def test_sort_degrades_too(self):
        f = Frame({"k": np.array([3.5, 1.25, 2.75, 0.5])})
        ref = f.sort("k").to_pydict()
        with faults.inject_faults("grouped_flush:device_error:1", seed=3):
            g = Frame({"k": np.array([3.5, 1.25, 2.75, 0.5])})
            out = g.sort("k").to_pydict()
        _eq(out, ref)


# ---------------------------------------------------------------------------
# ingest_native: io error / torn chunk / thread death / pool exhaustion
# ---------------------------------------------------------------------------

def _write_csv(tmp_path, name="big.csv", rows=4000):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        for i in range(rows):
            f.write(f"{i},{i * 2},{i / 4}\n")
    return p


needs_stream = pytest.mark.skipif(
    not native_csv.streaming_available(),
    reason="native streaming library not built")


@pytest.fixture
def small_chunks():
    saved = config.ingest_chunk_bytes
    config.ingest_chunk_bytes = 4096
    yield
    config.ingest_chunk_bytes = saved


class TestIngestChaos:
    @needs_stream
    @pytest.mark.parametrize("kind,rung", [
        ("io_error", "python"), ("torn_chunk", "python"),
        ("thread_death", "python"), ("pool_exhaust", "chunked")])
    def test_fault_degrades_with_identical_data(self, tmp_path,
                                                small_chunks, kind, rung):
        path = _write_csv(tmp_path)
        ref = read_csv(path).to_pydict()
        before = profiling.counters.get("ingest.fault_fallback")
        with faults.inject_faults(f"ingest_native:{kind}:1",
                                  seed=1) as plan:
            out = read_csv(path).to_pydict()
        _eq(out, ref)
        assert plan.fired == [("ingest_native", kind, 1)]
        assert profiling.counters.get("ingest.fault_fallback") \
            == before + 1
        ev = RECOVERY_LOG.events(site="ingest_native", action="fallback")
        assert ev and ev[-1].rung == rung

    @needs_stream
    def test_missing_file_still_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_csv(str(tmp_path / "nope.csv"))

    @needs_stream
    @pytest.mark.parametrize("kind,exc", [
        ("io_error", OSError),
        ("torn_chunk", native_csv.NativeIngestError),
        ("thread_death", native_csv.NativeIngestError)])
    def test_explicit_native_engine_never_degrades(self, tmp_path,
                                                   small_chunks, kind,
                                                   exc):
        path = _write_csv(tmp_path)
        with faults.inject_faults(f"ingest_native:{kind}:1", seed=1):
            with pytest.raises(exc):
                read_csv(path, engine="native")

    def test_producer_exception_propagates_not_hangs(self):
        """A dying prefetch producer surfaces as NativeIngestError at the
        consumer instead of leaving it blocked on the bounded queue."""
        calls = []

        def next_chunk():
            if calls:
                raise RuntimeError("producer boom")
            calls.append(1)
            return 5, "payload"

        saved = config.ingest_prefetch
        config.ingest_prefetch = 2
        try:
            it = native_csv._prefetch_iter(next_chunk)
            assert next(it) == (5, "payload")
            t0 = time.monotonic()
            with pytest.raises(native_csv.NativeIngestError) as ei:
                next(it)
            assert time.monotonic() - t0 < 30.0
            assert isinstance(ei.value.__cause__, RuntimeError)
        finally:
            config.ingest_prefetch = saved

    @needs_stream
    def test_pool_buffers_returned_on_parse_failure(self, tmp_path,
                                                    small_chunks,
                                                    monkeypatch):
        """The pooled bind-mode buffers return to the pool on a
        mid-stream parse failure (the old code leaked them on every
        non-success exit). Forced into "copy" handoff mode — alias mode
        never pools, and on this failure path no column is ever handed
        to the engine, so the mode only gates the checkin."""
        monkeypatch.setattr(native_csv, "_device_handoff_mode",
                            lambda: "copy")
        path = str(tmp_path / "bad.csv")
        with open(path, "w") as f:
            for i in range(2500):
                f.write(f"{i},{i * 2}\n")
            f.write("oops,text\n")
            for i in range(2500):
                f.write(f"{i},{i * 2}\n")
        with native_csv._POOL_LOCK:
            saved_pool = list(native_csv._POOL)
            native_csv._POOL.clear()
        try:
            frame = read_csv(path)   # python engine takes over
            assert "_c0" in frame.columns
            with native_csv._POOL_LOCK:
                assert len(native_csv._POOL) == 1
        finally:
            with native_csv._POOL_LOCK:
                native_csv._POOL.clear()
                native_csv._POOL.extend(saved_pool)


# ---------------------------------------------------------------------------
# serve: worker requeue ladder + admission chaos + breaker lifecycle
# ---------------------------------------------------------------------------

class TestServeChaos:
    def _server(self, **kw):
        kw.setdefault("workers", 4)
        kw.setdefault("breaker_threshold", 3)
        kw.setdefault("breaker_cooldown", 0.4)
        return QueryServer(**kw).start()

    def test_worker_fault_requeues_then_succeeds(self):
        srv = self._server()
        try:
            before = profiling.counters.get("serve.requeue")
            with faults.inject_faults("serve_exec:device_error:1", seed=2):
                r = srv.submit(lambda ctx: 41 + 1,
                               tenant="t0").result(timeout=60)
            assert r.ok and r.value == 42
            assert profiling.counters.get("serve.requeue") == before + 1
            ev = RECOVERY_LOG.events(site="serve_exec", action="retry")
            assert ev and ev[-1].rung == "requeue"
        finally:
            srv.stop()

    def test_persistent_fault_exhausts_to_structured_error(self):
        srv = self._server()
        try:
            with faults.inject_faults(
                    "serve_exec:device_error:1,2,3,4,5,6,7,8", seed=2):
                r = srv.submit(lambda ctx: 1,
                               tenant="t1").result(timeout=60)
            assert r.status == "error"
            assert "InjectedDeviceError" in r.error
            assert RECOVERY_LOG.events(site="serve_exec",
                                       action="exhausted")
        finally:
            srv.stop()

    def test_requeue_is_deadline_aware(self):
        """A faulted job whose deadline already passed fails instead of
        requeuing — and its result() stays bounded either way."""
        srv = self._server()
        try:
            release = threading.Event()
            before = profiling.counters.get("serve.requeue")
            with faults.inject_faults(
                    "serve_exec:device_error:1,2,3,4,5,6,7,8", seed=2):
                fut = srv.submit(
                    lambda ctx: release.wait(5) or 1, tenant="t2",
                    deadline_s=0.2)
                r = fut.result(timeout=30)
            release.set()
            assert r.status in ("deadline_exceeded", "error")
            # never an unbounded requeue loop
            assert profiling.counters.get("serve.requeue") - before <= 3
        finally:
            srv.stop()

    def test_tenant_bug_fails_fast_no_requeue(self):
        srv = self._server()
        try:
            before = profiling.counters.get("serve.requeue")

            def bad(ctx):
                raise ValueError("tenant bug")

            r = srv.submit(bad, tenant="t3").result(timeout=60)
            assert r.status == "error" and "ValueError" in r.error
            assert profiling.counters.get("serve.requeue") == before
        finally:
            srv.stop()

    def test_breaker_trip_shed_halfopen_closed_lifecycle(self):
        srv = self._server()
        try:
            key = srv.admission.breaker_key("t4")
            with faults.inject_faults("serve_admit:breaker_trip:1",
                                      seed=2):
                r = srv.submit(lambda ctx: 1,
                               tenant="t4").result(timeout=60)
                assert r.status == "shed" and r.reason == "breaker_open"
                assert srv.breaker.snapshot()[key]["open"]
                r2 = srv.submit(lambda ctx: 1,
                                tenant="t4").result(timeout=60)
                assert r2.status == "shed"
                time.sleep(0.5)
                assert srv.breaker.allow(key)    # half-open
                r3 = srv.submit(lambda ctx: 7,
                                tenant="t4").result(timeout=60)
                assert r3.ok and r3.value == 7
                assert key not in srv.breaker.snapshot()   # closed
        finally:
            srv.stop()

    def test_admission_oom_fault_rejects_memory(self):
        srv = self._server()
        try:
            before = profiling.counters.get("serve.reject.memory")
            with faults.inject_faults("serve_admit:oom:1", seed=2):
                r = srv.submit(lambda ctx: 1,
                               tenant="t5").result(timeout=60)
            assert r.status == "rejected" and r.reason == "memory"
            assert profiling.counters.get("serve.reject.memory") \
                == before + 1
        finally:
            srv.stop()

    def test_no_plan_submit_never_consults_fired(self, monkeypatch):
        def boom(*a, **kw):
            raise AssertionError("fired() on the clean submit path")

        monkeypatch.setattr(faults, "fired", boom)
        srv = self._server()
        try:
            r = srv.submit(lambda ctx: 1, tenant="t6").result(timeout=60)
            assert r.ok
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# recovery telemetry: per-site counters, Prometheus HELP, span annotation
# ---------------------------------------------------------------------------

class TestRecoveryTelemetry:
    def test_per_site_counters_mirror_events(self):
        RECOVERY_LOG.record("pipeline_flush", "retry", attempt=1)
        RECOVERY_LOG.record("pipeline_flush", "retry", attempt=2)
        RECOVERY_LOG.record("grouped_flush", "fallback", rung="host")
        snap = profiling.counters.snapshot("recovery.")
        assert snap["recovery.retry"] == 2
        assert snap["recovery.retry.pipeline_flush"] == 2
        assert snap["recovery.fallback.grouped_flush"] == 1

    def test_prometheus_scrape_carries_per_site_series_with_help(self):
        from sparkdq4ml_tpu.utils import observability as obs

        RECOVERY_LOG.record("serve_exec", "retry", attempt=1)
        text = obs.prometheus_text()
        assert "sparkdq4ml_recovery_retry_serve_exec" in text
        assert ("# HELP sparkdq4ml_recovery_retry_serve_exec "
                "recovery.retry.serve_exec") in text

    def test_explain_analyze_shows_absorbing_operator(self, session):
        from sparkdq4ml_tpu.utils import observability as obs

        f = Frame({"a": np.arange(64.0)})
        f.create_or_replace_temp_view("t_chaos_xp")
        try:
            with faults.inject_faults("pipeline_flush:device_error:1",
                                      seed=4):
                out = session.sql("EXPLAIN ANALYZE SELECT a, a*2 AS d "
                                  "FROM t_chaos_xp WHERE a > 3")
            text = str(out.to_pydict()["plan"][0])
        finally:
            # the ANALYZE pass records spans into the process-global
            # buffer; leaving them behind breaks buffer-positional
            # assertions in suites that run right after this one
            obs.TRACER.clear()
        line = next(ln for ln in text.splitlines()
                    if "recovery_fault" in ln)
        assert "pipeline_flush:device_error" in line
        assert "FusedStage" in line or "Filter" in line


# ---------------------------------------------------------------------------
# conf vocabulary: spark.chaos.* session-scoped
# ---------------------------------------------------------------------------

class TestChaosConf:
    def test_chaos_conf_session_scoped(self):
        import sparkdq4ml_tpu as dq

        assert config.chaos_seeds == 5 and config.chaos_soak_s == 0.0
        s = dq.TpuSession.builder().app_name("chaos-conf").master(
            "local[*]").config("spark.chaos.seed", "9").config(
            "spark.chaos.seeds", "11").config(
            "spark.chaos.soakSeconds", "2.5").get_or_create()
        try:
            assert config.chaos_seed == 9
            assert config.chaos_seeds == 11
            assert config.chaos_soak_s == 2.5
        finally:
            s.stop()
        assert config.chaos_seed == 0
        assert config.chaos_seeds == 5
        assert config.chaos_soak_s == 0.0


# ---------------------------------------------------------------------------
# the soak harness: tier-1 smoke + slow full arm
# ---------------------------------------------------------------------------

def _load_soak():
    import importlib.util

    spec = importlib.util.spec_from_file_location("chaos_soak", SOAK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestChaosSoak:
    def test_schedule_is_pure_function_of_seed(self):
        soak = _load_soak()
        assert soak.build_schedule(5) == soak.build_schedule(5)
        assert soak.build_schedule(5) != soak.build_schedule(6)
        for s in range(10):
            faults.parse_plan(soak.build_schedule(s), seed=s)   # parses

    def test_soak_smoke_five_seeds(self):
        """The tier-1 smoke of the headline gate: ≥ 5 seeded random
        fault schedules over the concurrent serving workload — zero
        hangs, golden results on every success, coherent counters,
        breaker recovery on the tripped seeds."""
        soak = _load_soak()
        summary = soak.run_soak(seeds=5, clients=3, queries=1, workers=4)
        assert summary["ok"], summary["per_seed"]
        assert summary["seeds"] == 5
        assert summary["completed"] > 0
        assert summary["faults_fired"] > 0
        # seeds 0 and 3 schedule a breaker trip; recovery must be seen
        assert summary["breakers_tripped"] >= 1
        assert summary["breakers_recovered"] == summary["breakers_probed"]

    @pytest.mark.slow
    def test_soak_full_fifty_seeds_32_clients(self):
        """The full acceptance arm: ``--seeds 50`` over the 32-client
        serving workload (slow; also runnable as
        ``python scripts/chaos_soak.py --seeds 50``)."""
        soak = _load_soak()
        summary = soak.run_soak(seeds=50, clients=32, queries=1,
                                workers=8)
        assert summary["ok"], summary["failed_seeds"]
        assert summary["faults_fired"] > 0
        assert summary["breakers_recovered"] == summary["breakers_probed"]
