"""FPGrowth: frequent itemsets vs brute-force enumeration, association
rule metrics by hand, transform semantics, persistence."""

import itertools

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import FPGrowth, FPGrowthModel
from sparkdq4ml_tpu.models.text import _obj_array


def brute_force_itemsets(txns, min_count):
    """All itemsets with count >= min_count, by exhaustive enumeration."""
    universe = sorted({i for t in txns for i in t})
    out = {}
    for r in range(1, len(universe) + 1):
        for combo in itertools.combinations(universe, r):
            c = sum(1 for t in txns if set(combo) <= set(t))
            if c >= min_count:
                out[frozenset(combo)] = c
    return out


BASKETS = [["bread", "milk"],
           ["bread", "diaper", "beer", "eggs"],
           ["milk", "diaper", "beer", "cola"],
           ["bread", "milk", "diaper", "beer"],
           ["bread", "milk", "diaper", "cola"]]


class TestFPGrowth:
    def test_matches_brute_force(self):
        f = Frame({"items": _obj_array(BASKETS)})
        model = FPGrowth(min_support=0.4, min_confidence=0.5).fit(f)
        got = {frozenset(s): c for s, c in model.itemsets}
        want = brute_force_itemsets(BASKETS, min_count=2)
        assert got == want

    @pytest.mark.parametrize("support", [0.2, 0.6, 1.0])
    def test_random_data_matches_brute_force(self, support):
        rng = np.random.default_rng(3)
        universe = list("abcdef")
        txns = [list(rng.choice(universe,
                                size=rng.integers(1, 5), replace=False))
                for _ in range(30)]
        f = Frame({"items": _obj_array(txns)})
        model = FPGrowth(min_support=support).fit(f)
        got = {frozenset(s): c for s, c in model.itemsets}
        dedup = [tuple(dict.fromkeys(t)) for t in txns]
        want = brute_force_itemsets(dedup,
                                    int(np.ceil(support * len(txns))))
        assert got == want

    def test_association_rule_metrics(self):
        f = Frame({"items": _obj_array(BASKETS)})
        model = FPGrowth(min_support=0.4, min_confidence=0.5).fit(f)
        d = model.association_rules.to_pydict()
        rules = {(tuple(a), tuple(c)): (conf, lift, sup)
                 for a, c, conf, lift, sup in zip(
                     d["antecedent"], d["consequent"], d["confidence"],
                     d["lift"], d["support"])}
        # {beer} -> diaper: conf = freq(beer,diaper)/freq(beer) = 3/3
        conf, lift, sup = rules[(("beer",), ("diaper",))]
        assert conf == pytest.approx(1.0)
        assert lift == pytest.approx(1.0 / (4 / 5))   # P(diaper) = 4/5
        assert sup == pytest.approx(3 / 5)
        # every rule clears the confidence threshold
        assert np.all(np.asarray(d["confidence"]) >= 0.5)

    def test_transform_fires_rules(self):
        f = Frame({"items": _obj_array(BASKETS)})
        model = FPGrowth(min_support=0.4, min_confidence=0.9).fit(f)
        g = Frame({"items": _obj_array([["beer"], ["bread", "milk"],
                                        None])})
        pred = model.transform(g).to_pydict()["prediction"]
        assert "diaper" in pred[0]          # beer -> diaper fires
        assert "beer" not in pred[1]
        # no row predicts an item it already has
        for items, p in zip([["beer"], ["bread", "milk"]], pred[:2]):
            assert not (set(items) & set(p))

    def test_min_support_validation(self):
        with pytest.raises(ValueError, match="min_support"):
            FPGrowth(min_support=0.0)
        with pytest.raises(ValueError, match="min_confidence"):
            FPGrowth(min_confidence=1.5)

    def test_masked_rows_excluded(self):
        txns = BASKETS + [["poison", "bread"]] * 3
        f = Frame({"items": _obj_array(txns)})
        keep = np.asarray([True] * 5 + [False] * 3)
        model = FPGrowth(min_support=0.4).fit(f.filter(keep))
        all_items = {i for s, _ in model.itemsets for i in s}
        assert "poison" not in all_items
        assert model.num_transactions == 5

    def test_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f = Frame({"items": _obj_array(BASKETS)})
        model = FPGrowth(min_support=0.4, min_confidence=0.5).fit(f)
        model.save(str(tmp_path / "fp"))
        loaded = load_stage(str(tmp_path / "fp"))
        assert isinstance(loaded, FPGrowthModel)
        assert {frozenset(s): c for s, c in loaded.itemsets} == \
            {frozenset(s): c for s, c in model.itemsets}
        d = loaded.association_rules.to_pydict()
        assert len(d["confidence"]) == \
            len(model.association_rules.to_pydict()["confidence"])
