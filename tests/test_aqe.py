"""Adaptive query execution suite (ISSUE 19, tier-1, ``aqe`` marker).

The acceptance surface:

* **build-side flip** — a join whose optimizer estimate drifted past
  ``spark.aqe.driftFactor`` re-decides the hash-build side from the
  OBSERVED valid-row counts, bit-identical to the static plan;
* **broadcast shuffle-skip** — a drifted sharded join whose observed
  build side fits ``spark.aqe.broadcastThreshold`` bytes skips the
  hash-partition Exchange entirely (``shard.join_partitioned`` pinned
  unchanged), results exact;
* **skew split** — an Exchange partition crossing ``spark.aqe.
  skewFactor`` x the mean splits into balanced probe chunks; the plan
  equals both the unsplit partitioned plan AND the unpartitioned plan
  (the PR-13 stable left-index merge), gated off for right/outer;
* **downstream re-bucket** — a WHERE whose history says far fewer rows
  survive compacts into the smaller power-of-two bucket (fewer padded
  slots downstream), bit-parity with AQE off, device-budget re-check;
* **grouped-lowering dense-skip** — cardinality history above the dense
  slot-table range skips the doomed dense dispatch, parity pinned;
* **disabled mode** — ``spark.aqe.enabled=false`` reduces every hook to
  one conf read (decision functions monkeypatched to RAISE stay
  uncalled) and pins EXPLAIN byte-identical to the static engine;
* **degradation** — the ``aqe`` fault site (``device_error`` raise and
  ``stall`` due-test) degrades each DECISION to the static plan
  (``aqe.fallback`` + recovery event, rung ``static``), results golden
  on every rung;
* **satellites** — the flop-cost term in the level-2 join reorder
  (``flops_for_selectivity`` bridge + the re-ranked pick), the
  decorrelation-aware pushdown into correlated-subquery branches
  (outer EXPLAIN pinned, branch rewrite counted), the ``spark.aqe.*``
  session-conf scoping, and the per-page wire-deadline re-check on the
  serving stream paths (``net.page_deadline``).
"""

from __future__ import annotations

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame, _vector_join_plan
from sparkdq4ml_tpu.ops import compiler
from sparkdq4ml_tpu.ops.compiler import bucket_size
from sparkdq4ml_tpu.parallel import mesh as pmesh
from sparkdq4ml_tpu.parallel import shard
from sparkdq4ml_tpu.sql import adaptive
from sparkdq4ml_tpu.sql import optimizer as opt
from sparkdq4ml_tpu.utils import faults, observability as obs
from sparkdq4ml_tpu.utils import profiling, statstore
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.aqe


@pytest.fixture(autouse=True)
def _clean_aqe_state():
    saved = (config.aqe_enabled, config.aqe_drift_factor,
             config.aqe_broadcast_threshold, config.aqe_skew_factor,
             config.optimizer_enabled, config.optimizer_level)
    statstore.STORE.clear()
    compiler.clear_cache()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("aqe.")
    yield
    (config.aqe_enabled, config.aqe_drift_factor,
     config.aqe_broadcast_threshold, config.aqe_skew_factor,
     config.optimizer_enabled, config.optimizer_level) = saved
    statstore.STORE.clear()
    compiler.clear_cache()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("aqe.")
    obs.TRACER.clear()


def _exec(session, sql):
    out = session.sql(sql)
    jax.block_until_ready(out._mask)
    return out.to_pydict()


def _assert_exact(off, on):
    assert list(off) == list(on)
    for c in off:
        np.testing.assert_array_equal(np.asarray(off[c]),
                                      np.asarray(on[c]),
                                      err_msg=f"column {c!r}")


def _assert_sorted(off, on):
    assert sorted(off) == sorted(on)
    cols = sorted(off)
    a = np.array([np.asarray(off[c], dtype=np.float64) for c in cols])
    b = np.array([np.asarray(on[c], dtype=np.float64) for c in cols])
    assert a.shape == b.shape
    np.testing.assert_array_equal(a[:, np.lexsort(a[::-1])],
                                  b[:, np.lexsort(b[::-1])])


def _replans(trigger=None):
    name = "aqe.replans" + (f".{trigger}" if trigger else "")
    return profiling.counters.get(name)


# ---------------------------------------------------------------------------
# Build-side flip (Frame.join est= hook)
# ---------------------------------------------------------------------------


class TestBuildFlip:
    def _frames(self):
        rng = np.random.default_rng(11)
        left = Frame({"k": np.arange(30, dtype=np.float64),
                      "v": rng.normal(size=30)})
        right = Frame({"k": (np.arange(4096) % 64).astype(np.float64),
                       "w": rng.normal(size=4096)})
        return left, right

    def test_drift_flips_build_side_bit_identical(self):
        left, right = self._frames()
        config.aqe_enabled = False
        ref = left.join(right, on="k").to_pydict()
        config.aqe_enabled = True
        # the estimate claims the LEFT side is huge; the observed 30
        # valid rows drift past the factor, so the build side re-decides
        got = left.join(right, on="k", est=(30 * 4096, 4096)).to_pydict()
        assert _replans("build-flip") == 1
        _assert_exact(ref, got)

    def test_no_drift_keeps_static_plan(self):
        left, right = self._frames()
        config.aqe_enabled = True
        left.join(right, on="k", est=(30, 4096))
        assert _replans() == 0

    def test_cold_estimate_never_triggers(self):
        left, right = self._frames()
        config.aqe_enabled = True
        left.join(right, on="k", est=(None, None))
        left.join(right, on="k")
        assert _replans() == 0


# ---------------------------------------------------------------------------
# Skew split (partitioned exchange)
# ---------------------------------------------------------------------------


def _skewed_plan_inputs(n=2000, keys=512, seed=5):
    """~70% of probe rows land one (continuous-float) key — that key's
    Exchange partition crosses 2x the mean while the rest stay near it.
    Continuous keys matter: integer-valued doubles share their low
    mantissa bits and would all hash into one partition anyway."""
    rng = np.random.default_rng(seed)
    rk = rng.random(keys) * 100.0
    lk = np.where(rng.random(n) < 0.7, rk[7], rk[rng.integers(0, keys, n)])
    li = np.arange(n, dtype=np.int64)
    ri = np.arange(keys, dtype=np.int64)
    return [lk], [rk], li, ri


class TestSkewSplit:
    def test_split_plan_is_bit_identical(self):
        lcols, rcols, li, ri = _skewed_plan_inputs()
        config.aqe_skew_factor = 2.0
        config.aqe_enabled = False
        ref = shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "inner", 4)
        config.aqe_enabled = True
        got = shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "inner", 4)
        assert _replans("skew-split") >= 1
        flat = _vector_join_plan(lcols, rcols, li, ri, "inner")
        for a, b in ((ref, got), (flat, got)):
            np.testing.assert_array_equal(a[0], b[0])
            np.testing.assert_array_equal(a[1], b[1])

    def test_left_join_split_parity(self):
        lcols, rcols, li, ri = _skewed_plan_inputs(seed=6)
        config.aqe_skew_factor = 2.0
        config.aqe_enabled = False
        ref = shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "left", 4)
        config.aqe_enabled = True
        got = shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "left", 4)
        assert _replans("skew-split") >= 1
        np.testing.assert_array_equal(ref[0], got[0])
        np.testing.assert_array_equal(ref[1], got[1])

    def test_outer_join_never_splits(self):
        # unmatched-right detection is cross-chunk for right/outer —
        # the split must stay gated off no matter the skew
        lcols, rcols, li, ri = _skewed_plan_inputs()
        config.aqe_enabled = True
        shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "outer", 4)
        assert _replans("skew-split") == 0

    def test_below_skew_factor_never_splits(self):
        rng = np.random.default_rng(9)
        rk = rng.random(512) * 100.0            # balanced continuous keys
        lk = rk[rng.integers(0, 512, 2000)]
        config.aqe_enabled = True
        shard.partitioned_join_plan(
            _vector_join_plan, [lk], [rk],
            np.arange(2000, dtype=np.int64),
            np.arange(512, dtype=np.int64), "inner", 4)
        assert _replans("skew-split") == 0


# ---------------------------------------------------------------------------
# Broadcast shuffle-skip (sharded exchange elision)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the conftest's 8 forced host devices")
class TestBroadcastSkip:
    @contextlib.contextmanager
    def _sharding(self, min_rows=8):
        saved = (config.shard_enabled, config.shard_min_rows,
                 config.shard_devices)
        config.shard_enabled = True
        config.shard_min_rows = min_rows
        config.shard_devices = 0
        shard.configure(pmesh.make_mesh())
        try:
            yield
        finally:
            (config.shard_enabled, config.shard_min_rows,
             config.shard_devices) = saved
            shard.reset()

    def test_small_observed_build_side_skips_exchange(self):
        rng = np.random.default_rng(21)
        with self._sharding():
            big = shard.maybe_shard_frame(Frame({
                "k": (np.arange(4096) % 60).astype(np.float64),
                "v": rng.normal(size=4096)}))
            assert big._shard is not None
            small = Frame({"k": np.arange(60, dtype=np.float64),
                           "w": rng.normal(size=60)})
            config.aqe_enabled = False
            before = profiling.counters.get("shard.join_partitioned")
            ref = big.join(small, on="k").to_pydict()
            assert profiling.counters.get(
                "shard.join_partitioned") == before + 1
            # estimates said both sides were big; the observed 60-row
            # build side fits the broadcast threshold → no Exchange
            config.aqe_enabled = True
            mid = profiling.counters.get("shard.join_partitioned")
            got = big.join(small, on="k",
                           est=(4096, 4096)).to_pydict()
            assert profiling.counters.get(
                "shard.join_partitioned") == mid
            assert _replans("broadcast") == 1
            _assert_exact(ref, got)

    def test_over_threshold_build_side_keeps_exchange(self):
        rng = np.random.default_rng(22)
        with self._sharding():
            big = shard.maybe_shard_frame(Frame({
                "k": (np.arange(4096) % 60).astype(np.float64),
                "v": rng.normal(size=4096)}))
            small = Frame({"k": np.arange(60, dtype=np.float64),
                           "w": rng.normal(size=60)})
            config.aqe_enabled = True
            config.aqe_broadcast_threshold = 16   # nothing fits 16 bytes
            before = profiling.counters.get("shard.join_partitioned")
            big.join(small, on="k", est=(4096, 4096))
            assert profiling.counters.get(
                "shard.join_partitioned") == before + 1
            assert _replans("broadcast") == 0


# ---------------------------------------------------------------------------
# Downstream re-bucket (fewer padded slots after the WHERE boundary)
# ---------------------------------------------------------------------------


def _rebucket_view(session, name="aqe_t", n=4096, seed=17):
    rng = np.random.default_rng(seed)
    f = Frame({"k": rng.integers(0, 32, n).astype(np.float64),
               "v": rng.normal(size=n)})
    f.create_or_replace_temp_view(name)
    return f


REBUCKET_SQL = "SELECT k, sum(v) AS s FROM aqe_t WHERE v > 2.0 GROUP BY k"


def _seed_filter_history(session, sql=REBUCKET_SQL):
    """One AQE-off run records the WHERE's observed selectivity; the
    drain makes it readable. Returns the off-arm (reference) result."""
    config.aqe_enabled = False
    ref = _exec(session, sql)
    statstore.STORE.drain_pending()
    return ref


class TestRebucket:
    def test_unit_shrink_preserves_rows_and_slots(self):
        rng = np.random.default_rng(3)
        f = Frame({"k": rng.integers(0, 8, 4096).astype(np.float64),
                   "v": rng.normal(size=4096)}).filter(dq.col("v") > 2.0)
        ref = f.to_pydict()
        observed = len(ref["v"])
        assert 0 < observed < 200
        config.aqe_enabled = True
        out = adaptive.maybe_rebucket(f, est=observed)
        # the survivors compact to their true count; every downstream
        # flush pads to the (much smaller) power-of-two bucket
        assert out.num_slots == observed
        assert bucket_size(out.num_slots) < 4096
        assert _replans("re-bucket") == 1
        _assert_exact(ref, out.to_pydict())

    def test_unit_respects_device_budget(self, monkeypatch):
        rng = np.random.default_rng(4)
        f = Frame({"v": rng.normal(size=4096)}).filter(dq.col("v") > 2.0)
        f._host_mask()
        config.aqe_enabled = True
        monkeypatch.setattr(compiler, "flush_budget", lambda: 8)
        out = adaptive.maybe_rebucket(f, est=64)
        assert out is f                      # shrunk stage still over budget
        assert _replans() == 0

    def test_no_history_means_static_plan(self):
        rng = np.random.default_rng(5)
        f = Frame({"v": rng.normal(size=4096)}).filter(dq.col("v") > 2.0)
        config.aqe_enabled = True
        assert adaptive.maybe_rebucket(f, est=None) is f
        assert _replans() == 0

    def test_sql_rebucket_bit_parity(self, session):
        _rebucket_view(session)
        ref = _seed_filter_history(session)
        config.aqe_enabled = True
        got = _exec(session, REBUCKET_SQL)
        assert _replans("re-bucket") == 1
        _assert_exact(ref, got)

    def test_seeded_workload_fewer_padded_slots(self, session):
        """The acceptance workload: seeded history + a skewed exchange;
        the on-arm re-plans at least once and the re-bucketed stage
        provably runs with fewer padded slots."""
        _rebucket_view(session)
        ref = _seed_filter_history(session)
        config.aqe_enabled = True
        config.aqe_skew_factor = 2.0
        with adaptive.capture() as events:
            got = _exec(session, REBUCKET_SQL)
            lcols, rcols, li, ri = _skewed_plan_inputs()
            shard.partitioned_join_plan(
                _vector_join_plan, lcols, rcols, li, ri, "inner", 4)
        _assert_exact(ref, got)
        assert _replans() >= 2
        rebuckets = [e for e in events if e.trigger == "re-bucket"]
        assert rebuckets and any(e.trigger == "skew-split" for e in events)
        ev = rebuckets[0]
        assert bucket_size(max(ev.est_after, 1)) < ev.est_before


# ---------------------------------------------------------------------------
# Grouped-lowering dense-skip from cardinality history
# ---------------------------------------------------------------------------


class TestGroupedLowering:
    def test_history_above_dense_range_skips_dense(self, session):
        rng = np.random.default_rng(31)
        f = Frame({"k": rng.integers(0, 64, 1024).astype(np.float64),
                   "v": rng.normal(size=1024)})
        f.create_or_replace_temp_view("aqe_g")
        sql = "SELECT k, sum(v) AS s FROM aqe_g GROUP BY k"
        config.aqe_enabled = False
        ref = _exec(session, sql)
        # the off-run recorded the real output cardinality under the
        # executor's own card| key; inflate that SAME entry until the
        # estimated group count clears any dense slot-table range — the
        # dense dispatch (and its host sync) must then be skipped
        cards = [k for k in list(statstore.STORE._entries)
                 if k.startswith("card|")]
        assert cards, "the grouped flush should record cardinality"
        statstore.STORE.record_rows(cards[0], "cardinality",
                                    1, 1_000_000)
        config.aqe_enabled = True
        got = _exec(session, sql)
        assert _replans("grouped-lowering") == 1
        _assert_sorted(ref, got)


# ---------------------------------------------------------------------------
# EXPLAIN surface: == Adaptive == and the disabled-mode pins
# ---------------------------------------------------------------------------


class TestExplain:
    def test_analyze_renders_adaptive_section(self, session):
        _rebucket_view(session)
        _seed_filter_history(session)
        config.aqe_enabled = True
        plan = _exec(session, "EXPLAIN ANALYZE " + REBUCKET_SQL)["plan"][0]
        assert "== Adaptive ==" in plan
        assert "re-bucket:" in plan

    def test_no_replan_renders_no_section(self, session):
        _rebucket_view(session)
        config.aqe_enabled = True     # no history → nothing drifts
        plan = _exec(session, "EXPLAIN ANALYZE " + REBUCKET_SQL)["plan"][0]
        assert "== Adaptive ==" not in plan

    def test_disabled_mode_explain_byte_identical(self, session):
        _rebucket_view(session)
        _seed_filter_history(session)
        config.aqe_enabled = False
        off = _exec(session, "EXPLAIN " + REBUCKET_SQL)["plan"][0]
        config.aqe_enabled = True
        on = _exec(session, "EXPLAIN " + REBUCKET_SQL)["plan"][0]
        assert off == on


class TestDisabledMode:
    def test_hooks_reduce_to_one_conf_read(self, session, monkeypatch):
        """With AQE off every hook is a single flag read: the decision
        functions are monkeypatched to RAISE, so reaching any of them
        fails the test outright."""
        def boom(*a, **kw):
            raise AssertionError("adaptive hook entered with AQE off")

        _rebucket_view(session)
        _seed_filter_history(session)       # leaves aqe_enabled False
        for fn in ("guard", "drift", "record", "maybe_rebucket"):
            monkeypatch.setattr(adaptive, fn, boom)
        # join est hook + exchange skew hook + re-bucket + grouped hook
        rng = np.random.default_rng(41)
        left = Frame({"k": np.arange(30, dtype=np.float64),
                      "v": rng.normal(size=30)})
        right = Frame({"k": (np.arange(512) % 30).astype(np.float64),
                       "w": rng.normal(size=512)})
        left.join(right, on="k", est=(30 * 4096, 512))
        lcols, rcols, li, ri = _skewed_plan_inputs()
        shard.partitioned_join_plan(
            _vector_join_plan, lcols, rcols, li, ri, "inner", 4)
        plan = _exec(session, "EXPLAIN ANALYZE " + REBUCKET_SQL)["plan"][0]
        assert "== Adaptive ==" not in plan
        assert _replans() == 0


# ---------------------------------------------------------------------------
# Degradation ladder: the aqe fault site
# ---------------------------------------------------------------------------


class TestFaultLadder:
    def _flip_scenario(self):
        rng = np.random.default_rng(51)
        left = Frame({"k": np.arange(30, dtype=np.float64),
                      "v": rng.normal(size=30)})
        right = Frame({"k": (np.arange(4096) % 64).astype(np.float64),
                       "w": rng.normal(size=4096)})
        config.aqe_enabled = False
        ref = left.join(right, on="k").to_pydict()
        config.aqe_enabled = True
        return left, right, ref

    @pytest.mark.parametrize("kind", ["device_error", "stall"])
    def test_fault_degrades_decision_to_static_plan(self, kind):
        left, right, ref = self._flip_scenario()
        faults.install_plan(faults.parse_plan(f"aqe:{kind}:1"))
        before = profiling.counters.get("aqe.fallback")
        got = left.join(right, on="k", est=(30 * 4096, 4096)).to_pydict()
        _assert_exact(ref, got)              # golden on the static rung
        assert profiling.counters.get("aqe.fallback") == before + 1
        assert _replans() == 0
        assert any(getattr(e, "site", None) == "aqe"
                   and getattr(e, "action", None) == "fallback"
                   and getattr(e, "rung", None) == "static"
                   for e in RECOVERY_LOG.events())

    def test_fault_degrades_rebucket(self, session):
        _rebucket_view(session)
        ref = _seed_filter_history(session)
        config.aqe_enabled = True
        faults.install_plan(faults.parse_plan("aqe:device_error:1"))
        got = _exec(session, REBUCKET_SQL)
        _assert_exact(ref, got)
        assert _replans("re-bucket") == 0
        assert profiling.counters.get("aqe.fallback") >= 1

    def test_headline_golden_on_every_rung(self, session):
        from sparkdq4ml_tpu.models import LinearRegression

        config.aqe_enabled = True
        faults.install_plan(faults.parse_plan("aqe:device_error:3"))
        df = run_dq_pipeline(session, dataset_path("abstract"))
        assert df.count() == 24
        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(
            prepare_features(df))
        assert float(model.summary.root_mean_squared_error) == \
            pytest.approx(2.809940, rel=1e-3)


# ---------------------------------------------------------------------------
# Satellite: flop-cost term in the level-2 join reorder
# ---------------------------------------------------------------------------


class TestFlopCostReorder:
    def test_flops_for_selectivity_bridges_plan_keys(self):
        # cost profiles land on FULL pipeline plan keys; the optimizer
        # probes by REDUCED selectivity key — the bridge must connect
        # the two and keep the largest recorded program
        statstore.STORE.record_cost("ns:t|f32|F:gt|P:proj", "pipeline",
                                    {"flops": 123.0})
        statstore.STORE.record_cost("f32|F:gt|P:other", "pipeline",
                                    {"flops": 60.0})
        statstore.STORE.record_cost("f32|F:lt|P:proj", "pipeline",
                                    {"flops": 999.0})
        assert statstore.STORE.flops_for_selectivity("f32|F:gt") == 123.0
        assert statstore.STORE.flops_for_selectivity("f32|F:nope") is None
        assert statstore.STORE.flops_for_selectivity(None) is None

    def _register(self, session):
        rng = np.random.default_rng(61)
        big = Frame({"k": rng.integers(0, 64, 2000).astype(np.float64),
                     "v": rng.normal(size=2000)})
        d1 = Frame({"k": np.arange(64, dtype=np.float64),
                    "a": rng.normal(size=64)})
        d2 = Frame({"k": np.arange(64, dtype=np.float64),
                    "b": rng.normal(size=64)})
        for name, f in (("big2", big), ("d1", d1), ("d2", d2)):
            f.create_or_replace_temp_view(name)

    SQL = "SELECT v, a, b FROM big2 JOIN d1 USING (k) JOIN d2 USING (k)"

    def test_equal_rows_cold_flops_keeps_order(self, session):
        self._register(session)
        config.optimizer_enabled = True
        config.optimizer_level = 2
        plan = _exec(session, "EXPLAIN " + self.SQL)["plan"][0]
        assert "join-reorder" not in plan    # 64r vs 64r, no tiebreaker

    def test_flop_term_breaks_row_tie(self, session, monkeypatch):
        self._register(session)
        config.optimizer_enabled = False
        config.optimizer_level = 2
        off = _exec(session, self.SQL)
        # d1's (hypothetical) filter program is the expensive one — the
        # rank term must demote it behind the flop-free d2
        monkeypatch.setattr(
            opt, "_est_rel_flops",
            lambda rel, cat: 1e6 if rel.view == "d1" else None)
        config.optimizer_enabled = True
        on = _exec(session, self.SQL)
        _assert_sorted(off, on)
        plan = _exec(session, "EXPLAIN " + self.SQL)["plan"][0]
        assert "join-reorder" in plan
        assert "smallest rows x flop cost first" in plan


# ---------------------------------------------------------------------------
# Satellite: decorrelation-aware pushdown into subquery branches
# ---------------------------------------------------------------------------


class TestDecorrelatedPushdown:
    SQL = ("SELECT k, v FROM o WHERE EXISTS "
           "(SELECT j FROM i JOIN d USING (j) "
           "WHERE i.k = o.k AND w > 0)")

    def _register(self, session):
        rng = np.random.default_rng(71)
        Frame({"k": rng.integers(0, 40, 200).astype(np.float64),
               "v": rng.normal(size=200)}).create_or_replace_temp_view("o")
        Frame({"k": rng.integers(0, 40, 300).astype(np.float64),
               "j": rng.integers(0, 16, 300).astype(np.float64)}
              ).create_or_replace_temp_view("i")
        Frame({"j": np.arange(16, dtype=np.float64),
               "w": rng.normal(size=16)}).create_or_replace_temp_view("d")

    def test_branch_pushdown_parity_and_counter(self, session):
        self._register(session)
        config.optimizer_enabled = False
        before = profiling.counters.get("optimizer.rewrite")
        off = _exec(session, self.SQL)
        assert profiling.counters.get("optimizer.rewrite") == before
        config.optimizer_enabled = True
        on = _exec(session, self.SQL)
        # the branch is a full SELECT over its own scope: its residual
        # filter pushes into the scan like any executed query's would
        assert profiling.counters.get("optimizer.rewrite") > before
        _assert_exact(off, on)

    def test_outer_explain_pinned_branch_diff_renders(self, session):
        self._register(session)
        config.optimizer_enabled = False
        off = _exec(session, "EXPLAIN " + self.SQL)["plan"][0]
        config.optimizer_enabled = True
        on = _exec(session, "EXPLAIN " + self.SQL)["plan"][0]
        assert off == on                      # outer plan: no rewrites
        branch = _exec(session, "EXPLAIN SELECT j FROM i JOIN d "
                                "USING (j) WHERE w > 0")["plan"][0]
        assert "pushdown: (w > 0) -> Scan[d]" in branch


# ---------------------------------------------------------------------------
# Satellite: session-conf scoping + metric vocabulary
# ---------------------------------------------------------------------------


class TestConfAndMetrics:
    def test_session_conf_scoping(self):
        s = dq.TpuSession.builder().app_name("aqe-conf").master(
            "local[*]").config("spark.aqe.enabled", "false").config(
            "spark.aqe.driftFactor", "2.5").config(
            "spark.aqe.broadcastThreshold", "1234").config(
            "spark.aqe.skewFactor", "9").get_or_create()
        try:
            assert config.aqe_enabled is False
            assert config.aqe_drift_factor == 2.5
            assert config.aqe_broadcast_threshold == 1234
            assert config.aqe_skew_factor == 9.0
        finally:
            s.stop()
        assert config.aqe_enabled is True
        assert config.aqe_drift_factor == 4.0
        assert config.aqe_broadcast_threshold == 8 << 20
        assert config.aqe_skew_factor == 4.0

    def test_metric_vocabulary_registered(self):
        assert "aqe.replans" in obs.METRIC_NAMES
        assert "aqe.fallback" in obs.METRIC_NAMES
        assert "net.page_deadline" in obs.METRIC_NAMES
        assert "aqe.replans." in obs.METRIC_NAME_PREFIXES

    def test_fault_site_registered(self):
        assert "aqe" in faults.FAULT_SITES
        assert set(faults.FAULT_SITES["aqe"]) == {"device_error", "stall"}


# ---------------------------------------------------------------------------
# Satellite: per-page wire-deadline re-check on the stream paths
# ---------------------------------------------------------------------------


class TestPageDeadline:
    @pytest.fixture
    def served(self):
        from sparkdq4ml_tpu.serve import NetServer, QueryServer

        srv = QueryServer(workers=2).start()
        net = NetServer(srv, host="127.0.0.1", port=0,
                        conn_timeout_s=5.0).start()
        srv.net = net
        yield srv, net
        srv.stop()

    @pytest.mark.parametrize("transport", ["frame", "http"])
    def test_expired_deadline_truncates_stream(self, session, served,
                                               monkeypatch, transport):
        from sparkdq4ml_tpu.serve import NetServer, ResilientClient

        srv, net = served
        net.page_rows = 16
        ctx = srv.context("aqetenant")
        ctx.register_view("t", Frame({"x": np.arange(100.0)}))
        # the deadline expired while the result was still streaming —
        # every page boundary re-checks it, so the stream truncates
        # with a structured terminal status instead of running on
        monkeypatch.setattr(
            NetServer, "_stream_deadline",
            staticmethod(lambda fut: time.perf_counter() - 1.0))
        before = profiling.counters.get("net.page_deadline")
        with ResilientClient("127.0.0.1", net.port, transport=transport,
                             tenant="aqetenant") as c:
            r = c.query("SELECT x FROM t")
        assert r.status == "deadline_exceeded"
        assert not r.ok
        assert r.attempts == 1               # terminal: never retried
        assert profiling.counters.get("net.page_deadline") == before + 1
