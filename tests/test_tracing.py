"""Distributed request tracing + incident flight recorder (ISSUE 17).

Pins the tentpole contracts end-to-end:

* strict W3C-style ``traceparent`` parsing — every malformed shape
  (wrong type/length/version, non-hex, all-zero ids, a hostile 1 MB
  header) degrades to a locally-minted root, NEVER an error, over both
  wire framings against a real socket;
* client-side propagation — one trace id per logical query, a fresh
  child span id per attempt and per hedge, the id echoed back on every
  ``ClientResult`` (including client-synthesized ones);
* tail-based sampling — healthy trees age out of the bounded ring,
  error/deadline/fault/breaker/slow trees promote to the retained
  store and resolve via ``TAIL.lookup`` and ``/trace/<id>``;
* the incident flight recorder — atomic on-disk bundles, retention
  pruning, the ``incident`` fault site's degrade-to-memory ladder, and
  the breaker-trip trigger through a real serving stack;
* the disabled-mode contract — byte-identical wire frames and a
  one-flag-read no-op, pinned by monkeypatching every tracing hook to
  raise.
"""

import json
import os
import socket
import struct
import threading
import time

import pytest

from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.serve import NetServer, QueryServer, ResilientClient
from sparkdq4ml_tpu.serve.net import MAGIC
from sparkdq4ml_tpu.utils import faults, incidents, profiling, recovery
from sparkdq4ml_tpu.utils import observability as obs
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _tracing_clean():
    """Every test starts and ends with tracing off, buffers empty, and
    the incident recorder back at factory state."""
    obs.disable()
    obs.reset()
    profiling.counters.clear()
    faults.clear()
    RECOVERY_LOG.clear()
    recovery.DEVICE_BREAKER.reset()
    incidents.RECORDER.reset()
    incidents.RECORDER.configure(enabled=False, directory="",
                                 max_bundles=32, cooldown_s=5.0,
                                 slo_burn_threshold=8.0)
    yield
    obs.disable()
    obs.reset()
    profiling.counters.clear()
    faults.clear()
    RECOVERY_LOG.clear()
    recovery.DEVICE_BREAKER.reset()
    incidents.RECORDER.reset()
    incidents.RECORDER.configure(enabled=False, directory="",
                                 max_bundles=32, cooldown_s=5.0,
                                 slo_burn_threshold=8.0)


@pytest.fixture
def served():
    """A running QueryServer + NetServer on an ephemeral port."""
    srv = QueryServer(workers=2).start()
    net = NetServer(srv, host="127.0.0.1", port=0,
                    conn_timeout_s=2.0).start()
    srv.net = net
    net.register_job("answer", lambda ctx: 7)
    net.register_job("boom", _raise_value_error)
    yield srv, net
    srv.stop()


def _raise_value_error(ctx):
    raise ValueError("deliberate test failure")


VALID_TP = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"


def _frame_exchange(port, docs):
    out = []
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(MAGIC)
        for doc in docs:
            payload = json.dumps(doc).encode()
            s.sendall(struct.pack(">I", len(payload)) + payload)
            frames = []
            while True:
                head = _recv_exactly(s, 4)
                (length,) = struct.unpack(">I", head)
                frames.append(
                    json.loads(_recv_exactly(s, length).decode()))
                if frames[-1].get("end"):
                    break
            out.append(frames)
    return out


def _lookup_soon(trace_id, timeout_s=2.0):
    """Poll ``TAIL.lookup``: the end frame is sent BEFORE the server's
    finally-block finalizes the tree, so a fresh wire result may race
    the sampler by a few scheduler ticks."""
    deadline = time.monotonic() + timeout_s
    while True:
        docs = obs.TAIL.lookup(trace_id)
        if docs or time.monotonic() >= deadline:
            return docs
        time.sleep(0.01)


def _recv_exactly(s, n):
    buf = b""
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        assert chunk, f"peer closed mid-frame ({len(buf)}/{n})"
        buf += chunk
    return buf


# ---------------------------------------------------------------------------
# traceparent parsing: strict in, degrade on everything else
# ---------------------------------------------------------------------------

class TestTraceparentParse:
    def test_valid_traceparent_parses_remote(self):
        ctx = obs.TraceContext.parse(VALID_TP)
        assert ctx is not None and ctx.remote
        assert ctx.trace_id == "ab" * 16
        assert ctx.parent_id == "cd" * 8

    @pytest.mark.parametrize("bad", [
        None,                                       # absent
        1234,                                       # non-string
        b"00-" + b"ab" * 16 + b"-" + b"cd" * 8 + b"-01",  # bytes
        "",                                         # empty
        "garbage",                                  # short junk
        VALID_TP[:-1],                              # truncated by one
        VALID_TP + "0",                             # one char long
        "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # wrong version
        "zz-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # non-hex version
        "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",  # all-zero trace id
        "00-" + "ab" * 16 + "-" + "00" * 8 + "-01",  # all-zero span id
        "00-" + "AB" * 16 + "-" + "cd" * 8 + "-01",  # uppercase hex
        "00-" + "gg" * 16 + "-" + "cd" * 8 + "-01",  # non-hex trace id
        "00" + "-" * 53,                            # right length, dashes
        "00-" + "ab" * 16 + "-" + "cd" * 8 + "-0g",  # non-hex flags
        "x" * (1 << 20),                            # hostile 1 MB value
    ])
    def test_every_malformed_shape_is_rejected(self, bad):
        assert obs.TraceContext.parse(bad) is None

    def test_adopt_degrades_to_local_mint_and_is_idempotent(self):
        local = obs.TraceContext.adopt("not a traceparent")
        assert not local.remote and len(local.trace_id) == 32
        again = obs.TraceContext.adopt(local, defer=True)
        assert again is local and again.defer
        # defer only widens: re-adopting without defer keeps it set
        assert obs.TraceContext.adopt(local).defer

    def test_child_traceparent_fresh_span_id_same_trace(self):
        ctx = obs.TraceContext.mint()
        a, b = ctx.child_traceparent(), ctx.child_traceparent()
        assert a != b
        pa, pb = obs.TraceContext.parse(a), obs.TraceContext.parse(b)
        assert pa.trace_id == pb.trace_id == ctx.trace_id
        assert pa.parent_id != pb.parent_id


# ---------------------------------------------------------------------------
# wire-level degradation: hostile headers never 500, never hang
# ---------------------------------------------------------------------------

class TestWireDegradation:
    @pytest.mark.parametrize("hostile", [
        "garbage", VALID_TP[:-1],
        "01-" + "ab" * 16 + "-" + "cd" * 8 + "-01",
        "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",
    ])
    def test_frame_garbage_traceparent_degrades_to_local_root(
            self, served, hostile):
        srv, net = served
        obs.enable()
        (frames,) = _frame_exchange(net.port, [
            {"job": "answer", "tenant": "t", "traceparent": hostile}])
        end = frames[-1]
        assert end["status"] == "ok"
        # degraded = locally-minted root: an echoed trace id that is NOT
        # the hostile value's id, and resolvable server-side
        assert len(end["trace_id"]) == 32
        assert end["trace_id"] != "ab" * 16
        assert _lookup_soon(end["trace_id"])

    def test_http_garbage_traceparent_degrades_not_500(self, served):
        srv, net = served
        obs.enable()
        body = json.dumps({"job": "answer", "tenant": "t"}).encode()
        req = (b"POST /query HTTP/1.1\r\nHost: dq\r\n"
               b"traceparent: total nonsense value here\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: " + str(len(body)).encode() +
               b"\r\nConnection: close\r\n\r\n" + body)
        with socket.create_connection(("127.0.0.1", net.port),
                                      timeout=10) as s:
            s.sendall(req)
            raw = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                raw += chunk
        status = int(raw.split(b" ", 2)[1])
        assert status == 200
        assert b'"trace_id"' in raw

    def test_http_hostile_1mb_header_is_bounded_never_hangs(self):
        """A 1 MB traceparent header against a small maxFrameBytes is
        refused with a structured 413 inside the connection timeout —
        the length bound fires before any parse work."""
        srv = QueryServer(workers=1).start()
        net = NetServer(srv, host="127.0.0.1", port=0,
                        conn_timeout_s=5.0,
                        max_frame_bytes=64 * 1024).start()
        srv.net = net
        obs.enable()
        try:
            req = (b"POST /query HTTP/1.1\r\nHost: dq\r\n"
                   b"traceparent: " + b"x" * (1 << 20) + b"\r\n"
                   b"Content-Length: 2\r\n\r\n{}")
            t0 = time.monotonic()
            raw = b""
            reset = False
            try:
                with socket.create_connection(
                        ("127.0.0.1", net.port), timeout=15) as s:
                    s.sendall(req)
                    while True:
                        chunk = s.recv(65536)
                        if not chunk:
                            break
                        raw += chunk
            except ConnectionResetError:
                # the server 413s and closes with ~1 MB unread in its
                # receive buffer; that close is a TCP RST which may
                # clobber the response in flight — a prompt reset is
                # still a bounded refusal, not a hang
                reset = True
            took = time.monotonic() - t0
            if not reset:
                assert int(raw.split(b" ", 2)[1]) == 413
            assert took < 10.0, f"hostile header stalled {took:.1f}s"
        finally:
            srv.stop()

    def test_absent_traceparent_still_minted_and_echoed(self, served):
        srv, net = served
        obs.enable()
        (frames,) = _frame_exchange(net.port,
                                    [{"job": "answer", "tenant": "t"}])
        assert len(frames[-1]["trace_id"]) == 32

    def test_valid_traceparent_adopted_verbatim(self, served):
        srv, net = served
        obs.enable()
        (frames,) = _frame_exchange(net.port, [
            {"job": "answer", "tenant": "t", "traceparent": VALID_TP}])
        assert frames[-1]["trace_id"] == "ab" * 16
        (tree,) = _lookup_soon("ab" * 16)
        root = [s for s in tree["spans"]
                if s["name"] == "serve.query"][0]
        assert root["attrs"]["wire_trace_id"] == "ab" * 16
        assert root["attrs"]["wire_parent_id"] == "cd" * 8
        assert root["attrs"]["remote"] is True


# ---------------------------------------------------------------------------
# client propagation: one trace id per logical query, joinable results
# ---------------------------------------------------------------------------

class TestClientPropagation:
    def test_client_result_joins_server_tree(self, served):
        srv, net = served
        obs.enable()
        with ResilientClient("127.0.0.1", net.port,
                             transport="frame") as c:
            r = c.call_job("answer")
        assert r.ok and len(r.trace_id) == 32
        (tree,) = _lookup_soon(r.trace_id)
        names = {s["name"] for s in tree["spans"]}
        assert {"serve.query", "serve.admit",
                "serve.queue"} <= names

    def test_both_transports_carry_the_same_contract(self, served):
        srv, net = served
        obs.enable()
        for transport in ("frame", "http"):
            with ResilientClient("127.0.0.1", net.port,
                                 transport=transport) as c:
                r = c.call_job("answer")
            assert r.ok and r.trace_id, transport
            assert _lookup_soon(r.trace_id), transport

    def test_retries_share_trace_id_with_fresh_attempt_span(self):
        """Each wire attempt re-stamps a fresh child span id under the
        SAME trace id — observed through the per-attempt doc."""
        obs.enable()
        from sparkdq4ml_tpu.serve import client as client_mod

        c = ResilientClient("127.0.0.1", 1, transport="frame")
        seen = []

        def fake_attempt(doc, attempt, remaining):
            seen.append(doc.get("traceparent"))
            if len(seen) < 3:
                raise client_mod.WireError("induced")
            from sparkdq4ml_tpu.serve.client import ClientResult
            return ClientResult(status="ok", tenant="t")

        c._hedged_attempt = fake_attempt
        r = c._run({"job": "x"}, tenant="t", deadline_s=None, tag=None)
        assert r.ok and r.trace_id
        assert len(seen) == 3 and all(seen)
        parsed = [obs.TraceContext.parse(tp) for tp in seen]
        assert len({p.trace_id for p in parsed}) == 1
        assert len({p.parent_id for p in parsed}) == 3
        assert parsed[0].trace_id == r.trace_id

    def test_client_synthesized_results_carry_trace_id(self):
        obs.enable()
        from sparkdq4ml_tpu.utils.recovery import RetryPolicy

        c = ResilientClient(
            "127.0.0.1", 1, transport="frame",
            policy=RetryPolicy(max_attempts=1, backoff_base=0.001))
        r = c.query("SELECT 1")     # nothing listens on port 1
        assert r.status == "error" and r.reason == "net_exhausted"
        assert r.trace_id and len(r.trace_id) == 32

    def test_hedge_doc_restamps_span_id_only(self):
        obs.enable()
        ctx = obs.TraceContext.mint()
        doc = {"job": "x", "traceparent": ctx.child_traceparent()}
        hedged = ResilientClient._hedge_doc(doc)
        p0 = obs.TraceContext.parse(doc["traceparent"])
        p1 = obs.TraceContext.parse(hedged["traceparent"])
        assert p1.trace_id == p0.trace_id == ctx.trace_id
        assert p1.parent_id != p0.parent_id
        # without a traceparent the doc passes through untouched
        assert ResilientClient._hedge_doc({"job": "x"}) == {"job": "x"}


# ---------------------------------------------------------------------------
# tail-based sampling: keep-policy, ring bounds, lookup
# ---------------------------------------------------------------------------

class TestTailSampling:
    def test_healthy_tree_rings_but_is_not_retained(self, served):
        srv, net = served
        obs.enable()
        with ResilientClient("127.0.0.1", net.port,
                             transport="frame") as c:
            r = c.call_job("answer")
        (doc,) = _lookup_soon(r.trace_id)
        assert doc["kept"] is False and doc["keep_reasons"] == []
        assert r.trace_id not in obs.TAIL.retained_ids()

    def test_error_tree_is_kept_and_counted(self, served):
        srv, net = served
        obs.enable()
        with ResilientClient("127.0.0.1", net.port,
                             transport="frame") as c:
            r = c.call_job("boom")
        assert r.status == "error"
        (doc,) = _lookup_soon(r.trace_id)
        assert doc["kept"] and "error" in doc["keep_reasons"]
        assert r.trace_id in obs.TAIL.retained_ids()
        assert profiling.counters.snapshot().get("trace.kept", 0) >= 1

    def test_deadline_tree_is_kept(self, served):
        srv, net = served
        obs.enable()
        slow = threading.Event()
        net.register_job("slow", lambda ctx: slow.wait(2.0))
        from sparkdq4ml_tpu.utils.recovery import RetryPolicy

        with ResilientClient(
                "127.0.0.1", net.port, transport="frame",
                policy=RetryPolicy(max_attempts=1)) as c:
            r = c.call_job("slow", deadline_s=0.15)
        slow.set()
        assert r.status == "deadline_exceeded"
        assert r.trace_id
        deadline_kept = [
            d for d in _lookup_soon(r.trace_id) if d["kept"]]
        assert deadline_kept, "deadline verdict must promote the tree"
        assert any("deadline_exceeded" in d["keep_reasons"]
                   for d in deadline_kept)

    def test_slow_tree_kept_when_over_slo(self):
        obs.enable()
        obs.TAIL.configure(ring_size=8, retained_size=8)
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx, tenant="t"):
            pass
        obs.TAIL.finish_request(ctx, status="ok", reason="",
                                e2e_ms=500.0, breaker_opened=False,
                                slo_ms=100.0)
        (doc,) = obs.TAIL.lookup(ctx.trace_id)
        assert doc["kept"] and doc["keep_reasons"] == ["slow"]

    def test_recovery_fault_annotation_keeps_tree(self):
        obs.enable()
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx, tenant="t") as root:
            root.attrs["recovery_fault"] = "serve_exec:device_error"
        obs.TAIL.finish_request(ctx, status="ok", reason="",
                                e2e_ms=1.0, breaker_opened=False,
                                slo_ms=None)
        (doc,) = obs.TAIL.lookup(ctx.trace_id)
        assert doc["kept"] and doc["keep_reasons"] == ["recovery_fault"]

    def test_ring_is_bounded_and_drops_are_counted(self):
        obs.enable()
        obs.TAIL.configure(ring_size=4, retained_size=4)
        for _ in range(10):
            ctx = obs.TraceContext.mint()
            with obs.request_span("serve.query", ctx):
                pass
            obs.TAIL.finish_request(ctx, status="ok", reason="",
                                    e2e_ms=1.0, breaker_opened=False,
                                    slo_ms=None)
        assert len(obs.TAIL.recent(limit=100)) == 4
        assert profiling.counters.snapshot().get("trace.dropped", 0) == 6

    def test_requeued_attempt_merges_into_one_tree(self):
        """Re-rooting the same context (the serve requeue ladder) carries
        the earlier attempt's spans into the new bucket."""
        obs.enable()
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx, attempt=1):
            pass
        with obs.request_span("serve.query", ctx, attempt=2):
            pass
        obs.TAIL.finish_request(ctx, status="error", reason="",
                                e2e_ms=1.0, breaker_opened=False,
                                slo_ms=None)
        (doc,) = obs.TAIL.lookup(ctx.trace_id)
        roots = [s for s in doc["spans"] if s["name"] == "serve.query"]
        assert len(roots) == 2
        assert {r["attrs"]["attempt"] for r in roots} == {1, 2}

    def test_lookup_unknown_id_is_empty(self):
        obs.enable()
        assert obs.TAIL.lookup("ff" * 16) == []


# ---------------------------------------------------------------------------
# incident flight recorder
# ---------------------------------------------------------------------------

class TestIncidentRecorder:
    def test_bundle_written_atomically_and_loadable(self, tmp_path):
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=0.0)
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx):
            pass
        obs.TAIL.finish_request(ctx, status="error", reason="",
                                e2e_ms=1.0, breaker_opened=True,
                                slo_ms=None)
        iid = incidents.RECORDER.record("breaker_trip", trace=ctx,
                                        detail="test")
        assert iid is not None
        files = [f for f in os.listdir(tmp_path)
                 if f.endswith(".json")]
        assert files == [f"{iid}.json"]
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".tmp")]
        with open(tmp_path / files[0]) as f:
            bundle = json.load(f)
        assert bundle["trigger"] == "breaker_trip"
        assert bundle["trace_id"] == ctx.trace_id
        assert bundle["trace_trees"], "joined span tree must ride along"
        assert "recovery" in bundle and "metrics_delta" in bundle
        assert incidents.RECORDER.get(iid) == bundle
        assert profiling.counters.snapshot().get("incident.written", 0) == 1

    def test_retention_prunes_oldest(self, tmp_path):
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     max_bundles=3, cooldown_s=0.0)
        ids = [incidents.RECORDER.record("slo_burn", detail=str(i))
               for i in range(6)]
        assert all(ids)
        files = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".json"))
        assert len(files) == 3
        assert f"{ids[-1]}.json" in files

    def test_cooldown_suppresses_repeat_triggers(self, tmp_path):
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=60.0)
        assert incidents.RECORDER.record("slo_burn") is not None
        assert incidents.RECORDER.record("slo_burn") is None
        # a DIFFERENT trigger is not suppressed
        assert incidents.RECORDER.record("breaker_trip") is not None

    def test_io_fault_degrades_to_memory_then_disables_disk(
            self, tmp_path):
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=0.0)
        faults.install_plan(faults.parse_plan("incident:io_error:p=1"))
        ids = [incidents.RECORDER.record("slo_burn", detail=str(i))
               for i in range(4)]
        assert all(ids)
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".json")]
        assert profiling.counters.snapshot().get("incident.failed", 0) >= 3
        rep = incidents.RECORDER.report()
        assert rep["disk_disabled"] and rep["in_memory"] == 4
        # bundles are still retrievable from the memory rung
        assert incidents.RECORDER.get(ids[0])["trigger"] == "slo_burn"
        events = [e for e in RECOVERY_LOG.events()
                  if e.site == "incident"]
        assert events and events[-1].rung == "disabled"
        faults.clear()
        # the ladder is terminal for the recorder's lifetime until
        # reconfigured with a directory (which resets the rung)
        incidents.RECORDER.record("slo_burn", detail="post")
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".json")]

    def test_inactive_recorder_records_nothing(self, tmp_path):
        # tracing on but recorder not opted in
        obs.enable()
        assert incidents.RECORDER.record("breaker_trip") is None
        # recorder opted in but tracing off
        obs.disable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=0.0)
        assert incidents.RECORDER.record("breaker_trip") is None
        assert not os.listdir(tmp_path)

    def test_breaker_trip_through_serving_stack(self, tmp_path):
        """Consecutive failures past the breaker threshold fire ONE
        breaker_trip incident with the tripping request's trace id."""
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=0.0)
        srv = QueryServer(workers=1, breaker_threshold=3,
                          breaker_cooldown=30.0).start()
        net = NetServer(srv, host="127.0.0.1", port=0,
                        conn_timeout_s=2.0).start()
        srv.net = net
        net.register_job("boom", _raise_value_error)
        try:
            with ResilientClient("127.0.0.1", net.port,
                                 transport="frame") as c:
                for _ in range(3):
                    r = c.call_job("boom")
                    assert r.status == "error"
        finally:
            srv.stop()
        rows = [r for r in incidents.RECORDER.list()
                if r.get("trigger") == "breaker_trip"]
        assert len(rows) == 1
        bundle = incidents.RECORDER.get(rows[0]["id"])
        assert bundle["trace_id"] and bundle["trace_trees"]
        assert bundle["breaker"], "breaker snapshot rides along"


# ---------------------------------------------------------------------------
# telemetry surfaces: /trace filter, /trace/<id>, /incidents, exemplars
# ---------------------------------------------------------------------------

class TestTelemetrySurfaces:
    @pytest.fixture
    def telemetry(self):
        from sparkdq4ml_tpu.serve.http import TelemetryServer

        t = TelemetryServer(None, port=0).start()
        yield t
        t.stop()

    @staticmethod
    def _get(port, path):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}") as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def _one_tree(self, status="error"):
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx, tenant="t"):
            pass
        obs.TAIL.finish_request(ctx, status=status, reason="",
                                e2e_ms=3.0, breaker_opened=False,
                                slo_ms=None)
        return ctx

    def test_trace_route_filters_by_trace_id_and_limit(self, telemetry):
        obs.enable()
        ctx = self._one_tree()
        self._one_tree()
        code, doc = self._get(telemetry.port,
                              f"/trace?trace_id={ctx.trace_id}")
        assert code == 200
        assert doc["spans"], "filter must match the wire trace id"
        assert all(s["attrs"].get("wire_trace_id") == ctx.trace_id
                   for s in doc["spans"])
        code, doc = self._get(telemetry.port, "/trace?limit=1")
        assert code == 200 and len(doc["spans"]) == 1
        # a bogus limit falls back to the default bound, not a 500
        code, _ = self._get(telemetry.port, "/trace?limit=bogus")
        assert code == 200

    def test_trace_tree_route_and_404(self, telemetry):
        obs.enable()
        ctx = self._one_tree()
        code, doc = self._get(telemetry.port, f"/trace/{ctx.trace_id}")
        assert code == 200
        assert doc["trace_id"] == ctx.trace_id
        assert doc["trees"][0]["kept"]
        code, _ = self._get(telemetry.port, "/trace/" + "ee" * 16)
        assert code == 404

    def test_incidents_routes(self, telemetry, tmp_path):
        obs.enable()
        incidents.RECORDER.configure(directory=str(tmp_path),
                                     cooldown_s=0.0)
        iid = incidents.RECORDER.record("fault_ladder", detail="t")
        code, doc = self._get(telemetry.port, "/incidents")
        assert code == 200
        assert [r["id"] for r in doc["incidents"]] == [iid]
        code, bundle = self._get(telemetry.port, f"/incidents/{iid}")
        assert code == 200 and bundle["id"] == iid
        code, _ = self._get(telemetry.port, "/incidents/inc-nope")
        assert code == 404

    def test_exemplars_only_behind_conf_flag(self):
        obs.enable()
        ctx = self._one_tree()        # kept → exemplar registered
        assert obs.TAIL.exemplars("serve.e2e_ms")
        obs.METRICS.observe("serve.e2e_ms", 3.0)
        saved = config.trace_exemplars
        try:
            config.trace_exemplars = False
            assert "# {trace_id=" not in obs.prometheus_text()
            config.trace_exemplars = True
            text = obs.prometheus_text()
            assert f'# {{trace_id="{ctx.trace_id}"}}' in text
        finally:
            config.trace_exemplars = saved


# ---------------------------------------------------------------------------
# disabled mode: byte-identical wire + one-flag-read no-op
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_wire_frames_byte_identical_and_hooks_never_run(
            self, served, monkeypatch):
        """With observability off, NO tracing hook may execute (pinned
        by raising from all of them) and the wire docs must not grow a
        traceparent/trace_id key."""
        srv, net = served
        assert not obs.TRACER.enabled

        def boom(*a, **k):
            raise AssertionError("tracing hook ran while disabled")

        monkeypatch.setattr(obs.TraceContext, "mint",
                            classmethod(boom))
        monkeypatch.setattr(obs.TraceContext, "adopt",
                            classmethod(boom))
        monkeypatch.setattr(obs.TAIL, "open_request", boom)
        monkeypatch.setattr(obs.TAIL, "finish_request", boom)
        monkeypatch.setattr(obs.TAIL, "complete", boom)
        monkeypatch.setattr(incidents.RECORDER, "record", boom)
        (frames,) = _frame_exchange(net.port,
                                    [{"job": "answer", "tenant": "t"}])
        end = frames[-1]
        assert end["status"] == "ok"
        assert "trace_id" not in end
        with ResilientClient("127.0.0.1", net.port,
                             transport="http") as c:
            r = c.call_job("answer")
        assert r.ok and r.trace_id is None

    def test_request_span_is_shared_noop_when_disabled(self):
        assert obs.request_span("x", obs.TraceContext("a" * 32)) \
            is obs._NOOP
        obs.enable()
        assert obs.request_span("x", None) is obs._NOOP

    def test_emit_span_noop_when_disabled(self):
        obs.emit_span("x", dur_ms=5.0)      # must not raise or record
        assert obs.TRACER.spans() == []


# ---------------------------------------------------------------------------
# conf vocabulary: session-scoped save/restore
# ---------------------------------------------------------------------------

class TestTracingConf:
    def test_trace_and_incident_conf_applied_and_restored(
            self, tmp_path):
        import sparkdq4ml_tpu as dq

        before = (config.trace_ring_size, config.trace_retained_size,
                  config.trace_exemplars, config.incident_enabled,
                  config.incident_dir, config.incident_max_bundles,
                  config.incident_cooldown_s,
                  config.incident_slo_burn_threshold)
        s = (dq.TpuSession.builder()
             .config("spark.trace.ringSize", 99)
             .config("spark.trace.retainedSize", 11)
             .config("spark.trace.exemplars", "true")
             .config("spark.incident.enabled", "true")
             .config("spark.incident.dir", str(tmp_path))
             .config("spark.incident.maxBundles", 5)
             .config("spark.incident.cooldownS", 0.5)
             .config("spark.incident.sloBurnThreshold", 3.0)
             .get_or_create())
        try:
            assert config.trace_ring_size == 99
            assert config.trace_retained_size == 11
            assert config.trace_exemplars is True
            assert config.incident_enabled is True
            assert config.incident_dir == str(tmp_path)
            assert config.incident_max_bundles == 5
            assert config.incident_cooldown_s == 0.5
            assert config.incident_slo_burn_threshold == 3.0
            # and the process-global instances picked the bounds up
            assert obs.TAIL.ring_size == 99
            assert obs.TAIL.retained_size == 11
            assert incidents.RECORDER.directory == str(tmp_path)
            assert incidents.RECORDER.max_bundles == 5
        finally:
            s.stop()
        after = (config.trace_ring_size, config.trace_retained_size,
                 config.trace_exemplars, config.incident_enabled,
                 config.incident_dir, config.incident_max_bundles,
                 config.incident_cooldown_s,
                 config.incident_slo_burn_threshold)
        assert after == before

    def test_incident_report_shape(self, tmp_path):
        import sparkdq4ml_tpu as dq

        s = (dq.TpuSession.builder()
             .config("spark.observability.enabled", "true")
             .config("spark.incident.dir", str(tmp_path))
             .config("spark.incident.cooldownS", 0)
             .get_or_create())
        try:
            iid = incidents.RECORDER.record("slo_burn", detail="rpt")
            rep = s.incident_report()
            assert rep["active"] and rep["dir"] == str(tmp_path)
            assert [r["id"] for r in rep["incidents"]] == [iid]
            assert "tail" in rep and "ring_size" in rep["tail"]
        finally:
            s.stop()
