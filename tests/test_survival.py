"""AFTSurvivalRegression: coefficient parity vs a scipy BFGS fit of the
identical Weibull-AFT likelihood, censoring semantics, quantile math,
sharded≡single, persistence."""

import numpy as np
import pytest

from conftest import assert_devices
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (AFTSurvivalRegression,
                                   AFTSurvivalRegressionModel,
                                   VectorAssembler)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def aft_data(n=250, seed=0, censor_frac=0.3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 2))
    beta = np.asarray([0.8, -0.5])
    sigma = 0.5
    eps = np.log(rng.exponential(size=n))          # Gumbel(min) via -log E
    t = np.exp(1.2 + X @ beta + sigma * eps)
    censor = (rng.random(n) > censor_frac).astype(np.float64)
    # censored rows observe a time before the event
    t_obs = np.where(censor == 1.0, t, t * rng.uniform(0.3, 1.0, size=n))
    return X, t_obs, censor


def build_frame(X, t, c):
    cols = {"x0": X[:, 0], "x1": X[:, 1], "label": t, "censor": c}
    return VectorAssembler(["x0", "x1"], "features").transform(Frame(cols))


def scipy_aft(X, t, c):
    """BFGS on the identical negative log-likelihood (the test oracle)."""
    from scipy.optimize import minimize

    n, d = X.shape
    mu_x = X.mean(axis=0)
    sd_x = X.std(ddof=1, axis=0)
    Xs = (X - 0.0) / sd_x       # match the model: scale only, no centering
    lt = np.log(t)

    def nll(p):
        beta, b0, logsig = p[:d], p[d], p[d + 1]
        sig = np.exp(logsig)
        eps = (lt - b0 - Xs @ beta) / sig
        return np.sum(np.exp(eps) - c * (eps - logsig)) / n

    p0 = np.zeros(d + 2)
    p0[d] = lt.mean()
    r = minimize(nll, p0, method="BFGS", options={"maxiter": 500})
    return r.x[:d] / sd_x, r.x[d], float(np.exp(r.x[d + 1]))


class TestAFT:
    def test_matches_scipy_mle(self):
        X, t, c = aft_data()
        f = build_frame(X, t, c)
        model = AFTSurvivalRegression(max_iter=800, step_size=0.05).fit(f)
        beta_ref, b0_ref, sig_ref = scipy_aft(X, t, c)
        np.testing.assert_allclose(model.coefficients, beta_ref,
                                   rtol=2e-2, atol=2e-3)
        assert model.intercept == pytest.approx(b0_ref, rel=2e-2)
        assert model.scale == pytest.approx(sig_ref, rel=5e-2)

    def test_recovers_planted_coefficients(self):
        X, t, c = aft_data(n=800, seed=3, censor_frac=0.2)
        f = build_frame(X, t, c)
        model = AFTSurvivalRegression(max_iter=800, step_size=0.05).fit(f)
        # planted betas (0.8, -0.5) — censoring biases slightly
        assert model.coefficients[0] == pytest.approx(0.8, abs=0.15)
        assert model.coefficients[1] == pytest.approx(-0.5, abs=0.15)
        assert 0.3 < model.scale < 0.8

    def test_censoring_changes_fit(self):
        X, t, _ = aft_data(seed=5)
        f_all = build_frame(X, t, np.ones_like(t))
        f_cens = build_frame(X, t, np.zeros_like(t))
        m1 = AFTSurvivalRegression(max_iter=200).fit(f_all)
        m2 = AFTSurvivalRegression(max_iter=200).fit(f_cens)
        assert not np.allclose(m1.coefficients, m2.coefficients)

    def test_quantiles_and_predict(self):
        X, t, c = aft_data()
        f = build_frame(X, t, c)
        est = AFTSurvivalRegression(max_iter=300,
                                    quantile_probabilities=(0.25, 0.5, 0.75),
                                    quantiles_col="q")
        model = est.fit(f)
        p = model.predict(X[0])
        qs = model.predict_quantiles(X[0])
        mu = np.log(p)
        expect = p * (-np.log1p(-np.asarray([0.25, 0.5, 0.75]))) ** \
            model.scale
        np.testing.assert_allclose(qs, expect, rtol=1e-9)
        assert np.all(np.diff(qs) > 0)               # quantiles ascend
        d = model.transform(f).to_pydict()
        assert np.asarray(d["q"]).shape == (250, 3)
        assert np.all(np.isfinite(np.asarray(d["prediction"])))

    def test_validations(self):
        X, t, c = aft_data(n=40)
        t[3] = -1.0
        with pytest.raises(ValueError, match="> 0"):
            AFTSurvivalRegression(max_iter=10).fit(build_frame(X, t, c))
        t[3] = np.inf
        with pytest.raises(ValueError, match="finite"):
            AFTSurvivalRegression(max_iter=10).fit(build_frame(X, t, c))
        t[3] = 1.0
        c[5] = 0.5
        with pytest.raises(ValueError, match="censor"):
            AFTSurvivalRegression(max_iter=10).fit(build_frame(X, t, c))
        with pytest.raises(ValueError, match="quantile"):
            AFTSurvivalRegression(quantile_probabilities=(0.5, 1.0))
        assert AFTSurvivalRegression().setPredictionCol(
            "p").prediction_col == "p"

    def test_sharded_equals_single(self):
        assert_devices(8)
        X, t, c = aft_data(n=203, seed=7)
        f = build_frame(X, t, c)
        kw = dict(max_iter=150, step_size=0.05)
        single = AFTSurvivalRegression(**kw).fit(f)
        sharded = AFTSurvivalRegression(**kw).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(sharded.coefficients,
                                   single.coefficients, rtol=1e-7,
                                   atol=1e-9)
        assert sharded.scale == pytest.approx(single.scale, rel=1e-7)

    def test_masked_rows_excluded(self):
        X, t, c = aft_data(n=100, seed=9)
        keep = np.ones(100, bool)
        keep[::5] = False
        tp = t.copy()
        tp[~keep] = 1e9                 # poisoned survival times, masked
        f_masked = build_frame(X, tp, c).filter(keep)
        f_clean = build_frame(X[keep], t[keep], c[keep])
        kw = dict(max_iter=150, step_size=0.05)
        m1 = AFTSurvivalRegression(**kw).fit(f_masked)
        m2 = AFTSurvivalRegression(**kw).fit(f_clean)
        np.testing.assert_allclose(m1.coefficients, m2.coefficients,
                                   rtol=1e-7, atol=1e-9)

    def test_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        X, t, c = aft_data(n=60)
        model = AFTSurvivalRegression(max_iter=100).fit(build_frame(X, t, c))
        model.save(str(tmp_path / "aft"))
        loaded = load_stage(str(tmp_path / "aft"))
        assert isinstance(loaded, AFTSurvivalRegressionModel)
        assert loaded.predict(X[0]) == pytest.approx(model.predict(X[0]))
