"""Round-3 scalar-function sweep (math + string) and stat.sampleBy, through
the column API and SQL."""

import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu import functions as F


@pytest.fixture
def nums():
    return Frame({"x": [0.0, 0.5, 1.0], "y": [3.0, 4.0, 0.0]})


@pytest.fixture
def strs():
    return Frame({"s": ["hello world", "a,b,c", None],
                  "t": ["x", "y", "z"]})


def one_col(frame, expr, name="o"):
    return list(frame.with_column(name, expr).to_pydict()[name])


class TestMath:
    def test_trig_numpy_parity(self, nums):
        got = one_col(nums, F.sin(F.col("x")))
        np.testing.assert_allclose(got, np.sin([0.0, 0.5, 1.0]), rtol=1e-6)
        got = one_col(nums, F.atan2(F.col("y"), F.col("x")))
        np.testing.assert_allclose(
            got, np.arctan2([3.0, 4.0, 0.0], [0.0, 0.5, 1.0]), rtol=1e-6)

    def test_hypot_log1p(self, nums):
        got = one_col(nums, F.hypot(F.col("x"), F.col("y")))
        np.testing.assert_allclose(got, np.hypot([0, .5, 1], [3, 4, 0]),
                                   rtol=1e-6)
        got = one_col(nums, F.log1p(F.col("x")))
        np.testing.assert_allclose(got, np.log1p([0, .5, 1]), rtol=1e-6)

    def test_degrees_radians_roundtrip(self, nums):
        got = one_col(nums, F.radians(F.degrees(F.col("x"))))
        np.testing.assert_allclose(got, [0.0, 0.5, 1.0], rtol=1e-6)

    def test_sql_math(self, nums):
        s = dq.TpuSession.builder().app_name("fx").get_or_create()
        nums.create_or_replace_temp_view("nums")
        out = s.sql("SELECT TANH(x) AS th FROM nums").to_pydict()
        np.testing.assert_allclose(out["th"], np.tanh([0, .5, 1]), rtol=1e-6)


class TestString:
    def test_regexp_replace_extract(self, strs):
        got = one_col(strs, F.regexp_replace(F.col("s"), r"[aeiou]", "_"))
        assert got[0] == "h_ll_ w_rld" and got[2] is None
        got = one_col(strs, F.regexp_extract(F.col("s"), r"(\w+) (\w+)", 2))
        assert got[0] == "world" and got[1] == ""

    def test_split(self, strs):
        got = one_col(strs, F.split(F.col("s"), ","))
        assert got[1] == ["a", "b", "c"] and got[2] is None

    def test_concat_ws_skips_nulls(self, strs):
        got = one_col(strs, F.concat_ws("-", F.col("s"), F.col("t")))
        assert got[0] == "hello world-x"
        assert got[2] == "z"                      # null s skipped, not nulled

    def test_pads_and_repeat_reverse(self, strs):
        got = one_col(strs, F.lpad(F.col("t"), 3, "0"))
        assert got[0] == "00x"
        got = one_col(strs, F.rpad(F.col("t"), 3, "ab"))
        assert got[0] == "xab"
        got = one_col(strs, F.repeat(F.col("t"), 3))
        assert got[0] == "xxx"
        got = one_col(strs, F.reverse(F.col("s")))
        assert got[0] == "dlrow olleh"

    def test_truncating_pad(self, strs):
        got = one_col(strs, F.lpad(F.col("s"), 5, "*"))
        assert got[0] == "hello"                  # Spark truncates past len

    def test_instr_locate(self, strs):
        got = one_col(strs, F.instr(F.col("s"), "world"))
        assert got[0] == 7                        # 1-based
        assert np.isnan(np.float64(got[2]))       # Spark: instr(null)=null
        got = one_col(strs, F.locate("l", F.col("s"), 5))
        assert got[0] == 10                       # search starts at pos 5

    def test_initcap_translate(self, strs):
        got = one_col(strs, F.initcap(F.col("s")))
        assert got[0] == "Hello World"
        got = one_col(strs, F.translate(F.col("s"), "lo", "01"))
        assert got[0] == "he001 w1r0d"

    def test_sql_string_fns(self, strs):
        s = dq.TpuSession.builder().app_name("fs").get_or_create()
        strs.create_or_replace_temp_view("strs")
        out = s.sql("SELECT INITCAP(t) AS i FROM strs").to_pydict()
        assert list(out["i"]) == ["X", "Y", "Z"]


class TestSampleBy:
    def test_stratified_fractions(self):
        rng = np.random.default_rng(0)
        g = np.asarray(["a", "b"])[rng.integers(0, 2, size=4000)]
        f = Frame({"g": g, "v": np.arange(4000, dtype=np.float64)})
        out = f.stat.sample_by("g", {"a": 0.8, "b": 0.1}, seed=3)
        d = out.to_pydict()
        kept = dict(zip(*np.unique(d["g"], return_counts=True)))
        total = dict(zip(*np.unique(g, return_counts=True)))
        assert abs(kept["a"] / total["a"] - 0.8) < 0.05
        assert abs(kept["b"] / total["b"] - 0.1) < 0.05

    def test_absent_stratum_sampled_at_zero(self):
        f = Frame({"g": ["a", "a", "c", "c"], "v": [1.0, 2.0, 3.0, 4.0]})
        out = f.stat.sample_by("g", {"a": 1.0}, seed=1)
        assert set(out.to_pydict()["g"]) == {"a"}

    def test_validation(self):
        f = Frame({"g": ["a"], "v": [1.0]})
        with pytest.raises(ValueError, match="stratum"):
            f.stat.sample_by("g", {"a": 1.5})

    def test_numeric_strata(self):
        f = Frame({"k": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]})
        out = f.stat.sampleBy("k", {1: 1.0, 2: 0.0}, seed=0)
        assert out.to_pydict()["k"].tolist() == [1, 1]


class TestReviewRegressions:
    def test_column_valued_pattern_rejected(self, strs):
        with pytest.raises(ValueError, match="must be a literal"):
            one_col(strs, F.fn("instr", F.col("s"), F.col("t")))

    def test_concat_ws_skips_nan(self):
        f = Frame({"x": [1.0, np.nan], "t": ["a", "b"]})
        got = one_col(f, F.concat_ws("-", F.col("x"), F.col("t")))
        assert got[0] == "1.0-a" and got[1] == "b"

    def test_pad_nonpositive_length_empty(self, strs):
        assert one_col(strs, F.lpad(F.col("t"), -1, "*"))[0] == ""
        assert one_col(strs, F.rpad(F.col("t"), 0, "*"))[0] == ""

    def test_translate_first_mapping_wins(self, strs):
        got = one_col(strs, F.translate(F.col("t"), "xx", "12"))
        assert got[0] == "1"


class TestArrayFunctions:
    """array_contains / element_at / size over list cells (split output)."""

    def _frame(self):
        from sparkdq4ml_tpu import Frame
        return Frame({"s": np.asarray(["a,b,c", "x", None], dtype=object)})

    def test_array_contains(self):
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        o = np.asarray(f.with_column("h", F.array_contains(F.col("arr"),
                                                           F.lit("b")))
                        .to_pydict()["h"], np.float64)
        assert o[0] == 1.0 and o[1] == 0.0
        assert np.isnan(o[2])                  # null cell -> null

    def test_element_at_one_based_and_negative(self):
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        o = (f.with_column("e2", F.element_at(F.col("arr"), 2))
              .with_column("last", F.element_at(F.col("arr"), -1))
              .with_column("oob", F.element_at(F.col("arr"), 9))).to_pydict()
        assert list(o["e2"]) == ["b", None, None]
        assert list(o["last"]) == ["c", "x", None]
        assert list(o["oob"]) == [None, None, None]

    def test_element_at_zero_rejected(self):
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        with pytest.raises(ValueError, match="1-based"):
            f.with_column("z", F.element_at(F.col("arr"), 0)).to_pydict()

    def test_size_with_legacy_null(self):
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        o = f.with_column("n", F.size(F.col("arr"))).to_pydict()["n"]
        assert list(np.asarray(o)) == [3, 1, -1]   # Spark 2.4 sizeOfNull

    def test_null_predicate_drops_row_in_filter(self):
        # SQL three-valued logic: WHERE over a null predicate excludes
        # the row (a bare NaN->bool cast would keep it)
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        kept = f.filter(F.array_contains(F.col("arr"), F.lit("b")))
        assert kept.count() == 1
        assert list(kept.to_pydict()["s"]) == ["a,b,c"]

    def test_bare_string_value_is_literal(self):
        f = self._frame().with_column("arr", F.split(F.col("s"), ","))
        o = np.asarray(f.with_column("h", F.array_contains(F.col("arr"), "b"))
                        .to_pydict()["h"], np.float64)
        assert o[0] == 1.0 and o[1] == 0.0

    def test_non_array_column_rejected(self):
        f = self._frame()
        with pytest.raises(ValueError, match="array column"):
            f.with_column("n", F.size(F.col("s"))).to_pydict()


class TestExplode:
    def _frame(self):
        from sparkdq4ml_tpu import Frame
        f = Frame({"id": np.asarray([1.0, 2.0, 3.0]),
                   "s": np.asarray(["a,b", "c", None], dtype=object)})
        return f.with_column("arr", F.split(F.col("s"), ","))

    def test_method_form(self):
        out = self._frame().explode("arr", "x").to_pydict()
        assert list(out["x"]) == ["a", "b", "c"]
        np.testing.assert_allclose(np.asarray(out["id"]), [1.0, 1.0, 2.0])

    def test_null_and_empty_rows_dropped(self):
        from sparkdq4ml_tpu import Frame, functions as F2
        f = Frame({"s": np.asarray([None, ""], dtype=object)}) \
            .with_column("arr", F2.split(F2.col("s"), ","))
        # null cell drops; "" splits to [""] (one empty-string element)
        out = f.explode("arr", "x")
        assert list(out.to_pydict()["x"]) == [""]

    def test_explode_outer(self):
        out = self._frame().explode("arr", "x", keep_nulls=True).to_pydict()
        assert len(out["x"]) == 4
        assert out["x"][3] is None

    def test_select_generator_form(self):
        out = self._frame().select(
            "id", F.explode(F.col("arr")).alias("x")).to_pydict()
        assert list(out["x"]) == ["a", "b", "c"]
        np.testing.assert_allclose(np.asarray(out["id"]), [1.0, 1.0, 2.0])

    def test_default_generator_name_is_col(self):
        out = self._frame().select("id", F.explode(F.col("arr")))
        assert out.columns == ["id", "col"]

    def test_two_generators_rejected(self):
        f = self._frame()
        with pytest.raises(ValueError, match="one explode"):
            f.select(F.explode(F.col("arr")), F.explode(F.col("arr")))

    def test_eval_outside_select_raises(self):
        f = self._frame()
        with pytest.raises(ValueError, match="generator"):
            f.with_column("x", F.explode(F.col("arr")))

    def test_numeric_elements_land_on_device(self):
        from sparkdq4ml_tpu.frame.frame import Frame, list_column
        f = Frame({"arr": list_column([[1.0, 2.0], [3.0]])})
        out = f.explode("arr").to_pydict()
        np.testing.assert_allclose(np.asarray(out["arr"]), [1.0, 2.0, 3.0])

    def test_masked_rows_never_explode(self):
        import sparkdq4ml_tpu as dq
        f = self._frame().filter(dq.col("id") < 2.0)
        out = f.explode("arr", "x").to_pydict()
        assert list(out["x"]) == ["a", "b"]

    def test_source_column_kept_when_selected(self):
        out = self._frame().select(
            "arr", F.explode(F.col("arr")).alias("x")).to_pydict()
        assert "arr" in out and "x" in out
        assert out["arr"][0] == ["a", "b"]        # repeated source cell

    def test_explode_of_expression(self):
        from sparkdq4ml_tpu import Frame
        f = Frame({"s": np.asarray(["a,b", "c"], dtype=object)})
        out = f.select(F.explode(F.split(F.col("s"), ",")).alias("x"))
        assert list(out.to_pydict()["x"]) == ["a", "b", "c"]

    def test_cast_of_explode_gives_generator_error(self):
        f = self._frame()
        with pytest.raises(ValueError, match="generator"):
            f.select("id", F.explode(F.col("arr")).cast("int"))

    def test_plain_string_column_rejected(self):
        f = self._frame()
        with pytest.raises(ValueError, match="array column"):
            f.explode("s")

    def test_all_null_outer_stays_object(self):
        from sparkdq4ml_tpu import Frame, functions as F2
        f = Frame({"s": np.asarray([None], dtype=object)}) \
            .with_column("arr", F2.split(F2.col("s"), ","))
        out = f.explode("arr", "x", keep_nulls=True).to_pydict()
        assert out["x"][0] is None                # None, not float NaN

    def test_explode_outer_select_form(self):
        out = self._frame().select(
            "id", F.explode_outer(F.col("arr")).alias("x")).to_pydict()
        assert len(out["x"]) == 4
        assert out["x"][3] is None
        assert np.asarray(out["id"])[3] == 3.0

    def test_posexplode(self):
        out = self._frame().select(
            "id", F.posexplode(F.col("arr"))).to_pydict()
        assert list(np.asarray(out["pos"])) == [0, 1, 0]
        assert list(out["col"]) == ["a", "b", "c"]

    def test_posexplode_alias_names_value_column(self):
        out = self._frame().select(
            F.posexplode(F.col("arr")).alias("v")).to_pydict()
        assert "pos" in out and "v" in out

    def test_posexplode_spark_column_order(self):
        out = self._frame().select("id", F.posexplode(F.col("arr")))
        assert out.columns == ["id", "pos", "col"]   # Spark's (pos, col)

    def test_position_name_collision_raises(self):
        from sparkdq4ml_tpu import Frame, functions as F2
        f = Frame({"pos": np.asarray([1.0, 2.0]),
                   "s": np.asarray(["a,b", "c"], dtype=object)})
        fa = f.with_column("arr", F2.split(F2.col("s"), ","))
        with pytest.raises(ValueError, match="collides"):
            fa.select("pos", F2.posexplode(F2.col("arr")))


class TestNullSemanticsProbes:
    """Spark null-handling parity found by probing: greatest/least skip
    nulls (NULL only when all operands are null); string fns over a NULL
    (float-NaN) input yield NULL instead of crashing."""

    def test_greatest_least_skip_nulls(self, session):
        d = session.sql("SELECT greatest(1, NULL, 3) AS g, "
                        "least(5, NULL, 3) AS l, "
                        "greatest(NULL, NULL) AS an").to_pydict()
        assert d["g"].tolist() == [3.0]
        assert d["l"].tolist() == [3.0]
        import numpy as np
        assert np.isnan(d["an"][0])

    def test_string_fn_over_null_literal(self, session):
        d = session.sql("SELECT upper(NULL) AS u, lower(NULL) AS lo, "
                        "trim(NULL) AS t").to_pydict()
        assert list(d["u"]) == [None]
        assert list(d["lo"]) == [None]
        assert list(d["t"]) == [None]


class TestDivisionModSemantics:
    """Spark non-ANSI arithmetic: x/0 and x%0 are NULL; % sign follows
    the dividend, pmod's the divisor."""

    def test_divide_by_zero_is_null(self, session):
        import numpy as np
        d = session.sql("SELECT 1 / 0 AS a, 0.0 / 0 AS b, 10 / 4 AS c") \
            .to_pydict()
        assert np.isnan(d["a"][0]) and np.isnan(d["b"][0])
        assert d["c"][0] == 2.5

    def test_mod_family(self, session):
        import numpy as np
        d = session.sql("SELECT 7 % 3 AS a, mod(0-7, 3) AS m, "
                        "pmod(0-7, 3) AS p, 5 % 0 AS z").to_pydict()
        assert d["a"][0] == 1.0
        assert d["m"][0] == -1.0     # dividend sign (Java/Spark %)
        assert d["p"][0] == 2.0      # positive modulus
        assert np.isnan(d["z"][0])

    def test_fluent_mod_operator(self):
        from sparkdq4ml_tpu import Frame
        f = Frame({"x": [7.0, -7.0]})
        assert f.with_column("m", f["x"] % 3).to_pydict()["m"].tolist() \
            == [1.0, -1.0]


class TestKeywordNamedStringFns:
    def test_left_right_call_forms(self, session):
        d = session.sql("SELECT left('hello', 2) AS l, "
                        "right('hello', 2) AS r").to_pydict()
        assert list(d["l"]) == ["he"] and list(d["r"]) == ["lo"]

    def test_overlay(self, session):
        d = session.sql("SELECT overlay('hello', 'XX', 2) AS a, "
                        "overlay('hello', 'XX', 2, 3) AS b").to_pydict()
        assert list(d["a"]) == ["hXXlo"]
        assert list(d["b"]) == ["hXXo"]

    def test_left_join_grammar_unaffected(self, session):
        from sparkdq4ml_tpu import Frame
        Frame({"k": [1.0], "x": [2.0]}).create_or_replace_temp_view("lj_a")
        Frame({"k": [1.0], "y": [3.0]}).create_or_replace_temp_view("lj_b")
        out = session.sql("SELECT x, y FROM lj_a LEFT JOIN lj_b USING (k)")
        assert out.count() == 1
        session.catalog.drop("lj_a")
        session.catalog.drop("lj_b")


class TestRowFunctions:
    """Frame-aware nullary fns: mono id, rand/randn, uuid, typeof."""

    def test_monotonically_increasing_id(self, session):
        from sparkdq4ml_tpu import Frame, functions as F
        f = Frame({"x": [5.0, 6.0, 7.0]})
        ids = f.with_column("id", F.monotonically_increasing_id()) \
            .to_pydict()["id"].tolist()
        assert ids == [0, 1, 2]

    def test_rand_deterministic_with_seed(self, session):
        from sparkdq4ml_tpu import Frame
        Frame({"x": [1.0, 2.0]}).create_or_replace_temp_view("rf")
        a = session.sql("SELECT rand(7) AS r FROM rf").to_pydict()["r"]
        b = session.sql("SELECT rand(7) AS r FROM rf").to_pydict()["r"]
        assert (a == b).all()
        assert ((a >= 0) & (a < 1)).all()
        session.catalog.drop("rf")

    def test_uuid_unique_per_row(self, session):
        from sparkdq4ml_tpu import Frame
        Frame({"x": [1.0, 2.0, 3.0]}).create_or_replace_temp_view("uf")
        u = session.sql("SELECT uuid() AS u FROM uf").to_pydict()["u"]
        assert len(set(u)) == 3 and all(len(x) == 36 for x in u)
        session.catalog.drop("uf")

    def test_typeof(self, session):
        from sparkdq4ml_tpu import Frame
        Frame({"x": [1.0]}).create_or_replace_temp_view("tf")
        d = session.sql("SELECT typeof(x) AS a, typeof('s') AS b FROM tf") \
            .to_pydict()
        assert list(d["a"]) == ["double"] and list(d["b"]) == ["string"]
        session.catalog.drop("tf")
