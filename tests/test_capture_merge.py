"""merge_best: the capture daemon's per-measurement min-estimator.

Contention on the shared 1-core host is strictly additive on every
measured time (observed live: the same sweep captured 0.0247 ms idle vs
0.3782 ms while pytest ran; sklearn baselines inflated ~2x when a test
run overlapped the daemon's bench), so min over runs per measurement is
the honest point estimate — the cross-run analogue of min-over-reps
inside one run.
"""

import importlib.util
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "tpu_capture_daemon",
    os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                 "tpu_capture_daemon.py"))
daemon = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(daemon)


def _capture(device_ms, baseline_ms, xla_ms=1.0, pallas_ms=None,
             backend="tpu", **cfg_extra):
    return {
        "metric": "m", "value": device_ms, "unit": "ms",
        "vs_baseline": round(baseline_ms / device_ms, 2),
        "backend": backend,
        "pallas_max_rel_diff": 1e-6,
        "configs": [
            {"config": "a_linear", "device_ms": device_ms,
             "baseline_ms": baseline_ms,
             "vs_baseline": round(baseline_ms / device_ms, 2), **cfg_extra},
            {"config": "dq_parse_csv_1000000", "native_ms": 80.0,
             "python_ms": 3000.0, "native_gbps": 0.1,
             "native_vs_python": 37.5},
        ],
        "sweep": [
            {"rows": 100, "features": 16, "xla_ms": xla_ms,
             "xla_gbps": 100.0 / xla_ms, "mfu": 0.1,
             "bf16_ms": None, "pallas_ms": pallas_ms,
             "pallas_gbps": None if pallas_ms is None else 50.0,
             **({"pallas_error": "HTTP 500"} if pallas_ms is None else {})},
        ],
    }


class TestMergeBest:
    def test_first_run_passthrough(self):
        m = daemon.merge_best(_capture(1.0, 10.0), None)
        assert m["runs_merged"] == 1
        assert m["value"] == 1.0

    def test_min_each_side_independently(self):
        # Run 1: clean baseline, slow device. Run 2: fast device,
        # contended (inflated) baseline. The merge takes the best of
        # each and recomputes the ratio.
        r1 = _capture(2.0, 10.0)
        r2 = _capture(1.0, 25.0)
        m = daemon.merge_best(r2, daemon.merge_best(r1, None))
        a = m["configs"][0]
        assert a["device_ms"] == 1.0
        assert a["baseline_ms"] == 10.0
        assert a["vs_baseline"] == 10.0
        assert m["value"] == 1.0
        assert m["vs_baseline"] == 10.0
        assert m["runs_merged"] == 2
        assert "estimator_note" in m

    def test_inverse_fields_rescale(self):
        r1 = _capture(1.0, 10.0, xla_ms=2.0)   # xla_gbps 50, mfu 0.1
        r2 = _capture(1.0, 10.0, xla_ms=1.0)   # xla_gbps 100
        m = daemon.merge_best(r1, daemon.merge_best(r2, None))
        cell = m["sweep"][0]
        assert cell["xla_ms"] == 1.0
        assert cell["xla_gbps"] == pytest.approx(100.0)

    def test_pallas_error_cleared_by_successful_run(self):
        failed = _capture(1.0, 10.0, pallas_ms=None)
        ok = _capture(1.0, 10.0, pallas_ms=3.0)
        m = daemon.merge_best(failed, daemon.merge_best(ok, None))
        cell = m["sweep"][0]
        assert cell["pallas_ms"] == 3.0
        assert "pallas_error" not in cell

    def test_rel_diff_stays_conservative_max(self):
        r1 = _capture(1.0, 10.0)
        r1["pallas_max_rel_diff"] = 5e-5
        r2 = _capture(1.0, 10.0)
        m = daemon.merge_best(r2, daemon.merge_best(r1, None))
        assert m["pallas_max_rel_diff"] == 5e-5

    def test_backend_mismatch_resets(self):
        cpu = _capture(0.5, 10.0, backend="cpu")
        tpu = _capture(1.0, 10.0)
        m = daemon.merge_best(tpu, daemon.merge_best(cpu, None))
        assert m["runs_merged"] == 1
        assert m["value"] == 1.0

    def test_csv_row_mins(self):
        r1 = _capture(1.0, 10.0)
        r1["configs"][1]["native_ms"] = 60.0
        r2 = _capture(1.0, 10.0)
        m = daemon.merge_best(r2, daemon.merge_best(r1, None))
        csv = m["configs"][1]
        assert csv["native_ms"] == 60.0
        assert csv["native_vs_python"] == 50.0


class TestPruneQuality:
    def test_quality_ranks_by_device_time(self, tmp_path):
        import json
        good = tmp_path / "BENCH_TPU_1.json"
        good.write_text(json.dumps(_capture(0.5, 10.0)))
        bad = tmp_path / "BENCH_TPU_2.json"
        bad.write_text(json.dumps(_capture(1.0, 100.0)))
        assert daemon._capture_quality(str(good)) > \
            daemon._capture_quality(str(bad))

    def test_cpu_and_garbage_rank_lowest(self, tmp_path):
        import json
        cpu = tmp_path / "a.json"
        cpu.write_text(json.dumps(_capture(0.1, 10.0, backend="cpu")))
        garbage = tmp_path / "b.json"
        garbage.write_text("[1, 2")
        tpu = tmp_path / "c.json"
        tpu.write_text(json.dumps(_capture(5.0, 10.0)))
        assert daemon._capture_quality(str(tpu)) > \
            daemon._capture_quality(str(cpu))
        assert daemon._capture_quality(str(garbage)) == float("-inf")
