"""PrefixSpan: the Spark programming-guide fixture, itemset extensions,
support thresholds, pattern-length caps, and string items."""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import PrefixSpan
from sparkdq4ml_tpu.models.text import _obj_array


def seq_frame(seqs):
    return Frame({"sequence": _obj_array(seqs)})


def mined(frame, **kw):
    out = PrefixSpan(**kw).find_frequent_sequential_patterns(frame)
    d = out.to_pydict()
    return {tuple(tuple(sorted(i)) for i in s): int(f)
            for s, f in zip(d["sequence"], d["freq"])}


class TestSparkDocsFixture:
    # the example from Spark's ml-frequent-pattern-mining guide
    SEQS = [[[1, 2], [3]],
            [[1], [3, 2], [1, 2]],
            [[1, 2], [5]],
            [[6]]]

    def test_expected_patterns(self):
        got = mined(seq_frame(self.SEQS), min_support=0.5,
                    max_pattern_length=5)
        expected = {
            ((1,),): 3,
            ((2,),): 3,
            ((3,),): 2,
            ((1,), (3,)): 2,
            ((1, 2),): 3,
        }
        assert got == expected


class TestSemantics:
    def test_itemset_vs_sequence_extension(self):
        # (a b) together twice vs a-then-b twice are different patterns
        seqs = [[["a", "b"]], [["a", "b"]], [["a"], ["b"]], [["a"], ["b"]]]
        got = mined(seq_frame(seqs), min_support=0.5)
        assert got[(("a", "b"),)] == 2
        assert got[(("a",), ("b",))] == 2
        assert got[(("a",),)] == 4

    def test_min_support_threshold(self):
        seqs = [[["x"]], [["x"]], [["y"]], [["z"]]]
        got = mined(seq_frame(seqs), min_support=0.5)
        assert got == {(("x",),): 2}

    def test_max_pattern_length_counts_items(self):
        seqs = [[["a"], ["b"], ["c"]]] * 2
        got1 = mined(seq_frame(seqs), min_support=1.0, max_pattern_length=1)
        assert set(got1) == {(("a",),), (("b",),), (("c",),)}
        got2 = mined(seq_frame(seqs), min_support=1.0, max_pattern_length=2)
        assert (("a",), ("b",)) in got2 and (("a",), ("b",), ("c",)) not in got2

    def test_repeated_item_across_itemsets(self):
        seqs = [[["a"], ["a"]], [["a"], ["a"]]]
        got = mined(seq_frame(seqs), min_support=1.0)
        assert got[(("a",),)] == 2
        assert got[(("a",), ("a",))] == 2

    def test_support_counts_sequences_not_occurrences(self):
        seqs = [[["a"], ["a"], ["a"]], [["b"]]]
        got = mined(seq_frame(seqs), min_support=0.5)
        assert got[(("a",),)] == 1   # one sequence, many occurrences

    def test_later_itemset_supplies_itemset_extension(self):
        # (a b) appears only in the SECOND 'a'-containing itemset; the
        # first-occurrence projection must still find the i-extension
        seqs = [[["a"], ["a", "b"]], [["a", "b"]]]
        got = mined(seq_frame(seqs), min_support=1.0)
        assert got[(("a", "b"),)] == 2

    def test_duplicate_items_in_itemset_deduped(self):
        seqs = [[["a", "a", "b"]], [["b", "a"]]]
        got = mined(seq_frame(seqs), min_support=1.0)
        assert got[(("a", "b"),)] == 2

    def test_empty_frame(self):
        out = PrefixSpan().find_frequent_sequential_patterns(
            seq_frame([]))
        assert len(out.to_pydict()["freq"]) == 0

    def test_validation(self):
        with pytest.raises(ValueError, match="min_support"):
            PrefixSpan(min_support=1.5)
        with pytest.raises(ValueError, match="max_pattern_length"):
            PrefixSpan(max_pattern_length=0)

    def test_camelcase_surface(self):
        ps = (PrefixSpan().setMinSupport(0.4).setMaxPatternLength(3)
              .setSequenceCol("s").setMaxLocalProjDBSize(1000))
        assert ps.min_support == 0.4 and ps.max_pattern_length == 3
        assert ps.sequence_col == "s"
        f = Frame({"s": _obj_array([[["p"], ["q"]], [["p"], ["q"]]])})
        d = ps.findFrequentSequentialPatterns(f).to_pydict()
        pats = {tuple(tuple(i) for i in s) for s in d["sequence"]}
        assert (("p",), ("q",)) in pats


def _occurs(pattern, seq):
    """Oracle: does ``pattern`` (list of itemsets) embed in ``seq`` with
    strictly increasing itemset positions and subset containment?"""
    def rec(pi, start):
        if pi == len(pattern):
            return True
        need = set(pattern[pi])
        for i in range(start, len(seq)):
            if need <= set(seq[i]) and rec(pi + 1, i + 1):
                return True
        return False
    return rec(0, 0)


def _brute_force(seqs, min_count, max_len, alphabet):
    """Enumerate every canonical pattern up to ``max_len`` items by DFS,
    counting support by direct embedding checks."""
    out = {}

    def grow(pattern, n_items):
        if n_items >= max_len:
            return
        cands = []
        for a in alphabet:
            cands.append(pattern + [(a,)])                    # s-extension
        if pattern:
            last = pattern[-1]
            for a in alphabet:
                if a > last[-1]:
                    cands.append(pattern[:-1] + [last + (a,)])  # i-extension
        for cand in cands:
            c = sum(_occurs(cand, s) for s in seqs)
            if c >= min_count:
                key = tuple(tuple(p) for p in cand)
                if key not in out:
                    out[key] = c
                    grow(cand, n_items + 1)

    grow([], 0)
    return out


class TestBruteForceParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_corpora(self, seed):
        rng = np.random.default_rng(seed)
        alphabet = ["a", "b", "c", "d"]
        seqs = []
        for _ in range(8):
            seq = []
            for _ in range(rng.integers(1, 5)):
                size = rng.integers(1, 4)
                seq.append(sorted(set(rng.choice(alphabet, size=size))))
            seqs.append(seq)
        min_support = float(rng.choice([0.25, 0.5]))
        max_len = int(rng.choice([2, 3, 4]))
        got = mined(seq_frame(seqs), min_support=min_support,
                    max_pattern_length=max_len)
        import math
        want = _brute_force([[tuple(i) for i in s] for s in seqs],
                            max(1, math.ceil(min_support * len(seqs))),
                            max_len, alphabet)
        assert got == want


class TestMaskRespected:
    def test_filtered_rows_do_not_vote(self):
        seqs = [[["a"], ["b"]], [["a"], ["b"]], [["z"]], [["z"]]]
        f = Frame({"sequence": _obj_array(seqs),
                   "keep": np.asarray([1.0, 1.0, 0.0, 0.0])})
        f = f.filter(np.asarray(f.to_pydict()["keep"]) == 1.0)
        got = mined(f, min_support=1.0)
        # z rows are masked out: min_support=1.0 is over the 2 kept rows
        assert got == {(("a",),): 2, (("b",),): 2, (("a",), ("b",)): 2}
