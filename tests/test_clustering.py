"""KMeans + ClusteringEvaluator: single-device, sharded, masked, persisted.

Parity oracle: sklearn.cluster.KMeans on the same data (SURVEY.md §4's
cross-check pattern); sharded ≡ single-device on the fake 8-device CPU mesh.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from sparkdq4ml_tpu import Frame, col
from sparkdq4ml_tpu.models import (ClusteringEvaluator, KMeans, KMeansModel,
                                   VectorAssembler)


def three_blobs(n_per=50, seed=7):
    rng = np.random.default_rng(seed)
    centers = np.asarray([[0.0, 0.0], [10.0, 0.0], [0.0, 10.0]])
    pts = np.concatenate([c + 0.5 * rng.normal(size=(n_per, 2))
                          for c in centers])
    f = Frame({"x": pts[:, 0].astype(np.float32),
               "y": pts[:, 1].astype(np.float32)})
    return VectorAssembler(["x", "y"], "features").transform(f), centers


class TestKMeansFit:
    def test_recovers_blob_centers(self):
        f, true_centers = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        got = np.sort(np.asarray(model.clusterCenters()), axis=0)
        want = np.sort(true_centers, axis=0)
        assert np.allclose(got, want, atol=0.5)

    def test_summary_and_cost(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        s = model.summary
        assert s.k == 3
        assert sorted(s.cluster_sizes) == [50, 50, 50]
        assert s.training_cost == pytest.approx(model.compute_cost(f),
                                                rel=1e-3)
        assert 0 < s.num_iter <= 20

    def test_transform_and_predict_agree(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        out = model.transform(f).to_pydict()
        assert set(np.unique(out["prediction"])) == {0.0, 1.0, 2.0}
        i = 5
        assert model.predict([out["x"][i], out["y"][i]]) == \
            int(out["prediction"][i])

    def test_masked_rows_do_not_vote(self):
        f = Frame({"x": [0.0, 0.1, 5.0, 1000.0],
                   "y": [0.0, 0.1, 5.0, 1000.0]})
        f = VectorAssembler(["x", "y"], "features").transform(f)
        f = f.filter(col("x") < 100.0)
        model = KMeans(k=2, seed=0).fit(f)
        centers = np.asarray(model.clusterCenters())
        assert np.abs(centers).max() < 100.0  # outlier never pulled a center

    def test_k_exceeds_rows_raises(self):
        f = Frame({"x": [1.0, 2.0]})
        f = VectorAssembler(["x"], "features").transform(f)
        with pytest.raises(ValueError, match="exceeds"):
            KMeans(k=5).fit(f)

    def test_random_init_mode(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=3, init_mode="random").fit(f)
        assert len(model.clusterCenters()) == 3

    def test_sklearn_parity_on_cost(self):
        pytest.importorskip("sklearn")
        from sklearn.cluster import KMeans as SkKMeans

        f, _ = three_blobs()
        d = f.to_pydict()
        X = np.stack([d["x"], d["y"]], axis=1).astype(np.float64)
        sk = SkKMeans(n_clusters=3, n_init=5, random_state=0).fit(X)
        model = KMeans(k=3, seed=1, max_iter=50).fit(f)
        assert model.compute_cost(f) == pytest.approx(sk.inertia_, rel=0.05)


class TestShardedKMeans:
    def test_sharded_equals_single_device(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        f, _ = three_blobs(n_per=33)  # 99 rows: exercises shard padding
        single = KMeans(k=3, seed=1).fit(f)
        sharded = KMeans(k=3, seed=1).fit(f, mesh=make_mesh(8))
        got = np.sort(np.asarray(sharded.clusterCenters()), axis=0)
        want = np.sort(np.asarray(single.clusterCenters()), axis=0)
        assert np.allclose(got, want, atol=1e-3)
        assert sharded.training_cost == pytest.approx(single.training_cost,
                                                      rel=1e-3)


class TestClusteringEvaluator:
    def test_good_clustering_scores_high(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        score = ClusteringEvaluator().evaluate(model.transform(f))
        assert score > 0.8

    def test_bad_clustering_scores_lower(self):
        f, _ = three_blobs()
        good = ClusteringEvaluator().evaluate(
            KMeans(k=3, seed=1).fit(f).transform(f))
        bad = ClusteringEvaluator().evaluate(
            KMeans(k=2, seed=1).fit(f).transform(f))
        assert good > bad

    def test_sklearn_silhouette_parity(self):
        pytest.importorskip("sklearn")
        from sklearn.metrics import silhouette_score

        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        out = model.transform(f)
        d = out.to_pydict()
        X = np.stack([d["x"], d["y"]], axis=1).astype(np.float64)
        # sklearn uses euclidean; Spark (and we) use squared euclidean —
        # both should agree the clustering is strong, not numerically equal
        ours = ClusteringEvaluator().evaluate(out)
        theirs = silhouette_score(X, d["prediction"].astype(int))
        assert ours > 0.8 and theirs > 0.7

    def test_single_cluster_is_nan(self):
        f, _ = three_blobs()
        out = KMeans(k=1, seed=1).fit(f).transform(f)
        assert np.isnan(ClusteringEvaluator().evaluate(out))


class TestKMeansPersistence:
    def test_model_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        path = str(tmp_path / "km")
        model.save(path)
        loaded = load_stage(path)
        assert isinstance(loaded, KMeansModel)
        assert np.allclose(np.asarray(loaded.clusterCenters()),
                           np.asarray(model.clusterCenters()))
        out = loaded.transform(f).to_pydict()
        assert set(np.unique(out["prediction"])) == {0.0, 1.0, 2.0}


# ---------------------------------------------------------------------------
# GaussianMixture
# ---------------------------------------------------------------------------

def _blobs(n=300, k=3, d=2, seed=0, spread=6.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * spread
    y = rng.integers(0, k, size=n)
    X = centers[y] + rng.normal(size=(n, d))
    return X, y, centers


class TestGaussianMixture:
    def test_recovers_separated_components(self):
        from sparkdq4ml_tpu.models import GaussianMixture

        X, y, centers = _blobs(seed=3)
        f = Frame({"features": X})
        m = GaussianMixture(k=3, max_iter=200, tol=1e-9, seed=0).fit(f)
        # each true center has a fitted mean nearby
        for c in centers:
            assert np.min(np.linalg.norm(m.means - c, axis=1)) < 0.5
        assert m.weights.sum() == pytest.approx(1.0, abs=1e-6)
        assert m.k == 3

    def test_sklearn_loglik_parity(self):
        sk = pytest.importorskip("sklearn.mixture")
        from sparkdq4ml_tpu.models import GaussianMixture

        X, y, _ = _blobs(n=400, seed=5)
        f = Frame({"features": X})
        m = GaussianMixture(k=3, max_iter=300, tol=1e-10, seed=0).fit(f)
        ref = sk.GaussianMixture(n_components=3, covariance_type="full",
                                 tol=1e-10, max_iter=300, n_init=5,
                                 random_state=0).fit(X)
        # per-sample average log-likelihood should match the sklearn
        # optimum closely on well-separated data
        ours = m.summary.log_likelihood / len(X)
        assert ours == pytest.approx(ref.score(X), abs=0.02)

    def test_posterior_and_transform(self):
        from sparkdq4ml_tpu.models import GaussianMixture

        X, y, _ = _blobs(n=200, seed=7)
        f = Frame({"features": X})
        m = GaussianMixture(k=3, max_iter=100, seed=0).fit(f)
        out = m.transform(f).to_pydict()
        probs = np.stack(out["probability"])
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
        np.testing.assert_array_equal(out["prediction"],
                                      probs.argmax(axis=1))
        p0 = m.predict_probability(X[0])
        assert m.predict(X[0]) == int(np.argmax(p0))

    def test_masked_rows_do_not_vote(self):
        from sparkdq4ml_tpu.models import GaussianMixture

        X, y, _ = _blobs(n=200, seed=11)
        Xbad = X.copy()
        bad = np.arange(len(X)) % 5 == 0
        Xbad[bad] = 1e6          # absurd rows that must be ignored
        f = Frame({"features": Xbad}).filter(jnp.asarray(~bad))
        fclean = Frame({"features": X[~bad]})
        m1 = GaussianMixture(k=3, max_iter=150, seed=0).fit(f)
        m2 = GaussianMixture(k=3, max_iter=150, seed=0).fit(fclean)
        order1 = np.argsort(m1.means[:, 0])
        order2 = np.argsort(m2.means[:, 0])
        np.testing.assert_allclose(m1.means[order1], m2.means[order2],
                                   atol=1e-4)

    def test_sharded_equals_single(self):
        from sparkdq4ml_tpu.models import GaussianMixture
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        X, y, _ = _blobs(n=240, seed=13)
        f = Frame({"features": X})
        m1 = GaussianMixture(k=3, max_iter=100, seed=0).fit(
            f, mesh=make_mesh(1))
        m8 = GaussianMixture(k=3, max_iter=100, seed=0).fit(
            f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.means, m1.means, rtol=1e-7, atol=1e-9)
        np.testing.assert_allclose(m8.weights, m1.weights, rtol=1e-7)

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models import GaussianMixture
        from sparkdq4ml_tpu.models.base import load_stage

        X, y, _ = _blobs(n=150, seed=17)
        f = Frame({"features": X})
        m = GaussianMixture(k=2, max_iter=50, seed=0).fit(f)
        m.save(str(tmp_path / "gmm"))
        loaded = load_stage(str(tmp_path / "gmm"))
        np.testing.assert_allclose(loaded.means, m.means)
        assert loaded.predict(X[0]) == m.predict(X[0])


# ---------------------------------------------------------------------------
# BisectingKMeans
# ---------------------------------------------------------------------------

class TestBisectingKMeans:
    def test_k_leaves_on_blobs(self):
        from sparkdq4ml_tpu.models import BisectingKMeans

        X, y, centers = _blobs(n=300, k=4, seed=21)
        f = Frame({"features": X})
        m = BisectingKMeans(k=4, seed=0).fit(f)
        assert m.k == 4
        assert len(m.cluster_centers()) == 4
        assert sum(m.cluster_sizes) == 300
        for c in centers:
            got = np.stack(m.cluster_centers())
            assert np.min(np.linalg.norm(got - c, axis=1)) < 1.0

    def test_transform_and_predict_consistent(self):
        from sparkdq4ml_tpu.models import BisectingKMeans

        X, y, _ = _blobs(n=200, k=3, seed=23)
        f = Frame({"features": X})
        m = BisectingKMeans(k=3, seed=0).fit(f)
        d = m.transform(f).to_pydict()
        preds = d["prediction"]
        assert set(np.unique(preds)) <= {0.0, 1.0, 2.0}
        for i in (0, 7, 42):
            assert m.predict(X[i]) == int(preds[i])

    def test_compute_cost_positive_and_small_on_tight_blobs(self):
        from sparkdq4ml_tpu.models import BisectingKMeans, KMeans

        X, y, _ = _blobs(n=300, k=3, seed=29)
        f = Frame({"features": X})
        m = BisectingKMeans(k=3, seed=0).fit(f)
        km = KMeans(k=3, seed=0, max_iter=50).fit(f)
        # bisecting should be in the same cost ballpark as flat k-means
        assert m.compute_cost(f) < 3.0 * km.compute_cost(f)

    def test_k1_returns_mean(self):
        from sparkdq4ml_tpu.models import BisectingKMeans

        X, y, _ = _blobs(n=50, k=2, seed=31)
        f = Frame({"features": X})
        m = BisectingKMeans(k=1).fit(f)
        assert m.k == 1
        np.testing.assert_allclose(m.cluster_centers()[0], X.mean(axis=0),
                                   atol=1e-5)

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models import BisectingKMeans
        from sparkdq4ml_tpu.models.base import load_stage

        X, y, _ = _blobs(n=120, k=3, seed=37)
        f = Frame({"features": X})
        m = BisectingKMeans(k=3, seed=0).fit(f)
        m.save(str(tmp_path / "bkm"))
        loaded = load_stage(str(tmp_path / "bkm"))
        for i in (0, 5, 11):
            assert loaded.predict(X[i]) == m.predict(X[i])
        assert loaded.k == 3

    def test_respects_mask(self):
        from sparkdq4ml_tpu.models import BisectingKMeans

        X, y, _ = _blobs(n=200, k=3, seed=41)
        Xbad = X.copy()
        bad = np.arange(len(X)) % 4 == 0
        Xbad[bad] = 500.0
        f = Frame({"features": Xbad}).filter(jnp.asarray(~bad))
        m = BisectingKMeans(k=3, seed=0).fit(f)
        centers = np.stack(m.cluster_centers())
        assert np.all(np.abs(centers) < 100.0)


class TestMaskedNanRows:
    """Masked slots may hold NaN (dropna/filter keep values in place);
    every clustering fit must zero them out of the statistics."""

    def _nan_frame(self, n=120, k=2, seed=51):
        X, y, _ = _blobs(n=n, k=k, seed=seed)
        bad = np.arange(n) % 4 == 0
        Xbad = X.copy()
        Xbad[bad] = np.nan
        return (Frame({"features": Xbad}).filter(jnp.asarray(~bad)),
                Frame({"features": X[~bad]}))

    def test_kmeans_ignores_nan_masked_rows(self):
        from sparkdq4ml_tpu.models import KMeans

        f, fclean = self._nan_frame()
        m = KMeans(k=2, seed=0, max_iter=30).fit(f)
        mc = KMeans(k=2, seed=0, max_iter=30).fit(fclean)
        assert np.all(np.isfinite(np.stack(m.cluster_centers())))
        got = np.stack(sorted(m.cluster_centers(), key=lambda c: c[0]))
        want = np.stack(sorted(mc.cluster_centers(), key=lambda c: c[0]))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_gmm_ignores_nan_masked_rows(self):
        from sparkdq4ml_tpu.models import GaussianMixture

        f, fclean = self._nan_frame(seed=53)
        m = GaussianMixture(k=2, seed=0, max_iter=60).fit(f)
        assert np.all(np.isfinite(m.means))
        assert np.all(np.isfinite(m.covs))

    def test_bisecting_ignores_nan_masked_rows(self):
        from sparkdq4ml_tpu.models import BisectingKMeans

        f, fclean = self._nan_frame(seed=55)
        m = BisectingKMeans(k=2, seed=0).fit(f)
        assert m.k == 2
        assert np.all(np.isfinite(np.stack(m.cluster_centers())))
