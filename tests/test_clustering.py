"""KMeans + ClusteringEvaluator: single-device, sharded, masked, persisted.

Parity oracle: sklearn.cluster.KMeans on the same data (SURVEY.md §4's
cross-check pattern); sharded ≡ single-device on the fake 8-device CPU mesh.
"""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame, col
from sparkdq4ml_tpu.models import (ClusteringEvaluator, KMeans, KMeansModel,
                                   VectorAssembler)


def three_blobs(n_per=50, seed=7):
    rng = np.random.default_rng(seed)
    centers = np.asarray([[0.0, 0.0], [10.0, 0.0], [0.0, 10.0]])
    pts = np.concatenate([c + 0.5 * rng.normal(size=(n_per, 2))
                          for c in centers])
    f = Frame({"x": pts[:, 0].astype(np.float32),
               "y": pts[:, 1].astype(np.float32)})
    return VectorAssembler(["x", "y"], "features").transform(f), centers


class TestKMeansFit:
    def test_recovers_blob_centers(self):
        f, true_centers = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        got = np.sort(np.asarray(model.clusterCenters()), axis=0)
        want = np.sort(true_centers, axis=0)
        assert np.allclose(got, want, atol=0.5)

    def test_summary_and_cost(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        s = model.summary
        assert s.k == 3
        assert sorted(s.cluster_sizes) == [50, 50, 50]
        assert s.training_cost == pytest.approx(model.compute_cost(f),
                                                rel=1e-3)
        assert 0 < s.num_iter <= 20

    def test_transform_and_predict_agree(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        out = model.transform(f).to_pydict()
        assert set(np.unique(out["prediction"])) == {0.0, 1.0, 2.0}
        i = 5
        assert model.predict([out["x"][i], out["y"][i]]) == \
            int(out["prediction"][i])

    def test_masked_rows_do_not_vote(self):
        f = Frame({"x": [0.0, 0.1, 5.0, 1000.0],
                   "y": [0.0, 0.1, 5.0, 1000.0]})
        f = VectorAssembler(["x", "y"], "features").transform(f)
        f = f.filter(col("x") < 100.0)
        model = KMeans(k=2, seed=0).fit(f)
        centers = np.asarray(model.clusterCenters())
        assert np.abs(centers).max() < 100.0  # outlier never pulled a center

    def test_k_exceeds_rows_raises(self):
        f = Frame({"x": [1.0, 2.0]})
        f = VectorAssembler(["x"], "features").transform(f)
        with pytest.raises(ValueError, match="exceeds"):
            KMeans(k=5).fit(f)

    def test_random_init_mode(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=3, init_mode="random").fit(f)
        assert len(model.clusterCenters()) == 3

    def test_sklearn_parity_on_cost(self):
        pytest.importorskip("sklearn")
        from sklearn.cluster import KMeans as SkKMeans

        f, _ = three_blobs()
        d = f.to_pydict()
        X = np.stack([d["x"], d["y"]], axis=1).astype(np.float64)
        sk = SkKMeans(n_clusters=3, n_init=5, random_state=0).fit(X)
        model = KMeans(k=3, seed=1, max_iter=50).fit(f)
        assert model.compute_cost(f) == pytest.approx(sk.inertia_, rel=0.05)


class TestShardedKMeans:
    def test_sharded_equals_single_device(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        f, _ = three_blobs(n_per=33)  # 99 rows: exercises shard padding
        single = KMeans(k=3, seed=1).fit(f)
        sharded = KMeans(k=3, seed=1).fit(f, mesh=make_mesh(8))
        got = np.sort(np.asarray(sharded.clusterCenters()), axis=0)
        want = np.sort(np.asarray(single.clusterCenters()), axis=0)
        assert np.allclose(got, want, atol=1e-3)
        assert sharded.training_cost == pytest.approx(single.training_cost,
                                                      rel=1e-3)


class TestClusteringEvaluator:
    def test_good_clustering_scores_high(self):
        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        score = ClusteringEvaluator().evaluate(model.transform(f))
        assert score > 0.8

    def test_bad_clustering_scores_lower(self):
        f, _ = three_blobs()
        good = ClusteringEvaluator().evaluate(
            KMeans(k=3, seed=1).fit(f).transform(f))
        bad = ClusteringEvaluator().evaluate(
            KMeans(k=2, seed=1).fit(f).transform(f))
        assert good > bad

    def test_sklearn_silhouette_parity(self):
        pytest.importorskip("sklearn")
        from sklearn.metrics import silhouette_score

        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        out = model.transform(f)
        d = out.to_pydict()
        X = np.stack([d["x"], d["y"]], axis=1).astype(np.float64)
        # sklearn uses euclidean; Spark (and we) use squared euclidean —
        # both should agree the clustering is strong, not numerically equal
        ours = ClusteringEvaluator().evaluate(out)
        theirs = silhouette_score(X, d["prediction"].astype(int))
        assert ours > 0.8 and theirs > 0.7

    def test_single_cluster_is_nan(self):
        f, _ = three_blobs()
        out = KMeans(k=1, seed=1).fit(f).transform(f)
        assert np.isnan(ClusteringEvaluator().evaluate(out))


class TestKMeansPersistence:
    def test_model_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, _ = three_blobs()
        model = KMeans(k=3, seed=1).fit(f)
        path = str(tmp_path / "km")
        model.save(path)
        loaded = load_stage(path)
        assert isinstance(loaded, KMeansModel)
        assert np.allclose(np.asarray(loaded.clusterCenters()),
                           np.asarray(model.clusterCenters()))
        out = loaded.transform(f).to_pydict()
        assert set(np.unique(out["prediction"])) == {0.0, 1.0, 2.0}
