"""Date/time functions: epoch-day device columns, on-device civil-calendar
field extraction (cross-checked against python datetime), arithmetic,
parsing/formatting round-trips, null handling, and SQL."""

import datetime as dt

import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu import functions as F


def one_col(frame, expr, name="o"):
    return list(frame.with_column(name, expr).to_pydict()[name])


@pytest.fixture
def dates():
    return Frame({"s": ["2024-02-29", "1969-07-20", "2000-12-31", None],
                  "t": ["29/02/2024", "20/07/1969", "31/12/2000", "bogus"]})


class TestToDate:
    def test_default_format(self, dates):
        got = one_col(dates, F.to_date(F.col("s")))
        epoch = dt.date(1970, 1, 1)
        want = [(dt.date(2024, 2, 29) - epoch).days,
                (dt.date(1969, 7, 20) - epoch).days,
                (dt.date(2000, 12, 31) - epoch).days]
        assert got[:3] == want
        assert np.isnan(got[3])                   # null → NaN (engine null)

    def test_custom_format_and_unparseable(self, dates):
        got = one_col(dates, F.to_date(F.col("t"), "dd/MM/yyyy"))
        assert got[0] == (dt.date(2024, 2, 29) - dt.date(1970, 1, 1)).days
        assert np.isnan(got[3])                   # bogus → null


class TestFields:
    @pytest.mark.parametrize("seed", range(3))
    def test_fields_match_datetime(self, seed):
        rng = np.random.default_rng(seed)
        days = rng.integers(-40000, 40000, size=200)
        f = Frame({"d": np.asarray(days, np.int32)})
        epoch = dt.date(1970, 1, 1)
        pydates = [epoch + dt.timedelta(days=int(v)) for v in days]
        assert one_col(f, F.year(F.col("d"))) == [p.year for p in pydates]
        assert one_col(f, F.month(F.col("d"))) == [p.month for p in pydates]
        assert one_col(f, F.dayofmonth(F.col("d"))) == [p.day for p in pydates]
        assert one_col(f, F.dayofyear(F.col("d"))) == \
            [p.timetuple().tm_yday for p in pydates]
        # Spark: 1=Sunday..7=Saturday; python isoweekday: 1=Mon..7=Sun
        assert one_col(f, F.dayofweek(F.col("d"))) == \
            [p.isoweekday() % 7 + 1 for p in pydates]
        assert one_col(f, F.quarter(F.col("d"))) == \
            [(p.month - 1) // 3 + 1 for p in pydates]


class TestArithmetic:
    def test_datediff_add_sub(self, dates):
        f = dates.with_column("d", F.to_date(F.col("s")))
        f = f.with_column("d2", F.date_add(F.col("d"), 10))
        got = one_col(f, F.datediff(F.col("d2"), F.col("d")))
        assert got[:3] == [10, 10, 10]
        assert np.isnan(got[3])                   # null propagates as NaN
        back = one_col(f, F.date_sub(F.col("d2"), 10))
        assert back[:3] == one_col(f, F.col("d"))[:3]

    def test_current_date_is_today(self):
        f = Frame({"x": [0.0]})
        got = one_col(f, F.current_date())
        assert got[0] == (dt.date.today() - dt.date(1970, 1, 1)).days


class TestFormatting:
    def test_date_format_round_trip(self, dates):
        f = dates.with_column("d", F.to_date(F.col("s")))
        got = one_col(f, F.date_format(F.col("d"), "yyyy-MM-dd"))
        assert got[:3] == ["2024-02-29", "1969-07-20", "2000-12-31"]
        assert got[3] is None

    def test_unix_timestamp_round_trip(self):
        f = Frame({"ts": ["2024-06-01 12:30:45", "1970-01-01 00:00:00"]})
        secs = one_col(f, F.unix_timestamp(F.col("ts")))
        assert secs[1] == 0
        assert secs[0] == int((dt.datetime(2024, 6, 1, 12, 30, 45)
                               - dt.datetime(1970, 1, 1)).total_seconds())
        f2 = f.with_column("u", F.unix_timestamp(F.col("ts")))
        back = one_col(f2, F.from_unixtime(F.col("u")))
        assert back == ["2024-06-01 12:30:45", "1970-01-01 00:00:00"]


class TestSql:
    def test_sql_date_chain(self):
        s = dq.TpuSession.builder().app_name("dates").get_or_create()
        Frame({"s": ["2023-03-15", "2023-11-02"]}) \
            .create_or_replace_temp_view("dv")
        out = s.sql("SELECT YEAR(TO_DATE(s)) AS y, QUARTER(TO_DATE(s)) AS q "
                    "FROM dv").to_pydict()
        assert out["y"].tolist() == [2023, 2023]
        assert out["q"].tolist() == [1, 4]

    def test_unsupported_format_token_raises(self):
        f = Frame({"s": ["2020-01-01"]})
        with pytest.raises(ValueError, match="unsupported date-format"):
            one_col(f, F.to_date(F.col("s"), "EEE yyyy"))

    def test_single_letter_tokens(self):
        f = Frame({"s": ["3/7/2020"]})
        got = one_col(f, F.to_date(F.col("s"), "M/d/yyyy"))
        assert got[0] == (dt.date(2020, 3, 7) - dt.date(1970, 1, 1)).days

    def test_null_dates_visible_to_filters(self):
        f = Frame({"s": ["2020-01-05", "garbage"]})
        f = f.with_column("y", F.year(F.to_date(F.col("s"))))
        kept = f.filter(dq.col("y") < 2025)
        assert kept.count() == 1                   # null row excluded
        assert f.filter(dq.col("y").is_null()).count() == 1


class TestImplicitStringDateCast:
    """Spark implicitly casts yyyy-MM-dd strings to dates in date
    functions; the engine's date fns must accept string columns directly
    (not only to_date output)."""

    def _frame(self):
        return Frame({"d": np.asarray(
            ["2026-01-31", "2026-02-28", None, "2025-12-01"], dtype=object)})

    def test_year_month_on_string_column(self):
        f = self._frame()
        o = (f.with_column("y", F.year(F.col("d")))
              .with_column("m", F.month(F.col("d")))).to_pydict()
        assert list(o["y"])[:2] == [2026.0, 2026.0]
        assert np.isnan(o["y"][2])
        assert list(o["m"])[:2] == [1.0, 2.0]

    def test_date_add_datediff_on_string_column(self):
        f = self._frame()
        o = (f.with_column("a", F.date_add(F.col("d"), 31))
              .with_column("dd", F.datediff(F.col("d"), F.col("d")))
              ).to_pydict()
        # 2026-01-31 + 31 days = 2026-03-03 = epoch day 20515
        assert o["a"][0] == 20515.0
        assert np.isnan(o["a"][2])
        assert o["dd"][0] == 0.0

    def test_date_format_on_string_column(self):
        f = self._frame()
        o = f.with_column("s", F.date_format(F.col("d"), "dd/MM/yyyy"))
        got = o.to_pydict()["s"]
        assert list(got) == ["31/01/2026", "28/02/2026", None, "01/12/2025"]

    def test_unparseable_string_yields_null(self):
        f = Frame({"d": np.asarray(["not-a-date", "2026-01-01"],
                                   dtype=object)})
        o = f.with_column("y", F.year(F.col("d"))).to_pydict()
        assert np.isnan(o["y"][0]) and o["y"][1] == 2026.0

    def test_timestamp_shaped_strings_cast_by_date_prefix(self):
        f = Frame({"d": np.asarray(
            ["2026-01-01 10:00:00", "2026-02-03T04:05:06", "  ", None],
            dtype=object)})
        o = f.with_column("y", F.year(F.col("d"))).to_pydict()
        assert o["y"][0] == 2026.0 and o["y"][1] == 2026.0
        assert np.isnan(o["y"][2]) and np.isnan(o["y"][3])

    def test_partial_dates_cast_like_spark(self):
        f = Frame({"d": np.asarray(["2026", "2026-07", "2026-07-15"],
                                   dtype=object)})
        o = (f.with_column("y", F.year(F.col("d")))
              .with_column("m", F.month(F.col("d")))).to_pydict()
        assert list(o["y"]) == [2026.0, 2026.0, 2026.0]
        assert list(o["m"]) == [1.0, 7.0, 7.0]      # missing fields -> 01

    def test_date_format_preserves_time_of_day_for_strings(self):
        f = Frame({"d": np.asarray(["2026-01-01 10:30:45", "2026-01-02"],
                                   dtype=object)})
        o = f.with_column("s", F.date_format(F.col("d"),
                                             "yyyy-MM-dd HH:mm:ss"))
        got = list(o.to_pydict()["s"])
        assert got == ["2026-01-01 10:30:45", "2026-01-02 00:00:00"]

    def test_timezone_and_trailing_content_ignored(self):
        f = Frame({"d": np.asarray(
            ["2026-01-01 10:00:00+09:00", "2026-01-01 10:00:00 UTC",
             "2026-03-05 trailing junk", "2026-13-01"], dtype=object)})
        o = f.with_column("y", F.year(F.col("d"))).to_pydict()
        assert list(o["y"])[:3] == [2026.0, 2026.0, 2026.0]
        assert np.isnan(o["y"][3])           # month 13 -> null, not wrap
