"""Multi-host bootstrap (``master("pod")``): config plumbing into
``jax.distributed.initialize`` (mocked), and a real 2-process CPU
integration run with a local coordinator asserting the mesh spans both
processes — the closest one-machine analogue of a TPU pod, mirroring how
the reference gets a multi-executor cluster from one JVM with
``master("local[*]")`` (`DataQuality4MachineLearningApp.java:40`).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from sparkdq4ml_tpu import TpuSession

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


class TestPodBootstrapPlumbing:
    """Unit tests of TpuSession._init_distributed with a recording stub."""

    @pytest.fixture
    def record(self, monkeypatch):
        import jax

        calls = []
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: calls.append(kw))
        return calls

    def test_local_master_does_not_initialize(self, record):
        s = TpuSession(master="local[2]")
        assert record == []
        s.stop()

    def test_pod_master_auto_bootstrap(self, record):
        # bare pod: coordinator/ranks come from the TPU metadata (no kwargs)
        s = TpuSession(master="pod")
        assert record == [{}]
        s.stop()

    def test_explicit_coordinator_conf_plumbed(self, record):
        s = TpuSession(master="pod", conf={
            "spark.distributed.coordinator": "10.0.0.1:8476",
            "spark.distributed.numProcesses": "4",
            "spark.distributed.processId": "2",
        })
        assert record == [{
            "coordinator_address": "10.0.0.1:8476",
            "num_processes": 4,
            "process_id": 2,
        }]
        s.stop()

    def test_coordinator_conf_without_pod_master_initializes(self, record):
        s = TpuSession(master="local[*]", conf={
            "spark.distributed.coordinator": "10.0.0.1:8476",
            "spark.distributed.numProcesses": "2",
            "spark.distributed.processId": "0",
        })
        assert len(record) == 1
        assert record[0]["coordinator_address"] == "10.0.0.1:8476"
        s.stop()

    def test_idempotent_when_client_exists(self, record, monkeypatch):
        from jax._src import distributed as _dist

        monkeypatch.setattr(_dist.global_state, "client", object(),
                            raising=False)
        s = TpuSession(master="pod")
        assert record == []
        s.stop()


_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, "@REPO@")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from sparkdq4ml_tpu import TpuSession

    pid = int(sys.argv[1])
    s = (TpuSession.builder().app_name("podtest").master("pod")
         .config("spark.distributed.coordinator", "127.0.0.1:@PORT@")
         .config("spark.distributed.numProcesses", "2")
         .config("spark.distributed.processId", str(pid))
         .get_or_create())
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2 * jax.local_device_count()
    assert s.mesh.devices.size == len(jax.devices())

    # the mesh spans both processes: a global psum over the pod mesh
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparkdq4ml_tpu.parallel.mesh import DATA_AXIS

    n_local = jax.local_device_count()
    total = len(jax.devices())
    local = np.full((n_local,), float(pid + 1), np.float32)
    garr = jax.make_array_from_single_device_arrays(
        (total,), NamedSharding(s.mesh, P(DATA_AXIS)),
        [jax.device_put(local[i:i+1], d)
         for i, d in enumerate(jax.local_devices())])
    tot = jax.jit(lambda x: jnp.sum(x))(garr)
    # process 0 contributes 1.0 per local device, process 1 contributes 2.0
    expect = 3.0 * n_local
    assert float(tot) == expect, (float(tot), expect)
    print(f"proc {pid} ok: devices={total} sum={float(tot)}")
""")


@pytest.mark.slow
def test_two_process_cpu_pod():
    """Real jax.distributed over two CPU processes and one coordinator."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no accelerator auto-register
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)              # 1 local CPU device per process
    script = _WORKER.replace("@REPO@", REPO).replace("@PORT@", str(port))
    procs = [subprocess.Popen([sys.executable, "-c", script, str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} ok" in out
