"""Device-cost observatory suite (ISSUE 15, tier-1, ``costprof`` marker).

Tentpole coverage: the AOT cost extractor (``analysis/program/costs.py``
— flops/bytes monotone in rows, per-collective bytes scaling with the
mesh, zero counted compiles/syncs during extraction), the per-key
profile cache + statstore persistence (``utils/costprof.py``), EXPLAIN
ANALYZE cost columns on the headline DQ+Lasso workload with goldens
unchanged, roofline verdict sanity (memory-bound elementwise chain vs
compute-bound Gramian, sync/host arms), the shard-skew gauge and
exchange-volume counters, the ``/profile`` + ``/profile/trace`` HTTP
routes with managed-capture retention, the ``cost_profile`` fault-site
degradation ladder, the ``program-handle`` dqlint rule, and the
disabled-mode pins (``spark.costprof.enabled=false`` = one flag read,
byte-identical pre-observatory EXPLAIN output).
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.analysis.program import costs as prog_costs
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.serve import TelemetryServer
from sparkdq4ml_tpu.utils import costprof, faults
from sparkdq4ml_tpu.utils import observability as obs
from sparkdq4ml_tpu.utils import profiling, statstore
from sparkdq4ml_tpu.utils.observability import ProgramHandle
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.costprof


@pytest.fixture(autouse=True)
def _clean_costprof_state():
    """Profile cache, statstore, chaos plan, and conf are process-global."""
    costprof.clear()
    statstore.STORE.clear()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("costprof.")
    profiling.counters.clear("shard.exchange_bytes")
    saved = (config.costprof_enabled, config.costprof_ridge,
             config.profiling_max_captures, config.stats_enabled)
    yield
    obs.disable()
    (config.costprof_enabled, config.costprof_ridge,
     config.profiling_max_captures, config.stats_enabled) = saved
    costprof.clear()
    statstore.STORE.clear()
    faults.clear()
    RECOVERY_LOG.clear()


def _get(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _elementwise_handle(n: int, steps: int = 6,
                        key: str = "ew") -> ProgramHandle:
    """A memory-bound chain: O(1) flops per byte moved."""
    def body(x):
        for i in range(steps):
            x = x * 1.5 + float(i)
        return x

    spec = jax.ShapeDtypeStruct((n,), np.float32)
    return ProgramHandle("test", f"{key}|n={n}", body, args=(spec,))


def _gram_handle(n: int, d: int, key: str = "gram") -> ProgramHandle:
    """A compute-bound Gramian: O(d) flops per byte at n >> d."""
    def body(x):
        return x.T @ x

    spec = jax.ShapeDtypeStruct((n, d), np.float32)
    return ProgramHandle("test", f"{key}|{n}x{d}", body, args=(spec,))


def _psum_handle(devices: int, n: int = 1024) -> ProgramHandle:
    from jax.sharding import PartitionSpec as P

    from sparkdq4ml_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                              shard_map)

    mesh = make_mesh(devices=jax.devices()[:devices])

    def local(x):
        return jax.lax.psum(x.sum(), DATA_AXIS)

    fn = shard_map(local, mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P())
    spec = jax.ShapeDtypeStruct((n,), np.float32)
    return ProgramHandle("test", f"psum|{devices}", fn, args=(spec,),
                         mesh=mesh, guarded=True)


# ---------------------------------------------------------------------------
# Extractor unit pins
# ---------------------------------------------------------------------------


class TestExtractor:
    def test_profile_fields_present(self):
        doc = prog_costs.extract(_elementwise_handle(4096))
        assert doc is not None
        assert doc["flops"] > 0
        assert doc["bytes_accessed"] > 0
        assert doc["output_bytes"] > 0
        assert doc["devices"] == 1
        assert doc["extract_ms"] >= 0

    def test_flops_and_bytes_monotone_in_rows(self):
        small = prog_costs.extract(_elementwise_handle(1024))
        big = prog_costs.extract(_elementwise_handle(8192))
        assert big["flops"] > small["flops"]
        assert big["bytes_accessed"] > small["bytes_accessed"]
        assert big["output_bytes"] > small["output_bytes"]

    def test_transcendentals_counted(self):
        def body(x):
            return jax.numpy.exp(x)

        h = ProgramHandle("test", "exp", body,
                          args=(jax.ShapeDtypeStruct((512,), np.float32),))
        doc = prog_costs.extract(h)
        assert doc["transcendentals"] >= 512

    @pytest.mark.skipif(len(jax.devices()) < 8,
                        reason="needs 8 forced host devices")
    def test_collective_bytes_match_mesh_size(self):
        d4 = prog_costs.collective_bytes(_psum_handle(4))
        d8 = prog_costs.collective_bytes(_psum_handle(8))
        assert "psum" in d4 and "psum" in d8
        # a scalar psum's aggregate payload is itemsize x devices
        assert d8["psum"] == 2 * d4["psum"]
        doc = prog_costs.extract(_psum_handle(8))
        assert doc["collectives"]["psum"] == d8["psum"]
        assert doc["devices"] == 8

    def test_extraction_counts_no_compiles_no_syncs(self):
        """The acceptance pin: extraction performs zero counted host
        syncs and zero counted compiles — it targets the UN-counted
        trace bodies, and nothing executes on device."""
        session = dq.TpuSession.builder().app_name(
            "costprof-pin").master("local[*]").get_or_create()
        try:
            f = Frame({"v": np.arange(512, dtype=np.float64)})
            f.create_or_replace_temp_view("cp_pin")
            session.sql("SELECT v * 2 AS w FROM cp_pin WHERE v > 10") \
                .count()
            session.sql("SELECT v, count(*) c FROM cp_pin GROUP BY v") \
                .count()
            costprof.clear()
            before = {k: profiling.counters.get(k) for k in (
                "frame.host_sync", "pipeline.compile", "pipeline.hit",
                "grouped.compile", "grouped.hit", "stats.drain_sync")}
            out = costprof.extract_all(budget=100)
            assert any(v["profile"] is not None for v in out.values())
            for k, v in before.items():
                assert profiling.counters.get(k) == v, k
        finally:
            session.stop()


# ---------------------------------------------------------------------------
# Roofline verdicts + achieved throughput
# ---------------------------------------------------------------------------


class TestRoofline:
    def test_elementwise_chain_is_memory_bound(self):
        prof = costprof.CostProfile.from_doc(
            prog_costs.extract(_elementwise_handle(65536)))
        assert prof.intensity < config.costprof_ridge
        assert costprof.roofline(prof) == "memory"

    def test_gramian_is_compute_bound(self):
        prof = costprof.CostProfile.from_doc(
            prog_costs.extract(_gram_handle(4096, 64)))
        assert prof.intensity >= config.costprof_ridge
        assert costprof.roofline(prof) == "compute"

    def test_ridge_conf_moves_the_verdict(self):
        prof = costprof.CostProfile.from_doc(
            prog_costs.extract(_gram_handle(4096, 64)))
        config.costprof_ridge = 1e9
        assert costprof.roofline(prof) == "memory"

    def test_sync_bound_tiny_program_with_sync(self):
        prof = costprof.CostProfile(flops=10.0, bytes_accessed=64.0)
        assert costprof.roofline(prof, host_syncs=1) == "sync"
        assert costprof.roofline(prof, host_syncs=0) == "memory"

    def test_host_verdict_without_profile(self):
        assert costprof.roofline(None) == "host"

    def test_achieved_throughput(self):
        prof = costprof.CostProfile(flops=2e9, bytes_accessed=1e9)
        gflops, gbps = costprof.achieved(prof, wall_ms=1000.0)
        assert gflops == pytest.approx(2.0)
        assert gbps == pytest.approx(1.0)
        assert costprof.achieved(prof, None) == (None, None)
        assert costprof.achieved(None, 5.0) == (None, None)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE cost columns (headline workload, goldens pinned)
# ---------------------------------------------------------------------------


#: The second headline DQ filter — the view ``run_dq_pipeline`` leaves
#: registered holds the second-stage frame, so this is the statement an
#: EXPLAIN ANALYZE can replay against it.
HEADLINE_DQ2 = ("SELECT guest, price_correct_correl AS price "
                "FROM price WHERE price_correct_correl > 0")


class TestExplainCostColumns:
    def test_headline_analyze_renders_cost_columns_goldens_unchanged(
            self, session):
        df = run_dq_pipeline(session, dataset_path("abstract"))
        assert df.count() == 24                       # golden
        plan = session.sql("EXPLAIN ANALYZE " + HEADLINE_DQ2) \
            .to_pydict()["plan"][0]
        assert "est_flops=" in plan
        assert "est_bytes=" in plan
        assert "gflops=" in plan and "gbps=" in plan
        assert "bound=" in plan
        # the fused stage ran a device program: a real verdict, not "-"
        fused = next(ln for ln in plan.splitlines()
                     if ln.startswith("FusedStage"))
        assert "bound=memory" in fused or "bound=compute" in fused \
            or "bound=sync" in fused
        assert "est_flops=-" not in fused
        # golden model numbers stay exact with the observatory on
        from sparkdq4ml_tpu.models import LinearRegression

        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(
            prepare_features(df))
        assert float(model.summary.root_mean_squared_error) == \
            pytest.approx(2.809940, rel=1e-3)

    def test_grouped_node_gets_cost_columns(self, session):
        f = Frame({"k": (np.arange(2048) % 8).astype(np.float64),
                   "v": np.arange(2048, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_g")
        session.sql("SELECT k, sum(v) s FROM cp_g GROUP BY k").count()
        plan = session.sql(
            "EXPLAIN ANALYZE SELECT k, sum(v) s FROM cp_g GROUP BY k") \
            .to_pydict()["plan"][0]
        seg = next(ln for ln in plan.splitlines()
                   if ln.lstrip("+- ").startswith("SegmentedAggregate"))
        assert "est_flops=" in seg and "bound=" in seg
        assert "est_flops=-" not in seg

    def test_disabled_mode_restores_pre_observatory_output(
            self, session, monkeypatch):
        f = Frame({"v": np.arange(256, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_off")
        sql = "EXPLAIN ANALYZE SELECT v * 3 AS w FROM cp_off WHERE v > 5"
        session.sql(sql)                    # warm plans either way
        config.costprof_enabled = False
        # one-flag-read pin: with the observatory off, none of its
        # machinery may run at all
        monkeypatch.setattr(costprof, "profile_for", _raise_hook)
        monkeypatch.setattr(costprof, "report", _raise_hook)
        plan = session.sql(sql).to_pydict()["plan"][0]
        for key in ("est_flops", "est_bytes", "gflops", "gbps", "bound="):
            assert key not in plan
        config.costprof_enabled = True
        plan_on = session.sql(sql).to_pydict()["plan"][0]
        assert "bound=" in plan_on          # flag flips it back on


def _raise_hook(*a, **kw):
    raise AssertionError("costprof hook ran in disabled mode")


# ---------------------------------------------------------------------------
# Cardinality history (satellite: aggregates no longer estimate blind)
# ---------------------------------------------------------------------------


class TestCardinalityHistory:
    def test_group_by_est_rows_from_history(self, session):
        f = Frame({"k": (np.arange(4096) % 16).astype(np.float64),
                   "v": np.arange(4096, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_card")
        sql = "SELECT k, count(*) c FROM cp_card GROUP BY k"
        cold = session.sql("EXPLAIN " + sql).to_pydict()["plan"][0]
        agg_cold = next(ln for ln in cold.splitlines()
                        if ln.startswith(("SegmentedAggregate",
                                          "Aggregate")))
        assert "est_rows=-" in agg_cold     # blind before history
        session.sql(sql).count()            # record the cardinality
        warm = session.sql("EXPLAIN " + sql).to_pydict()["plan"][0]
        agg_warm = next(ln for ln in warm.splitlines()
                        if ln.startswith(("SegmentedAggregate",
                                          "Aggregate")))
        assert "est_rows=16" in agg_warm

    def test_distinct_est_rows_from_history(self, session):
        f = Frame({"k": (np.arange(2048) % 32).astype(np.float64)})
        f.create_or_replace_temp_view("cp_dcard")
        sql = "SELECT DISTINCT k FROM cp_dcard"
        session.sql(sql).count()
        plan = session.sql("EXPLAIN " + sql).to_pydict()["plan"][0]
        dist = next(ln for ln in plan.splitlines()
                    if ln.startswith("Distinct"))
        assert "est_rows=32" in dist

    def test_cardinality_key_is_order_insensitive(self):
        from sparkdq4ml_tpu.ops import segments

        a = np.arange(8, dtype=np.float32)
        b = np.arange(8, dtype=np.int32)
        k1 = segments.cardinality_history_key("g", ["x", "y"], [a, b])
        k2 = segments.cardinality_history_key("g", ["y", "x"], [b, a])
        assert k1 == k2
        assert segments.cardinality_history_key(
            "g", ["x"], [np.array(["s"], dtype=object)]) is None


# ---------------------------------------------------------------------------
# Profile cache + statstore persistence
# ---------------------------------------------------------------------------


class TestPersistence:
    def test_statstore_roundtrip_of_cost_profiles(self, tmp_path):
        doc = {"flops": 123.0, "bytes_accessed": 456.0,
               "output_bytes": 7.0, "devices": 2,
               "collectives": {"psum": 64}, "peak_bytes": 2048}
        statstore.STORE.record_cost("K1", "cost:test", doc)
        path = str(tmp_path / "stats.jsonl")
        assert statstore.STORE.save(path)
        fresh = statstore.StatStore()
        assert fresh.load(path) >= 1
        got = fresh.cost("K1")
        assert got is not None
        assert got["flops"] == 123.0
        assert got["collectives"] == {"psum": 64}

    def test_cost_survives_winner_merge(self):
        with_cost = statstore.KeyStats("K", "pipeline")
        with_cost.cost = {"flops": 5.0}
        heavier = statstore.KeyStats("K", "pipeline")
        heavier.flushes = 50                 # more evidence, no cost
        target: dict = {}
        statstore.StatStore._merge_into(target, [with_cost])
        statstore.StatStore._merge_into(target, [heavier])
        assert target["K"].cost == {"flops": 5.0}
        # and the reverse order keeps it too
        target2: dict = {}
        statstore.StatStore._merge_into(target2, [heavier])
        statstore.StatStore._merge_into(target2, [with_cost])
        assert target2["K"].cost == {"flops": 5.0}

    def test_profile_for_adopts_persisted_doc_without_extraction(
            self, monkeypatch):
        statstore.STORE.record_cost(
            "PK", "cost:test", {"flops": 9.0, "bytes_accessed": 90.0})
        monkeypatch.setattr(costprof, "_extract", _raise_hook)
        prof = costprof.profile_for("PK")
        assert prof is not None and prof.flops == 9.0

    def test_bytes_bound_folds_cost_peak(self):
        s = statstore.StatStore()
        s.record_flush("K", "pipeline", est_bytes=100)
        assert s.bytes_bound("K") == 100
        s.record_cost("K", "cost:test", {"peak_bytes": 5000})
        assert s.bytes_bound("K") == 5000

    def test_extraction_records_into_statstore(self, session):
        f = Frame({"v": np.arange(512, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_rec")
        session.sql("SELECT v + 1 AS w FROM cp_rec WHERE v > 3").count()
        out = costprof.extract_all(budget=100)
        keys = [k for k, v in out.items()
                if v["cache"] == "pipeline" and v["profile"] is not None]
        assert keys
        assert statstore.STORE.cost(keys[0]) is not None


# ---------------------------------------------------------------------------
# Fault-site ladder
# ---------------------------------------------------------------------------


class TestFaultLadder:
    def test_cost_profile_site_registered(self):
        assert "cost_profile" in faults.FAULT_SITES

    def test_injected_fault_degrades_to_unprofiled(self, session):
        f = Frame({"v": np.arange(512, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_fault")
        session.sql("SELECT v - 1 AS w FROM cp_fault WHERE v > 2").count()
        handles, _ = obs.CACHES.programs()
        key = next(h.program_key for h in handles
                   if h.cache == "pipeline")
        statstore.STORE.clear()              # no persisted shortcut
        before = profiling.counters.get("costprof.failed")
        with faults.inject_faults("cost_profile:device_error:1"):
            assert costprof.profile_for(key) is None
        assert profiling.counters.get("costprof.failed") == before + 1
        events = [e for e in RECOVERY_LOG.events()
                  if e.site == "cost_profile"]
        assert events and events[-1].action == "fallback"
        # the failure is cached: no re-extraction storm per scrape
        assert costprof.profile_for(key) is None
        # a fresh cache re-earns the profile once chaos stops
        costprof.clear()
        assert costprof.profile_for(key) is not None

    def test_report_survives_extraction_faults(self, session):
        f = Frame({"v": np.arange(512, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_fsurv")
        session.sql("SELECT v * 4 AS w FROM cp_fsurv WHERE v > 1").count()
        statstore.STORE.clear()
        with faults.inject_faults("cost_profile:device_error:p=1.0"):
            doc = costprof.report()
        assert doc["enabled"] is True
        assert all(r["flops"] is None for r in doc["entries"])


# ---------------------------------------------------------------------------
# Shard skew + exchange volume
# ---------------------------------------------------------------------------


class TestShardCost:
    def test_skew_gauge_under_forced_imbalance(self):
        from sparkdq4ml_tpu.parallel import shard

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 forced host devices")
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(devices=jax.devices()[:8])
        balanced = shard.ShardedStore(mesh, rows=4096, bucket=512)
        shard.record_skew(balanced)
        assert obs.METRICS.get_gauge("shard.skew") == pytest.approx(1.0)
        lopsided = shard.ShardedStore(mesh, rows=513, bucket=512)
        shard.record_skew(lopsided)
        # worst shard holds 512 of 513 rows: ~8x the mean
        assert obs.METRICS.get_gauge("shard.skew") == pytest.approx(
            512 / (513 / 8), rel=1e-3)

    def test_exchange_counter_families(self):
        from sparkdq4ml_tpu.parallel.shard import record_exchange

        base = profiling.counters.get("shard.exchange_bytes")
        record_exchange("gather", 1000)
        record_exchange("psum", 24)
        assert profiling.counters.get("shard.exchange_bytes") \
            == base + 1024
        assert profiling.counters.get("shard.exchange_bytes.gather") \
            >= 1000
        assert profiling.counters.get("shard.exchange_bytes.psum") >= 24

    def test_exchange_disabled_is_noop(self):
        from sparkdq4ml_tpu.parallel.shard import record_exchange

        config.costprof_enabled = False
        base = profiling.counters.get("shard.exchange_bytes")
        record_exchange("gather", 4096)
        assert profiling.counters.get("shard.exchange_bytes") == base

    def test_metric_families_registered(self):
        assert "shard.skew" in obs.METRIC_NAMES
        assert "shard.exchange_bytes" in obs.METRIC_NAMES
        assert "shard.exchange_bytes." in obs.METRIC_NAME_PREFIXES
        assert "costprof." in obs.METRIC_NAME_PREFIXES
        assert "costprof.extracted" in obs.METRIC_NAMES
        assert "costprof.failed" in obs.METRIC_NAMES


# ---------------------------------------------------------------------------
# HTTP routes
# ---------------------------------------------------------------------------


class TestProfileRoutes:
    def test_profile_route_schema(self, session):
        f = Frame({"v": np.arange(1024, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_http")
        session.sql("SELECT v / 2 AS w FROM cp_http WHERE v > 7").count()
        with TelemetryServer(port=0) as ts:
            code, body = _get(
                f"http://127.0.0.1:{ts.port}/profile?top=4")
            assert code == 200
            doc = json.loads(body)
            for key in ("enabled", "entries", "size", "pending",
                        "capture", "skew", "exchange_bytes",
                        "ridge_flops_per_byte"):
                assert key in doc, key
            assert doc["enabled"] is True
            assert doc["entries"]
            row = doc["entries"][0]
            for key in ("key", "cache", "flops", "bytes", "gflops",
                        "gbps", "bound", "device_time_share",
                        "collectives"):
                assert key in row, key

    def test_profile_route_disabled_pin(self, monkeypatch):
        config.costprof_enabled = False
        monkeypatch.setattr(costprof, "report", _raise_hook)
        with TelemetryServer(port=0) as ts:
            code, body = _get(f"http://127.0.0.1:{ts.port}/profile")
        assert code == 200
        assert json.loads(body) == {"enabled": False, "entries": []}

    def test_profile_trace_arms_and_rejects_concurrent(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDQ4ML_CAPTURE_DIR", str(tmp_path))
        with TelemetryServer(port=0) as ts:
            base = f"http://127.0.0.1:{ts.port}"
            code, body = _get(base + "/profile/trace?seconds=5&label=t1")
            assert code == 200
            doc = json.loads(body)
            assert doc["armed"] is True
            assert os.path.isdir(doc["path"])
            assert "-t1" in doc["path"]
            # one capture at a time: the second arm answers 409
            try:
                _get(base + "/profile/trace?seconds=1")
                raise AssertionError("expected 409")
            except urllib.error.HTTPError as e:
                assert e.code == 409
            finally:
                profiling.stop_capture()
            # /profile surfaces the newest capture path
            code, body = _get(base + "/profile")
            assert json.loads(body)["capture"] == doc["path"]

    def test_capture_retention_bounded(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SPARKDQ4ML_CAPTURE_DIR", str(tmp_path))
        config.profiling_max_captures = 2
        for i in range(5):
            os.makedirs(tmp_path / f"cap-2026010{i}-000000-1-x")
        assert profiling.prune_captures() == 3
        assert len(profiling.captures()) == 2
        # newest survive
        assert profiling.latest_capture().endswith("cap-20260104-000000-1-x")


# ---------------------------------------------------------------------------
# session.profile_report + disabled-mode pins
# ---------------------------------------------------------------------------


class TestProfileReport:
    def test_report_rows_join_statstore(self, session):
        f = Frame({"v": np.arange(2048, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_rep")
        sql = "SELECT v * 2 AS w FROM cp_rep WHERE v > 100"
        for _ in range(3):
            session.sql(sql).count()
        doc = session.profile_report()
        for _ in range(32):              # budgeted extraction refills
            if not doc["pending"]:
                break
            doc = session.profile_report()
        assert doc["enabled"] is True and doc["size"] >= 1
        assert not doc["pending"]
        # the one plan with recorded wall mass ranks first by share
        row = doc["entries"][0]
        assert row["cache"] == "pipeline"
        assert row["device_time_share"] == pytest.approx(1.0)
        assert row["bound"] in ("compute", "memory", "sync")
        assert row["flushes"] >= 3
        assert row["wall_ms_p50"] is not None
        assert row["gflops"] is not None and row["gbps"] is not None
        shares = [r["device_time_share"] for r in doc["entries"]
                  if r["device_time_share"] is not None]
        assert shares == sorted(shares, reverse=True)

    def test_grouped_rows_join_wall_history(self, session):
        """Review regression: grouped flushes record statstore history
        under the struct key ('G|...'), not the per-lowering cache key —
        the report must join through the producer-declared stats_key or
        every grouped plan reads flushes=0 / throughput None."""
        f = Frame({"k": (np.arange(2048) % 8).astype(np.float64),
                   "v": np.arange(2048, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_gjoin")
        sql = "SELECT k, sum(v) s FROM cp_gjoin GROUP BY k"
        for _ in range(3):
            session.sql(sql).count()
        doc = costprof.report(budget=100)
        grouped = [r for r in doc["entries"]
                   if r["cache"] == "grouped" and r["flushes"] >= 3]
        assert grouped, doc["entries"]
        assert grouped[0]["wall_ms_p50"] is not None
        assert grouped[0]["gflops"] is not None

    def test_pending_rows_are_not_verdicted_host(self, session):
        """Review regression: a budget-exhausted (pending) or degraded
        entry is still a device program — its bound must render null,
        never the roofline's 'host' verdict."""
        f = Frame({"v": np.arange(512, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_pend")
        for _ in range(2):
            session.sql("SELECT v * 9 AS w FROM cp_pend WHERE v > 4") \
                .count()
        doc = costprof.report(budget=0)
        assert doc["pending"] >= 1
        for r in doc["entries"]:
            if r["pending"]:
                assert r["bound"] is None

    def test_capture_timer_bound_to_its_own_capture(
            self, tmp_path, monkeypatch):
        """Review regression: a stale stop timer from an earlier capture
        must not truncate a newer one."""
        import time

        monkeypatch.setenv("SPARKDQ4ML_CAPTURE_DIR", str(tmp_path))
        path_a = profiling.start_capture(0.1, label="a")
        assert profiling.stop_capture() == path_a     # manual stop
        path_b = profiling.start_capture(60, label="b")
        try:
            # a's timer (and an explicit stale-expected stop) are no-ops
            assert profiling.stop_capture(expected=path_a) is None
            time.sleep(0.3)
            assert profiling.capture_active() == path_b
        finally:
            assert profiling.stop_capture() == path_b

    def test_report_refuses_when_disabled(self, session, monkeypatch):
        config.costprof_enabled = False
        monkeypatch.setattr(costprof, "report", _raise_hook)
        doc = session.profile_report()
        assert doc == {"enabled": False, "entries": [], "size": 0,
                       "pending": 0}

    def test_extraction_budget_leaves_pending(self, session):
        f = Frame({"v": np.arange(256, dtype=np.float64)})
        f.create_or_replace_temp_view("cp_bud")
        session.sql("SELECT v + 2 AS a FROM cp_bud WHERE v > 1").count()
        session.sql("SELECT v, max(v) m FROM cp_bud GROUP BY v").count()
        out = costprof.extract_all(budget=0)
        assert out and all(v["pending"] for v in out.values()
                           if v["profile"] is None)
        out2 = costprof.extract_all(budget=100)
        assert any(v["profile"] is not None for v in out2.values())

    def test_costprof_conf_keys_session_scoped(self):
        s = (dq.TpuSession.builder().app_name("cp-conf")
             .master("local[*]")
             .config("spark.costprof.enabled", "false")
             .config("spark.costprof.ridge", "32.5")
             .config("spark.profiling.maxCaptures", "7")
             .get_or_create())
        try:
            assert config.costprof_enabled is False
            assert config.costprof_ridge == 32.5
            assert config.profiling_max_captures == 7
        finally:
            s.stop()
        assert config.costprof_enabled is True     # restored


# ---------------------------------------------------------------------------
# dqlint program-handle rule
# ---------------------------------------------------------------------------


class TestProgramHandleRule:
    @staticmethod
    def _run(text: str):
        from sparkdq4ml_tpu.analysis.core import SourceFile
        from sparkdq4ml_tpu.analysis.rules.program_handles import (
            ProgramHandleRule)

        src = SourceFile("x.py", "sparkdq4ml_tpu/x.py", text=text)
        rule = ProgramHandleRule()
        return [f for f in rule.visit(src) if f is not None]

    def test_register_without_programs_flagged(self):
        findings = self._run(
            "CACHES.register('mycache', stats_fn)\n")
        assert findings and "register_programs" in findings[0].message

    def test_register_with_programs_sanctioned(self):
        findings = self._run(
            "CACHES.register('mycache', stats_fn)\n"
            "CACHES.register_programs('mycache', programs_fn)\n")
        assert not findings

    def test_unrelated_registry_ignored(self):
        findings = self._run("router.register('x', handler)\n")
        assert not findings

    def test_counted_fn_entry_flagged(self):
        findings = self._run(
            "h = ProgramHandle('c', 'k', entry.fn, args=())\n")
        assert findings and "COUNTED" in findings[0].message

    def test_trace_body_sanctioned(self):
        findings = self._run(
            "h = ProgramHandle('c', 'k', entry.trace_body, args=())\n")
        assert not findings

    def test_missing_fn_flagged(self):
        findings = self._run("h = ProgramHandle('c', 'k')\n")
        assert findings and "untraceable" in findings[0].message

    def test_rule_in_catalog(self):
        from sparkdq4ml_tpu.analysis.rules import ALL_RULES, get_rules

        names = [c.name for c in ALL_RULES]
        assert "program-handle" in names
        assert get_rules(["program-handle"])


# ---------------------------------------------------------------------------
# Bench-gate recognition
# ---------------------------------------------------------------------------


@pytest.mark.bench_regress
class TestBenchGate:
    def test_costprof_section_recognized_and_gated(self, tmp_path):
        import subprocess
        import sys

        script = os.path.join(os.path.dirname(__file__), "..",
                              "scripts", "check_bench_regress.py")
        old = {"costprof": {"report_ms": 10.0, "disabled_flush_ms": 1.0}}
        new_ok = {"costprof": {"report_ms": 10.5,
                               "disabled_flush_ms": 1.05}}
        new_bad = {"costprof": {"report_ms": 20.0,
                                "disabled_flush_ms": 1.0}}
        p_old = tmp_path / "old.json"
        p_old.write_text(json.dumps(old))
        for doc, want in ((new_ok, 0), (new_bad, 1)):
            p_new = tmp_path / "new.json"
            p_new.write_text(json.dumps(doc))
            r = subprocess.run(
                [sys.executable, script, "--old", str(p_old),
                 "--new", str(p_new)], capture_output=True, text=True)
            assert r.returncode == want, r.stdout + r.stderr
