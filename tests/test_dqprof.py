"""Data-quality observatory suite (tier-1, ``dqprof`` marker).

Tentpole coverage: the column-profile sketch algebra
(``utils/dqprof.py`` — raw-moment decode, Welford/Chan merge
associativity, fixed histogram bucket edges, null/NaN arms, empty-column
sentinels), decomposable shard-merge parity vs single-device, the
zero-added-sync contract (deferred sketches, one counted cold-path
drain) and the disabled-mode raise-monkeypatch pins, statstore baseline
persistence (round-trip + winner-merge keeps profiles), the drift
scorer's threshold flip (gauge + incident bundle + tail-sampler
keep-reason), per-rule violation accounting on the eager UDF path,
the ``dq_profile`` fault-site degradation ladder, the ``/dq`` HTTP
route schema + disabled pin, and the ``== Data Quality ==`` EXPLAIN
ANALYZE section with the headline goldens (24 rows / RMSE 2.8099)
unchanged.
"""

from __future__ import annotations

import json
import types
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.serve import TelemetryServer
from sparkdq4ml_tpu.utils import dqprof, faults, incidents
from sparkdq4ml_tpu.utils import observability as obs
from sparkdq4ml_tpu.utils import profiling, statstore
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.dqprof


@pytest.fixture(autouse=True)
def _clean_dqprof_state():
    """Profiles, statstore, chaos plan, recorder, and conf are
    process-global."""
    dqprof.clear()
    statstore.STORE.clear()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("dq.")
    obs.METRICS.clear()
    incidents.RECORDER.reset()
    incidents.RECORDER.configure(enabled=False, directory="",
                                 max_bundles=32, cooldown_s=5.0,
                                 slo_burn_threshold=8.0)
    saved = (config.dq_profile_enabled, config.dq_histogram_bins,
             config.dq_drift_threshold, config.dq_baseline_mode,
             config.stats_enabled)
    yield
    obs.disable()
    (config.dq_profile_enabled, config.dq_histogram_bins,
     config.dq_drift_threshold, config.dq_baseline_mode,
     config.stats_enabled) = saved
    dqprof.clear()
    statstore.STORE.clear()
    faults.clear()
    RECOVERY_LOG.clear()
    incidents.RECORDER.reset()
    incidents.RECORDER.configure(enabled=False, directory="",
                                 max_bundles=32, cooldown_s=5.0,
                                 slo_burn_threshold=8.0)


def _get(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _profile_of(values, bins: int = 32,
                mask=None) -> dqprof.ColumnProfile:
    """One drained single-device sketch of ``values``."""
    x = np.asarray(values, dtype=np.float64)
    m = (np.ones(x.shape, bool) if mask is None
         else np.asarray(mask, bool))
    raw = jax.device_get(dqprof._sketch_body(bins)(
        jax.numpy.asarray(x), jax.numpy.asarray(m)))
    prof = dqprof.ColumnProfile.from_raw(raw)
    assert prof is not None
    return prof


def _flush_chain(frame) -> int:
    """A fused 3-column arithmetic chain + filter, forced to execute."""
    f = frame
    for i in range(3):
        f = f.with_column(f"c{i}", dq.col("v") * float(i + 1) + 0.5)
    f = f.filter(dq.col("c2") > 0)
    return int(f.count())


# ---------------------------------------------------------------------------
# Sketch units: raw-moment decode, merge algebra, histogram, NaN arms
# ---------------------------------------------------------------------------


class TestSketchUnits:
    def test_device_sketch_matches_numpy(self):
        vals = np.linspace(-50.0, 200.0, 400)
        p = _profile_of(vals)
        assert p.count == 400 and p.nulls == 0
        assert p.mean == pytest.approx(vals.mean(), rel=1e-5)
        assert p.variance == pytest.approx(vals.var(ddof=1), rel=1e-4)
        assert p.min == pytest.approx(vals.min())
        assert p.max == pytest.approx(vals.max())
        assert sum(p.hist) == 400 and len(p.hist) == 32

    def test_welford_merge_associative(self):
        rng = np.random.default_rng(11)
        a, b, c = (rng.normal(loc=m, scale=3.0, size=257)
                   for m in (0.0, 5.0, -2.0))
        pa, pb, pc = (_profile_of(v) for v in (a, b, c))
        left = pa.copy()
        left.merge(pb)
        left.merge(pc)                       # (a + b) + c
        right = pb.copy()
        right.merge(pc)
        merged = pa.copy()
        merged.merge(right)                  # a + (b + c)
        whole = np.concatenate([a, b, c])
        for p in (left, merged):
            assert p.count == whole.size
            assert p.mean == pytest.approx(whole.mean(), rel=1e-5)
            assert p.variance == pytest.approx(whole.var(ddof=1),
                                               rel=1e-4)
            assert p.min == pytest.approx(whole.min())
            assert p.max == pytest.approx(whole.max())
        assert left.mean == pytest.approx(merged.mean, rel=1e-9)
        assert left.m2 == pytest.approx(merged.m2, rel=1e-8)
        assert left.hist == merged.hist

    def test_histogram_edges_fixed_and_monotone(self):
        edges = dqprof.histogram_edges(32)
        assert len(edges) == 33
        assert all(b > a for a, b in zip(edges, edges[1:]))
        # symmetric log-compressed domain: edge k mirrors edge -k,
        # zero sits exactly on the middle edge
        assert edges[0] == pytest.approx(-edges[-1])
        assert edges[16] == pytest.approx(0.0, abs=1e-9)
        # deterministic: the merge contract across sessions
        assert dqprof.histogram_edges(32) == edges

    def test_histogram_buckets_match_edges(self):
        # values chosen in bucket interiors: the f32 device transform
        # and the f64 host edges must not disagree at a boundary
        vals = np.array([-1234.5, -3.0, -0.5, 0.5, 3.0, 7777.0])
        p = _profile_of(vals, bins=16)
        edges = np.asarray(dqprof.histogram_edges(16))
        expect, _ = np.histogram(vals, bins=edges)
        assert sum(p.hist) == vals.size
        assert p.hist == [int(c) for c in expect]

    def test_null_nan_arms(self):
        vals = np.array([1.0, np.nan, 3.0, np.nan, 5.0, 7.0])
        mask = np.array([True, True, True, False, False, True])
        p = _profile_of(vals, mask=mask)
        # one NaN under the mask counts as a null; the masked-out NaN
        # and the masked-out 5.0 count as nothing at all
        assert p.nulls == 1
        assert p.count == 3
        assert p.mean == pytest.approx(np.mean([1.0, 3.0, 7.0]))
        assert p.min == pytest.approx(1.0)
        assert p.max == pytest.approx(7.0)
        assert sum(p.hist) == 3

    def test_empty_column_sentinels(self):
        p = _profile_of(np.arange(8.0), mask=np.zeros(8, bool))
        assert p.count == 0 and p.nulls == 0
        assert p.min is None and p.max is None
        assert p.variance is None
        assert sum(p.hist) == 0

    def test_profile_doc_roundtrip_and_version_gate(self):
        p = _profile_of(np.arange(64.0))
        doc = p.to_doc()
        assert doc["version"] == dqprof.PROFILE_VERSION
        back = dqprof.ColumnProfile.from_doc(doc)
        assert back is not None
        assert back.to_doc() == doc
        skewed = dict(doc, version=dqprof.PROFILE_VERSION + 1)
        assert dqprof.ColumnProfile.from_doc(skewed) is None
        assert dqprof.ColumnProfile.from_doc("nope") is None


# ---------------------------------------------------------------------------
# Decomposable shard merge: per-shard partials + psum/pmin/pmax
# ---------------------------------------------------------------------------


class TestShardMerge:
    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs 4 forced host devices")
    def test_sharded_sketch_parity_vs_single_device(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(devices=jax.devices()[:4])
        shard = types.SimpleNamespace(mesh=mesh, devices=4)
        rng = np.random.default_rng(7)
        vals = jax.numpy.asarray(rng.normal(scale=20.0, size=1024))
        mask = jax.numpy.asarray(rng.random(1024) > 0.2)
        single_fn = dqprof._program("sketch", 1024, vals.dtype, None)[0]
        sharded_fn = dqprof._program("sketch", 1024, vals.dtype,
                                     shard)[0]
        single = dqprof.ColumnProfile.from_raw(
            jax.device_get(single_fn(vals, mask)))
        merged = dqprof.ColumnProfile.from_raw(
            jax.device_get(sharded_fn(vals, mask)))
        # count/nulls/min/max/histogram are exact under any partition;
        # the f32 moment sums agree to summation-order rounding
        assert merged.count == single.count
        assert merged.nulls == single.nulls
        assert merged.min == pytest.approx(single.min)
        assert merged.max == pytest.approx(single.max)
        assert merged.hist == single.hist
        assert merged.mean == pytest.approx(single.mean, rel=1e-5)
        assert merged.m2 == pytest.approx(single.m2, rel=1e-4)

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="needs 4 forced host devices")
    def test_sharded_rule_counts_exact(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(devices=jax.devices()[:4])
        shard = types.SimpleNamespace(mesh=mesh, devices=4)
        vals = jax.numpy.asarray(
            np.where(np.arange(512) % 3 == 0, -1.0, 2.0))
        mask = jax.numpy.asarray(np.ones(512, bool))
        fn = dqprof._program("rule", 512, vals.dtype, shard)[0]
        total, passed = (int(round(float(v)))
                         for v in jax.device_get(fn(vals, mask)))
        assert total == 512
        assert passed == int(np.sum(np.arange(512) % 3 != 0))

    def test_host_merge_of_chunked_profiles_matches_whole(self):
        rng = np.random.default_rng(3)
        whole = rng.normal(size=900)
        merged = _profile_of(whole[:300])
        merged.merge(_profile_of(whole[300:600]))
        merged.merge(_profile_of(whole[600:]))
        direct = _profile_of(whole)
        assert merged.count == direct.count
        assert merged.hist == direct.hist
        assert merged.mean == pytest.approx(direct.mean, rel=1e-5)
        assert merged.variance == pytest.approx(direct.variance,
                                                rel=1e-4)


# ---------------------------------------------------------------------------
# Hot-path contract: deferred sketches, zero added syncs, disabled pins
# ---------------------------------------------------------------------------


class TestHotPathPins:
    def test_enabled_flush_adds_no_syncs_and_defers_drain(self, session):
        frame = Frame({"v": np.arange(2048, dtype=np.float64)})
        watched = ("frame.host_sync", "pipeline.compile",
                   "stats.drain_sync", "dq.drain_sync")

        def deltas():
            before = {k: profiling.counters.get(k) for k in watched}
            _flush_chain(frame)
            return {k: profiling.counters.get(k) - before[k]
                    for k in watched}

        config.dq_profile_enabled = False
        _flush_chain(frame)                  # warm the fused plan
        off = deltas()
        config.dq_profile_enabled = True
        _flush_chain(frame)                  # warm the sketch programs
        dqprof.clear()
        on = deltas()
        # the profiled flush costs the SAME number of host syncs and
        # pipeline compiles as the unprofiled one — sketches are
        # deferred device reductions, not synced reads
        assert on == off
        with dqprof._LOCK:
            assert len(dqprof._PENDING) > 0
        # the one counted sync happens at the cold report, not before
        base = profiling.counters.get("dq.drain_sync")
        rep = dqprof.report()
        assert rep["size"] > 0
        assert profiling.counters.get("dq.drain_sync") == base + 1

    def test_disabled_mode_never_touches_dqprof(self, session,
                                                monkeypatch):
        frame = Frame({"v": np.arange(512, dtype=np.float64)})
        config.dq_profile_enabled = True
        _flush_chain(frame)                  # warm while enabled
        config.dq_profile_enabled = False

        def _raise(*a, **kw):
            raise AssertionError("dqprof hook ran in disabled mode")

        monkeypatch.setattr(dqprof, "observe_flush", _raise)
        monkeypatch.setattr(dqprof, "record_eval", _raise)
        monkeypatch.setattr(dqprof, "drain", _raise)
        assert _flush_chain(frame) > 0
        # eager UDF path too: a registered rule evaluates, no hook runs
        dq.register_builtin_rules()
        f2 = Frame({"price": np.arange(32, dtype=np.float64) + 20.0})
        f2 = f2.with_column("pnm", dq.call_udf("minimumPriceRule",
                                               dq.col("price")))
        assert int(f2.count()) == 32

    def test_disabled_report_refuses(self, monkeypatch):
        config.dq_profile_enabled = False

        def _raise(*a, **kw):
            raise AssertionError("drain ran in disabled mode")

        monkeypatch.setattr(dqprof, "drain", _raise)
        assert dqprof.report() == {"enabled": False, "columns": [],
                                   "rules": [], "size": 0, "pending": 0}
        assert dqprof.rule_marks() is None
        assert dqprof.explain_lines(None) == []

    def test_pending_bound_drops_oldest_and_counts(self):
        config.dq_profile_enabled = True
        v = jax.numpy.float32(1.0)
        dqprof._enqueue([("rule", f"r{i}", 1, v)
                         for i in range(dqprof.MAX_PENDING + 5)])
        with dqprof._LOCK:
            assert len(dqprof._PENDING) == dqprof.MAX_PENDING
        assert profiling.counters.get("dq.pending_dropped") == 5

    def test_program_handles_registered(self, session):
        frame = Frame({"v": np.arange(256, dtype=np.float64)})
        config.dq_profile_enabled = True
        _flush_chain(frame)
        handles, errors = obs.CACHES.programs()
        assert "dqprof" not in errors
        mine = [h for h in handles if h.cache == "dqprof"]
        assert mine, "sketch programs must be registry-enumerable"
        assert all(h.program_key.startswith("dq") for h in mine)


# ---------------------------------------------------------------------------
# Statstore baselines: round-trip + winner-merge keeps profiles
# ---------------------------------------------------------------------------


class TestStatstoreBaselines:
    def test_record_profile_roundtrip(self, tmp_path):
        doc = _profile_of(np.arange(100.0)).to_doc()
        statstore.STORE.record_profile("dqprof|price", "dqprof", doc)
        path = str(tmp_path / "stats.jsonl")
        assert statstore.STORE.save(path)
        fresh = statstore.StatStore()
        assert fresh.load(path) >= 1
        assert fresh.profile("dqprof|price") == doc

    def test_profile_survives_winner_merge(self):
        with_prof = statstore.KeyStats("K", "dqprof")
        with_prof.profile = {"version": 1, "count": 9}
        heavier = statstore.KeyStats("K", "dqprof")
        heavier.flushes = 50                 # more evidence, no profile
        target: dict = {}
        statstore.StatStore._merge_into(target, [with_prof])
        statstore.StatStore._merge_into(target, [heavier])
        assert target["K"].profile == {"version": 1, "count": 9}
        target2: dict = {}
        statstore.StatStore._merge_into(target2, [heavier])
        statstore.StatStore._merge_into(target2, [with_prof])
        assert target2["K"].profile == {"version": 1, "count": 9}

    def test_pre_dq_docs_load_without_profile(self):
        # a persisted doc from before the observatory has no "profile"
        # field — loading must not invent one, saving must not emit one
        doc = statstore.KeyStats("old", "x").to_doc()
        doc.pop("profile", None)
        ks = statstore.KeyStats.from_doc(doc)
        assert ks.profile is None
        assert "profile" not in ks.to_doc()

    def test_drain_persists_and_adopts_baseline(self, session):
        config.dq_profile_enabled = True
        config.stats_enabled = True
        frame = Frame({"v": np.arange(128, dtype=np.float64)})
        _flush_chain(frame)
        rep = dqprof.report()
        cols = [c["column"] for c in rep["columns"]]
        assert cols
        persisted = statstore.STORE.profile(f"dqprof|{cols[0]}")
        assert persisted is not None
        assert persisted["version"] == dqprof.PROFILE_VERSION
        # a fresh observatory adopts the persisted snapshot as baseline
        # instead of re-learning one ("first" mode, snapshot present)
        dqprof.clear()
        before = profiling.counters.get("dq.baseline_pinned")
        _flush_chain(frame)
        rep2 = dqprof.report()
        row = next(c for c in rep2["columns"]
                   if c["column"] == cols[0])
        assert row["baseline_count"] == persisted["count"]
        assert profiling.counters.get("dq.baseline_pinned") > before

    def test_baseline_mode_off_disables_drift(self, session):
        config.dq_profile_enabled = True
        config.dq_baseline_mode = "off"
        frame = Frame({"v": np.arange(128, dtype=np.float64)})
        _flush_chain(frame)
        rep = dqprof.report()
        assert rep["columns"]
        assert all(c["drift"] is None for c in rep["columns"])
        assert profiling.counters.get("dq.baseline_pinned") == 0


# ---------------------------------------------------------------------------
# Drift: threshold flip → gauge + incident bundle + tail keep-reason
# ---------------------------------------------------------------------------


class TestDrift:
    def test_psi_zero_on_identical_and_positive_on_shift(self):
        base = _profile_of(np.random.default_rng(1).normal(size=500))
        assert dqprof.drift_score(base, base) == pytest.approx(0.0)
        shifted = _profile_of(
            np.random.default_rng(1).normal(size=500) * 100.0 + 500.0)
        score = dqprof.drift_score(base, shifted)
        assert score is not None and score > 1.0
        assert dqprof.drift_score(None, base) is None
        assert dqprof.drift_score(base, dqprof.ColumnProfile()) is None

    def test_threshold_flip_sets_gauge_incident_and_tail_keep(
            self, session):
        config.dq_profile_enabled = True
        config.dq_drift_threshold = 0.25
        obs.enable()
        obs.TAIL.configure(ring_size=8, retained_size=8)
        incidents.RECORDER.configure(enabled=True, cooldown_s=0.0)
        frame = Frame({"v": np.arange(256, dtype=np.float64)})
        _flush_chain(frame)
        dqprof.report()                       # pins the baseline
        assert profiling.counters.get("dq.drift_breach") == 0
        shifted = Frame(
            {"v": np.arange(256, dtype=np.float64) * 500.0 + 1e4})
        ctx = obs.TraceContext.mint()
        with obs.request_span("serve.query", ctx, tenant="t"):
            _flush_chain(shifted)
            rep = dqprof.report()             # drains inside the span
        drifted = [c for c in rep["columns"]
                   if c["drift"] is not None
                   and c["drift"] > config.dq_drift_threshold]
        assert drifted, "distribution shift must score past threshold"
        col = drifted[0]["column"]
        assert obs.METRICS.get_gauge(f"dq.drift.{col}") == \
            pytest.approx(drifted[0]["drift"])
        assert profiling.counters.get("dq.drift_breach") >= 1
        # the incident bundle carries the before/after profiles
        bundles = [b for b in incidents.RECORDER.list()
                   if b["trigger"] == "dq_drift"]
        assert bundles
        bundle = incidents.RECORDER.get(bundles[-1]["id"])
        assert bundle["dq_drift"]["column"] in [c["column"]
                                                for c in drifted]
        assert bundle["dq_drift"]["score"] > 0.25
        assert bundle["dq_drift"]["baseline"]["count"] > 0
        assert bundle["dq_drift"]["current"]["count"] > 0
        assert bundle["dq"]["enabled"] is True
        # the span annotation promotes the tree in the tail sampler
        obs.TAIL.finish_request(ctx, status="ok", reason="",
                                e2e_ms=1.0, breaker_opened=False,
                                slo_ms=None)
        doc = obs.TAIL.lookup(ctx.trace_id)[0]
        assert doc["kept"] and "dq_drift" in doc["keep_reasons"]

    def test_no_breach_below_threshold(self, session):
        config.dq_profile_enabled = True
        config.dq_drift_threshold = 0.25
        frame = Frame({"v": np.arange(256, dtype=np.float64)})
        _flush_chain(frame)
        dqprof.report()
        _flush_chain(frame)                   # identical distribution
        rep = dqprof.report()
        assert profiling.counters.get("dq.drift_breach") == 0
        assert all((c["drift"] or 0.0) <= 0.25 for c in rep["columns"])


# ---------------------------------------------------------------------------
# Rule violation accounting (eager UDF path + report + spike incident)
# ---------------------------------------------------------------------------


class TestRuleAccounting:
    def test_eager_udf_evals_accounted(self, session):
        config.dq_profile_enabled = True
        dq.register_builtin_rules()
        price = np.where(np.arange(40) % 4 == 0, 5.0, 50.0)
        f = Frame({"price": price.astype(np.float64)})
        f = f.with_column("pnm", dq.call_udf("minimumPriceRule",
                                             dq.col("price")))
        f.count()
        rep = dqprof.report()
        row = next(r for r in rep["rules"]
                   if r["rule"] == "minimumPriceRule")
        # the eager fallback may evaluate the column more than once;
        # the tallies scale together and the RATE stays exact
        evals = row["evals"]
        assert evals >= 1
        assert row["rows"] == 40 * evals
        assert row["violations"] == 10 * evals
        assert row["rate"] == pytest.approx(0.25)
        assert profiling.counters.get(
            "dq.violations.minimumPriceRule") == 10 * evals
        assert obs.METRICS.get_gauge(
            "dq.violation_rate.minimumPriceRule") == pytest.approx(0.25)

    def test_violation_spike_captures_incident(self, session):
        config.dq_profile_enabled = True
        obs.enable()
        incidents.RECORDER.configure(enabled=True, cooldown_s=0.0)
        dq.register_builtin_rules()
        bad = Frame({"price": np.full(32, 1.0)})   # all under the floor
        bad = bad.with_column("pnm", dq.call_udf("minimumPriceRule",
                                                 dq.col("price")))
        bad.count()
        before = profiling.counters.get("dq.violation_spike")
        dqprof.report()
        assert profiling.counters.get("dq.violation_spike") == before + 1
        bundles = [b for b in incidents.RECORDER.list()
                   if b["trigger"] == "dq_violations"]
        assert bundles
        bundle = incidents.RECORDER.get(bundles[-1]["id"])
        assert bundle["dq_violations"]["rule"] == "minimumPriceRule"
        assert bundle["dq_violations"]["rate"] == pytest.approx(1.0)

    def test_trace_time_evals_not_enqueued(self, session):
        config.dq_profile_enabled = True
        with dqprof._LOCK:
            n0 = len(dqprof._PENDING)

        def traced(x):
            # a tracer inside a jit body must never enqueue — the
            # compiled replay would double-count every execution
            dqprof.record_eval("someRule", x)
            return x

        jax.block_until_ready(jax.jit(traced)(jax.numpy.arange(4.0)))
        with dqprof._LOCK:
            assert len(dqprof._PENDING) == n0


# ---------------------------------------------------------------------------
# Fault ladder: dq_profile degrades the flush to unprofiled, never down
# ---------------------------------------------------------------------------


class TestFaultLadder:
    def test_dq_profile_site_registered(self):
        assert "dq_profile" in faults.FAULT_SITES
        assert "device_error" in faults.FAULT_SITES["dq_profile"]

    def test_injected_fault_degrades_to_unprofiled(self, session):
        config.dq_profile_enabled = True
        frame = Frame({"v": np.arange(512, dtype=np.float64)})
        _flush_chain(frame)                   # warm plans + sketches
        dqprof.clear()
        RECOVERY_LOG.clear()
        before = profiling.counters.get("dq.profile_failed")
        with faults.inject_faults("dq_profile:device_error:p=1.0"):
            assert _flush_chain(frame) > 0    # the flush itself survives
        assert profiling.counters.get("dq.profile_failed") > before
        events = RECOVERY_LOG.events(site="dq_profile")
        assert events and events[-1].action == "fallback"
        assert events[-1].rung == "unprofiled"
        # degraded flushes contributed nothing; the observatory is
        # coherent, not corrupt — and chaos ending resumes profiling
        assert dqprof.report()["size"] == 0
        _flush_chain(frame)
        assert dqprof.report()["size"] > 0

    def test_report_survives_faults(self, session):
        config.dq_profile_enabled = True
        frame = Frame({"v": np.arange(128, dtype=np.float64)})
        with faults.inject_faults("dq_profile:device_error:p=1.0"):
            _flush_chain(frame)
            rep = dqprof.report()
        assert rep["enabled"] is True
        assert isinstance(rep["columns"], list)


# ---------------------------------------------------------------------------
# /dq HTTP route
# ---------------------------------------------------------------------------


class TestDqRoute:
    def test_dq_route_schema(self, session):
        config.dq_profile_enabled = True
        dq.register_builtin_rules()
        f = Frame({"price": np.arange(64, dtype=np.float64) + 20.0})
        f = f.with_column("pnm", dq.call_udf("minimumPriceRule",
                                             dq.col("price")))
        f.count()
        _flush_chain(Frame({"v": np.arange(128, dtype=np.float64)}))
        with TelemetryServer(port=0) as ts:
            code, body = _get(f"http://127.0.0.1:{ts.port}/dq?top=4")
        assert code == 200
        doc = json.loads(body)
        for key in ("enabled", "columns", "rules", "size", "pending",
                    "bins", "drift_threshold", "baseline_mode"):
            assert key in doc, key
        assert doc["enabled"] is True
        assert doc["rules"] and doc["columns"]
        col = doc["columns"][0]
        for key in ("column", "count", "nulls", "mean", "min", "max",
                    "hist", "drift", "baseline_count", "version"):
            assert key in col, key
        rule = doc["rules"][0]
        for key in ("rule", "evals", "rows", "violations", "rate"):
            assert key in rule, key

    def test_dq_route_disabled_pin(self, monkeypatch):
        config.dq_profile_enabled = False

        def _raise(*a, **kw):
            raise AssertionError("dq report ran in disabled mode")

        monkeypatch.setattr(dqprof, "report", _raise)
        with TelemetryServer(port=0) as ts:
            code, body = _get(f"http://127.0.0.1:{ts.port}/dq")
        assert code == 200
        assert json.loads(body) == {"enabled": False, "columns": [],
                                    "rules": []}

    def test_dq_route_in_404_listing(self):
        with TelemetryServer(port=0) as ts:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(f"http://127.0.0.1:{ts.port}/nope")
            assert exc.value.code == 404
            routes = json.loads(exc.value.read().decode())["routes"]
            assert "/dq" in routes


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE section + headline goldens
# ---------------------------------------------------------------------------


#: A rule-bearing replay against the view ``run_dq_pipeline`` leaves
#: registered — the UDF call sits IN the statement so execution
#: re-evaluates the rule (a materialized view column would not).
HEADLINE_RULE_SQL = (
    "SELECT guest, priceCorrelationRule(price, guest) AS pcc "
    "FROM price WHERE priceCorrelationRule(price, guest) > 0")


class TestExplainSection:
    def test_headline_analyze_renders_dq_section_goldens_unchanged(
            self, session):
        config.dq_profile_enabled = True
        df = run_dq_pipeline(session, dataset_path("abstract"))
        assert df.count() == 24                       # golden
        plan = session.sql("EXPLAIN ANALYZE " + HEADLINE_RULE_SQL) \
            .to_pydict()["plan"][0]
        assert "== Data Quality ==" in plan
        assert "rule priceCorrelationRule:" in plan
        assert "violations=" in plan and "rate=" in plan
        # golden model numbers stay exact with the observatory on
        from sparkdq4ml_tpu.models import LinearRegression

        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(
            prepare_features(df))
        assert float(model.summary.root_mean_squared_error) == \
            pytest.approx(2.809940, rel=1e-3)

    def test_rule_free_analyze_has_no_section(self, session):
        config.dq_profile_enabled = True
        f = Frame({"v": np.arange(256, dtype=np.float64)})
        f.create_or_replace_temp_view("dqp_plain")
        plan = session.sql(
            "EXPLAIN ANALYZE SELECT v * 2 AS w FROM dqp_plain "
            "WHERE v > 5").to_pydict()["plan"][0]
        assert "== Data Quality ==" not in plan

    def test_disabled_mode_pins_analyze_byte_identical(
            self, session, monkeypatch):
        dq.register_builtin_rules()
        f = Frame({"price": np.arange(64, dtype=np.float64) + 20.0})
        f.create_or_replace_temp_view("dqp_off")
        sql = ("EXPLAIN ANALYZE SELECT minimumPriceRule(price) AS p "
               "FROM dqp_off WHERE minimumPriceRule(price) > 0")
        config.dq_profile_enabled = True
        session.sql(sql)                      # warm plans either way
        config.dq_profile_enabled = False

        def _raise(*a, **kw):
            raise AssertionError("dq EXPLAIN hook ran in disabled mode")

        monkeypatch.setattr(dqprof, "rule_marks", _raise)
        monkeypatch.setattr(dqprof, "explain_lines", _raise)
        plan_off = session.sql(sql).to_pydict()["plan"][0]
        assert "== Data Quality ==" not in plan_off
        monkeypatch.undo()
        config.dq_profile_enabled = True
        plan_on = session.sql(sql).to_pydict()["plan"][0]
        assert "== Data Quality ==" in plan_on    # flag flips it back

    def test_plain_explain_untouched(self, session):
        config.dq_profile_enabled = True
        f = Frame({"v": np.arange(64, dtype=np.float64)})
        f.create_or_replace_temp_view("dqp_ex")
        plan = session.sql(
            "EXPLAIN SELECT v FROM dqp_ex").to_pydict()["plan"][0]
        assert "Data Quality" not in plan
