"""Statistics: Frame.stat (corr/cov/quantiles/crosstab/freqItems) and
ml.stat Correlation/Summarizer. Oracle: numpy/scipy on the same valid rows;
reference-data fixture: guest↔price correlation on the DQ-cleaned datasets
(the quantity the reference's second rule is written around)."""

import jax.numpy as jnp
import numpy as np
import pytest

from sparkdq4ml_tpu.frame import Frame
from sparkdq4ml_tpu.models import Correlation, Summarizer, VectorAssembler
from sparkdq4ml_tpu.models.stat import summary

from conftest import dataset_path, run_dq_pipeline


@pytest.fixture
def xy():
    rng = np.random.default_rng(5)
    x = rng.normal(size=60)
    y = 2.0 * x + rng.normal(scale=0.5, size=60)
    z = rng.normal(size=60)
    return Frame({"x": x, "y": y, "z": z}), x, y, z


class TestFrameStat:
    def test_pearson_corr_matches_numpy(self, xy):
        f, x, y, _ = xy
        got = f.stat.corr("x", "y")
        np.testing.assert_allclose(got, np.corrcoef(x, y)[0, 1], rtol=1e-9)

    def test_cov_matches_numpy(self, xy):
        f, x, y, _ = xy
        np.testing.assert_allclose(f.stat.cov("x", "y"),
                                   np.cov(x, y, ddof=1)[0, 1], rtol=1e-9)

    def test_spearman(self, xy):
        import scipy.stats

        f, x, y, _ = xy
        np.testing.assert_allclose(f.stat.corr("x", "y", "spearman"),
                                   scipy.stats.spearmanr(x, y).statistic,
                                   rtol=1e-9)

    def test_mask_respected(self, xy):
        f, x, y, _ = xy
        g = f.filter(f["x"] > 0)
        keep = x > 0
        np.testing.assert_allclose(g.stat.corr("x", "y"),
                                   np.corrcoef(x[keep], y[keep])[0, 1],
                                   rtol=1e-9)

    def test_dq_pipeline_correlation(self, session):
        """After DQ cleaning, guest and price are near-perfectly correlated
        (the linear pattern price ≈ 5·guest + 20, SURVEY.md §2.1)."""
        df = run_dq_pipeline(session, dataset_path("full"))
        c = df.stat.corr("guest", "price")
        assert c > 0.999

    def test_approx_quantile(self, xy):
        f, x, *_ = xy
        qs = f.stat.approx_quantile("x", [0.0, 0.5, 1.0])
        assert qs[0] == pytest.approx(float(np.min(x)))
        assert qs[2] == pytest.approx(float(np.max(x)))
        assert abs(qs[1] - float(np.median(x))) < 0.2

    def test_crosstab(self):
        f = Frame({"a": ["x", "x", "y"], "b": ["1", "2", "1"]})
        ct = f.stat.crosstab("a", "b").to_pydict()
        assert list(ct["a_b"]) == ["x", "y"]
        assert list(ct["1"]) == [1, 1]
        assert list(ct["2"]) == [1, 0]

    def test_freq_items(self):
        f = Frame({"a": ["x"] * 9 + ["y"]})
        out = f.stat.freq_items(["a"], support=0.5).to_pydict()
        assert out["a_freqItems"][0] == ["x"]


class TestMlStat:
    def test_correlation_matrix(self, xy):
        f, x, y, z = xy
        f = VectorAssembler(["x", "y", "z"], "features").transform(f)
        got = Correlation.corr(f, "features")
        expect = np.corrcoef(np.stack([x, y, z]))
        np.testing.assert_allclose(got, expect, rtol=1e-8, atol=1e-10)

    def test_correlation_spearman(self, xy):
        import scipy.stats

        f, x, y, z = xy
        f = VectorAssembler(["x", "y", "z"], "features").transform(f)
        got = Correlation.corr(f, "features", method="spearman")
        expect = scipy.stats.spearmanr(np.stack([x, y, z], axis=1)).statistic
        np.testing.assert_allclose(got, expect, rtol=1e-8)

    def test_constant_feature_nan_off_diagonal(self):
        f = Frame({"a": [1.0, 1.0, 1.0], "b": [1.0, 2.0, 3.0]})
        f = VectorAssembler(["a", "b"], "features").transform(f)
        got = Correlation.corr(f, "features")
        assert np.isnan(got[0, 1])
        assert got[0, 0] == 1.0 and got[1, 1] == 1.0

    def test_summarizer(self, xy):
        f, x, y, z = xy
        f = VectorAssembler(["x", "y", "z"], "features").transform(f)
        s = summary(f, "features")
        X = np.stack([x, y, z], axis=1)
        assert s["count"] == 60
        np.testing.assert_allclose(s["mean"], X.mean(axis=0), rtol=1e-9)
        np.testing.assert_allclose(s["variance"], X.var(axis=0, ddof=1),
                                   rtol=1e-8)
        np.testing.assert_allclose(s["min"], X.min(axis=0), rtol=1e-9)
        np.testing.assert_allclose(s["max"], X.max(axis=0), rtol=1e-9)
        np.testing.assert_allclose(s["normL2"],
                                   np.sqrt((X ** 2).sum(axis=0)), rtol=1e-9)

    def test_summarizer_metric_selection(self, xy):
        f, *_ = xy
        f = VectorAssembler(["x"], "features").transform(f)
        s = Summarizer.metrics("mean", "count").summary(f, "features")
        assert set(s) == {"mean", "count"}
        with pytest.raises(ValueError, match="unknown metrics"):
            Summarizer.metrics("median")


class TestChiSquareTest:
    def test_scipy_parity(self):
        from scipy import stats as sstats

        from sparkdq4ml_tpu.models import ChiSquareTest

        rng = np.random.default_rng(0)
        n = 500
        # feature 0 depends on the label; feature 1 is independent
        y = rng.integers(0, 3, size=n).astype(float)
        x0 = ((y + rng.integers(0, 2, size=n)) % 4).astype(float)
        x1 = rng.integers(0, 5, size=n).astype(float)
        X = np.stack([x0, x1], axis=1)
        f = Frame({"features": X, "label": y})
        out = ChiSquareTest.test(f, "features", "label").to_pydict()
        pv = out["pValues"][0]
        st = out["statistics"][0]
        dof = out["degreesOfFreedom"][0]
        for j, xj in enumerate([x0, x1]):
            table = np.zeros((int(xj.max()) + 1, 3))
            for a, b in zip(xj.astype(int), y.astype(int)):
                table[a, b] += 1
            table = table[table.sum(1) > 0][:, table.sum(0) > 0]
            ref = sstats.chi2_contingency(table, correction=False)
            assert st[j] == pytest.approx(ref.statistic, rel=1e-9)
            assert pv[j] == pytest.approx(ref.pvalue, abs=1e-12)
            assert dof[j] == ref.dof
        # dependent feature rejects, independent doesn't
        assert pv[0] < 1e-6
        assert pv[1] > 0.01

    def test_respects_mask(self):
        from sparkdq4ml_tpu.models import ChiSquareTest

        y = np.asarray([0, 0, 1, 1, 0, 1] * 20, float)
        x = np.asarray([0, 1, 0, 1, 0, 1] * 20, float)
        f = Frame({"features": x[:, None], "label": y})
        keep = np.arange(len(y)) % 3 != 0
        fm = f.filter(jnp.asarray(keep))
        out = ChiSquareTest.test(fm, "features", "label").to_pydict()
        from scipy import stats as sstats
        table = np.zeros((2, 2))
        for a, b in zip(x[keep].astype(int), y[keep].astype(int)):
            table[a, b] += 1
        ref = sstats.chi2_contingency(table, correction=False)
        assert out["statistics"][0][0] == pytest.approx(ref.statistic,
                                                        rel=1e-9)

    def test_rejects_continuous_features(self):
        from sparkdq4ml_tpu.models import ChiSquareTest

        f = Frame({"features": np.asarray([[0.5], [1.2]]),
                   "label": np.asarray([0.0, 1.0])})
        with pytest.raises(ValueError, match="categorical"):
            ChiSquareTest.test(f)


class TestKolmogorovSmirnovTest:
    def test_scipy_parity_norm(self):
        from scipy import stats as sstats

        from sparkdq4ml_tpu.models import KolmogorovSmirnovTest

        rng = np.random.default_rng(0)
        x = rng.normal(size=400)
        f = Frame({"x": x})
        out = KolmogorovSmirnovTest.test(f, "x", "norm", 0.0, 1.0).to_pydict()
        ref = sstats.kstest(x, "norm", args=(0.0, 1.0), mode="asymp")
        assert out["statistic"][0] == pytest.approx(ref.statistic, rel=1e-9)
        assert out["pValue"][0] == pytest.approx(ref.pvalue, abs=1e-6)

    def test_shifted_sample_rejected(self):
        from sparkdq4ml_tpu.models import KolmogorovSmirnovTest

        rng = np.random.default_rng(1)
        x = rng.normal(loc=1.0, size=300)
        f = Frame({"x": x})
        out = KolmogorovSmirnovTest.test(f, "x", "norm").to_pydict()
        assert out["pValue"][0] < 1e-6
        out2 = KolmogorovSmirnovTest.test(f, "x", "norm", 1.0, 1.0).to_pydict()
        assert out2["pValue"][0] > 1e-3    # this draw sits at p≈0.007

    def test_respects_mask_and_default_params(self):
        from sparkdq4ml_tpu.models import KolmogorovSmirnovTest
        from scipy import stats as sstats

        rng = np.random.default_rng(2)
        x = rng.normal(size=300)
        x[::5] = 1e3                      # masked out below
        keep = np.arange(300) % 5 != 0
        f = Frame({"x": x}).filter(jnp.asarray(keep))
        out = KolmogorovSmirnovTest.test(f, "x").to_pydict()
        ref = sstats.kstest(x[keep], "norm", mode="asymp")
        assert out["statistic"][0] == pytest.approx(ref.statistic, rel=1e-9)


class TestSummarizerWeightCol:
    def test_weighted_matches_repetition(self):
        from sparkdq4ml_tpu.models.stat import Summarizer
        rng = np.random.default_rng(2)
        n, d = 30, 4
        X = rng.normal(size=(n, d))
        w = rng.integers(1, 4, size=n).astype(np.float64)
        fw = Frame({"features": X, "w": w})
        idx = np.repeat(np.arange(n), w.astype(int))
        fr = Frame({"features": X[idx]})
        s = Summarizer(Summarizer.METRICS)
        a = s.summary(fw, weight_col="w")
        b = s.summary(fr)
        np.testing.assert_allclose(a["mean"], b["mean"], rtol=1e-9)
        np.testing.assert_allclose(a["variance"], b["variance"], rtol=1e-9)
        np.testing.assert_allclose(a["normL1"], b["normL1"], rtol=1e-9)
        np.testing.assert_allclose(a["normL2"], b["normL2"], rtol=1e-9)
        np.testing.assert_allclose(a["min"], b["min"])
        np.testing.assert_allclose(a["max"], b["max"])
        assert a["count"] == n            # weight-positive ROWS, unweighted

    def test_weighted_mesh_matches_single(self):
        from sparkdq4ml_tpu.models.stat import Summarizer
        from sparkdq4ml_tpu.parallel.mesh import make_mesh
        rng = np.random.default_rng(3)
        X = rng.normal(size=(25, 3))
        w = rng.uniform(0.5, 2.0, size=25)
        f = Frame({"features": X, "w": w})
        s = Summarizer(Summarizer.METRICS)
        a = s.summary(f, weight_col="w")
        b = s.summary(f, mesh=make_mesh(8), weight_col="w")
        for k in ("mean", "variance", "normL1", "normL2", "min", "max"):
            np.testing.assert_allclose(a[k], b[k], rtol=1e-8)

    def test_negative_weight_rejected(self):
        from sparkdq4ml_tpu.models.stat import Summarizer
        f = Frame({"features": np.asarray([[1.0], [2.0]]),
                   "w": np.asarray([1.0, -2.0])})
        with pytest.raises(ValueError, match="nonnegative"):
            Summarizer(("mean",)).summary(f, weight_col="w")
