"""dqaudit — jaxpr-level program auditor (ISSUE 9).

Every detector is proven LIVE by a seeded offender (through the
``scripts/check_static.py --tier program`` CLI, which must exit 1),
proven QUIET on healthy programs, and the whole tier is proven clean on
the real tree through a fresh-process CLI run over the headline
workload. The accuracy pin asserts the static peak bound brackets the
measured peak on the headline DQ query within a documented slack
factor; the hot-path pin asserts the audit package is never imported by
the default query path.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

import sparkdq4ml_tpu as dq  # noqa: E402
from sparkdq4ml_tpu.analysis.program import (audit_programs,  # noqa: E402
                                             get_detectors)
from sparkdq4ml_tpu.analysis.program import jaxpr_tools as JT  # noqa: E402
from sparkdq4ml_tpu.analysis.program.detectors import \
    AuditContext  # noqa: E402
from sparkdq4ml_tpu.config import config  # noqa: E402
from sparkdq4ml_tpu.frame.frame import Frame  # noqa: E402
from sparkdq4ml_tpu.utils import observability as obs  # noqa: E402
from sparkdq4ml_tpu.utils import profiling  # noqa: E402
from sparkdq4ml_tpu.utils.observability import ProgramHandle  # noqa: E402

from conftest import dataset_path  # noqa: E402

pytestmark = pytest.mark.program_audit

S = jax.ShapeDtypeStruct
SCRIPT = os.path.join(REPO, "scripts", "check_static.py")
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def _f32(*shape):
    return S(tuple(shape), np.float32)


def _handle(fn, *args, **kw):
    return ProgramHandle(kw.pop("cache", "test"),
                         kw.pop("program_key", "test-plan"), fn,
                         args=args, **kw)


# ---------------------------------------------------------------------------
# jaxpr tools: signature + static peak bound
# ---------------------------------------------------------------------------


class TestJaxprTools:
    def test_signature_stable_across_buckets(self):
        fn = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
        a = JT.structural_signature(JT.trace(fn, (_f32(8),)))
        b = JT.structural_signature(JT.trace(fn, (_f32(1024),)))
        assert a == b

    def test_signature_differs_on_structure(self):
        a = JT.structural_signature(JT.trace(lambda x: x + 1.0,
                                             (_f32(8),)))
        b = JT.structural_signature(JT.trace(lambda x: x * 2.0,
                                             (_f32(8),)))
        assert a != b

    def test_signature_sees_weak_type(self):
        f = lambda x, lit: x * lit  # noqa: E731
        weak = JT.structural_signature(JT.trace(f, (_f32(8), 2.0)))
        strong = JT.structural_signature(
            JT.trace(f, (_f32(8), np.float32(2.0))))
        assert weak != strong   # the aval weak flag is structural

    def test_peak_bytes_simple_program(self):
        # x:f32[8] in, one add out: 32 entry + 32 live at the eqn
        closed = JT.trace(lambda x: x + 1.0, (_f32(8),))
        assert JT.peak_bytes(closed) == 64

    def test_peak_bytes_liveness_frees_dead_operands(self):
        # a chain a->b->c->d of same-size ops: peak stays ~2 buffers,
        # far below the 4-buffer no-free upper bound
        def chain(x):
            a = x + 1.0
            b = a * 2.0
            c = b - 3.0
            return c / 4.0

        closed = JT.trace(chain, (_f32(1024),))
        peak = JT.peak_bytes(closed)
        assert 2 * 4096 <= peak <= 3 * 4096

    def test_peak_bytes_counts_captured_consts_once(self):
        big = np.arange(100, dtype=np.float32)          # 400 bytes
        closed = JT.trace(lambda x: x + jax.numpy.asarray(big),
                          (_f32(100),))
        # entry = input 400 + const 400; the add allocates 400 more —
        # 1200, NOT 1600 (constvars and closed.consts are the same
        # buffers and must not both be charged)
        assert JT.peak_bytes(closed) == 1200

    def test_peak_bytes_recurses_into_jitted_bodies(self):
        inner = jax.jit(lambda x: x @ x.T)
        closed = JT.trace(lambda x: inner(x).sum(), (_f32(64, 64),))
        assert JT.peak_bytes(closed) >= 2 * 64 * 64 * 4

    def test_collective_and_callback_scans(self):
        from jax.sharding import PartitionSpec as P

        from sparkdq4ml_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                                  shard_map)

        mesh = make_mesh(4)
        sm = shard_map(lambda x: jax.lax.psum(x.sum(), DATA_AXIS),
                       mesh=mesh, in_specs=(P(DATA_AXIS),),
                       out_specs=P())
        colls = JT.collective_eqns(JT.trace(sm, (_f32(8),)))
        assert colls == [("psum", (DATA_AXIS,))]

        def cb(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2, _f32(4), x)

        assert JT.callback_eqns(JT.trace(cb, (_f32(4),))) \
            == [("pure_callback", "")] or \
            JT.callback_eqns(JT.trace(cb, (_f32(4),)))[0][0] \
            == "pure_callback"


# ---------------------------------------------------------------------------
# detectors: seeded offender + sanctioned pair each
# ---------------------------------------------------------------------------


class TestStaticMemoryDetector:
    def test_over_budget_plan_flagged(self):
        h = _handle(lambda x: x @ x.T + 1.0, _f32(512, 512))
        res = audit_programs([h], ctx=AuditContext(device_budget=1 << 16))
        assert [f.rule for f in res.findings] == ["audit-memory"]
        assert "exceeds" in res.findings[0].message

    def test_fitting_plan_quiet_and_bound_recorded(self):
        h = _handle(lambda x: x + 1.0, _f32(8))
        ctx = AuditContext(device_budget=1 << 20)
        res = audit_programs([h], ctx=ctx)
        assert res.findings == []
        assert res.program_stats["test-plan"]["est_peak_bytes"] == 64

    def test_no_budget_on_cpu_is_advisory_only(self):
        # XLA:CPU exposes no allocator bytes_limit; with no explicit
        # budget the bound is computed but not gated
        h = _handle(lambda x: x @ x.T, _f32(256, 256))
        res = audit_programs([h], ctx=AuditContext(device_budget=0))
        assert res.findings == []
        assert res.program_stats["test-plan"]["est_peak_bytes"] > 0


class TestHiddenSyncDetector:
    def test_pure_callback_in_jitted_body_flagged(self):
        def prog(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2, _f32(4), x)

        res = audit_programs([_handle(prog, _f32(4))],
                             ctx=AuditContext())
        assert "audit-sync" in [f.rule for f in res.findings]
        assert "pure_callback" in res.findings[0].message

    def test_debug_print_flagged(self):
        def prog(x):
            jax.debug.print("x={x}", x=x)
            return x + 1.0

        res = audit_programs([_handle(prog, _f32(4))],
                             ctx=AuditContext())
        assert any(f.rule == "audit-sync" and "callback" in f.message
                   for f in res.findings)

    def test_large_const_capture_flagged_small_quiet(self):
        big = np.arange(4096, dtype=np.float32)     # 16 KiB
        res = audit_programs(
            [_handle(lambda x: x + jax.numpy.asarray(big), _f32(4096))],
            ctx=AuditContext(const_bytes=4096))
        assert any(f.rule == "audit-sync"
                   and "host constant capture" in f.message
                   for f in res.findings)
        res = audit_programs(
            [_handle(lambda x: x + jax.numpy.asarray(big), _f32(4096))],
            ctx=AuditContext(const_bytes=1 << 20))
        assert res.findings == []


class TestCollectiveTopologyDetector:
    def _psum_program(self, mesh):
        from jax.sharding import PartitionSpec as P

        from sparkdq4ml_tpu.parallel.mesh import DATA_AXIS, shard_map

        return shard_map(lambda x: jax.lax.psum(x.sum(), DATA_AXIS),
                         mesh=mesh, in_specs=(P(DATA_AXIS),),
                         out_specs=P())

    def test_unguarded_inner_psum_flagged(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(4)
        h = _handle(self._psum_program(mesh), _f32(8),
                    mesh=mesh, guarded=False)
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-collective"]
        assert "collective_guard" in res.findings[0].message

    def test_undeclared_guard_flagged_too(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(4)
        h = _handle(self._psum_program(mesh), _f32(8), mesh=mesh)
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-collective"]

    def test_axis_mismatch_flagged(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        data_mesh = make_mesh(4)
        wrong_mesh = make_mesh(4, axis_name="model")
        h = _handle(self._psum_program(data_mesh), _f32(8),
                    mesh=wrong_mesh, guarded=True)
        res = audit_programs([h], ctx=AuditContext())
        assert any("cannot bind" in f.message for f in res.findings)

    def test_guarded_program_quiet(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(4)
        h = _handle(self._psum_program(mesh), _f32(8),
                    mesh=mesh, guarded=True)
        res = audit_programs([h], ctx=AuditContext())
        assert res.findings == []

    def test_collective_free_program_ignores_guard_state(self):
        h = _handle(lambda x: x + 1.0, _f32(8), guarded=False)
        res = audit_programs([h], ctx=AuditContext())
        assert res.findings == []


class TestRetraceHazardDetector:
    def test_shape_specialized_plan_flagged(self):
        def shapey(x):
            return x * 2.0 if x.shape[0] > 8 else x + 1.0

        h = _handle(shapey, _f32(8),
                    variants={"bucket": ((_f32(16),), {})})
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-retrace"]
        assert "bucket" in res.findings[0].message

    def test_shape_specialization_between_fresh_variants_flagged(self):
        # the list form real producers declare: two FRESH traces
        # compared against each other (stale-trace-cache immune)
        def shapey(x):
            return x * 2.0 if x.shape[0] > 16 else x + 1.0

        h = _handle(shapey, _f32(8),
                    variants={"bucket": [((_f32(16),), {}),
                                         ((_f32(32),), {})]})
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-retrace"]
        assert "bucket" in res.findings[0].message

    def test_weak_type_leak_flagged(self):
        def weaky(x, lit):
            aval = getattr(lit, "aval", None)
            if aval is not None and aval.weak_type:
                return x + lit
            return x * 2.0

        h = _handle(weaky, _f32(8), 3.0,
                    variants={"weak": ((_f32(8), np.float32(3.0)), {})})
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-retrace"]

    def test_excess_observed_traces_flagged(self):
        h = _handle(lambda x: x + 1.0, _f32(8),
                    meta={"expected_traces": 2, "observed_traces": 5})
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-retrace"]
        assert "5 observed" in res.findings[0].message

    def test_literal_hoisting_regression_flagged(self):
        mk = lambda key: _handle(  # noqa: E731
            lambda x: x + 1.0, _f32(8), cache="pipeline",
            program_key=key,
            meta={"dedup_key": "f|F:B(>,C('p'),V(#))"})
        res = audit_programs(
            [mk("f|F:B(>,C('p'),V(3))"), mk("f|F:B(>,C('p'),V(4))")],
            ctx=AuditContext())
        rules = [f.rule for f in res.findings]
        assert rules == ["audit-retrace", "audit-retrace"]
        assert "literal" in res.findings[0].message

    def test_variant_trace_failure_is_a_finding(self):
        h = _handle(lambda x: x + 1.0, _f32(8),
                    variants={"bucket": ((_f32(16), _f32(2)), {})})
        res = audit_programs([h], ctx=AuditContext())
        assert [f.rule for f in res.findings] == ["audit-retrace"]
        assert "raised" in res.findings[0].message

    def test_stable_plan_quiet(self):
        h = _handle(lambda x: (x * 2.0).sum(), _f32(8),
                    variants={"bucket": ((_f32(16),), {})},
                    meta={"expected_traces": 2, "observed_traces": 2})
        res = audit_programs([h], ctx=AuditContext())
        assert res.findings == []


# ---------------------------------------------------------------------------
# driver: skip semantics, registry enumeration, zero counted syncs
# ---------------------------------------------------------------------------


class TestAuditDriver:
    def test_untraceable_handle_skips_not_fails(self):
        def broken(x):
            raise RuntimeError("no trace for you")

        res = audit_programs([_handle(broken, _f32(4)),
                              _handle(lambda x: x + 1.0, _f32(4))],
                             ctx=AuditContext())
        assert res.findings == []
        assert res.programs == 1
        assert len(res.skipped) == 1 and "no trace" in res.skipped[0][1]

    def test_enumerator_errors_surface(self):
        def bad_provider():
            raise RuntimeError("enumerator broke")

        obs.CACHES.register_programs("test.bad", bad_provider)
        try:
            res = audit_programs()
            assert "test.bad" in res.enum_errors
        finally:
            obs.CACHES.unregister("test.bad")

    def test_registry_enumerates_executed_plans(self, session):
        Frame({"a": [1.0, 2.0, 3.0, 4.0]}).create_or_replace_temp_view(
            "audit_t")
        session.sql("SELECT a * 3 AS b FROM audit_t WHERE a > 1"
                    ).to_pydict()
        handles, errors = obs.CACHES.programs()
        assert errors == {}
        pipe = [h for h in handles if h.cache == "pipeline"]
        assert pipe, "pipeline plan not enumerable"
        report = obs.cache_report()
        keys = {e["program_key"]
                for e in report["pipeline"]["entries"]}
        assert all(h.program_key in keys for h in pipe)
        # every enumerated handle re-traces abstractly
        for h in pipe:
            JT.trace(h.fn, h.args, h.kwargs)

    def test_audit_performs_zero_counted_syncs_and_compiles(self, session):
        Frame({"a": [1.0, 2.0, 3.0, 4.0]}).create_or_replace_temp_view(
            "audit_s")
        session.sql("SELECT a + 1 AS b FROM audit_s WHERE a > 2"
                    ).to_pydict()
        before = profiling.counters.snapshot()
        res = audit_programs()
        after = profiling.counters.snapshot()
        for key in ("frame.host_sync", "pipeline.compile",
                    "grouped.compile", "pipeline.flush"):
            assert after.get(key, 0) == before.get(key, 0), key
        assert res.programs >= 1

    def test_session_audit_report_shape_and_conf_gate(self, session):
        Frame({"a": [1.0, 2.0]}).create_or_replace_temp_view("audit_r")
        session.sql("SELECT a FROM audit_r WHERE a > 1").to_pydict()
        doc = session.audit_report()
        assert doc["enabled"] is True
        assert doc["clean"] in (True, False)
        assert set(doc["by_detector"]) == {
            "audit-memory", "audit-sync", "audit-collective",
            "audit-retrace"}
        config.audit_enabled = False
        try:
            off = session.audit_report()
            assert off == {"enabled": False, "clean": None,
                           "findings": [], "programs": 0}
        finally:
            config.audit_enabled = True

    def test_audit_conf_session_scoped(self):
        assert config.audit_memory_fraction == pytest.approx(0.9)
        s = dq.TpuSession.builder().app_name("audit-conf").master(
            "local[*]").config("spark.audit.memoryFraction", "0.5"
                               ).config("spark.audit.deviceBudget",
                                        str(1 << 20)
                                        ).config("spark.audit.constBytes",
                                                 "128").get_or_create()
        try:
            assert config.audit_memory_fraction == pytest.approx(0.5)
            assert config.audit_device_budget == 1 << 20
            assert config.audit_const_bytes == 128
        finally:
            s.stop()
        assert config.audit_memory_fraction == pytest.approx(0.9)
        assert config.audit_device_budget == 0
        assert config.audit_const_bytes == 4096


# ---------------------------------------------------------------------------
# producer coverage: grouped, solver, fit-factory handles
# ---------------------------------------------------------------------------


class TestProducerHandles:
    def test_grouped_plan_enumerable_and_stable(self, session):
        Frame({"k": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]}
              ).create_or_replace_temp_view("audit_g")
        session.sql("SELECT k, sum(v) s FROM audit_g GROUP BY k"
                    ).to_pydict()
        handles, _ = obs.CACHES.programs()
        grouped = [h for h in handles if h.cache == "grouped"]
        assert grouped
        h = grouped[-1]
        (v2, kw2), (v4, kw4) = h.variants["bucket"]
        assert JT.structural_signature(JT.trace(h.fn, v2, kw2)) \
            == JT.structural_signature(JT.trace(h.fn, v4, kw4))

    def test_solver_entry_enumerable(self):
        from sparkdq4ml_tpu.models import solvers

        A = jax.numpy.eye(4, dtype=jax.numpy.float64) * 3.0
        solvers.solve(A, 0.1, 0.0, max_iter=5, tol=1e-6,
                      fit_intercept=True, standardization=True,
                      solver="auto")
        handles, _ = obs.CACHES.programs()
        solver = [h for h in handles if h.cache == "solver"]
        assert solver
        res = audit_programs(solver, ctx=AuditContext())
        assert res.findings == [] and res.programs == len(solver)

    def test_fit_factory_enumerable_with_mesh_and_guard(self, session):
        from sparkdq4ml_tpu.models import (LinearRegression,
                                           VectorAssembler)

        df = Frame({"x": [float(i % 7) for i in range(32)],
                    "y": [float(i) for i in range(32)]})
        df = df.with_column("label", df.col("y"))
        df = VectorAssembler(["x"], "features").transform(df)
        LinearRegression(max_iter=5, reg_param=0.1,
                         elastic_net_param=1.0).fit(df, mesh=session.mesh)
        handles, _ = obs.CACHES.programs()
        fits = [h for h in handles if h.cache == "fit.factories"
                and h.mesh is not None]
        assert fits, "sharded fit handle missing"
        h = fits[-1]
        assert h.guarded is True
        colls = JT.collective_eqns(JT.trace(h.fn, h.args, h.kwargs))
        assert colls, "sharded fit traced without collectives"
        res = audit_programs(fits, ctx=AuditContext())
        assert res.findings == []

    def test_factory_memo_keeps_lru_surface(self):
        from sparkdq4ml_tpu.parallel import distributed

        info = distributed.fused_linear_fit_packed.cache_info()
        assert hasattr(info, "hits") and hasattr(info, "misses")
        assert distributed.fused_linear_fit_packed.entries() is not None


# ---------------------------------------------------------------------------
# EXPLAIN est peak (static, pre-execution)
# ---------------------------------------------------------------------------


class TestExplainEstPeak:
    def _view(self):
        Frame({"a": [1.0, 2.0, 3.0, 4.0], "k": [1, 1, 2, 2]}
              ).create_or_replace_temp_view("audit_e")

    def test_explain_renders_est_peak_zero_execution(self, session):
        self._view()
        before = profiling.counters.snapshot()
        out = session.sql(
            "EXPLAIN SELECT a, a * 2 AS b FROM audit_e WHERE a > 1")
        after = profiling.counters.snapshot()
        text = str(out.to_pydict()["plan"][0])
        assert "est_peak=" in text
        for line in text.splitlines()[1:]:
            if any(op in line for op in ("Scan", "FusedStage", "Sort")):
                assert "est_peak=" in line, line
        for key in ("pipeline.flush", "pipeline.compile",
                    "grouped.compile", "frame.host_sync"):
            assert after.get(key, 0) == before.get(key, 0), key

    def test_est_peak_monotone_up_the_chain(self, session):
        self._view()
        import re

        text = str(session.sql(
            "EXPLAIN SELECT a FROM audit_e WHERE a > 1 ORDER BY a"
        ).to_pydict()["plan"][0])
        peaks = [int(m) for m in re.findall(r"est_peak=(\d+)", text)]
        assert peaks == sorted(peaks, reverse=True)

    def test_budget_warning_line(self, session):
        self._view()
        config.audit_device_budget = 8    # absurd: everything overflows
        try:
            text = str(session.sql(
                "EXPLAIN SELECT a FROM audit_e WHERE a > 1"
            ).to_pydict()["plan"][0])
        finally:
            config.audit_device_budget = 0
        assert "!! est peak" in text
        assert "spark.audit.memoryFraction" in text

    def test_audit_disabled_removes_est_column(self, session):
        self._view()
        config.audit_enabled = False
        try:
            text = str(session.sql(
                "EXPLAIN SELECT a FROM audit_e WHERE a > 1"
            ).to_pydict()["plan"][0])
        finally:
            config.audit_enabled = True
        assert "est_peak" not in text

    def test_analyze_carries_both_est_and_measured(self, session):
        self._view()
        text = str(session.sql(
            "EXPLAIN ANALYZE SELECT a FROM audit_e WHERE a > 1"
        ).to_pydict()["plan"][0])
        line = next(ln for ln in text.splitlines()
                    if "FusedStage" in ln)
        assert "est_peak=" in line and "wall_ms=" in line


# ---------------------------------------------------------------------------
# the CLI gate: seeded offenders exit 1; fresh-process clean run
# ---------------------------------------------------------------------------


def _cli_with_offender(handle, detector) -> tuple:
    """Run the --tier program arm in-process with ``handle`` seeded into
    the registry; returns (exit_code, captured findings count)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("check_static", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    obs.CACHES.register_programs("test.offender", lambda: [handle])
    try:
        rc = mod.main(["--tier", "program", "--no-workload",
                       "--detectors", detector])
    finally:
        obs.CACHES.unregister("test.offender")
    return rc


class TestCheckStaticProgramTier:
    def test_memory_offender_exits_1(self, capsys):
        h = _handle(lambda x: x @ x.T, _f32(512, 512))
        config.audit_device_budget = 1 << 16
        try:
            rc = _cli_with_offender(h, "audit-memory")
        finally:
            config.audit_device_budget = 0
        assert rc == 1
        assert "audit-memory" in capsys.readouterr().out

    def test_sync_offender_exits_1(self, capsys):
        def prog(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2, _f32(4), x)

        assert _cli_with_offender(_handle(prog, _f32(4)),
                                  "audit-sync") == 1
        assert "pure_callback" in capsys.readouterr().out

    def test_collective_offender_exits_1(self, capsys):
        from jax.sharding import PartitionSpec as P

        from sparkdq4ml_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                                  shard_map)

        mesh = make_mesh(4)
        sm = shard_map(lambda x: jax.lax.psum(x.sum(), DATA_AXIS),
                       mesh=mesh, in_specs=(P(DATA_AXIS),),
                       out_specs=P())
        h = _handle(sm, _f32(8), mesh=mesh, guarded=False)
        assert _cli_with_offender(h, "audit-collective") == 1
        assert "collective_guard" in capsys.readouterr().out

    def test_retrace_offender_exits_1(self, capsys):
        def shapey(x):
            return x * 2.0 if x.shape[0] > 8 else x + 1.0

        h = _handle(shapey, _f32(8),
                    variants={"bucket": ((_f32(16),), {})})
        assert _cli_with_offender(h, "audit-retrace") == 1
        assert "audit-retrace" in capsys.readouterr().out

    def test_source_tier_preserves_program_baseline_entries(self, tmp_path):
        """A source-only --update-baseline must not erase grandfathered
        program-tier entries from the shared baseline file, and a run
        where the program tier did not run must not call them stale."""
        import importlib.util

        bl = tmp_path / "bl.json"
        bl.write_text(json.dumps({"entries": [
            {"rule": "audit-retrace", "path": "program:pipeline",
             "fingerprint": "some-plan-key"}]}))
        spec = importlib.util.spec_from_file_location("check_static",
                                                      SCRIPT)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main([REPO, "--tier", "source", "--baseline", str(bl),
                       "--update-baseline"])
        assert rc == 0
        doc = json.loads(bl.read_text())
        assert {"rule": "audit-retrace", "path": "program:pipeline",
                "fingerprint": "some-plan-key"} in doc["entries"]
        # and a plain source-tier run does not report it stale
        rc = mod.main([REPO, "--tier", "source", "--baseline", str(bl)])
        assert rc == 0

    def test_whole_tree_clean_through_cli(self):
        p = subprocess.run(
            [sys.executable, SCRIPT, "--tier", "program", "--json"],
            capture_output=True, text=True, timeout=420, env=_ENV,
            cwd=REPO)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        doc = json.loads(p.stdout[p.stdout.index("{"):])
        live = [f for f in doc["findings"] if not f["baselined"]]
        assert live == []
        assert doc["programs"] >= 4
        assert len(doc["detectors"]) == 4
        assert doc["workload"]["count"] == 24          # golden pin
        assert all("est_peak_bytes" in v
                   for v in doc["program_stats"].values())


# ---------------------------------------------------------------------------
# accuracy pin + hot-path isolation (fresh processes)
# ---------------------------------------------------------------------------

_ACCURACY_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.utils import meminfo

spark = dq.TpuSession.builder().app_name("pin").master(
    "local[*]").get_or_create()
dq.register_builtin_rules()
meminfo.reset_peak()
df = (spark.read.format("csv").option("inferSchema", "true")
      .option("header", "false").load({data!r}))
df = df.with_column_renamed("_c0", "guest")
df = df.with_column_renamed("_c1", "price")
df = df.with_column("price_no_min",
                    dq.call_udf("minimumPriceRule", dq.col("price")))
df.create_or_replace_temp_view("price")
est_text = spark.sql(
    "EXPLAIN SELECT cast(guest as int) guest, price_no_min AS price "
    "FROM price WHERE price_no_min > 0").to_pydict()["plan"][0]
import re
est = max(int(m) for m in re.findall(r"est_peak=(\d+)", est_text))
out = spark.sql(
    "SELECT cast(guest as int) guest, price_no_min AS price "
    "FROM price WHERE price_no_min > 0")
rows = out.to_pydict()
meminfo.sample()            # fold the live census into the peak tracker
measured = meminfo.peak_bytes()
assert measured > 0
# the static bound brackets the measured peak: >= (it is a bound) and
# within the documented CPU slack factor (the census counts every live
# array incl. the source frame; the bound assumes no aliasing)
SLACK = 64
assert est >= measured, (est, measured)
assert est <= SLACK * measured, (est, measured)
print("PIN_OK", est, measured)
"""

_HOTPATH_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.frame.frame import Frame

spark = dq.TpuSession.builder().app_name("hot").master(
    "local[*]").get_or_create()
Frame({{"a": [1.0, 2.0, 3.0, 4.0]}}).create_or_replace_temp_view("t")
spark.sql("SELECT a * 2 AS b FROM t WHERE a > 1").to_pydict()
spark.sql("SELECT a, count(*) c FROM t GROUP BY a").to_pydict()
spark.cache_report()
assert "sparkdq4ml_tpu.analysis" not in sys.modules, "analysis leaked"
assert "sparkdq4ml_tpu.analysis.program" not in sys.modules
spark.stop()
print("HOTPATH_OK")
"""


class TestOfflineContracts:
    def test_static_bound_brackets_measured_peak(self):
        code = _ACCURACY_SCRIPT.format(
            repo=REPO, data=dataset_path("abstract"))
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300,
                           env=_ENV, cwd=REPO)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        assert "PIN_OK" in p.stdout

    def test_audit_package_never_on_the_query_path(self):
        code = _HOTPATH_SCRIPT.format(repo=REPO)
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300,
                           env=_ENV, cwd=REPO)
        assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
        assert "HOTPATH_OK" in p.stdout
