"""Native C++ CSV engine: parity with the Python parser on the reference
fixtures, fallback behavior, and the ctypes contract. Skipped when
native/libdqcsv.so is not built (`make -C native`)."""

import subprocess
import sys

import numpy as np
import pytest

from conftest import dataset_path
from sparkdq4ml_tpu.frame import native_csv
from sparkdq4ml_tpu.frame.csv import read_csv

needs_native = pytest.mark.skipif(not native_csv.available(),
                                  reason="native/libdqcsv.so not built")


@needs_native
class TestNativeParity:
    @pytest.mark.parametrize("name,rows", [("abstract", 40), ("small", 27),
                                           ("full", 1040)])
    def test_reference_datasets_match_python_engine(self, name, rows):
        py = read_csv(dataset_path(name), engine="python")
        nat = read_csv(dataset_path(name), engine="native")
        assert nat.count() == py.count() == rows
        assert nat.columns == py.columns
        for col in py.columns:
            np.testing.assert_allclose(
                np.asarray(nat.to_pydict()[col], np.float64),
                np.asarray(py.to_pydict()[col], np.float64), rtol=1e-12)
        assert dict(nat.dtypes()) == dict(py.dtypes())

    def test_bare_cr_handled(self, tmp_path):
        p = tmp_path / "cr.csv"
        p.write_bytes(b"1,2.5\r3,4.5\r")
        df = read_csv(str(p), engine="native")
        assert df.count() == 2
        assert df.collect() == [(1, 2.5), (3, 4.5)]

    def test_empty_field_is_nan_and_promotes(self, tmp_path):
        p = tmp_path / "n.csv"
        p.write_bytes(b"1,2\n,3\n")
        df = read_csv(str(p), engine="native")
        d = df.to_pydict()
        assert np.isnan(d["_c0"][1])
        assert dict(df.dtypes())["_c0"] in ("double", "float")

    def test_non_numeric_falls_back_to_python(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_bytes(b"a,1\nb,2\n")
        df = read_csv(str(p), engine="auto")  # native returns -1 -> python
        assert dict(df.dtypes())["_c0"] == "string"
        assert df.count() == 2

    def test_native_engine_rejects_non_numeric_when_forced(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_bytes(b"a,1\n")
        # engine="native" means "use the native tokenizer when the content
        # allows"; non-numeric content degrades to the python parser rather
        # than failing the read.
        df = read_csv(str(p), engine="native")
        assert dict(df.dtypes())["_c0"] == "string"

    def test_float_parse_bit_identical_fuzz(self, tmp_path):
        """The Clinger fast path must be BIT-identical to Python's
        correctly-rounded float() — across magnitudes, precisions, exponent
        forms, and the fallback cases (>15 digits, huge exponents)."""
        rng = np.random.default_rng(99)
        vals = np.concatenate([
            rng.uniform(-1e3, 1e3, 200),
            rng.uniform(-1, 1, 200) * 10.0 ** rng.integers(-30, 30, 200),
            np.asarray([0.0, -0.0, 1e-308, 1e308, 123456789012345678.0,
                        0.1, 2.5, 1e22, 1e23, 1e-22, 1e-23]),
        ])
        # repr() gives shortest round-trip strings; also exercise fixed
        # long-mantissa renderings (forces the strtod fallback)
        lines = [repr(float(v)) for v in vals]
        lines += [f"{v:.20f}" for v in vals[:50]]
        path = tmp_path / "fuzz.csv"
        path.write_text("\n".join(lines) + "\n")
        nat = read_csv(str(path), engine="native")
        py = read_csv(str(path), engine="python")
        a = np.asarray(nat.to_pydict()["_c0"], np.float64)
        b = np.asarray(py.to_pydict()["_c0"], np.float64)
        assert a.shape == b.shape == (len(lines),)
        # bit-identical, not just close
        np.testing.assert_array_equal(a.view(np.int64), b.view(np.int64))

    def test_exponent_and_sign_forms(self, tmp_path):
        path = tmp_path / "forms.csv"
        path.write_text("1e3,+2.5,-0.125,3E-2\n"
                        "0001.5000,.5,5.,1e+0\n")
        nat = read_csv(str(path), engine="native")
        py = read_csv(str(path), engine="python")
        for col in py.columns:
            np.testing.assert_array_equal(
                np.asarray(nat.to_pydict()[col], np.float64),
                np.asarray(py.to_pydict()[col], np.float64))

    def test_parallel_chunk_path_matches_serial(self, tmp_path,
                                                monkeypatch):
        """DQCSV_THREADS forces the multi-chunk parse + parallel transpose
        even on a small file — chunk alignment, row0 offsets, short-row
        NaN padding, blank lines, and int flags must all match serial."""
        rng = np.random.default_rng(17)
        lines = []
        for i in range(997):   # odd count so chunks split unevenly
            if i % 101 == 0:
                lines.append("")                       # blank record
            if i % 97 == 0:
                lines.append(f"{i}")                   # short row -> NaN pad
            else:
                lines.append(f"{i},{rng.uniform(-5, 5):.6f},{i * 2}")
        path = tmp_path / "par.csv"
        path.write_text("\r\n".join(lines) + "\r\n")   # CRLF separators
        monkeypatch.delenv("DQCSV_THREADS", raising=False)
        serial = read_csv(str(path), engine="native")
        monkeypatch.setenv("DQCSV_THREADS", "5")
        par = read_csv(str(path), engine="native")
        assert par.count() == serial.count()
        assert par.columns == serial.columns
        assert dict(par.dtypes()) == dict(serial.dtypes())
        for col in serial.columns:
            np.testing.assert_array_equal(
                np.asarray(par.to_pydict()[col], np.float64),
                np.asarray(serial.to_pydict()[col], np.float64))

    def test_parallel_wide_row_rejected_in_any_chunk(self, tmp_path,
                                                     monkeypatch):
        lines = [f"{i},{i}" for i in range(300)]
        lines[250] = "1,2,3"                           # wide row, late chunk
        path = tmp_path / "wide.csv"
        path.write_text("\n".join(lines) + "\n")
        monkeypatch.setenv("DQCSV_THREADS", "4")
        # wide rows are a python-engine case: the native parser must
        # signal fallback (None), not mis-parse, from a worker chunk too
        assert native_csv.try_read_csv(str(path), header=False,
                                       infer_schema=True,
                                       delimiter=",") is None

    def test_missing_file(self):
        with pytest.raises(FileNotFoundError):
            read_csv("/nonexistent-file.csv", engine="native")

    def test_header_true_native_parity(self, tmp_path, monkeypatch):
        """header=True now rides the native tokenizer: names come from the
        header record host-side, the C side skips it (skip_header) and
        parses the numeric body — must match the python engine exactly."""
        p = tmp_path / "h.csv"
        p.write_text("guests,price\n10,12.50\n24,99.25\n3,5.00\n")
        py = read_csv(str(p), header=True, engine="python")
        nat = read_csv(str(p), header=True, engine="native")
        assert nat.columns == py.columns == ["guests", "price"]
        assert dict(nat.dtypes()) == dict(py.dtypes())
        for col in py.columns:
            np.testing.assert_array_equal(
                np.asarray(nat.to_pydict()[col], np.float64),
                np.asarray(py.to_pydict()[col], np.float64))
        monkeypatch.setenv("DQCSV_THREADS", "3")
        par = read_csv(str(p), header=True, engine="native")
        assert par.columns == py.columns
        assert par.collect() == nat.collect()

    def test_header_quoted_names_and_crlf(self, tmp_path):
        p = tmp_path / "hq.csv"
        p.write_bytes(b'"a,b",c\r\n1,2\r\n3,4\r\n')
        nat = read_csv(str(p), header=True, engine="native")
        py = read_csv(str(p), header=True, engine="python")
        assert nat.columns == py.columns == ["a,b", "c"]
        assert nat.collect() == py.collect() == [(1, 2), (3, 4)]

    def test_header_wider_than_body_falls_back(self, tmp_path):
        # ragged header vs body: python-engine semantics take over
        p = tmp_path / "rag.csv"
        p.write_text("a,b,c\n1,2\n")
        nat = read_csv(str(p), header=True, engine="native")
        py = read_csv(str(p), header=True, engine="python")
        assert nat.columns == py.columns
        assert nat.count() == py.count() == 1

    def test_header_unicode_blank_first_line_parity(self, tmp_path):
        # python's blank-record skip is str.strip() (drops a \x0b-only
        # line); the C prologue's is space/tab-only and would eat the
        # REAL header as its header record, returning an extra data row.
        # The wrapper must detect the disagreement and fall back.
        p = tmp_path / "vt.csv"
        p.write_bytes(b"\x0b\n1,2\n3,4\n")
        nat = read_csv(str(p), header=True, engine="native")
        py = read_csv(str(p), header=True, engine="python")
        assert nat.columns == py.columns
        assert nat.count() == py.count()
        assert nat.collect() == py.collect()

    def test_header_large_quoted_file_stays_native(self, tmp_path):
        # quotes in the probe window must not punt when the header record
        # provably ends inside it (unquoted terminator found): the C side
        # handles RFC-4180 fine, and large quoted exports are common
        # (pandas QUOTE_NONNUMERIC).
        from sparkdq4ml_tpu.frame.native_csv import _read_header_names

        p = tmp_path / "bigq.csv"
        lines = ['"a","b"'] + [f'"{i}","{i}.5"' for i in range(20000)]
        p.write_text("\n".join(lines) + "\n")
        assert p.stat().st_size > (1 << 16)
        assert _read_header_names(str(p), ",", '"') == ["a", "b"]
        nat = read_csv(str(p), header=True, engine="native")
        py = read_csv(str(p), header=True, engine="python")
        assert nat.columns == py.columns == ["a", "b"]
        assert nat.count() == py.count() == 20000

    def test_header_non_numeric_body_falls_back(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_text("name,x\nalice,1\nbob,2\n")
        df = read_csv(str(p), header=True, engine="native")
        assert df.columns == ["name", "x"]
        assert dict(df.dtypes())["name"] == "string"

    def test_trailing_delimiter_final_record_kept(self, tmp_path,
                                                  monkeypatch):
        # "...3," with no final newline: the implicit last field is empty,
        # and the half-written record must NOT be silently dropped —
        # python-engine parity (a plausible truncated-mid-write input).
        p = tmp_path / "t.csv"
        p.write_bytes(b"1,2\n3,")
        py = read_csv(str(p), engine="python")
        monkeypatch.delenv("DQCSV_THREADS", raising=False)
        nat = read_csv(str(p), engine="native")
        monkeypatch.setenv("DQCSV_THREADS", "3")
        par = read_csv(str(p), engine="native")
        assert nat.count() == par.count() == py.count() == 2
        for fr in (nat, par):
            d = fr.to_pydict()
            assert float(d["_c0"][1]) == 3.0
            assert np.isnan(float(d["_c1"][1]))

    @pytest.mark.parametrize("sep,trailing", [("\n", True), ("\n", False),
                                              ("\r\n", True), ("\r", True)])
    def test_bitmap_walk_messy_grid_fuzz(self, tmp_path, sep, trailing,
                                         monkeypatch):
        """Randomized messy-but-numeric grid through the bitmap walk
        (single-thread fast path): blank records, empty / whitespace-only
        fields, short rows, signs, exponents, >7-digit mantissas — across
        LF / CRLF / bare-CR separators with and without a final newline.
        Serial native must match the parallel-chunk engine cell for cell
        (both ultimately defined by parse_span semantics)."""
        rng = np.random.default_rng(23)
        cells = ["7", "4.25", "-3.5", "+0.125", "1e3", "2.5E-2", " 8 ",
                 "", "  ", "123456789.25", "98765432", ".5", "5.", "0"]
        lines = []
        for i in range(503):
            if i % 83 == 0:
                lines.append("")                          # blank record
            if i % 71 == 0:
                lines.append(str(rng.integers(0, 99)))    # short row
            else:
                lines.append(",".join(
                    cells[rng.integers(0, len(cells))] for _ in range(3)))
        text = sep.join(lines) + (sep if trailing else "")
        path = tmp_path / "messy.csv"
        path.write_bytes(text.encode())
        monkeypatch.delenv("DQCSV_THREADS", raising=False)
        serial = read_csv(str(path), engine="native")
        monkeypatch.setenv("DQCSV_THREADS", "4")
        par = read_csv(str(path), engine="native")
        py = read_csv(str(path), engine="python")
        assert serial.count() == par.count() == py.count()
        assert dict(serial.dtypes()) == dict(par.dtypes())
        for col in serial.columns:
            a = np.asarray(serial.to_pydict()[col], np.float64)
            b = np.asarray(par.to_pydict()[col], np.float64)
            np.testing.assert_array_equal(a.view(np.int64), b.view(np.int64))


def test_engine_native_unavailable_raises(monkeypatch):
    monkeypatch.setattr(native_csv, "_LIB", None)
    monkeypatch.setattr(native_csv, "_LIB_TRIED", True)
    with pytest.raises(RuntimeError):
        native_csv.try_read_csv("x.csv", header=False, infer_schema=True,
                                delimiter=",", required=True)


def test_python_engine_never_touches_native(monkeypatch):
    calls = []
    monkeypatch.setattr(native_csv, "try_read_csv",
                        lambda *a, **k: calls.append(1) or None)
    read_csv(dataset_path("small"), engine="python")
    assert calls == []
