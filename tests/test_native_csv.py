"""Native C++ CSV engine: parity with the Python parser on the reference
fixtures, fallback behavior, and the ctypes contract. Skipped when
native/libdqcsv.so is not built (`make -C native`)."""

import subprocess
import sys

import numpy as np
import pytest

from conftest import dataset_path
from sparkdq4ml_tpu.frame import native_csv
from sparkdq4ml_tpu.frame.csv import read_csv

needs_native = pytest.mark.skipif(not native_csv.available(),
                                  reason="native/libdqcsv.so not built")


@needs_native
class TestNativeParity:
    @pytest.mark.parametrize("name,rows", [("abstract", 40), ("small", 27),
                                           ("full", 1040)])
    def test_reference_datasets_match_python_engine(self, name, rows):
        py = read_csv(dataset_path(name), engine="python")
        nat = read_csv(dataset_path(name), engine="native")
        assert nat.count() == py.count() == rows
        assert nat.columns == py.columns
        for col in py.columns:
            np.testing.assert_allclose(
                np.asarray(nat.to_pydict()[col], np.float64),
                np.asarray(py.to_pydict()[col], np.float64), rtol=1e-12)
        assert dict(nat.dtypes()) == dict(py.dtypes())

    def test_bare_cr_handled(self, tmp_path):
        p = tmp_path / "cr.csv"
        p.write_bytes(b"1,2.5\r3,4.5\r")
        df = read_csv(str(p), engine="native")
        assert df.count() == 2
        assert df.collect() == [(1, 2.5), (3, 4.5)]

    def test_empty_field_is_nan_and_promotes(self, tmp_path):
        p = tmp_path / "n.csv"
        p.write_bytes(b"1,2\n,3\n")
        df = read_csv(str(p), engine="native")
        d = df.to_pydict()
        assert np.isnan(d["_c0"][1])
        assert dict(df.dtypes())["_c0"] in ("double", "float")

    def test_non_numeric_falls_back_to_python(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_bytes(b"a,1\nb,2\n")
        df = read_csv(str(p), engine="auto")  # native returns -1 -> python
        assert dict(df.dtypes())["_c0"] == "string"
        assert df.count() == 2

    def test_native_engine_rejects_non_numeric_when_forced(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_bytes(b"a,1\n")
        # engine="native" means "use the native tokenizer when the content
        # allows"; non-numeric content degrades to the python parser rather
        # than failing the read.
        df = read_csv(str(p), engine="native")
        assert dict(df.dtypes())["_c0"] == "string"

    def test_missing_file(self):
        with pytest.raises(FileNotFoundError):
            read_csv("/nonexistent-file.csv", engine="native")


def test_engine_native_unavailable_raises(monkeypatch):
    monkeypatch.setattr(native_csv, "_LIB", None)
    monkeypatch.setattr(native_csv, "_LIB_TRIED", True)
    with pytest.raises(RuntimeError):
        native_csv.try_read_csv("x.csv", header=False, infer_schema=True,
                                delimiter=",", required=True)


def test_python_engine_never_touches_native(monkeypatch):
    calls = []
    monkeypatch.setattr(native_csv, "try_read_csv",
                        lambda *a, **k: calls.append(1) or None)
    read_csv(dataset_path("small"), engine="python")
    assert calls == []
