"""unionByName, intersect/exceptAll/subtract, replace, withColumns, toDF,
summary — the Dataset API completeness batch."""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame, col


@pytest.fixture
def ab():
    return Frame({"a": [1.0, 2.0], "b": np.asarray(["x", "y"], dtype=object)})


class TestUnionByName:
    def test_reorders_columns(self, ab):
        other = Frame({"b": np.asarray(["z"], dtype=object), "a": [3.0]})
        out = ab.union_by_name(other)
        d = out.to_pydict()
        assert d["a"].tolist() == pytest.approx([1.0, 2.0, 3.0])
        assert d["b"].tolist() == ["x", "y", "z"]

    def test_mismatch_raises(self, ab):
        with pytest.raises(ValueError, match="column sets differ"):
            ab.union_by_name(Frame({"a": [1.0]}))

    def test_allow_missing_null_fills(self, ab):
        other = Frame({"a": [3.0], "c": [9.0]})
        out = ab.union_by_name(other, allow_missing_columns=True)
        d = out.to_pydict()
        assert d["a"].tolist() == pytest.approx([1.0, 2.0, 3.0])
        assert d["b"].tolist() == ["x", "y", None]
        assert np.isnan(d["c"][:2]).all() and d["c"][2] == pytest.approx(9.0)


class TestSetOps:
    def test_intersect(self):
        x = Frame({"v": [1.0, 2.0, 2.0, 3.0]})
        y = Frame({"v": [2.0, 3.0, 4.0]})
        assert sorted(r[0] for r in x.intersect(y).collect()) == [2.0, 3.0]

    def test_except_all_keeps_duplicates(self):
        x = Frame({"v": [1.0, 1.0, 1.0, 2.0]})
        y = Frame({"v": [1.0, 2.0]})
        assert sorted(r[0] for r in x.except_all(y).collect()) == [1.0, 1.0]

    def test_subtract_distinct(self):
        x = Frame({"v": [1.0, 1.0, 2.0, 3.0]})
        y = Frame({"v": [2.0]})
        assert sorted(r[0] for r in x.subtract(y).collect()) == [1.0, 3.0]

    def test_respects_mask(self):
        x = Frame({"v": [1.0, 2.0, 3.0]}).filter(col("v") < 3.0)
        y = Frame({"v": [1.0]})
        assert [r[0] for r in x.subtract(y).collect()] == [2.0]

    def test_null_safe(self):
        # Spark set ops are null-safe: NaN rows match each other
        nan = float("nan")
        f = Frame({"a": [1.0, nan]})
        got = [r[0] for r in f.intersect(f).collect()]
        assert len(got) == 2
        assert f.subtract(f).count() == 0
        assert f.except_all(f).count() == 0


class TestReplace:
    def test_scalar_numeric(self):
        f = Frame({"v": [1.0, 2.0, 1.0]})
        out = f.replace(1.0, 9.0).to_pydict()
        assert out["v"].tolist() == pytest.approx([9.0, 2.0, 9.0])

    def test_dict_and_strings(self):
        f = Frame({"s": np.asarray(["a", "b"], dtype=object),
                   "v": [1.0, 2.0]})
        out = f.replace({"a": "z", 2.0: 0.0}).to_pydict()
        assert out["s"].tolist() == ["z", "b"]
        assert out["v"].tolist() == pytest.approx([1.0, 0.0])

    def test_list_form_and_subset(self):
        f = Frame({"u": [1.0, 2.0], "v": [1.0, 2.0]})
        out = f.replace([1.0, 2.0], 0.0, subset=["u"]).to_pydict()
        assert out["u"].tolist() == pytest.approx([0.0, 0.0])
        assert out["v"].tolist() == pytest.approx([1.0, 2.0])

    def test_int_column_widens_for_float_replacement(self):
        f = Frame({"v": np.asarray([1, 2], np.int32)})
        out = f.replace(1, 0.5).to_pydict()
        assert out["v"].tolist() == pytest.approx([0.5, 2.0])

    def test_list_to_list_zips_pairwise(self):
        f = Frame({"v": [2.0, 1.0, 3.0]})
        out = f.replace([1.0, 2.0], [9.0, 8.0]).to_pydict()
        assert out["v"].tolist() == pytest.approx([8.0, 9.0, 3.0])
        with pytest.raises(ValueError, match="length"):
            f.replace([1.0, 2.0], [9.0])

    def test_replace_with_null(self):
        f = Frame({"v": [1.0, 2.0]})
        out = f.replace(2.0, None).to_pydict()
        assert out["v"][0] == pytest.approx(1.0) and np.isnan(out["v"][1])
        g = Frame({"v": np.asarray([1, 2], np.int32)})
        out2 = g.replace(2, None).to_pydict()
        assert np.isnan(out2["v"][1])  # int widens to float for the null


class TestMisc:
    def test_with_columns(self, ab):
        out = ab.with_columns({"c": col("a") * 2, "d": col("a") + 1})
        d = out.to_pydict()
        assert d["c"].tolist() == pytest.approx([2.0, 4.0])
        assert d["d"].tolist() == pytest.approx([2.0, 3.0])

    def test_with_columns_resolves_against_input(self):
        # Spark: every expr sees the ORIGINAL columns, not earlier entries
        f = Frame({"a": [1.0]})
        d = f.with_columns({"a": col("a") + 1, "b": col("a")}).to_pydict()
        assert d["a"].tolist() == pytest.approx([2.0])
        assert d["b"].tolist() == pytest.approx([1.0])

    def test_to_df(self, ab):
        out = ab.to_df("x", "y")
        assert out.columns == ["x", "y"]
        with pytest.raises(ValueError, match="expects 2"):
            ab.to_df("only_one")
        with pytest.raises(ValueError, match="unique"):
            ab.to_df("a", "a")

    def test_summary_percentiles(self):
        f = Frame({"v": [float(i) for i in range(1, 101)]})
        d = f.summary().to_pydict()
        row = {s: v for s, v in zip(d["summary"], d["v"])}
        assert float(row["50%"]) == pytest.approx(50.5)
        assert float(row["count"]) == 100
        assert float(row["max"]) == pytest.approx(100.0)

    def test_summary_custom_stats(self):
        f = Frame({"v": [1.0, 2.0, 3.0]})
        d = f.summary("min", "90%").to_pydict()
        assert d["summary"].tolist() == ["min", "90%"]


class TestDescribeStrings:
    def test_string_columns_described_like_spark(self):
        f = Frame({"s": np.asarray(["b", "a", None], dtype=object),
                   "x": np.asarray([1.0, 2.0, 3.0])})
        d = f.describe().to_pydict()
        assert "s" in d and "x" in d
        s = list(d["s"])
        assert s[0] == "2"                       # non-null count
        assert s[1] is None and s[2] is None     # mean/stddev null
        assert s[3] == "a" and s[4] == "b"       # lexicographic min/max

    def test_named_string_column(self):
        f = Frame({"s": np.asarray(["x"], dtype=object)})
        d = f.describe("s").to_pydict()
        assert list(d["s"])[0] == "1"


class TestDistinctNullSafety:
    def test_distinct_collapses_nan_rows(self):
        import math

        from sparkdq4ml_tpu import Frame
        f = Frame({"k": [math.nan, math.nan, 1.0]})
        assert f.distinct().count() == 2   # Spark: null rows equal

    def test_distinct_collapses_none_strings(self):
        import numpy as np

        from sparkdq4ml_tpu import Frame
        f = Frame({"s": np.asarray([None, None, "a"], object)})
        assert f.distinct().count() == 2

    def test_sql_distinct_null_safe(self, session):
        import math

        from sparkdq4ml_tpu import Frame
        Frame({"k": [math.nan, math.nan, 2.0]}) \
            .create_or_replace_temp_view("dn")
        assert session.sql("SELECT DISTINCT k FROM dn").count() == 2
        session.catalog.drop("dn")
