"""LogisticRegression: sklearn parity oracle, DQ-pipeline integration
(BASELINE.json config d), distributed equality, API surface."""

import numpy as np
import pytest

from conftest import dataset_path, run_dq_pipeline
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (LogisticRegression, LogisticRegressionModel,
                                   VectorAssembler)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def _synth(n=300, d=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    w = np.asarray([1.5, -2.0, 0.8])[:d]
    logits = X @ w + 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
    f = Frame({"features": X, "label": y})
    return f, X, y


class TestSklearnParity:
    def test_unregularized_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        model = LogisticRegression(max_iter=800, tol=1e-12).fit(f)
        ref = sk.LogisticRegression(penalty=None, tol=1e-10, max_iter=2000)
        ref.fit(X, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0], atol=2e-3)
        assert model.intercept == pytest.approx(ref.intercept_[0], abs=2e-3)

    def test_l1_matches_sklearn_on_standardized(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.05
        model = LogisticRegression(reg_param=lam, elastic_net_param=1.0,
                                   max_iter=3000, tol=1e-13).fit(f)
        # sklearn: min (1/C)·(‖w‖₁) + Σ logloss on pre-standardized features
        sx = X.std(axis=0, ddof=1)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), l1_ratio=1.0,
                                    solver="saga", tol=1e-12,
                                    max_iter=50000)
        ref.fit(X / sx, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0] / sx,
                                   atol=3e-3)

    def test_ridge_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.1
        model = LogisticRegression(reg_param=lam, elastic_net_param=0.0,
                                   max_iter=2000, tol=1e-13).fit(f)
        sx = X.std(axis=0, ddof=1)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), l1_ratio=0.0,
                                    tol=1e-12, max_iter=10000)
        ref.fit(X / sx, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0] / sx,
                                   atol=2e-3)


class TestStandardizationFalse:
    def test_l2_penalizes_raw_coefficients(self):
        """standardization=False L2 must equal sklearn ridge-logistic on RAW
        features with C = 1/(n·λ) (penalty weight 1/σ² in scaled space)."""
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.1
        model = LogisticRegression(reg_param=lam, elastic_net_param=0.0,
                                   standardization=False, max_iter=3000,
                                   tol=1e-13).fit(f)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), l1_ratio=0.0,
                                    tol=1e-12, max_iter=10000)
        ref.fit(X, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0], atol=2e-3)


class TestDqPipelineClassifier:
    """BASELINE.json config (d): binary classifier on the DQ-filtered rows —
    label = 'is this a premium-priced event' (price above the per-guest
    trend), a plausible catering business question."""

    def test_classifier_on_dq_rows(self, session):
        import sparkdq4ml_tpu as dq

        df = run_dq_pipeline(session, dataset_path("full"))
        df = df.with_column("label",
                            (dq.col("price") > dq.col("guest") * 5.0 + 20.0)
                            .cast("double"))
        df = VectorAssembler(["guest", "price"], "features").transform(df)
        model = LogisticRegression(max_iter=400).fit(df)
        s = model.summary
        assert s.accuracy > 0.8          # separable up to the data's noise band
        assert s.area_under_roc > 0.9
        assert s.total_iterations >= 1
        assert len(s.objective_history) == s.total_iterations + 1
        # objective history starts at log(2) (w=0) and decreases
        assert s.objective_history[0] == pytest.approx(np.log(2), abs=1e-6)
        assert s.objective_history[-1] < s.objective_history[0]

    def test_transform_columns(self, session):
        f, X, y = _synth(80)
        model = LogisticRegression(max_iter=200).fit(f)
        out = model.transform(f)
        assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
        d = out.to_pydict()
        np.testing.assert_allclose(
            d["probability"], 1 / (1 + np.exp(-d["rawPrediction"])), rtol=1e-5)
        assert set(np.unique(d["prediction"])) <= {0.0, 1.0}


class TestDistributed:
    def test_sharded_equals_single(self):
        f, X, y = _synth(200)
        m1 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficients, m1.coefficients, rtol=1e-8)
        assert m8.intercept == pytest.approx(m1.intercept, rel=1e-8)

    def test_sharded_with_masked_rows(self):
        f, X, y = _synth(203)  # odd row count forces padding
        import jax.numpy as jnp
        f = f.filter(jnp.asarray(np.arange(203) % 7 != 0))
        m1 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficients, m1.coefficients, rtol=1e-8)


class TestApi:
    def test_predict_scalar(self):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100).fit(f)
        p = m.predict_probability(X[0])
        assert 0.0 <= p <= 1.0
        assert m.predict(X[0]) in (0.0, 1.0)

    def test_threshold(self):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100, threshold=0.99).fit(f)
        d = m.transform(f).to_pydict()
        assert (d["prediction"] == 1.0).sum() <= (d["probability"] > 0.5).sum()

    def test_save_load(self, tmp_path):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100).fit(f)
        m.save(str(tmp_path / "lr"))
        loaded = LogisticRegressionModel.load(str(tmp_path / "lr"))
        np.testing.assert_array_equal(loaded.coefficients, m.coefficients)
        assert loaded.predict(X[0]) == m.predict(X[0])

    def test_family_validation(self):
        with pytest.raises(ValueError):
            LogisticRegression(family="multinomial")

    def test_evaluate_and_roc(self):
        f, X, y = _synth(100)
        m = LogisticRegression(max_iter=200).fit(f)
        s = m.evaluate(f)
        roc = s.roc
        d = roc.to_pydict()
        assert d["FPR"][0] == 0.0 and d["TPR"][-1] == 1.0
        assert 0.5 < s.area_under_roc <= 1.0
