"""LogisticRegression: sklearn parity oracle, DQ-pipeline integration
(BASELINE.json config d), distributed equality, API surface."""

import numpy as np
import pytest

from conftest import dataset_path, run_dq_pipeline
from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (LogisticRegression, LogisticRegressionModel,
                                   VectorAssembler)
from sparkdq4ml_tpu.parallel.mesh import make_mesh


def _synth(n=300, d=3, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    w = np.asarray([1.5, -2.0, 0.8])[:d]
    logits = X @ w + 0.5
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
    f = Frame({"features": X, "label": y})
    return f, X, y


class TestSklearnParity:
    def test_unregularized_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        model = LogisticRegression(max_iter=800, tol=1e-12).fit(f)
        ref = sk.LogisticRegression(penalty=None, tol=1e-10, max_iter=2000)
        ref.fit(X, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0], atol=2e-3)
        assert model.intercept == pytest.approx(ref.intercept_[0], abs=2e-3)

    def test_l1_matches_sklearn_on_standardized(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.05
        model = LogisticRegression(reg_param=lam, elastic_net_param=1.0,
                                   max_iter=3000, tol=1e-13).fit(f)
        # sklearn: min (1/C)·(‖w‖₁) + Σ logloss on pre-standardized
        # features. penalty="elasticnet" is required for l1_ratio to
        # apply at all — without it modern sklearn warns and silently
        # fits L2, turning this into a parity test against the wrong
        # objective.
        sx = X.std(axis=0, ddof=1)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam),
                                    penalty="elasticnet", l1_ratio=1.0,
                                    solver="saga", tol=1e-12,
                                    max_iter=50000)
        ref.fit(X / sx, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0] / sx,
                                   atol=3e-3)

    def test_ridge_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.1
        model = LogisticRegression(reg_param=lam, elastic_net_param=0.0,
                                   max_iter=2000, tol=1e-13).fit(f)
        sx = X.std(axis=0, ddof=1)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), l1_ratio=0.0,
                                    tol=1e-12, max_iter=10000)
        ref.fit(X / sx, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0] / sx,
                                   atol=2e-3)


class TestStandardizationFalse:
    def test_l2_penalizes_raw_coefficients(self):
        """standardization=False L2 must equal sklearn ridge-logistic on RAW
        features with C = 1/(n·λ) (penalty weight 1/σ² in scaled space)."""
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth()
        lam = 0.1
        model = LogisticRegression(reg_param=lam, elastic_net_param=0.0,
                                   standardization=False, max_iter=3000,
                                   tol=1e-13).fit(f)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), l1_ratio=0.0,
                                    tol=1e-12, max_iter=10000)
        ref.fit(X, y)
        np.testing.assert_allclose(model.coefficients, ref.coef_[0], atol=2e-3)


class TestDqPipelineClassifier:
    """BASELINE.json config (d): binary classifier on the DQ-filtered rows —
    label = 'is this a premium-priced event' (price above the per-guest
    trend), a plausible catering business question."""

    def test_classifier_on_dq_rows(self, session):
        import sparkdq4ml_tpu as dq

        df = run_dq_pipeline(session, dataset_path("full"))
        df = df.with_column("label",
                            (dq.col("price") > dq.col("guest") * 5.0 + 20.0)
                            .cast("double"))
        df = VectorAssembler(["guest", "price"], "features").transform(df)
        model = LogisticRegression(max_iter=400).fit(df)
        s = model.summary
        assert s.accuracy > 0.8          # separable up to the data's noise band
        assert s.area_under_roc > 0.9
        assert s.total_iterations >= 1
        assert len(s.objective_history) == s.total_iterations + 1
        # objective history starts at log(2) (w=0) and decreases
        assert s.objective_history[0] == pytest.approx(np.log(2), abs=1e-6)
        assert s.objective_history[-1] < s.objective_history[0]

    def test_transform_columns(self, session):
        f, X, y = _synth(80)
        model = LogisticRegression(max_iter=200).fit(f)
        out = model.transform(f)
        assert {"rawPrediction", "probability", "prediction"} <= set(out.columns)
        d = out.to_pydict()
        np.testing.assert_allclose(
            d["probability"], 1 / (1 + np.exp(-d["rawPrediction"])), rtol=1e-5)
        assert set(np.unique(d["prediction"])) <= {0.0, 1.0}


class TestDistributed:
    def test_sharded_equals_single(self):
        f, X, y = _synth(200)
        m1 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficients, m1.coefficients, rtol=1e-8)
        assert m8.intercept == pytest.approx(m1.intercept, rel=1e-8)

    def test_sharded_with_masked_rows(self):
        f, X, y = _synth(203)  # odd row count forces padding
        import jax.numpy as jnp
        f = f.filter(jnp.asarray(np.arange(203) % 7 != 0))
        m1 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficients, m1.coefficients, rtol=1e-8)


class TestApi:
    def test_predict_scalar(self):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100).fit(f)
        p = m.predict_probability(X[0])
        assert 0.0 <= p <= 1.0
        assert m.predict(X[0]) in (0.0, 1.0)

    def test_threshold(self):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100, threshold=0.99).fit(f)
        d = m.transform(f).to_pydict()
        assert (d["prediction"] == 1.0).sum() <= (d["probability"] > 0.5).sum()

    def test_save_load(self, tmp_path):
        f, X, y = _synth(60)
        m = LogisticRegression(max_iter=100).fit(f)
        m.save(str(tmp_path / "lr"))
        loaded = LogisticRegressionModel.load(str(tmp_path / "lr"))
        np.testing.assert_array_equal(loaded.coefficients, m.coefficients)
        assert loaded.predict(X[0]) == m.predict(X[0])

    def test_family_validation(self):
        with pytest.raises(ValueError):
            LogisticRegression(family="gaussian")

    def test_evaluate_and_roc(self):
        f, X, y = _synth(100)
        m = LogisticRegression(max_iter=200).fit(f)
        s = m.evaluate(f)
        roc = s.roc
        d = roc.to_pydict()
        assert d["FPR"][0] == 0.0 and d["TPR"][-1] == 1.0
        assert 0.5 < s.area_under_roc <= 1.0


def _synth_multi(n=400, d=4, k=3, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    W = rng.normal(size=(k, d)) * 1.5
    b = rng.normal(size=k)
    logits = X @ W.T + b
    p = np.exp(logits - logits.max(axis=1, keepdims=True))
    p /= p.sum(axis=1, keepdims=True)
    y = np.array([rng.choice(k, p=p[i]) for i in range(n)], np.float64)
    f = Frame({"features": X, "label": y})
    return f, X, y


class TestMultinomial:
    def test_auto_family_selects_multinomial(self):
        f, X, y = _synth_multi(120)
        m = LogisticRegression(max_iter=200).fit(f)
        assert m.is_multinomial
        assert m.num_classes == 3
        assert m.coefficient_matrix.shape == (3, 4)
        assert m.intercept_vector.shape == (3,)
        with pytest.raises(RuntimeError):
            m.coefficients
        with pytest.raises(RuntimeError):
            m.intercept

    def test_binomial_family_rejects_multiclass(self):
        f, X, y = _synth_multi(60)
        with pytest.raises(ValueError, match="binomial"):
            LogisticRegression(family="binomial").fit(f)

    def test_unregularized_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth_multi()
        model = LogisticRegression(max_iter=3000, tol=1e-13).fit(f)
        ref = sk.LogisticRegression(penalty=None, tol=1e-10, max_iter=5000)
        ref.fit(X, y)
        # both solutions are centered across classes (zero init preserves
        # the sum-to-zero manifold; ours pivots explicitly)
        np.testing.assert_allclose(model.coefficient_matrix, ref.coef_,
                                   atol=5e-3)
        np.testing.assert_allclose(
            model.intercept_vector,
            ref.intercept_ - ref.intercept_.mean(), atol=5e-3)

    def test_ridge_matches_sklearn(self):
        sk = pytest.importorskip("sklearn.linear_model")
        f, X, y = _synth_multi()
        lam = 0.05
        model = LogisticRegression(reg_param=lam, elastic_net_param=0.0,
                                   standardization=False, max_iter=4000,
                                   tol=1e-14).fit(f)
        ref = sk.LogisticRegression(C=1.0 / (len(y) * lam), tol=1e-12,
                                    max_iter=20000)
        ref.fit(X, y)
        np.testing.assert_allclose(model.coefficient_matrix, ref.coef_,
                                   atol=3e-3)

    def test_l1_produces_sparsity(self):
        f, X, y = _synth_multi(300)
        dense = LogisticRegression(max_iter=500).fit(f)
        sparse = LogisticRegression(reg_param=0.3, elastic_net_param=1.0,
                                    max_iter=500).fit(f)
        assert np.sum(sparse.coefficient_matrix == 0.0) \
            > np.sum(dense.coefficient_matrix == 0.0)

    def test_transform_columns(self):
        f, X, y = _synth_multi(100)
        m = LogisticRegression(max_iter=300).fit(f)
        out = m.transform(f)
        d = out.to_pydict()
        probs = np.stack(d["probability"])
        assert probs.shape == (100, 3)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
        raw = np.stack(d["rawPrediction"])
        np.testing.assert_array_equal(d["prediction"], raw.argmax(axis=1))

    def test_predict_scalar(self):
        f, X, y = _synth_multi(100)
        m = LogisticRegression(max_iter=300).fit(f)
        pred = m.predict(X[0])
        assert pred in (0.0, 1.0, 2.0)
        p = m.predict_probability(X[0])
        assert p.shape == (3,)
        assert p.sum() == pytest.approx(1.0, abs=1e-6)
        assert pred == float(np.argmax(p))

    def test_summary(self):
        f, X, y = _synth_multi(300)
        m = LogisticRegression(max_iter=400).fit(f)
        s = m.summary
        assert s.accuracy > 0.7
        assert s.objective_history[0] == pytest.approx(np.log(3), abs=1e-6)
        assert s.objective_history[-1] < s.objective_history[0]
        assert len(s.objective_history) == s.total_iterations + 1
        assert 0.0 < s.weighted_precision <= 1.0
        assert 0.0 < s.weighted_recall <= 1.0
        assert 0.0 < s.weighted_f_measure <= 1.0
        assert s.precision_by_label.shape == (3,)

    def test_sharded_equals_single(self):
        f, X, y = _synth_multi(200)
        m1 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=300, reg_param=0.05,
                                elastic_net_param=0.5).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficient_matrix,
                                   m1.coefficient_matrix, rtol=1e-8, atol=1e-12)
        np.testing.assert_allclose(m8.intercept_vector, m1.intercept_vector,
                                   rtol=1e-8, atol=1e-12)

    def test_sharded_with_masked_rows(self):
        f, X, y = _synth_multi(203)
        import jax.numpy as jnp
        f = f.filter(jnp.asarray(np.arange(203) % 7 != 0))
        m1 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(1))
        m8 = LogisticRegression(max_iter=200).fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(m8.coefficient_matrix,
                                   m1.coefficient_matrix, rtol=1e-8, atol=1e-12)

    def test_save_load_roundtrip(self, tmp_path):
        f, X, y = _synth_multi(100)
        m = LogisticRegression(max_iter=200).fit(f)
        m.save(str(tmp_path / "mlr"))
        loaded = LogisticRegressionModel.load(str(tmp_path / "mlr"))
        assert loaded.is_multinomial
        np.testing.assert_array_equal(loaded.coefficient_matrix,
                                      m.coefficient_matrix)
        np.testing.assert_array_equal(loaded.intercept_vector,
                                      m.intercept_vector)
        assert loaded.predict(X[3]) == m.predict(X[3])

    def test_binary_via_multinomial_family(self):
        """K=2 with family='multinomial' → 2-row pivoted matrix whose margin
        difference reproduces the binomial fit (MLlib's documented
        relationship)."""
        f, X, y = _synth(200)
        mb = LogisticRegression(max_iter=2000, tol=1e-13).fit(f)
        mm = LogisticRegression(family="multinomial", max_iter=4000,
                                tol=1e-13).fit(f)
        assert mm.coefficient_matrix.shape == (2, X.shape[1])
        np.testing.assert_allclose(
            mm.coefficient_matrix[1] - mm.coefficient_matrix[0],
            mb.coefficients, atol=5e-3)

    def test_evaluate_multiclass(self):
        f, X, y = _synth_multi(150)
        m = LogisticRegression(max_iter=300).fit(f)
        s = m.evaluate(f)
        assert 0.0 <= s.accuracy <= 1.0
        assert s.labels.tolist() == [0.0, 1.0, 2.0]


class TestThresholdCurves:
    @pytest.fixture(scope="class")
    def summary(self):
        rng = np.random.default_rng(0)
        n = 120
        x = rng.normal(size=n)
        y = (x + 0.5 * rng.normal(size=n) > 0).astype(np.float64)
        f = VectorAssembler(["x"], "features").transform(
            Frame({"x": x, "label": y}))
        return LogisticRegression(max_iter=100).fit(f).summary

    def test_pr_curve_matches_sklearn(self, summary):
        from sklearn.metrics import precision_recall_curve
        d = summary.pr.to_pydict()
        prec_sk, rec_sk, _ = precision_recall_curve(
            summary._label, summary._prob)
        ours = set(zip(np.round(d["recall"], 9),
                       np.round(d["precision"], 9)))
        # sklearn's curve points (reversed order) must all appear in ours
        missing = [(r, p) for p, r in zip(np.round(prec_sk, 9),
                                          np.round(rec_sk, 9))
                   if (r, p) not in ours and r > 0]
        assert not missing

    def test_by_threshold_frames(self, summary):
        p = summary.precision_by_threshold.to_pydict()
        r = summary.recall_by_threshold.to_pydict()
        fm = summary.f_measure_by_threshold.to_pydict()
        assert list(p.keys()) == ["threshold", "precision"]
        assert list(r.keys()) == ["threshold", "recall"]
        assert list(fm.keys()) == ["threshold", "F-Measure"]
        # recall is monotone nondecreasing as the threshold drops
        assert np.all(np.diff(r["recall"]) >= -1e-12)
        assert r["recall"][-1] == pytest.approx(1.0)
        # f = harmonic mean of the other two, pointwise
        f_chk = (2 * np.asarray(p["precision"]) * np.asarray(r["recall"])
                 / np.maximum(np.asarray(p["precision"])
                              + np.asarray(r["recall"]), 1e-30))
        np.testing.assert_allclose(fm["F-Measure"], f_chk, rtol=1e-9)

    def test_camelcase_surface(self, summary):
        assert summary.precisionByThreshold.count() == \
            summary.recallByThreshold.count()


class TestWeightCol:
    """weightCol: integer weight k must equal the row repeated k times
    (the weighted mean-loss objective makes this exact), binary and
    multinomial."""

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        n, d = 60, 3
        X = rng.normal(size=(n, d))
        logits = X @ np.asarray([1.5, -2.0, 0.7]) + 0.3
        yb = (logits + rng.logistic(size=n) > 0).astype(np.float64)
        ym = rng.integers(0, 3, size=n).astype(np.float64)
        w = rng.integers(1, 4, size=n).astype(np.float64)
        return X, yb, ym, w

    def _frames(self, X, y, w):
        n, d = X.shape
        cols = {f"x{j}": X[:, j] for j in range(d)}
        fw = VectorAssembler([f"x{j}" for j in range(d)], "features") \
            .transform(Frame({**cols, "label": y, "w": w}))
        idx = np.repeat(np.arange(n), w.astype(int))
        fr = VectorAssembler([f"x{j}" for j in range(d)], "features") \
            .transform(Frame({**{f"x{j}": X[idx, j] for j in range(d)},
                              "label": y[idx]}))
        return fw, fr

    @pytest.mark.parametrize("params", [
        dict(max_iter=300),
        dict(max_iter=300, reg_param=0.05, elastic_net_param=1.0),
        dict(max_iter=300, reg_param=0.1, elastic_net_param=0.3),
    ])
    def test_binary_weight_equals_repetition(self, data, params):
        X, yb, _, w = data
        fw, fr = self._frames(X, yb, w)
        mw = LogisticRegression(weight_col="w", **params).fit(fw)
        mr = LogisticRegression(**params).fit(fr)
        np.testing.assert_allclose(mw.coefficients, mr.coefficients,
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(mw.intercept, mr.intercept,
                                   rtol=1e-4, atol=1e-6)

    def test_multinomial_weight_equals_repetition(self, data):
        X, _, ym, w = data
        fw, fr = self._frames(X, ym, w)
        mw = LogisticRegression(weight_col="w", family="multinomial",
                                max_iter=300).fit(fw)
        mr = LogisticRegression(family="multinomial", max_iter=300).fit(fr)
        np.testing.assert_allclose(mw.coefficient_matrix,
                                   mr.coefficient_matrix,
                                   rtol=1e-4, atol=1e-5)

    def test_sklearn_sample_weight_parity(self, data):
        from sklearn.linear_model import LogisticRegression as SkLogit
        X, yb, _, w = data
        fw, _ = self._frames(X, yb, w)
        m = LogisticRegression(max_iter=500, tol=1e-10,
                               weight_col="w").fit(fw)
        sk = SkLogit(C=1e8, max_iter=2000, tol=1e-10).fit(
            X, yb, sample_weight=w)
        np.testing.assert_allclose(m.coefficients, sk.coef_.ravel(),
                                   rtol=2e-3, atol=2e-4)

    def test_negative_weights_rejected(self, data):
        X, yb, _, w = data
        cols = {f"x{j}": X[:, j] for j in range(X.shape[1])}
        fw = VectorAssembler(list(cols), "features").transform(
            Frame({**cols, "label": yb, "w": -w}))
        with pytest.raises(ValueError, match="nonnegative"):
            LogisticRegression(weight_col="w").fit(fw)

    def test_sharded_weighted_matches_single(self, data):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh
        X, yb, _, w = data
        fw, _ = self._frames(X, yb, w)
        est = LogisticRegression(weight_col="w", max_iter=200)
        a = est.fit(fw)
        b = est.fit(fw, mesh=make_mesh(8))
        np.testing.assert_allclose(a.coefficients, b.coefficients,
                                   rtol=1e-8, atol=1e-10)

    def test_masked_row_weights_never_participate(self):
        import sparkdq4ml_tpu as dq
        f = VectorAssembler(["x"], "features").transform(
            Frame({"x": np.asarray([-2.0, -1.0, 1.0, 2.0, 9.0]),
                   "label": np.asarray([0.0, 0.0, 1.0, 1.0, 1.0]),
                   "w": np.asarray([1.0, 2.0, 1.0, 2.0, np.nan])}))
        f = f.filter(dq.col("x") < 5.0)       # masks the NaN-weight row
        m = LogisticRegression(weight_col="w", max_iter=50).fit(f)
        assert np.all(np.isfinite(m.coefficients))


class TestNewtonSolver:
    """Damped Newton/IRLS auto-routing for L1-free penalties
    (classification._logistic_newton_core)."""

    def _fit_packed(self, Z, hyper, solver, d, max_iter=200):
        from sparkdq4ml_tpu.models.classification import \
            fused_logistic_fit_packed
        from sparkdq4ml_tpu.parallel.distributed import unpack_fit_result
        fit = fused_logistic_fit_packed(None, max_iter, 1e-9, True, True,
                                        solver=solver)
        return unpack_fit_result(np.asarray(fit(Z, hyper)), d)

    def _packed(self, n=2000, d=6, seed=3):
        import jax.numpy as jnp

        from sparkdq4ml_tpu.parallel.distributed import pack_design
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) + 0.4 * rng.normal(size=n) > 0)
        return pack_design(jnp.asarray(X), jnp.asarray(y, jnp.float32),
                           jnp.asarray(np.ones(n, bool))), d

    @pytest.mark.parametrize("reg", [0.0, 0.01, 0.5])
    def test_newton_matches_fista_optimum(self, reg):
        import jax.numpy as jnp
        Z, d = self._packed()
        hyper = jnp.asarray([reg, 0.0], jnp.float32)
        rf = self._fit_packed(Z, hyper, "fista", d, max_iter=3000)
        rn = self._fit_packed(Z, hyper, "newton", d, max_iter=50)
        # f32 near a (flat at reg=0) optimum: solver-path differences of a
        # few 1e-3 are the float32 noise floor, not a solver gap
        np.testing.assert_allclose(rn.coefficients, rf.coefficients,
                                   rtol=5e-3, atol=5e-3)
        assert int(rn.iterations) < int(rf.iterations)

    def test_newton_converges_fast(self):
        import jax.numpy as jnp
        Z, d = self._packed()
        rn = self._fit_packed(Z, jnp.asarray([0.01, 0.0], jnp.float32),
                              "newton", d, max_iter=50)
        assert bool(rn.converged)
        assert int(rn.iterations) <= 15

    def test_separable_data_stays_finite(self):
        import jax.numpy as jnp

        from sparkdq4ml_tpu.parallel.distributed import pack_design
        rng = np.random.default_rng(5)
        X = rng.normal(size=(400, 4)).astype(np.float32)
        y = (X @ rng.normal(size=4) > 0)   # perfectly separable, reg=0
        Z = pack_design(jnp.asarray(X), jnp.asarray(y, jnp.float32),
                        jnp.asarray(np.ones(400, bool)))
        rn = self._fit_packed(Z, jnp.asarray([0.0, 0.0], jnp.float32),
                              "newton", 4, max_iter=40)
        assert np.all(np.isfinite(np.asarray(rn.coefficients)))
        assert np.isfinite(float(rn.intercept))

    def test_estimator_routes_l2_to_newton_and_l1_to_fista(self):
        # Routing is observable through iteration counts: Newton converges
        # in <=15 iterations where FISTA needs far more at tol=1e-9.
        f, X, yb = _synth(n=500, seed=7)
        l2 = LogisticRegression(reg_param=0.01, elastic_net_param=0.0,
                                max_iter=300, tol=1e-9).fit(f)
        l1 = LogisticRegression(reg_param=0.01, elastic_net_param=1.0,
                                max_iter=300, tol=1e-9).fit(f)
        assert l2.summary.total_iterations <= 15
        # same optimum family, different solvers: both finite and sane
        assert np.all(np.isfinite(l1.coefficients))

    def test_newton_sharded_matches_single(self):
        f, X, yb = _synth(n=400, seed=9)
        est = LogisticRegression(reg_param=0.05, elastic_net_param=0.0,
                                 max_iter=100, tol=1e-10)
        a = est.fit(f)
        b = est.fit(f, mesh=make_mesh(8))
        np.testing.assert_allclose(a.coefficients, b.coefficients,
                                   rtol=1e-6, atol=1e-8)
        assert a.intercept == pytest.approx(b.intercept, abs=1e-6)

    def test_newton_weighted_matches_repetition(self):
        rng = np.random.default_rng(11)
        n, d = 60, 3
        X = rng.normal(size=(n, d))
        y = (X @ rng.normal(size=d) + 0.3 * rng.normal(size=n) > 0
             ).astype(np.float64)
        w = rng.integers(1, 4, size=n).astype(np.float64)
        fw = VectorAssembler([f"x{j}" for j in range(d)], "features").transform(
            Frame({**{f"x{j}": X[:, j] for j in range(d)},
                   "label": y, "w": w}))
        idx = np.repeat(np.arange(n), w.astype(int))
        fr = VectorAssembler([f"x{j}" for j in range(d)], "features").transform(
            Frame({**{f"x{j}": X[idx, j] for j in range(d)},
                   "label": y[idx]}))
        est_w = LogisticRegression(reg_param=0.1, elastic_net_param=0.0,
                                   weight_col="w", max_iter=100, tol=1e-10)
        est_r = LogisticRegression(reg_param=0.1, elastic_net_param=0.0,
                                   max_iter=100, tol=1e-10)
        a = est_w.fit(fw)
        b = est_r.fit(fr)
        np.testing.assert_allclose(a.coefficients, b.coefficients,
                                   rtol=1e-4, atol=1e-6)


class TestSoftmaxNewtonSolver:
    """Block-Hessian Newton routing for L1-free multinomial fits
    (classification._softmax_newton_core)."""

    def _fit(self, solver, reg=0.05, mesh=None, max_iter=200):
        import jax.numpy as jnp

        from sparkdq4ml_tpu.models.classification import (
            fused_softmax_fit_packed, unpack_softmax_result)
        from sparkdq4ml_tpu.parallel.distributed import (pack_design,
                                                         place_packed)
        f, X, y = _synth_multi(n=500, seed=13)
        d = X.shape[1]
        K = int(y.max()) + 1
        Z = place_packed(pack_design(
            jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.float32),
            jnp.asarray(np.ones(len(y), bool))), mesh)
        fit = fused_softmax_fit_packed(mesh, K, max_iter, 1e-9, True, True,
                                       solver=solver)
        hyper = jnp.asarray([reg, 0.0], jnp.float32)
        return unpack_softmax_result(np.asarray(fit(Z, hyper)), K, d)

    def test_newton_matches_fista_optimum(self):
        rf = self._fit("fista", max_iter=3000)
        rn = self._fit("newton", max_iter=60)
        np.testing.assert_allclose(rn.coefficient_matrix,
                                   rf.coefficient_matrix,
                                   rtol=5e-3, atol=5e-3)
        # intercepts are unpenalized => the softmax shift degeneracy makes
        # them gauge-dependent; compare after the MLlib centering pivot
        # (the estimator applies this same pivot before exposing them)
        bn = rn.intercept_vector - rn.intercept_vector.mean()
        bf = rf.intercept_vector - rf.intercept_vector.mean()
        np.testing.assert_allclose(bn, bf, rtol=5e-3, atol=5e-3)
        assert int(rn.iterations) < int(rf.iterations)

    def test_newton_converges_fast(self):
        rn = self._fit("newton", max_iter=60)
        assert int(rn.iterations) <= 20

    def test_newton_sharded_matches_single(self):
        from sparkdq4ml_tpu.parallel.mesh import make_mesh
        a = self._fit("newton")
        b = self._fit("newton", mesh=make_mesh(8))
        np.testing.assert_allclose(a.coefficient_matrix, b.coefficient_matrix,
                                   rtol=1e-5, atol=1e-7)

    def test_estimator_multinomial_l2_routes_newton(self):
        f, X, y = _synth_multi(n=400, seed=3)
        m = LogisticRegression(family="multinomial", reg_param=0.05,
                               elastic_net_param=0.0, max_iter=200,
                               tol=1e-9).fit(f)
        assert m.summary.total_iterations <= 20
        # sklearn cross-check on the same RAW data (standardization
        # conventions differ between the stacks, so compare predictions,
        # not coefficients, and allow >90% agreement)
        from sklearn.linear_model import LogisticRegression as Sk
        sk = Sk(C=1.0 / (0.05 * len(y)), max_iter=2000, tol=1e-10).fit(X, y)
        ours = m.transform(f).to_pydict()["prediction"]
        agree = np.mean(np.asarray(ours) == sk.predict(X))
        assert agree > 0.9
