"""Cost-based plan optimizer suite (ISSUE 14, tier-1, ``optimizer``
marker).

The acceptance surface:

* **parity across the SQL surface** — filters, joins (both build
  sides), GROUP BY, CTEs, set ops, LIMIT/OFFSET each pinned EQUAL with
  ``spark.optimizer.enabled`` on vs off (exact column equality for the
  order-preserving level-1 rewrites; sorted-row equality for the
  level-2 join reorder, where SQL imposes no order), plus sharded-mode
  (``spark.shard.enabled``) parity on the join paths;
* **EXPLAIN** — the before/after plan diff and per-rewrite annotations
  render with ZERO execution (compile/flush/sync counters pinned), and
  ``build=left`` hints show on Join nodes;
* **degradation** — the ``optimizer`` fault site degrades to the
  unrewritten plan (recovery event + ``optimizer.fallback``), results
  unchanged;
* **lowering hooks** — the compiler's warm-prefix stage split
  (``optimizer.split``), the statstore-informed planned memory chunking
  (``optimizer.mem_chunk``), and the grouped engine's dense-skip
  (``optimizer.dense_skip``), each parity-asserted;
* **cost-model glue** — ``Digest.p50/p90`` are THE quantile accessors
  (stats_report and the cost model read the same numbers),
  ``bytes_bound``/``miss_count``;
* **satellite** — history-informed ``est_rows`` propagates through
  With/SetOps wrapper nodes (a Scan of a CTE name resolves against the
  CTE body's estimate instead of going ``-``).
"""

from __future__ import annotations

import contextlib
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.ops import compiler
from sparkdq4ml_tpu.parallel import mesh as pmesh
from sparkdq4ml_tpu.parallel import shard
from sparkdq4ml_tpu.sql import optimizer as opt
from sparkdq4ml_tpu.utils import faults, observability as obs
from sparkdq4ml_tpu.utils import profiling, statstore
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG
from sparkdq4ml_tpu.utils.statstore import Digest

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.optimizer


@pytest.fixture(autouse=True)
def _clean_optimizer_state():
    saved = (config.optimizer_enabled, config.optimizer_level,
             config.audit_device_budget)
    statstore.STORE.clear()
    compiler.clear_cache()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("optimizer.")
    yield
    (config.optimizer_enabled, config.optimizer_level,
     config.audit_device_budget) = saved
    statstore.STORE.clear()
    compiler.clear_cache()
    faults.clear()
    RECOVERY_LOG.clear()
    profiling.counters.clear("optimizer.")
    # EXPLAIN ANALYZE's query_stats window records into the process
    # tracer buffer; flush it so span-inspecting suites later in the
    # run never see this file's spans
    obs.TRACER.clear()


def _register(session, n=4000, seed=3, shard_frames=False):
    """The suite's relations: a fact table, a full dim, a partial dim
    (64 of 128 keys), and a string-keyed pair."""
    rng = np.random.default_rng(seed)
    big = Frame({"k": rng.integers(0, 128, n).astype(np.float64),
                 "v": rng.normal(size=n),
                 "x1": rng.normal(size=n), "x2": rng.normal(size=n)})
    mid = Frame({"k": np.arange(128).astype(np.float64),
                 "u": rng.normal(size=128)})
    small = Frame({"k": np.arange(64).astype(np.float64),
                   "w": rng.normal(size=64)})
    if shard_frames:
        big = shard.maybe_shard_frame(big)
        mid = shard.maybe_shard_frame(mid)
    for name, f in (("big", big), ("mid", mid), ("small", small)):
        f.create_or_replace_temp_view(name)
    return big, mid, small


def _exec(session, sql):
    out = session.sql(sql)
    jax.block_until_ready(out._mask)
    return out.to_pydict()


def _pair(session, sql, level=1):
    """(off, on) result dicts for one statement."""
    config.optimizer_level = level
    config.optimizer_enabled = False
    off = _exec(session, sql)
    config.optimizer_enabled = True
    on = _exec(session, sql)
    return off, on


def _assert_exact(off, on):
    assert list(off) == list(on)
    for c in off:
        np.testing.assert_array_equal(np.asarray(off[c]),
                                      np.asarray(on[c]),
                                      err_msg=f"column {c!r}")


def _assert_sorted(off, on):
    assert sorted(off) == sorted(on)
    cols = sorted(off)
    a = np.array([np.asarray(off[c], dtype=np.float64) for c in cols])
    b = np.array([np.asarray(on[c], dtype=np.float64) for c in cols])
    assert a.shape == b.shape
    np.testing.assert_array_equal(a[:, np.lexsort(a[::-1])],
                                  b[:, np.lexsort(b[::-1])])


# ---------------------------------------------------------------------------
# Parity across the SQL surface (optimizer on vs off)
# ---------------------------------------------------------------------------


class TestParity:
    def test_plain_filter(self, session):
        _register(session)
        off, on = _pair(session,
                        "SELECT v, x1 FROM big WHERE v < 0 AND k > 5")
        _assert_exact(off, on)

    def test_join_pushdown_both_sides(self, session):
        _register(session)
        off, on = _pair(
            session,
            "SELECT k, v, u FROM big JOIN mid USING (k) "
            "WHERE v < -0.5 AND u > 0")
        _assert_exact(off, on)
        assert len(off["k"]) > 0

    def test_join_build_side_small_left(self, session):
        _register(session)
        off, on = _pair(session,
                        "SELECT k, w, v FROM small JOIN big USING (k)")
        _assert_exact(off, on)
        plan = _exec(session,
                     "EXPLAIN SELECT k, w, v FROM small JOIN big "
                     "USING (k)")["plan"][0]
        assert "build=left" in plan

    def test_left_join_pushes_left_only(self, session):
        _register(session)
        sql = ("SELECT k, v, w FROM big LEFT JOIN small USING (k) "
               "WHERE v < -0.5 AND x1 > 0")
        off, on = _pair(session, sql)
        _assert_exact(off, on)
        plan = _exec(session, "EXPLAIN " + sql)["plan"][0]
        # left-side conjuncts pushed; the LEFT join's right side is NOT
        # a pushdown target (null-extension semantics)
        assert "pushdown" in plan
        assert "Scan[small]\n" in plan + "\n"

    def test_group_by_over_join(self, session):
        _register(session)
        off, on = _pair(
            session,
            "SELECT k, count(*) c, sum(v) s FROM big JOIN small "
            "USING (k) WHERE v < 0.5 GROUP BY k ORDER BY k")
        _assert_exact(off, on)

    def test_cte(self, session):
        _register(session)
        off, on = _pair(
            session,
            "WITH f AS (SELECT k, v FROM big WHERE v < 0) "
            "SELECT k, v, u FROM f JOIN mid USING (k) WHERE u > 0")
        _assert_exact(off, on)

    def test_set_ops(self, session):
        _register(session)
        off, on = _pair(
            session,
            "SELECT k FROM big WHERE v < -1 UNION "
            "SELECT k FROM small WHERE w > 0")
        _assert_exact(off, on)

    def test_limit_offset(self, session):
        _register(session)
        off, on = _pair(
            session,
            "SELECT k, v, u FROM big JOIN mid USING (k) "
            "WHERE v < 0 ORDER BY v LIMIT 7 OFFSET 2")
        _assert_exact(off, on)
        assert len(off["k"]) == 7

    def test_collision_column_referenced_only_via_alias(self, session):
        # x exists on BOTH sides but is referenced only as b.x: pruning
        # must keep the collision twin so the output stays named x_right
        a = Frame({"k": np.arange(8).astype(np.float64),
                   "x": np.arange(8) * 1.0,
                   "junk": np.arange(8) * 3.0})
        b = Frame({"k": np.arange(8).astype(np.float64),
                   "x": np.arange(8) * 2.0})
        a.create_or_replace_temp_view("ca")
        b.create_or_replace_temp_view("cb")
        off, on = _pair(session,
                        "SELECT b.x, a.k FROM ca a JOIN cb b USING (k)")
        assert list(off) == list(on) == ["x_right", "k"]
        _assert_exact(off, on)

    def test_joined_derived_table_inner_rewrites_apply(self, session):
        _register(session)
        sql = ("SELECT big.k, v, sw FROM big JOIN "
               "(SELECT s.k, w AS sw FROM small s JOIN mid USING (k) "
               "WHERE u > 0) sub USING (k) WHERE v < 0")
        off, on = _pair(session, sql)
        _assert_exact(off, on)
        config.optimizer_enabled = True
        plan = _exec(session, "EXPLAIN " + sql)["plan"][0]
        # the inner join's pushdown lands in the AFTER tree, not just
        # the rewrite list (regression: joins_out discarded the
        # recursively optimized derived-table entry)
        after = plan.split("== Before Optimization ==")[0]
        assert "pushdown: (u > 0) -> Scan[mid]" in plan
        assert after.count("Scan[(subquery)]") >= 2

    def test_string_key_join(self, session):
        left = Frame({"s": np.asarray(["a", "b", "c", "b"], object),
                      "v": [1.0, 2.0, 3.0, 4.0]})
        right = Frame({"s": np.asarray(["b", "c", "d"], object),
                       "w": [10.0, 20.0, 30.0]})
        left.create_or_replace_temp_view("ls")
        right.create_or_replace_temp_view("rs")
        off, on = _pair(session,
                        "SELECT s, v, w FROM ls JOIN rs USING (s)")
        _assert_exact(off, on)

    def test_join_reorder_level2(self, session):
        _register(session)
        sql = ("SELECT v, u, w FROM big JOIN mid USING (k) "
               "JOIN small USING (k) WHERE v < 0")
        off, on = _pair(session, sql, level=2)
        _assert_sorted(off, on)
        config.optimizer_enabled = True
        config.optimizer_level = 2
        plan = _exec(session, "EXPLAIN " + sql)["plan"][0]
        assert "join-reorder" in plan

    def test_headline_golden_unchanged(self, session):
        from sparkdq4ml_tpu.models import LinearRegression

        results = {}
        for arm in (False, True):
            config.optimizer_enabled = arm
            config.optimizer_level = 2
            df = run_dq_pipeline(session, dataset_path("abstract"))
            count = df.count()
            model = LinearRegression(max_iter=40, reg_param=1.0,
                                     elastic_net_param=1.0).fit(
                prepare_features(df))
            results[arm] = (count,
                            float(model.summary.root_mean_squared_error))
        assert results[False][0] == results[True][0] == 24
        assert results[False][1] == results[True][1]
        assert results[True][1] == pytest.approx(2.809940, rel=1e-3)


# ---------------------------------------------------------------------------
# EXPLAIN: before/after diff, zero execution, annotations
# ---------------------------------------------------------------------------


class TestExplain:
    SQL = ("SELECT k, v, u FROM big JOIN mid USING (k) "
           "WHERE v < -0.5 AND u > 0")

    def test_diff_renders_with_zero_execution(self, session):
        _register(session)
        config.optimizer_enabled = True
        before = profiling.counters.snapshot()
        frame = session.sql("EXPLAIN " + self.SQL)
        after = profiling.counters.snapshot()
        plan = frame.to_pydict()["plan"][0]   # the read is outside the
        #                                       zero-execution window
        for key in ("pipeline.flush", "pipeline.compile",
                    "grouped.compile", "frame.host_sync"):
            assert after.get(key, 0) == before.get(key, 0), key
        assert "== Rewrites ==" in plan
        assert "== Before Optimization ==" in plan
        assert "pushdown" in plan and "prune" in plan
        # the optimized tree shows the pushed filter under the scan
        assert "Scan[(subquery)]" in plan

    def test_disabled_mode_renders_literal_plan(self, session):
        _register(session)
        config.optimizer_enabled = False
        plan = _exec(session, "EXPLAIN " + self.SQL)["plan"][0]
        assert "== Rewrites ==" not in plan
        assert "Scan[big]" in plan

    def test_explain_analyze_executes_optimized_plan(self, session):
        _register(session)
        config.optimizer_enabled = True
        plan = _exec(session, "EXPLAIN ANALYZE " + self.SQL)["plan"][0]
        assert "== Rewrites ==" in plan
        assert "== Query Stats ==" in plan


# ---------------------------------------------------------------------------
# Degradation ladder + disabled-mode contract
# ---------------------------------------------------------------------------


class TestDegradation:
    def test_fault_degrades_to_unrewritten_plan(self, session):
        _register(session)
        sql = "SELECT k, v, u FROM big JOIN mid USING (k) WHERE v < 0"
        config.optimizer_enabled = False
        ref = _exec(session, sql)
        config.optimizer_enabled = True
        faults.install_plan(faults.parse_plan("optimizer:device_error:1"))
        before = profiling.counters.get("optimizer.fallback")
        got = _exec(session, sql)
        _assert_exact(ref, got)
        assert profiling.counters.get("optimizer.fallback") == before + 1
        assert any(getattr(e, "site", None) == "optimizer"
                   and getattr(e, "action", None) == "fallback"
                   for e in RECOVERY_LOG.events())

    def test_disabled_applies_no_rewrites(self, session):
        _register(session)
        config.optimizer_enabled = False
        before = profiling.counters.get("optimizer.rewrite")
        _exec(session,
              "SELECT k, v, u FROM big JOIN mid USING (k) WHERE v < 0")
        assert profiling.counters.get("optimizer.rewrite") == before

    def test_session_conf_scoping(self):
        s = dq.TpuSession.builder().app_name("opt-conf").master(
            "local[*]").config("spark.optimizer.enabled", "false").config(
            "spark.optimizer.level", "2").get_or_create()
        try:
            assert config.optimizer_enabled is False
            assert config.optimizer_level == 2
        finally:
            s.stop()
        assert config.optimizer_enabled is True
        assert config.optimizer_level == 1


# ---------------------------------------------------------------------------
# Lowering hooks
# ---------------------------------------------------------------------------


class TestLoweringHooks:
    def _chain(self, f, steps, tail_col=None):
        for i in range(steps):
            src = tail_col if tail_col and i >= steps // 2 else "v"
            f = f.with_column(f"c{i}", dq.col(src) * float(i + 1) + 0.5)
        return f

    def test_stage_split_at_warm_prefix(self, monkeypatch):
        monkeypatch.setattr(compiler, "_SPLIT_MIN_COMPILE_MS", 0.0)
        config.optimizer_enabled = True
        rng = np.random.default_rng(0)
        f = Frame({"v": rng.normal(size=256),
                   "y": rng.normal(size=256)})
        # reference result, literal mega-stage (level 1: no split)
        config.optimizer_level = 1
        ref = self._chain(f, 12, "y")
        jax.block_until_ready(ref._mask)
        ref_col = np.asarray(ref._data["c11"])
        compiler.clear_cache()
        statstore.STORE.clear()
        # warm the 6-step prefix, then flush the 12-step chain at level 2
        config.optimizer_level = 2
        warm = self._chain(f, 6)
        jax.block_until_ready(warm._mask)
        before = profiling.counters.get("optimizer.split")
        out = self._chain(f, 12, "y")
        jax.block_until_ready(out._mask)
        assert profiling.counters.get("optimizer.split") == before + 1
        np.testing.assert_array_equal(np.asarray(out._data["c11"]),
                                      ref_col)

    def test_planned_memory_chunking_from_history(self):
        config.optimizer_enabled = True
        config.optimizer_level = 1
        rng = np.random.default_rng(1)
        f = Frame({"v": rng.normal(size=64)})
        out = f.with_column("d", dq.col("v") * 2.0)
        jax.block_until_ready(out._mask)
        ref = np.asarray(out._data["d"])
        entries = compiler.cache_stats()["entries"]
        assert len(entries) == 1
        key = entries[0]["program_key"]
        # remembered peak far over the budget, static estimate far under
        statstore.STORE.record_flush(key, "pipeline", est_bytes=1 << 40)
        config.audit_device_budget = 1 << 20
        before = profiling.counters.get("pipeline.oom_chunked")
        mem0 = profiling.counters.get("optimizer.mem_chunk")
        out2 = f.with_column("d", dq.col("v") * 2.0)
        jax.block_until_ready(out2._mask)
        assert profiling.counters.get("optimizer.mem_chunk") == mem0 + 1
        assert profiling.counters.get("pipeline.oom_chunked") == before + 1
        np.testing.assert_array_equal(np.asarray(out2._data["d"]), ref)

    def test_grouped_dense_skip_from_miss_history(self):
        config.optimizer_enabled = True
        rng = np.random.default_rng(2)
        vals = rng.normal(size=32)

        # key range 0..1e9 overflows the dense table at 32 rows -> the
        # dense attempt misses and reroutes; two misses teach the skip
        def grouped():
            f = Frame({"k": np.asarray([0.0, 1e9] * 16), "v": vals})
            return f.group_by("k").agg({"v": "sum"}).to_pydict()

        ref = grouped()
        grouped()
        before = profiling.counters.get("optimizer.dense_skip")
        miss0 = profiling.counters.get("grouped.dense_miss")
        got = grouped()
        assert profiling.counters.get("optimizer.dense_skip") == before + 1
        assert profiling.counters.get("grouped.dense_miss") == miss0
        _assert_exact(ref, got)


# ---------------------------------------------------------------------------
# Cost-model glue (statstore satellites)
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_digest_p50_p90(self):
        d = Digest()
        assert d.p50() is None and d.p90() is None
        for v in (0.2, 0.2, 4.0, 90.0):
            d.observe(v)
        assert d.p50() == d.quantile(0.5)
        assert d.p90() == d.quantile(0.9)
        assert d.p50() <= d.p90()

    def test_report_reads_the_same_accessors(self):
        store = statstore.StatStore()
        store.record_flush("k1", "pipeline", wall_ms=3.0)
        store.record_flush("k1", "pipeline", wall_ms=40.0, compiled=True)
        row = store.report(drain=False)["entries"][0]
        with store._lock:
            ks = store._entries["k1"]
            assert row["wall_ms_p50"] == ks.wall_ms.p50()
            assert row["wall_ms_p90"] == ks.wall_ms.p90()
            assert row["compile_ms_p50"] == ks.compile_ms.p50()
        assert store.compile_ms_p50("k1") == row["compile_ms_p50"]
        assert store.wall_ms_p50("k1") == row["wall_ms_p50"]

    def test_bytes_bound_and_miss_count(self):
        store = statstore.StatStore()
        assert store.bytes_bound("nope") is None
        store.record_flush("k1", "pipeline", est_bytes=100,
                           peak_bytes=900)
        assert store.bytes_bound("k1") == 900
        assert store.miss_count("g") == 0
        store.record_miss("g")
        store.record_miss("g")
        assert store.miss_count("g") == 2


# ---------------------------------------------------------------------------
# est_rows through With/SetOps wrappers (satellite)
# ---------------------------------------------------------------------------


class TestEstRowsWrappers:
    def test_cte_scan_resolves_body_estimate(self, session):
        _register(session)
        # teach the store the filter's selectivity, then EXPLAIN a CTE
        _exec(session, "SELECT v FROM big WHERE v < -1.0")
        statstore.STORE.drain_pending()
        plan = _exec(
            session,
            "EXPLAIN WITH c AS (SELECT v FROM big WHERE v < -1.0) "
            "SELECT v FROM c LIMIT 5")["plan"][0]
        scan_line = next(ln for ln in plan.splitlines()
                         if "Scan[c]" in ln)
        assert "est_rows=-" not in scan_line
        assert "est_rows=" in scan_line
        with_line = next(ln for ln in plan.splitlines()
                         if ln.startswith("With["))
        assert "est_rows=5" in with_line        # LIMIT bound propagated

    def test_setops_branches_annotated(self, session):
        _register(session)
        plan = _exec(
            session,
            "EXPLAIN SELECT v FROM big UNION ALL "
            "SELECT w FROM small")["plan"][0]
        setops_line = next(ln for ln in plan.splitlines()
                           if ln.startswith("SetOps["))
        assert "est_rows=4064" in setops_line   # 4000 + 64, static slots


# ---------------------------------------------------------------------------
# Sharded-mode parity on the join paths (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the conftest's 8 forced host devices")
class TestShardedParity:
    @contextlib.contextmanager
    def _sharding(self, min_rows=8):
        saved = (config.shard_enabled, config.shard_min_rows,
                 config.shard_devices)
        config.shard_enabled = True
        config.shard_min_rows = min_rows
        config.shard_devices = 0
        shard.configure(pmesh.make_mesh())
        try:
            yield
        finally:
            (config.shard_enabled, config.shard_min_rows,
             config.shard_devices) = saved
            shard.reset()

    def test_sharded_join_pushdown_parity(self, session):
        with self._sharding():
            _register(session, shard_frames=True)
            off, on = _pair(
                session,
                "SELECT k, v, u FROM big JOIN mid USING (k) "
                "WHERE v < -0.5")
            _assert_exact(off, on)

    def test_sharded_join_reorder_parity(self, session):
        with self._sharding():
            _register(session, shard_frames=True)
            off, on = _pair(
                session,
                "SELECT v, u, w FROM big JOIN mid USING (k) "
                "JOIN small USING (k) WHERE v < 0", level=2)
            _assert_sorted(off, on)
