"""Sharded frames (ISSUE 13): row-partitioned Frame/SQL execution.

The acceptance surface of the sharded-frames refactor:

* **bit-parity** — the full compilable-op sweep over masked rows must
  produce BIT-identical results with ``spark.shard.enabled`` on vs off
  (the elementwise shard_map lowering makes this a construction
  property), across 2/4/8 forced host devices and the edge shapes
  (all-masked, one-row-per-shard, rows < devices);
* **structural pins on CPU** — one fused program per flush with ZERO
  counted host syncs, grouped aggregation = ONE sync, collect = ONE
  sync, steady-state cache replay = zero new compiles, sharded and
  single-device plans coexisting in one cache;
* **degradation ladders** — ``shard_flush`` (device fault → gather to
  single-device → eager replay) and ``shard_merge`` (fault in the merge
  collective → gather) keep results correct under injected chaos;
* **integration** — session conf save/restore, sharded ingest hand-off,
  EXPLAIN's ``ShardedStage``/``Exchange`` operators, statstore keys,
  program-audit handles (mesh + guard declared), the fit-packing
  pass-through, serving under concurrency, and the bench-regression
  gate recognizing the ``sharded`` section.

The golden workload (dataset-abstract: count 24 / RMSE 2.809940;
dataset-full: RMSE 1.805140) is pinned with sharding ON.
"""

import contextlib
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.ops import compiler
from sparkdq4ml_tpu.ops import expressions as E
from sparkdq4ml_tpu.ops import segments
from sparkdq4ml_tpu.parallel import mesh as pmesh
from sparkdq4ml_tpu.parallel import shard
from sparkdq4ml_tpu.utils import faults, profiling
from sparkdq4ml_tpu.utils.recovery import RECOVERY_LOG

DATA_DIR = os.path.join(os.path.dirname(__file__), "..", "data")

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest's 8 forced host devices")


@contextlib.contextmanager
def sharding(min_rows=8, devices=0):
    """Enable the shard context over the forced-host-device mesh for one
    test block, with full save/restore (the session-free equivalent of
    ``spark.shard.*`` conf)."""
    saved = (config.shard_enabled, config.shard_min_rows,
             config.shard_devices)
    config.shard_enabled = True
    config.shard_min_rows = min_rows
    config.shard_devices = devices
    shard.configure(pmesh.make_mesh())
    try:
        yield
    finally:
        (config.shard_enabled, config.shard_min_rows,
         config.shard_devices) = saved
        shard.reset()


def _frame(n=100, seed=0, with_nan=True, mask_frac=0.3):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    if with_nan and n:
        a[rng.integers(0, n, max(n // 7, 1))] = np.nan
    cols = {
        "a": a,
        "b": rng.integers(-5, 9, n).astype(np.int64),
        "c": rng.uniform(0.1, 10.0, n),
        "flag": rng.integers(0, 2, n).astype(bool),
    }
    f = Frame(cols)
    if mask_frac and n:
        keep = jnp.asarray(rng.random(n) >= mask_frac)
        f = f._with(mask=jnp.logical_and(f._mask, keep))
    return f


def _eq(a: dict, b: dict):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]),
                                      err_msg=f"column {k!r}")


#: The compilable-op sweep: every family the pipeline compiler defers.
SWEEP = [
    ("arith", lambda f: f.with_column("o", E.col("a") * 2.5 + E.col("c"))),
    ("div_mod", lambda f: f.with_column("o", E.col("c") / 3.0)
        .with_column("p", E.col("b") % 4)),
    ("cmp_filter", lambda f: f.filter(E.col("a") > 0.1)),
    ("bool_ops", lambda f: f.filter((E.col("c") > 1.0) & ~E.col("flag")
                                    | (E.col("b") == 2))),
    ("neg_cast", lambda f: f.with_column("o", (-E.col("a")).cast("int"))),
    ("isnull", lambda f: f.with_column("o", E.col("a").is_null())),
    ("case_when", lambda f: f.with_column(
        "o", E.when(E.col("a") > 0, E.col("c")).otherwise(E.col("b")))),
    ("isin", lambda f: f.filter(E.col("b").isin(1, 2, 5))),
    ("funcs", lambda f: f.with_column("o", E.Func("sqrt", [E.col("c")]))
        .with_column("p", E.Func("pow", [E.col("c"), E.Lit(2)]))),
    ("with_columns", lambda f: f.with_columns(
        {"o": E.col("a") + 1, "a": E.col("a") * 0.0})),
    ("chain20", lambda f: _chain20(f)),
    ("fused_select", lambda f: f.filter(E.col("c") > 0.5).select(
        (E.col("c") * 2).alias("o"), (E.col("b") + 1).alias("p"))),
]


def _chain20(f):
    for i in range(10):
        f = f.with_column(f"x{i}", E.col("c") * float(i + 1) - 0.5)
        f = f.filter(E.col(f"x{i}") > float(-10 - i))
    return f


class TestBitParity:
    @pytest.mark.parametrize("name,op", SWEEP, ids=[n for n, _ in SWEEP])
    def test_sweep_bit_identical(self, name, op):
        f = _frame()
        ref = op(f).to_pydict()
        with sharding():
            out = op(shard.shard_frame(f)).to_pydict()
        _eq(ref, out)

    @pytest.mark.parametrize("devices", [2, 4, 8])
    def test_device_counts(self, devices):
        f = _frame(seed=3)
        ref = _chain20(f).to_pydict()
        with sharding(devices=devices):
            g = shard.shard_frame(f)
            assert g._shard.devices == devices
            _eq(ref, _chain20(g).to_pydict())

    def test_edge_shapes(self):
        with sharding(min_rows=1):
            # all-masked
            f = _frame(32, seed=5)
            f = f._with(mask=jnp.zeros((f.num_slots,), jnp.bool_))
            ref = _chain20(f).to_pydict()
            _eq(ref, _chain20(shard.shard_frame(f)).to_pydict())
            # rows < devices
            f3 = _frame(3, seed=6, mask_frac=0.0)
            _eq(_chain20(f3).to_pydict(),
                _chain20(shard.shard_frame(f3)).to_pydict())

    def test_one_row_per_shard(self):
        saved = config.pipeline_min_bucket
        config.pipeline_min_bucket = 1
        try:
            with sharding(min_rows=1):
                f = _frame(8, seed=7, mask_frac=0.0)
                g = shard.shard_frame(f)
                assert g._shard.bucket == 1 and g.num_slots == 8
                _eq(_chain20(f).to_pydict(), _chain20(g).to_pydict())
        finally:
            config.pipeline_min_bucket = saved

    def test_empty_frame_never_shards(self):
        with sharding(min_rows=1):
            f = Frame({"a": np.asarray([], np.float64)})
            assert shard.maybe_shard_frame(f) is f

    def test_below_min_rows_never_shards(self):
        with sharding(min_rows=1000):
            f = _frame(50)
            assert shard.maybe_shard_frame(f) is f

    def test_raw_column_at_true_row_count_places(self):
        with sharding():
            f = _frame(40, mask_frac=0.0)
            g = shard.shard_frame(f)
            vals = np.arange(40, dtype=np.float64)
            out = g.with_column("raw", vals)
            ref = f.with_column("raw", vals)
            _eq(ref.to_pydict(), out.to_pydict())


class TestStructuralPins:
    def test_flush_zero_host_syncs_and_one_program(self):
        with sharding():
            g = shard.shard_frame(_frame(200, seed=9))
            g = _chain20(g)
            before_sync = profiling.counters.get("frame.host_sync")
            before_flush = profiling.counters.get("pipeline.flush")
            jax.block_until_ready(g._mask)          # forces the flush
            assert profiling.counters.get("frame.host_sync") \
                == before_sync
            assert profiling.counters.get("pipeline.flush") \
                == before_flush + 1                  # ONE fused program

    def test_collect_is_one_sync(self):
        with sharding():
            g = shard.shard_frame(_frame(64, seed=10))
            g._mask                                  # settle pending
            before = profiling.counters.get("frame.host_sync")
            g.to_pydict()
            assert profiling.counters.get("frame.host_sync") == before + 1

    def test_grouped_is_one_sync(self):
        with sharding():
            g = shard.shard_frame(_frame(128, seed=11))
            g._mask
            before = profiling.counters.get("frame.host_sync")
            g.group_by("b").agg({"c": "sum"})
            assert profiling.counters.get("frame.host_sync") == before + 1

    def test_cache_replay_zero_new_compiles(self):
        with sharding():
            g1 = shard.shard_frame(_frame(77, seed=12))
            _chain20(g1).to_pydict()
            before = profiling.counters.get("pipeline.compile")
            g2 = shard.shard_frame(_frame(77, seed=13))
            _chain20(g2).to_pydict()
            assert profiling.counters.get("pipeline.compile") == before

    def test_sharded_and_single_plans_coexist(self):
        compiler.clear_cache()
        f = _frame(66, seed=14)
        step = lambda fr: fr.with_column("o", E.col("c") * 7.0)  # noqa: E731
        step(f).to_pydict()
        with sharding():
            step(shard.shard_frame(f)).to_pydict()
        keys = [e["program_key"] for e in compiler.cache_stats()["entries"]]
        tagged = [k for k in keys if k.startswith("shard[")]
        plain = [k for k in keys if not k.startswith("shard[")]
        assert tagged and plain
        # and the single-device plan still replays cleanly
        before = profiling.counters.get("pipeline.compile")
        step(f._with()).to_pydict()
        assert profiling.counters.get("pipeline.compile") == before

    def test_sharded_layout_in_explain_string(self):
        with sharding():
            g = shard.shard_frame(_frame(40, mask_frac=0.0))
            text = g.explain_string()
            assert "row-sharded over 8 device(s)" in text


class TestGroupedSharded:
    def _cmp(self, ref, out, int_cols=()):
        assert set(ref) == set(out)
        for k in ref:
            r, o = np.asarray(ref[k]), np.asarray(out[k])
            if k in int_cols or r.dtype.kind in "iub":
                np.testing.assert_array_equal(r, o, err_msg=k)
            else:
                np.testing.assert_allclose(r, o, rtol=1e-9, atol=1e-12,
                                           equal_nan=True, err_msg=k)

    def test_full_agg_family_parity(self):
        f = _frame(300, seed=20)
        aggs = {"a": "avg", "c": "sum"}
        ref = f.group_by("b").agg(aggs).to_pydict()
        with sharding():
            out = shard.shard_frame(f).group_by("b").agg(aggs).to_pydict()
        self._cmp(ref, out)

    @pytest.mark.parametrize("fn", ["count", "sum", "avg", "min", "max",
                                    "variance", "stddev", "var_pop",
                                    "stddev_pop"])
    def test_each_fn(self, fn):
        f = _frame(200, seed=21)
        ref = f.group_by("b").agg({"a": fn, "c": fn}).to_pydict()
        with sharding():
            out = shard.shard_frame(f).group_by("b") \
                .agg({"a": fn, "c": fn}).to_pydict()
        self._cmp(ref, out)

    def test_int_sums_exact(self):
        f = _frame(500, seed=22)
        ref = f.group_by("flag").agg({"b": "sum"}).to_pydict()
        with sharding():
            out = shard.shard_frame(f).group_by("flag") \
                .agg({"b": "sum"}).to_pydict()
        self._cmp(ref, out, int_cols=("sum(b)",))

    def test_float_keys_with_nulls(self):
        f = _frame(150, seed=23)
        ref = f.group_by("a").count().to_pydict()
        with sharding():
            out = shard.shard_frame(f).group_by("a").count().to_pydict()
        self._cmp(ref, out)

    def test_unsupported_aggs_gather_and_stay_correct(self):
        f = _frame(120, seed=24)
        for aggs in ({"c": "first"}, {"b": "count_distinct"}):
            ref = f.group_by("flag").agg(aggs).to_pydict()
            with sharding():
                out = shard.shard_frame(f).group_by("flag") \
                    .agg(aggs).to_pydict()
            self._cmp(ref, out)

    def test_dense_range_miss_reroutes_correctly(self):
        # huge key spread defeats the dense table → sorted single-device
        rng = np.random.default_rng(25)
        f = Frame({"k": rng.integers(0, 2**40, 90).astype(np.float64),
                   "v": rng.normal(size=90)})
        ref = f.group_by("k").agg({"v": "sum"}).to_pydict()
        with sharding():
            before = profiling.counters.get("grouped.dense_miss")
            out = shard.shard_frame(f).group_by("k") \
                .agg({"v": "sum"}).to_pydict()
            assert profiling.counters.get("grouped.dense_miss") > before
        self._cmp(ref, out)

    def test_distinct_parity_and_order(self):
        f = _frame(140, seed=26)
        ref = f.select("b", "flag").distinct().to_pydict()
        with sharding():
            out = shard.shard_frame(f).select("b", "flag") \
                .distinct().to_pydict()
        _eq(ref, out)

    def test_drop_duplicates_parity(self):
        f = _frame(90, seed=27)
        ref = f.drop_duplicates(["b"]).to_pydict()
        with sharding():
            out = shard.shard_frame(f).drop_duplicates(["b"]).to_pydict()
        _eq(ref, out)

    def test_sort_parity(self):
        f = _frame(80, seed=28)
        ref = f.sort("a", "b").to_pydict()
        with sharding():
            out = shard.shard_frame(f).sort("a", "b").to_pydict()
        _eq(ref, out)


class TestJoinSharded:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer",
                                     "left_semi", "left_anti"])
    def test_parity(self, how):
        rng = np.random.default_rng(30)
        l = Frame({"k": rng.integers(0, 12, 70).astype(np.float64),
                   "v": rng.normal(size=70)})
        r = Frame({"k": rng.integers(0, 15, 50).astype(np.float64),
                   "w": rng.normal(size=50)})
        ref = l.join(r, "k", how).to_pydict()
        with sharding():
            before = profiling.counters.get("shard.join_partitioned")
            out = shard.shard_frame(l).join(shard.shard_frame(r),
                                            "k", how).to_pydict()
            assert profiling.counters.get("shard.join_partitioned") \
                == before + 1
        _eq(ref, out)

    def test_multi_key_and_nan_keys(self):
        rng = np.random.default_rng(31)
        k1 = rng.integers(0, 5, 60).astype(np.float64)
        k1[::9] = np.nan
        l = Frame({"k1": k1, "k2": rng.integers(0, 3, 60).astype(np.float64),
                   "v": rng.normal(size=60)})
        r = Frame({"k1": k1[:40].copy(), "k2": rng.integers(0, 3, 40)
                   .astype(np.float64), "w": rng.normal(size=40)})
        ref = l.join(r, ["k1", "k2"], "inner").to_pydict()
        with sharding():
            out = shard.shard_frame(l).join(shard.shard_frame(r),
                                            ["k1", "k2"],
                                            "inner").to_pydict()
        _eq(ref, out)

    def test_below_min_rows_host_fallback(self):
        rng = np.random.default_rng(32)
        l = Frame({"k": rng.integers(0, 5, 30).astype(np.float64)})
        r = Frame({"k": rng.integers(0, 5, 20).astype(np.float64)})
        ref = l.join(r, "k", "inner").to_pydict()
        with sharding(min_rows=8):
            ls, rs = shard.shard_frame(l), shard.shard_frame(r)
            config.shard_min_rows = 10_000   # join below the bound
            before = profiling.counters.get("shard.join_partitioned")
            out = ls.join(rs, "k", "inner").to_pydict()
            assert profiling.counters.get("shard.join_partitioned") \
                == before
        _eq(ref, out)


class TestLadders:
    def test_shard_flush_device_error_recovers(self):
        f = _frame(100, seed=40)
        ref = _chain20(f).to_pydict()
        with sharding():
            g = shard.shard_frame(f)
            with faults.inject_faults("shard_flush:device_error:1",
                                      seed=3) as plan:
                out = _chain20(g).to_pydict()
            assert plan.fired
        _eq(ref, out)

    def test_persistent_fault_gathers_and_degrades(self):
        f = _frame(100, seed=41)
        ref = _chain20(f).to_pydict()
        RECOVERY_LOG.clear()
        with sharding():
            g = _chain20(shard.shard_frame(f))
            with faults.inject_faults(
                    "shard_flush:device_error:1,2,3,4,5,6,7,8", seed=3):
                out = g.to_pydict()
            ev = RECOVERY_LOG.events(site="shard_flush",
                                     action="fallback")
            assert ev and ev[-1].rung == "gather"
            assert g._shard is None          # layout degraded, data safe
        _eq(ref, out)

    def test_shard_merge_fault_gathers(self):
        f = _frame(100, seed=42)
        ref = f.group_by("b").agg({"c": "sum"}).to_pydict()
        RECOVERY_LOG.clear()
        with sharding():
            g = shard.shard_frame(f)
            before = profiling.counters.get("grouped.shard_gather")
            with faults.inject_faults("shard_merge:device_error:1",
                                      seed=3) as plan:
                out = g.group_by("b").agg({"c": "sum"}).to_pydict()
            assert plan.fired
            assert profiling.counters.get("grouped.shard_gather") \
                == before + 1
        for k in ref:
            np.testing.assert_allclose(np.asarray(ref[k]),
                                       np.asarray(out[k]), rtol=1e-9)

    def test_distinct_merge_fault_gathers(self):
        f = _frame(100, seed=43)
        ref = f.select("b").distinct().to_pydict()
        with sharding():
            g = shard.shard_frame(f)
            with faults.inject_faults("shard_merge:device_error:1",
                                      seed=3) as plan:
                out = g.select("b").distinct().to_pydict()
            assert plan.fired
        _eq(ref, out)

    def test_oom_budget_degrades_to_chunked(self):
        f = _frame(200, seed=44)
        ref = _chain20(f).to_pydict()
        RECOVERY_LOG.clear()
        with sharding():
            g = _chain20(shard.shard_frame(f))
            before = profiling.counters.get("pipeline.oom_chunked")
            with faults.inject_faults("oom:oom:1:n=64", seed=3):
                out = g.to_pydict()
            assert profiling.counters.get("pipeline.oom_chunked") \
                == before + 1
            ev = RECOVERY_LOG.events(site="shard_flush",
                                     action="fallback")
            assert ev and ev[-1].rung == "chunked"
        _eq(ref, out)

    def test_nan_corruption_arm_still_validates(self):
        f = _frame(100, seed=45, with_nan=False, mask_frac=0.0)
        ref = f.with_column("o", E.col("c") * 2).to_pydict()
        with sharding():
            g = shard.shard_frame(f)
            with faults.inject_faults("pipeline_flush:nan:1", seed=5):
                out = g.with_column("o", E.col("c") * 2).to_pydict()
        _eq(ref, out)


class TestSessionConfAndIngest:
    def _session(self, **extra):
        import sparkdq4ml_tpu as dq

        b = (dq.TpuSession.builder().app_name("shard-test")
             .master("local[*]")
             .config("spark.shard.enabled", "true")
             .config("spark.shard.minRows", "8"))
        for k, v in extra.items():
            b = b.config(k, v)
        return b.get_or_create()

    def test_conf_applies_and_stop_restores(self):
        prev = (config.shard_enabled, config.shard_min_rows)
        s = self._session()
        try:
            assert config.shard_enabled is True
            assert config.shard_min_rows == 8
            assert shard.active_mesh() is not None
        finally:
            s.stop()
        assert (config.shard_enabled, config.shard_min_rows) == prev
        assert shard.active_mesh() is None

    def test_read_csv_lands_sharded_and_explain_renders(self):
        import sparkdq4ml_tpu as dq

        s = self._session()
        try:
            dq.register_builtin_rules()
            df = (s.read.format("csv").option("inferSchema", "true")
                  .load(os.path.join(DATA_DIR, "dataset-abstract.csv")))
            assert df._shard is not None
            assert df._shard.devices == 8
            df.create_or_replace_temp_view("prices")
            plan = s.sql("EXPLAIN SELECT _c1 p FROM prices "
                         "WHERE _c1 > 0").to_pydict()["plan"][0]
            assert "ShardedStage[8]" in plan
            assert "rows_per_shard" in plan
            agg_plan = s.sql(
                "EXPLAIN SELECT _c0, count(*) c FROM prices "
                "GROUP BY _c0").to_pydict()["plan"][0]
            assert "Exchange[merge:psum]" in agg_plan
        finally:
            s.stop()

    def test_golden_workload_sharded(self):
        import sparkdq4ml_tpu as dq
        from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler

        s = self._session()
        try:
            dq.register_builtin_rules()
            df = (s.read.format("csv").option("inferSchema", "true")
                  .load(os.path.join(DATA_DIR, "dataset-abstract.csv")))
            df = df.with_column_renamed("_c0", "guest") \
                   .with_column_renamed("_c1", "price")
            df = df.with_column(
                "price_no_min",
                dq.call_udf("minimumPriceRule", dq.col("price")))
            df.create_or_replace_temp_view("price")
            df = s.sql("SELECT cast(guest as int) guest, price_no_min AS "
                       "price FROM price WHERE price_no_min > 0")
            df = df.with_column(
                "price_correct_correl",
                dq.call_udf("priceCorrelationRule", dq.col("price"),
                            dq.col("guest")))
            df.create_or_replace_temp_view("price")
            df = s.sql("SELECT guest, price_correct_correl AS price "
                       "FROM price WHERE price_correct_correl > 0")
            assert df.count() == 24
            df = df.with_column("label", df.col("price"))
            df = VectorAssembler(["guest"], "features").transform(df)
            model = LinearRegression(max_iter=40, reg_param=1.0,
                                     elastic_net_param=1.0).fit(df)
            assert model.summary.root_mean_squared_error == pytest.approx(
                2.809940, rel=1e-3)
        finally:
            s.stop()

    def test_serving_soak_with_sharding(self):
        """8 concurrent golden queries through the QueryServer with
        sharding active: bounded results, golden numbers, no deadlock
        (the shard execution guard serializes multi-device dispatch)."""
        import sparkdq4ml_tpu as dq
        from sparkdq4ml_tpu.serve import QueryServer

        s = self._session()
        path = os.path.join(DATA_DIR, "dataset-abstract.csv")

        def job(ctx):
            df = (ctx.read.format("csv").option("inferSchema", "true")
                  .load(path))
            ctx.register_view("t", df)
            out = ctx.sql("SELECT count(*) c FROM t WHERE _c1 > 0")
            return int(out.to_pydict()["c"][0])

        try:
            with QueryServer(s, workers=4, metrics_port=0) as srv:
                futs = [srv.submit(job, tenant=f"t{i % 3}")
                        for i in range(8)]
                results = [f.result(timeout=120) for f in futs]
            assert all(r.ok for r in results)
            assert len({r.value for r in results}) == 1
        finally:
            s.stop()


class TestObservatoryAndAudit:
    def test_statstore_records_shard_tagged_key(self):
        from sparkdq4ml_tpu.utils import statstore

        with sharding():
            f = _frame(120, seed=50)
            # a uniquely-NAMED filter column ⇒ a fresh selectivity entry
            # (plan keys carry column names; literals are hoisted)
            f = f._with(data={**f._data, "selbase50": f._data["c"]})
            g = shard.shard_frame(f)
            g.filter(E.col("selbase50") > 1.0)._mask  # one sharded flush
            statstore.STORE.drain_pending()
            rep = statstore.STORE.report(drain=False)
            tagged = [e for e in rep["entries"]
                      if "shard[" in e["key"] and e["kind"] == "pipeline"]
            assert tagged
            # selectivity evidence landed (the deferred per-shard counts)
            sel = [e for e in rep["entries"]
                   if e["kind"] == "filter" and "selbase50" in e["key"]]
            assert sel and sel[0]["sel_observations"] == 1
            # baseline is TRUE rows (120), never the padded slot count
            # (128) — the layout-stripped entry is shared with the
            # single-device twin and must not skew by the padding factor
            assert sel[0]["rows_in"] == 120

    def test_selectivity_key_is_layout_agnostic(self):
        from sparkdq4ml_tpu.utils.statstore import selectivity_key

        plain = "f8/i8|F:B(>,C('c':f8),Lf)"
        assert selectivity_key("shard[8]|" + plain) \
            == selectivity_key(plain)

    def test_program_handles_declare_mesh_and_guard(self):
        from sparkdq4ml_tpu.utils import observability as obs

        compiler.clear_cache()
        segments.clear_cache()
        with sharding():
            g = shard.shard_frame(_frame(64, seed=51))
            g.with_column("o", E.col("c") + 1)._mask
            g.group_by("b").agg({"c": "sum"})
        handles, errors = obs.CACHES.programs()
        assert not errors
        sharded = [h for h in handles
                   if getattr(h.mesh, "devices", None) is not None
                   and h.mesh.devices.size > 1]
        assert sharded, "no sharded ProgramHandle registered"
        assert all(h.guarded for h in sharded)

    def test_audit_collective_detector_clean(self):
        from sparkdq4ml_tpu.analysis.program import detectors as det
        from sparkdq4ml_tpu.utils import observability as obs

        compiler.clear_cache()
        segments.clear_cache()
        with sharding():
            g = shard.shard_frame(_frame(64, seed=52))
            g.group_by("b").agg({"c": "avg"})
            handles, _ = obs.CACHES.programs()
            target = [h for h in handles if "GDH" in h.program_key]
            assert target
            ctx = det.AuditContext.from_config()
            (rule,) = det.get_detectors(["audit-collective"])
            findings = []
            for h in target:
                findings.extend(rule.check(h, ctx))
            assert not findings, [f.message for f in findings]


class TestFitPassthrough:
    def test_place_sharded_consumes_shard_partials(self):
        from sparkdq4ml_tpu.parallel.distributed import place_sharded

        with sharding():
            g = shard.shard_frame(
                Frame({"x": np.arange(64, dtype=np.float64),
                       "y": np.arange(64, dtype=np.float64) * 2}))
            X = jnp.asarray(g._data["x"])[:, None]
            # a 2-D feature matrix in the frame's layout
            X = jax.device_put(X, g._shard.sharding())
            y = jnp.asarray(g._data["y"])
            m = g._mask
            before = profiling.counters.get("shard.fit_passthrough")
            Xo, yo, mo = place_sharded(X, y, m, g._shard.mesh)
            assert profiling.counters.get("shard.fit_passthrough") \
                == before + 1
            assert Xo is X and yo is y and mo is m


class TestBenchGate:
    def test_regress_gate_sees_sharded_metrics(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "cbr", os.path.join(os.path.dirname(__file__), "..",
                                "scripts", "check_bench_regress.py"))
        cbr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cbr)

        def doc(pipe_ms, speedup):
            return {"sharded": {"pipeline": [
                {"config": "pipeline_r1000000_d8", "rows": 1000000,
                 "devices": 8, "pipeline_ms": pipe_ms,
                 "speedup_vs_1dev": speedup}]}}

        old = cbr.flatten_metrics(doc(100.0, 2.0))
        new = cbr.flatten_metrics(doc(200.0, 0.9))
        assert old, "sharded metrics were not recognized"
        regressions = cbr.compare(old, new, 0.15)
        names = {r["metric"] for r in regressions}
        assert any("pipeline_ms" in m for m in names)
        assert any("speedup_vs_1dev" in m for m in names)
        assert cbr.load_bench_doc.__doc__  # module loaded intact

    def test_load_bench_doc_accepts_sharded_only(self, tmp_path):
        import importlib.util
        import json

        spec = importlib.util.spec_from_file_location(
            "cbr2", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "check_bench_regress.py"))
        cbr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cbr)
        p = tmp_path / "BENCH_r99.json"
        p.write_text(json.dumps({"sharded": {"pipeline": []}}))
        assert cbr.load_bench_doc(str(p)) is not None


class TestChaosSmoke:
    @pytest.mark.slow
    def test_five_seed_soak_with_sharding(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "chaos_soak", os.path.join(os.path.dirname(__file__), "..",
                                       "scripts", "chaos_soak.py"))
        soak = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak)
        summary = soak.run_soak(seeds=5, clients=3, queries=1, workers=4)
        assert summary["ok"], summary["failed_seeds"]
