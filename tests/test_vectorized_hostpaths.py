"""Vectorized host-path parity (VERDICT r2 item 6): the numeric-key join
plan and the flattened-numpy text hashing must produce byte-identical
results to the general (dict/loop) implementations they replace.
"""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models.text import (CountVectorizer, HashingTF,
                                        _obj_array, _stable_hash)


def _join_frames(seed=0, n=500, dup=True):
    """Numeric- and string-keyed variants of the SAME logical join input:
    the string variant forces the dict fallback, so result parity proves
    the vectorized plan emits identical (order included) pairs."""
    rng = np.random.default_rng(seed)
    lk = rng.integers(0, 40 if dup else 10**6, size=n)
    rk = rng.integers(0, 40 if dup else 10**6, size=int(n * 0.8))
    a = rng.normal(size=n)
    b = rng.normal(size=rk.size)
    num = (Frame({"k": lk, "a": a}), Frame({"k": rk, "b": b}))
    s = (Frame({"k": np.asarray([f"id{v:07d}" for v in lk], object), "a": a}),
         Frame({"k": np.asarray([f"id{v:07d}" for v in rk], object), "b": b}))
    return num, s


JOIN_TYPES = ["inner", "left", "right", "outer", "left_semi", "left_anti"]


class TestVectorJoinParity:
    @pytest.mark.parametrize("how", JOIN_TYPES)
    def test_matches_dict_path(self, how):
        (ln, rn), (ls, rs) = _join_frames()
        dv = ln.join(rn, "k", how).to_pydict()
        ds = ls.join(rs, "k", how).to_pydict()
        assert len(dv["a"]) == len(ds["a"])
        np.testing.assert_allclose(np.asarray(dv["a"], np.float64),
                                   np.asarray(ds["a"], np.float64),
                                   equal_nan=True)
        if how not in ("left_semi", "left_anti"):
            np.testing.assert_allclose(np.asarray(dv["b"], np.float64),
                                       np.asarray(ds["b"], np.float64),
                                       equal_nan=True)

    @pytest.mark.parametrize("how", JOIN_TYPES)
    def test_multi_key_matches_dict_path(self, how):
        rng = np.random.default_rng(3)
        n = 400
        lk1 = rng.integers(0, 12, size=n)
        lk2 = rng.integers(0, 6, size=n)
        rk1 = rng.integers(0, 12, size=n)
        rk2 = rng.integers(0, 6, size=n)
        a = rng.normal(size=n)
        b = rng.normal(size=n)
        sfy = lambda k1, k2: (
            np.asarray([f"a{v}" for v in k1], object),
            np.asarray([f"b{v}" for v in k2], object))
        ls1, ls2 = sfy(lk1, lk2)
        rs1, rs2 = sfy(rk1, rk2)
        dv = Frame({"k1": lk1, "k2": lk2, "a": a}).join(
            Frame({"k1": rk1, "k2": rk2, "b": b}), ["k1", "k2"],
            how).to_pydict()
        ds = Frame({"k1": ls1, "k2": ls2, "a": a}).join(
            Frame({"k1": rs1, "k2": rs2, "b": b}), ["k1", "k2"],
            how).to_pydict()
        np.testing.assert_allclose(np.asarray(dv["a"], np.float64),
                                   np.asarray(ds["a"], np.float64),
                                   equal_nan=True)

    def test_nan_keys_fall_back(self):
        """Float keys containing NaN must take the dict path (NaN != NaN)."""
        l = Frame({"k": np.asarray([1.0, np.nan, 2.0]),
                   "a": np.asarray([1.0, 2.0, 3.0])})
        r = Frame({"k": np.asarray([np.nan, 2.0]),
                   "b": np.asarray([10.0, 20.0])})
        out = l.join(r, "k", "inner").to_pydict()
        # dict semantics: the NaN rows never match (distinct float objects)
        assert list(np.asarray(out["k"], np.float64)) == [2.0]

    def test_huge_int_keys_fall_back_correctly(self):
        """int64 keys beyond 2^53 can't round-trip float64 — dict path."""
        big = np.asarray([2**60 + 1, 2**60 + 2], np.int64)
        l = Frame({"k": big, "a": np.asarray([1.0, 2.0])})
        r = Frame({"k": big[::-1].copy(), "b": np.asarray([10.0, 20.0])})
        out = l.join(r, "k", "inner").to_pydict()
        assert sorted(np.asarray(out["b"], np.float64)) == [10.0, 20.0]


class TestVectorTextParity:
    def _docs(self, n=300, seed=0, with_none=True):
        rng = np.random.default_rng(seed)
        words = [f"w{i}" for i in range(50)]
        docs = [list(np.asarray(words)[rng.integers(0, 50,
                                                    size=rng.integers(0, 9))])
                for _ in range(n)]
        if with_none:
            docs[5] = None
            docs[17] = []
        return Frame({"toks": _obj_array(docs)}), docs

    def test_hashing_tf_matches_naive(self):
        f, docs = self._docs()
        for binary in (False, True):
            tf = HashingTF(num_features=37, input_col="toks",
                           output_col="tf", binary=binary)
            M = np.asarray(tf.transform(f).to_pydict()["tf"], np.float64)
            ref = np.zeros_like(M)
            for i, toks in enumerate(docs):
                for t in toks or []:
                    j = _stable_hash(t, 37)
                    ref[i, j] = 1.0 if binary else ref[i, j] + 1.0
            np.testing.assert_array_equal(M, ref)

    @pytest.mark.parametrize("min_df,min_tf,binary", [
        (1.0, 1.0, False), (3.0, 2.0, False), (0.05, 0.3, True)])
    def test_count_vectorizer_matches_naive(self, min_df, min_tf, binary):
        f, docs = self._docs(seed=2)
        cv = CountVectorizer(vocab_size=30, min_df=min_df, min_tf=min_tf,
                             binary=binary, input_col="toks",
                             output_col="cnt")
        model = cv.fit(f)
        # naive df
        df = {}
        n_docs = 0
        for toks in docs:
            if toks is None:
                continue
            n_docs += 1
            for t in set(toks):
                df[t] = df.get(t, 0) + 1
        thresh = min_df if min_df >= 1.0 else min_df * n_docs
        terms = sorted(((t, c) for t, c in df.items() if c >= thresh),
                       key=lambda tc: (-tc[1], tc[0]))
        assert model.vocabulary == [t for t, _ in terms[:30]]
        # naive transform
        M = np.asarray(model.transform(f).to_pydict()["cnt"], np.float64)
        idx = {t: i for i, t in enumerate(model.vocabulary)}
        ref = np.zeros_like(M)
        for i, toks in enumerate(docs):
            if toks is None:
                continue
            for t in toks:
                if t in idx:
                    ref[i, idx[t]] += 1.0
            if min_tf >= 1.0:
                ref[i][ref[i] < min_tf] = 0.0
            elif len(toks):
                ref[i][ref[i] / len(toks) < min_tf] = 0.0
            if binary:
                ref[i] = (ref[i] > 0).astype(ref.dtype)
        np.testing.assert_array_equal(M, ref)
