"""Fused expression-pipeline compiler (ops/compiler.py + frame deferral).

Covers the ISSUE-3 acceptance surface:

* eager-vs-fused equivalence property tests over the compilable expression
  op surface (bit-identical results, NaN-aware),
* plan-keyed jit cache reuse: a second identical SQL query and a second
  CSV load of a *different* row count within the same bucket each add
  ZERO new compiles (literal hoisting + shape-bucketed padding),
* golden DQ row counts (40→34→24) and the example-app RMSE with the
  pipeline on vs off,
* ``spark.pipeline.enabled=false`` restores the exact eager path,
* the batched host-sync / honest ``cache()`` satellites,
* a tier-1-safe smoke: fused throughput ≥ eager on a 10-op chain.
"""

import os
import tempfile
import time

import numpy as np
import pytest

from conftest import dataset_path, prepare_features, run_dq_pipeline

pytestmark = pytest.mark.pipeline_compiler
from sparkdq4ml_tpu.config import config
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.ops import compiler
from sparkdq4ml_tpu.ops import expressions as E
from sparkdq4ml_tpu.utils.profiling import counters


@pytest.fixture(autouse=True)
def _fresh_pipeline_state():
    """Each test sees a clean plan cache / counters and pipeline ON."""
    saved = config.pipeline
    config.pipeline = True
    compiler.clear_cache()
    counters.clear("pipeline")
    counters.clear("frame.")
    yield
    config.pipeline = saved
    compiler.clear_cache()


def _eager(fn):
    """Run ``fn`` with the pipeline disabled (the exact legacy path)."""
    config.pipeline = False
    try:
        return fn()
    finally:
        config.pipeline = True


def _frames_equal(a: Frame, b: Frame):
    assert a.columns == b.columns
    da, db = a.to_pydict(), b.to_pydict()
    for name in a.columns:
        va, vb = np.asarray(da[name]), np.asarray(db[name])
        assert va.shape == vb.shape, name
        if va.dtype == object:
            assert list(va) == list(vb), name
        else:
            assert va.dtype == vb.dtype, name
            np.testing.assert_array_equal(va, vb, err_msg=name)


def _base_frame():
    return Frame({
        "price": [10.0, 25.5, 3.0, 95.0, float("nan"), 7.25],
        "guest": [2, 5, 1, 20, 8, 3],
        "flag": [True, False, True, True, False, True],
        "city": ["ny", "sf", None, "la", "ny", "sf"],
    })


# ---------------------------------------------------------------------------
# Eager-vs-fused equivalence over the compilable op surface
# ---------------------------------------------------------------------------

def _op_surface():
    c = E.col
    return [
        ("arith", lambda f: (c("price") * 2.0 + c("guest") - 1.5)),
        ("div_null", lambda f: c("price") / (c("guest") - 2)),   # /0 → NULL
        ("mod", lambda f: c("price") % 4),
        ("neg", lambda f: -c("price")),
        ("cmp_chain", lambda f: (c("price") > 5.0) & (c("guest") <= 8)),
        ("or_not", lambda f: (c("price") < 4) | ~(c("guest") == 5)),
        ("isnull", lambda f: c("price").is_null()),
        ("isnotnull", lambda f: c("price").is_not_null()),
        ("cast_int", lambda f: c("price").cast("int")),
        ("cast_double", lambda f: c("guest").cast("double")),
        ("cast_bool_int", lambda f: c("flag").cast("int")),
        ("between", lambda f: c("price").between(5, 30)),
        ("isin", lambda f: c("guest").isin(1, 5, 20)),
        ("not_isin_null", lambda f: E.InList(
            c("guest"), [E.Lit(1), E.Lit(None)], negated=True)),
        ("case_when", lambda f: E.when(c("price") < 5.0, -1.0)
         .when(c("price") > 90.0, 99.0).otherwise(c("price"))),
        ("case_no_else", lambda f: E.when(c("price") < 5.0, 1.0)),
        ("func_sqrt", lambda f: E.fn("sqrt", c("price"))),
        ("func_pow", lambda f: E.fn("pow", c("guest"), E.Lit(2))),
        ("func_greatest", lambda f: E.fn("greatest", c("price"),
                                         c("guest"))),
        ("func_coalesce", lambda f: E.fn("coalesce", c("price"),
                                         c("guest"))),
        ("func_isnan", lambda f: E.fn("isnan", c("price"))),
        ("func_pmod", lambda f: E.fn("pmod", -c("price"), c("guest"))),
        ("alias", lambda f: (c("price") + 1).alias("bumped")),
    ]


@pytest.mark.parametrize("name,build",
                         _op_surface(), ids=[n for n, _ in _op_surface()])
def test_with_column_eager_fused_equivalence(name, build):
    fused = _base_frame().with_column("out", build(None))
    assert fused._pending, f"{name} did not defer (compilable surface)"
    eager = _eager(lambda: _base_frame().with_column("out", build(None)))
    assert not eager._pending
    _frames_equal(fused, eager)
    # the fused result must come from the COMPILED program, not a silent
    # eager-replay rescue
    assert counters.get("pipeline.fallback") == 0, name


@pytest.mark.parametrize("name,build",
                         _op_surface(), ids=[n for n, _ in _op_surface()])
def test_filter_eager_fused_equivalence(name, build):
    """Every surface expr as a WHERE predicate (numeric → SQL truthiness,
    NULL drops the row — both paths must agree)."""
    fused = _base_frame().filter(build(None))
    eager = _eager(lambda: _base_frame().filter(build(None)))
    assert fused.count() == eager.count(), name
    _frames_equal(fused, eager)


def test_chained_pipeline_equivalence():
    """A realistic 8-op chain: intermediate columns feed later filters."""
    def chain(f):
        f = f.with_column("p2", f["price"] * 2.0)
        f = f.with_column("tier", E.when(E.col("p2") > 50.0, 2.0)
                          .otherwise(1.0))
        f = f.filter(f["price"] > 1.0)
        f = f.with_column("adj", E.col("p2") + E.col("tier"))
        f = f.filter(E.col("adj") < 200.0)
        f = f.with_column("g2", f["guest"].cast("double") / 2)
        return f

    fused = chain(_base_frame())
    assert len(fused._pending) == 6
    eager = _eager(lambda: chain(_base_frame()))
    _frames_equal(fused, eager)
    assert counters.get("pipeline.compile") == 1   # ONE program, 6 ops
    assert counters.get("pipeline.fallback") == 0


def test_with_columns_batch_semantics():
    """withColumns resolves every expr against the INPUT frame (Spark):
    replacing a column and referencing it elsewhere sees the original."""
    def run(f):
        return f.with_columns({"price": f["price"] * 0.0,
                               "orig": f["price"] + 1.0})

    fused = run(_base_frame())
    eager = _eager(lambda: run(_base_frame()))
    _frames_equal(fused, eager)
    assert counters.get("pipeline.fallback") == 0


def test_read_then_replace_column_compiles():
    """A step that READS a column a later step REPLACES must receive the
    base column as a program input (the step-evolved schema), not fall
    back to eager replay — and the base frame's buffer stays intact."""
    f = _base_frame()
    g = f.with_column("p2", E.col("price") * 2.0).with_column(
        "price", E.col("price") + 1.0).filter(E.col("price") > 5.0)
    d = g.to_pydict()
    np.testing.assert_allclose(np.asarray(d["p2"]),
                               np.asarray(d["price"]) * 2 - 2)
    assert counters.get("pipeline.fallback") == 0
    assert counters.get("pipeline.compile") == 1
    # the source frame still sees the ORIGINAL prices
    assert f.to_pydict()["price"][0] == 10.0


def test_non_compilable_exprs_stay_eager():
    f = _base_frame()
    g = f.with_column("up", E.fn("upper", f["city"]))     # host string fn
    assert not g._pending
    h = f.filter(f["city"].like("n%"))                    # host matcher
    assert not h._pending
    r = f.with_column("r", E.RowFunc("rand", 7))          # row generator
    assert not r._pending
    # round: jit would strength-reduce its constant divisor (1-ULP
    # divergence), so it is excluded from the compilable surface
    rd = f.with_column("rd", E.fn("round", f["price"], E.Lit(1)))
    assert not rd._pending
    eager = _eager(
        lambda: _base_frame().with_column(
            "rd", E.fn("round", E.col("price"), E.Lit(1))))
    _frames_equal(rd, eager)


def test_wrong_arity_builtin_raises_at_call_site():
    """hypot(one_arg) must not defer (arity gate) — the eager path
    raises immediately, same as with the pipeline off."""
    f = _base_frame()
    with pytest.raises(TypeError):
        f.with_column("bad", E.Func("hypot", [E.col("price")]))


def test_failed_flush_keeps_pending_and_keeps_raising(monkeypatch):
    """If the compiler bails AND the eager replay raises, the error must
    surface on EVERY read — never a silent revert to the pre-op frame."""
    from sparkdq4ml_tpu.ops import compiler as pc

    f = _base_frame().with_column("x", E.col("price") + 1.0)
    assert f._pending

    def boom(*a, **k):
        raise pc.PipelineError("forced")

    import sparkdq4ml_tpu.frame.frame as frame_mod

    real_replay = frame_mod.Frame._eager_replay

    def bad_replay(self, steps):
        raise RuntimeError("replay exploded")

    monkeypatch.setattr(frame_mod.Frame, "_eager_replay", bad_replay)
    monkeypatch.setattr(pc, "run_pipeline", boom)
    with pytest.raises(RuntimeError, match="replay exploded"):
        f.to_pydict()
    assert f._pending                 # ops NOT silently dropped
    assert "x" in f.columns
    with pytest.raises(RuntimeError, match="replay exploded"):
        f.count()                     # raises consistently, every read
    # restore the replay: the frame recovers and produces the op's result
    monkeypatch.setattr(frame_mod.Frame, "_eager_replay", real_replay)
    assert f.to_pydict()["x"][0] == 11.0


def test_plan_summary_fused_marker_is_honest():
    """FusedStage only prints when the WHERE + projections are
    structurally compilable; string predicates keep Project <- Filter."""
    from sparkdq4ml_tpu.sql.parser import parse, plan_summary

    fused = plan_summary(parse("SELECT a, a+1 b FROM t WHERE a > 1"))
    assert "FusedStage(Project[2] <- Filter)" in fused
    stringy = plan_summary(
        parse("SELECT name FROM t WHERE name LIKE 'x%'"))
    assert "FusedStage" not in stringy
    assert "Project[1] <- Filter" in stringy
    udf = plan_summary(parse("SELECT a FROM t WHERE myudf(a) > 0"))
    assert "FusedStage" not in udf


def test_sibling_frames_share_prefix_safely():
    """Two frames deferring off one parent must not corrupt each other
    (donation only ever touches fresh padded buffers)."""
    f = _base_frame().with_column("p2", E.col("price") * 2.0)
    a = f.filter(E.col("price") > 5.0)
    b = f.filter(E.col("price") > 90.0)
    na, nb = a.count(), b.count()
    assert (na, nb) == (4, 1)
    # the parent (and its base arrays) stay fully usable after both flush
    assert f.count() == 6
    assert _base_frame().count() == 6


def test_mask_composes_with_prior_filters():
    f = _base_frame().filter(E.col("guest") > 1)     # defers
    g = f.filter(E.col("price") < 50.0)              # same program
    eager = _eager(lambda: _base_frame().filter(E.col("guest") > 1)
                   .filter(E.col("price") < 50.0))
    assert g.count() == eager.count()
    _frames_equal(g, eager)


def test_numpy_scalar_literals_stay_eager():
    """np.int64/np.bool_ literals take Lit.eval's host object-array
    branch, so they must not defer (and must not share a plan key with
    the Python-int literal whose eval differs)."""
    from sparkdq4ml_tpu.ops.compiler import is_compilable, schema_of

    f = _base_frame()
    g = f.with_column("x", E.when(f["guest"] > 2, E.Lit(np.int64(5)))
                      .otherwise(E.Lit(np.int64(1))))
    assert not g._pending
    schema = schema_of(f._data_store)
    assert not is_compilable(E.Lit(np.int64(5)), schema)
    assert not is_compilable(E.Lit(np.bool_(True)), schema)
    # np.float64 IS a float subclass and evals on device — it may defer
    assert is_compilable(E.Lit(np.float64(5.0)), schema)


def test_pipeline_conf_is_session_scoped():
    """A session disabling the pipeline must not leave the process on
    the eager path after stop() (same scoping rule as the fault plan)."""
    import sparkdq4ml_tpu as dq

    assert config.pipeline is True
    s = (dq.TpuSession.builder().app_name("scoped")
         .config("spark.pipeline.enabled", "false")
         .config("spark.pipeline.minBucket", 16).get_or_create())
    assert config.pipeline is False
    assert config.pipeline_min_bucket == 16
    s.stop()
    assert config.pipeline is True
    assert config.pipeline_min_bucket == 8


def test_enabled_false_restores_exact_eager_path():
    config.pipeline = False
    f = _base_frame()
    g = f.with_column("x", f["price"] + 1).filter(f["price"] > 5)
    assert not g._pending
    assert counters.get("pipeline.flush") == 0
    assert counters.get("pipeline.compile") == 0


# ---------------------------------------------------------------------------
# Plan key: literal hoisting + shape buckets
# ---------------------------------------------------------------------------

def test_bucket_size_rule():
    assert compiler.bucket_size(1) == config.pipeline_min_bucket
    assert compiler.bucket_size(8) == 8
    assert compiler.bucket_size(9) == 16
    assert compiler.bucket_size(600) == 1024
    assert compiler.bucket_size(1024) == 1024
    assert compiler.bucket_size(1025) == 2048
    # above the exact-shape threshold the bucket IS n (pad+slice copies
    # are O(n) and outweigh an occasional retrace at this scale)
    big = config.pipeline_exact_threshold + 12345
    assert compiler.bucket_size(big) == big


def test_literal_hoisting_shares_one_program():
    """price < 3 and price < 4 (and < 7.5) are ONE compiled program."""
    for threshold in (3.0, 4.0, 7.5):
        f = _base_frame().filter(E.col("price") < threshold)
        f._flush()
    assert counters.get("pipeline.compile") == 1
    assert counters.get("pipeline.hit") == 2
    # ... and the results use the right literal, not the cached one
    assert _base_frame().filter(E.col("price") < 4.0).count() == 1
    assert _base_frame().filter(E.col("price") < 90.0).count() == 4


def test_func_literal_args_hoist_and_share():
    """pow(x, 2) and pow(x, 3) are one compiled program (the exponent is
    a hoisted runtime scalar — also keeps XLA from strength-reducing the
    constant form into a 1-ULP divergence)."""
    for exponent in (2, 3, 5):
        f = _base_frame().with_column(
            "p", E.fn("pow", E.col("guest"), E.Lit(exponent)))
        f._flush()
    assert counters.get("pipeline.compile") == 1
    assert counters.get("pipeline.hit") == 2
    out = _base_frame().with_column(
        "p", E.fn("pow", E.col("guest"), E.Lit(3))).to_pydict()["p"]
    assert out[0] == 8.0


def test_different_lengths_same_bucket_share_one_program():
    def load(n):
        return Frame({"v": np.arange(n, dtype=np.float64)})

    a = load(600).with_column("w", E.col("v") * 3.0)
    a._flush()
    compiles = counters.get("pipeline.compile")
    b = load(700).with_column("w", E.col("v") * 3.0)   # same 1024 bucket
    b._flush()
    assert counters.get("pipeline.compile") == compiles   # 0 new compiles
    assert b.to_pydict()["w"][-1] == 699.0 * 3.0
    c = load(1500).with_column("w", E.col("v") * 3.0)  # 2048: new trace
    c._flush()
    assert counters.get("pipeline.compile") == compiles + 1


def test_dtype_config_flip_is_not_served_stale():
    """`/` bakes float_dtype() into the program; flipping the engine
    float dtype must miss the plan cache, not serve the old dtype."""
    import jax.numpy as jnp

    col = jnp.asarray([1.0, 2.0, 3.0], jnp.float64)
    out64 = Frame({"a": col}).with_column("h", E.col("a") / 2)
    assert np.asarray(out64.to_pydict()["h"]).dtype == np.float64
    saved = config.default_float_dtype
    config.default_float_dtype = jnp.float32
    try:
        out32 = Frame({"a": col}).with_column("h", E.col("a") / 2)
        assert np.asarray(out32.to_pydict()["h"]).dtype == np.float32
    finally:
        config.default_float_dtype = saved


def test_adversarial_column_names_cannot_collide_plan_keys():
    """Names containing the key's own delimiter syntax must not alias a
    structurally different plan (names are repr-escaped in the key)."""
    base = Frame({"b": [1.0, 2.0]})
    first = base.with_column("a", E.col("b")).with_column("c", E.Lit(1.0))
    first._flush()
    evil_name = "a)=C('b':<f8)|W(c"
    evil = base.with_column(evil_name, E.Lit(1.0))
    evil._flush()
    assert counters.get("pipeline.compile") == 2      # distinct plans
    assert evil.columns == ["b", evil_name]
    assert np.asarray(evil._data[evil_name]).tolist() == [1.0, 1.0]


def test_structural_mismatch_recompiles():
    _base_frame().filter(E.col("price") < 3.0)._flush()
    _base_frame().filter(E.col("price") <= 3.0)._flush()   # different op
    assert counters.get("pipeline.compile") == 2


# ---------------------------------------------------------------------------
# SQL wiring: repeated queries are cache hits
# ---------------------------------------------------------------------------

def _sql_frame(session, n, name="t"):
    rng = np.random.default_rng(3)
    Frame({"guest": rng.integers(1, 40, n).astype(np.float64),
           "price": rng.uniform(1.0, 120.0, n)}
          ).create_or_replace_temp_view(name)


def test_second_identical_sql_query_adds_zero_compiles(session):
    _sql_frame(session, 600)
    q = ("SELECT cast(guest as int) guest, price * 2 AS p2 "
         "FROM t WHERE price > 50")
    first = session.sql(q)
    first.count()
    compiles = counters.get("pipeline.compile")
    assert compiles >= 1
    second = session.sql(q)
    second.count()
    assert counters.get("pipeline.compile") == compiles   # pure cache hit
    assert first.count() == second.count()


def test_second_csv_of_different_length_adds_zero_compiles(session):
    """The two-loads scenario from the issue: different row counts within
    one padding bucket replay the same compiled plan."""
    def write_csv(n):
        rng = np.random.default_rng(n)
        fd, path = tempfile.mkstemp(suffix=".csv")
        with os.fdopen(fd, "w") as fh:
            for _ in range(n):
                fh.write(f"{rng.integers(1, 40)},"
                         f"{rng.uniform(1.0, 120.0):.2f}\n")
        return path

    q = ("SELECT cast(_c0 as int) guest, _c1 * 1.1 AS price "
         "FROM v WHERE _c1 > 20")
    paths = [write_csv(520), write_csv(760)]     # both bucket 1024
    try:
        df = (session.read.format("csv").option("inferSchema", "true")
              .load(paths[0]))
        df.create_or_replace_temp_view("v")
        session.sql(q).count()
        compiles = counters.get("pipeline.compile")
        df2 = (session.read.format("csv").option("inferSchema", "true")
               .load(paths[1]))
        df2.create_or_replace_temp_view("v")
        session.sql(q).count()
        assert counters.get("pipeline.compile") == compiles
    finally:
        for p in paths:
            os.remove(p)


def test_sql_results_identical_pipeline_on_off(session):
    _sql_frame(session, 300)
    q = ("SELECT guest, price / 2 AS half, price * guest AS tot "
         "FROM t WHERE price > 30 AND guest < 35")
    on = session.sql(q)
    off = _eager(lambda: session.sql(q))
    _frames_equal(on, off)


# ---------------------------------------------------------------------------
# Golden regression gates: DQ row counts + example-app RMSE, on and off
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("enabled", [True, False],
                         ids=["pipeline_on", "pipeline_off"])
def test_golden_dq_counts_and_rmse(session, enabled):
    from sparkdq4ml_tpu.models import LinearRegression

    config.pipeline = enabled
    df = run_dq_pipeline(session, dataset_path("abstract"))
    assert df.count() == 24
    df = prepare_features(df)
    model = (LinearRegression().setMaxIter(40).setRegParam(1)
             .setElasticNetParam(1)).fit(df)
    assert model.summary.root_mean_squared_error == pytest.approx(
        2.809940, abs=1e-4)


# ---------------------------------------------------------------------------
# Satellites: batched host sync, honest cache(), counters
# ---------------------------------------------------------------------------

def test_to_pydict_is_one_batched_sync():
    f = _base_frame()
    f.count()                       # materialize everything first
    counters.clear("frame.host_sync")
    f.to_pydict()
    assert counters.get("frame.host_sync") == 1      # mask + columns batch


def test_show_limited_sync_count():
    f = _base_frame()
    f.count()
    counters.clear("frame.host_sync")
    f.show_string(2)
    # total count (1 mask pull) + limited to_pydict (mask + column batch)
    assert counters.get("frame.host_sync") <= 3


def test_cache_materializes_and_counts():
    f = _base_frame().with_column("p2", E.col("price") * 2.0)
    out = f.cache()
    assert out is f
    assert not f._pending            # cache() is a materialization point
    assert counters.get("frame.cache") == 1
    assert counters.get("pipeline.flush") == 1


def test_cache_emits_span(session):
    from sparkdq4ml_tpu.utils import observability as obs

    obs.enable()
    try:
        _base_frame().cache()
        assert any(s.name == "frame.cache" for s in obs.TRACER.spans())
    finally:
        obs.disable()


def test_flush_span_attrs(session):
    from sparkdq4ml_tpu.utils import observability as obs

    obs.enable()
    try:
        f = _base_frame().filter(E.col("price") > 5.0)
        f.count()
        spans = [s for s in obs.TRACER.spans()
                 if s.name == "frame.pipeline.flush"]
        assert spans
        assert spans[0].attrs["steps"] == 1
        assert spans[0].attrs["bucket"] == 8
        assert spans[0].attrs["cache"] in ("compile", "hit")
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# Tier-1-safe perf smoke: fused >= eager on a 10-op chain
# ---------------------------------------------------------------------------

def _ten_op_chain(f):
    for i in range(5):
        f = f.with_column(f"c{i}", E.col("v") * float(i + 1) + 0.5)
        f = f.filter(E.col(f"c{i}") > -1.0)
    return f


def test_fused_speedup_at_least_one_on_ten_op_chain():
    import jax

    n = 200_000
    base = Frame({"v": np.arange(n, dtype=np.float64)})

    def run():
        out = _ten_op_chain(base)
        jax.block_until_ready(list(out._data.values()) + [out._mask])
        return out

    def best_of(k):
        times = []
        for _ in range(k):
            t0 = time.perf_counter()
            run()
            times.append(time.perf_counter() - t0)
        return min(times)

    run()                            # warm both compile caches
    fused = best_of(5)
    config.pipeline = False
    try:
        run()
        eager = best_of(5)
    finally:
        config.pipeline = True
    assert fused <= eager, (
        f"fused 10-op chain slower than eager: {fused:.4f}s vs "
        f"{eager:.4f}s")
