"""RFormula, VectorIndexer, ChiSqSelector, Interaction, SQLTransformer —
the spark.ml.feature transformer sweep (VERDICT round-1 item 9), each with
behavioral tests plus a persistence round-trip."""

import jax.numpy as jnp
import numpy as np
import pytest

from sparkdq4ml_tpu import Frame
from sparkdq4ml_tpu.models import (ChiSqSelector, Interaction, RFormula,
                                   SQLTransformer, VectorAssembler,
                                   VectorIndexer)
from sparkdq4ml_tpu.models.base import load_stage


class TestInteraction:
    def test_scalar_product(self):
        f = Frame({"a": [2.0, 3.0], "b": [5.0, 7.0]})
        out = Interaction(["a", "b"], "ab").transform(f).to_pydict()
        np.testing.assert_allclose(np.stack(out["ab"]).ravel(), [10.0, 21.0])

    def test_vector_scalar_kron(self):
        f = Frame({"v": np.asarray([[1.0, 2.0], [3.0, 4.0]]),
                   "s": [10.0, 100.0]})
        out = Interaction(["v", "s"], "vs").transform(f).to_pydict()
        np.testing.assert_allclose(np.stack(out["vs"]),
                                   [[10.0, 20.0], [300.0, 400.0]])

    def test_three_way(self):
        f = Frame({"a": [2.0], "v": np.asarray([[1.0, 3.0]]), "b": [5.0]})
        out = Interaction(["a", "v", "b"], "i").transform(f).to_pydict()
        np.testing.assert_allclose(np.stack(out["i"]), [[10.0, 30.0]])

    def test_needs_two_columns(self):
        with pytest.raises(ValueError, match="two"):
            Interaction(["a"]).transform(Frame({"a": [1.0]}))

    def test_persistence(self, tmp_path):
        t = Interaction(["a", "b"], "ab")
        t.save(str(tmp_path / "i"))
        loaded = load_stage(str(tmp_path / "i"))
        assert loaded.input_cols == ["a", "b"]
        assert loaded.output_col == "ab"


class TestSQLTransformer:
    def test_select_expression(self):
        f = Frame({"v1": [1.0, 2.0], "v2": [3.0, 4.0]})
        t = SQLTransformer("SELECT *, v1 + v2 AS v3 FROM __THIS__")
        out = t.transform(f).to_pydict()
        np.testing.assert_allclose(out["v3"], [4.0, 6.0])
        assert set(t.transform(f).columns) == {"v1", "v2", "v3"}

    def test_where_filters(self):
        f = Frame({"v1": [1.0, 5.0, 9.0]})
        t = SQLTransformer("SELECT v1 FROM __THIS__ WHERE v1 > 2")
        out = t.transform(f)
        assert out.count() == 2

    def test_does_not_pollute_session_catalog(self):
        from sparkdq4ml_tpu.sql.catalog import default_catalog

        before = default_catalog().list_views()
        SQLTransformer("SELECT v1 FROM __THIS__").transform(
            Frame({"v1": [1.0]}))
        assert default_catalog().list_views() == before

    def test_persistence(self, tmp_path):
        t = SQLTransformer("SELECT * FROM __THIS__")
        t.save(str(tmp_path / "sqlt"))
        loaded = load_stage(str(tmp_path / "sqlt"))
        assert loaded.statement == "SELECT * FROM __THIS__"
        out = loaded.transform(Frame({"x": [1.0, 2.0]}))
        assert out.count() == 2


class TestVectorIndexer:
    def _frame(self):
        # feature 0: continuous; feature 1: categorical {0, 5, 10}
        X = np.asarray([[0.13, 0.0], [1.7, 5.0], [2.9, 10.0], [3.3, 0.0],
                        [4.8, 5.0], [5.1, 10.0], [6.2, 0.0], [7.7, 5.0]])
        return Frame({"features": X}), X

    def test_detects_and_reindexes_categorical(self):
        f, X = self._frame()
        model = VectorIndexer(max_categories=4).fit(f)
        assert list(model.category_maps) == [1]
        assert model.category_maps[1] == [0.0, 5.0, 10.0]
        out = np.stack(model.transform(f).to_pydict()["indexed"])
        np.testing.assert_allclose(out[:, 0], X[:, 0], rtol=1e-6)
        np.testing.assert_allclose(out[:, 1],
                                   [0, 1, 2, 0, 1, 2, 0, 1])

    def test_unseen_category_errors(self):
        f, X = self._frame()
        model = VectorIndexer(max_categories=4).fit(f)
        f2 = Frame({"features": np.asarray([[1.0, 7.0]])})
        with pytest.raises(ValueError, match="unseen"):
            model.transform(f2)

    def test_unseen_category_keep(self):
        f, X = self._frame()
        model = VectorIndexer(max_categories=4,
                              handle_invalid="keep").fit(f)
        f2 = Frame({"features": np.asarray([[1.0, 7.0]])})
        out = np.stack(model.transform(f2).to_pydict()["indexed"])
        assert out[0, 1] == 3.0          # numCategories slot

    def test_all_continuous_passthrough(self):
        f, X = self._frame()
        model = VectorIndexer(max_categories=2).fit(f)
        assert model.category_maps == {}
        out = np.stack(model.transform(f).to_pydict()["indexed"])
        np.testing.assert_allclose(out, X, rtol=1e-6)

    def test_persistence(self, tmp_path):
        f, X = self._frame()
        model = VectorIndexer(max_categories=4).fit(f)
        model.save(str(tmp_path / "vi"))
        loaded = load_stage(str(tmp_path / "vi"))
        assert loaded.category_maps == model.category_maps
        np.testing.assert_allclose(
            np.stack(loaded.transform(f).to_pydict()["indexed"]),
            np.stack(model.transform(f).to_pydict()["indexed"]))


class TestChiSqSelector:
    def _frame(self, n=300, seed=0):
        rng = np.random.default_rng(seed)
        y = rng.integers(0, 2, size=n).astype(float)
        x_dep = ((y + rng.integers(0, 2, size=n)) % 3).astype(float)
        x_noise1 = rng.integers(0, 4, size=n).astype(float)
        x_noise2 = rng.integers(0, 3, size=n).astype(float)
        X = np.stack([x_noise1, x_dep, x_noise2], axis=1)
        return Frame({"features": X, "label": y}), X

    def test_top_features_picks_dependent(self):
        f, X = self._frame()
        model = ChiSqSelector(num_top_features=1).fit(f)
        assert model.selected_features == [1]
        out = np.stack(model.transform(f).to_pydict()["selected"])
        np.testing.assert_allclose(out[:, 0], X[:, 1], rtol=1e-6)

    def test_percentile(self):
        f, X = self._frame()
        model = ChiSqSelector(selector_type="percentile",
                              percentile=0.34).fit(f)
        assert len(model.selected_features) == 1

    def test_fpr(self):
        f, X = self._frame()
        model = ChiSqSelector(selector_type="fpr", fpr=1e-4).fit(f)
        assert model.selected_features == [1]

    def test_selected_indices_sorted(self):
        f, X = self._frame()
        model = ChiSqSelector(num_top_features=3).fit(f)
        assert model.selected_features == sorted(model.selected_features)
        assert len(model.selected_features) == 3

    def test_persistence(self, tmp_path):
        f, X = self._frame()
        model = ChiSqSelector(num_top_features=2).fit(f)
        model.save(str(tmp_path / "cs"))
        loaded = load_stage(str(tmp_path / "cs"))
        assert loaded.selected_features == model.selected_features


class TestRFormula:
    def _frame(self):
        return Frame({
            "y": [1.0, 2.0, 3.0, 4.0],
            "a": [0.5, 1.5, 2.5, 3.5],
            "b": [10.0, 20.0, 30.0, 40.0],
            "c": np.asarray(["us", "eu", "us", "ap"], object),
        })

    def test_numeric_terms(self):
        f = self._frame()
        model = RFormula("y ~ a + b").fit(f)
        out = model.transform(f).to_pydict()
        X = np.stack(out["features"])
        np.testing.assert_allclose(X[:, 0], [0.5, 1.5, 2.5, 3.5])
        np.testing.assert_allclose(X[:, 1], [10.0, 20.0, 30.0, 40.0])
        np.testing.assert_allclose(out["label"], [1.0, 2.0, 3.0, 4.0])

    def test_dot_expands_all_but_label(self):
        f = self._frame()
        model = RFormula("y ~ . - c").fit(f)
        X = np.stack(model.transform(f).to_pydict()["features"])
        assert X.shape == (4, 2)

    def test_string_term_dummy_coded_drop_last(self):
        f = self._frame()
        model = RFormula("y ~ c").fit(f)
        X = np.stack(model.transform(f).to_pydict()["features"])
        # 3 categories (us freq 2, then ap/eu alphabetical) → 2 dummies
        assert X.shape == (4, 2)
        np.testing.assert_allclose(X.sum(axis=1), [1.0, 0.0, 1.0, 1.0])

    def test_interaction_term(self):
        f = self._frame()
        model = RFormula("y ~ a:b").fit(f)
        X = np.stack(model.transform(f).to_pydict()["features"])
        np.testing.assert_allclose(X[:, 0],
                                   [0.5 * 10, 1.5 * 20, 2.5 * 30, 3.5 * 40])

    def test_string_label_indexed(self):
        f = Frame({"lab": np.asarray(["no", "yes", "no"], object),
                   "x": [1.0, 2.0, 3.0]})
        model = RFormula("lab ~ x").fit(f)
        out = model.transform(f).to_pydict()
        assert set(out["label"]) == {0.0, 1.0}

    def test_fitted_on_one_frame_transforms_another(self):
        f = self._frame()
        model = RFormula("y ~ c").fit(f)
        f2 = Frame({"y": [9.0], "a": [0.0], "b": [0.0],
                    "c": np.asarray(["eu"], object)})
        X = np.stack(model.transform(f2).to_pydict()["features"])
        assert X.shape == (1, 2)

    def test_feeds_linear_regression(self):
        from sparkdq4ml_tpu.models import LinearRegression

        rng = np.random.default_rng(0)
        a = rng.normal(size=50)
        y = 3.0 * a + 2.0 + 0.01 * rng.normal(size=50)
        f = Frame({"y": y, "a": a})
        pipe_f = RFormula("y ~ a").fit(f).transform(f)
        m = LinearRegression(max_iter=50).fit(pipe_f)
        assert m.coefficients[0] == pytest.approx(3.0, abs=0.02)

    def test_persistence(self, tmp_path):
        f = self._frame()
        model = RFormula("y ~ a + c").fit(f)
        model.save(str(tmp_path / "rf"))
        loaded = load_stage(str(tmp_path / "rf"))
        np.testing.assert_allclose(
            np.stack(loaded.transform(f).to_pydict()["features"]),
            np.stack(model.transform(f).to_pydict()["features"]))

    def test_estimator_persistence(self, tmp_path):
        est = RFormula("y ~ a + b", features_col="feats")
        est.save(str(tmp_path / "rfe"))
        loaded = load_stage(str(tmp_path / "rfe"))
        assert loaded.formula == "y ~ a + b"
        assert loaded.features_col == "feats"


class TestVectorSizeHint:
    def test_matching_size_passes_through(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        f = Frame({"v": np.asarray([[1.0, 2.0], [3.0, 4.0]])})
        out = VectorSizeHint(input_col="v", size=2).transform(f)
        assert out.columns == f.columns
        np.testing.assert_allclose(np.stack(out.to_pydict()["v"]),
                                   [[1.0, 2.0], [3.0, 4.0]])

    def test_mismatch_errors(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        f = Frame({"v": np.asarray([[1.0, 2.0, 3.0]])})
        with pytest.raises(ValueError, match="size 3, expected 2"):
            VectorSizeHint(input_col="v", size=2).transform(f)

    def test_scalar_column_counts_as_size_one(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        f = Frame({"x": [1.0, 2.0]})
        VectorSizeHint(input_col="x", size=1).transform(f)
        with pytest.raises(ValueError, match="size 1, expected 4"):
            VectorSizeHint(input_col="x", size=4).transform(f)

    def test_optimistic_skips_validation(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        f = Frame({"v": np.asarray([[1.0, 2.0, 3.0]])})
        out = VectorSizeHint(input_col="v", size=2,
                             handle_invalid="optimistic").transform(f)
        assert out.columns == f.columns
        assert out.count() == 1

    def test_skip_drops_mismatching_rows(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        f = Frame({"v": np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])})
        out = VectorSizeHint(input_col="v", size=2,
                             handle_invalid="skip").transform(f)
        assert out.count() == 0          # uniform column: all rows invalid
        ok = VectorSizeHint(input_col="v", size=3,
                            handle_invalid="skip").transform(f)
        assert ok.count() == 2

    def test_bad_handle_invalid_rejected(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        with pytest.raises(ValueError, match="handle_invalid"):
            VectorSizeHint(input_col="v", size=2, handle_invalid="bogus")

    def test_unset_params_error(self):
        from sparkdq4ml_tpu.models import VectorSizeHint
        with pytest.raises(ValueError, match="must be set"):
            VectorSizeHint().transform(Frame({"x": [1.0]}))

    def test_in_pipeline_before_assembler(self):
        from sparkdq4ml_tpu.models import Pipeline, VectorSizeHint
        f = Frame({"a": [1.0, 2.0], "b": [3.0, 4.0]})
        pipe = Pipeline(stages=[
            VectorSizeHint(input_col="a", size=1),
            VectorAssembler(["a", "b"], "features")])
        out = pipe.fit(f).transform(f)
        assert np.stack(out.to_pydict()["features"]).shape == (2, 2)

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models import VectorSizeHint
        st = VectorSizeHint(input_col="v", size=3, handle_invalid="optimistic")
        st.save(str(tmp_path / "vsh"))
        back = load_stage(str(tmp_path / "vsh"))
        assert back.input_col == "v" and back.size == 3
        assert back.handle_invalid == "optimistic"


class TestOneHotEncoderPlural:
    """inputCols/outputCols form (Spark 2.4 OneHotEncoderEstimator /
    3.x OneHotEncoder)."""

    def test_multi_column_encode(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        f = Frame({"a": np.asarray([0.0, 1.0, 2.0]),
                   "b": np.asarray([1.0, 0.0, 1.0])})
        m = OneHotEncoder(input_cols=["a", "b"],
                          output_cols=["av", "bv"]).fit(f)
        assert m.categorySizes == [3, 2]
        out = m.transform(f).to_pydict()
        av = np.asarray(out["av"])
        bv = np.asarray(out["bv"])
        assert av.shape == (3, 2)           # dropLast: 3 cats -> width 2
        np.testing.assert_array_equal(av[0], [1.0, 0.0])
        np.testing.assert_array_equal(av[2], [0.0, 0.0])  # last cat -> zeros
        assert bv.shape == (3, 1)
        # dropLast keeps the category-0 indicator column only
        np.testing.assert_array_equal(bv[:, 0], [0.0, 1.0, 0.0])

    def test_save_load_plural(self, tmp_path):
        from sparkdq4ml_tpu.models import OneHotEncoder, OneHotEncoderModel
        f = Frame({"a": np.asarray([0.0, 1.0]), "b": np.asarray([0.0, 1.0])})
        m = OneHotEncoder(input_cols=["a", "b"], output_cols=["av", "bv"],
                          drop_last=False).fit(f)
        m.save(str(tmp_path / "ohe"))
        loaded = OneHotEncoderModel.load(str(tmp_path / "ohe"))
        out = loaded.transform(f).to_pydict()
        assert np.asarray(out["av"]).shape == (2, 2)

    def test_both_forms_rejected(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        with pytest.raises(ValueError, match="not both"):
            OneHotEncoder(input_col="a", input_cols=["a"])

    def test_mismatched_outputs_rejected(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        f = Frame({"a": np.asarray([0.0])})
        with pytest.raises(ValueError, match="match"):
            OneHotEncoder(input_cols=["a"], output_cols=[]).fit(f)

    def test_single_col_back_compat(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        f = Frame({"k": np.asarray([0.0, 1.0, 2.0, 1.0])})
        m = OneHotEncoder(input_col="k", output_col="kv").fit(f)
        out = np.asarray(m.transform(f).to_pydict()["kv"])
        assert out.shape == (4, 2)
        np.testing.assert_array_equal(out[1], [0.0, 1.0])

    def test_output_name_colliding_with_later_input(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        f = Frame({"a": np.asarray([0.0, 1.0, 2.0]),
                   "b": np.asarray([1.0, 0.0, 1.0])})
        m = OneHotEncoder(input_cols=["a", "b"],
                          output_cols=["b", "c"]).fit(f)
        out = m.transform(f).to_pydict()
        # column 'c' must encode the ORIGINAL b, not a's one-hot output
        np.testing.assert_array_equal(np.asarray(out["c"])[:, 0],
                                      [0.0, 1.0, 0.0])

    def test_empty_plural_rejected(self):
        from sparkdq4ml_tpu.models import OneHotEncoder
        f = Frame({"a": np.asarray([0.0])})
        with pytest.raises(ValueError, match="empty"):
            OneHotEncoder(input_cols=[], output_cols=[]).fit(f)

    def test_model_invariant_enforced(self):
        from sparkdq4ml_tpu.models import OneHotEncoderModel
        with pytest.raises(ValueError, match="lengths"):
            OneHotEncoderModel(3, None, None, category_sizes=[3, 2],
                               input_cols=["a", "b"], output_cols=["av"])

    def test_corrupted_save_rejected_on_load(self, tmp_path):
        import json, os
        from sparkdq4ml_tpu.models import OneHotEncoder, OneHotEncoderModel
        f = Frame({"a": np.asarray([0.0, 1.0]), "b": np.asarray([0.0, 1.0])})
        m = OneHotEncoder(input_cols=["a", "b"],
                          output_cols=["av", "bv"]).fit(f)
        path = str(tmp_path / "ohe")
        m.save(path)
        meta_path = os.path.join(path, "stage.json")
        if not os.path.exists(meta_path):
            meta_path = next(os.path.join(path, p) for p in os.listdir(path)
                             if p.endswith(".json"))
        meta = json.load(open(meta_path))
        # truncate output_cols wherever the attrs landed in the payload
        def truncate(d):
            for k, v in list(d.items()):
                if k == "output_cols" and isinstance(v, list):
                    d[k] = v[:1]
                elif isinstance(d[k], dict):
                    truncate(d[k])
        truncate(meta)
        json.dump(meta, open(meta_path, "w"))
        with pytest.raises(ValueError, match="lengths"):
            OneHotEncoderModel.load(path)
