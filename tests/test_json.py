"""JSON-lines reader/writer: schema inference (Spark's union-of-keys,
int→double promotion, nested values as host objects), multiLine arrays,
round-trips, and the session.read surface."""

import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from sparkdq4ml_tpu import Frame


@pytest.fixture
def session():
    return dq.TpuSession.builder().app_name("json").get_or_create()


def write(tmp_path, text, name="data.json"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


class TestReadJson:
    def test_basic_schema_inference(self, session, tmp_path):
        p = write(tmp_path, '{"a": 1, "b": 2.5, "s": "x"}\n'
                            '{"a": 2, "b": 3, "s": "y"}\n')
        df = session.read.json(p)
        d = df.to_pydict()
        assert d["a"].tolist() == [1, 2]
        assert d["a"].dtype.kind == "i"            # all-int stays integral
        np.testing.assert_allclose(d["b"], [2.5, 3.0])   # int+float → double
        assert list(d["s"]) == ["x", "y"]

    def test_missing_keys_null(self, session, tmp_path):
        p = write(tmp_path, '{"a": 1}\n{"a": 2, "extra": "e"}\n')
        d = session.read.json(p).to_pydict()
        assert list(d["extra"]) == [None, "e"]
        assert d["a"].tolist() == [1, 2]

    def test_missing_int_promotes_to_double_with_nan(self, session, tmp_path):
        p = write(tmp_path, '{"a": 1}\n{"b": 2}\n')
        d = session.read.json(p).to_pydict()
        assert np.isnan(d["a"][1]) and d["a"][0] == 1.0

    def test_nested_values_stay_objects(self, session, tmp_path):
        p = write(tmp_path,
                  '{"tags": ["x", "y"], "meta": {"k": 1}}\n'
                  '{"tags": [], "meta": {"k": 2}}\n')
        d = session.read.json(p).to_pydict()
        assert d["tags"][0] == ["x", "y"]
        assert d["meta"][1] == {"k": 2}

    def test_bool_column(self, session, tmp_path):
        p = write(tmp_path, '{"f": true}\n{"f": false}\n')
        d = session.read.json(p).to_pydict()
        assert d["f"].tolist() == [True, False]

    def test_multiline_array(self, session, tmp_path):
        p = write(tmp_path, '[{"a": 1}, {"a": 2}]')
        df = (session.read.format("json").option("multiLine", "true")
              .load(p))
        assert df.to_pydict()["a"].tolist() == [1, 2]

    def test_blank_lines_skipped(self, session, tmp_path):
        p = write(tmp_path, '{"a": 1}\n\n{"a": 2}\n\n')
        assert session.read.json(p).count() == 2

    def test_errors(self, session, tmp_path):
        with pytest.raises(FileNotFoundError):
            session.read.json(str(tmp_path / "missing.json"))
        p = write(tmp_path, '[1, 2]', "arr.json")
        with pytest.raises(ValueError, match="not an object"):
            session.read.json(p, multiLine=True)
        p = write(tmp_path, '{"a": 1}', "obj.json")
        with pytest.raises(ValueError, match="top-level array"):
            session.read.json(p, multiLine=True)


class TestWriteJson:
    def test_round_trip(self, session, tmp_path):
        f = Frame({"a": [1.5, np.nan], "s": ["x", None],
                   "i": np.asarray([7, 8], np.int64)})
        out = str(tmp_path / "out.json")
        f.write.json(out)
        back = session.read.json(out)
        d = back.to_pydict()
        assert d["a"][0] == 1.5 and np.isnan(d["a"][1])   # NaN → null → NaN
        assert list(d["s"]) == ["x", None]
        assert d["i"].tolist() == [7, 8]

    def test_masked_rows_not_written(self, session, tmp_path):
        f = Frame({"a": [1.0, 2.0, 3.0]})
        f = f.filter(dq.col("a") > 1.5)
        out = str(tmp_path / "masked.json")
        f.write.json(out)
        assert session.read.json(out).count() == 2

    def test_mode_guard(self, tmp_path):
        f = Frame({"a": [1.0]})
        out = str(tmp_path / "dup.json")
        f.write.json(out)
        with pytest.raises(FileExistsError):
            f.write.json(out)
        f.write.mode("overwrite").json(out)


class TestReviewRegressions:
    def test_huge_int_promotes_instead_of_crashing(self, session, tmp_path):
        p = write(tmp_path, '{"a": 9223372036854775808}\n{"a": 1}\n')
        d = session.read.json(p).to_pydict()
        assert d["a"][0] == float(2**63) and d["a"][1] == 1.0

    def test_nested_nan_written_as_null(self, session, tmp_path):
        import json as _json
        from sparkdq4ml_tpu.frame.frame import list_column
        f = Frame({"x": list_column([[1.0, float("nan")], [2.0]])})
        out = str(tmp_path / "nested.json")
        f.write.json(out)
        lines = [ln for ln in open(out).read().splitlines() if ln]
        parsed = [_json.loads(ln) for ln in lines]   # must be strict JSON
        assert parsed[0]["x"] == [1.0, None]

    def test_float_dtype_config_honored(self, session, tmp_path):
        from sparkdq4ml_tpu.config import float_dtype
        p = write(tmp_path, '{"b": 2.5}\n')
        d = session.read.json(p).to_pydict()
        assert d["b"].dtype == np.dtype(float_dtype())
