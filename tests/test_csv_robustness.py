"""CSV robustness (VERDICT r2 item 7): quoted fields with embedded record
separators, Spark's malformed-record ``mode`` option, and quote handling in
the native tokenizer — the Univocity-parser behavior behind the reference's
CSV options (`DataQuality4MachineLearningApp.java:53-55`).
"""

import numpy as np
import pytest

from sparkdq4ml_tpu.frame import native_csv
from sparkdq4ml_tpu.frame.csv import parse_csv_text, read_csv

needs_native = pytest.mark.skipif(not native_csv.available(),
                                  reason="native/libdqcsv.so not built")


class TestQuotedRecordSeparators:
    def test_embedded_newlines_in_quoted_field(self):
        text = 'a,"line1\nline2",c\r\nd,"x\ry",f\n'
        rows = parse_csv_text(text)
        assert rows == [["a", "line1\nline2", "c"], ["d", "x\ry", "f"]]

    def test_embedded_crlf_and_escaped_quotes(self):
        text = '"he said ""hi""\r\nbye",2\n3,4\n'
        rows = parse_csv_text(text)
        assert rows == [['he said "hi"\r\nbye', "2"], ["3", "4"]]

    def test_quoted_delimiters(self):
        assert parse_csv_text('"1,000",2\n') == [["1,000", "2"]]

    def test_quote_free_fast_path_unchanged(self):
        assert parse_csv_text("1,2\r3,4\r") == [["1", "2"], ["3", "4"]]
        assert parse_csv_text("a\r\n\nb\r\rc\n") == [["a"], ["b"], ["c"]]

    def test_quoted_blank_line_is_kept(self):
        # a quoted empty field is a record; a truly blank line is skipped
        assert parse_csv_text('""\n\n1\n') == [[""], ["1"]]

    def test_trailing_quoted_empty_record_no_newline(self):
        # a file ending in a lone quoted "" without a trailing newline must
        # keep that record (parity with the native engine)
        assert parse_csv_text('1,2\n""') == [["1", "2"], [""]]
        assert parse_csv_text('""') == [[""]]

    def test_split_fields_wraps_scanner(self):
        from sparkdq4ml_tpu.frame.csv import split_fields

        assert split_fields('a,"b,c",d') == ["a", "b,c", "d"]
        assert split_fields('"say ""hi""",x') == ['say "hi"', "x"]
        assert split_fields("") == [""]

    def test_multibyte_quote_falls_back_to_python(self, tmp_path):
        # a 1-char/2-byte quote must not crash the ctypes binding
        p = tmp_path / "mb.csv"
        p.write_text("«1»,2\n")
        d = read_csv(str(p), engine="auto", quote="«").to_pydict()
        assert len(d["_c0"]) == 1

    def test_read_csv_multiline_quoted(self, tmp_path):
        p = tmp_path / "q.csv"
        p.write_text('name,note\n"bob","likes\nnewlines"\n"amy",ok\n')
        df = read_csv(str(p), header=True, infer_schema=True,
                      engine="python")
        d = df.to_pydict()
        assert list(d["name"]) == ["bob", "amy"]
        assert list(d["note"]) == ["likes\nnewlines", "ok"]


class TestModeOption:
    def _write(self, tmp_path, text, name="m.csv"):
        p = tmp_path / name
        p.write_text(text)
        return str(p)

    def test_permissive_pads_and_truncates(self, tmp_path):
        p = self._write(tmp_path, "1,2\n3\n4,5,6\n")
        d = read_csv(p, engine="python").to_pydict()
        assert list(d["_c0"]) == [1.0, 3.0, 4.0]
        v = np.asarray(d["_c1"], np.float64)
        assert v[0] == 2.0 and np.isnan(v[1]) and v[2] == 5.0

    def test_dropmalformed_drops_wrong_field_count(self, tmp_path):
        p = self._write(tmp_path, "1,2\n3\n4,5,6\n7,8\n")
        d = read_csv(p, engine="python", mode="DROPMALFORMED").to_pydict()
        assert list(np.asarray(d["_c0"], np.int64)) == [1, 7]
        assert list(np.asarray(d["_c1"], np.int64)) == [2, 8]

    def test_failfast_raises(self, tmp_path):
        p = self._write(tmp_path, "1,2\n3\n")
        with pytest.raises(ValueError, match="FAILFAST"):
            read_csv(p, engine="python", mode="FAILFAST")

    def test_failfast_clean_file_ok(self, tmp_path):
        p = self._write(tmp_path, "1,2\n3,4\n")
        d = read_csv(p, engine="python", mode="FAILFAST").to_pydict()
        assert list(np.asarray(d["_c1"], np.int64)) == [2, 4]

    def test_mode_option_via_reader(self, tmp_path):
        from sparkdq4ml_tpu.frame.csv import DataFrameReader

        p = self._write(tmp_path, "1,2\n3\n")
        df = (DataFrameReader().format("csv")
              .option("inferSchema", "true").option("mode", "dropMalformed")
              .load(p))
        assert df.count() == 1

    def test_unknown_mode_rejected(self, tmp_path):
        p = self._write(tmp_path, "1,2\n")
        with pytest.raises(ValueError, match="mode"):
            read_csv(p, mode="lenient")

    def test_native_engine_rejects_non_permissive(self, tmp_path):
        p = self._write(tmp_path, "1,2\n")
        with pytest.raises(RuntimeError, match="PERMISSIVE"):
            read_csv(p, engine="native", mode="FAILFAST")


@needs_native
class TestNativeQuoting:
    def test_quoted_numeric_fields(self, tmp_path):
        p = tmp_path / "n.csv"
        p.write_text('"1",2\n"3","4.5"\n')
        nat = read_csv(str(p), engine="native").to_pydict()
        py = read_csv(str(p), engine="python").to_pydict()
        for k in nat:
            np.testing.assert_allclose(np.asarray(nat[k], np.float64),
                                       np.asarray(py[k], np.float64))

    def test_quoted_field_with_embedded_newline_falls_back(self, tmp_path):
        # embedded separators make the field non-numeric → both engines
        # must agree via the python fallback (engine="auto")
        p = tmp_path / "nl.csv"
        p.write_text('1,"a\nb"\n2,c\n')
        d = read_csv(str(p), engine="auto").to_pydict()
        assert list(np.asarray(d["_c0"], np.int64)) == [1, 2]
        assert list(d["_c1"]) == ["a\nb", "c"]

    def test_quoted_number_with_embedded_crlf(self, tmp_path):
        # a quoted NUMERIC field containing a record separator stays one
        # record on the native path too (strtod rejects it → python agrees)
        p = tmp_path / "q2.csv"
        p.write_text('"12\r\n34",5\n6,7\n')
        d = read_csv(str(p), engine="auto").to_pydict()
        assert len(d["_c0"]) == 2

    def test_thousands_style_quoted_delim(self, tmp_path):
        p = tmp_path / "t.csv"
        p.write_text('"1000",2\n"3000",4\n')
        nat = read_csv(str(p), engine="native").to_pydict()
        assert list(np.asarray(nat["_c0"], np.int64)) == [1000, 3000]

    def test_reference_datasets_still_native(self):
        from conftest import dataset_path

        nat = read_csv(dataset_path("full"), engine="native")
        py = read_csv(dataset_path("full"), engine="python")
        assert nat.count() == py.count() == 1040
        for k in ("_c0", "_c1"):
            np.testing.assert_allclose(
                np.asarray(nat.to_pydict()[k], np.float64),
                np.asarray(py.to_pydict()[k], np.float64))
