"""Concurrent query-serving layer (serve/) — multi-tenant sessions,
shared plan cache, admission control, deadlines, SLO metrics, and the
engine-wide thread-safety audit (ISSUE 6).

Covers: tenant catalog isolation, golden results under 32-way
concurrency (count=24 / RMSE 2.80994), the cross-tenant plan-cache reuse
pin (second tenant's identical query = 0 new compiles), the isolated-
cache control mode, every admission gate (global queue, per-tenant
quota, memory, breaker shedding), structured deadline errors that never
hang, per-tenant metric isolation + the Prometheus scrape, concurrent
``query_stats`` collectors at server scale, the 16-thread jit-cache
hammer, the thread-safe session singleton, and the serving extensions of
the bench-regression gate.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import sparkdq4ml_tpu as dq
from conftest import dataset_path
from sparkdq4ml_tpu.frame import aggregates as A
from sparkdq4ml_tpu.frame.frame import Frame
from sparkdq4ml_tpu.ops import compiler, segments
from sparkdq4ml_tpu.ops import expressions as E
from sparkdq4ml_tpu.serve import (QueryDeadlineExceeded, QueryRefused,
                                  QueryServer, TenantQuota)
from sparkdq4ml_tpu.utils import observability as obs
from sparkdq4ml_tpu.utils.profiling import counters

pytestmark = pytest.mark.serve

GOLDEN_COUNT = 24
GOLDEN_RMSE = 2.809940


def headline_job(path):
    """The reference app's DQ+Lasso flow (the headline query) as a
    tenant-scoped server job: same call sequence as
    ``conftest.run_dq_pipeline`` + fit, but temp views live in the
    tenant's own catalog."""
    from sparkdq4ml_tpu.models import LinearRegression, VectorAssembler

    def job(ctx):
        dq.register_builtin_rules()
        df = (ctx.read.format("csv").option("inferSchema", "true")
              .option("header", "false").load(path))
        df = df.with_column_renamed("_c0", "guest") \
               .with_column_renamed("_c1", "price")
        df = df.with_column("price_no_min",
                            dq.call_udf("minimumPriceRule", dq.col("price")))
        ctx.register_view("price", df)
        df = ctx.sql("SELECT cast(guest as int) guest, price_no_min AS "
                     "price FROM price WHERE price_no_min > 0")
        df = df.with_column(
            "price_correct_correl",
            dq.call_udf("priceCorrelationRule", dq.col("price"),
                        dq.col("guest")))
        ctx.register_view("price", df)
        df = ctx.sql("SELECT guest, price_correct_correl AS price "
                     "FROM price WHERE price_correct_correl > 0")
        df = df.with_column("label", df.col("price"))
        df = VectorAssembler(["guest"], "features").transform(df)
        model = LinearRegression(max_iter=40, reg_param=1.0,
                                 elastic_net_param=1.0).fit(df)
        return {"count": df.count(),
                "rmse": float(model.summary.root_mean_squared_error)}
    return job


def _plan_compiles(report):
    return sum(int(report.get(k, {}).get("misses", 0))
               for k in ("pipeline", "grouped"))


def _plan_hits(report):
    return sum(int(report.get(k, {}).get("hits", 0))
               for k in ("pipeline", "grouped"))


# ---------------------------------------------------------------------------
# Basics: submission surface, tenant isolation, lifecycle
# ---------------------------------------------------------------------------

class TestBasics:
    def test_sql_string_and_callable_jobs(self, session):
        with QueryServer(session, workers=2) as srv:
            f = Frame({"x": np.arange(10.0)})
            srv.context("a").register_view("t", f)
            res = srv.submit("SELECT x FROM t WHERE x > 6",
                             tenant="a").result()
            assert res.ok and res.status == "ok"
            assert res.value.count() == 3
            assert res.queue_ms is not None and res.e2e_ms is not None

            res2 = srv.submit(lambda ctx: 41 + 1, tenant="a").result()
            assert res2.value == 42
            assert res2.value_or_raise() == 42

    def test_tenant_view_isolation(self, session):
        """Two tenants both own a view named ``t`` — no collision (the
        multi-tenant property the process-default catalog cannot give)."""
        with QueryServer(session, workers=2) as srv:
            srv.context("a").register_view("t", Frame({"x": np.arange(3.0)}))
            srv.context("b").register_view("t", Frame({"x": np.arange(7.0)}))
            ra = srv.submit("SELECT count(*) c FROM t", tenant="a").result()
            rb = srv.submit("SELECT count(*) c FROM t", tenant="b").result()
            assert int(np.asarray(ra.value.to_pydict()["c"])[0]) == 3
            assert int(np.asarray(rb.value.to_pydict()["c"])[0]) == 7

    def test_execution_error_is_structured(self, session):
        with QueryServer(session, workers=1) as srv:
            def boom(ctx):
                raise ValueError("tenant bug")
            res = srv.submit(boom, tenant="a").result()
            assert res.status == "error"
            assert "ValueError" in res.error and "tenant bug" in res.error
            with pytest.raises(Exception, match="tenant bug"):
                res.value_or_raise()

    def test_submit_requires_running_server(self, session):
        srv = QueryServer(session, workers=1)
        with pytest.raises(RuntimeError, match="not running"):
            srv.submit(lambda ctx: 1)
        srv.start()
        try:
            assert srv.submit(lambda ctx: 1).result().ok
        finally:
            srv.stop()
        with pytest.raises(RuntimeError, match="not running"):
            srv.submit(lambda ctx: 1)

    def test_stop_drain_false_rejects_queued(self, session):
        srv = QueryServer(session, workers=1).start()
        started, release = threading.Event(), threading.Event()

        def blocker(ctx):
            started.set()
            release.wait(5)
            return "done"

        f0 = srv.submit(blocker, tenant="a")
        assert started.wait(5)
        f1 = srv.submit(lambda ctx: 1, tenant="a")   # queued behind blocker
        rej0 = counters.get("serve.reject.shutdown")
        t = threading.Thread(target=srv.stop, kwargs={"drain": False})
        t.start()
        r1 = f1.result(timeout=5)
        assert r1.status == "rejected" and r1.reason == "shutdown"
        release.set()
        t.join(5)
        assert f0.result(timeout=5).ok       # in-flight still finished
        # refusals are observable, never silent — shutdown included
        assert counters.get("serve.reject.shutdown") == rej0 + 1
        assert obs.METRICS.get_gauge("serve.workers") == 0

    def test_session_serve_accessor_and_stop(self, session):
        srv = session.serve(workers=2)
        assert srv.running
        assert session.serve() is srv        # same running server back
        assert srv.submit(lambda ctx: 7).result().value == 7
        session.stop()
        assert not srv.running

    def test_restart_after_timed_out_stop_keeps_pool_size(self, session):
        """A worker wedged in a device call past stop()'s join timeout
        rejoins the pool on restart: start() spawns only the difference
        (regression: a full new set ran the pool oversized with threads
        no later stop() ever joined, and the workers gauge lied)."""
        srv = QueryServer(session, workers=2).start()
        started, release = threading.Event(), threading.Event()
        try:
            def blocker(ctx):
                started.set()
                release.wait(10)
                return "done"

            fut = srv.submit(blocker, tenant="a")
            assert started.wait(5)
            srv.stop(timeout=0.5)                # straggler left behind
            assert obs.METRICS.get_gauge("serve.workers") == 1
            srv.start()                          # spawns exactly one more
            assert len(srv._threads) == 2
            assert obs.METRICS.get_gauge("serve.workers") == 2
            release.set()
            assert fut.result(timeout=5).value == "done"
            assert srv.submit(lambda ctx: 1, tenant="a").result(
                timeout=5).ok
        finally:
            release.set()
            srv.stop(timeout=5)


# ---------------------------------------------------------------------------
# Golden results under concurrency + shared plan cache
# ---------------------------------------------------------------------------

class TestConcurrentGolden:
    def test_32_tenants_all_get_golden_numbers(self, session):
        """The acceptance pin: 32 concurrent clients, one tenant each,
        all running the headline DQ+Lasso query — every result must be
        count=24 / RMSE 2.80994 (concurrency must never change
        results)."""
        job = headline_job(dataset_path("abstract"))
        with QueryServer(session, workers=8, max_queue=128) as srv:
            futs = [srv.submit(job, tenant=f"tenant-{i:02d}")
                    for i in range(32)]
            results = [f.result(timeout=300) for f in futs]
        assert all(r.ok for r in results), \
            [r.error for r in results if not r.ok]
        for r in results:
            assert r.value["count"] == GOLDEN_COUNT
            assert r.value["rmse"] == pytest.approx(GOLDEN_RMSE, abs=1e-4)

    def test_cross_tenant_plan_reuse_zero_new_compiles(self, session):
        """The shared-cache pin: tenant B's FIRST query replays tenant
        A's compiled programs — the cache_report diff shows zero new
        pipeline/grouped compiles and at least one fresh hit."""
        job = headline_job(dataset_path("abstract"))
        compiler.clear_cache()
        segments.clear_cache()
        with QueryServer(session, workers=2) as srv:
            assert srv.shared_plan_cache
            r_a = srv.submit(job, tenant="alpha").result()
            assert r_a.ok and r_a.value["count"] == GOLDEN_COUNT
            rep0 = srv.cache_report()
            r_b = srv.submit(job, tenant="beta").result()
            rep1 = srv.cache_report()
        assert r_b.ok and r_b.value["count"] == GOLDEN_COUNT
        assert _plan_compiles(rep1) - _plan_compiles(rep0) == 0
        assert _plan_hits(rep1) > _plan_hits(rep0)

    def test_isolated_cache_mode_compiles_per_tenant(self, session):
        """shared_plan_cache=False partitions the plan caches by tenant
        (the bench's control arm): tenant B's first query does NOT reuse
        tenant A's programs."""
        job = headline_job(dataset_path("abstract"))
        compiler.clear_cache()
        segments.clear_cache()
        try:
            with QueryServer(session, workers=2,
                             shared_plan_cache=False) as srv:
                r_a = srv.submit(job, tenant="alpha").result()
                rep0 = srv.cache_report()
                r_b = srv.submit(job, tenant="beta").result()
                rep1 = srv.cache_report()
            assert r_a.ok and r_b.ok
            assert _plan_compiles(rep1) - _plan_compiles(rep0) > 0
            # same tenant again: its namespaced plans replay
            with QueryServer(session, workers=2,
                             shared_plan_cache=False) as srv:
                rep2 = srv.cache_report()
                r_a2 = srv.submit(job, tenant="alpha").result()
                rep3 = srv.cache_report()
            assert r_a2.ok
            assert _plan_compiles(rep3) - _plan_compiles(rep2) == 0
        finally:
            compiler.clear_cache()   # drop the tenant-salted entries
            segments.clear_cache()

    def test_lazy_frame_value_materializes_in_tenant_namespace(self,
                                                               session):
        """A callable job returning a LAZY Frame (pending fused-pipeline
        steps) must flush inside the serve scope: left lazy, the
        client's first read would flush on the client thread — outside
        the tenant's plan namespace, silently un-partitioning the
        isolated-cache mode (regression: confirmed escape)."""
        compiler.clear_cache()
        try:
            def lazy_job(ctx):
                f = Frame({"v": np.arange(48.0)})
                return f.with_column("c", E.col("v") * 3.0) \
                        .filter(E.col("c") > 6.0)        # NOT materialized

            with QueryServer(session, workers=1,
                             shared_plan_cache=False) as srv:
                res = srv.submit(lazy_job, tenant="nsq").result()
            assert res.ok
            # the worker flushed it: nothing pending, and the plan landed
            # under the tenant namespace (a fresh read compiles nothing)
            assert not res.value._pending
            assert res.value.count() == 45
            report = compiler.cache_stats()
            assert report["size"] == 1
            assert "ns:'nsq'" in report["entries"][0]["key"]
        finally:
            compiler.clear_cache()

    def test_plan_namespace_scopes_keys(self):
        compiler.clear_cache()

        def chain():
            f = Frame({"v": np.arange(32.0)})
            f = f.with_column("c", E.col("v") * 2.0) \
                 .filter(E.col("c") > 3.0)
            return f.count()

        try:
            with compiler.plan_namespace("t1"):
                assert chain() == 30
            assert compiler.cache_len() == 1
            with compiler.plan_namespace("t2"):
                assert chain() == 30
            assert compiler.cache_len() == 2    # t2 compiled its own
            chain()                             # shared (empty) namespace
            assert compiler.cache_len() == 3
            with compiler.plan_namespace("t1"):
                assert chain() == 30
            assert compiler.cache_len() == 3    # t1 replayed
        finally:
            compiler.clear_cache()


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

class TestAdmission:
    def _blocking_server(self, session, **kw):
        srv = QueryServer(session, **kw).start()
        started, release = threading.Event(), threading.Event()

        def blocker(ctx):
            started.set()
            release.wait(10)
            return "done"

        fut = srv.submit(blocker, tenant="a")
        assert started.wait(5)
        return srv, fut, release

    def test_queue_bounds_global_and_per_tenant(self, session):
        srv, fut, release = self._blocking_server(
            session, workers=1, max_queue=2,
            default_quota=TenantQuota(max_in_flight=1, max_queued=1))
        try:
            f1 = srv.submit(lambda ctx: 1, tenant="a")   # a queued: 1
            r2 = srv.submit(lambda ctx: 1, tenant="a").result()
            assert r2.status == "rejected"
            assert r2.reason == "tenant_queue_full"
            f3 = srv.submit(lambda ctx: 1, tenant="b")   # global queued: 2
            r4 = srv.submit(lambda ctx: 1, tenant="c").result()
            assert r4.status == "rejected" and r4.reason == "queue_full"
            with pytest.raises(QueryRefused, match="queue"):
                r4.value_or_raise()
            release.set()
            assert fut.result(timeout=10).ok
            assert f1.result(timeout=10).ok
            assert f3.result(timeout=10).ok
        finally:
            release.set()
            srv.stop()

    def test_refused_submissions_allocate_no_tenant_state(self, session):
        """Refused work must not grow per-tenant state: a flood of
        rejected submissions under unique tenant names leaves _tenants
        (and the scheduler's round-robin scan) untouched."""
        srv, fut, release = self._blocking_server(
            session, workers=1, max_queue=1)
        try:
            srv.submit(lambda ctx: 1, tenant="a")   # fills max_queue=1
            for i in range(20):
                r = srv.submit(lambda ctx: 1, tenant=f"ghost{i}").result()
                assert r.status == "rejected" and r.reason == "queue_full"
            tenants = srv.stats()["tenants"]
            assert not any(t.startswith("ghost") for t in tenants)
        finally:
            release.set()
            srv.stop()

    def test_admitted_flood_reaps_idle_stateless_tenants(self, session):
        """The admitted-flood sibling of the refused-flood pin: one
        trivial admitted query per unique tenant name must not grow the
        tenant table (and the round-robin scan) past the reap threshold.
        Tenants with durable state — registered views, custom quota, an
        exposed context — survive the sweep."""
        from sparkdq4ml_tpu.serve import server as srv_mod

        old = srv_mod.TENANT_REAP_THRESHOLD
        srv_mod.TENANT_REAP_THRESHOLD = 8
        try:
            with QueryServer(session, workers=2) as srv:
                srv.context("keeper").register_view(
                    "t", Frame({"x": np.arange(3.0)}))
                srv.set_quota("vip", TenantQuota(max_in_flight=1,
                                                 max_queued=2))
                for i in range(50):
                    assert srv.submit(lambda ctx: i,
                                      tenant=f"fly{i}").result().ok
                tenants = srv.stats()["tenants"]
                assert len(tenants) <= 8 + 1   # threshold + the newest
                assert "keeper" in tenants and "vip" in tenants
                # reaped names come back transparently
                assert srv.submit(lambda ctx: 1, tenant="fly0").result().ok
        finally:
            srv_mod.TENANT_REAP_THRESHOLD = old

    def test_reap_clears_breaker_state(self, session):
        """The breaker entry is tenant bookkeeping: reaping the tenant
        but leaving its ``CircuitBreaker._state`` key behind would grow
        one dict entry per failed-once tenant forever — the exact
        admitted-flood leak the sweep exists to bound."""
        from sparkdq4ml_tpu.serve import server as srv_mod

        old = srv_mod.TENANT_REAP_THRESHOLD
        srv_mod.TENANT_REAP_THRESHOLD = 8
        try:
            with QueryServer(session, workers=2) as srv:
                def boom(ctx):
                    raise ValueError("nope")

                for i in range(30):
                    r = srv.submit(boom, tenant=f"fail{i}").result()
                    assert r.status == "error"
                assert srv.submit(lambda ctx: 1, tenant="last").result().ok
                stale = [k for k in srv.breaker.snapshot()
                         if k.startswith("serve/fail")]
                # reaped tenants took their breaker entry with them (the
                # +2 slack: the newest tenant plus one whose worker is
                # still between _finish and the in_flight decrement)
                assert len(stale) <= srv_mod.TENANT_REAP_THRESHOLD + 2
        finally:
            srv_mod.TENANT_REAP_THRESHOLD = old

    def test_memory_gate_structured_rejection(self, session):
        with QueryServer(session, workers=1,
                         memory_limit_bytes=1) as srv:
            res = srv.submit(lambda ctx: 1, tenant="big",
                             est_bytes=1 << 30).result()
            assert res.status == "rejected" and res.reason == "memory"
            assert "B exceeds" in res.detail
            # no estimate declared -> the gate stays advisory and admits
            assert srv.submit(lambda ctx: 2, tenant="big").result().ok
        assert counters.get("serve.reject.memory") >= 1

    def test_would_fit_census(self):
        from sparkdq4ml_tpu.utils import meminfo

        fits, live = meminfo.would_fit(1, 1 << 62)
        assert fits and live >= 0
        fits, _ = meminfo.would_fit(1 << 62, 1)
        assert not fits
        assert meminfo.headroom(1) in (0, 1)

    def test_breaker_sheds_then_recovers(self, session):
        with QueryServer(session, workers=1, breaker_threshold=2,
                         breaker_cooldown=0.2) as srv:
            def boom(ctx):
                raise RuntimeError("down")
            for _ in range(2):
                assert srv.submit(boom, tenant="c").result().status == "error"
            shed = srv.submit(lambda ctx: 1, tenant="c").result()
            assert shed.status == "shed" and shed.reason == "breaker_open"
            # healthy tenants are unaffected by c's breaker
            assert srv.submit(lambda ctx: 1, tenant="d").result().ok
            snap = srv.breaker.snapshot()
            assert snap["serve/c"]["open"] is True
            time.sleep(0.25)                     # cooldown -> half-open
            ok = srv.submit(lambda ctx: 1, tenant="c").result()
            assert ok.ok
            assert srv.breaker.snapshot().get("serve/c") is None

    def test_stats_snapshot_shape(self, session):
        with QueryServer(session, workers=2) as srv:
            srv.submit(lambda ctx: 1, tenant="a").result()
            st = srv.stats()
        assert st["workers"] == 2 and st["shared_plan_cache"] is True
        assert st["tenants"]["a"]["max_in_flight"] == 4
        assert "serve.admit" in st["counters"]


# ---------------------------------------------------------------------------
# Deadlines: structured, prompt, never a hang
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_exec_overrun_returns_structured_error_promptly(self, session):
        # Determinism on the fault-throttled CI host (PR-7 flake note): no
        # wall-clock margin — the job blocks on an Event we control, so
        # "returned promptly, not hung" is proven by the result arriving
        # WHILE the job is still provably running (the event is unset),
        # not by a scheduler-sensitive elapsed-time bound.
        release = threading.Event()
        with QueryServer(session, workers=1) as srv:
            fut = srv.submit(lambda ctx: release.wait(30) or "late",
                             tenant="a", deadline_s=0.15)
            t0 = time.perf_counter()
            res = fut.result()
            waited = time.perf_counter() - t0
            assert not release.is_set()          # job still held: no hang
            # generous monotonic bound (0.15 s deadline, 30 s job hold):
            # catches a regression that waits for worker completion
            # without being schedulable-noise-sensitive
            assert waited < 10.0
            assert res.status == "deadline_exceeded"
            assert res.where in ("exec", "wait")
            assert res.value is None             # late value is discarded
            with pytest.raises(QueryDeadlineExceeded):
                res.value_or_raise()
            release.set()                        # let the worker drain
        assert counters.get("serve.deadline_exceeded") >= 1

    def test_queue_overrun_never_executes(self, session):
        with QueryServer(session, workers=1) as srv:
            started, release = threading.Event(), threading.Event()

            def blocker(ctx):
                started.set()
                release.wait(5)

            ran = []
            srv.submit(blocker, tenant="a")
            assert started.wait(5)
            late0 = counters.get("serve.late_result")
            fut = srv.submit(lambda ctx: ran.append(1), tenant="a",
                             deadline_s=0.1)
            res = fut.result()
            assert res.status == "deadline_exceeded"
            assert res.where in ("queue", "wait")
            release.set()
            time.sleep(0.1)
            assert ran == []                     # the work never ran
            # and NOT a "late result": nothing executed, so nothing was
            # discarded (regression: the worker's losing queue-deadline
            # resolution used to inflate serve.late_result)
            assert counters.get("serve.late_result") == late0

    def test_deadline_overruns_land_in_e2e_histogram(self, session):
        """e2e is the client-experienced latency: a deadline overrun
        resolved from the queue pop or the waiter lands in
        ``serve.e2e_ms`` exactly once (regression: those paths were
        silently skipped while exec-path overruns recorded, so a
        scrape-derived p99 read healthy under queue saturation — the
        regime deadlines exist for)."""
        obs.METRICS.clear()
        with QueryServer(session, workers=1) as srv:
            started, release = threading.Event(), threading.Event()

            def blocker(ctx):
                started.set()
                release.wait(5)

            srv.submit(blocker, tenant="a")
            assert started.wait(5)
            res = srv.submit(lambda ctx: 1, tenant="a",
                             deadline_s=0.1).result()
            assert res.status == "deadline_exceeded"
            # the overrun is IN (blocker still running: count is exactly 1)
            assert obs.METRICS.snapshot()["serve.e2e_ms"]["count"] == 1
            release.set()
        # stop() drained: blocker completed (+1), and the worker's
        # losing pop of the already-resolved job must NOT re-observe
        assert obs.METRICS.snapshot()["serve.e2e_ms"]["count"] == 2

    def test_default_deadline_from_conf(self, session):
        srv = QueryServer.from_conf(
            session, {"spark.serve.defaultDeadline": "0.05",
                      "spark.serve.workers": "1"})
        assert srv.default_deadline_s == pytest.approx(0.05)
        srv.start()
        try:
            res = srv.submit(lambda ctx: time.sleep(0.6), tenant="a").result()
            assert res.status == "deadline_exceeded"
        finally:
            srv.stop(timeout=2)

    def test_no_deadline_result_timeout_raises(self, session):
        with QueryServer(session, workers=1) as srv:
            started, release = threading.Event(), threading.Event()

            def blocker(ctx):
                started.set()
                release.wait(5)
                return "ok"

            fut = srv.submit(blocker, tenant="a")
            assert started.wait(5)
            with pytest.raises(TimeoutError):
                fut.result(timeout=0.1)
            release.set()
            assert fut.result(timeout=5).value == "ok"


# ---------------------------------------------------------------------------
# SLO observability: metrics, per-tenant isolation, Prometheus
# ---------------------------------------------------------------------------

class TestObservability:
    def test_per_tenant_latency_isolation(self, session):
        obs.METRICS.clear()
        with QueryServer(session, workers=2) as srv:
            for _ in range(3):
                srv.submit(lambda ctx: 1, tenant="iso_ta").result()
            srv.submit(lambda ctx: 1, tenant="iso_tb").result()
        snap = obs.METRICS.snapshot()
        assert snap["serve.e2e_ms.iso_ta"]["count"] == 3
        assert snap["serve.e2e_ms.iso_tb"]["count"] == 1
        assert snap["serve.e2e_ms"]["count"] >= 4
        assert snap["serve.queue_ms"]["count"] >= 4
        assert snap["serve.exec_ms"]["count"] >= 4

    def test_single_scrape_covers_engine_and_server(self, session):
        """session.metrics()/metrics_text() merge the server-scope
        series: one scrape covers engine + server, with HELP lines."""
        with QueryServer(session, workers=1) as srv:
            srv.submit(lambda ctx: Frame({"x": np.arange(4.0)}).count(),
                       tenant="a").result()
        m = session.metrics()
        assert m.get("serve.admit", 0) >= 1
        assert m.get("serve.complete", 0) >= 1
        assert isinstance(m.get("serve.e2e_ms"), dict)
        text = session.metrics_text()
        # HELP text comes from the METRIC_NAMES registry (ISSUE 12)
        assert "# HELP sparkdq4ml_serve_admit serve.admit - queries " \
            "admitted" in text
        assert "# TYPE sparkdq4ml_serve_e2e_ms histogram" in text
        assert "sparkdq4ml_serve_queue_depth" in text
        assert "sparkdq4ml_serve_in_flight" in text

    def test_collect_stats_attaches_query_collector(self, session):
        was_enabled = obs.TRACER.enabled
        with QueryServer(session, workers=1) as srv:
            def job(ctx):
                f = Frame({"x": np.arange(8.0)})
                f = f.with_column("y", E.col("x") + 1.0)
                return f.count()
            res = srv.submit(job, tenant="a", collect_stats=True).result()
        assert res.ok and res.value == 8
        assert res.stats is not None
        assert res.stats.spans                       # per-query span stream
        assert any("with_column" in s.name or "pipeline" in s.name
                   for s in res.stats.spans)
        assert obs.TRACER.enabled == was_enabled     # restored after

    def test_tenant_series_cardinality_cap(self, session):
        from sparkdq4ml_tpu.serve import server as server_mod

        obs.METRICS.clear()
        old = server_mod.MAX_TENANT_SERIES
        server_mod.MAX_TENANT_SERIES = 2
        try:
            with QueryServer(session, workers=1) as srv:
                for name in ("cap_a", "cap_b", "cap_c"):
                    srv.submit(lambda ctx: 1, tenant=name).result()
        finally:
            server_mod.MAX_TENANT_SERIES = old
        snap = obs.METRICS.snapshot()
        assert "serve.e2e_ms.cap_a" in snap
        assert "serve.e2e_ms.cap_b" in snap
        assert "serve.e2e_ms.cap_c" not in snap      # over the cap
        assert snap["serve.e2e_ms"]["count"] == 3    # aggregate keeps all


# ---------------------------------------------------------------------------
# Satellite: concurrent query_stats collectors at server scale
# ---------------------------------------------------------------------------

class TestConcurrentQueryStats:
    def test_eight_threads_staggered_enter_exit(self):
        """8 threads × staggered query_stats windows: each collector sees
        only its own thread's spans, and the LAST collector out restores
        the prior (disabled) tracing state — the PR-5 refcounted restore
        at serving scale."""
        assert not obs.TRACER.enabled
        errors, streams = [], {}

        def worker(i):
            try:
                time.sleep(0.01 * (i % 4))           # staggered enter
                with obs.query_stats(sample_memory=False) as qs:
                    f = Frame({"x": np.arange(16.0) + i})
                    f = f.with_column("y", E.col("x") * 2.0)
                    f.count()
                    time.sleep(0.01 * ((i + 2) % 4))  # staggered exit
                streams[i] = (threading.get_ident(), list(qs.spans))
            except Exception as e:                   # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors
        assert len(streams) == 8
        for i, (tid, spans) in streams.items():
            assert spans, f"collector {i} saw no spans"
            assert all(s.tid == tid for s in spans)  # thread-scoped
        assert not obs.TRACER.enabled                # restore held

    def test_server_collect_stats_under_concurrency(self, session):
        with QueryServer(session, workers=4) as srv:
            def job(ctx):
                f = Frame({"x": np.arange(8.0)})
                return f.with_column("y", E.col("x") + 1.0).count()
            futs = [srv.submit(job, tenant=f"qs{i}", collect_stats=True)
                    for i in range(8)]
            results = [f.result(timeout=60) for f in futs]
        assert all(r.ok and r.value == 8 for r in results)
        assert all(r.stats is not None and r.stats.spans for r in results)
        assert not obs.TRACER.enabled


# ---------------------------------------------------------------------------
# Satellite: the 16-thread jit-cache hammer
# ---------------------------------------------------------------------------

class TestHammer:
    def test_sixteen_threads_mixed_queries_no_lost_updates(self, session):
        """16 threads × mixed pipeline/grouped/sort queries while a
        scraper thread iterates CACHES.report(), prometheus_text(), and
        metrics_snapshot(): no RuntimeError (dict changed during
        iteration), no lost per-plan stat updates — after the storm,
        sum(per-entry hits+compiles) over the pipeline cache equals the
        flush counter exactly."""
        compiler.clear_cache()
        segments.clear_cache()
        counters.clear("pipeline")
        counters.clear("grouped")
        errors: list = []
        stop_scrape = threading.Event()
        ITERS, THREADS = 6, 16

        def scraper():
            while not stop_scrape.is_set():
                try:
                    obs.cache_report()
                    obs.prometheus_text()
                    obs.metrics_snapshot()
                except Exception as e:               # noqa: BLE001
                    errors.append(f"scraper: {e!r}")
                    return

        def worker(i):
            try:
                rng = np.random.default_rng(i)
                for it in range(ITERS):
                    # pipeline chain: 4 plan shapes shared across threads
                    # (i % 4) -> heavy cross-thread hit/evict traffic.
                    # Bounded uniform data: every row must survive the
                    # filter so the count pins row preservation.
                    f = Frame({"v": rng.uniform(0.0, 1.0, 64)})
                    f = f.with_column(f"c{i % 4}",
                                      E.col("v") * float(it + 1) + 0.5)
                    f = f.filter(E.col(f"c{i % 4}") > -10.0)
                    assert f.count() == 64
                    # grouped aggregation (device segment-reduce path)
                    g = Frame({"k": (np.arange(64) % 4).astype(np.float64),
                               "v": rng.normal(size=64)})
                    out = g.group_by("k").agg(A.sum("v"))
                    assert out.count() == 4
                    # device distinct
                    d = Frame({"k": (np.arange(32) % 8).astype(np.float64)})
                    assert d.distinct().count() == 8
            except Exception as e:                   # noqa: BLE001
                errors.append(f"worker {i}: {e!r}")

        scr = threading.Thread(target=scraper)
        scr.start()
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        stop_scrape.set()
        scr.join(30)
        assert errors == []
        # no lost updates: every flush landed on exactly one cached
        # plan's hit/compile tally (no fallbacks, no evictions)
        assert counters.get("pipeline.fallback") == 0
        assert counters.get("pipeline.evict") == 0
        stats = compiler.cache_stats()
        entry_sum = sum(e["hits"] + e["compiles"] for e in stats["entries"])
        assert entry_sum == counters.get("pipeline.flush")
        assert counters.get("pipeline.flush") == THREADS * ITERS
        gstats = segments.cache_stats()
        g_entry_sum = sum(e["hits"] + e["builds"]
                          for e in gstats["entries"])
        assert g_entry_sum >= THREADS * ITERS * 2    # agg + distinct plans
        assert counters.get("grouped.fallback") == 0


# ---------------------------------------------------------------------------
# Satellite: thread-safe session singleton
# ---------------------------------------------------------------------------

class TestSessionThreadSafety:
    def test_get_or_create_race_yields_one_session(self):
        from sparkdq4ml_tpu import session as sess_mod

        assert sess_mod._ACTIVE is None
        out, errors = [], []
        barrier = threading.Barrier(16)

        def racer():
            try:
                barrier.wait(10)
                s = dq.TpuSession.builder().app_name("race") \
                    .master("local[*]").get_or_create()
                out.append(s)
            except Exception as e:                   # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=racer) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        try:
            assert errors == []
            assert len(out) == 16
            assert len({id(s) for s in out}) == 1    # ONE session object
            assert dq.TpuSession.active() is out[0]
        finally:
            if out:
                out[0].stop()

    def test_stop_vs_inflight_conf_restore(self):
        """A session that changed pipeline conf restores it exactly once
        even when stop() races a concurrent builder re-init — the
        _CONF_LOCK pin."""
        from sparkdq4ml_tpu.config import config

        default_pipeline = config.pipeline
        s = dq.TpuSession.builder().app_name("restore") \
            .config("spark.pipeline.enabled", "false").get_or_create()
        assert config.pipeline is False

        def reinit():
            dq.TpuSession.builder() \
                .config("spark.pipeline.enabled", "false").get_or_create()

        t = threading.Thread(target=reinit)
        t.start()
        s.stop()
        t.join(30)
        # whichever order the race resolved, a final stop of the active
        # session (if the re-init re-created state) must land back at
        # the process default
        active = dq.TpuSession.active()
        if active is not None:
            active.stop()
        assert config.pipeline == default_pipeline


# ---------------------------------------------------------------------------
# Disabled mode / no-op contract
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_conf_disables_session_serve(self):
        from sparkdq4ml_tpu.config import config

        s = dq.TpuSession.builder().app_name("noserve") \
            .config("spark.serve.enabled", "false").get_or_create()
        try:
            assert config.serve_enabled is False
            with pytest.raises(RuntimeError, match="disabled"):
                s.serve()
        finally:
            s.stop()
        assert config.serve_enabled is True          # session-scoped restore

    def test_conf_accepts_no_spelling(self):
        """``spark.serve.enabled=no`` disables serving — the session conf
        parser accepts the same boolean spellings as the serve layer's
        own ``_CONF_BOOL_FALSE`` (regression: "no" was silently ignored
        and the server started anyway)."""
        from sparkdq4ml_tpu.config import config

        s = dq.TpuSession.builder().app_name("noserve2") \
            .config("spark.serve.enabled", "no").get_or_create()
        try:
            assert config.serve_enabled is False
            with pytest.raises(RuntimeError, match="disabled"):
                s.serve()
        finally:
            s.stop()
        assert config.serve_enabled is True

    def test_unstarted_layer_records_nothing(self, session):
        counters.clear("serve.")
        obs.METRICS.clear()
        f = Frame({"x": np.arange(16.0)})
        f = f.with_column("y", E.col("x") * 2.0)
        assert f.count() == 16
        session.sql("SELECT 1 AS one")
        assert counters.snapshot("serve.") == {}
        assert not any(k.startswith("serve.")
                       for k in obs.METRICS.snapshot())


# ---------------------------------------------------------------------------
# Satellite: bench-regression gate covers the serving metrics
# ---------------------------------------------------------------------------

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(REPO, "scripts", "check_bench_regress.py")


def _run_script(*args):
    return subprocess.run([sys.executable, SCRIPT, *args],
                          capture_output=True, text=True, timeout=60)


def _write(path, doc):
    with open(path, "w") as f:
        json.dump(doc, f)


@pytest.mark.bench_regress
class TestBenchRegressServing:
    OLD = {"serving": {"config": "serving", "clients": 32,
                       "shared_cache": {"qps": 100.0, "p50_ms": 8.0,
                                        "p99_ms": 40.0},
                       "isolated_cache": {"qps": 20.0, "p99_ms": 300.0},
                       "shared_vs_isolated_qps": 5.0}}

    def test_qps_drop_fails(self, tmp_path):
        new = json.loads(json.dumps(self.OLD))
        new["serving"]["shared_cache"]["qps"] = 50.0   # -50%
        _write(tmp_path / "o.json", self.OLD)
        _write(tmp_path / "n.json", new)
        p = _run_script("--old", str(tmp_path / "o.json"),
                        "--new", str(tmp_path / "n.json"))
        assert p.returncode == 1
        assert "serving/shared_cache/qps" in p.stdout

    def test_p99_rise_fails(self, tmp_path):
        new = json.loads(json.dumps(self.OLD))
        new["serving"]["shared_cache"]["p99_ms"] = 80.0  # +100%
        _write(tmp_path / "o.json", self.OLD)
        _write(tmp_path / "n.json", new)
        p = _run_script("--old", str(tmp_path / "o.json"),
                        "--new", str(tmp_path / "n.json"))
        assert p.returncode == 1
        assert "serving/shared_cache/p99_ms" in p.stdout

    def test_improvement_passes(self, tmp_path):
        new = json.loads(json.dumps(self.OLD))
        new["serving"]["shared_cache"]["qps"] = 200.0
        new["serving"]["shared_cache"]["p99_ms"] = 20.0
        _write(tmp_path / "o.json", self.OLD)
        _write(tmp_path / "n.json", new)
        p = _run_script("--old", str(tmp_path / "o.json"),
                        "--new", str(tmp_path / "n.json"))
        assert p.returncode == 0
        assert "PASS" in p.stdout

    def test_serving_only_doc_is_parseable(self, tmp_path):
        _write(tmp_path / "o.json", self.OLD)
        _write(tmp_path / "n.json", self.OLD)
        p = _run_script("--old", str(tmp_path / "o.json"),
                        "--new", str(tmp_path / "n.json"))
        assert p.returncode == 0
        assert "PASS" in p.stdout
