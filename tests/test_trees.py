"""Tree family: DecisionTree/RandomForest/GBT × classifier/regressor.

Quality oracles (SURVEY.md §4 pattern): sklearn trees on the same data —
exact split parity is not expected (histogram binning vs exact splits), so
assertions are on fit quality, structure, and invariants (masked rows,
determinism, persistence)."""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame, col
from sparkdq4ml_tpu.models import (DecisionTreeClassifier,
                                   DecisionTreeRegressor, GBTClassifier,
                                   GBTRegressor, RandomForestClassifier,
                                   RandomForestRegressor, VectorAssembler)


def reg_frame(n=400, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, size=(n, 3))
    y = np.where(X[:, 0] > 0, 5.0, -5.0) + X[:, 1] ** 2 \
        + 0.1 * rng.normal(size=n)
    cols = {f"x{j}": X[:, j].astype(np.float32) for j in range(3)}
    cols["label"] = y.astype(np.float32)
    f = Frame(cols)
    return VectorAssembler([f"x{j}" for j in range(3)],
                           "features").transform(f), X, y


def clf_frame(n=400, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, size=(n, 3))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0.5)).astype(np.float64)
    cols = {f"x{j}": X[:, j].astype(np.float32) for j in range(3)}
    cols["label"] = y.astype(np.float32)
    f = Frame(cols)
    return VectorAssembler([f"x{j}" for j in range(3)],
                           "features").transform(f), X, y


def r2(y, p):
    return 1 - np.sum((y - p) ** 2) / np.sum((y - y.mean()) ** 2)


class TestDecisionTreeRegressor:
    def test_fits_step_function(self):
        f, X, y = reg_frame()
        model = DecisionTreeRegressor(max_depth=4).fit(f)
        pred = model.transform(f).to_pydict()["prediction"]
        assert r2(y, pred) > 0.9
        # the dominant split must be on feature 0
        assert np.argmax(model.feature_importances) == 0

    def test_sklearn_quality_parity(self):
        pytest.importorskip("sklearn")
        from sklearn.tree import DecisionTreeRegressor as SkDT

        f, X, y = reg_frame()
        ours = DecisionTreeRegressor(max_depth=4).fit(f)
        sk = SkDT(max_depth=4).fit(X, y)
        ours_r2 = r2(y, ours.transform(f).to_pydict()["prediction"])
        sk_r2 = r2(y, sk.predict(X))
        assert ours_r2 > sk_r2 - 0.05  # binning costs at most a little

    def test_predict_matches_transform(self):
        f, X, _ = reg_frame(n=50)
        model = DecisionTreeRegressor(max_depth=3).fit(f)
        out = model.transform(f).to_pydict()["prediction"]
        assert model.predict(X[7]) == pytest.approx(out[7], rel=1e-5)

    def test_masked_rows_do_not_vote(self):
        f = Frame({"x0": [0.0, 1.0, 2.0, 3.0],
                   "label": [1.0, 1.0, 5.0, 500.0]})
        f = VectorAssembler(["x0"], "features").transform(f)
        model = DecisionTreeRegressor(max_depth=2).fit(
            f.filter(col("label") < 100.0))
        assert model.predict([3.0]) < 100.0

    def test_min_instances_limits_splits(self):
        f, _, _ = reg_frame(n=100)
        stump = DecisionTreeRegressor(max_depth=5,
                                      min_instances_per_node=60).fit(f)
        deep = DecisionTreeRegressor(max_depth=5).fit(f)
        assert np.asarray(stump.is_leaf).sum() > np.asarray(deep.is_leaf).sum()

    def test_nan_label_in_masked_slot_is_harmless(self):
        # dropna is mask-based: the NaN stays in the slot with mask=False
        f = Frame({"x0": [0.0, 1.0, 2.0, 3.0],
                   "label": [1.0, 3.0, 5.0, float("nan")]})
        f = VectorAssembler(["x0"], "features").transform(f)
        f = f.dropna(subset=["label"])
        model = DecisionTreeRegressor(max_depth=2).fit(f)
        assert np.isfinite(model.predict([1.0]))
        gbt = GBTRegressor(max_iter=3, max_depth=2).fit(f)
        assert np.isfinite(gbt.predict([1.0]))

    def test_nan_label_in_valid_row_raises(self):
        f = Frame({"x0": [0.0, 1.0], "label": [1.0, float("nan")]})
        f = VectorAssembler(["x0"], "features").transform(f)
        with pytest.raises(ValueError, match="NaN"):
            DecisionTreeRegressor().fit(f)

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, X, _ = reg_frame(n=80)
        model = DecisionTreeRegressor(max_depth=3).fit(f)
        model.save(str(tmp_path / "dt"))
        loaded = load_stage(str(tmp_path / "dt"))
        assert loaded.predict(X[3]) == pytest.approx(model.predict(X[3]),
                                                     rel=1e-6)


class TestDecisionTreeClassifier:
    def test_fits_xor(self):
        f, X, y = clf_frame()
        model = DecisionTreeClassifier(max_depth=4).fit(f)
        out = model.transform(f).to_pydict()
        assert np.mean(out["prediction"] == y) > 0.95
        probs = np.stack(out["probability"])
        assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
        # rawPrediction = leaf class counts (MLlib), not the probabilities:
        # row sums are leaf sizes (≥ 1), and for a single tree the
        # normalized counts reproduce the probability column
        raw = np.stack(out["rawPrediction"])
        assert raw.sum(axis=1).min() >= 1.0
        assert not np.allclose(raw, probs)
        assert np.allclose(raw / raw.sum(axis=1, keepdims=True), probs,
                           atol=1e-5)

    def test_entropy_impurity(self):
        f, X, y = clf_frame()
        model = DecisionTreeClassifier(max_depth=4, impurity="entropy").fit(f)
        out = model.transform(f).to_pydict()
        assert np.mean(out["prediction"] == y) > 0.95

    def test_multiclass(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(300, 2))
        y = (X[:, 0] > 0).astype(int) + 2 * (X[:, 1] > 0).astype(int) % 3
        f = Frame({"x0": X[:, 0].astype(np.float32),
                   "x1": X[:, 1].astype(np.float32),
                   "label": y.astype(np.float32)})
        f = VectorAssembler(["x0", "x1"], "features").transform(f)
        model = DecisionTreeClassifier(max_depth=4).fit(f)
        assert model.num_classes == int(y.max()) + 1
        out = model.transform(f).to_pydict()
        assert np.mean(out["prediction"] == y) > 0.9

    def test_label_validation(self):
        f = Frame({"x0": [1.0, 2.0], "label": [0.5, 1.0]})
        f = VectorAssembler(["x0"], "features").transform(f)
        with pytest.raises(ValueError, match="integers"):
            DecisionTreeClassifier().fit(f)

    def test_masked_out_of_range_label_is_harmless(self):
        f = Frame({"x0": [0.0, 1.0, 2.0, 3.0],
                   "label": [0.0, 1.0, 0.0, 5.0]})
        f = VectorAssembler(["x0"], "features").transform(f)
        model = DecisionTreeClassifier(max_depth=2).fit(
            f.filter(col("label") < 2.0))
        assert model.num_classes == 2  # the masked 5 never entered the fit


class TestRandomForest:
    def test_regression_beats_single_tree_oob_style(self):
        f, X, y = reg_frame(n=300, seed=5)
        test_f, Xt, yt = reg_frame(n=200, seed=99)
        tree = DecisionTreeRegressor(max_depth=6).fit(f)
        # "all" isolates the bagging effect; "auto" (Spark: d/3 per node)
        # would also decorrelate features, a different comparison
        forest = RandomForestRegressor(num_trees=30, max_depth=6,
                                       feature_subset_strategy="all",
                                       seed=7).fit(f)
        assert forest.num_trees == 30
        t_r2 = r2(yt, tree.transform(test_f).to_pydict()["prediction"])
        f_r2 = r2(yt, forest.transform(test_f).to_pydict()["prediction"])
        assert f_r2 > t_r2 - 0.02  # ensemble at least matches one tree

    def test_classification_soft_vote(self):
        f, X, y = clf_frame()
        model = RandomForestClassifier(num_trees=15, max_depth=5,
                                       seed=3).fit(f)
        out = model.transform(f).to_pydict()
        assert np.mean(out["prediction"] == y) > 0.93
        probs = np.stack(out["probability"])
        assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
        # MLlib contract: prediction == argmax(rawPrediction) for forests
        raw = np.stack(out["rawPrediction"])
        assert np.array_equal(np.argmax(raw, axis=1),
                              np.asarray(out["prediction"]).astype(int))

    def test_deterministic_given_seed(self):
        f, X, _ = clf_frame(n=120)
        a = RandomForestClassifier(num_trees=5, seed=11).fit(f)
        b = RandomForestClassifier(num_trees=5, seed=11).fit(f)
        assert np.array_equal(np.asarray(a.value), np.asarray(b.value))

    def test_feature_subset_strategies(self):
        f, _, _ = clf_frame(n=100)
        for strat in ("auto", "sqrt", "log2", "all", "0.5", "2"):
            m = RandomForestClassifier(num_trees=3, max_depth=3,
                                       feature_subset_strategy=strat,
                                       seed=1).fit(f)
            assert m.num_trees == 3
        with pytest.raises(ValueError, match="featureSubsetStrategy"):
            RandomForestClassifier(feature_subset_strategy="bogus").fit(f)

    def test_subset_counts_follow_spark_table(self):
        from sparkdq4ml_tpu.models.tree import _n_subset_features

        # auto: all for one tree; sqrt / onethird for forests
        assert _n_subset_features("auto", 9, True, 1) == 9
        assert _n_subset_features("auto", 9, True, 10) == 3
        assert _n_subset_features("auto", 9, False, 10) == 3
        assert _n_subset_features("auto", 12, False, 10) == 4
        assert _n_subset_features("2", 10, True, 5) == 2   # integer form
        assert _n_subset_features("0.5", 10, True, 5) == 5  # fraction form

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, X, _ = clf_frame(n=100)
        model = RandomForestClassifier(num_trees=4, max_depth=3,
                                       seed=2).fit(f)
        model.save(str(tmp_path / "rf"))
        loaded = load_stage(str(tmp_path / "rf"))
        assert loaded.predict(X[5]) == model.predict(X[5])
        assert loaded.num_trees == 4


class TestGBT:
    def test_regression_quality(self):
        f, X, y = reg_frame(n=300, seed=8)
        model = GBTRegressor(max_iter=40, step_size=0.2, max_depth=3,
                             seed=4).fit(f)
        pred = model.transform(f).to_pydict()["prediction"]
        assert r2(y, pred) > 0.95
        assert model.num_trees == 40

    def test_boosting_improves_with_rounds(self):
        f, X, y = reg_frame(n=250, seed=9)
        weak = GBTRegressor(max_iter=2, step_size=0.2, max_depth=2,
                            seed=4).fit(f)
        strong = GBTRegressor(max_iter=30, step_size=0.2, max_depth=2,
                              seed=4).fit(f)
        r_weak = r2(y, weak.transform(f).to_pydict()["prediction"])
        r_strong = r2(y, strong.transform(f).to_pydict()["prediction"])
        assert r_strong > r_weak

    def test_classification(self):
        f, X, y = clf_frame(n=300, seed=10)
        model = GBTClassifier(max_iter=30, step_size=0.3, max_depth=3,
                              seed=5).fit(f)
        out = model.transform(f).to_pydict()
        assert np.mean(out["prediction"] == y) > 0.95
        probs = np.stack(out["probability"])
        assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
        raw = np.stack(out["rawPrediction"])
        assert np.allclose(raw[:, 0], -raw[:, 1], atol=1e-5)

    def test_binary_label_validation(self):
        f = Frame({"x0": [1.0, 2.0], "label": [0.0, 2.0]})
        f = VectorAssembler(["x0"], "features").transform(f)
        with pytest.raises(ValueError, match="binary"):
            GBTClassifier().fit(f)

    def test_persistence(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f, X, _ = reg_frame(n=80)
        model = GBTRegressor(max_iter=5, max_depth=2, seed=1).fit(f)
        model.save(str(tmp_path / "gbt"))
        loaded = load_stage(str(tmp_path / "gbt"))
        assert loaded.predict(X[2]) == pytest.approx(model.predict(X[2]),
                                                     rel=1e-5)


class TestAdvisorFindings:
    def test_nan_feature_in_valid_row_rejected(self):
        f = Frame({"x0": [1.0, float("nan"), 3.0, 4.0],
                   "label": [1.0, 2.0, 3.0, 4.0]})
        f = VectorAssembler(["x0"], "features").transform(f)
        with pytest.raises(ValueError, match="feature matrix"):
            DecisionTreeRegressor(max_depth=2).fit(f)

    def test_forest_prediction_is_equal_tree_average(self):
        # MLlib semantics: average per-tree leaf means with equal weight,
        # not pooled [w, wy] leaf stats (which would weight by leaf size).
        f, X, _ = reg_frame(n=120, seed=3)
        model = RandomForestRegressor(num_trees=5, max_depth=3,
                                      seed=7).fit(f)
        vals = np.asarray(model._leaf_values(X[:10]))   # (T, n, 3)
        per_tree = vals[:, :, 1] / np.maximum(vals[:, :, 0], 1e-12)
        expected = per_tree.mean(axis=0)
        got = np.asarray(model._predict_array(X[:10]))
        np.testing.assert_allclose(got, expected, rtol=1e-6)


class TestGbtValidationEarlyStopping:
    def _data(self, seed=0):
        rng = np.random.default_rng(seed)
        n = 400
        X = rng.normal(size=(n, 3))
        y = (np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2
             + 0.3 * rng.normal(size=n))
        is_val = (np.arange(n) % 4 == 0).astype(np.float64)
        return Frame({"features": X, "label": y, "is_val": is_val})

    def test_stops_early_and_truncates(self):
        from sparkdq4ml_tpu.models import GBTRegressor
        f = self._data()
        full = GBTRegressor(max_iter=40, step_size=0.3, max_depth=3,
                            seed=1).fit(f)
        es = GBTRegressor(max_iter=40, step_size=0.3, max_depth=3, seed=1,
                          validation_indicator_col="is_val",
                          validation_tol=0.05).fit(f)
        assert es.value.shape[0] <= full.value.shape[0]
        assert es.value.shape[0] >= 1

    def test_validation_rows_not_trained_on(self):
        from sparkdq4ml_tpu.models import GBTRegressor
        f = self._data(seed=1)
        # poison the validation rows' labels; with the indicator they are
        # held out, so the fitted trees must match a fit on clean rows
        d = f.to_pydict()
        X = np.stack(d["features"])
        y = np.asarray(d["label"]).copy()
        is_val = np.asarray(d["is_val"])
        ybad = y.copy()
        ybad[is_val > 0] = 1e6
        # validation loss on garbage labels: immediately non-improving →
        # both fits see the same training rows; compare one-round models
        m_ind = GBTRegressor(max_iter=1, max_depth=2, seed=2,
                             validation_indicator_col="is_val").fit(
            Frame({"features": X, "label": ybad, "is_val": is_val}))
        m_clean = GBTRegressor(max_iter=1, max_depth=2, seed=2).fit(
            Frame({"features": X[is_val == 0], "label": y[is_val == 0]}))
        np.testing.assert_allclose(m_ind.f0, m_clean.f0, rtol=1e-9)
        np.testing.assert_allclose(m_ind.threshold, m_clean.threshold,
                                   rtol=1e-6)

    def test_classifier_surface(self):
        from sparkdq4ml_tpu.models import GBTClassifier
        rng = np.random.default_rng(3)
        n = 300
        X = rng.normal(size=(n, 2))
        y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
        f = Frame({"features": X, "label": y,
                   "v": (np.arange(n) % 5 == 0).astype(np.float64)})
        m = (GBTClassifier(max_iter=20, seed=4)
             .set_validation_indicator_col("v").set_validation_tol(0.02)
             .fit(f))
        pred = np.asarray(m.transform(f)._column_values("prediction"))
        assert np.mean(pred == y) > 0.85
