"""Imputer, Normalizer, Binarizer, PolynomialExpansion, QuantileDiscretizer.

Cross-checked against MLlib conventions: mean/median/mode surrogates over
non-missing valid rows, unit p-norm rows (zero rows unchanged), x > threshold
binarization, total-degree monomial expansion, quantile splits with ±inf ends.
"""

import numpy as np
import pytest

from sparkdq4ml_tpu import Frame, col
from sparkdq4ml_tpu.models import (Binarizer, Imputer, Normalizer,
                                   PolynomialExpansion, QuantileDiscretizer,
                                   VectorAssembler)

nan = float("nan")


class TestImputer:
    def test_mean_imputation(self):
        f = Frame({"a": [1.0, nan, 3.0], "b": [10.0, 20.0, nan]})
        model = Imputer(["a", "b"]).fit(f)
        assert model.surrogates == pytest.approx([2.0, 15.0])
        out = model.transform(f).to_pydict()
        assert out["a"].tolist() == pytest.approx([1.0, 2.0, 3.0])
        assert out["b"].tolist() == pytest.approx([10.0, 20.0, 15.0])

    def test_median_and_mode(self):
        f = Frame({"a": [1.0, 2.0, 100.0, nan]})
        assert Imputer(["a"], strategy="median").fit(f).surrogates == \
            pytest.approx([2.0])
        g = Frame({"a": [5.0, 5.0, 7.0, 7.0, 3.0]})
        # tie 5 vs 7 → smallest (Spark)
        assert Imputer(["a"], strategy="mode").fit(g).surrogates == \
            pytest.approx([5.0])

    def test_sentinel_missing_value(self):
        f = Frame({"a": [1.0, -1.0, 3.0]})
        model = Imputer(["a"], missing_value=-1.0).fit(f)
        out = model.transform(f).to_pydict()
        assert out["a"].tolist() == pytest.approx([1.0, 2.0, 3.0])

    def test_sentinel_still_imputes_nan(self):
        # Spark imputes nulls regardless of the missingValue sentinel
        f = Frame({"a": [1.0, -1.0, nan, 3.0]})
        out = Imputer(["a"], missing_value=-1.0).fit(f).transform(f) \
            .to_pydict()
        assert out["a"].tolist() == pytest.approx([1.0, 2.0, 2.0, 3.0])

    def test_output_cols_and_masked_rows(self):
        f = Frame({"a": [1.0, nan, 99.0]}).filter(
            np.asarray([True, True, False]))
        model = Imputer(["a"], ["a_imp"]).fit(f)
        assert model.surrogates == pytest.approx([1.0])  # 99 is masked out
        out = model.transform(f)
        assert "a_imp" in out.columns and "a" in out.columns

    def test_surrogate_df(self):
        f = Frame({"a": [2.0, 4.0]})
        sdf = Imputer(["a"]).fit(f).surrogate_df
        assert sdf.to_pydict()["a"].tolist() == pytest.approx([3.0])

    def test_all_missing_raises(self):
        f = Frame({"a": [nan, nan]})
        with pytest.raises(ValueError, match="no valid"):
            Imputer(["a"]).fit(f)


class TestNormalizer:
    def test_l2_rows(self):
        f = Frame({"x": [3.0, 0.0], "y": [4.0, 0.0]})
        f = VectorAssembler(["x", "y"], "v").transform(f)
        out = Normalizer("v", "nv").transform(f).to_pydict()
        assert out["nv"][0].tolist() == pytest.approx([0.6, 0.8])
        assert out["nv"][1].tolist() == pytest.approx([0.0, 0.0])  # zero row

    def test_l1_and_inf(self):
        f = Frame({"x": [1.0], "y": [-3.0]})
        f = VectorAssembler(["x", "y"], "v").transform(f)
        l1 = Normalizer("v", "o", p=1.0).transform(f).to_pydict()["o"][0]
        assert l1.tolist() == pytest.approx([0.25, -0.75])
        linf = Normalizer("v", "o", p=float("inf")).transform(f) \
            .to_pydict()["o"][0]
        assert linf.tolist() == pytest.approx([1 / 3, -1.0])


class TestBinarizer:
    def test_threshold(self):
        f = Frame({"x": [0.1, 0.5, 0.9, nan]})
        out = Binarizer(0.5, "x", "b").transform(f).to_pydict()
        assert out["b"].tolist() == [0.0, 0.0, 1.0, 0.0]  # NaN → 0 (Spark)


class TestPolynomialExpansion:
    def test_degree2_two_features(self):
        f = Frame({"x": [2.0], "y": [3.0]})
        f = VectorAssembler(["x", "y"], "v").transform(f)
        out = PolynomialExpansion(2, "v", "p").transform(f).to_pydict()
        # degree 1: x, y; degree 2: x², xy, y²
        assert sorted(out["p"][0].tolist()) == pytest.approx(
            sorted([2.0, 3.0, 4.0, 6.0, 9.0]))

    def test_degree3_count(self):
        f = Frame({"x": [1.0], "y": [1.0]})
        f = VectorAssembler(["x", "y"], "v").transform(f)
        out = PolynomialExpansion(3, "v", "p").transform(f).to_pydict()
        # C(2+1-1,1)+C(2+2-1,2)+C(2+3-1,3) = 2+3+4 = 9 monomials
        assert len(out["p"][0]) == 9

    def test_scalar_column(self):
        f = Frame({"x": [2.0]})
        out = PolynomialExpansion(3, "x", "p").transform(f).to_pydict()
        assert out["p"][0].tolist() == pytest.approx([2.0, 4.0, 8.0])


class TestQuantileDiscretizer:
    def test_buckets(self):
        f = Frame({"x": [float(i) for i in range(100)]})
        bucketizer = QuantileDiscretizer(4, "x", "q").fit(f)
        out = bucketizer.transform(f).to_pydict()
        counts = np.bincount(out["q"].astype(int))
        assert len(counts) == 4 and all(20 <= c <= 30 for c in counts)

    def test_open_ends_cover_unseen_values(self):
        f = Frame({"x": [1.0, 2.0, 3.0, 4.0]})
        b = QuantileDiscretizer(2, "x", "q").fit(f)
        far = Frame({"x": [-1000.0, 1000.0]})
        out = b.transform(far).to_pydict()
        assert out["q"].tolist() == [0.0, 1.0]

    def test_duplicate_quantiles_collapse(self):
        f = Frame({"x": [1.0] * 50 + [2.0]})
        b = QuantileDiscretizer(4, "x", "q").fit(f)
        assert len(b.splits) < 6  # fewer buckets than requested

    def test_fit_ignores_masked_rows(self):
        f = Frame({"x": [1.0, 2.0, 3.0, 1000.0]}).filter(
            col("x") < 100.0)
        b = QuantileDiscretizer(2, "x", "q").fit(f)
        assert b.splits[1] == pytest.approx(2.0)


class TestPersistence:
    def test_imputer_model_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        f = Frame({"a": [1.0, nan, 3.0]})
        model = Imputer(["a"]).fit(f)
        path = str(tmp_path / "imp")
        model.save(path)
        loaded = load_stage(path)
        out = loaded.transform(f).to_pydict()
        assert out["a"].tolist() == pytest.approx([1.0, 2.0, 3.0])

    def test_normalizer_roundtrip(self, tmp_path):
        from sparkdq4ml_tpu.models.base import load_stage

        t = Normalizer("v", "nv", p=1.0)
        path = str(tmp_path / "norm")
        t.save(path)
        loaded = load_stage(path)
        assert loaded.p == 1.0 and loaded.input_col == "v"
